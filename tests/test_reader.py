"""End-to-end read conformance against pyarrow-written files.

The canonical-implementation cross-check that the reference gets from
parquet-testing/parquet-mr corpora (SURVEY.md §4.5-4.6): pyarrow writes a matrix of
{types × codecs × page versions × encodings × null patterns}; our reader must
produce identical values.
"""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from tpu_parquet.column import ByteArrayData
from tpu_parquet.footer import ParquetError
from tpu_parquet.reader import FileReader


def write(tmp_path, table, name="t.parquet", **kw):
    p = tmp_path / name
    pq.write_table(table, p, **kw)
    return p


def expect_column(path, col_name, expected):
    with FileReader(path) as r:
        got = r.read_pylist()[col_name]
    assert len(got) == len(expected)
    for i, (g, e) in enumerate(zip(got, expected)):
        if e is None:
            assert g is None, f"row {i}: expected None, got {g!r}"
        elif isinstance(e, float):
            assert g == pytest.approx(e, nan_ok=True), f"row {i}"
        else:
            assert g == e, f"row {i}: {g!r} != {e!r}"


# ---------------------------------------------------------------------------
# Minimum end-to-end slice (SURVEY.md §7.3): int64 PLAIN + SNAPPY
# ---------------------------------------------------------------------------

def test_int64_plain_snappy(tmp_path):
    data = list(range(100_000))
    p = write(
        tmp_path, pa.table({"v": pa.array(data, pa.int64())}),
        compression="snappy", use_dictionary=False,
    )
    with FileReader(p) as r:
        cols = r.read_all()
        np.testing.assert_array_equal(cols["v"].values, np.arange(100_000))


@pytest.mark.parametrize("codec", ["none", "snappy", "gzip", "zstd"])
@pytest.mark.parametrize("page_version", ["1.0", "2.0"])
def test_codec_page_matrix(tmp_path, codec, page_version):
    if codec == "zstd":
        from conftest import require_codec
        from tpu_parquet.format import CompressionCodec

        require_codec(CompressionCodec.ZSTD)
    rng = np.random.default_rng(1)
    ints = rng.integers(-(2**60), 2**60, 5000)
    data = {
        "i32": pa.array(rng.integers(-(2**31), 2**31, 5000), pa.int32()),
        "i64": pa.array(ints, pa.int64()),
        "f32": pa.array(rng.normal(size=5000).astype(np.float32), pa.float32()),
        "f64": pa.array(rng.normal(size=5000), pa.float64()),
        "b": pa.array(rng.integers(0, 2, 5000).astype(bool)),
        "s": pa.array([f"val_{i % 100}" for i in range(5000)]),
    }
    table = pa.table(data)
    p = write(
        tmp_path, table, compression=codec, data_page_version=page_version,
    )
    with FileReader(p) as r:
        assert r.num_rows == 5000
        got = r.read_pylist()
    for name in data:
        expected = table.column(name).to_pylist()
        if name in ("f32", "f64"):
            np.testing.assert_allclose(got[name], expected, rtol=1e-6)
        else:
            assert got[name] == expected


@pytest.mark.parametrize("page_version", ["1.0", "2.0"])
def test_nulls_optional_columns(tmp_path, page_version):
    rng = np.random.default_rng(2)
    vals = [None if rng.random() < 0.3 else int(i) for i in range(10_000)]
    strs = [None if rng.random() < 0.3 else f"s{i}" for i in range(10_000)]
    table = pa.table({
        "v": pa.array(vals, pa.int64()),
        "s": pa.array(strs, pa.string()),
    })
    p = write(tmp_path, table, data_page_version=page_version,
              use_dictionary=False)
    expect_column(p, "v", vals)
    expect_column(p, "s", strs)


def test_all_null_column(tmp_path):
    table = pa.table({"v": pa.array([None] * 100, pa.int64())})
    p = write(tmp_path, table)
    expect_column(p, "v", [None] * 100)


def test_dictionary_encoded_strings(tmp_path):
    vals = [f"city_{i % 50}" for i in range(50_000)]
    p = write(tmp_path, pa.table({"s": pa.array(vals)}), use_dictionary=True)
    expect_column(p, "s", vals)


def test_dictionary_encoded_numbers_with_nulls(tmp_path):
    rng = np.random.default_rng(3)
    vals = [None if rng.random() < 0.1 else int(rng.integers(0, 20)) for _ in range(20_000)]
    p = write(tmp_path, pa.table({"v": pa.array(vals, pa.int64())}),
              use_dictionary=True)
    expect_column(p, "v", vals)


def test_dictionary_fallback_mixed_pages(tmp_path):
    # dictionary overflow mid-chunk: arrow falls back to plain pages in the same
    # chunk; both page kinds must decode
    vals = [f"unique_{i}" for i in range(100_000)]
    p = write(tmp_path, pa.table({"s": pa.array(vals)}),
              use_dictionary=True, dictionary_pagesize_limit=4096)
    expect_column(p, "s", vals)


def test_delta_binary_packed(tmp_path):
    rng = np.random.default_rng(4)
    i64 = rng.integers(-(2**40), 2**40, 30_000)
    i32 = rng.integers(-(2**28), 2**28, 30_000).astype(np.int32)
    table = pa.table({"a": pa.array(i64, pa.int64()),
                      "b": pa.array(i32, pa.int32())})
    p = write(tmp_path, table, use_dictionary=False,
              column_encoding={"a": "DELTA_BINARY_PACKED",
                               "b": "DELTA_BINARY_PACKED"})
    with FileReader(p) as r:
        cols = r.read_all()
    np.testing.assert_array_equal(cols["a"].values, i64)
    np.testing.assert_array_equal(cols["b"].values, i32)


def test_delta_byte_array_encodings(tmp_path):
    vals = sorted(f"prefix_shared_{i:06d}" for i in range(5000))
    table = pa.table({
        "dba": pa.array(vals), "dlba": pa.array(vals),
    })
    p = write(tmp_path, table, use_dictionary=False,
              column_encoding={"dba": "DELTA_BYTE_ARRAY",
                               "dlba": "DELTA_LENGTH_BYTE_ARRAY"})
    expect_column(p, "dba", vals)
    expect_column(p, "dlba", vals)


def test_byte_stream_split(tmp_path):
    rng = np.random.default_rng(5)
    f32 = rng.normal(size=5000).astype(np.float32)
    f64 = rng.normal(size=5000)
    table = pa.table({"a": pa.array(f32, pa.float32()),
                      "b": pa.array(f64, pa.float64())})
    p = write(tmp_path, table, use_dictionary=False,
              column_encoding={"a": "BYTE_STREAM_SPLIT",
                               "b": "BYTE_STREAM_SPLIT"})
    with FileReader(p) as r:
        cols = r.read_all()
    np.testing.assert_array_equal(cols["a"].values, f32)
    np.testing.assert_array_equal(cols["b"].values, f64)


def test_fixed_len_byte_array(tmp_path):
    vals = [bytes([i] * 16) for i in range(200)]
    table = pa.table({"u": pa.array(vals, pa.binary(16))})
    p = write(tmp_path, table, use_dictionary=False)
    expect_column(p, "u", vals)


def test_boolean_rle_v2(tmp_path):
    rng = np.random.default_rng(6)
    vals = rng.integers(0, 2, 10_000).astype(bool).tolist()
    table = pa.table({"b": pa.array(vals)})
    # v2 pages encode booleans with RLE
    p = write(tmp_path, table, data_page_version="2.0", use_dictionary=False,
              column_encoding={"b": "RLE"})
    expect_column(p, "b", vals)


def test_multi_row_group_and_multi_page(tmp_path):
    vals = list(range(250_000))
    table = pa.table({"v": pa.array(vals, pa.int64())})
    p = write(tmp_path, table, row_group_size=50_000,
              data_page_size=4096, use_dictionary=False)
    with FileReader(p) as r:
        assert r.num_row_groups == 5
        rg0 = r.read_row_group(0)
        np.testing.assert_array_equal(rg0["v"].values, np.arange(50_000))
        all_cols = r.read_all()
        np.testing.assert_array_equal(all_cols["v"].values, np.array(vals))


def test_column_projection(tmp_path):
    table = pa.table({"a": [1, 2, 3], "b": ["x", "y", "z"], "c": [1.0, 2.0, 3.0]})
    p = write(tmp_path, table)
    with FileReader(p, columns=["a", "c"]) as r:
        cols = r.read_all()
        assert set(cols) == {"a", "c"}
        np.testing.assert_array_equal(cols["a"].values, [1, 2, 3])


def test_nested_list_levels_decoded(tmp_path):
    table = pa.table({
        "lst": pa.array([[1, 2], None, [], [3, 4, 5]], pa.list_(pa.int64())),
    })
    p = write(tmp_path, table, use_dictionary=False)
    with FileReader(p) as r:
        cols = r.read_all()
    cd = cols["lst.list.element"]
    assert cd.max_def == 3 and cd.max_rep == 1
    np.testing.assert_array_equal(cd.values, [1, 2, 3, 4, 5])
    # slots: [1,2] -> d3r0,d3r1 | None -> d0r0 | [] -> d1r0 | [3,4,5] -> d3r0,d3r1,d3r1
    np.testing.assert_array_equal(cd.def_levels, [3, 3, 0, 1, 3, 3, 3])
    np.testing.assert_array_equal(cd.rep_levels, [0, 1, 0, 0, 0, 1, 1])


def test_int96_timestamps(tmp_path):
    import datetime

    ts = [datetime.datetime(2020, 1, 1) + datetime.timedelta(hours=i) for i in range(100)]
    table = pa.table({"t": pa.array(ts, pa.timestamp("ns"))})
    p = write(tmp_path, table, use_deprecated_int96_timestamps=True)
    with FileReader(p) as r:
        cols = r.read_all()
    assert cols["t"].values.shape == (100, 3)


def test_crc_validation(tmp_path):
    table = pa.table({"v": pa.array(range(1000), pa.int64())})
    p = write(tmp_path, table, write_page_checksum=True, use_dictionary=False)
    with FileReader(p, validate_crc=True) as r:
        np.testing.assert_array_equal(r.read_all()["v"].values, np.arange(1000))
    # corrupt one byte of page *payload* (end of chunk, past the header) -> CRC
    # must catch it; without validation the corrupt value is returned silently
    blob = bytearray(p.read_bytes())
    with FileReader(blob) as probe:
        md = probe.metadata.row_groups[0].columns[0].meta_data
        end = md.data_page_offset + md.total_compressed_size
    blob[end - 10] ^= 0xFF
    with pytest.raises(ParquetError, match="CRC"):
        with FileReader(bytes(blob), validate_crc=True) as r:
            r.read_all()
    with FileReader(bytes(blob), validate_crc=False) as r:
        assert not np.array_equal(r.read_all()["v"].values, np.arange(1000))


def test_memory_budget(tmp_path):
    from tpu_parquet.alloc import MemoryBudgetExceeded

    table = pa.table({"v": pa.array(range(100_000), pa.int64())})
    p = write(tmp_path, table, use_dictionary=False)
    with FileReader(p, max_memory=1000) as r:
        with pytest.raises(MemoryBudgetExceeded):
            r.read_all()
    with FileReader(p, max_memory=100 * 1024 * 1024) as r:
        assert len(r.read_all()["v"].values) == 100_000


def test_metadata_accessors(tmp_path):
    table = pa.table({"v": [1, 2, 3]})
    p = write(tmp_path, table)
    with FileReader(p) as r:
        assert r.num_rows == 3
        assert "parquet-cpp-arrow" in r.created_by
        assert r.row_group_num_rows(0) == 3
        assert len(r.columns()) == 1
        # pyarrow stashes its schema in key-value metadata
        assert isinstance(r.key_value_metadata(), dict)


def test_empty_table(tmp_path):
    table = pa.table({"v": pa.array([], pa.int64())})
    p = write(tmp_path, table)
    with FileReader(p) as r:
        assert r.num_rows == 0
        cols = r.read_all()
        assert len(cols["v"].values) == 0


def test_set_selected_columns_midread(tmp_path):
    """SetSelectedColumns parity (schema.go:347-367): re-project between row
    groups; unselected chunks are seeked past, not decoded."""
    import io

    from tpu_parquet.format import FieldRepetitionType as FRT, Type
    from tpu_parquet.schema.core import build_schema, data_column
    from tpu_parquet.writer import FileWriter

    schema = build_schema([
        data_column("a", Type.INT64, FRT.REQUIRED),
        data_column("b", Type.INT64, FRT.REQUIRED),
    ])
    buf = io.BytesIO()
    with FileWriter(buf, schema) as w:
        for g in range(3):
            for i in range(10):
                w.write_row({"a": g * 100 + i, "b": -(g * 100 + i)})
            w.flush_row_group()
    with FileReader(io.BytesIO(buf.getvalue())) as r:
        g0 = r.read_row_group(0)
        assert set(g0) == {"a", "b"}
        r.set_selected_columns(["b"])
        g1 = r.read_row_group(1)
        assert set(g1) == {"b"} and g1["b"].values[0] == -100
        r.set_selected_columns(None)
        g2 = r.read_row_group(2)
        assert set(g2) == {"a", "b"}
        with pytest.raises(ParquetError, match="no schema columns"):
            r.set_selected_columns(["nope"])


def test_set_selected_columns_failure_keeps_selection(tmp_path):
    """A failed re-projection must leave the previous selection intact —
    not an applied-empty selection that silently reads {}."""
    import io

    from tpu_parquet.format import FieldRepetitionType as FRT, Type
    from tpu_parquet.schema.core import build_schema, data_column
    from tpu_parquet.writer import FileWriter

    schema = build_schema([data_column("a", Type.INT64, FRT.REQUIRED)])
    buf = io.BytesIO()
    with FileWriter(buf, schema) as w:
        w.write_row({"a": 7})
    with FileReader(io.BytesIO(buf.getvalue())) as r:
        with pytest.raises(ParquetError):
            r.set_selected_columns(["typo"])
        g = r.read_row_group(0)  # selection unchanged: still decodes "a"
        assert set(g) == {"a"} and g["a"].values[0] == 7
