"""Statistics-based row-group pruning (predicate pushdown).

Soundness oracle: for random data and random predicates, any row group the
pruner drops must contain ZERO matching rows (brute-force check); pruning is
allowed to keep non-matching groups (conservative), never to drop matching
ones.  Reader integration: pruned groups' bytes are never read, and both
readers (host + device) skip them in iteration.
"""

import io

import numpy as np
import pytest

from tpu_parquet.device_reader import DeviceFileReader
from tpu_parquet.errors import ParquetError
from tpu_parquet.format import FieldRepetitionType as FRT, Type
from tpu_parquet.predicate import col, prune_row_groups
from tpu_parquet.reader import FileReader
from tpu_parquet.schema.core import build_schema, data_column
from tpu_parquet.writer import FileWriter

RNG = np.random.default_rng(5)


def _file(rows_per_group=100, groups=8, with_nulls=True):
    schema = build_schema([
        data_column("a", Type.INT64, FRT.REQUIRED),
        data_column("b", Type.DOUBLE, FRT.REQUIRED),
        data_column("x", Type.INT32, FRT.OPTIONAL),
    ])
    buf = io.BytesIO()
    all_rows = []
    with FileWriter(buf, schema) as w:  # explicit flush = one group per batch
        for g in range(groups):
            base = g * 1000
            rows = [
                {
                    "a": int(base + RNG.integers(0, 500)),
                    "b": float(g) + float(RNG.uniform(0, 1)),
                    "x": (None if with_nulls and RNG.random() < 0.3
                          else int(RNG.integers(-50, 50))),
                }
                for _ in range(rows_per_group)
            ]
            for row in rows:
                w.write_row(row)
            w.flush_row_group()
            all_rows.append(rows)
    return buf.getvalue(), all_rows


def _matches(row, pred_fn):
    return pred_fn(row)


PREDS = [
    (col("a") > 3500, lambda r: r["a"] > 3500),
    (col("a") <= 1200, lambda r: r["a"] <= 1200),
    ((col("a") >= 2000) & (col("a") < 3000),
     lambda r: 2000 <= r["a"] < 3000),
    (col("b") < 2.0, lambda r: r["b"] < 2.0),
    ((col("a") > 6800) | (col("b") < 0.5),
     lambda r: r["a"] > 6800 or r["b"] < 0.5),
    (~(col("a") > 3500), lambda r: not (r["a"] > 3500)),
    (col("a") == 123456, lambda r: r["a"] == 123456),
    (col("x").is_null(), lambda r: r["x"] is None),
    (col("x").not_null(), lambda r: r["x"] is not None),
    (col("a").between(1000, 1999), lambda r: 1000 <= r["a"] <= 1999),
]


@pytest.mark.parametrize("idx", range(len(PREDS)))
def test_pruning_soundness(idx):
    pred, oracle = PREDS[idx]
    data, all_rows = _file()
    with FileReader(io.BytesIO(data)) as r:
        keep = prune_row_groups(r.metadata, r.schema, pred)
    assert len(keep) == len(all_rows)
    for g, (kept, rows) in enumerate(zip(keep, all_rows)):
        if not kept:
            assert not any(oracle(row) for row in rows), (
                f"group {g} pruned but contains matching rows"
            )


def test_pruning_actually_prunes():
    data, _ = _file()
    with FileReader(io.BytesIO(data), row_filter=col("a") > 6000) as r:
        kept = [i for i in range(r.num_row_groups) if r.row_group_selected(i)]
        assert 0 < len(kept) < r.num_row_groups  # prunes some, not all
        groups = list(r.iter_row_groups())
        assert len(groups) == len(kept)
        # every surviving group's max >= filter bound
        for cols in groups:
            assert int(np.asarray(cols["a"].values).max()) > 6000


def test_iter_rows_respects_filter():
    data, all_rows = _file()
    pred, oracle = (col("a") <= 1200, lambda r: r["a"] <= 1200)
    with FileReader(io.BytesIO(data), row_filter=pred) as r:
        got = list(r.iter_rows())
    # all matching rows are present (pruning never loses matches)
    want_matching = [row for rows in all_rows for row in rows if oracle(row)]
    got_a = {row["a"] for row in got}
    for row in want_matching:
        assert row["a"] in got_a


def test_device_reader_filter():
    data, all_rows = _file()
    with DeviceFileReader(io.BytesIO(data), row_filter=col("a") > 6000) as r:
        n_groups = sum(1 for _ in r.iter_row_groups())
    with FileReader(io.BytesIO(data), row_filter=col("a") > 6000) as hr:
        kept = [i for i in range(hr.num_row_groups) if hr.row_group_selected(i)]
    assert n_groups == len(kept) < len(all_rows)


def test_unknown_column_raises():
    data, _ = _file()
    with pytest.raises(ParquetError, match="unknown column"):
        FileReader(io.BytesIO(data), row_filter=col("nope") > 1)


def test_missing_stats_never_prunes():
    data, _ = _file()
    with FileReader(io.BytesIO(data)) as r:
        # strip statistics from the footer copy
        for rg in r.metadata.row_groups:
            for c in rg.columns:
                c.meta_data.statistics = None
        keep = prune_row_groups(r.metadata, r.schema, col("a") > 10**9)
    assert all(keep)


def test_string_stats_pruning(tmp_path):
    import pyarrow as pa
    import pyarrow.parquet as pq

    p = tmp_path / "s.parquet"
    names = [f"{c}{i:03d}" for c in "abcd" for i in range(100)]
    pq.write_table(pa.table({"s": names}), p, row_group_size=100)
    with FileReader(p, row_filter=col("s") >= "c") as r:
        kept = [i for i in range(r.num_row_groups) if r.row_group_selected(i)]
        assert kept == [2, 3]
        vals = [v for cols in r.iter_row_groups()
                for v in cols["s"].values.to_list()]
    assert vals and all(v >= b"c" for v in vals)  # only c/d groups decoded


def test_all_null_group_comparison_pruned():
    schema = build_schema([data_column("x", Type.INT32, FRT.OPTIONAL)])
    buf = io.BytesIO()
    with FileWriter(buf, schema) as w:
        for v in (None, 7):
            w.write_row({"x": v})
            w.flush_row_group()
    with FileReader(io.BytesIO(buf.getvalue())) as r:
        keep = prune_row_groups(r.metadata, r.schema, col("x") > 0)
    assert keep == [False, True]  # all-null group can satisfy no comparison


def test_float_nan_ne_not_pruned():
    """A NaN row satisfies != and negated comparisons; float groups must
    never be pruned by them (stats exclude NaNs)."""
    schema = build_schema([data_column("b", Type.DOUBLE, FRT.REQUIRED)])
    buf = io.BytesIO()
    with FileWriter(buf, schema) as w:
        w.write_row({"b": 5.0})
        w.write_row({"b": float("nan")})
    data = buf.getvalue()
    with FileReader(io.BytesIO(data)) as r:
        for pred in (col("b") != 5.0, ~(col("b") < 6.0), ~(col("b") <= 5.0)):
            keep = prune_row_groups(r.metadata, r.schema, pred)
            assert keep == [True], pred


def test_unsigned_logical_type_not_pruned():
    """logicalType-only UINT columns: signed decode of stats is wrong-order;
    must degrade to no-evidence instead of pruning."""
    from tpu_parquet.format import IntType, LogicalType
    from tpu_parquet.schema.core import ColumnParameters

    schema = build_schema([data_column(
        "u", Type.INT32, FRT.REQUIRED,
        ColumnParameters(logical_type=LogicalType(
            INTEGER=IntType(bitWidth=32, isSigned=False))),
    )])
    buf = io.BytesIO()
    with FileWriter(buf, schema) as w:
        # stored bits 0xFFFFFFFF = unsigned 4294967295; signed decode sees -1
        w.write_row({"u": -1})
    with FileReader(io.BytesIO(buf.getvalue())) as r:
        keep = prune_row_groups(r.metadata, r.schema,
                                col("u") > 3_000_000_000)
    assert keep == [True]


def test_num_selected_rows():
    data, all_rows = _file()
    with FileReader(io.BytesIO(data), row_filter=col("a") > 6000) as r:
        kept = [i for i in range(r.num_row_groups) if r.row_group_selected(i)]
        assert r.num_selected_rows == sum(
            r.row_group_num_rows(i) for i in kept)
        assert r.num_rows == sum(len(rows) for rows in all_rows)


def test_parse_filter_grammar():
    from tpu_parquet.predicate import parse_filter

    data, all_rows = _file()
    with FileReader(io.BytesIO(data)) as r:
        for text, oracle in [
            ("a > 3500", lambda row: row["a"] > 3500),
            ("3500 < a", lambda row: row["a"] > 3500),
            ("a > 2000 and a < 3000 or b < 0.5",
             lambda row: (2000 < row["a"] < 3000) or row["b"] < 0.5),
            ("not (a > 3500)", lambda row: not (row["a"] > 3500)),
            ("x == None", lambda row: row["x"] is None),
            ("x != None", lambda row: row["x"] is not None),
            ("a >= -1", lambda row: True),
        ]:
            keep = prune_row_groups(r.metadata, r.schema, parse_filter(text))
            for kept, rows in zip(keep, all_rows):
                if not kept:
                    assert not any(oracle(row) for row in rows), text


def test_parse_filter_rejects():
    from tpu_parquet.predicate import parse_filter

    for bad in ("a >", "import os", "a + 1 > 2", "f(x) > 1", "a > b",
                "a > None", "1 < a < 3"):
        with pytest.raises(ParquetError):
            parse_filter(bad)


def test_cli_stats_and_filter(tmp_path, capsys):
    from tpu_parquet.cli import pq_tool

    def run_tool(args):
        out = io.StringIO()
        parsed = pq_tool.build_parser().parse_args(args)
        rc = parsed.func(parsed, out=out)
        return rc, out.getvalue()

    data, _ = _file()
    p = tmp_path / "f.parquet"
    p.write_bytes(data)
    rc, out = run_tool(["stats", str(p)])
    assert rc == 0
    assert "row group 0" in out and "min=" in out and "nulls=" in out
    rc, out = run_tool(["rowcount", "--filter", "a > 6000", str(p)])
    assert rc == 0
    n = int(out.strip())
    assert 0 < n < 800
    rc, out = run_tool(["head", "-n", "3", "--filter", "a > 6000", str(p)])
    assert rc == 0
    assert len(out.strip().splitlines()) == 3


def test_decimal_columns_never_pruned(tmp_path):
    """DECIMAL stats order numerically (and rows yield scaled Decimals) —
    pruning on them would be unsound; must degrade to no-evidence."""
    import pyarrow as pa
    import pyarrow.parquet as pq
    from decimal import Decimal

    p = tmp_path / "d.parquet"
    pq.write_table(pa.table({
        "d": pa.array([Decimal("-0.05"), Decimal("0.02")],
                      type=pa.decimal128(9, 2)),
        "di": pa.array([Decimal("12.34"), Decimal("99.99")],
                       type=pa.decimal128(5, 2)),
    }), p)
    with FileReader(p) as r:
        for text in ("d < 100", "di < 100", "d > 100"):
            from tpu_parquet.predicate import parse_filter
            keep = prune_row_groups(r.metadata, r.schema, parse_filter(text))
            assert all(keep), text


def test_constructor_failure_closes_file(tmp_path):
    """The fd must close EAGERLY on constructor failure — not by refcount
    luck.  Holding every exception's traceback keeps the half-built reader
    (and, absent the fix, its open file object) alive, so a leak would show
    up as a growing /proc/self/fd count."""
    import os

    data, _ = _file()
    p = tmp_path / "f.parquet"
    p.write_bytes(data)
    held = []
    before = len(os.listdir("/proc/self/fd"))
    for _ in range(8):
        try:
            FileReader(str(p), row_filter=col("typo") > 1)
            raise AssertionError("expected ParquetError")
        except ParquetError as e:
            held.append(e)  # tb pins the half-built reader alive
    after = len(os.listdir("/proc/self/fd"))
    assert after == before, f"leaked {after - before} fds"
    del held


def test_page_level_pruning_device_reader(tmp_path):
    """Page-level predicate pushdown (beyond the reference): within a
    surviving row group, whole-page-aligned runs the predicate provably
    cannot match are skipped — never decompressed, staged, or decoded.
    Yielded rows stay a SUPERSET of matching rows and identical across
    columns; pages_pruned lands in ReaderStats."""
    import numpy as np
    from tpu_parquet.device_reader import DeviceFileReader
    from tpu_parquet.format import (
        CompressionCodec, FieldRepetitionType as FRT, Type,
    )
    from tpu_parquet.schema.core import build_schema, data_column
    from tpu_parquet.writer import FileWriter

    n = 40000
    sorted_keys = np.arange(n, dtype=np.int64) * 3          # sorted -> prunable
    payload = np.arange(n, dtype=np.int64) * 7 + 1
    schema = build_schema([
        data_column("k", Type.INT64, FRT.REQUIRED),
        data_column("v", Type.INT64, FRT.REQUIRED),
    ])
    p = str(tmp_path / "pp.parquet")
    with FileWriter(p, schema, codec=CompressionCodec.SNAPPY,
                    use_dictionary=False, page_size=4096,
                    row_group_size=1 << 20) as w:
        w.write_columns({"k": sorted_keys, "v": payload})

    pred = col("k") >= int(sorted_keys[n - 2000])
    with DeviceFileReader(p, row_filter=pred) as r:
        ks, vs = [], []
        for rg in r.iter_row_groups():
            ks.append(np.asarray(rg["k"].to_host()))
            vs.append(np.asarray(rg["v"].to_host()))
        st = r.stats()
    ks = np.concatenate(ks)
    vs = np.concatenate(vs)
    assert st.pages_pruned > 0, "no pages pruned on a sorted filter column"
    # identical row set across columns, aligned
    assert len(ks) == len(vs)
    assert np.array_equal(vs, (ks // 3) * 7 + 1)
    # superset of matching rows, subset of all rows
    want = sorted_keys[sorted_keys >= int(sorted_keys[n - 2000])]
    assert set(want).issubset(set(ks.tolist()))
    assert len(ks) < n
    # unfiltered read unchanged
    with DeviceFileReader(p) as r:
        total = sum(len(np.asarray(rg["k"].to_host()))
                    for rg in r.iter_row_groups())
        assert r.stats().pages_pruned == 0
    assert total == n


def test_page_pruning_misaligned_column_boundaries(tmp_path):
    """Columns with DIFFERENT pages-per-row (int32 vs int64 vs strings) must
    stay row-aligned after pruning: droppable runs shrink to a fixed point
    of every selected column's page edges.  With no shared interior edges
    the sound outcome is NO pruning (conservative by design — sub-page row
    surgery would need per-column defined-rank gathers); alignment and
    values must be exact either way."""
    import numpy as np
    from tpu_parquet.column import ByteArrayData, ColumnData
    from tpu_parquet.device_reader import DeviceFileReader
    from tpu_parquet.format import (
        CompressionCodec, ConvertedType, LogicalType, StringType,
    )
    from tpu_parquet.schema.core import ColumnParameters

    n = 30000
    k = np.arange(n, dtype=np.int64) * 5
    v32 = (np.arange(n) % 1000).astype(np.int32)
    s = [f"sv{i % 300:03d}".encode() for i in range(n)]
    offs = np.cumsum([0] + [len(x) for x in s]).astype(np.int64)
    heap = np.frombuffer(b"".join(s), np.uint8).copy()
    S = ColumnParameters(logical_type=LogicalType(STRING=StringType()),
                         converted_type=ConvertedType.UTF8)
    schema = build_schema([
        data_column("k", Type.INT64, FRT.REQUIRED),
        data_column("v32", Type.INT32, FRT.REQUIRED),
        data_column("s", Type.BYTE_ARRAY, FRT.REQUIRED, S),
    ])
    p = str(tmp_path / "mis.parquet")
    with FileWriter(p, schema, codec=CompressionCodec.SNAPPY,
                    use_dictionary=False, page_size=3000,
                    row_group_size=1 << 22) as w:
        w.write_columns({
            "k": k, "v32": v32,
            "s": ColumnData(values=ByteArrayData(offsets=offs, heap=heap)),
        })
    pred = col("k") < int(k[3000])
    with DeviceFileReader(p, row_filter=pred) as r:
        rows = {"k": [], "v32": [], "s": []}
        for rg in r.iter_row_groups():
            rows["k"].append(np.asarray(rg["k"].to_host()))
            rows["v32"].append(np.asarray(rg["v32"].to_host()))
            sb = rg["s"].to_host()
            rows["s"].append(sb)
        st = r.stats()
    kk = np.concatenate(rows["k"])
    vv = np.concatenate(rows["v32"])
    n_s = sum(len(x) for x in rows["s"])
    # these three grids (375/750/333 rows per page) share no interior edge:
    # the fixed-point shrink must decline to prune rather than misalign
    assert st.pages_pruned == 0
    assert len(kk) == len(vv) == n_s == n, (len(kk), len(vv), n_s)
    idx = (kk // 5).astype(np.int64)
    assert np.array_equal(vv, v32[idx])
    assert (kk < int(k[3000])).sum() == 3000


def test_header_only_walk_matches_walk_pages(tmp_path):
    """_walk_headers_file (pruning planner's seek-based walk) must yield the
    SAME data-page ordinal sequence as chunk_decode.walk_pages — skip_pages
    indices computed by one are applied against the other."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    from tpu_parquet.chunk_decode import validate_chunk_meta, walk_pages
    from tpu_parquet.device_reader import DeviceFileReader
    from tpu_parquet.format import PageType
    from tpu_parquet.reader import FileReader

    p = str(tmp_path / "hdrs.parquet")
    n = 40_000
    pq.write_table(
        pa.table({
            "a": np.arange(n, dtype=np.int64),
            "s": pa.array([f"v{i % 13}" for i in range(n)]),  # dict page
        }),
        p, compression="snappy", row_group_size=n,
        data_page_size=4096,
    )
    with FileReader(p) as host:
        rg = host.metadata.row_groups[0]
        for chunk in rg.columns:
            leaf = {tuple(l.path): l for l in host.schema.leaves}[
                tuple(chunk.meta_data.path_in_schema)]
            md, offset = validate_chunk_meta(chunk, leaf)
            host._f.seek(offset)
            buf = host._f.read(md.total_compressed_size)
            want = [ps.header for ps in walk_pages(buf, md.num_values)
                    if ps.header.type in (PageType.DATA_PAGE,
                                          PageType.DATA_PAGE_V2)]
            got = DeviceFileReader._walk_headers_file(
                host._f, offset, md.total_compressed_size, md.num_values)
            assert len(got) == len(want) > 1
            for g, w in zip(got, want):
                gh = g.data_page_header or g.data_page_header_v2
                wh = w.data_page_header or w.data_page_header_v2
                assert gh.num_values == wh.num_values
