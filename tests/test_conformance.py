"""External-conformance tier: the reference's parquet-testing matrix, rebuilt.

The reference validates against ~20 apache/parquet-testing sample files
(/root/reference/parquet_test.go:17-43), the impala TPC-H customer golden
comparison (parquet_compatibility_test.go:18-91), and a parquet-mr Docker
interop matrix (compatibility/run_tests.bash:14-19).  Those corpora are not
available offline, so this tier recreates every file *shape* from that list
with pyarrow — the canonical Apache Parquet C++ implementation — as the
foreign writer, and goes further than the reference: where the Go tests only
assert that every row reads without error, these assert full-file value
equality against the independently-kept source data.

Two shapes pyarrow cannot write (unannotated repeated fields, BYTE_ARRAY
decimals) are written by our own writer and cross-read by pyarrow — the
write-side interop direction the reference gets from parquet-mr.
"""

import datetime
from decimal import Decimal

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from tpu_parquet.reader import FileReader


def roundtrip_rows(path):
    with FileReader(path) as r:
        return list(r.iter_rows_logical())


def norm(v):
    """Normalize a python value for cross-implementation comparison."""
    if isinstance(v, dict):
        return {k: norm(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [norm(x) for x in v]
    if isinstance(v, bytes):
        return v
    if isinstance(v, str):
        return v
    if isinstance(v, bool):
        return v
    if isinstance(v, float):
        return round(v, 9)
    return v


def assert_file_equals(path, expected_rows):
    got = roundtrip_rows(path)
    assert len(got) == len(expected_rows), (len(got), len(expected_rows))
    for i, (g, e) in enumerate(zip(got, expected_rows)):
        assert norm(g) == norm(e), f"row {i}: {g!r} != {e!r}"


# ---------------------------------------------------------------------------
# alltypes_plain / alltypes_dictionary / alltypes_plain.snappy
# (parquet_test.go:18-20 — 11-column mixed-type impala shape)
# ---------------------------------------------------------------------------

def _alltypes_table(n=8):
    rng = np.random.default_rng(0)
    return pa.table({
        "id": np.arange(n, dtype=np.int32),
        "bool_col": (np.arange(n) % 2 == 0),
        "tinyint_col": (np.arange(n) % 2).astype(np.int32),
        "smallint_col": (np.arange(n) % 2).astype(np.int32),
        "int_col": (np.arange(n) % 2).astype(np.int32),
        "bigint_col": ((np.arange(n) % 2) * 10).astype(np.int64),
        "float_col": ((np.arange(n) % 2) * 1.1).astype(np.float32),
        "double_col": (np.arange(n) % 2) * 10.1,
        "date_string_col": [f"0{(i % 3) + 1}/01/09".encode() for i in range(n)],
        "string_col": [str(i % 2).encode() for i in range(n)],
    })


def _expected_rows(table):
    return table.to_pylist()


@pytest.mark.parametrize("opts", [
    dict(use_dictionary=False, compression="none"),      # alltypes_plain
    dict(use_dictionary=True, compression="none"),       # alltypes_dictionary
    dict(use_dictionary=False, compression="snappy"),    # alltypes_plain.snappy
])
def test_alltypes_shapes(tmp_path, opts):
    t = _alltypes_table()
    p = tmp_path / "t.parquet"
    pq.write_table(t, p, **opts)
    assert_file_equals(p, _expected_rows(t))


def test_alltypes_with_int96_timestamp_reads(tmp_path):
    """INT96 timestamps (impala files): reference asserts readability only
    (parquet_test.go:61-65); we additionally check the value count."""
    n = 8
    t = _alltypes_table(n).append_column(
        "timestamp_col",
        pa.array([datetime.datetime(2009, 1, 1, 0, i) for i in range(n)],
                 type=pa.timestamp("ns")),
    )
    p = tmp_path / "t.parquet"
    pq.write_table(t, p, use_deprecated_int96_timestamps=True)
    rows = roundtrip_rows(p)
    assert len(rows) == n
    assert all(r["timestamp_col"] is not None for r in rows)


# ---------------------------------------------------------------------------
# binary.parquet (single BYTE_ARRAY column, parquet_test.go:21)
# ---------------------------------------------------------------------------

def test_binary(tmp_path):
    vals = [bytes([i]) for i in range(12)]
    t = pa.table({"foo": pa.array(vals, type=pa.binary())})
    p = tmp_path / "t.parquet"
    pq.write_table(t, p)
    assert_file_equals(p, _expected_rows(t))


# ---------------------------------------------------------------------------
# decimals: int32_decimal, int64_decimal, fixed_length_decimal(_legacy),
# byte_array_decimal (parquet_test.go:22,28-29,32-33)
# ---------------------------------------------------------------------------

def _decimal_expected(n, scale, kind, byte_width=None):
    out = []
    for i in range(1, n + 1):
        unscaled = i * 100
        if kind == "int":
            out.append(unscaled)
        else:
            nbytes = byte_width or max((unscaled.bit_length() + 8) // 8, 1)
            out.append(unscaled.to_bytes(nbytes, "big", signed=True))
    return out


@pytest.mark.parametrize("precision,kind", [
    (4, "int"),     # int32_decimal
    (10, "int"),    # int64_decimal
    (25, "flba"),   # fixed_length_decimal
])
def test_decimal_shapes(tmp_path, precision, kind):
    n = 24
    vals = [Decimal(i) for i in range(1, n + 1)]
    t = pa.table({"value": pa.array(vals, type=pa.decimal128(precision, 2))})
    p = tmp_path / "t.parquet"
    pq.write_table(t, p, store_decimal_as_integer=(kind == "int"))
    with FileReader(p) as r:
        got = [row["value"] for row in r.iter_rows_logical()]
    if kind == "int":
        assert got == _decimal_expected(n, 2, "int")
    else:
        import pyarrow.parquet as _pq
        byte_width = 11  # pyarrow FLBA width for decimal128(25, 2)
        assert got == _decimal_expected(n, 2, "flba", byte_width)


def test_byte_array_decimal_written_by_us_read_by_pyarrow(tmp_path):
    """BYTE_ARRAY decimal (parquet_test.go:22): pyarrow won't write this
    shape, so our writer produces it and pyarrow is the foreign reader."""
    from tpu_parquet.format import (
        ConvertedType, DecimalType, FieldRepetitionType as FRT, LogicalType, Type,
    )
    from tpu_parquet.schema.core import ColumnParameters, build_schema, data_column
    from tpu_parquet.writer import FileWriter

    n = 24
    schema = build_schema([
        data_column("value", Type.BYTE_ARRAY, FRT.REQUIRED, ColumnParameters(
            logical_type=LogicalType(DECIMAL=DecimalType(scale=2, precision=4)),
            converted_type=ConvertedType.DECIMAL, scale=2, precision=4,
        )),
    ])
    p = tmp_path / "t.parquet"
    expected = _decimal_expected(n, 2, "bytes")
    with FileWriter(p, schema) as w:
        for b in expected:
            w.write_row({"value": b})
    # our reader
    with FileReader(p) as r:
        got = [row["value"] for row in r.iter_rows_logical()]
    assert got == expected
    # foreign reader
    vals = pq.read_table(p)["value"].to_pylist()
    assert vals == [Decimal(i) for i in range(1, n + 1)]


# ---------------------------------------------------------------------------
# datapage_v2.snappy (v2 pages, strings + nulls, parquet_test.go:23)
# ---------------------------------------------------------------------------

def test_datapage_v2_snappy(tmp_path):
    t = pa.table({
        "a": ["abc", "abc", "abc", None, "abc"],
        "b": pa.array([1, 2, 3, 4, 5], type=pa.int32()),
        "c": pa.array([2.0, 3.0, 4.0, 5.0, 2.0]),
        "d": [True, True, True, False, True],
        "e": pa.array([[1, 2, 3], None, None, [1, 2, 3], [1, 2]],
                      type=pa.list_(pa.int32())),
    })
    p = tmp_path / "t.parquet"
    pq.write_table(t, p, compression="snappy", data_page_version="2.0")
    assert_file_equals(p, _expected_rows(t))


# ---------------------------------------------------------------------------
# delta_binary_packed / delta_encoding_{optional,required}_column
# (parquet_test.go:24-27)
# ---------------------------------------------------------------------------

def test_delta_binary_packed_many_widths(tmp_path):
    rng = np.random.default_rng(7)
    cols = {
        f"bitwidth{w}": rng.integers(-(1 << min(w, 62)), 1 << min(w, 62), 200)
        for w in (0, 1, 7, 15, 26, 40, 63)
    }
    cols["int_value"] = rng.integers(-(1 << 30), 1 << 30, 200).astype(np.int32)
    t = pa.table(cols)
    p = tmp_path / "t.parquet"
    pq.write_table(
        t, p, use_dictionary=False,
        column_encoding={c: "DELTA_BINARY_PACKED" for c in cols},
    )
    assert_file_equals(p, _expected_rows(t))


@pytest.mark.parametrize("optional", [True, False])
def test_delta_encoding_optional_required(tmp_path, optional):
    rng = np.random.default_rng(8)
    vals = rng.integers(-(1 << 40), 1 << 40, 100).tolist()
    if optional:
        vals = [None if i % 7 == 3 else v for i, v in enumerate(vals)]
    t = pa.table({"c": pa.array(vals, type=pa.int64())})
    p = tmp_path / "t.parquet"
    pq.write_table(t, p, use_dictionary=False,
                   column_encoding={"c": "DELTA_BINARY_PACKED"})
    assert_file_equals(p, _expected_rows(t))


# ---------------------------------------------------------------------------
# list_columns / nested_lists.snappy (parquet_test.go:34-35)
# ---------------------------------------------------------------------------

def test_list_columns(tmp_path):
    t = pa.table({
        "int64_list": pa.array(
            [[1, 2, 3], [None, 1], None, [4]], type=pa.list_(pa.int64())),
        "utf8_list": pa.array(
            [["abc", "efg", "hij"], None, ["xyz"], []],
            type=pa.list_(pa.string())),
    })
    p = tmp_path / "t.parquet"
    pq.write_table(t, p)
    assert_file_equals(p, _expected_rows(t))


def test_nested_lists_snappy(tmp_path):
    inner = pa.list_(pa.string())
    mid = pa.list_(inner)
    t = pa.table({
        "a": pa.array(
            [[[["a", "b"], ["c"]], [None, ["d"]]],
             [[["a", "b"], ["c", "d"]], [None, ["e"]]],
             [[["a", "b"], ["c", "d"], ["e"]], [None, ["f"]]]],
            type=pa.list_(mid)),
        "b": pa.array([1, 1, 1], type=pa.int32()),
    })
    p = tmp_path / "t.parquet"
    pq.write_table(t, p, compression="snappy")
    assert_file_equals(p, _expected_rows(t))


# ---------------------------------------------------------------------------
# nested_maps.snappy (map<string, map<int32, bool>>, parquet_test.go:36)
# ---------------------------------------------------------------------------

def test_nested_maps_snappy(tmp_path):
    inner = pa.map_(pa.int32(), pa.bool_())
    t = pa.table({
        "a": pa.array(
            [[("a", [(1, True), (2, False)])],
             [("b", [(1, True)])],
             [("c", None)],
             [("d", [])],
             [("e", [(1, True)])],
             [("f", [(3, True), (4, False), (5, True)])]],
            type=pa.map_(pa.string(), inner)),
        "b": pa.array([1] * 6, type=pa.int32()),
        "c": pa.array([1.0] * 6),
    })
    p = tmp_path / "t.parquet"
    pq.write_table(t, p, compression="snappy")
    got = roundtrip_rows(p)
    exp = t.to_pylist()
    assert len(got) == len(exp)
    for g, e in zip(got, exp):
        # pyarrow maps come back as lists of (k, v) pairs; ours as dicts
        e_map = {k: (dict(v) if v is not None else None) for k, v in e["a"]}
        assert norm(g["a"]) == norm(e_map)
        assert g["b"] == e["b"] and g["c"] == e["c"]


# ---------------------------------------------------------------------------
# nonnullable/nullable impala nested (struct/array/map torture,
# parquet_test.go:37-38) + nulls.snappy (parquet_test.go:39)
# ---------------------------------------------------------------------------

def _impala_nested_type(nullable):
    return pa.struct([
        ("a", pa.int32()),
        ("b", pa.list_(pa.int32())),
        ("c", pa.struct([("d", pa.list_(pa.list_(pa.struct([
            ("e", pa.int32()), ("f", pa.string())]))))])),
        ("g", pa.map_(pa.string(), pa.struct([
            ("h", pa.struct([("i", pa.list_(pa.float64()))]))]))),
    ])


@pytest.mark.parametrize("nullable", [False, True])
def test_impala_nested_shapes(tmp_path, nullable):
    typ = _impala_nested_type(nullable)
    base = {
        "a": 7,
        "b": [2, 3],
        "c": {"d": [[{"e": 1, "f": "x"}, {"e": 2, "f": "y"}], [{"e": 3, "f": "z"}]]},
        "g": [("k1", {"h": {"i": [1.5, 2.5]}})],
    }
    rows = [base, None if nullable else base]
    if not nullable:
        rows = [base, base]
    t = pa.table({"nested": pa.array(rows, type=typ),
                  "id": pa.array([1, 2], type=pa.int64())})
    p = tmp_path / "t.parquet"
    pq.write_table(t, p)
    got = roundtrip_rows(p)
    assert len(got) == 2
    g0 = got[0]["nested"]
    assert g0["a"] == 7 and g0["b"] == [2, 3]
    assert g0["c"]["d"][0][0] == {"e": 1, "f": "x"}
    assert norm(g0["g"]) == {"k1": {"h": {"i": [1.5, 2.5]}}}
    if nullable:
        assert got[1]["nested"] is None


def test_nulls_snappy(tmp_path):
    """struct<b_c_int:int32> where every value is null (nulls.snappy shape)."""
    typ = pa.struct([("b_c_int", pa.int32())])
    t = pa.table({"b_struct": pa.array([None] * 8, type=typ)})
    p = tmp_path / "t.parquet"
    pq.write_table(t, p, compression="snappy")
    got = roundtrip_rows(p)
    assert len(got) == 8
    assert all(r["b_struct"] is None for r in got)


# ---------------------------------------------------------------------------
# repeated_no_annotation (parquet_test.go:40): unannotated repeated group —
# pyarrow can't write it, so our writer produces it and both readers read it
# ---------------------------------------------------------------------------

def test_repeated_no_annotation_written_by_us(tmp_path):
    from tpu_parquet.format import (
        ConvertedType, FieldRepetitionType as FRT, Type,
    )
    from tpu_parquet.schema.core import (
        build_schema, data_column, group_column,
    )
    from tpu_parquet.writer import FileWriter

    schema = build_schema([
        data_column("id", Type.INT32, FRT.REQUIRED),
        group_column("phoneNumbers", [
            group_column("phone", [
                data_column("number", Type.INT64, FRT.REQUIRED),
                data_column("kind", Type.BYTE_ARRAY, FRT.OPTIONAL),
            ], FRT.REPEATED),
        ], FRT.OPTIONAL),
    ])
    rows = [
        {"id": 1, "phoneNumbers": None},
        {"id": 2, "phoneNumbers": {"phone": []}},
        {"id": 3, "phoneNumbers": {"phone": [
            {"number": 5555555555, "kind": None}]}},
        {"id": 4, "phoneNumbers": {"phone": [
            {"number": 1111111111, "kind": b"home"},
            {"number": 2222222222, "kind": None},
            {"number": 3333333333, "kind": b"mobile"}]}},
    ]
    p = tmp_path / "t.parquet"
    with FileWriter(p, schema) as w:
        for row in rows:
            w.write_row(row)
    with FileReader(p) as r:
        got = list(r.iter_rows())
    assert got[0]["phoneNumbers"] is None
    assert got[3]["phoneNumbers"]["phone"][0]["number"] == 1111111111
    assert got[3]["phoneNumbers"]["phone"][2]["kind"] == b"mobile"
    # foreign reader
    ft = pq.read_table(p)
    assert ft.num_rows == 4
    fl = ft.to_pylist()
    assert fl[3]["phoneNumbers"]["phone"][0]["number"] == 1111111111


# ---------------------------------------------------------------------------
# impala TPC-H customer golden (parquet_compatibility_test.go:18-91):
# {none,gzip,snappy} files against independently-kept golden values
# ---------------------------------------------------------------------------

CUSTOMER_GOLDEN = [
    (1, "Customer#000000001", "IVhzIApeRb ot,c,E", 15, "25-989-741-2988",
     Decimal("711.56"), "BUILDING", "regular, express deps"),
    (2, "Customer#000000002", "XSTf4,NCwDVaWNe6tEgvwfmRchLXak", 13,
     "23-768-687-3665", Decimal("121.65"), "AUTOMOBILE", "furiously special"),
    (3, "Customer#000000003", "MG9kdTD2WBHm", 1, "11-719-748-3364",
     Decimal("7498.12"), "AUTOMOBILE", "special packages wake"),
]


@pytest.mark.parametrize("codec", ["none", "gzip", "snappy"])
def test_customer_golden(tmp_path, codec):
    t = pa.table({
        "c_custkey": pa.array([r[0] for r in CUSTOMER_GOLDEN], pa.int64()),
        "c_name": [r[1] for r in CUSTOMER_GOLDEN],
        "c_address": [r[2] for r in CUSTOMER_GOLDEN],
        "c_nationkey": pa.array([r[3] for r in CUSTOMER_GOLDEN], pa.int32()),
        "c_phone": [r[4] for r in CUSTOMER_GOLDEN],
        "c_acctbal": pa.array([r[5] for r in CUSTOMER_GOLDEN],
                              pa.decimal128(12, 2)),
        "c_mktsegment": [r[6] for r in CUSTOMER_GOLDEN],
        "c_comment": [r[7] for r in CUSTOMER_GOLDEN],
    })
    p = tmp_path / "customer.parquet"
    pq.write_table(t, p, compression=codec, store_decimal_as_integer=True)
    got = roundtrip_rows(p)
    for g, e in zip(got, CUSTOMER_GOLDEN):
        assert g["c_custkey"] == e[0]
        assert g["c_name"] == e[1]
        assert g["c_address"] == e[2]
        assert g["c_nationkey"] == e[3]
        assert g["c_phone"] == e[4]
        assert g["c_acctbal"] == int(e[5] * 100)  # unscaled DECIMAL(12,2)
        assert g["c_mktsegment"] == e[6]
        assert g["c_comment"] == e[7]


# ---------------------------------------------------------------------------
# write-side interop matrix (compatibility/run_tests.bash:14-19 analog):
# our writer → pyarrow reads identical values, {codec} × {page version}
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("codec_name", ["UNCOMPRESSED", "GZIP", "SNAPPY", "ZSTD"])
@pytest.mark.parametrize("v2", [False, True])
def test_writer_interop_matrix(tmp_path, codec_name, v2):
    from conftest import require_codec
    from tpu_parquet.column import ByteArrayData, ColumnData
    from tpu_parquet.format import (
        CompressionCodec, ConvertedType, FieldRepetitionType as FRT,
        LogicalType, StringType, Type,
    )
    from tpu_parquet.schema.core import ColumnParameters, build_schema, data_column
    from tpu_parquet.writer import FileWriter

    require_codec(getattr(CompressionCodec, codec_name))

    rng = np.random.default_rng(99)
    n = 1000
    ints = rng.integers(-(1 << 50), 1 << 50, n)
    doubles = rng.standard_normal(n)
    strs = [f"value_{i % 17}".encode() for i in range(n)]
    offs = np.zeros(n + 1, dtype=np.int64)
    np.cumsum([len(s) for s in strs], out=offs[1:])
    heap = np.frombuffer(b"".join(strs), dtype=np.uint8).copy()

    schema = build_schema([
        data_column("i", Type.INT64, FRT.REQUIRED),
        data_column("d", Type.DOUBLE, FRT.REQUIRED),
        data_column("s", Type.BYTE_ARRAY, FRT.REQUIRED, ColumnParameters(
            logical_type=LogicalType(STRING=StringType()),
            converted_type=ConvertedType.UTF8)),
    ])
    p = tmp_path / "t.parquet"
    with FileWriter(p, schema, codec=getattr(CompressionCodec, codec_name),
                    data_page_version=2 if v2 else 1) as w:
        w.write_columns({
            "i": ints, "d": doubles,
            "s": ColumnData(values=ByteArrayData(offsets=offs, heap=heap)),
        })
    ft = pq.read_table(p)
    np.testing.assert_array_equal(ft["i"].to_numpy(), ints)
    np.testing.assert_array_equal(ft["d"].to_numpy(), doubles)
    assert ft["s"].to_pylist() == [s.decode() for s in strs]
