"""Request-lifecycle resilience for the serve tier (ISSUE 11).

The contracts under test, in rough order of importance:

- **Deadline propagation**: `ScanRequest(deadline_s=)` rides the cancel
  token into every store read and unit boundary — an expired request
  raises a typed `DeadlineExceededError` quickly, stops issuing new IO,
  and releases its admission-budget charge; everyone else is untouched.
- **Cancellation**: `ticket.cancel()` has the same containment contract
  (`CancelledError`), and a cancelled request leaves no orphaned in-flight
  range registered anywhere a flight dump would show.
- **Per-scan RetryBudget** (the PR 7 scoping fix): two concurrent requests
  on ONE shared store spend their OWN budgets — one flaky request can
  neither drain nor refresh another's.
- **Hedged reads**: a fetch slower than the hedge delay gets a duplicate,
  first success wins with the loser accounted (wasted bytes, verified
  identity), results bit-identical, no leaked racer threads.
- **Circuit breakers**: N classified failures open a file's circuit;
  requests fast-fail with `CircuitOpenError` NAMING the file; healthy
  files are unaffected; a half-open probe closes it after cooldown.
- **Brownout**: past `TPQ_SERVE_BROWNOUT` occupancy, low-priority requests
  shed with a drain-rate `retry_after_s` while high priority still admits.
- **Chaos harness**: a seeded `ChaosSchedule` (stall storm + per-file
  blackout) over a live ScanService proves the whole matrix
  deterministically; its blob codec round-trips and rejects lies (fuzz
  target #17's corpus rides tests/fuzz_corpus).
"""

import io
import json
import os
import threading
import time

import numpy as np
import pytest

from tpu_parquet.column import ByteArrayData, ColumnData
from tpu_parquet.errors import (CancelledError, CircuitOpenError,
                                DeadlineExceededError, OverloadError,
                                ParquetError, RetryExhaustedError)
from tpu_parquet.format import (CompressionCodec, FieldRepetitionType as FRT,
                                Type)
from tpu_parquet.iostore import (FaultInjectingStore, FaultSpec,
                                 GenericRangeStore, IOConfig, LocalStore)
from tpu_parquet.reader import FileReader
from tpu_parquet.resilience import (BreakerBoard, CancelToken, ChaosPhase,
                                    ChaosSchedule)
from tpu_parquet.schema.core import build_schema, data_column
from tpu_parquet.serve import (PRIORITY_HIGH, PRIORITY_LOW, ScanRequest,
                               ScanService)
from tpu_parquet.writer import FileWriter


def _strings(vals):
    return ColumnData(values=ByteArrayData(
        offsets=np.cumsum([0] + [len(v) for v in vals]),
        heap=np.frombuffer(b"".join(vals), np.uint8).copy(),
    ))


def _write_file(path, seed=0, groups=3, rows=500):
    rng = np.random.default_rng(seed)
    schema = build_schema([
        data_column("a", Type.INT64, FRT.REQUIRED),
        data_column("s", Type.BYTE_ARRAY, FRT.REQUIRED),
    ])
    pool = [b"alpha", b"beta", b"gamma", b"delta", b""]
    with open(path, "wb") as fh:
        with FileWriter(fh, schema, codec=CompressionCodec.SNAPPY) as w:
            for _g in range(groups):
                svals = [pool[i] for i in rng.integers(0, len(pool), rows)]
                w.write_columns({
                    "a": rng.integers(-(1 << 40), 1 << 40, rows),
                    "s": _strings(svals),
                })
                w.flush_row_group()
    return path


@pytest.fixture(scope="module")
def files(tmp_path_factory):
    d = tmp_path_factory.mktemp("resilience")
    return [_write_file(str(d / f"f{i}.parquet"), seed=i) for i in range(3)]


def _latency_factory(latency_s, **cfg):
    return lambda f: FaultInjectingStore(
        LocalStore(f), FaultSpec(latency_s=latency_s),
        config=IOConfig(backoff_ms=0, **cfg))


# ---------------------------------------------------------------------------
# deadline propagation
# ---------------------------------------------------------------------------

def test_deadline_expired_typed_fast_and_budget_released(files):
    # 6 chunks x 60ms injected latency each = ~360ms sequential floor; a
    # 100ms deadline must fail LONG before that — typed, with the budget
    # free and the transport left idle (no new reads after the verdict)
    svc = ScanService(concurrency=2, queue_depth=8, max_memory=1 << 24,
                      store=_latency_factory(0.06))
    t0 = time.perf_counter()
    with pytest.raises(DeadlineExceededError):
        svc.scan(ScanRequest(files[0], deadline_s=0.1), timeout=30)
    elapsed = time.perf_counter() - t0
    assert elapsed < 2.0, f"expiry took {elapsed:.2f}s — not a fast fail"
    assert svc._budget.held == 0
    stores = list(svc._served_stores)
    reads_after = [s.stats.progress() for s in stores]
    time.sleep(0.25)
    assert [s.stats.progress() for s in stores] == reads_after, \
        "reads continued after the deadline verdict"
    for s in stores:
        assert "inflight_offset" not in s.stats.sample()
    sv = svc.serve_stats()
    assert sv["failed"] == 1 and sv["deadline_exceeded"] == 1
    svc.close()


def test_deadline_expired_in_queue_never_reads(files):
    # one worker wedged on a slow request: a queued request whose deadline
    # expires BEFORE a worker frees up must fail without reading a byte
    svc = ScanService(concurrency=1, queue_depth=8,
                      store=_latency_factory(0.08))
    slow = svc.submit(ScanRequest(files[0]))
    quick = svc.submit(ScanRequest(files[1], deadline_s=0.01))
    with pytest.raises(DeadlineExceededError):
        quick.result(30)
    slow.result(60)
    assert svc.serve_stats()["deadline_exceeded"] == 1
    svc.close()


def test_deadline_reaches_store_reads(files):
    # the deadline must bind INSIDE read_range too: a single stalled fetch
    # longer than the whole deadline resolves at ~deadline, not stall_s
    store = FaultInjectingStore(
        LocalStore(open(files[0], "rb")),
        FaultSpec(stall_first=1, stall_s=5.0),
        config=IOConfig(backoff_ms=0, retries=0))
    tok = store.begin_scan(cancel=CancelToken.with_timeout(0.15))
    t0 = time.perf_counter()
    with pytest.raises(DeadlineExceededError):
        store.read_range(4, 1000, scan=tok)
    assert time.perf_counter() - t0 < 2.0
    store.close()


# ---------------------------------------------------------------------------
# cancellation
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("prefetch", [0, 4])
def test_cancel_mid_flight_typed_and_no_orphans(files, prefetch):
    spec = FaultSpec(stall_first=1, stall_s=10.0)
    stores = []

    def factory(f):
        st = FaultInjectingStore(LocalStore(f), spec,
                                 config=IOConfig(backoff_ms=0, retries=2))
        stores.append(st)
        return st

    svc = ScanService(concurrency=2, queue_depth=8, max_memory=1 << 24,
                      store=factory)
    ticket = svc.submit(ScanRequest(files[0], prefetch=prefetch))
    time.sleep(0.1)  # let it reach the injected stall
    ticket.cancel()
    for st in stores:
        st.release()  # unblock the stall so the attempt can observe cancel
    with pytest.raises(CancelledError):
        ticket.result(30)
    # no orphaned in-flight range anywhere a flight dump would report it
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        if all("inflight_offset" not in st.stats.sample() for st in stores):
            break
        time.sleep(0.02)
    for st in stores:
        assert "inflight_offset" not in st.stats.sample()
    assert svc._budget.held == 0
    sv = svc.serve_stats()
    assert sv["cancelled"] == 1 and sv["failed"] == 1
    svc.close()


def test_cancel_before_start_is_typed(files):
    svc = ScanService(concurrency=1, queue_depth=8,
                      store=_latency_factory(0.1))
    blocker = svc.submit(ScanRequest(files[0]))
    queued = svc.submit(ScanRequest(files[1]))
    queued.cancel()
    with pytest.raises(CancelledError):
        queued.result(30)
    blocker.result(60)
    svc.close()


def test_prefetch_map_cancel_releases_budget():
    from tpu_parquet.alloc import InFlightBudget
    from tpu_parquet.pipeline import prefetch_map

    budget = InFlightBudget(1 << 20)
    token = CancelToken()
    out = []
    gen = prefetch_map(range(100), lambda x: x * 2, prefetch=2,
                       budget=budget, cost=lambda x: 1024, cancel=token)
    out.append(next(gen))
    token.cancel()
    with pytest.raises(CancelledError):
        for v in gen:
            out.append(v)
    assert budget.held == 0, "cancelled map left budget bytes charged"
    assert out[0] == 0


# ---------------------------------------------------------------------------
# per-scan RetryBudget scoping (the PR 7 fix)
# ---------------------------------------------------------------------------

def test_scan_tokens_isolate_retry_budgets(files):
    with open(files[0], "rb") as f:
        st = FaultInjectingStore(LocalStore(f),
                                 config=IOConfig(retry_budget=3))
        t1 = st.begin_scan()
        t2 = st.begin_scan()
        assert t1.budget is not t2.budget
        assert t1.budget.spend() and t1.budget.spend()
        assert t2.budget.spent == 0, "budgets shared across scan tokens"
        st.close()


def test_concurrent_requests_one_store_budget_isolation(files):
    # ONE FaultInjectingStore instance shared by two concurrent request
    # streams over the same file: A's projection reads the big 'a' chunks
    # (every attempt faults; its budget of 2 must exhaust), B's reads the
    # small 's' chunks (healthy; every scan re-begins and must never see
    # A's spends or refresh A's budget mid-failure)
    big = FaultSpec(fail_first=1 << 30, match=lambda o, s: s > 2000)
    with open(files[0], "rb") as f:
        store = FaultInjectingStore(
            LocalStore(f), big,
            config=IOConfig(retries=20, backoff_ms=0.1, retry_budget=2,
                            coalesce_gap=0))
        results = {"a": None, "b_ok": 0}

        def client_a():
            try:
                with FileReader(files[0], columns=["a"], store=store,
                                prefetch=2) as r:
                    r.read_all()
                results["a"] = "completed"
            except RetryExhaustedError as e:
                results["a"] = str(e)
            except Exception as e:  # noqa: BLE001
                results["a"] = f"WRONG: {e!r}"

        def client_b():
            for _ in range(4):
                with FileReader(files[0], columns=["s"], store=store,
                                prefetch=0) as r:
                    r.read_all()
                results["b_ok"] += 1

        ta = threading.Thread(target=client_a)
        tb = threading.Thread(target=client_b)
        ta.start(); tb.start()
        ta.join(60); tb.join(60)
        store.close()
    # A exhausted ITS OWN budget (2), even while B's begin_scan calls were
    # minting fresh tokens — the store-wide reset bug would have kept
    # refreshing A's budget until its 21-attempt retry cap fired instead
    assert results["a"] is not None and "retry budget" in results["a"], \
        results["a"]
    assert results["b_ok"] == 4, "healthy concurrent scans were impacted"


# ---------------------------------------------------------------------------
# hedged reads
# ---------------------------------------------------------------------------

class _SlowFirstStore(GenericRangeStore):
    """First attempt at any offset is slow; duplicates are fast.  The
    deterministic hedge showcase — and `payload_fn` lets the mismatch test
    make the duplicate return different bytes."""

    def __init__(self, data, config, slow_s=0.4, payload_fn=None):
        super().__init__(config=config)
        self.data = data
        self.slow_s = slow_s
        self.payload_fn = payload_fn
        self.calls = {}
        self._calls_lock = threading.Lock()

    def size(self):
        return len(self.data)

    def _fetch_once(self, offset, size, timeout):
        with self._calls_lock:
            n = self.calls.get(offset, 0)
            self.calls[offset] = n + 1
        if n == 0:
            time.sleep(self.slow_s)
        buf = self.data[offset: offset + size]
        if self.payload_fn is not None:
            buf = self.payload_fn(buf, n)
        return buf


def test_hedged_read_first_wins_and_loser_accounted():
    data = bytes(range(256)) * 64
    st = _SlowFirstStore(data, IOConfig(hedge_ms=20, backoff_ms=0))
    t0 = time.perf_counter()
    buf = st.read_range(512, 1024)
    fast = time.perf_counter() - t0
    assert buf == data[512:1536]  # bit-identical to the object
    assert fast < st.slow_s, f"hedge did not cut the stall: {fast:.3f}s"
    d = st.stats.as_dict()
    assert d["hedges_issued"] == 1 and d["hedges_won"] == 1
    st.close()  # joins the slow primary racer
    d = st.stats.as_dict()
    assert d["hedges_wasted_bytes"] == 1024  # loser paid, accounted
    assert d["hedge_mismatches"] == 0
    assert not [t for t in threading.enumerate()
                if t.name.startswith("tpq-hedge")]


def test_hedged_read_mismatch_detected():
    data = b"x" * 4096
    # the duplicate (attempt 1) returns DIFFERENT bytes of the same length
    st = _SlowFirstStore(
        data, IOConfig(hedge_ms=10, backoff_ms=0), slow_s=0.3,
        payload_fn=lambda buf, n: buf if n == 0 else b"y" * len(buf))
    st.read_range(0, 100)
    st.close()
    assert st.stats.as_dict()["hedge_mismatches"] == 1


def test_hedge_auto_learns_p90_delay():
    data = bytes(range(256)) * 512
    st = _SlowFirstStore(data, IOConfig(hedge_ms=-1.0, backoff_ms=0),
                         slow_s=0.5)
    st.slow_s = 0.0  # warmup: fast everywhere, populate the latency hist
    for i in range(20):
        st.read_range(i * 128, 64)
    # auto mode hedges the slowest DECILE by definition, so a warmup read
    # may occasionally race itself — but a genuinely slow fetch must lose
    # to its duplicate decisively
    st.slow_s = 0.5  # now the first attempt at a NEW offset stalls
    won_before = st.stats.as_dict()["hedges_won"]
    t0 = time.perf_counter()
    buf = st.read_range(100_000, 256)
    assert buf == data[100_000:100_256]
    assert time.perf_counter() - t0 < 0.5
    assert st.stats.as_dict()["hedges_won"] == won_before + 1
    st.close()


def test_hedging_off_by_default():
    cfg = IOConfig.from_env()
    assert cfg.hedge_ms == 0.0
    st = _SlowFirstStore(b"z" * 1024, IOConfig(backoff_ms=0), slow_s=0.01)
    st.read_range(0, 64)
    assert st.stats.as_dict()["hedges_issued"] == 0
    st.close()


# ---------------------------------------------------------------------------
# circuit breakers
# ---------------------------------------------------------------------------

def _blackout_factory(victim_path, healthy_cfg=None):
    """Per-file store factory: the victim file always fails, others clean."""
    cfg = healthy_cfg or IOConfig(retries=1, backoff_ms=0, retry_budget=8)

    def factory(f):
        name = os.path.abspath(getattr(f, "name", "") or "")
        spec = (FaultSpec(fail_first=1 << 30)
                if name == os.path.abspath(victim_path) else FaultSpec())
        return FaultInjectingStore(LocalStore(f), spec, config=cfg)

    return factory


def test_circuit_trips_within_n_failures_and_names_file(files):
    victim, healthy = files[2], files[0]
    board = BreakerBoard(fails=2, window_s=60, cooldown_s=60)
    svc = ScanService(concurrency=2, queue_depth=32, breakers=board,
                      store=_blackout_factory(victim))
    failures = 0
    for _ in range(2):  # exactly N=2 classified failures trip the circuit
        with pytest.raises(RetryExhaustedError):
            svc.scan(ScanRequest(victim), timeout=60)
        failures += 1
    with pytest.raises(CircuitOpenError) as ei:
        svc.scan(ScanRequest(victim), timeout=60)
    assert ei.value.file == str(victim)
    assert ei.value.retry_after_s is not None
    # ...while concurrent requests on a healthy file complete clean
    with FileReader(healthy) as r:
        want_rows = r.num_rows
    res = svc.scan(ScanRequest(healthy, columns=["a"]), timeout=60)
    got = res[healthy]["a"]
    parts = got if isinstance(got, list) else [got]
    assert sum(p.num_leaf_slots for p in parts) == want_rows
    circ = svc.serve_stats()["circuit"]
    assert circ["open_now"] == 1 and circ["open_files"] == [str(victim)]
    assert circ["opened"] == 1 and circ["fast_fails"] >= 1
    # the flight-dump sample names the open circuit too (autopsy's input)
    assert svc.sample()["circuit_open"][0]["file"] == str(victim)
    svc.close()


def test_circuit_half_open_probe_closes(files):
    clk = [0.0]
    board = BreakerBoard(fails=2, window_s=60, cooldown_s=5,
                         clock=lambda: clk[0])
    key, name = ("file", "k", 1, 2), "/data/x.parquet"
    board.note(key, name, ok=False)
    board.note(key, name, ok=False)
    with pytest.raises(CircuitOpenError):
        board.admit(key, name)
    clk[0] = 6.0  # cooldown passed: ONE half-open probe admits
    board.admit(key, name)
    with pytest.raises(CircuitOpenError):
        board.admit(key, name)  # second caller held while probe is out
    board.note(key, name, ok=True)  # probe succeeded
    board.admit(key, name)
    c = board.counters()
    assert c["open_now"] == 0 and c["closed"] == 1
    # ...and a failing probe re-opens with a fresh cooldown
    board.note(key, name, ok=False)
    board.note(key, name, ok=False)
    clk[0] = 12.0
    board.admit(key, name)          # probe
    board.note(key, name, ok=False)  # probe failed
    assert board.counters()["reopened"] == 1
    with pytest.raises(CircuitOpenError):
        board.admit(key, name)


def test_abandoned_probe_never_wedges_breaker_open():
    # a half-open probe that dies with an UNCLASSIFIED error (deadline
    # expiry, caller cancel) never calls note(); after a further cooldown
    # of silence the probe slot is forfeit and a new probe admits
    clk = [0.0]
    board = BreakerBoard(fails=1, window_s=60, cooldown_s=5,
                         clock=lambda: clk[0])
    key, name = ("file", "k", 1, 2), "/data/x.parquet"
    board.note(key, name, ok=False)  # opens
    clk[0] = 6.0
    board.admit(key, name)  # the probe... which silently vanishes
    clk[0] = 8.0
    with pytest.raises(CircuitOpenError):
        board.admit(key, name)  # probe still nominally out
    clk[0] = 12.0  # a full cooldown after the probe went quiet
    board.admit(key, name)  # slot forfeited: this caller is the new probe
    board.note(key, name, ok=True)
    assert board.counters()["open_now"] == 0


def test_default_scan_token_never_inherits_request_verdict(files):
    # a shared store's scan-less readers (footer reads, cache warms) must
    # not inherit a foreign request's deadline/cancel from begin_scan
    with open(files[0], "rb") as f:
        st = FaultInjectingStore(LocalStore(f),
                                 config=IOConfig(backoff_ms=0))
        expired = CancelToken.with_timeout(0.0)
        tok = st.begin_scan(cancel=expired)
        with pytest.raises(DeadlineExceededError):
            st.read_range(4, 100, scan=tok)  # the request itself: typed
        st.read_range(4, 100)  # a scan-less caller: unaffected
        st.close()


def test_deadline_failures_never_trip_circuits(files):
    board = BreakerBoard(fails=1, window_s=60, cooldown_s=60)
    svc = ScanService(concurrency=2, queue_depth=8, breakers=board,
                      store=_latency_factory(0.08))
    with pytest.raises(DeadlineExceededError):
        svc.scan(ScanRequest(files[0], deadline_s=0.02), timeout=30)
    # an impatient caller must not poison the file for everyone else
    assert board.counters()["open_now"] == 0
    svc.scan(ScanRequest(files[0], columns=["a"]), timeout=60)
    svc.close()


# ---------------------------------------------------------------------------
# brownout load shedding
# ---------------------------------------------------------------------------

def test_brownout_sheds_low_admits_high(files):
    svc = ScanService(concurrency=1, queue_depth=4, brownout=0.25,
                      store=_latency_factory(0.05))
    tickets, shed = [], None
    for _ in range(10):
        try:
            tickets.append(svc.submit(
                ScanRequest(files[0], columns=["a"],
                            priority=PRIORITY_LOW)))
        except OverloadError as e:
            shed = e
    assert shed is not None, "brownout never shed low-priority work"
    assert shed.retry_after_s is not None and shed.retry_after_s > 0
    assert shed.shed_priority == PRIORITY_LOW
    assert shed.queue_depth is not None and shed.in_flight is not None
    # high-priority still admits under the same pressure
    tickets.append(svc.submit(
        ScanRequest(files[0], columns=["a"], priority=PRIORITY_HIGH)))
    for t in tickets:
        t.result(60)
    sv = svc.serve_stats()
    assert sv["sheds"]["low"] >= 1 and sv["completed"] == len(tickets)
    svc.close()


def test_brownout_disabled_and_default(files):
    with ScanService(concurrency=1, queue_depth=4, brownout=0.0) as svc:
        assert svc.brownout == 0.0
    with ScanService(concurrency=1) as svc:
        assert svc.brownout == pytest.approx(0.85)  # TPQ_SERVE_BROWNOUT


# ---------------------------------------------------------------------------
# the chaos harness (acceptance matrix)
# ---------------------------------------------------------------------------

def test_chaos_schedule_roundtrip_and_invariants():
    s = ChaosSchedule.generate(seed=42, n_phases=6, horizon=400, files=3)
    assert ChaosSchedule.from_blob(s.to_blob()) == s
    assert ChaosSchedule.generate(seed=42, n_phases=6, horizon=400,
                                  files=3) == s
    prev_end = 0
    for p in s.phases:
        assert p.end > p.start >= prev_end
        prev_end = p.end
    with pytest.raises(ParquetError):
        ChaosSchedule([ChaosPhase(0, 10, "stall", stall_s=60.0)])
    with pytest.raises(ParquetError):
        ChaosSchedule([ChaosPhase(0, 10, "stall"),
                       ChaosPhase(5, 15, "transient")])
    with pytest.raises(ParquetError):
        ChaosSchedule.from_blob(b"TPQC\x01junk")


def test_chaos_matrix_blackout_trips_circuit_healthy_files_clean(
        files, tmp_path):
    # seeded schedule: a stall storm over the first reads, then a per-file
    # blackout pinned to files[2] for the rest of the run
    schedule = ChaosSchedule([
        ChaosPhase(0, 8, "stall", intensity=1, stall_s=0.05),
        ChaosPhase(8, 1 << 20, "blackout", file_index=2),
    ], seed=11)
    factory = schedule.store_factory(
        files, config=IOConfig(retries=1, backoff_ms=1.0, retry_budget=32))
    board = BreakerBoard(fails=2, window_s=60, cooldown_s=60)
    # ground truth for bit-identity, read clean
    expect = {}
    for p in files[:2]:
        with FileReader(p, columns=["a"]) as r:
            expect[p] = r.read_all()["a"].values.copy()

    with ScanService(concurrency=2, queue_depth=32, breakers=board,
                     store=factory) as svc:
        # healthy files ride THROUGH the stall storm (first attempts
        # stall, retries recover) — bit-identical output, and their reads
        # advance the shared ordinal clock into the blackout phase
        for p in files[:2]:
            got = svc.scan(ScanRequest(p, columns=["a"]),
                           timeout=120)[p]["a"]
            parts = got if isinstance(got, list) else [got]
            np.testing.assert_array_equal(
                np.concatenate([np.asarray(q.values) for q in parts]),
                expect[p])
        # the blacked-out file: N classified failures, then the circuit
        outcome = []
        for _ in range(4):
            try:
                svc.scan(ScanRequest(files[2], columns=["a"]), timeout=120)
                outcome.append("ok")
            except RetryExhaustedError:
                outcome.append("fail")
            except CircuitOpenError:
                outcome.append("open")
        assert outcome[:2] == ["fail", "fail"], outcome  # trips at N=2
        assert set(outcome[2:]) == {"open"}, outcome     # then fast-fails
        # ...while the healthy files STILL complete clean mid-blackout
        for p in files[:2]:
            got = svc.scan(ScanRequest(p, columns=["a"]),
                           timeout=120)[p]["a"]
            parts = got if isinstance(got, list) else [got]
            np.testing.assert_array_equal(
                np.concatenate([np.asarray(q.values) for q in parts]),
                expect[p])
        circ = svc.serve_stats()["circuit"]
        assert circ["open_files"] == [str(files[2])]
        factory.release()


# ---------------------------------------------------------------------------
# observability: serve-stats / doctor / autopsy surfaces
# ---------------------------------------------------------------------------

def _reg_tree_with_everything():
    return {
        "obs_version": 1,
        "pipeline": {"io_seconds": 0.4, "stage_seconds": 0.1,
                     "stall_seconds": 0.0},
        "reader": {},
        "io": {"reads": 100, "hedges_issued": 10, "hedges_won": 1,
               "hedges_wasted_bytes": 5000, "hedge_mismatches": 0},
        "serve": {
            "submitted": 10, "completed": 5, "rejected": 3, "failed": 2,
            "queue_wait_seconds": 0.2, "exec_seconds": 1.0, "rows": 100,
            "queue_depth_peak": 4, "deadline_exceeded": 1, "cancelled": 1,
            "sheds": {"low": 3, "normal": 0},
            "circuit": {"opened": 1, "reopened": 0, "closed": 0,
                        "fast_fails": 2, "open_now": 1,
                        "open_files": ["/data/bad.parquet"]},
            "cache": {"footer_hits": 1, "footer_misses": 1, "plan_hits": 1,
                      "plan_misses": 1, "dict_hits": 0, "dict_misses": 0,
                      "evictions": 0, "invalidations": 0, "held_bytes": 10,
                      "capacity_bytes": 100, "entries": 2},
        },
        "histograms": {},
    }


def test_doctor_circuit_open_and_hedge_ineffective():
    from tpu_parquet.obs import doctor_registry

    rep = doctor_registry(_reg_tree_with_everything())
    assert rep["circuit_open"]["verdict"] == "circuit-open"
    assert rep["circuit_open"]["files"] == ["/data/bad.parquet"]
    assert rep["hedge"]["verdict"] == "hedge-ineffective"
    assert rep["hedge"]["win_rate"] == 0.1
    # a healthy hedge win-rate raises no advisory
    tree = _reg_tree_with_everything()
    tree["io"]["hedges_won"] = 9
    assert "hedge" not in doctor_registry(tree)
    # and a closed board raises no circuit block
    tree["serve"]["circuit"]["open_now"] = 0
    assert "circuit_open" not in doctor_registry(tree)


def test_doctor_cli_prints_circuit_and_hedge(tmp_path):
    from tpu_parquet.cli import pq_tool

    path = str(tmp_path / "reg.json")
    with open(path, "w") as f:
        json.dump(_reg_tree_with_everything(), f)
    buf = io.StringIO()
    rc = pq_tool.cmd_doctor(
        type("A", (), {"file": path, "config": None})(), out=buf)
    out = buf.getvalue()
    assert rc == 0
    assert "circuit-open: /data/bad.parquet" in out
    assert "hedge-ineffective" in out and "TPQ_IO_HEDGE_MS" in out


def test_serve_stats_cli_lifecycle_circuit_hedge_lines(tmp_path):
    from tpu_parquet.cli import pq_tool

    path = str(tmp_path / "reg.json")
    with open(path, "w") as f:
        json.dump(_reg_tree_with_everything(), f)
    buf = io.StringIO()
    rc = pq_tool.cmd_serve_stats(
        type("A", (), {"file": path, "config": None})(), out=buf)
    out = buf.getvalue()
    assert rc == 0
    assert "lifecycle: 1 deadline-exceeded, 1 cancelled, shed 3 low" in out
    assert "circuit: 1 open now (/data/bad.parquet)" in out
    assert "hedges: 10 issued, 1 won (10%)" in out


def test_autopsy_names_open_circuit(files, tmp_path):
    from tpu_parquet.cli import pq_tool
    from tpu_parquet.obs import autopsy_dump

    doc = {
        "flight_version": 1, "reason": "explicit", "pid": 1234,
        "threads": {}, "stacks": {}, "budgets": [], "samples": {
            "serve": {
                "queue_depth": 0, "in_flight": 0, "requests": {},
                "circuit_open": [{"file": str(files[2]),
                                  "retry_after_s": 4.5,
                                  "state": "open"}],
            },
        },
    }
    rep = autopsy_dump(doc)
    assert rep["verdict"] == "circuit-open"
    assert str(files[2]) in rep["probable_cause"]
    path = str(tmp_path / "dump.json")
    with open(path, "w") as f:
        json.dump(doc, f)
    buf = io.StringIO()
    rc = pq_tool.cmd_autopsy(type("A", (), {"file": path})(), out=buf)
    assert rc == 0
    assert f"circuit: OPEN for {str(files[2])!r}" in buf.getvalue()


def test_registry_serve_merge_with_new_keys(files):
    # cross-process merge: lifecycle flows add, the open_now gauge maxes
    from tpu_parquet.obs import StatsRegistry

    tree = _reg_tree_with_everything()
    reg = StatsRegistry()
    reg.merge_dict(tree)
    reg.merge_dict(tree)
    sv = reg.as_dict()["serve"]
    assert sv["deadline_exceeded"] == 2 and sv["sheds"]["low"] == 6
    assert sv["circuit"]["opened"] == 2      # transitions are flows
    assert sv["circuit"]["open_now"] == 1    # gauge: max, not sum
    io_sec = reg.as_dict()["io"]
    assert io_sec["hedges_issued"] == 20


def test_io_stats_survive_store_collection(files):
    # factory stores die with their readers; the service must bank their
    # counters at close so completed work never reports zero hedges/reads
    import gc

    svc = ScanService(
        concurrency=2, queue_depth=8,
        store=lambda f: FaultInjectingStore(
            LocalStore(f), FaultSpec(fail_first=1),
            config=IOConfig(backoff_ms=0)))
    svc.scan(ScanRequest(files[0]), timeout=60)
    gc.collect()
    io_sec = svc.obs_registry().as_dict()["io"]
    assert io_sec and io_sec["reads"] > 0 and io_sec["retries"] > 0, io_sec
    svc.close()


def test_breaker_board_drops_recovered_entries():
    board = BreakerBoard(fails=5, window_s=60, cooldown_s=5)
    key = ("file", "k", 1, 2)
    board.note(key, "f", ok=False)  # one blip: entry created, still closed
    assert len(board._breakers) == 1
    board.note(key, "f", ok=True)   # recovered: the entry must not linger
    assert len(board._breakers) == 0


def test_overload_error_carries_lifecycle_fields():
    e = OverloadError("shed", queue_depth=3, in_flight=2,
                      retry_after_s=0.7, shed_priority=0)
    assert e.retry_after_s == 0.7 and e.shed_priority == 0
    assert not issubclass(DeadlineExceededError, ParquetError)
    assert not issubclass(CancelledError, ParquetError)
    assert not issubclass(CircuitOpenError, ParquetError)
    assert issubclass(DeadlineExceededError, TimeoutError)


def test_no_leaked_threads_after_everything(files):
    # the hedge duplicate path and the cancel paths must leave nothing
    # behind (the bench exit-3 gate watches the same prefixes)
    time.sleep(0.1)
    leaked = [t.name for t in threading.enumerate()
              if t.name.startswith(("tpq-hedge", "tpq-serve"))]
    assert not leaked, leaked
