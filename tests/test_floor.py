"""High-level object API (floor) tests: dataclass round-trips with logical types,
marshaller hooks, Time type, INT96, and pyarrow cross-reads.

Mirrors floor/writeread_test.go + floor/writer_test.go + floor/reader_test.go.
"""

import dataclasses
import datetime
import decimal
import uuid
from typing import Dict, List, Optional

import pyarrow.parquet as pq
import pytest

from tpu_parquet.floor import Reader, Time, Writer
from tpu_parquet.floor.marshal import MarshalError
from tpu_parquet.schema.autoschema import schema_from_type
from tpu_parquet.schema.dsl import parse_schema_definition

UTC = datetime.timezone.utc


@dataclasses.dataclass
class Trip:
    id: int
    rider: str
    fare: Optional[float]
    pickup: datetime.datetime
    day: datetime.date
    stops: List[str]
    meta: Dict[str, int]


def sample_trips(n=100):
    return [
        Trip(
            id=i,
            rider=f"rider_{i % 10}",
            fare=None if i % 9 == 0 else i * 1.5,
            pickup=datetime.datetime(2023, 1, 1, tzinfo=UTC)
            + datetime.timedelta(minutes=i),
            day=datetime.date(2023, 1, 1) + datetime.timedelta(days=i % 30),
            stops=[f"s{j}" for j in range(i % 4)],
            meta={"n": i},
        )
        for i in range(n)
    ]


def test_dataclass_roundtrip(tmp_path):
    p = tmp_path / "trips.parquet"
    trips = sample_trips()
    with Writer(p, obj_type=Trip) as w:
        w.write_many(trips)
    with Reader(p, obj_type=Trip) as r:
        assert r.num_rows == 100
        got = r.scan_all()
    assert got == trips


def test_pyarrow_reads_floor_output(tmp_path):
    p = tmp_path / "trips.parquet"
    with Writer(p, obj_type=Trip) as w:
        w.write_many(sample_trips(10))
    t = pq.read_table(p)
    assert t.num_rows == 10
    row = t.to_pylist()[3]
    assert row["rider"] == "rider_3"
    assert row["day"] == datetime.date(2023, 1, 4)
    assert row["pickup"] == datetime.datetime(2023, 1, 1, 0, 3, tzinfo=UTC)


def test_timestamp_units(tmp_path):
    schema = parse_schema_definition("""message m {
      optional int64 ms (TIMESTAMP(MILLIS,true));
      optional int64 us (TIMESTAMP(MICROS,true));
      optional int64 ns (TIMESTAMP(NANOS,true));
    }""")
    dt = datetime.datetime(2024, 6, 15, 12, 30, 45, 123456, tzinfo=UTC)
    p = tmp_path / "ts.parquet"
    with Writer(p, schema=schema) as w:
        w.write({"ms": dt, "us": dt, "ns": dt})
    with Reader(p) as r:
        row = next(iter(r))
    assert row["us"] == dt
    assert row["ns"] == dt
    assert row["ms"] == dt.replace(microsecond=123000)  # millis truncation


def test_time_type(tmp_path):
    schema = parse_schema_definition("""message m {
      optional int32 tm (TIME(MILLIS,true));
      optional int64 tu (TIME(MICROS,true));
    }""")
    t = Time.from_parts(14, 30, 15, 500_000_000)
    p = tmp_path / "time.parquet"
    with Writer(p, schema=schema) as w:
        w.write({"tm": t, "tu": t})
    with Reader(p) as r:
        row = next(iter(r))
    assert row["tm"] == t
    assert row["tu"] == t
    assert str(t) == "14:30:15.5Z"
    assert t.to_datetime_time().hour == 14


def test_time_validation():
    with pytest.raises(ValueError):
        Time(-1)
    with pytest.raises(ValueError):
        Time.from_parts(24, 0)
    assert Time.from_milliseconds(1000).second == 1


def test_uuid_and_decimal(tmp_path):
    schema = parse_schema_definition("""message m {
      required fixed_len_byte_array(16) uid (UUID);
      optional int32 price (DECIMAL(9,2));
      optional binary big (DECIMAL(20,4));
    }""")
    u = uuid.UUID("12345678-1234-5678-1234-567812345678")
    p = tmp_path / "ud.parquet"
    with Writer(p, schema=schema) as w:
        w.write({"uid": u, "price": decimal.Decimal("123.45"),
                 "big": decimal.Decimal("-99999.1234")})
    with Reader(p) as r:
        row = next(iter(r))
    assert row["uid"] == u
    assert row["price"] == decimal.Decimal("123.45")
    assert row["big"] == decimal.Decimal("-99999.1234")
    # pyarrow agrees on the decimal interpretation
    t = pq.read_table(p)
    assert t.column("price").to_pylist() == [decimal.Decimal("123.45")]


def test_int96_timestamps(tmp_path):
    schema = parse_schema_definition(
        "message m { optional int96 ts; }"
    )
    dt = datetime.datetime(2021, 7, 4, 9, 30, 0, 250000, tzinfo=UTC)
    p = tmp_path / "i96.parquet"
    with Writer(p, schema=schema, use_dictionary=False) as w:
        w.write({"ts": dt})
    with Reader(p) as r:
        row = next(iter(r))
    assert row["ts"] == dt
    # pyarrow reads INT96 as timestamp too
    assert pq.read_table(p).column("ts").to_pylist()[0] == dt.replace(tzinfo=None)


def test_pre_epoch_timestamps(tmp_path):
    schema = parse_schema_definition(
        "message m { optional int64 us (TIMESTAMP(MICROS,true)); }"
    )
    dt = datetime.datetime(1969, 12, 31, 23, 59, 59, 500000, tzinfo=UTC)
    p = tmp_path / "pre.parquet"
    with Writer(p, schema=schema) as w:
        w.write({"us": dt})
    with Reader(p) as r:
        assert next(iter(r))["us"] == dt
    assert pq.read_table(p).column("us").to_pylist()[0] == dt


def test_optional_columnar_write_without_levels(tmp_path):
    # all-defined shorthand: ColumnData with max_def>0, def_levels=None
    import numpy as np

    from tpu_parquet.column import ColumnData
    from tpu_parquet.schema.core import build_schema, data_column
    from tpu_parquet.format import FieldRepetitionType as FRT, Type
    from tpu_parquet.writer import FileWriter

    schema = build_schema([data_column("v", Type.INT64, FRT.OPTIONAL)])
    cd = ColumnData(values=np.arange(5, dtype=np.int64), max_def=1, max_rep=0)
    p = tmp_path / "nolev.parquet"
    with FileWriter(p, schema) as w:
        w.write_columns({"v": cd})
    assert pq.read_table(p).column("v").to_pylist() == [0, 1, 2, 3, 4]


def test_decimal_printer_roundtrip_converted_only():
    # legacy converted-type-only DECIMAL must print parameterized and re-parse
    from tpu_parquet.format import ConvertedType, SchemaElement, Type as T
    from tpu_parquet.schema.core import Schema, SchemaNode
    from tpu_parquet.schema.dsl import schema_to_string as s2s

    elem = SchemaElement(name="d", type=int(T.INT32), repetition_type=1,
                         converted_type=int(ConvertedType.DECIMAL),
                         precision=9, scale=2)
    s = Schema(SchemaNode(SchemaElement(name="m"), [SchemaNode(elem, None)]))
    text = s2s(s)
    assert "DECIMAL(9,2)" in text
    s2 = parse_schema_definition(text)
    assert s2.leaves[0].element.precision == 9


def test_custom_marshaller_hooks(tmp_path):
    class Point:
        def __init__(self, x, y):
            self.x, self.y = x, y

        def to_parquet_row(self):
            return {"x": self.x, "y": self.y}

        @classmethod
        def from_parquet_row(cls, row):
            return cls(row["x"], row["y"])

        def __eq__(self, other):
            return (self.x, self.y) == (other.x, other.y)

    schema = parse_schema_definition(
        "message p { required int64 x; required int64 y; }"
    )
    p = tmp_path / "pt.parquet"
    with Writer(p, schema=schema) as w:
        w.write(Point(3, 4))
    with Reader(p, obj_type=Point) as r:
        got = r.scan_all()
    assert got == [Point(3, 4)]


def test_unmarshalable_raises(tmp_path):
    schema = parse_schema_definition("message m { required int64 x; }")
    p = tmp_path / "bad.parquet"
    with Writer(p, schema=schema) as w:
        with pytest.raises(MarshalError):
            w.write(42)


def test_nested_dataclasses(tmp_path):
    @dataclasses.dataclass
    class Addr:
        city: str
        zip: Optional[str]

    @dataclasses.dataclass
    class Person:
        name: str
        addr: Optional[Addr]
        previous: List[Addr]

    people = [
        Person("ann", Addr("berlin", "10115"), [Addr("munich", None)]),
        Person("bob", None, []),
    ]
    p = tmp_path / "people.parquet"
    with Writer(p, obj_type=Person) as w:
        w.write_many(people)
    with Reader(p, obj_type=Person) as r:
        got = r.scan_all()
    assert got == people


def test_datetime_time_field(tmp_path):
    @dataclasses.dataclass
    class Sched:
        at: datetime.time

    s = Sched(at=datetime.time(8, 45, 30, tzinfo=UTC))
    p = tmp_path / "sched.parquet"
    with Writer(p, obj_type=Sched) as w:
        w.write(s)
    with Reader(p, obj_type=Sched) as r:
        got = r.scan_all()[0]
    assert got.at.replace(tzinfo=UTC) == s.at


def test_int96_and_timestamp_string_unix_parity(tmp_path):
    """floor/writer.go:249-258 + 317-340 parity: INT96 fields accept ints
    (magnitude-based unix-time heuristic: s/ms/us/ns) and strings
    (best-effort parse); TIMESTAMP logical columns accept strings too."""
    schema = parse_schema_definition(
        "message m { required int96 ts; "
        "required int64 lt (TIMESTAMP(MILLIS, true)); }"
    )
    dt = datetime.datetime(2021, 1, 1, 12, 0, 0, tzinfo=UTC)
    unix_s = int(dt.timestamp())
    rows = [
        {"ts": unix_s, "lt": "2021-01-01T12:00:00+00:00"},        # int seconds
        {"ts": unix_s * 1000, "lt": "2021-01-01 12:00:00+00:00"},  # int millis
        {"ts": unix_s * 1_000_000, "lt": dt},                      # int micros
        {"ts": str(unix_s), "lt": dt},                             # digit string
        {"ts": "2021-01-01T12:00:00Z", "lt": dt},                  # ISO string
    ]
    p = str(tmp_path / "ts.parquet")
    w = Writer(p, schema)
    for r in rows:
        w.write(r)
    w.close()
    r = Reader(p)
    out = list(r)
    r.close()
    assert len(out) == 5
    for row in out:
        assert row["ts"] == dt, row
        assert row["lt"] == dt, row


def test_int96_implausible_unix_int_rejected(tmp_path):
    schema = parse_schema_definition("message m { required int96 ts; }")
    p = str(tmp_path / "bad.parquet")
    w = Writer(p, schema)
    with pytest.raises(MarshalError):
        w.write({"ts": 10**20})  # more digits than unix nanos of now
    w.close()


def test_all_null_byte_array_chunk_statistics(tmp_path):
    """Advisor finding: a fully-null BYTE_ARRAY chunk with write_statistics
    must produce null_count-only stats, not crash in the min/max pass."""
    schema = parse_schema_definition(
        "message m { optional binary s (STRING); }"
    )
    p = str(tmp_path / "nulls.parquet")
    w = Writer(p, schema)
    for _ in range(10):
        w.write({"s": None})
    w.close()
    import tpu_parquet as tpq

    meta = tpq.read_file_metadata(p)
    st = meta.row_groups[0].columns[0].meta_data.statistics
    assert st is not None and st.null_count == 10
    assert st.min_value is None and st.max_value is None
    assert pq.read_table(p)["s"].to_pylist() == [None] * 10
