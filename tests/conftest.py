"""Test harness config.

Force JAX onto a virtual 8-device CPU mesh so multi-chip sharding paths
(tpu_parquet/parallel) are exercised without TPU hardware, per the driver
contract.  The axon site hook imports jax before this file runs, so the env
vars alone are not sufficient — the jax.config.update below is load-bearing.
"""

import os
import sys

# force-set (not setdefault): the environment pins JAX_PLATFORMS=axon (real TPU
# tunnel), but tests must run on the virtual 8-device CPU mesh for determinism
# and multi-chip sharding coverage.  The axon site hook may import jax before
# this file runs, so set the config too, not just the env var.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def require_codec(codec) -> None:
    """Skip (never fail) when a codec has no registered implementation.

    The sealed CI image ships without the ``zstandard`` module, so ZSTD
    matrix cells would otherwise FAIL with a codec error and bury real
    regressions among 15 standing red tests (round-7 hygiene).  An explicit
    skip keeps the cells visible as environment gaps, exactly like the
    corpus runners' missing-file skips.
    """
    import pytest

    from tpu_parquet.compress import registered_codecs

    if int(codec) not in registered_codecs():
        name = getattr(codec, "name", str(codec))
        pytest.skip(f"codec {name} unavailable in this image "
                    f"(zstandard module not installed)")
