"""Parallel (mesh/shard_map) decode tests on the virtual 8-device CPU mesh.

Covers: page batching, data-parallel sharded decode for hybrid/delta/plain,
the 2-D mesh variant with a model-sharded dictionary (masked gather + psum
routing), global stats collectives, and the work-list shard planner.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from tpu_parquet import parallel as par
from tpu_parquet.kernels import delta as delta_host, rle as rle_host

RNG = np.random.default_rng(11)


@pytest.fixture(scope="module")
def mesh():
    assert jax.device_count() >= 8, "conftest must provide 8 virtual devices"
    return par.make_mesh(jax.devices()[:8])


def _hybrid_batch(n_pages, count, width, dict_len):
    vals = [RNG.integers(0, dict_len, count).astype(np.uint64) for _ in range(n_pages)]
    raws = [rle_host.encode(v, width) for v in vals]
    return par.pack_hybrid_pages(raws, width, count), vals


def test_sharded_dict_decode(mesh):
    batch, vals = _hybrid_batch(16, 500, 7, 100)
    dictionary = RNG.integers(-(1 << 40), 1 << 40, 100)
    dict_u8 = jnp.asarray(dictionary.view(np.uint8).reshape(100, 8))
    out, stats = par.sharded_dict_decode(batch, dict_u8, "int64", mesh, with_stats=True)
    expect = np.stack([dictionary[v.astype(np.int64)] for v in vals])
    np.testing.assert_array_equal(np.asarray(out), expect)
    st = np.asarray(stats)
    assert st[0] == 16 * 500
    assert st[1] == min(int(v.min()) for v in vals)
    assert st[2] == max(int(v.max()) for v in vals)
    # output keeps its sharding for downstream pjit consumption
    assert "data" in str(out.sharding)


def test_sharded_dict_decode_2d():
    devs = np.asarray(jax.devices()[:8]).reshape(4, 2)
    mesh2 = Mesh(devs, ("data", "model"))
    batch, vals = _hybrid_batch(8, 256, 6, 50)
    dictionary = RNG.integers(-(1 << 30), 1 << 30, 50)
    dict_u8 = jnp.asarray(dictionary.view(np.uint8).reshape(50, 8))
    out = par.sharded_dict_decode_2d(batch, dict_u8, "int64", mesh2)
    expect = np.stack([dictionary[v.astype(np.int64)] for v in vals])
    np.testing.assert_array_equal(np.asarray(out), expect)


def test_sharded_dict_decode_2d_uneven_dict():
    # dict size not divisible by model axis → padding path
    devs = np.asarray(jax.devices()[:8]).reshape(2, 4)
    mesh2 = Mesh(devs, ("data", "model"))
    batch, vals = _hybrid_batch(4, 128, 6, 37)
    dictionary = RNG.integers(0, 1 << 20, 37)
    dict_u8 = jnp.asarray(dictionary.view(np.uint8).reshape(37, 8))
    out = par.sharded_dict_decode_2d(batch, dict_u8, "int64", mesh2)
    expect = np.stack([dictionary[v.astype(np.int64)] for v in vals])
    np.testing.assert_array_equal(np.asarray(out), expect)


@pytest.mark.parametrize("bits", [32, 64])
def test_sharded_delta_decode(mesh, bits):
    dt = np.int32 if bits == 32 else np.int64
    count = 384
    vals = [np.cumsum(RNG.integers(-40, 40, count)).astype(dt) for _ in range(16)]
    raws = [delta_host.encode(v, bits=bits) for v in vals]
    batch = par.pack_delta_pages(raws, bits, count)
    out = par.sharded_delta_decode(batch, bits, mesh)
    np.testing.assert_array_equal(np.asarray(out), np.stack(vals))


def test_sharded_plain_decode(mesh):
    count = 512
    vals = [RNG.integers(-(1 << 50), 1 << 50, count) for _ in range(8)]
    bufs = np.zeros((8, par._bucket(count * 8 + par._SLACK, 64)), np.uint8)
    for i, v in enumerate(vals):
        bufs[i, : count * 8] = v.view(np.uint8)
    out = par.sharded_plain_decode(jnp.asarray(bufs), "int64", count, mesh)
    np.testing.assert_array_equal(np.asarray(out), np.stack(vals))


def test_column_stats(mesh):
    vals = RNG.integers(-1000, 1000, (8, 256))
    st = np.asarray(par.column_stats(jnp.asarray(vals), mesh))
    assert st[0] == vals.size
    assert st[1] == vals.min()
    assert st[2] == vals.max()


def test_plan_shards_balanced():
    sizes = [100, 90, 80, 70, 30, 30, 20, 10]
    plan = par.plan_shards(sizes, 3)
    # every group assigned exactly once
    assert sorted(i for s in plan for i in s) == list(range(8))
    loads = [sum(sizes[i] for i in s) for s in plan]
    assert max(loads) - min(loads) <= 60  # LPT bound for this instance
    # deterministic
    assert plan == par.plan_shards(sizes, 3)


def test_plan_shards_more_shards_than_groups():
    plan = par.plan_shards([10, 20], 4)
    assert sorted(i for s in plan for i in s) == [0, 1]
    assert sum(1 for s in plan if s) == 2


def test_graft_entry_single_chip():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "__graft_entry__", "/root/repo/__graft_entry__.py"
    )
    g = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(g)
    fn, args = g.entry()
    from tpu_parquet.jax_kernels import enable_x64

    # trace under x64: the example args are int64 metadata, and a no-x64
    # jit boundary would downcast them before the kernels' scoped_x64
    # contexts apply (mixed i32/i64 jaxpr on 0.4.x jax)
    with enable_x64():
        out = jax.jit(fn)(*args)
    jax.block_until_ready(out)
    assert out[0].shape == (256,)
    assert out[1].shape == (256,)


def test_graft_dryrun_multichip():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "__graft_entry__", "/root/repo/__graft_entry__.py"
    )
    g = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(g)
    g.dryrun_multichip(8)
    g.dryrun_multichip(2)


def test_pack_hybrid_pages_tail_padding(mesh):
    """Short tail page pads with a synthetic zero run; decode matches.

    Page-batch size must divide the mesh's data axis (8 here) — the short page
    sits last, as a real chunk's tail page would.
    """
    count, width = 200, 5
    pages = [RNG.integers(0, 20, count).astype(np.uint64) for _ in range(7)]
    vals_tail = RNG.integers(0, 20, 57).astype(np.uint64)
    raws = [rle_host.encode(v, width) for v in pages] + [
        rle_host.encode(vals_tail, width)
    ]
    batch = par.pack_hybrid_pages(
        raws, width, count, counts=[count] * 7 + [57]
    )
    dictionary = RNG.integers(0, 1 << 30, 20)
    dict_u8 = jnp.asarray(dictionary.view(np.uint8).reshape(20, 8))
    out, _ = par.sharded_dict_decode(batch, dict_u8, "int64", mesh)
    got = np.asarray(out)
    for i, v in enumerate(pages):
        np.testing.assert_array_equal(got[i], dictionary[v.astype(np.int64)])
    np.testing.assert_array_equal(got[7, :57], dictionary[vals_tail.astype(np.int64)])
    np.testing.assert_array_equal(got[7, 57:], np.full(count - 57, dictionary[0]))


# ---------------------------------------------------------------------------
# Multi-host work list → global sharded array (SURVEY.md §5.8)
# ---------------------------------------------------------------------------

def _write_span_file(tmp_path, rows=1000, rg_rows=137):
    from tpu_parquet.format import CompressionCodec, FieldRepetitionType as FRT, Type
    from tpu_parquet.schema.core import build_schema, data_column
    from tpu_parquet.writer import FileWriter

    vals = np.arange(rows, dtype=np.int64) * 3 - 500
    schema = build_schema([data_column("v", Type.INT64, FRT.REQUIRED)])
    p = tmp_path / "span.parquet"
    with FileWriter(p, schema, codec=CompressionCodec.SNAPPY,
                    use_dictionary=False) as w:
        for lo in range(0, rows, rg_rows):
            w.write_columns({"v": vals[lo : lo + rg_rows]})
            w.flush_row_group()
    return p, vals


def test_shard_row_ranges_properties():
    spans = par.shard_row_ranges(1000, 8)
    assert len(spans) == 8
    assert spans[0] == (0, 125) and spans[-1] == (875, 1000)
    # uneven: equal spans, short tail
    spans = par.shard_row_ranges(1001, 8)
    assert all(hi - lo == 126 for lo, hi in spans[:-1])
    assert spans[-1] == (882, 1001)
    assert par.shard_row_ranges(0, 4) == [(0, 0)] * 4


def test_decode_row_span_touches_only_needed_groups(tmp_path):
    from tpu_parquet.reader import FileReader

    p, vals = _write_span_file(tmp_path)
    with FileReader(p) as r:
        np.testing.assert_array_equal(
            par.decode_row_span(r, "v", 130, 290), vals[130:290]
        )
        np.testing.assert_array_equal(
            par.decode_row_span(r, "v", 0, 1000), vals
        )
        np.testing.assert_array_equal(
            par.decode_row_span(r, "v", 999, 1000), vals[999:]
        )


def test_global_column_array(mesh, tmp_path):
    """Work list → per-device decode → one global row-sharded array."""
    from tpu_parquet.reader import FileReader

    p, vals = _write_span_file(tmp_path)
    with FileReader(p) as r:
        arr, valid = par.global_column_array(r, "v", mesh)
    assert valid == 1000
    assert arr.shape == (1000,)  # 1000 divides evenly over 8 shards
    np.testing.assert_array_equal(np.asarray(arr), vals)
    # every device holds exactly its contiguous span
    for shard in arr.addressable_shards:
        lo = shard.index[0].start or 0
        np.testing.assert_array_equal(np.asarray(shard.data), vals[lo : lo + 125])


def test_global_column_array_padded_tail(mesh, tmp_path):
    from tpu_parquet.reader import FileReader

    p, vals = _write_span_file(tmp_path, rows=997)
    with FileReader(p) as r:
        arr, valid = par.global_column_array(r, "v", mesh)
    assert valid == 997
    per = -(-997 // 8)
    assert arr.shape == (per * 8,)
    np.testing.assert_array_equal(np.asarray(arr)[:997], vals)
    assert not np.any(np.asarray(arr)[997:])  # zero tail padding


def test_process_local_column_single_process(mesh, tmp_path):
    """Multi-host API path on a single process: the same plan/assembly code
    runs with process_count()==1 (decodes everything locally)."""
    from tpu_parquet.reader import FileReader

    p, vals = _write_span_file(tmp_path)
    with FileReader(p) as r:
        arr, valid = par.process_local_column(r, "v", mesh)
    assert valid == 1000
    np.testing.assert_array_equal(np.asarray(arr), vals)
