"""Device chunk-decoder tests: DeviceChunkDecoder vs host ChunkDecoder.

Files are written by our own FileWriter (itself pyarrow-validated in
test_writer.py); every column chunk is decoded by both paths and compared
bit-for-bit — values, offsets/heap, and def/rep level arrays.
"""

import io

import numpy as np
import pytest

from tpu_parquet.column import ByteArrayData, ColumnData
from tpu_parquet.format import (
    CompressionCodec,
    ConvertedType,
    Encoding,
    FieldRepetitionType as FRT,
    LogicalType,
    StringType,
    Type,
)
from tpu_parquet.jax_decode import DeviceChunkDecoder, read_chunk_device
from tpu_parquet.chunk_decode import read_chunk
from tpu_parquet.reader import FileReader
from tpu_parquet.schema.core import (
    ColumnParameters,
    build_schema,
    data_column,
    list_column,
)
from tpu_parquet.writer import FileWriter

RNG = np.random.default_rng(7)


def _roundtrip_compare(schema, rows, **writer_kw):
    buf = io.BytesIO()
    with FileWriter(buf, schema, **writer_kw) as w:
        w.write_rows(rows)
    buf.seek(0)
    r = FileReader(buf)
    leaves = {l.path: l for l in r.schema.leaves}
    for rg in r.metadata.row_groups:
        for chunk in rg.columns:
            path = tuple(chunk.meta_data.path_in_schema)
            leaf = leaves[path]
            host = read_chunk(r._f, chunk, leaf)
            dev = read_chunk_device(r._f, chunk, leaf)
            _assert_same(host, dev, path)


def _assert_same(host: ColumnData, dev, path):
    if isinstance(host.values, ByteArrayData):
        got = dev.to_host()
        assert isinstance(got, ByteArrayData), path
        np.testing.assert_array_equal(got.offsets, host.values.offsets, err_msg=str(path))
        np.testing.assert_array_equal(got.heap, host.values.heap, err_msg=str(path))
    else:
        got = dev.to_host()
        if host.values.dtype == np.bool_:
            got = got.astype(np.bool_)
        np.testing.assert_array_equal(got, host.values, err_msg=str(path))
    if host.def_levels is None:
        assert dev.def_levels is None, path
    else:
        np.testing.assert_array_equal(
            np.asarray(dev.def_levels), host.def_levels, err_msg=str(path)
        )
    if host.rep_levels is None:
        assert dev.rep_levels is None, path
    else:
        np.testing.assert_array_equal(
            np.asarray(dev.rep_levels), host.rep_levels, err_msg=str(path)
        )


def _string_col(name, repetition=FRT.OPTIONAL):
    return data_column(
        name, Type.BYTE_ARRAY, repetition,
        ColumnParameters(
            logical_type=LogicalType(STRING=StringType()),
            converted_type=ConvertedType.UTF8,
        ),
    )


def _mixed_schema():
    return build_schema([
        data_column("id", Type.INT64, FRT.REQUIRED),
        data_column("x", Type.INT32, FRT.OPTIONAL),
        data_column("score", Type.DOUBLE, FRT.OPTIONAL),
        data_column("ratio", Type.FLOAT, FRT.REQUIRED),
        data_column("active", Type.BOOLEAN, FRT.REQUIRED),
        _string_col("name"),
    ])


def _mixed_rows(n=5000):
    rows = []
    for i in range(n):
        rows.append({
            "id": i * 3 - 1000,
            "x": None if i % 7 == 0 else i % 1000,
            "score": None if i % 11 == 0 else i * 0.25,
            "ratio": float(i % 13) * 0.5,
            "active": i % 2 == 0,
            "name": f"name-{i % 300}".encode(),  # 300 distinct → dictionary
        })
    return rows


@pytest.mark.parametrize("codec", [
    CompressionCodec.UNCOMPRESSED,
    CompressionCodec.SNAPPY,
    CompressionCodec.GZIP,
    CompressionCodec.ZSTD,
])
def test_device_decode_codecs(codec):
    from conftest import require_codec

    require_codec(codec)
    _roundtrip_compare(_mixed_schema(), _mixed_rows(1500), codec=codec)


@pytest.mark.parametrize("version", [1, 2])
def test_device_decode_page_versions(version):
    _roundtrip_compare(
        _mixed_schema(), _mixed_rows(2000), data_page_version=version
    )


def test_device_decode_no_dictionary_plain():
    # unique values defeat the dictionary → PLAIN pages
    schema = build_schema([
        data_column("a", Type.INT64, FRT.REQUIRED),
        data_column("b", Type.DOUBLE, FRT.REQUIRED),
    ])
    rows = [{"a": i, "b": float(i) * 1.5} for i in range(3000)]
    _roundtrip_compare(schema, rows, use_dictionary=False)


def test_device_decode_delta_bp():
    schema = build_schema([
        data_column("i32", Type.INT32, FRT.REQUIRED),
        data_column("i64", Type.INT64, FRT.REQUIRED),
    ])
    rows = [
        {"i32": int(v32), "i64": int(v64)}
        for v32, v64 in zip(
            RNG.integers(-(1 << 30), 1 << 30, 4000),
            RNG.integers(-(1 << 62), 1 << 62, 4000),
        )
    ]
    _roundtrip_compare(
        schema, rows,
        use_dictionary=False,
        column_encodings={"i32": Encoding.DELTA_BINARY_PACKED,
                          "i64": Encoding.DELTA_BINARY_PACKED},
    )


def test_device_decode_delta_byte_arrays():
    schema = build_schema([
        _string_col("dl", FRT.REQUIRED),
        _string_col("db", FRT.REQUIRED),
    ])
    rows = [
        {"dl": f"value-{i}".encode(), "db": f"prefix-common-{i:06d}".encode()}
        for i in range(2000)
    ]
    _roundtrip_compare(
        schema, rows,
        use_dictionary=False,
        column_encodings={"dl": Encoding.DELTA_LENGTH_BYTE_ARRAY,
                          "db": Encoding.DELTA_BYTE_ARRAY},
    )


def test_device_decode_nested_lists():
    schema = build_schema([
        list_column("tags", data_column("element", Type.INT64, FRT.OPTIONAL)),
        _string_col("label"),
    ])
    rows = []
    for i in range(1500):
        if i % 13 == 0:
            tags = None
        elif i % 7 == 0:
            tags = []
        else:
            tags = [int(j) if j % 3 else None for j in range(i % 6)]
        rows.append({
            "tags": tags,
            "label": None if i % 5 == 0 else f"L{i % 40}".encode(),
        })
    _roundtrip_compare(schema, rows)


def test_device_decode_multi_page():
    # small page size → many pages per chunk, exercises concat paths
    _roundtrip_compare(
        _mixed_schema(), _mixed_rows(4000), page_size=4096,
    )


def test_device_decode_string_dictionary_heavy():
    schema = build_schema([_string_col("s", FRT.REQUIRED)])
    rows = [{"s": f"city-{i % 50}".encode()} for i in range(6000)]
    _roundtrip_compare(schema, rows)


def test_device_decode_boolean_rle():
    schema = build_schema([data_column("f", Type.BOOLEAN, FRT.REQUIRED)])
    rows = [{"f": (i // 100) % 2 == 0} for i in range(3000)]
    _roundtrip_compare(
        schema, rows, column_encodings={"f": Encoding.RLE},
    )


# ---------------------------------------------------------------------------
# Malformed input: the device path must match the host path's rejections
# ---------------------------------------------------------------------------

def test_device_rejects_truncated_plain_boolean():
    from tpu_parquet.footer import ParquetError
    from tpu_parquet.schema.core import build_schema as _bs
    schema = _bs([data_column("f", Type.BOOLEAN, FRT.REQUIRED)])
    leaf = schema.leaves[0]
    dec = DeviceChunkDecoder(leaf)
    with pytest.raises(ParquetError, match="truncated"):
        dec._decode_values_device(int(Encoding.PLAIN), b"\x01", 0, 100)


def test_device_rejects_bad_boolean_rle_length():
    from tpu_parquet.footer import ParquetError
    schema = build_schema([data_column("f", Type.BOOLEAN, FRT.REQUIRED)])
    leaf = schema.leaves[0]
    dec = DeviceChunkDecoder(leaf)
    # declared RLE stream length exceeds the page
    bad = (1000).to_bytes(4, "little") + b"\x02\x01"
    with pytest.raises(ParquetError, match="exceeds page"):
        dec._decode_values_device(int(Encoding.RLE), bad, 0, 8)


def test_device_rejects_truncated_plain_int64():
    from tpu_parquet.footer import ParquetError
    schema = build_schema([data_column("v", Type.INT64, FRT.REQUIRED)])
    leaf = schema.leaves[0]
    dec = DeviceChunkDecoder(leaf)
    with pytest.raises(ParquetError, match="truncated"):
        dec._decode_values_device(int(Encoding.PLAIN), b"\x00" * 17, 0, 100)


def test_device_v1_level_stream_bounded_by_prefix():
    """A v1 level stream whose runs need more bytes than its declared size
    must raise, not read into the value region (host parity)."""
    import io as _io
    from tpu_parquet.kernels.rle import RLEError
    # craft: declared size 1, but run header promises 13 groups of 8 values
    stream = (1).to_bytes(4, "little") + bytes([0x1B]) + b"\xff" * 20
    from tpu_parquet.kernels import rle as rle_host
    with pytest.raises(RLEError):
        rle_host.decode_prefixed(stream, 1, 104)


def _craft_dict_chunk(indices, dict_vals):
    """Build raw chunk bytes: dict page (PLAIN int64) + one v1 data page of
    RLE_DICTIONARY indices, uncompressed."""
    from tpu_parquet.format import (
        CompressionCodec, DataPageHeader, DictionaryPageHeader, PageHeader,
        PageType,
    )
    from tpu_parquet.kernels import rle as rle_host
    from tpu_parquet.thrift import write_struct

    dict_payload = np.asarray(dict_vals, dtype="<i8").tobytes()
    dict_header = write_struct(PageHeader(
        type=PageType.DICTIONARY_PAGE,
        uncompressed_page_size=len(dict_payload),
        compressed_page_size=len(dict_payload),
        dictionary_page_header=DictionaryPageHeader(
            num_values=len(dict_vals), encoding=int(Encoding.PLAIN),
        ),
    ))
    width = max(int(np.asarray(indices).max()).bit_length(), 1)
    data_payload = bytes([width]) + rle_host.encode(
        np.asarray(indices, dtype=np.uint64), width
    )
    data_header = write_struct(PageHeader(
        type=PageType.DATA_PAGE,
        uncompressed_page_size=len(data_payload),
        compressed_page_size=len(data_payload),
        data_page_header=DataPageHeader(
            num_values=len(indices),
            encoding=int(Encoding.RLE_DICTIONARY),
            definition_level_encoding=int(Encoding.RLE),
            repetition_level_encoding=int(Encoding.RLE),
        ),
    ))
    buf = dict_header + dict_payload + data_header + data_payload
    return buf, int(CompressionCodec.UNCOMPRESSED)


def test_device_rejects_out_of_range_dict_index():
    """Corrupt dictionary indices must raise from decode() itself — the
    deferred per-chunk check, driven end-to-end through a crafted chunk."""
    from tpu_parquet.footer import ParquetError

    schema = build_schema([data_column("v", Type.INT64, FRT.REQUIRED)])
    leaf = schema.leaves[0]
    buf, codec = _craft_dict_chunk([1, 9, 2], np.arange(4))  # 9 out of range
    dec = DeviceChunkDecoder(leaf)
    with pytest.raises(ParquetError, match="out of range"):
        dec.decode(buf, codec, 3)
    # the same chunk with in-range indices decodes fine
    buf, codec = _craft_dict_chunk([1, 3, 2], np.arange(4) * 10)
    out = DeviceChunkDecoder(leaf).decode(buf, codec, 3)
    np.testing.assert_array_equal(out.to_host(), [10, 30, 20])


def test_device_rejects_external_file_path():
    from tpu_parquet.footer import ParquetError
    from tpu_parquet.chunk_decode import validate_chunk_meta
    from tpu_parquet.format import ColumnChunk, ColumnMetaData

    schema = build_schema([data_column("v", Type.INT64, FRT.REQUIRED)])
    leaf = schema.leaves[0]
    md = ColumnMetaData(
        type=int(Type.INT64), data_page_offset=4,
        total_compressed_size=10, num_values=1,
    )
    chunk = ColumnChunk(file_path="elsewhere.parquet", meta_data=md)
    with pytest.raises(ParquetError, match="external file"):
        validate_chunk_meta(chunk, leaf)
