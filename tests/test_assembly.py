"""Dremel record assembly tests.

Golden def/rep-level vectors from the canonical Dremel-paper document (the same
fixtures the reference uses in data_store_test.go:18-497), plus round-trip
comparison against pyarrow's own nested to_pylist().
"""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from tpu_parquet.assembly import assemble_rows
from tpu_parquet.column import ByteArrayData, ColumnData
from tpu_parquet.logical import unwrap_row
from tpu_parquet.reader import FileReader
from tpu_parquet.schema.core import (
    Schema,
    SchemaNode,
    build_schema,
    data_column,
    group_column,
)
from tpu_parquet.format import FieldRepetitionType as FRT, Type


def write(tmp_path, table, **kw):
    p = tmp_path / "t.parquet"
    pq.write_table(table, p, **kw)
    return p


def roundtrip_rows(tmp_path, table, **kw):
    p = write(tmp_path, table, **kw)
    with FileReader(p) as r:
        raw = list(r.iter_rows())
        logical = [unwrap_row(r.schema, row) for row in raw]
    return raw, logical


# ---------------------------------------------------------------------------
# Dremel paper document (the reference's canonical fixture)
# ---------------------------------------------------------------------------

def dremel_schema() -> Schema:
    # message Document {
    #   required int64 DocId;
    #   optional group Links { repeated int64 Backward; repeated int64 Forward }
    #   repeated group Name {
    #     repeated group Language { required string Code; optional string Country }
    #     optional string Url } }
    return build_schema([
        data_column("DocId", Type.INT64, FRT.REQUIRED),
        group_column("Links", [
            data_column("Backward", Type.INT64, FRT.REPEATED),
            data_column("Forward", Type.INT64, FRT.REPEATED),
        ], FRT.OPTIONAL),
        group_column("Name", [
            group_column("Language", [
                data_column("Code", Type.BYTE_ARRAY, FRT.REQUIRED),
                data_column("Country", Type.BYTE_ARRAY, FRT.OPTIONAL),
            ], FRT.REPEATED),
            data_column("Url", Type.BYTE_ARRAY, FRT.OPTIONAL),
        ], FRT.REPEATED),
    ], root_name="Document")


def _ba(items):
    return ByteArrayData.from_list(items)


def test_dremel_paper_levels():
    """Assemble r1/r2 from the paper's exact level vectors."""
    schema = dremel_schema()
    # max levels sanity (paper): Code maxR=2 maxD=2; Country maxR=2 maxD=3;
    # Backward/Forward maxR=1 maxD=2; Url maxR=1 maxD=2; DocId maxR=0 maxD=0
    by = {".".join(l.path): l for l in schema.leaves}
    assert (by["Name.Language.Code"].max_rep, by["Name.Language.Code"].max_def) == (2, 2)
    assert (by["Name.Language.Country"].max_rep, by["Name.Language.Country"].max_def) == (2, 3)
    assert (by["Links.Backward"].max_rep, by["Links.Backward"].max_def) == (1, 2)
    assert (by["DocId"].max_rep, by["DocId"].max_def) == (0, 0)

    cols = {
        "DocId": ColumnData(
            values=np.array([10, 20], dtype=np.int64), max_def=0, max_rep=0,
        ),
        "Links.Backward": ColumnData(
            values=np.array([10, 30], dtype=np.int64),
            def_levels=np.array([1, 2, 2]),
            rep_levels=np.array([0, 0, 1]),
            max_def=2, max_rep=1,
        ),
        "Links.Forward": ColumnData(
            values=np.array([20, 40, 60, 80], dtype=np.int64),
            def_levels=np.array([2, 2, 2, 2]),
            rep_levels=np.array([0, 1, 1, 0]),
            max_def=2, max_rep=1,
        ),
        "Name.Language.Code": ColumnData(
            values=_ba([b"en-us", b"en", b"en-gb"]),
            def_levels=np.array([2, 2, 1, 2, 1]),
            rep_levels=np.array([0, 2, 1, 1, 0]),
            max_def=2, max_rep=2,
        ),
        "Name.Language.Country": ColumnData(
            values=_ba([b"us", b"gb"]),
            def_levels=np.array([3, 2, 1, 3, 1]),
            rep_levels=np.array([0, 2, 1, 1, 0]),
            max_def=3, max_rep=2,
        ),
        "Name.Url": ColumnData(
            values=_ba([b"http://A", b"http://B", b"http://C"]),
            def_levels=np.array([2, 2, 1, 2]),
            rep_levels=np.array([0, 1, 1, 0]),
            max_def=2, max_rep=1,
        ),
    }
    rows = assemble_rows(schema, cols)
    assert len(rows) == 2
    r1, r2 = rows
    assert r1["DocId"] == 10
    assert r1["Links"] == {"Backward": [], "Forward": [20, 40, 60]}
    assert len(r1["Name"]) == 3
    assert r1["Name"][0] == {
        "Language": [
            {"Code": b"en-us", "Country": b"us"},
            {"Code": b"en", "Country": None},
        ],
        "Url": b"http://A",
    }
    assert r1["Name"][1] == {"Language": [], "Url": b"http://B"}
    assert r1["Name"][2] == {
        "Language": [{"Code": b"en-gb", "Country": b"gb"}],
        "Url": None,
    }
    assert r2 == {
        "DocId": 20,
        "Links": {"Backward": [10, 30], "Forward": [80]},
        "Name": [{"Language": [], "Url": b"http://C"}],
    }


# ---------------------------------------------------------------------------
# pyarrow round-trips (nested shapes)
# ---------------------------------------------------------------------------

def test_flat_rows(tmp_path):
    table = pa.table({
        "a": [1, 2, None], "s": ["x", None, "z"], "f": [1.5, None, 3.5],
    })
    raw, logical = roundtrip_rows(tmp_path, table)
    assert logical == table.to_pylist()
    assert raw == logical  # flat: no wrappers


@pytest.mark.parametrize("page_version", ["1.0", "2.0"])
def test_list_of_ints(tmp_path, page_version):
    data = [[1, 2], None, [], [3], [4, 5, 6, 7]]
    table = pa.table({"lst": pa.array(data, pa.list_(pa.int64()))})
    raw, logical = roundtrip_rows(
        tmp_path, table, data_page_version=page_version, use_dictionary=False
    )
    assert [r["lst"] for r in logical] == data
    # raw rows keep the physical wrappers
    assert raw[0]["lst"] == {"list": [{"element": 1}, {"element": 2}]}
    assert raw[1]["lst"] is None
    assert raw[2]["lst"] == {"list": []}


def test_list_of_strings_with_null_elements(tmp_path):
    data = [["a", None], ["b"], None, []]
    table = pa.table({"lst": pa.array(data, pa.list_(pa.string()))})
    _, logical = roundtrip_rows(tmp_path, table)
    assert [r["lst"] for r in logical] == data


def test_nested_list_of_lists(tmp_path):
    data = [[[1, 2], [3]], None, [[], [4]], [[5]]]
    table = pa.table({"ll": pa.array(data, pa.list_(pa.list_(pa.int64())))})
    _, logical = roundtrip_rows(tmp_path, table)
    assert [r["ll"] for r in logical] == data


def test_map_column(tmp_path):
    data = [{"a": 1, "b": 2}, None, {}, {"c": 3}]
    table = pa.table({"m": pa.array(data, pa.map_(pa.string(), pa.int64()))})
    _, logical = roundtrip_rows(tmp_path, table)
    got = [r["m"] for r in logical]
    assert got[0] == {"a": 1, "b": 2}
    assert got[1] is None
    assert got[2] == {}
    assert got[3] == {"c": 3}


def test_struct_column(tmp_path):
    data = [{"x": 1, "y": "a"}, None, {"x": 3, "y": None}]
    table = pa.table({
        "st": pa.array(data, pa.struct([("x", pa.int64()), ("y", pa.string())])),
    })
    _, logical = roundtrip_rows(tmp_path, table)
    assert [r["st"] for r in logical] == data


def test_list_of_structs(tmp_path):
    data = [
        [{"x": 1, "y": "a"}, {"x": 2, "y": None}],
        None,
        [],
        [{"x": None, "y": "c"}],
    ]
    ty = pa.list_(pa.struct([("x", pa.int64()), ("y", pa.string())]))
    table = pa.table({"ls": pa.array(data, ty)})
    _, logical = roundtrip_rows(tmp_path, table)
    assert [r["ls"] for r in logical] == data


def test_struct_of_lists_and_maps(tmp_path):
    ty = pa.struct([
        ("tags", pa.list_(pa.string())),
        ("attrs", pa.map_(pa.string(), pa.float64())),
    ])
    data = [
        {"tags": ["a", "b"], "attrs": {"k": 1.0}},
        {"tags": [], "attrs": {}},
        None,
    ]
    table = pa.table({"s": pa.array(data, ty)})
    _, logical = roundtrip_rows(tmp_path, table)
    assert [r["s"] for r in logical] == data


def test_deep_nesting_map_of_lists(tmp_path):
    ty = pa.map_(pa.string(), pa.list_(pa.int64()))
    data = [{"a": [1, 2], "b": []}, {}, None, {"c": [3]}]
    table = pa.table({"m": pa.array(data, ty)})
    _, logical = roundtrip_rows(tmp_path, table)
    assert [r["m"] for r in logical] == data


def test_multi_rowgroup_row_iteration(tmp_path):
    data = [[i, i + 1] for i in range(1000)]
    table = pa.table({
        "id": pa.array(range(1000), pa.int64()),
        "lst": pa.array(data, pa.list_(pa.int64())),
    })
    p = write(tmp_path, table, row_group_size=100)
    with FileReader(p) as r:
        rows = [unwrap_row(r.schema, row) for row in r]
        assert len(rows) == 1000
        assert rows[500] == {"id": 500, "lst": [500, 501]}


def test_legacy_two_level_list_of_structs():
    # Hive-era layout: optional group col (LIST) { repeated group array {
    # required int32 x } } — the repeated group IS the element
    from tpu_parquet.schema.core import ColumnParameters
    from tpu_parquet.format import ConvertedType, LogicalType, ListType

    schema = build_schema([
        SchemaNode(
            __import__("tpu_parquet.format", fromlist=["SchemaElement"]).SchemaElement(
                name="col", repetition_type=int(FRT.OPTIONAL),
                converted_type=int(ConvertedType.LIST),
            ),
            [
                group_column("array", [data_column("x", Type.INT32, FRT.REQUIRED)],
                             FRT.REPEATED),
            ],
        )
    ])
    cols = {
        "col.array.x": ColumnData(
            values=np.array([1, 2], dtype=np.int32),
            def_levels=np.array([2, 2]),
            rep_levels=np.array([0, 1]),
            max_def=2, max_rep=1,
        )
    }
    rows = assemble_rows(schema, cols)
    assert rows == [{"col": {"array": [{"x": 1}, {"x": 2}]}}]
    assert unwrap_row(schema, rows[0]) == {"col": [{"x": 1}, {"x": 2}]}


def test_preload_cache_not_invalidated_by_iteration(tmp_path):
    table = pa.table({"v": pa.array(range(10), pa.int64())})
    p = write(tmp_path, table)
    with FileReader(p) as r:
        first = r.preload()
        r.seek_to_row_group(0)  # same group: cache must survive
        assert r.preload() is first
        rows = list(r.iter_rows())
        assert len(rows) == 10


def test_iterator_honors_seek(tmp_path):
    table = pa.table({"v": pa.array(range(10), pa.int64())})
    p = write(tmp_path, table, row_group_size=5)
    with FileReader(p) as r:
        r.seek_to_row_group(1)
        rows = [row["v"] for row in r.iter_rows()]
    assert rows == [5, 6, 7, 8, 9]


def test_assemble_window():
    schema = build_schema([data_column("v", Type.INT64, FRT.REQUIRED)])
    cols = {"v": ColumnData(values=np.arange(100, dtype=np.int64), max_def=0, max_rep=0)}
    rows = assemble_rows(schema, cols, start=10, count=3)
    assert rows == [{"v": 10}, {"v": 11}, {"v": 12}]


def test_legacy_map_key_value_on_repeated_group():
    # legacy layout: MAP_KEY_VALUE annotates the repeated group itself
    from tpu_parquet.format import ConvertedType, SchemaElement

    kv_elem = SchemaElement(
        name="map", repetition_type=int(FRT.REPEATED),
        converted_type=int(ConvertedType.MAP_KEY_VALUE),
    )
    schema = build_schema([
        group_column("m", [
            SchemaNode(kv_elem, [
                data_column("key", Type.INT64, FRT.REQUIRED),
                data_column("value", Type.INT64, FRT.REQUIRED),
            ]),
        ], FRT.OPTIONAL),
    ])
    cols = {
        "m.map.key": ColumnData(
            values=np.array([1, 2], dtype=np.int64),
            def_levels=np.array([2, 2]), rep_levels=np.array([0, 1]),
            max_def=2, max_rep=1,
        ),
        "m.map.value": ColumnData(
            values=np.array([7, 8], dtype=np.int64),
            def_levels=np.array([2, 2]), rep_levels=np.array([0, 1]),
            max_def=2, max_rep=1,
        ),
    }
    rows = assemble_rows(schema, cols)
    out = unwrap_row(schema, rows[0])
    assert out == {"m": {"map": {1: 7, 2: 8}}}


def test_projection_with_nested(tmp_path):
    table = pa.table({
        "id": pa.array([1, 2], pa.int64()),
        "lst": pa.array([[1], [2, 3]], pa.list_(pa.int64())),
    })
    p = write(tmp_path, table)
    with FileReader(p, columns=["lst"]) as r:
        rows = [unwrap_row(r.schema, row) for row in r]
    assert rows == [{"lst": [1]}, {"lst": [2, 3]}]
