"""Pallas bit-unpack kernel vs the NumPy reference (interpret mode on CPU).

The Mosaic kernel itself is exercised on real TPU by bench.py's microbench;
here the same kernel body runs through the Pallas interpreter so CI-style
tests cover the unrolled byte/shift logic for every width, including the
5-byte-span widths (26..32 with nonzero shift) and ragged tail tiles.
"""

import numpy as np
import pytest

from tpu_parquet.kernels import bitpack
from tpu_parquet.pallas_kernels import unpack_bits_pallas

RNG = np.random.default_rng(7)


@pytest.mark.parametrize("width", [1, 3, 7, 8, 13, 17, 25, 26, 31, 32])
def test_unpack_parity(width):
    n = 5000
    mask = (1 << width) - 1
    vals = RNG.integers(0, 1 << 32, n, dtype=np.uint64) & mask
    packed = np.frombuffer(bitpack.pack(vals, width), np.uint8)
    got = np.asarray(unpack_bits_pallas(packed, width, n, interpret=True))
    want = bitpack.unpack(packed, width, n).astype(np.uint32)
    np.testing.assert_array_equal(got, want)


def test_unpack_tile_boundary():
    # count exactly at and just past the 8192-value tile boundary
    width = 5
    for n in (8192, 8193, 16384 - 1):
        vals = RNG.integers(0, 32, n, dtype=np.uint64)
        packed = np.frombuffer(bitpack.pack(vals, width), np.uint8)
        got = np.asarray(unpack_bits_pallas(packed, width, n, interpret=True))
        np.testing.assert_array_equal(
            got, bitpack.unpack(packed, width, n).astype(np.uint32)
        )


def test_unpack_rejects_bad_width():
    with pytest.raises(ValueError):
        unpack_bits_pallas(np.zeros(8, np.uint8), 0, 8, interpret=True)
    with pytest.raises(ValueError):
        unpack_bits_pallas(np.zeros(8, np.uint8), 33, 8, interpret=True)
