"""Codec registry + snappy (native C++ and pure-Python) tests.

Cross-validated against pyarrow's canonical snappy/zstd/gzip codecs, mirroring the
role of compress_test.go in the reference.
"""

import os
import random

import pytest

from tpu_parquet import native
from tpu_parquet.compress import (
    BlockCompressor,
    CompressionError,
    SnappyCompressor,
    _py_snappy_compress,
    _py_snappy_decompress,
    compress_block,
    decompress_block,
    get_codec,
    register_codec,
    registered_codecs,
)
from tpu_parquet.format import CompressionCodec

pa = pytest.importorskip("pyarrow")


def _corpora():
    rng = random.Random(42)
    return [
        b"",
        b"a",
        b"abcd" * 3,
        b"hello world, hello world, hello world!" * 100,
        bytes(rng.randrange(256) for _ in range(10_000)),  # incompressible
        bytes(rng.randrange(4) for _ in range(100_000)),   # compressible
        b"\x00" * 200_000,                                  # highly repetitive
        os.urandom(70_000),                                 # > one 64K block
        b"x" * 65536 + b"y" * 65536 + os.urandom(100),
    ]


@pytest.mark.parametrize(
    "codec",
    [CompressionCodec.UNCOMPRESSED, CompressionCodec.SNAPPY,
     CompressionCodec.GZIP, CompressionCodec.ZSTD],
)
def test_registry_roundtrip(codec):
    from conftest import require_codec

    require_codec(codec)
    for data in _corpora():
        comp = compress_block(data, codec)
        # decompress output is bytes-LIKE (the zero-copy snappy path returns
        # a uint8 array); content equality is the contract
        assert bytes(decompress_block(comp, codec, len(data))) == data


def test_snappy_native_available():
    # The image has g++; the native codec must actually build and load.
    assert native.available(), "native snappy failed to build"


def test_native_snappy_decodes_pyarrow_output():
    for data in _corpora():
        comp = pa.compress(data, codec="snappy", asbytes=True)
        assert bytes(native.snappy_decompress(comp)) == data


def test_pyarrow_decodes_native_snappy_output():
    for data in _corpora():
        comp = native.snappy_compress(data)
        out = pa.decompress(
            comp, decompressed_size=len(data), codec="snappy", asbytes=True
        )
        assert out == data


def test_py_snappy_fallback_matches_native():
    for data in _corpora():
        comp = pa.compress(data, codec="snappy", asbytes=True)
        assert _py_snappy_decompress(comp) == data
        assert _py_snappy_decompress(_py_snappy_compress(data)) == data
        # fallback output must be readable by the canonical codec too
        assert pa.decompress(
            _py_snappy_compress(data), decompressed_size=len(data),
            codec="snappy", asbytes=True,
        ) == data


def test_snappy_compression_actually_compresses():
    data = b"the quick brown fox " * 5000
    comp = native.snappy_compress(data)
    assert len(comp) < len(data) // 4


def test_declared_size_mismatch_raises():
    comp = compress_block(b"hello world", CompressionCodec.SNAPPY)
    with pytest.raises(CompressionError):
        decompress_block(comp, CompressionCodec.SNAPPY, 5)
    with pytest.raises(CompressionError):
        decompress_block(b"hello", CompressionCodec.UNCOMPRESSED, 4)


def test_malformed_snappy_raises():
    bad_inputs = [
        b"\xff\xff\xff\xff\xff\xff",   # huge/invalid varint header
        b"\x05\xfc",                    # copy4 with no offset bytes
        b"\x0a\x01\x02",                # declared 10 bytes, tiny literal
        b"\x05\x09\x00\x10",            # copy with offset beyond output
    ]
    snappy = SnappyCompressor()
    for b in bad_inputs:
        with pytest.raises(CompressionError):
            snappy.decompress_block(b, 10)
        with pytest.raises(CompressionError):
            _py_snappy_decompress(b)


def test_unsupported_codec_raises():
    with pytest.raises(CompressionError):
        get_codec(CompressionCodec.LZO)


def test_pluggable_registry():
    class XorCodec(BlockCompressor):
        def compress_block(self, block):
            return bytes(b ^ 0x5A for b in block)

        def decompress_block(self, block, uncompressed_size):
            return bytes(b ^ 0x5A for b in block)

    register_codec(CompressionCodec.LZ4_RAW, XorCodec())
    try:
        data = b"pluggable codecs work"
        comp = compress_block(data, CompressionCodec.LZ4_RAW)
        assert decompress_block(comp, CompressionCodec.LZ4_RAW, len(data)) == data
        assert int(CompressionCodec.LZ4_RAW) in registered_codecs()
    finally:
        from tpu_parquet import compress as _c

        with _c._registry_lock:
            _c._registry.pop(int(CompressionCodec.LZ4_RAW), None)


def test_gzip_roundtrip_with_pyarrow():
    data = b"gzip interop " * 1000
    comp = compress_block(data, CompressionCodec.GZIP)
    assert pa.decompress(comp, decompressed_size=len(data), codec="gzip",
                         asbytes=True) == data
