"""Per-request tracing + tail sampling + live metrics export (ISSUE 19).

The contracts under test, in rough order of importance:

- tracing is bit-identity-neutral: a scan (and a streaming scan) through a
  fault-injecting store returns THE SAME bytes with tracing off
  (``TPQ_TRACE_TAIL=0``) and retain-all (``=1``), across the recoverable
  fault matrix — the spans observe the request, they never steer it;
- a slow/errored request under injected chaos is reconstructable after the
  fact: its retained tree is well-nested, carries the queue-wait / cache
  probe / range-fetch (with retry annotations) / decode story, survives
  ``trace_dump`` → ``pq_tool trace --request``;
- the tail sampler retains errored/flagged/slow/1-in-N trees into a ring
  bounded by BYTES with ledger-consistent counters, and ``offer``'s verdict
  gates exemplars so a histogram bucket only ever names a fetchable trace;
- exemplars ride ``LatencyHistogram.as_dict``/``from_dict`` round-trips,
  re-derive their own bucket from the raw value, and render as OpenMetrics
  exemplar suffixes (``# {trace_id="..."} value``) behind ``# EOF``;
- the ``slo-burn`` doctor verdict walks a breached per-tenant SLO histogram
  back to the offending bucket and its retained exemplar trace;
- ``MetricsDumper`` (``TPQ_METRICS_DUMP=path:interval``) writes atomic
  snapshots, stops with its service, and never leaves a thread behind.
"""

import argparse
import io
import json
import os
import threading
import time

import numpy as np
import pytest

from tpu_parquet.column import ByteArrayData, ColumnData
from tpu_parquet.format import (CompressionCodec, FieldRepetitionType as FRT,
                                Type)
from tpu_parquet.iostore import (FaultInjectingStore, FaultSpec, IOConfig,
                                 LocalStore)
from tpu_parquet.obs import (LatencyHistogram, MetricsDumper, RequestTrace,
                             TailSampler, current_request_trace,
                             diff_registry_trees, doctor_registry,
                             render_openmetrics, resolve_metrics_dump,
                             set_request_trace)
from tpu_parquet.schema.core import build_schema, data_column
from tpu_parquet.serve import ScanRequest, ScanService
from tpu_parquet.writer import FileWriter


def _strings(vals):
    return ColumnData(values=ByteArrayData(
        offsets=np.cumsum([0] + [len(v) for v in vals]),
        heap=np.frombuffer(b"".join(vals), np.uint8).copy(),
    ))


def _write_file(path, seed=0, groups=2, rows=400):
    rng = np.random.default_rng(seed)
    schema = build_schema([
        data_column("a", Type.INT64, FRT.REQUIRED),
        data_column("s", Type.BYTE_ARRAY, FRT.REQUIRED),
    ])
    pool = [b"alpha", b"beta", b"gamma", b"delta", b""]
    with open(path, "wb") as fh:
        with FileWriter(fh, schema, codec=CompressionCodec.SNAPPY) as w:
            for _g in range(groups):
                svals = [pool[i] for i in rng.integers(0, len(pool), rows)]
                w.write_columns({
                    "a": rng.integers(-(1 << 40), 1 << 40, rows),
                    "s": _strings(svals),
                })
                w.flush_row_group()
    return path


@pytest.fixture(scope="module")
def files(tmp_path_factory):
    d = tmp_path_factory.mktemp("reqtrace")
    return [_write_file(str(d / f"f{i}.parquet"), seed=i) for i in range(2)]


def _assert_cols_equal(got, want):
    assert set(got) == set(want)
    for name in want:
        g, w = got[name], want[name]
        if isinstance(w.values, ByteArrayData):
            np.testing.assert_array_equal(g.values.offsets, w.values.offsets)
            np.testing.assert_array_equal(g.values.heap, w.values.heap)
        else:
            np.testing.assert_array_equal(g.values, w.values)


def _drain(session):
    cols = {}
    for batch in session:
        mask = np.asarray(batch["mask"])
        for name, arr in batch.items():
            if name != "mask":
                cols.setdefault(name, []).append(np.asarray(arr)[mask])
    return {n: np.concatenate(v) for n, v in cols.items()}


# ---------------------------------------------------------------------------
# RequestTrace: well-nestedness, the span cap, cross-thread stacks
# ---------------------------------------------------------------------------

def test_trace_well_nested_and_closed():
    tr = RequestTrace()
    with tr.span("root", kind="request"):
        with tr.span("child"):
            tr.annotate(bytes=7)
        tr.add_timed("timed", 0.0, 0.001, n=3)
    dur = tr.finish()
    assert dur >= 0.0
    assert [s[0] for s in tr.spans] == ["root", "child", "timed"]
    # parent index strictly smaller than the child's own index
    assert [s[3] for s in tr.spans] == [-1, 0, 0]
    assert all(s[2] is not None and s[2] >= 0.0 for s in tr.spans)
    assert tr.spans[1][4] == {"bytes": 7}
    doc = tr.as_dict()
    assert doc["trace_id"] == tr.trace_id and doc["dropped"] == 0
    assert [s["parent"] for s in doc["spans"]] == [-1, 0, 0]


def test_trace_error_close_and_flags():
    tr = RequestTrace()
    with pytest.raises(ValueError):
        with tr.span("fetch", offset=0):
            raise ValueError("boom")
    tr.mark_error(ValueError("boom"))
    tr.set_flag("deadline")
    tr.finish()
    assert tr.spans[0][4]["error"] == "ValueError"
    assert tr.error == {"type": "ValueError", "message": "boom"}
    assert tr.flags == {"deadline"}


def test_trace_span_cap_counts_drops():
    tr = RequestTrace(max_spans=4)
    for i in range(9):
        with tr.span(f"s{i}"):
            pass
    tr.finish()
    assert len(tr.spans) == 4
    assert tr.dropped == 5
    assert tr.as_dict()["dropped"] == 5


def test_trace_cross_thread_nesting_and_orphan_close():
    tr = RequestTrace()
    started = threading.Event()
    release = threading.Event()

    def helper():
        s = tr.span("worker")  # first span on this thread: parents to root
        s.__enter__()
        with tr.span("inner"):
            started.set()
            release.wait(5.0)
        # "worker" left open on purpose: finish() must close the orphan

    with tr.span("request"):
        t = threading.Thread(target=helper)
        t.start()
        started.wait(5.0)
        with tr.span("main_child"):
            pass
        release.set()
        t.join()
    tr.finish()
    by_name = {s[0]: s for s in tr.spans}
    assert by_name["worker"][3] == -1          # own stack, not main's
    assert by_name["inner"][3] == tr.spans.index(by_name["worker"])
    assert by_name["main_child"][3] == tr.spans.index(by_name["request"])
    assert all(s[2] is not None for s in tr.spans)  # orphan closed


def test_current_request_trace_install_restore():
    assert current_request_trace() is None
    tr = RequestTrace()
    prev = set_request_trace(tr)
    assert prev is None and current_request_trace() is tr
    set_request_trace(prev)
    assert current_request_trace() is None


# ---------------------------------------------------------------------------
# TailSampler: retention policy, the byte-bounded ring, counters
# ---------------------------------------------------------------------------

def _finished_trace(nspans=3):
    tr = RequestTrace()
    for i in range(nspans):
        with tr.span(f"s{i}"):
            pass
    tr.finish()
    return tr


def test_sampler_one_in_n_and_interesting():
    s = TailSampler(one_in_n=100, ring_bytes=1 << 20)
    assert s.enabled
    assert not s.offer(_finished_trace(), duration_s=0.001)  # boring
    err = _finished_trace()
    assert s.offer(err, duration_s=0.001, error=True)        # errored
    flagged = _finished_trace()
    flagged.set_flag("shed")
    assert s.offer(flagged, duration_s=0.001)                # flagged
    marked = _finished_trace()
    marked.mark_error(ValueError("x"))
    assert s.offer(marked, duration_s=0.001)                 # trace.error
    c = s.counters()
    assert c["offered"] == 4 and c["retained"] == 3 and c["evicted"] == 0
    assert s.get(err.trace_id)["trace_id"] == err.trace_id
    assert s.get("nope") is None


def test_sampler_retain_all_and_slow_gate():
    s = TailSampler(one_in_n=1, ring_bytes=1 << 20)
    traces = [_finished_trace() for _ in range(3)]
    for tr in traces:
        assert s.offer(tr, duration_s=0.001)  # 1-in-1: everything retains
    ids = {t["trace_id"] for t in s.traces()}
    assert ids == {tr.trace_id for tr in traces}

    slow = TailSampler(one_in_n=10 ** 9, ring_bytes=1 << 20, slow_q=0.9)
    # below SLOW_MIN_SAMPLES nothing is "slow"; past it the tail retains
    for _ in range(TailSampler.SLOW_MIN_SAMPLES):
        slow.offer(_finished_trace(), duration_s=0.001)
    assert slow.offer(_finished_trace(), duration_s=0.5)  # way past p90
    assert not slow.offer(_finished_trace(), duration_s=0.0001)


def test_sampler_disabled_and_ring_byte_bound():
    off = TailSampler(one_in_n=0)
    assert not off.enabled
    assert not off.offer(_finished_trace(), duration_s=1.0, error=True)
    assert off.counters()["offered"] == 0

    s = TailSampler(one_in_n=1, ring_bytes=4096)
    for i in range(64):
        s.offer(_finished_trace(nspans=8), duration_s=0.001)
        c = s.counters()
        assert c["retained_bytes"] <= c["ring_capacity_bytes"], c
    c = s.counters()
    assert c["evicted"] > 0  # 64 8-span trees cannot fit 4 KiB
    assert len(s.traces()) == c["retained"] - c["evicted"]
    # one pathological tree larger than the whole ring: rejected, ring kept
    huge = RequestTrace(max_spans=4096)
    for i in range(2000):
        huge.add_timed(f"pad{i}", 0.0, 0.0, note="x" * 40)
    huge.finish()
    before = s.counters()["retained"]
    assert not s.offer(huge, duration_s=0.001)
    assert s.counters()["retained"] == before


def test_sampler_dump_roundtrip(tmp_path):
    s = TailSampler(one_in_n=1)
    tr = _finished_trace()
    s.offer(tr, duration_s=0.002)
    path = str(tmp_path / "traces.json")
    assert s.dump(path) == path
    with open(path) as f:
        doc = json.load(f)
    assert doc["trace_dump_version"] == 1
    assert doc["traces"][0]["trace_id"] == tr.trace_id


# ---------------------------------------------------------------------------
# exemplars: raw values, bucket re-derivation, serialization, OpenMetrics
# ---------------------------------------------------------------------------

def test_exemplar_roundtrip_and_bucket_rederive():
    h = LatencyHistogram()
    h.record(0.001)
    assert "exemplars" not in h.as_dict()  # absent key when none recorded
    h.record(0.004, exemplar="tpq-aaaa")
    h.record(0.2, exemplar="tpq-bbbb")
    for idx, (tid, val) in h.exemplars.items():
        assert LatencyHistogram.bucket_index(val) == idx, (idx, val)
    d = h.as_dict()
    assert set(d["exemplars"]) == {str(i) for i in h.exemplars}
    h2 = LatencyHistogram.from_dict(d)
    assert h2.exemplars == h.exemplars
    assert h2.count == h.count


def test_render_openmetrics_exemplars_and_eof():
    h = LatencyHistogram()
    h.record(0.004, exemplar="tpq-dead")
    tree = {"serve": {"requests": 3, "rejected": 0},
            "histograms": {"serve.request": h.as_dict()}}
    text = render_openmetrics(tree)
    assert text.endswith("# EOF\n")
    assert "# TYPE tpq_serve_requests gauge" in text
    assert "tpq_serve_requests 3" in text
    assert "# TYPE tpq_serve_request_seconds histogram" in text
    assert 'trace_id="tpq-dead"' in text
    assert "tpq_serve_request_seconds_count 1" in text
    with pytest.raises(ValueError):
        render_openmetrics([1, 2])  # type: ignore[arg-type]
    d = diff_registry_trees({"serve": {"requests": 3}},
                            {"serve": {"requests": 5, "rejected": 1}})
    assert d == {"serve.requests": (3, 5, 2), "serve.rejected": (0, 1, 1)}


# ---------------------------------------------------------------------------
# bit-identity: tracing on vs off across the recoverable fault matrix
# ---------------------------------------------------------------------------

RECOVERABLE = {
    "latency_spike": FaultSpec(latency_s=0.005),
    "transient_errors": FaultSpec(fail_first=2),
    "torn_read": FaultSpec(torn_first=1),
    "torn_then_error": FaultSpec(torn_first=1, fail_first=2),
}


def _fault_factory(spec):
    return lambda f: FaultInjectingStore(
        LocalStore(f), spec, config=IOConfig(retries=4, backoff_ms=1.0))


@pytest.mark.parametrize("fault", sorted(RECOVERABLE))
def test_scan_bit_identical_tracing_on_off(files, fault, monkeypatch):
    """The spans observe the request, they never steer it: the same
    faulted scan returns the same bytes with tracing off and retain-all,
    and the retained tree carries the fetch story (retry annotations)."""
    path = files[0]
    results = {}
    for mode, env in (("off", "0"), ("retain_all", "1")):
        monkeypatch.setenv("TPQ_TRACE_TAIL", env)
        svc = ScanService(concurrency=2,
                          store=_fault_factory(RECOVERABLE[fault]))
        try:
            results[mode] = svc.scan(ScanRequest(path), timeout=60)[path]
            c = svc.sampler.counters()
            if mode == "off":
                assert c["offered"] == 0  # genuinely zero-cost off
            else:
                assert c["retained"] >= 1
                docs = svc.sampler.traces()
                names = {s["name"] for d in docs for s in d["spans"]}
                assert {"submit", "queue_wait", "read", "fetch"} <= names
                for d in docs:  # retained trees are well-nested, closed
                    for i, s in enumerate(d["spans"]):
                        assert s["parent"] == -1 or 0 <= s["parent"] < i
                        assert s["dur_s"] is not None
                if "errors" in fault:
                    anns = [s.get("args") or {} for d in docs
                            for s in d["spans"] if s["name"] == "fetch"]
                    assert any(a.get("retries") for a in anns)
        finally:
            svc.close()
    _assert_cols_equal(results["retain_all"], results["off"])


@pytest.mark.parametrize("fault", ["transient_errors", "torn_then_error"])
def test_stream_bit_identical_tracing_on_off(files, fault, monkeypatch):
    drained = {}
    for mode, env in (("off", "0"), ("retain_all", "1")):
        monkeypatch.setenv("TPQ_TRACE_TAIL", env)
        svc = ScanService(concurrency=2,
                          store=_fault_factory(RECOVERABLE[fault]))
        try:
            session = svc.scan(
                ScanRequest(files, stream=True, batch_rows=128), timeout=60)
            drained[mode] = _drain(session)
            if mode == "retain_all":
                # the worker's finish/offer bookkeeping can trail the
                # consumer's last batch by a beat
                deadline = time.time() + 10.0
                while (time.time() < deadline
                       and not svc.sampler.counters()["retained"]):
                    time.sleep(0.01)
                docs = svc.sampler.traces()
                names = {s["name"] for d in docs for s in d["spans"]}
                # the streaming story: per-batch and per-group spans ride
                assert {"submit", "batch", "group"} <= names
        finally:
            svc.close()
    for name in drained["off"]:
        np.testing.assert_array_equal(drained["retain_all"][name],
                                      drained["off"][name])


def test_errored_request_trace_reconstructable(files, tmp_path, monkeypatch):
    """The acceptance story: a request that died under chaos is
    reconstructable — retained on error, fetchable by id, dumpable, and
    ``pq_tool trace --request`` prints its span tree with the error."""
    from tpu_parquet.cli import pq_tool

    monkeypatch.setenv("TPQ_TRACE_TAIL", "128")  # NOT retain-all: the
    # errored-trace gate, not 1-in-N, must do the retaining here
    path = files[0]
    svc = ScanService(concurrency=1, store=_fault_factory(
        FaultSpec(fail_first=10 ** 6)))  # never recovers: scan fails
    try:
        with pytest.raises(Exception):
            svc.scan(ScanRequest(path), timeout=60)
        docs = svc.sampler.traces()
        assert len(docs) == 1 and docs[0]["error"] is not None
        tid = docs[0]["trace_id"]
        assert svc.get_trace(tid)["trace_id"] == tid
        dump = str(tmp_path / "traces.json")
        svc.trace_dump(dump)
    finally:
        svc.close()
    buf = io.StringIO()
    rc = pq_tool.cmd_trace(argparse.Namespace(
        file=dump, request=tid, config=None), out=buf)
    out = buf.getvalue()
    assert rc == 0
    assert tid in out and "error:" in out and "fetch" in out
    # unknown id: exit 1 with the retained ids and the sampling advice
    buf = io.StringIO()
    rc = pq_tool.cmd_trace(argparse.Namespace(
        file=dump, request="tpq-nope", config=None), out=buf)
    assert rc == 1
    assert "TPQ_TRACE_TAIL" in buf.getvalue()


def test_exemplar_links_tenant_histogram_to_trace(files, monkeypatch):
    """Retain-all: the per-tenant SLO histogram's exemplars name traces
    the sampler can actually fetch back — the exemplar gate contract."""
    monkeypatch.setenv("TPQ_TRACE_TAIL", "1")
    svc = ScanService(concurrency=1)
    try:
        svc.register_tenant("acme", weight=2, slo_p99_ms=50.0)
        for _ in range(3):
            svc.scan(ScanRequest(files[0], tenant="acme"), timeout=60)
        tree = svc.obs_registry().as_dict()
        hd = tree["histograms"]["serve.tenant.acme"]
        assert hd.get("exemplars"), hd
        for idx, (tid, val) in hd["exemplars"].items():
            assert svc.get_trace(tid) is not None
            assert LatencyHistogram.bucket_index(float(val)) == int(idx)
        assert tree["serve"]["tenants"]["acme"]["traces_retained"] >= 3
        assert tree["serve"]["trace"]["retained"] >= 3
    finally:
        svc.close()


# ---------------------------------------------------------------------------
# slo-burn doctor verdict
# ---------------------------------------------------------------------------

def _slo_burn_tree(trace_id="tpq-feed-1"):
    h = LatencyHistogram()
    for _ in range(200):
        h.record(0.002)
    for _ in range(10):
        h.record(0.5, exemplar=trace_id)  # tail way past the 10ms SLO
    return {
        "obs_version": 1,
        "serve": {"requests": 210,
                  "tenants": {"acme": {"weight": 1, "slo_p99_ms": 10.0}}},
        "histograms": {"serve.tenant.acme": h.as_dict()},
    }


def test_doctor_slo_burn_names_bucket_and_exemplar(tmp_path):
    report = doctor_registry(_slo_burn_tree())
    assert report is not None
    sb = report.get("slo_burn")
    assert sb is not None and sb["verdict"] == "slo-burn"
    assert sb["tenant"] == "acme" and sb["burn_ratio"] > 1.0
    assert sb["exemplar_trace"] == "tpq-feed-1"
    assert sb["bucket"] == LatencyHistogram.bucket_index(0.5)
    assert sb["burning_tenants"] == ["acme"]
    assert "pq_tool trace --request tpq-feed-1" in sb["advice"]
    # within SLO: no verdict
    ok = _slo_burn_tree()
    ok["serve"]["tenants"]["acme"]["slo_p99_ms"] = 10_000.0
    rep = doctor_registry(ok)
    assert rep is None or rep.get("slo_burn") is None

    from tpu_parquet.cli import pq_tool

    path = str(tmp_path / "run.json")
    with open(path, "w") as f:
        json.dump(_slo_burn_tree(), f)
    buf = io.StringIO()
    rc = pq_tool.cmd_doctor(
        argparse.Namespace(file=path, config=None), out=buf)
    out = buf.getvalue()
    assert rc == 0
    assert "slo-burn:" in out and "'acme'" in out
    assert "tpq-feed-1" in out


# ---------------------------------------------------------------------------
# pq_tool metrics + serve-stats surfaces
# ---------------------------------------------------------------------------

def _metrics_ns(file, file2=None, **kw):
    kw.setdefault("config", None)
    kw.setdefault("watch", False)
    kw.setdefault("interval", 2.0)
    kw.setdefault("count", None)
    return argparse.Namespace(file=file, file2=file2, **kw)


def test_metrics_cli_render_diff_watch(tmp_path):
    from tpu_parquet.cli import pq_tool

    a = _slo_burn_tree()
    b = json.loads(json.dumps(a))
    b["serve"]["requests"] = 250
    pa, pb = str(tmp_path / "a.json"), str(tmp_path / "b.json")
    with open(pa, "w") as f:
        json.dump(a, f)
    with open(pb, "w") as f:
        json.dump(b, f)

    buf = io.StringIO()
    assert pq_tool.cmd_metrics(_metrics_ns(pa), out=buf) == 0
    text = buf.getvalue()
    assert "tpq_serve_requests 210" in text and "# EOF" in text
    assert 'trace_id="tpq-feed-1"' in text

    buf = io.StringIO()
    assert pq_tool.cmd_metrics(_metrics_ns(pa, pb), out=buf) == 0
    assert "serve.requests" in buf.getvalue()
    assert "210 -> 250" in buf.getvalue()

    buf = io.StringIO()  # --watch bounded by --count exits cleanly
    assert pq_tool.cmd_metrics(
        _metrics_ns(pa, watch=True, interval=0.01, count=2), out=buf) == 0
    assert "watching" in buf.getvalue()

    buf = io.StringIO()
    assert pq_tool.cmd_metrics(_metrics_ns(str(tmp_path / "nope.json")),
                               out=buf) == 1


def test_serve_stats_exemplar_rows_and_tracing_line(files, tmp_path,
                                                    monkeypatch):
    from tpu_parquet.cli import pq_tool

    monkeypatch.setenv("TPQ_TRACE_TAIL", "1")
    with ScanService(concurrency=1) as svc:
        svc.register_tenant("acme", weight=1, slo_p99_ms=75.0)
        svc.scan(ScanRequest(files[0], tenant="acme"), timeout=60)
        tree = svc.obs_registry().as_dict()
    path = str(tmp_path / "reg.json")
    with open(path, "w") as f:
        json.dump(tree, f)
    buf = io.StringIO()
    rc = pq_tool.cmd_serve_stats(
        argparse.Namespace(file=path, config=None), out=buf)
    out = buf.getvalue()
    assert rc == 0
    assert "tracing:" in out and "retained" in out
    assert "exemplars (bucket -> retained trace):" in out
    # at least one retained trace id appears in an exemplar row
    ex_ids = [ex[0] for hd in tree["histograms"].values()
              for ex in (hd.get("exemplars") or {}).values()]
    assert ex_ids and any(t in out for t in ex_ids)


# ---------------------------------------------------------------------------
# MetricsDumper: lifecycle, atomicity, the env spec
# ---------------------------------------------------------------------------

def test_resolve_metrics_dump_spec():
    assert resolve_metrics_dump("/tmp/m.json:2.5") == ("/tmp/m.json", 2.5)
    assert resolve_metrics_dump("") is None
    assert resolve_metrics_dump("noseparator") is None
    assert resolve_metrics_dump("path:notafloat") is None
    assert resolve_metrics_dump("path:-1") is None
    assert resolve_metrics_dump(":2.0") is None


def _dumper_threads():
    return [t.name for t in threading.enumerate()
            if t.name.startswith("tpq-metricsdump")]


def test_metrics_dumper_lifecycle(tmp_path):
    path = str(tmp_path / "snap.json")
    tree = {"serve": {"requests": 1}}
    d = MetricsDumper(lambda: tree, spec=f"{path}:0.02")
    assert d.enabled
    with d:
        time.sleep(0.08)
    assert not _dumper_threads()  # stop() joined
    assert d.written >= 2
    with open(path) as f:
        assert json.load(f) == tree  # atomic: never a torn file
    # malformed spec: inert, start() is a no-op, dump_once returns None
    inert = MetricsDumper(lambda: tree, spec="bad")
    assert not inert.enabled
    inert.start()
    assert not _dumper_threads()
    assert inert.dump_once() is None
    # a failing source is counted, never raised
    fail = MetricsDumper(lambda: 1 / 0, spec=f"{path}:5")
    assert fail.dump_once() is None and fail.dropped == 1


def test_service_dumper_env_snapshot(files, tmp_path, monkeypatch):
    path = str(tmp_path / "live.json")
    monkeypatch.setenv("TPQ_METRICS_DUMP", f"{path}:30")
    svc = ScanService(concurrency=1)
    try:
        svc.scan(ScanRequest(files[0]), timeout=60)
        assert _dumper_threads()  # running alongside the service
    finally:
        svc.close()
    assert not _dumper_threads()  # joined by close()
    with open(path) as f:  # stop() wrote the final end-state snapshot
        tree = json.load(f)
    assert tree["serve"]["submitted"] >= 1
