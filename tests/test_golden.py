"""Interop golden files: the in-image substitute for the parquet-mr leg.

The reference's cross-implementation ground truth is parquet-mr via Docker
(compatibility/run_tests.bash) — unrunnable here (no Java/network).  This is
the substitute that EXECUTES on every CI run, per {codec} x {v1,v2} x {CRC}
cell (compatibility/make_goldens.py writes the checked-in files):

  1. byte-stability: regenerating the cell reproduces the checked-in bytes
     EXACTLY for the fully-in-repo codecs (UNCOMPRESSED, SNAPPY — writer,
     thrift serializer, and snappy compressor all live in this tree), an
     encoding-level assertion no value comparison can substitute for;
  2. pyarrow (Arrow C++) reads every golden value-exact vs the generating
     data — the independent-implementation read;
  3. pyarrow REWRITES the table and this repo re-reads it value-exact with
     both the host and the device reader — the foreign-writer read.
"""

import io
import os

import numpy as np
import pyarrow.parquet as pq
import pytest

from compatibility.make_goldens import (
    CODECS, cell_name, golden_rows, write_cell,
)
from tpu_parquet.reader import FileReader

GOLDEN_DIR = os.path.join(os.path.dirname(__file__), "golden")
CELLS = [(c, v, crc) for c in CODECS for v in (1, 2) for crc in (0, 1)]
IDS = [cell_name(c, v, bool(crc)).replace(".parquet", "")
       for c, v, crc in CELLS]


def _rows_to_columns(rows):
    return {
        "id": [r["id"] for r in rows],
        "x": [r["x"] for r in rows],
        "score": [r["score"] for r in rows],
        "flag": [r["flag"] for r in rows],
        "name": [None if r["name"] is None else r["name"].decode()
                 for r in rows],
        "tags": [r["tags"] for r in rows],
    }


@pytest.mark.parametrize("codec,version,crc", CELLS, ids=IDS)
def test_golden_cell(codec, version, crc, tmp_path):
    from conftest import require_codec

    require_codec(CODECS[codec])
    crc = bool(crc)
    golden = os.path.join(GOLDEN_DIR, cell_name(codec, version, crc))
    assert os.path.exists(golden), "golden file missing — run make_goldens.py"

    from tpu_parquet import native

    # 1. byte-stability for the fully-in-repo codecs.  The snappy cells were
    # generated with the native compressor; the pure-Python fallback emits
    # different (literal-only) bytes, so they only byte-compare when the
    # native library is present (uncompressed always compares).
    if codec == "uncompressed" or (codec == "snappy" and native.available()):
        regen = str(tmp_path / "regen.parquet")
        write_cell(regen, codec, version, crc)
        with open(golden, "rb") as a, open(regen, "rb") as b:
            assert a.read() == b.read(), (
                f"{cell_name(codec, version, crc)} bytes drifted from the "
                "checked-in golden — if the format change is deliberate, "
                "regenerate via compatibility/make_goldens.py"
            )

    # 1b. the CRC dimension must assert something: read the golden back with
    # page-checksum validation ON (the _crc cells carry CRCs; the others
    # must also pass — absent CRCs are legal and skipped)
    with FileReader(golden, validate_crc=True) as r:
        assert sum(1 for _ in r.iter_row_groups()) >= 1

    # 2. pyarrow reads the golden value-exact
    want = _rows_to_columns(golden_rows())
    got = pq.read_table(golden)
    for col, vals in want.items():
        assert got[col].to_pylist() == vals, f"pyarrow mismatch in {col}"

    # 3. this repo re-reads pyarrow's rewrite (host + device readers)
    rewrite = str(tmp_path / "rewrite.parquet")
    pq.write_table(got, rewrite, compression={
        "uncompressed": "NONE", "snappy": "SNAPPY",
        "gzip": "GZIP", "zstd": "ZSTD"}[codec],
        data_page_version={1: "1.0", 2: "2.0"}[version])
    ids, got_names = [], []
    with FileReader(rewrite) as r:
        for rg in r.iter_row_groups():
            ids.extend(np.asarray(rg["id"].values).tolist())
            names = rg["name"]
            it = iter(names.values.to_list())
            for d in names.def_levels:
                got_names.append(
                    next(it).decode() if d == names.max_def else None)
    assert ids == want["id"]
    assert got_names == want["name"]

    from tpu_parquet.device_reader import DeviceFileReader

    with DeviceFileReader(rewrite, columns=["id"]) as r:
        dev_ids = np.concatenate(
            [np.asarray(rg["id"].to_host()) for rg in r.iter_row_groups()]
        )
    assert dev_ids.tolist() == want["id"]
