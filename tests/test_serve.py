"""tpu_parquet.serve: the high-QPS concurrent scan service (ISSUE 10).

The contracts under test, in rough order of importance:

- N concurrent clients over ONE ScanService get responses BIT-IDENTICAL to
  sequential one-shot reads, at prefetch {0, 4} — and the shared PlanCache
  counters prove each file's footer was parsed exactly once;
- a full admission queue fast-rejects with a typed OverloadError (never a
  blocked caller);
- a request stalled inside the IO transport fires the per-request watchdog:
  a flight dump whose autopsy NAMES the stuck request, HangError for that
  caller, and untouched service for everyone else;
- the footer read-through cache (ROADMAP item 4's owed piece) keys on file
  generation — local mtime/size, or a ByteStore's identity token + size —
  and a mutated file invalidates cleanly;
- the ScanPlan IR (scanplan.py) serialize/deserialize round-trips, rejects
  lying blobs, and replays (route + pruning memos) bit-identically.
"""

import io
import json
import os
import threading
import time

import numpy as np
import pytest

from tpu_parquet.column import ByteArrayData, ColumnData
from tpu_parquet.errors import HangError, OverloadError, ParquetError
from tpu_parquet.format import CompressionCodec, FieldRepetitionType as FRT, Type
from tpu_parquet.iostore import FaultInjectingStore, FaultSpec, LocalStore
from tpu_parquet.reader import FileReader
from tpu_parquet.scanplan import (ScanPlan, build_scan_plan,
                                  predicate_fingerprint)
from tpu_parquet.schema.core import build_schema, data_column
from tpu_parquet.serve import PlanCache, ScanRequest, ScanService
from tpu_parquet.writer import FileWriter


def _strings(vals):
    return ColumnData(values=ByteArrayData(
        offsets=np.cumsum([0] + [len(v) for v in vals]),
        heap=np.frombuffer(b"".join(vals), np.uint8).copy(),
    ))


def _write_file(path, seed=0, groups=2, rows=600):
    rng = np.random.default_rng(seed)
    schema = build_schema([
        data_column("a", Type.INT64, FRT.REQUIRED),
        data_column("s", Type.BYTE_ARRAY, FRT.REQUIRED),
    ])
    pool = [b"alpha", b"beta", b"gamma", b"delta", b"" ]
    with open(path, "wb") as fh:
        with FileWriter(fh, schema, codec=CompressionCodec.SNAPPY) as w:
            for _g in range(groups):
                svals = [pool[i] for i in rng.integers(0, len(pool), rows)]
                w.write_columns({
                    "a": rng.integers(-(1 << 40), 1 << 40, rows),
                    "s": _strings(svals),
                })
                w.flush_row_group()  # one row group per batch
    return path


@pytest.fixture(scope="module")
def files(tmp_path_factory):
    d = tmp_path_factory.mktemp("serve")
    return [_write_file(str(d / f"f{i}.parquet"), seed=i) for i in range(3)]


def _assert_cols_equal(got, want):
    assert set(got) == set(want)
    for name in want:
        g, w = got[name], want[name]
        assert g.num_leaf_slots == w.num_leaf_slots
        if isinstance(w.values, ByteArrayData):
            np.testing.assert_array_equal(g.values.offsets, w.values.offsets)
            np.testing.assert_array_equal(g.values.heap, w.values.heap)
        else:
            np.testing.assert_array_equal(g.values, w.values)
        for attr in ("def_levels", "rep_levels"):
            gv, wv = getattr(g, attr), getattr(w, attr)
            assert (gv is None) == (wv is None)
            if wv is not None:
                np.testing.assert_array_equal(gv, wv)


# ---------------------------------------------------------------------------
# the acceptance hammer: 16 concurrent clients, bit-identical, parsed once
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("prefetch", [0, 4])
def test_concurrent_hammer_bit_identical_and_parsed_once(files, prefetch):
    projections = [None, ["a"], ["s"], ["a", "s"]]
    # the one-shot ground truth: fresh reader per (file, projection)
    expect = {}
    for path in files:
        for cols in projections:
            with FileReader(path, columns=cols) as r:
                expect[(path, tuple(cols or ()))] = r.read_all()

    svc = ScanService(concurrency=4, queue_depth=256)
    results = {}
    errors = []

    def client(ci):
        try:
            for qi in range(4):
                path = files[(ci + qi) % len(files)]
                cols = projections[(ci * 3 + qi) % len(projections)]
                res = svc.scan(ScanRequest(path, columns=cols,
                                           prefetch=prefetch), timeout=120)
                results[(ci, qi)] = ((path, tuple(cols or ())), res[path])
        except BaseException as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=client, args=(ci,))
               for ci in range(16)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    try:
        assert not errors, errors[:2]
        assert len(results) == 64
        for (key, got) in results.values():
            _assert_cols_equal(got, expect[key])
        c = svc.cache.counters()
        # footers parsed exactly ONCE per file across all 64 requests
        assert c["footer_misses"] == len(files)
        assert c["footer_hits"] == 64 - len(files)
        # plans built once per (file, projection); dictionaries decoded
        # once per (file, row group, dict column)
        assert c["plan_misses"] == len(files) * len(projections)
        assert c["plan_hits"] > 0
        assert c["dict_hits"] > 0
        st = svc.serve_stats()
        assert st["completed"] == 64 and st["failed"] == 0
    finally:
        svc.close()


def test_device_request_matches_host(files):
    with ScanService(concurrency=2) as svc:
        host = svc.scan(ScanRequest(files[0]))[files[0]]
        dev = svc.scan(ScanRequest(files[0], device=True))[files[0]]
    a = dev["a"]
    parts = a if isinstance(a, list) else [a]
    got = np.concatenate([np.asarray(p.to_host()) for p in parts])
    np.testing.assert_array_equal(got, host["a"].values)


def test_row_filter_request(files):
    from tpu_parquet.predicate import parse_filter

    with FileReader(files[0], row_filter=parse_filter("a > 0")) as r:
        want = r.read_all()
    with ScanService(concurrency=2) as svc:
        got = svc.scan(ScanRequest(files[0], filter="a > 0"))[files[0]]
        got2 = svc.scan(ScanRequest(files[0], filter="a > 0"))[files[0]]
    _assert_cols_equal(got, want)
    _assert_cols_equal(got2, want)


def test_admission_budget_backpressure(files):
    # a budget far below one request's estimate: requests serialize through
    # the shared InFlightBudget (charged at the cap) but ALL complete
    with ScanService(concurrency=4, max_memory=1 << 16) as svc:
        tickets = [svc.submit(ScanRequest(files[i % len(files)]))
                   for i in range(8)]
        for t in tickets:
            t.result(timeout=120)
        assert svc.serve_stats()["completed"] == 8


# ---------------------------------------------------------------------------
# overload fast-reject
# ---------------------------------------------------------------------------

def test_overload_fast_reject(files):
    stores = []

    def factory(f):
        st = FaultInjectingStore(
            LocalStore(f), FaultSpec(stall_first=1, stall_s=30.0))
        stores.append(st)
        return st

    svc = ScanService(concurrency=1, queue_depth=1, store=factory)
    try:
        t1 = svc.submit(ScanRequest(files[0]))   # occupies the one worker
        time.sleep(0.15)                         # let it enter the stall
        t2 = svc.submit(ScanRequest(files[1]))   # fills the queue
        t0 = time.perf_counter()
        with pytest.raises(OverloadError) as ei:
            svc.submit(ScanRequest(files[2]))
        assert time.perf_counter() - t0 < 1.0    # fast-reject, not a wait
        assert ei.value.queue_depth == 1
        assert svc.serve_stats()["rejected"] == 1
        # release ALL stalls, including stores created after this point
        # (t2's reader opens its own store once t1's worker frees up)
        stop = threading.Event()

        def releaser():
            while not stop.is_set():
                for st in list(stores):
                    st.release()
                time.sleep(0.02)

        rel = threading.Thread(target=releaser)
        rel.start()
        try:
            t1.result(timeout=120)
            t2.result(timeout=120)
        finally:
            stop.set()
            rel.join()
    finally:
        for st in stores:
            st.release()
        svc.close()


def test_close_fails_queued_requests(files):
    stores = []

    def factory(f):
        st = FaultInjectingStore(
            LocalStore(f), FaultSpec(stall_first=1, stall_s=5.0))
        stores.append(st)
        return st

    svc = ScanService(concurrency=1, queue_depth=4, store=factory)
    svc.submit(ScanRequest(files[0]))
    time.sleep(0.1)
    queued = svc.submit(ScanRequest(files[1]))
    stop = threading.Event()

    def releaser():
        while not stop.is_set():
            for st in list(stores):
                st.release()
            time.sleep(0.02)

    rel = threading.Thread(target=releaser)
    rel.start()
    try:
        svc.close()
        # close() fails queued-but-unstarted requests instead of hanging
        # them (a request the worker picked up before the drain completes
        # normally instead — both are legal outcomes)
        try:
            queued.result(timeout=30)
        except OverloadError:
            pass  # drained at close: the documented outcome
    finally:
        stop.set()
        rel.join()
    # a post-close submit is an error, not a silent enqueue
    with pytest.raises(RuntimeError):
        svc.submit(ScanRequest(files[0]))


# ---------------------------------------------------------------------------
# stalled request: watchdog fires, autopsy names it, others unaffected
# ---------------------------------------------------------------------------

def test_stalled_request_watchdog_autopsy(files, tmp_path, monkeypatch):
    dump_path = str(tmp_path / "serve_hang.json")
    monkeypatch.setenv("TPQ_FLIGHT", dump_path)
    stall_target = files[0]
    stores = []

    def factory(f):
        if getattr(f, "name", "") == stall_target:
            st = FaultInjectingStore(
                LocalStore(f), FaultSpec(stall_first=64, stall_s=60.0))
            stores.append(st)
            return st
        return LocalStore(f)

    svc = ScanService(concurrency=3, queue_depth=32, store=factory,
                      hang_s=1.0)
    try:
        stuck = svc.submit(ScanRequest(stall_target))
        healthy = [svc.submit(ScanRequest(files[1 + (i % 2)]))
                   for i in range(6)]
        # the other clients are never wedged by the stalled one
        for t in healthy:
            t.result(timeout=120)
        with pytest.raises(HangError) as ei:
            stuck.result(timeout=120)
        assert ei.value.dump_path and os.path.exists(ei.value.dump_path)
        with open(ei.value.dump_path) as f:
            doc = json.load(f)
        from tpu_parquet.obs import autopsy_dump

        rep = autopsy_dump(doc)
        # the dump's serve sample names the stuck request and its file
        sv = rep.get("serve")
        assert sv is not None and sv["stuck_request"] is not None
        assert sv["stuck_request"]["path"] == str(stall_target)
        assert rep["verdict"] == "network-stall"
        # ... and the CLI prints it
        buf = io.StringIO()
        from tpu_parquet.cli import pq_tool as _pt

        rc = _pt.cmd_autopsy(
            type("A", (), {"file": ei.value.dump_path})(), out=buf)
        assert rc == 0
        assert "stuck request" in buf.getvalue()
        # the service keeps serving after the hang
        after = svc.scan(ScanRequest(files[1]), timeout=120)
        assert after[files[1]]["a"].num_leaf_slots > 0
    finally:
        for st in stores:
            st.release()
        svc.close()


# ---------------------------------------------------------------------------
# footer read-through cache + invalidation (ROADMAP item 4 owed piece)
# ---------------------------------------------------------------------------

def test_footer_cache_local_mutation_invalidates(tmp_path):
    path = _write_file(str(tmp_path / "mut.parquet"), seed=1, groups=1,
                       rows=100)
    cache = PlanCache()
    meta1, _ = cache.footer(path)
    meta1b, _ = cache.footer(path)
    c = cache.counters()
    assert c["footer_misses"] == 1 and c["footer_hits"] == 1
    assert meta1 is meta1b
    # mutate the file between opens: more rows, and a forced mtime bump so
    # the generation moves even on coarse-mtime filesystems
    _write_file(path, seed=2, groups=1, rows=150)
    st = os.stat(path)
    os.utime(path, ns=(st.st_atime_ns, st.st_mtime_ns + 1_000_000))
    meta2, _ = cache.footer(path)
    assert meta2.num_rows == 150 and meta1.num_rows == 100
    c = cache.counters()
    assert c["footer_misses"] == 2
    assert c["invalidations"] >= 1  # the stale generation was dropped


def test_footer_cache_store_identity_token(files):
    data = open(files[0], "rb").read()

    class _MemStore(FaultInjectingStore):
        def __init__(self, blob, token):
            super().__init__(LocalStore(io.BytesIO(blob)),
                             identity_token=token)

    cache = PlanCache()
    s1 = _MemStore(data, "obj://bucket/f0@etag1")
    meta1, _ = cache.footer(None, store=s1)
    # a RE-OPENED store (new object, same token + size) hits the cache:
    # the footer is parsed once per object generation, not per open
    s2 = _MemStore(data, "obj://bucket/f0@etag1")
    meta2, _ = cache.footer(None, store=s2)
    c = cache.counters()
    assert meta2 is meta1
    assert c["footer_misses"] == 1 and c["footer_hits"] == 1
    # a changed object (new etag) invalidates cleanly
    s3 = _MemStore(data, "obj://bucket/f0@etag2")
    meta3, _ = cache.footer(None, store=s3)
    assert meta3 is not meta1
    assert cache.counters()["footer_misses"] == 2
    # no identity token: never cached, never stale
    s4 = _MemStore(data, None)
    cache.footer(None, store=s4)
    cache.footer(None, store=s4)
    assert cache.counters()["footer_hits"] == 1  # unchanged


def test_plan_cache_lru_eviction(files):
    cache = PlanCache(max_bytes=1)  # everything evicts immediately
    cache.footer(files[0])
    cache.footer(files[1])
    c = cache.counters()
    assert c["evictions"] >= 1
    assert c["entries"] <= 1  # the LRU bound held


def test_scan_files_plan_cache(files):
    from tpu_parquet.device_reader import scan_files

    def collect(**kw):
        out = []
        for cols in scan_files(files, columns=["a"], **kw):
            out.append(np.asarray(cols["a"].to_host()))
        return np.concatenate(out)

    base = collect()
    cache = PlanCache()
    first = collect(plan_cache=cache)
    second = collect(plan_cache=cache)
    np.testing.assert_array_equal(base, first)
    np.testing.assert_array_equal(base, second)
    c = cache.counters()
    assert c["footer_misses"] == len(files)
    assert c["footer_hits"] >= len(files)  # the second sweep re-parsed nothing


# ---------------------------------------------------------------------------
# ScanPlan IR: round-trip, rejection, replay
# ---------------------------------------------------------------------------

def test_scanplan_roundtrip_and_cache_key(files):
    with FileReader(files[0]) as r:
        plan = r._plan
        assert plan is not None
        blob = plan.serialize()
    p2 = ScanPlan.deserialize(blob)
    assert p2.cache_key() == plan.cache_key()
    assert p2.serialize() == blob
    assert [rg.ordinal for rg in p2.row_groups] == [0, 1]
    assert p2.estimated_bytes() == plan.estimated_bytes() > 0


def test_scanplan_rejects_lying_blobs():
    from tpu_parquet.fuzz import crafted_scan_plan_blobs

    blobs = crafted_scan_plan_blobs()
    ScanPlan.deserialize(blobs[0])  # the good one adopts
    for bad in blobs[1:]:
        with pytest.raises(ParquetError):
            ScanPlan.deserialize(bad)


def test_scanplan_route_memo_replay_bit_identical(files):
    from tpu_parquet.device_reader import DeviceFileReader

    with DeviceFileReader(files[0]) as r1:
        base = [{k: np.asarray(v.to_host() if hasattr(v, "to_host") else v)
                 for k, v in g.items()} for g in r1.iter_row_groups()]
        plan = r1._plan
    routes = plan.routes_table()
    assert routes, "first scan must memoize its route choices"
    replay = ScanPlan.deserialize(plan.serialize())
    assert replay.routes_table() == routes
    with DeviceFileReader(files[0], plan=replay) as r2:
        assert r2._plan is replay
        again = [{k: np.asarray(v.to_host() if hasattr(v, "to_host") else v)
                  for k, v in g.items()} for g in r2.iter_row_groups()]
    assert len(base) == len(again)
    for g1, g2 in zip(base, again):
        for k in g1:
            np.testing.assert_array_equal(g1[k], g2[k])


def test_scanplan_mismatched_plan_falls_back(files):
    # a plan built for a different projection must NOT be adopted
    with FileReader(files[0], columns=["a"]) as r:
        narrow_plan = r._plan
    with FileReader(files[0], columns=["a", "s"], plan=narrow_plan) as r2:
        assert r2._plan is not narrow_plan  # rebuilt, not wrongly replayed
        out = r2.read_all()
        assert set(out) == {"a", "s"}


def test_predicate_fingerprint_stability():
    from tpu_parquet.predicate import col

    a = (col("a") > 5) & (col("s") == "x")
    b = (col("a") > 5) & (col("s") == "x")
    assert predicate_fingerprint(a) == predicate_fingerprint(b)
    assert predicate_fingerprint(a) != predicate_fingerprint(col("a") > 6)
    assert predicate_fingerprint(None) is None


def test_device_reader_pruning_memo(files, tmp_path):
    # sorted data so page pruning has stats to work with
    path = str(tmp_path / "sorted.parquet")
    schema = build_schema([data_column("a", Type.INT64, FRT.REQUIRED)])
    with open(path, "wb") as fh:
        with FileWriter(fh, schema, codec=CompressionCodec.SNAPPY) as w:
            w.write_columns({"a": np.arange(4000)})
            w.flush_row_group()
            w.write_columns({"a": np.arange(4000, 8000)})
    from tpu_parquet.device_reader import DeviceFileReader
    from tpu_parquet.predicate import col

    pred = col("a") >= 6000

    def scan(plan=None):
        with DeviceFileReader(path, row_filter=pred, plan=plan) as r:
            out = [np.asarray(g["a"].to_host())
                   for g in r.iter_row_groups()]
            return out, r._plan
    base, plan = scan()
    assert plan.pruning_hint(1) is not None  # memoized on the first scan
    again, _ = scan(plan=ScanPlan.deserialize(plan.serialize()))
    assert len(base) == len(again)
    for x, y in zip(base, again):
        np.testing.assert_array_equal(x, y)


# ---------------------------------------------------------------------------
# obs wiring: registry section, doctor verdict, serve-stats CLI
# ---------------------------------------------------------------------------

def test_registry_serve_section_and_merge(files):
    with ScanService(concurrency=2) as svc:
        svc.scan(ScanRequest(files[0]))
        reg = svc.obs_registry()
    tree = reg.as_dict()
    sv = tree["serve"]
    assert sv["submitted"] == 1 and sv["completed"] == 1
    assert "cache" in sv and sv["cache"]["footer_misses"] == 1
    assert {"serve.queue_wait", "serve.exec", "serve.request"} <= set(
        tree["histograms"])
    # cross-process style merge: flows add, gauges max
    from tpu_parquet.obs import StatsRegistry

    other = StatsRegistry()
    other.merge_dict(tree)
    other.merge_dict(tree)
    t2 = other.as_dict()
    assert t2["serve"]["submitted"] == 2
    assert t2["serve"]["queue_depth_peak"] == sv["queue_depth_peak"]
    assert (t2["serve"]["cache"]["capacity_bytes"]
            == sv["cache"]["capacity_bytes"])


def test_doctor_admission_bound():
    from tpu_parquet.obs import doctor_registry

    tree = {
        "pipeline": {"stage_seconds": 0.2, "io_seconds": 0.1,
                     "stall_seconds": 0.0},
        "reader": {},
        "serve": {"queue_wait_seconds": 5.0, "exec_seconds": 0.5},
    }
    rep = doctor_registry(tree)
    assert rep["verdict"] == "admission-bound"
    assert rep["dominant_lane"] == "admission"
    # without the serve section the old verdicts are untouched
    rep2 = doctor_registry({"pipeline": {"stage_seconds": 0.2},
                            "reader": {}})
    assert rep2["verdict"] == "link-bound"


def test_serve_stats_cli(files, tmp_path):
    with ScanService(concurrency=2) as svc:
        for _ in range(3):
            svc.scan(ScanRequest(files[0]))
        tree = svc.obs_registry().as_dict()
    path = str(tmp_path / "reg.json")
    with open(path, "w") as f:
        json.dump(tree, f)
    from tpu_parquet.cli import pq_tool

    buf = io.StringIO()
    rc = pq_tool.cmd_serve_stats(
        type("A", (), {"file": path, "config": None})(), out=buf)
    out = buf.getvalue()
    assert rc == 0
    assert "3 submitted" in out and "cache hits" in out and "p95" in out
    # a registry with no serve section is a one-line diagnosis, not a crash
    with open(path, "w") as f:
        json.dump({"obs_version": 1}, f)
    buf2 = io.StringIO()
    rc2 = pq_tool.cmd_serve_stats(
        type("A", (), {"file": path, "config": None})(), out=buf2)
    assert rc2 == 1 and "no `serve` section" in buf2.getvalue()


def test_overload_error_is_not_parquet_error():
    # load shedding must never look like malformed input to the fuzz
    # oracle or to quarantine containment
    assert not issubclass(OverloadError, ParquetError)
    assert not issubclass(OverloadError, IOError)
    e = OverloadError("full", queue_depth=4, in_flight=2)
    assert e.queue_depth == 4 and e.in_flight == 2


def test_service_thread_hygiene(files):
    before = {t.name for t in threading.enumerate()
              if t.name.startswith(("tpq-serve", "tpq-watchdog"))}
    svc = ScanService(concurrency=3, hang_s=60.0)
    svc.scan(ScanRequest(files[0]))
    svc.close()
    time.sleep(0.05)
    after = {t.name for t in threading.enumerate()
             if t.name.startswith(("tpq-serve", "tpq-watchdog"))}
    assert after <= before  # close() leaks no workers or watchdogs


# ---------------------------------------------------------------------------
# multi-tenant QoS (ISSUE 17): fair-share scheduling, tenant accounting
# ---------------------------------------------------------------------------

def test_fair_scheduler_drr_and_fifo():
    from tpu_parquet.serve import FairScheduler

    q = FairScheduler(64, fair=True)
    for i in range(3):
        q.put_nowait("noisy", 1, f"n{i}")
    for i in range(3):
        q.put_nowait("victim", 3, f"v{i}")
    order = [q.get() for _ in range(6)]
    # weight 3 buys the victim a 3-long run per noisy dequeue once its
    # queue is live — the flood cannot fence it out
    assert order.index("v0") <= 1 and order.index("v2") <= 4, order
    fifo = FairScheduler(64, fair=False)
    fifo.put_nowait("noisy", 1, "n0")
    fifo.put_nowait("victim", 3, "v0")
    fifo.put_nowait("noisy", 1, "n1")
    assert [fifo.get() for _ in range(3)] == ["n0", "v0", "n1"]


def test_fair_share_protects_victim_p99(files):
    # one worker + deterministic per-range latency + result cache OFF:
    # the queueing discipline is the only variable.  Noisy requests are
    # CHEAPER than the victim's (one column vs two), so under fair-share
    # the victim pays at most one residual noisy request — within 2x its
    # isolated p99 — while FIFO parks it behind the whole flood.
    from tpu_parquet.iostore import IOConfig

    lat, noisy_n, path = 0.012, 12, files[0]

    def mk(fair):
        svc = ScanService(
            concurrency=1, queue_depth=64, fair=fair, result_cache_mb=0,
            store=lambda f: FaultInjectingStore(
                LocalStore(f), FaultSpec(latency_s=lat),
                config=IOConfig(backoff_ms=1.0)))
        svc.register_tenant("victim", weight=4)
        svc.register_tenant("noisy", weight=1)
        # warm the footer/plan caches so the timed phase is pure data IO
        svc.scan(ScanRequest(path, tenant="victim"), timeout=60)
        return svc

    def victim_p99(svc, flood):
        tickets = [svc.submit(ScanRequest(path, columns=["a"],
                                          tenant="noisy"))
                   for _ in range(noisy_n if flood else 0)]
        walls = []
        for _ in range(4):
            t0 = time.perf_counter()
            svc.scan(ScanRequest(path, tenant="victim"), timeout=60)
            walls.append(time.perf_counter() - t0)
        for t in tickets:
            t.result(60)
        return max(walls)

    # the fair bound sits AT the theoretical residual (victim pays one
    # in-flight noisy request), so in-suite scheduler jitter can tip a
    # single measurement over it — re-measure the whole trio a few times
    # and accept any clean attempt (weather, not discipline, is what a
    # lone miss on this 2-core box measures)
    for attempt in range(3):
        svc = mk(True)
        iso = victim_p99(svc, flood=False)
        svc.close()
        svc = mk(True)
        fair = victim_p99(svc, flood=True)
        tstats = svc.serve_stats()["tenants"]
        svc.close()
        svc = mk(False)
        fifo = victim_p99(svc, flood=True)
        svc.close()
        if fair <= 2.0 * iso < fifo:
            break
    # the acceptance bar: fair-share holds the victim within 2x isolated;
    # FIFO demonstrably does not (same flood, same worker, same costs)
    assert fair <= 2.0 * iso, (iso, fair, fifo)
    assert fifo > 2.0 * iso, (iso, fair, fifo)
    # both tenants really ran, and the registry kept their books apart
    assert tstats["victim"]["submitted"] == 5
    assert tstats["noisy"]["submitted"] == noisy_n


def test_tenant_budget_slices_and_shed_accounting(files):
    from tpu_parquet.errors import CheckpointError  # noqa: F401 (import rail)

    svc = ScanService(concurrency=1, queue_depth=1, max_memory=1 << 20)
    try:
        svc.register_tenant("gold", weight=3)
        svc.register_tenant("bronze", weight=1)
        # budget slices follow weights: gold holds 3/5 of max_memory
        # (default tenant keeps its weight-1 share)
        slices = {n: t.budget.max_bytes
                  for n, t in svc.tenants.tenants().items()}
        assert slices["gold"] == 3 * slices["bronze"]
        # overflow rejections land on the SUBMITTING tenant's book, and
        # the typed error names it with a backoff hint
        plug = svc.submit(ScanRequest(files[0], tenant="gold"))
        shed = None
        for _ in range(12):
            try:
                svc.submit(ScanRequest(files[0], tenant="bronze"))
            except OverloadError as e:
                shed = e
                break
        plug.result(60)
        assert shed is not None and "bronze" in str(shed)
        assert shed.retry_after_s > 0
        st = svc.serve_stats()
        assert st["tenants"]["bronze"]["rejected"] >= 1
        assert st["tenants"]["gold"]["rejected"] == 0
        assert st["retry_after_hint_s"] > 0
    finally:
        svc.close()


def test_registry_tenants_subtree_and_merge(files):
    with ScanService(concurrency=1) as svc:
        svc.register_tenant("team-a", weight=2, slo_p99_ms=50.0)
        svc.scan(ScanRequest(files[0], tenant="team-a"))
        svc.scan(ScanRequest(files[0]))
        tree = svc.obs_registry().as_dict()
    sv = tree["serve"]
    ta = sv["tenants"]["team-a"]
    assert ta["submitted"] == ta["completed"] == 1
    assert ta["weight"] == 2 and ta["slo_p99_ms"] == 50.0
    assert {"rejected", "sheds", "cache_held_bytes", "budget_bytes",
            "rows"} <= set(ta)
    assert sv["tenants"]["default"]["submitted"] == 1
    assert "serve.tenant.team-a" in tree["histograms"]
    # merge discipline: lifecycle flows add, config/state gauges max
    from tpu_parquet.obs import StatsRegistry

    other = StatsRegistry()
    other.merge_dict(tree)
    other.merge_dict(tree)
    t2 = other.as_dict()["serve"]["tenants"]["team-a"]
    assert t2["submitted"] == 2 and t2["weight"] == 2


def test_doctor_overload_names_offending_tenant():
    from tpu_parquet.obs import OVERLOAD_MIN_REJECTS, doctor_registry

    tree = {
        "pipeline": {"io_seconds": 1.0}, "reader": {},
        "serve": {
            "queue_wait_seconds": 0.2, "rejected": 5,
            "sheds": {"low": 2, "normal": 0}, "retry_after_hint_s": 0.4,
            "tenants": {
                "noisy": {"submitted": 50, "rejected": 1},
                "victim": {"submitted": 2, "rejected": 4},
            },
        },
    }
    rep = doctor_registry(tree)
    ov = rep["overload"]
    assert ov["verdict"] == "overload"
    assert ov["offending_tenant"] == "noisy"  # demand, not reject count
    assert ov["victims"] == ["victim"]
    assert "noisy" in ov["advice"] and ov["retry_after_hint_s"] == 0.4
    # below the threshold the verdict stays silent (routine backpressure)
    tree["serve"]["rejected"] = OVERLOAD_MIN_REJECTS - 1 - 2  # sheds=2 ride
    assert "overload" not in doctor_registry(tree)


def test_serve_stats_cli_tenants(files, tmp_path):
    with ScanService(concurrency=1) as svc:
        svc.register_tenant("team-a", weight=2, slo_p99_ms=75.0)
        svc.scan(ScanRequest(files[0], tenant="team-a"))
        svc.scan(ScanRequest(files[0], stream=True, batch_rows=256))
        tree = svc.obs_registry().as_dict()
    path = str(tmp_path / "reg.json")
    with open(path, "w") as f:
        json.dump(tree, f)
    from tpu_parquet.cli import pq_tool

    buf = io.StringIO()
    rc = pq_tool.cmd_serve_stats(
        type("A", (), {"file": path, "config": None})(), out=buf)
    out = buf.getvalue()
    assert rc == 0
    assert "tenants:" in out and "team-a" in out and "slo 75" in out
    assert "streaming: 1 session(s)" in out
    assert "tenant.team-a" in out  # the per-tenant SLO histogram row


def test_tenant_env_spec(monkeypatch):
    from tpu_parquet.serve import parse_tenant_spec
    from tpu_parquet.serve.tenancy import TenantRegistry, fair_enabled

    # lenient by contract: a bare name defaults to weight 1, a malformed
    # weight clamps to 1 — a bad env var must not take the serve tier down
    assert parse_tenant_spec("a=3, b=1,junk,c=x,=9,") == {
        "a": (3, None), "b": (1, None), "junk": (1, None), "c": (1, None)}
    # optional :deadline_s rides the weight field; malformed or
    # non-positive deadlines degrade to None, never raise
    assert parse_tenant_spec("gold=4:2.5,slow=1:x,neg=2:-1") == {
        "gold": (4, 2.5), "slow": (1, None), "neg": (2, None)}
    monkeypatch.setenv("TPQ_SERVE_TENANTS", "gold=4,bronze=1")
    reg = TenantRegistry(max_memory=6 << 20)
    assert reg.get("gold").weight == 4
    assert reg.get("gold").budget.max_bytes == 4 * (1 << 20)
    monkeypatch.setenv("TPQ_SERVE_FAIR", "0")
    assert not fair_enabled(None)
    assert fair_enabled(True)  # the explicit flag outranks the env


def test_tenants_kwarg_coercion(files):
    # the natural call shapes all land in a real registry: a {name:
    # weight} mapping, a spec string, or a TenantRegistry — and anything
    # else is a TypeError at CONSTRUCTION, not an AttributeError deep in
    # submit()
    with ScanService(concurrency=1,
                     tenants={"gold": 3, "bronze": 1}) as svc:
        svc.scan(ScanRequest(files[0], tenant="gold"))
        svc.scan(ScanRequest(files[0]))  # tenant-less rides "default"
        tens = svc.obs_registry().as_dict()["serve"]["tenants"]
    assert tens["gold"]["weight"] == 3 and tens["gold"]["submitted"] == 1
    assert tens["default"]["submitted"] == 1
    with ScanService(concurrency=1, tenants="gold=3,bronze=1") as svc:
        assert svc.tenants.get("gold").weight == 3
    with pytest.raises(TypeError, match="tenants="):
        ScanService(concurrency=1, tenants=42)


def test_doctor_overload_on_serve_only_registry():
    # an overload where NOTHING got far enough to decode is exactly when
    # the operator reaches for doctor: no lane seconds must not mean no
    # verdict (the early None return lets overload evidence through)
    from tpu_parquet.obs import doctor_registry

    tree = {"serve": {"rejected": 6, "sheds": {"low": 0, "normal": 0},
                      "tenants": {"hog": {"submitted": 25, "rejected": 0},
                                  "v": {"submitted": 1, "rejected": 6}}}}
    rep = doctor_registry(tree)
    assert rep is not None and "lanes" not in rep
    assert rep["overload"]["offending_tenant"] == "hog"
    # a quiet serve-only tree still returns None (nothing to say)
    assert doctor_registry({"serve": {"rejected": 1}}) is None
