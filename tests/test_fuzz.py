"""Fuzz crasher corpus replay + deterministic smoke fuzzing.

The reference replays every go-fuzz crasher as a plain test
(fuzz_test.go:11-28, deltabp_decoder_test.go:152, alloc_test.go:15); here the
checked-in ``tests/fuzz_corpus/<target>-<sha>`` files — minimized crashers
found by ``python -m tpu_parquet.fuzz`` plus crafted hostile inputs — run
through their target on every test run, and a short deterministic mutation
batch per target keeps the harness itself exercised in CI.

The contract (tpu_parquet/fuzz.py): any input may raise ParquetError or
return; anything else is a bug.  Corpus findings fixed this round:
- a dictionary page with an absent encoding field crashed with a bare
  ValueError from the Encoding enum (chunk_decode._decode_dict_page);
- schema elements with invalid type/repetition enums did the same
  (schema/core.py properties);
- the native byte-array walk under-allocated its heap for streams that run
  out of records midway (heap corruption — native/__init__.py bytearray_walk).
"""

import os

import pytest

from tpu_parquet import fuzz

CORPUS = os.path.join(os.path.dirname(os.path.abspath(__file__)), "fuzz_corpus")


def _corpus_files():
    if not os.path.isdir(CORPUS):
        return []
    return sorted(os.listdir(CORPUS))


@pytest.mark.parametrize("name", _corpus_files())
def test_corpus_replay(name):
    target = name.rsplit("-", 1)[0]
    fn = fuzz.TARGETS[target]
    with open(os.path.join(CORPUS, name), "rb") as f:
        data = f.read()
    fn(data)  # must return or raise ParquetError; anything else fails the test


def test_corpus_is_populated():
    names = _corpus_files()
    assert len(names) >= 12, names
    assert all(n.rsplit("-", 1)[0] in fuzz.TARGETS for n in names)


@pytest.mark.parametrize("target", sorted(fuzz.TARGETS))
def test_smoke_fuzz(target):
    """Deterministic short fuzz batch per target — no crashers allowed."""
    runs = 120 if target == "file_reader" else 400
    crashers = fuzz.run_fuzz(target, runs=runs, seed=1234, save_crashers=False)
    assert not crashers, crashers[0][1]
