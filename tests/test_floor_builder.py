"""Builder-style marshalling round-trips (floor/interfaces/marshaller.go
MarshalObject/List/Map shapes, incl. the Athena bag special case at
marshaller.go:100-109, and unmarshaller.go typed access + ErrFieldNotPresent)."""

import pytest

from tpu_parquet.floor.builder import FieldNotPresent, RowBuilder, RowView
from tpu_parquet.footer import ParquetError
from tpu_parquet.reader import FileReader
from tpu_parquet.schema.dsl import parse_schema_definition
from tpu_parquet.writer import FileWriter

SCHEMA = """message test {
  required int64 id;
  optional binary name (STRING);
  required group who {
    required binary first (STRING);
    optional binary last (STRING);
  }
  optional group tags (LIST) {
    repeated group list {
      required binary element (STRING);
    }
  }
  optional group attrs (MAP) {
    repeated group key_value {
      required binary key (STRING);
      optional int64 value;
    }
  }
}"""

# Athena-style LIST naming (validateListLogicalType lenient shape)
SCHEMA_BAG = """message athena {
  optional group tags (LIST) {
    repeated group bag {
      optional binary array_element (STRING);
    }
  }
}"""


def _build_row(schema, i):
    b = RowBuilder(schema)
    b.field("id").set(i)
    if i % 3:
        b.field("name").set(f"name{i}".encode())
    who = b.field("who").group()
    who.field("first").set(b"Hans")
    if i % 2:
        who.field("last").set(b"Mustermann")
    lst = b.field("tags").list()
    for k in range(i % 4):
        lst.add().set(f"tag{k}".encode())
    m = b.field("attrs").map()
    for k in range(i % 3):
        kel, vel = m.add()
        kel.set(f"k{k}".encode())
        vel.set(i * 10 + k)
    return b.data


def test_builder_roundtrip(tmp_path):
    schema = parse_schema_definition(SCHEMA)
    p = tmp_path / "b.parquet"
    rows = [_build_row(schema.root, i) for i in range(50)]
    with FileWriter(p, schema, codec=1) as w:
        for r in rows:
            w.write_row(r)
    with FileReader(p) as r:
        got = list(r.iter_rows())
    assert len(got) == 50
    for i, row in enumerate(got):
        v = RowView(row, schema.root)
        assert v.field("id").int64() == i
        if i % 3:
            assert v.field("name").bytes() == f"name{i}".encode()
        who = v.field("who").group()
        assert who.field("first").bytes() == b"Hans"
        tags = [e.bytes() for e in v.field("tags").list()]
        assert tags == [f"tag{k}".encode() for k in range(i % 4)]
        attrs = {k.bytes(): val.int64() for k, val in v.field("attrs").map()}
        assert attrs == {f"k{k}".encode(): i * 10 + k for k in range(i % 3)}


def test_builder_athena_bag_shape():
    schema = parse_schema_definition(SCHEMA_BAG)
    b = RowBuilder(schema.root)
    lst = b.field("tags").list()
    lst.add().set(b"x")
    lst.add().set(b"y")
    # the builder must have chosen the bag/array_element naming from the schema
    assert b.data == {"tags": {"bag": [{"array_element": b"x"},
                                       {"array_element": b"y"}]}}
    v = RowView(b.data, schema.root)
    assert [e.bytes() for e in v.field("tags").list()] == [b"x", b"y"]


def test_view_errors():
    schema = parse_schema_definition(SCHEMA)
    v = RowView({"id": 7, "who": {"first": b"a"}}, schema.root)
    with pytest.raises(FieldNotPresent):
        v.field("missing")
    with pytest.raises(ParquetError):
        v.field("id").bytes()  # wrong type
    with pytest.raises(ParquetError):
        v.field("id").group()
    assert v.field("id").int64() == 7
    # FieldNotPresent is a KeyError too (except KeyError idiom works)
    assert issubclass(FieldNotPresent, KeyError)


def test_builder_without_schema_defaults_standard_list():
    b = RowBuilder()
    lst = b.field("tags").list()
    lst.add().set(b"a")
    assert b.data == {"tags": {"list": [{"element": b"a"}]}}
