"""Production Pallas decode path: A/B parity against the XLA extract path.

The batched reader routes uniform-width hybrid streams (dictionary indices,
def/rep levels) through pallas_kernels.unpack_bp_groups when TPQ_PALLAS=1 (or
natively on TPU).  On the CPU test backend the kernel runs through the Pallas
interpreter — slow but bit-exact — so these tests decode every file twice and
require identical output.  Reference semantics: hybrid_decoder.go:81-165.
"""

import numpy as np
import pytest

from tpu_parquet.format import CompressionCodec, FieldRepetitionType as FRT, Type
from tpu_parquet.kernels import bitpack, rle
from tpu_parquet.schema.core import build_schema, data_column
from tpu_parquet.writer import FileWriter


@pytest.mark.parametrize("width", [1, 2, 3, 7, 8, 13, 17, 24, 32])
def test_unpack_bp_groups_matches_host_unpack(width):
    import jax.numpy as jnp

    from tpu_parquet.pallas_kernels import bp_groups_pad, unpack_bp_groups

    rng = np.random.default_rng(width)
    n = 5000
    vals = rng.integers(0, 1 << min(width, 32), n, dtype=np.uint64)
    packed = np.frombuffer(bitpack.pack(vals, width), np.uint8)
    groups = -(-n // 8)
    gpad = bp_groups_pad(groups)
    buf = np.zeros(gpad * width + 64, dtype=np.uint8)
    buf[: packed.nbytes] = packed
    out = unpack_bp_groups(jnp.asarray(buf), 0, width, gpad, interpret=True)
    got = np.asarray(out)[:n].astype(np.uint64)
    np.testing.assert_array_equal(got, vals)


def test_unpack_bp_groups_nonzero_base():
    import jax.numpy as jnp

    from tpu_parquet.pallas_kernels import bp_groups_pad, unpack_bp_groups

    rng = np.random.default_rng(0)
    n, width = 4096, 11
    vals = rng.integers(0, 1 << width, n, dtype=np.uint64)
    packed = np.frombuffer(bitpack.pack(vals, width), np.uint8)
    base = 192  # 64-aligned staging offset
    gpad = bp_groups_pad(-(-n // 8))
    buf = np.zeros(base + gpad * width + 64, dtype=np.uint8)
    buf[base : base + packed.nbytes] = packed
    out = unpack_bp_groups(jnp.asarray(buf), base, width, gpad, interpret=True)
    np.testing.assert_array_equal(np.asarray(out)[:n].astype(np.uint64), vals)


def _mixed_run_values(rng, n, card):
    """Index stream with long repeated spans: forces RLE *and* BP runs."""
    vals = rng.integers(0, card, n, dtype=np.uint32)
    for x in rng.integers(0, max(n - 600, 1), 8):
        vals[x : x + 500] = vals[x]
    return vals


def _decode_both_ways(path, monkeypatch, columns=None):
    from tpu_parquet.device_reader import DeviceFileReader

    outs = {}
    for mode in ("0", "1"):
        monkeypatch.setenv("TPQ_PALLAS", mode)
        cols = {}
        with DeviceFileReader(path, columns=columns) as r:
            for got in r.iter_row_groups():
                for k, v in got.items():
                    cols.setdefault(k, []).append(v)
        outs[mode] = cols
    return outs["0"], outs["1"]


def _assert_cols_equal(a, b):
    assert set(a) == set(b)
    for k in a:
        assert len(a[k]) == len(b[k])
        for ca, cb in zip(a[k], b[k]):
            ha, hb = ca.to_host(), cb.to_host()
            if hasattr(ha, "offsets"):
                np.testing.assert_array_equal(ha.offsets, hb.offsets)
                np.testing.assert_array_equal(ha.heap, hb.heap)
            else:
                np.testing.assert_array_equal(ha, hb)
            da, _ = ca.levels_to_host()
            db, _ = cb.levels_to_host()
            if da is not None or db is not None:
                np.testing.assert_array_equal(da, db)


def test_dict_indices_pallas_parity(tmp_path, monkeypatch):
    """Dictionary column with mixed RLE/BP index runs decodes identically."""
    path = str(tmp_path / "dict.parquet")
    rng = np.random.default_rng(1)
    schema = build_schema([data_column("s", Type.BYTE_ARRAY, FRT.REQUIRED)])
    pool = [f"val_{i:03d}".encode() for i in range(700)]
    idx = _mixed_run_values(rng, 60_000, len(pool))
    from tpu_parquet.column import ByteArrayData, ColumnData

    lens = np.array([len(pool[i]) for i in idx])
    offs = np.zeros(len(idx) + 1, dtype=np.int64)
    np.cumsum(lens, out=offs[1:])
    heap = np.frombuffer(b"".join(pool[i] for i in idx), dtype=np.uint8).copy()
    with FileWriter(path, schema, codec=CompressionCodec.SNAPPY,
                    use_dictionary=True, page_size=16 << 10) as w:
        w.write_columns({"s": ColumnData(values=ByteArrayData(offsets=offs,
                                                              heap=heap))})
    xla, pallas = _decode_both_ways(path, monkeypatch)
    _assert_cols_equal(xla, pallas)


def test_levels_pallas_parity(tmp_path, monkeypatch):
    """Nullable column: def-level streams expand identically on both paths."""
    path = str(tmp_path / "nulls.parquet")
    rng = np.random.default_rng(2)
    schema = build_schema([data_column("v", Type.INT64, FRT.OPTIONAL)])
    n = 50_000
    vals = rng.integers(-1000, 1000, n)
    mask = rng.random(n) < 0.3
    # long all-null and all-present spans: RLE level runs next to BP ones
    mask[1000:3000] = True
    mask[10_000:14_000] = False
    from tpu_parquet.column import ColumnData

    col = ColumnData(
        values=vals[~mask].astype(np.int64),
        def_levels=(~mask).astype(np.uint32),
        max_def=1,
    )
    with FileWriter(path, schema, codec=CompressionCodec.UNCOMPRESSED,
                    page_size=8 << 10) as w:
        w.write_columns({"v": col})
    xla, pallas = _decode_both_ways(path, monkeypatch)
    _assert_cols_equal(xla, pallas)


def test_pallas_default_off_on_cpu(monkeypatch):
    """Without TPQ_PALLAS=1 the CPU backend keeps the XLA path (no
    interpreter in production), and TPQ_PALLAS=0 forces it off everywhere."""
    from tpu_parquet.device_reader import _pallas_interpret_mode

    monkeypatch.delenv("TPQ_PALLAS", raising=False)
    assert _pallas_interpret_mode() is None  # CPU conftest backend
    monkeypatch.setenv("TPQ_PALLAS", "0")
    assert _pallas_interpret_mode() is None
    monkeypatch.setenv("TPQ_PALLAS", "1")
    assert _pallas_interpret_mode() is True


def test_pallas_plan_declines_pathological_runs(tmp_path, monkeypatch):
    """A stream shattered into tiny alternating runs must fall back (and
    still decode correctly) — the segment-copy guard, not an error path."""
    monkeypatch.setenv("TPQ_PALLAS", "1")
    import jax.numpy as jnp

    from tpu_parquet.device_reader import (
        _PALLAS_MAX_SEGS, _RowGroupStager, _plan_hybrid_pallas,
    )
    from tpu_parquet.jax_decode import parse_hybrid_meta

    # alternating 8-value BP runs and RLE runs, enough to trip the guard
    width = 4
    parts = []
    n_pairs = _PALLAS_MAX_SEGS + 8
    for _ in range(n_pairs):
        parts.append(bytes([(1 << 1) | 1]) + bytes(width))  # 1-group BP run
        parts.append(bytes([16 << 1, 5]))  # RLE run: 16 copies of 5
    stream = b"".join(parts)
    count = n_pairs * 24
    meta = parse_hybrid_meta(stream, width, count, pos=0)
    stager = _RowGroupStager()
    plan = _plan_hybrid_pallas(stager, [(meta, stream, count)], width, count,
                               count, True)
    assert plan is None  # guard declined; callers use the XLA path


def test_streaming_stager_multi_strip_parity(tmp_path, monkeypatch):
    """Strip-streamed staging (iter_row_groups worker) assembles the same
    device buffer as the single-transfer path: shrink the strip size so a
    small file crosses many strip boundaries, decode both ways, compare."""
    from tpu_parquet.column import ColumnData
    from tpu_parquet.device_reader import DeviceFileReader, _RowGroupStager

    path = str(tmp_path / "strips.parquet")
    rng = np.random.default_rng(3)
    schema = build_schema([
        data_column("a", Type.INT64, FRT.REQUIRED),
        data_column("b", Type.INT32, FRT.REQUIRED),
    ])
    n = 200_000
    with FileWriter(path, schema, codec=CompressionCodec.SNAPPY) as w:
        w.write_columns({
            "a": ColumnData(values=rng.integers(-(1 << 62), 1 << 62, n)),
            "b": ColumnData(values=rng.integers(0, 1 << 30, n).astype(np.int32)),
        })

    def scan():
        cols = {}
        with DeviceFileReader(path) as r:
            for got in r.iter_row_groups():
                for k, v in got.items():
                    cols.setdefault(k, []).append(v.to_host())
        return cols

    ref = scan()  # strips never trip (file << 16 MiB)
    monkeypatch.setattr(_RowGroupStager, "STRIP", 1 << 16)
    got = scan()  # dozens of strips + tail
    assert set(ref) == set(got)
    for k in ref:
        for a, b in zip(ref[k], got[k]):
            np.testing.assert_array_equal(a, b)
