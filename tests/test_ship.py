"""Ship-planner tests: cost model, forced routes, and route bit-identity.

Every planner choice ({plain, narrow, narrow+snappy, device-snappy,
recompress}) must decode bit-identically to the host reader — the cost model
(tpu_parquet/ship.py) only ROUTES bytes, it never owns correctness — across
prefetch={0,4} (the sequential and overlapped host paths), including the
``TPQ_FORCE_ROUTE`` override that CI uses to pin routes deterministically.
"""

import os

import numpy as np
import pytest

from tpu_parquet import native
from tpu_parquet.column import ByteArrayData, ColumnData
from tpu_parquet.device_reader import DeviceFileReader
from tpu_parquet.format import CompressionCodec, FieldRepetitionType as FRT, Type
from tpu_parquet.reader import FileReader
from tpu_parquet.schema.core import build_schema, data_column
from tpu_parquet.ship import (
    ROUTES, ChunkFacts, ROUTE_DEVICE_SNAPPY, ROUTE_NARROW,
    ROUTE_NARROW_SNAPPY, ROUTE_PLAIN, ROUTE_RECOMPRESS, ShipPlanner,
)
from tpu_parquet.writer import FileWriter

N = 40_000


def _columns():
    rng = np.random.default_rng(17)
    pool = [f"supplier_{i % 400:04d}_{i % 7}".encode() for i in range(400)]
    idx = rng.integers(0, len(pool), N)
    offs = np.zeros(N + 1, dtype=np.int64)
    np.cumsum([len(pool[i]) for i in idx], out=offs[1:])
    heap = np.frombuffer(b"".join(pool[i] for i in idx), np.uint8).copy()
    return {
        # narrow span (k=3), residuals random: narrow engages, compression
        # of the narrow buffer buys little
        "ids": rng.integers(0, 200_000, N),
        # date-like (k=2, sorted-by-date run structure): narrow output is
        # low-entropy — the narrow+snappy composition's home turf
        "dates": np.repeat(19_000 + rng.integers(0, 1200, N // 50),
                           50).astype(np.int64),
        # full 63-bit range: every shrink route must decline
        "wide": rng.integers(-(1 << 62), 1 << 62, N),
        "dbl": np.repeat(rng.uniform(0.0, 1.0, N // 100), 100),
        "s": ColumnData(values=ByteArrayData(offsets=offs, heap=heap)),
    }


def _schema():
    return build_schema([
        data_column("ids", Type.INT64, FRT.REQUIRED),
        data_column("dates", Type.INT64, FRT.REQUIRED),
        data_column("wide", Type.INT64, FRT.REQUIRED),
        data_column("dbl", Type.DOUBLE, FRT.REQUIRED),
        data_column("s", Type.BYTE_ARRAY, FRT.REQUIRED),
    ])


@pytest.fixture(scope="module")
def ship_files(tmp_path_factory):
    root = tmp_path_factory.mktemp("ship")
    cols = _columns()
    paths = {}
    for codec in (CompressionCodec.SNAPPY, CompressionCodec.GZIP,
                  CompressionCodec.UNCOMPRESSED):
        p = str(root / f"ship_{codec.name.lower()}.parquet")
        with FileWriter(p, _schema(), codec=codec,
                        use_dictionary=False) as w:
            for lo in range(0, N, 10_000):  # several pages per chunk
                w.write_columns({
                    k: (v[lo:lo + 10_000] if not isinstance(v, ColumnData)
                        else ColumnData(values=ByteArrayData(
                            offsets=(v.values.offsets[lo:lo + 10_001]
                                     - v.values.offsets[lo]),
                            heap=v.values.heap[
                                v.values.offsets[lo]:v.values.offsets[
                                    min(lo + 10_000, N)]],
                        )))
                    for k, v in cols.items()
                })
        paths[codec.name.lower()] = p
    return paths, cols


def _ragged_rows(ba):
    off = np.asarray(ba.offsets)
    heap = np.asarray(ba.heap)
    return [heap[off[i]:off[i + 1]].tobytes() for i in range(len(off) - 1)]


def _assert_matches_host(path, prefetch):
    host = {}
    with FileReader(path) as r:
        for rg in r.iter_row_groups():
            for k, v in rg.items():
                host.setdefault(k, []).append(v)
    with DeviceFileReader(path, prefetch=prefetch) as r:
        for i, rg in enumerate(r.iter_row_groups()):
            for k, col in rg.items():
                got = col.to_host()
                want = host[k][i].values
                if isinstance(want, ByteArrayData):
                    assert _ragged_rows(got) == _ragged_rows(want), k
                else:
                    g, w = np.asarray(got), np.asarray(want)
                    assert g.dtype == w.dtype, k
                    assert np.array_equal(g.view(np.uint8).reshape(-1),
                                          w.view(np.uint8).reshape(-1)), k
        return r.stats()


# ---------------------------------------------------------------------------
# cost model units
# ---------------------------------------------------------------------------

def test_planner_orderings():
    p = ShipPlanner(link_mbps=350.0, force=None)
    L = 8 << 20
    # snappy file, ratio ~1, no narrow hint: keep the payload (the host
    # decompress it skips is the whole win)
    r = p.routes(ChunkFacts(logical=L, width=8, comp_bytes=int(0.99 * L)))
    assert r[0] == ROUTE_DEVICE_SNAPPY
    # narrow stats hint beats shipping the compressed stream
    r = p.routes(ChunkFacts(logical=L, width=8, narrow_k=3,
                            comp_bytes=L // 2, narrow_possible=True))
    assert r.index(ROUTE_NARROW) < r.index(ROUTE_DEVICE_SNAPPY)
    # byte-array heap in a gzip file: recompression wins over raw shipping
    r = p.routes(ChunkFacts(logical=L, width=0, comp_bytes=0))
    assert r[0] == ROUTE_RECOMPRESS
    # tiny stream: nothing beats just shipping it
    assert p.routes(ChunkFacts(logical=1000, width=0))[0] == ROUTE_PLAIN
    # every cost table includes the plain anchor
    assert ROUTE_PLAIN in p.costs(ChunkFacts(logical=L, width=8))
    assert p.decision_table(ChunkFacts(logical=L, width=8))[ROUTE_PLAIN] > 0


def test_planner_slow_link_prefers_composition():
    """On a congested link the narrow+snappy composition must outrank the
    uncompressed narrow ship — the whole point of composing the two."""
    slow = ShipPlanner(link_mbps=60.0, force=None)
    fast = ShipPlanner(link_mbps=5000.0, force=None)
    f = ChunkFacts(logical=8 << 20, width=8, narrow_k=3,
                   narrow_possible=True)
    r = slow.routes(f)
    assert r.index(ROUTE_NARROW_SNAPPY) < r.index(ROUTE_NARROW)
    # on a fast link the host passes dominate: plain must win
    assert fast.routes(f)[0] == ROUTE_PLAIN


def test_planner_env_overrides(monkeypatch):
    monkeypatch.setenv("TPQ_LINK_MBPS", "123.5")
    monkeypatch.setenv("TPQ_FORCE_ROUTE", "recompress")
    p = ShipPlanner()
    assert p.link_mbps == 123.5
    assert p.routes(ChunkFacts(logical=1 << 20, width=8)) == [
        ROUTE_RECOMPRESS, ROUTE_PLAIN]
    # malformed env value: ONE warning, then cost-ranked routing — the
    # TPQ_FORCE_ROUTE degradation contract (an env typo must never turn
    # reader construction, or a scan mid-flight through default_planner's
    # env re-read, into a raise).  An explicit force= argument is a
    # programming contract and still raises.
    monkeypatch.setenv("TPQ_FORCE_ROUTE", "bogus")
    assert ShipPlanner().force is None
    with pytest.raises(ValueError, match="warp"):
        ShipPlanner(force="warp")


# ---------------------------------------------------------------------------
# route bit-identity (the acceptance-criteria matrix)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("prefetch", [0, 4])
@pytest.mark.parametrize("codec", ["snappy", "gzip", "uncompressed"])
def test_planned_routes_bit_identical(ship_files, codec, prefetch,
                                      monkeypatch):
    monkeypatch.delenv("TPQ_FORCE_ROUTE", raising=False)
    paths, _ = ship_files
    st = _assert_matches_host(paths[codec], prefetch)
    d = st.as_dict()
    assert d["ship_routes"], "planner recorded no routes"
    assert d["link_bytes_shipped"] <= d["link_bytes_logical"]
    if native.available():
        # the headline claim: compressed shipping engages beyond PLAIN
        # fixed-width — the string heap must NOT ship as raw host bytes
        routes = set(d["ship_routes"])
        assert routes & {ROUTE_DEVICE_SNAPPY, ROUTE_RECOMPRESS,
                         ROUTE_NARROW, ROUTE_NARROW_SNAPPY}, d
        assert d["link_bytes_shipped"] < d["link_bytes_logical"]


@pytest.mark.parametrize("prefetch", [0, 4])
@pytest.mark.parametrize("route", list(ROUTES))
def test_forced_route_bit_identical(ship_files, route, prefetch,
                                    monkeypatch):
    """TPQ_FORCE_ROUTE pins the route (deterministic CI); infeasible forces
    (narrow on doubles, device_snappy on gzip) must fall back to plain with
    identical results, never an error."""
    paths, _ = ship_files
    monkeypatch.setenv("TPQ_FORCE_ROUTE", route)
    for codec in ("snappy", "gzip"):
        st = _assert_matches_host(paths[codec], prefetch)
        assert st.as_dict()["ship_routes"]


def test_forced_route_histogram(ship_files, monkeypatch):
    """The forced route must actually be TAKEN where feasible, and the
    counters must prove the byte cut."""
    if not native.available():
        pytest.skip("native library unavailable")
    paths, _ = ship_files
    monkeypatch.setenv("TPQ_FORCE_ROUTE", "recompress")
    st = _assert_matches_host(paths["gzip"], 0).as_dict()
    rec = st["ship_routes"].get(ROUTE_RECOMPRESS)
    assert rec is not None and rec["shipped"] < rec["logical"]
    monkeypatch.setenv("TPQ_FORCE_ROUTE", "narrow")
    st = _assert_matches_host(paths["gzip"], 0).as_dict()
    nar = st["ship_routes"].get(ROUTE_NARROW)
    assert nar is not None and nar["shipped"] < nar["logical"]


def test_narrow_snappy_composition_engages(ship_files, monkeypatch):
    """At congested-link settings the planner composes narrow + snappy on
    low-entropy int columns (`dates`), and the composed route reconstructs
    bit-exactly — the plain_int64-gap mechanism of the ISSUE."""
    if not native.available():
        pytest.skip("native library unavailable")
    paths, _ = ship_files
    monkeypatch.delenv("TPQ_FORCE_ROUTE", raising=False)
    monkeypatch.setenv("TPQ_LINK_MBPS", "60")
    st = _assert_matches_host(paths["gzip"], 0).as_dict()
    ns = st["ship_routes"].get(ROUTE_NARROW_SNAPPY)
    assert ns is not None, st["ship_routes"]
    assert ns["shipped"] < ns["logical"] // 2


def test_bytes_heap_ships_compressed_snappy(ship_files, monkeypatch):
    """The lineitem16 byte mover: PLAIN BYTE_ARRAY value heaps in a snappy
    file keep the file's own payload over the link."""
    if not native.available():
        pytest.skip("native library unavailable")
    paths, _ = ship_files
    monkeypatch.delenv("TPQ_FORCE_ROUTE", raising=False)
    st = _assert_matches_host(paths["snappy"], 0).as_dict()
    ds = st["ship_routes"].get(ROUTE_DEVICE_SNAPPY)
    assert ds is not None and ds["shipped"] < ds["logical"], st["ship_routes"]
    assert st["pages_device_expanded"] > 0


def test_dict_table_ships_compressed(tmp_path, monkeypatch):
    """Dictionary VALUE TABLES route through the planner too: a snappy
    file's fixed-width dictionary keeps its compressed page payload, a
    ragged (string) dictionary recompresses its heap — both decode
    bit-identically through materialize()."""
    if not native.available():
        pytest.skip("native library unavailable")
    monkeypatch.delenv("TPQ_FORCE_ROUTE", raising=False)
    rng = np.random.default_rng(23)
    # large dictionaries so the tables clear MIN_COMPRESS_BYTES
    pool_i = rng.integers(0, 1 << 45, 20_000)
    ints = pool_i[rng.integers(0, len(pool_i), N)]
    pool = [f"warehouse_row_{i:06d}".encode() for i in range(20_000)]
    sidx = rng.integers(0, len(pool), N)
    offs = np.zeros(N + 1, dtype=np.int64)
    np.cumsum([len(pool[i]) for i in sidx], out=offs[1:])
    heap = np.frombuffer(b"".join(pool[i] for i in sidx), np.uint8).copy()
    schema = build_schema([
        data_column("di", Type.INT64, FRT.REQUIRED),
        data_column("ds", Type.BYTE_ARRAY, FRT.REQUIRED),
    ])
    p = str(tmp_path / "dict.parquet")
    with FileWriter(p, schema, codec=CompressionCodec.SNAPPY,
                    use_dictionary=True) as w:
        w.write_columns({
            "di": ints,
            "ds": ColumnData(values=ByteArrayData(offsets=offs, heap=heap)),
        })
    with DeviceFileReader(p) as r:
        (rg,) = list(r.iter_row_groups())
        got_i = np.asarray(rg["di"].to_host())
        got_s = rg["ds"].to_host()
        st = r.stats().as_dict()
    assert np.array_equal(got_i, ints)
    assert _ragged_rows(got_s) == [pool[i] for i in sidx]
    routes = set(st["ship_routes"])
    assert routes & {ROUTE_DEVICE_SNAPPY, ROUTE_RECOMPRESS}, st["ship_routes"]


def test_op_cap_overflow_falls_back(ship_files, monkeypatch):
    """A stream shattered past the op-table cap must fall through to the
    next route (never error, never ship a broken table) — the satellite's
    op-count-cap-overflow case at the integration level."""
    import tpu_parquet.device_reader as DR

    paths, _ = ship_files
    monkeypatch.delenv("TPQ_FORCE_ROUTE", raising=False)
    monkeypatch.setattr(DR, "_SNAPPY_MAX_OPS", 2)
    st = _assert_matches_host(paths["snappy"], 0).as_dict()
    assert ROUTE_DEVICE_SNAPPY not in st["ship_routes"], st["ship_routes"]


def test_recompress_counted_in_pipeline_stats(ship_files, monkeypatch):
    """Link recompression runs on the prefetch pool's threads and its
    seconds surface in the `recompress` stage (pipeline.py STAGES)."""
    if not native.available():
        pytest.skip("native library unavailable")
    paths, _ = ship_files
    monkeypatch.delenv("TPQ_FORCE_ROUTE", raising=False)
    with DeviceFileReader(paths["gzip"], prefetch=4) as r:
        for _ in r.iter_row_groups():
            pass
        ps = r.pipeline_stats().as_dict()
        st = r.stats().as_dict()
    if ROUTE_RECOMPRESS in st["ship_routes"]:
        assert ps["recompress_seconds"] > 0.0
    assert "recompress_seconds" in ps


def test_plain_force_ships_everything_raw(ship_files, monkeypatch):
    """TPQ_FORCE_ROUTE=plain is the A/B baseline: logical == shipped."""
    paths, _ = ship_files
    monkeypatch.setenv("TPQ_FORCE_ROUTE", "plain")
    st = _assert_matches_host(paths["snappy"], 0).as_dict()
    assert set(st["ship_routes"]) == {ROUTE_PLAIN}
    assert st["link_bytes_shipped"] == st["link_bytes_logical"]


def test_reader_degrades_bogus_forced_route(ship_files, monkeypatch):
    """A typo'd TPQ_FORCE_ROUTE must not turn reader construction into a
    raise: one warning line, then cost-ranked routing, bit-identical
    results (the same degradation contract as every other TPQ_* knob)."""
    paths, _ = ship_files
    monkeypatch.setenv("TPQ_FORCE_ROUTE", "warp")
    st = _assert_matches_host(paths["snappy"], 0).as_dict()
    assert st["ship_routes"]  # the scan ran, cost-ranked
