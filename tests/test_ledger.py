"""Run ledger + noise-aware bench diff + doctor attribution tests (ISSUE 5).

Covers tentpole pieces 2 and 3: the append-only versioned ledger
(append -> read -> diff round-trip, env fingerprint, ``#N`` addressing),
the noise model (an injected 2x stage regression is flagged OUTSIDE the
rep-variance bounds and attributed to the stage that moved; a within-noise
rerun is NOT flagged), the CI gate (``bench.py --check-against`` exit
codes, unloadable baseline fails closed), ``doctor_registry``'s four
bottleneck verdicts with golden CLI output and the ``TPQ_LINK_MBPS``
recalibration band, and the end-to-end ``bench.py --smoke`` plumbing run
the tier-1 suite gates on.
"""

import io
import json
import os
import subprocess
import sys

import pytest

from tpu_parquet import ledger
from tpu_parquet.obs import DOCTOR_VERDICTS, doctor_registry

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO_ROOT, "bench.py")


# ---------------------------------------------------------------------------
# helpers: canned run records / registry trees
# ---------------------------------------------------------------------------

def _stages(io_s=0.0, dec=0.0, rec=0.0, stage=0.0, disp=0.0, fin=0.0,
            stall=0.0):
    return {
        "io_seconds": io_s, "decompress_seconds": dec,
        "recompress_seconds": rec, "stage_seconds": stage,
        "dispatch_seconds": disp, "finalize_seconds": fin,
        "stall_seconds": stall,
    }


def _cfg(device=1e7, host=1e6, device_reps=None, host_reps=None, rows=1000,
         stages=None, **extra):
    cfg = {
        "rows": rows,
        "device_rows_per_sec": device,
        "host_rows_per_sec": host,
        "device_windows_s": (device_reps if device_reps is not None
                             else [[0.100, 0.101, 0.099, 0.100, 0.102]]),
        "host_reps_s": (host_reps if host_reps is not None
                        else [1.00, 1.01, 0.99, 1.00]),
    }
    if stages is not None:
        cfg["obs"] = {"obs_version": 1, "pipeline": stages}
    cfg.update(extra)
    return cfg


def _record(**cfgs):
    return {"metric": "m", "value": 1.0, "unit": "rows/s",
            "vs_baseline": 1.0, "configs": cfgs}


# ---------------------------------------------------------------------------
# ledger records
# ---------------------------------------------------------------------------

def test_append_read_roundtrip_creates_parent_dirs(tmp_path):
    """The same contract as Tracer.write: a ledger path into a fresh tree
    must not fail at append time with a late FileNotFoundError."""
    path = str(tmp_path / "runs" / "today" / "ledger.jsonl")
    r0 = ledger.make_record(_record(c=_cfg()))
    r1 = ledger.make_record(_record(c=_cfg(device=2e7)))
    assert ledger.append(path, r0) == 0
    assert ledger.append(path, r1) == 1  # sequence numbers count lines
    back = ledger.read(path)
    assert back == [r0, r1]


def test_read_corrupt_line_names_position(tmp_path):
    path = tmp_path / "ledger.jsonl"
    path.write_text('{"ok": 1}\n{broken\n')  # complete (newline'd) bad line
    with pytest.raises(ValueError, match=r"ledger\.jsonl:2"):
        ledger.read(str(path))


def test_torn_tail_skipped_and_healed(tmp_path):
    """A writer killed mid-append leaves a partial final line (no newline):
    read() must skip it — the intact records stay usable — and the next
    append() truncates it away so lines can never glue."""
    path = str(tmp_path / "ledger.jsonl")
    ledger.append(path, {"v": 1})
    with open(path, "a") as f:
        f.write('{"v": 2, "par')  # died mid-write
    assert ledger.read(path) == [{"v": 1}]
    assert ledger.load_side(path) == {"v": 1}
    assert ledger.append(path, {"v": 3}) == 1  # torn record never counted
    assert ledger.read(path) == [{"v": 1}, {"v": 3}]


def test_make_record_fingerprint(monkeypatch):
    monkeypatch.setenv("TPQ_LINK_MBPS", "350")
    monkeypatch.setenv("TPQ_FORCE_ROUTE", "plain")
    rec = ledger.make_record(_record(c=_cfg()), ts=123.456)
    assert rec["ledger_version"] == ledger.LEDGER_VERSION
    assert rec["ts"] == 123.456
    # two runs with different TPQ_LINK_MBPS are different experiments —
    # the fingerprint says so
    assert rec["env"]["TPQ_LINK_MBPS"] == "350"
    assert rec["env"]["TPQ_FORCE_ROUTE"] == "plain"
    # the result-cache knobs are part of the experiment identity (ISSUE 14):
    # a warm-cache run and a cache-off run are different experiments
    monkeypatch.setenv("TPQ_RESULT_CACHE_MB", "128")
    monkeypatch.setenv("TPQ_RESULT_CACHE_HBM_MB", "32")
    rec2 = ledger.make_record(_record(c=_cfg()), ts=123.5)
    assert rec2["env"]["TPQ_RESULT_CACHE_MB"] == "128"
    assert rec2["env"]["TPQ_RESULT_CACHE_HBM_MB"] == "32"
    # the QoS/streaming knobs ride too (ISSUE 17): a fair-share run and a
    # FIFO run — or different tenant weights — are different experiments
    monkeypatch.setenv("TPQ_SERVE_FAIR", "0")
    monkeypatch.setenv("TPQ_SERVE_TENANTS", "gold=3,bronze=1")
    monkeypatch.setenv("TPQ_STREAM_BUFFER_BATCHES", "4")
    rec3 = ledger.make_record(_record(c=_cfg()), ts=124.0)
    assert rec3["env"]["TPQ_SERVE_FAIR"] == "0"
    assert rec3["env"]["TPQ_SERVE_TENANTS"] == "gold=3,bronze=1"
    assert rec3["env"]["TPQ_STREAM_BUFFER_BATCHES"] == "4"
    # the async-IO knobs ride too (ISSUE 18): an engine run at a different
    # in-flight cap — or the threaded fallback — is a different experiment
    monkeypatch.setenv("TPQ_IO_INFLIGHT", "64")
    monkeypatch.setenv("TPQ_IO_ASYNC", "0")
    rec4 = ledger.make_record(_record(c=_cfg()), ts=124.5)
    assert rec4["env"]["TPQ_IO_INFLIGHT"] == "64"
    assert rec4["env"]["TPQ_IO_ASYNC"] == "0"
    # the tracing/metrics knobs ride too (ISSUE 19): a retain-all run pays
    # for every tree where a tail-sampled one doesn't — different
    # experiments, and the dump spec names where the evidence went
    monkeypatch.setenv("TPQ_TRACE_TAIL", "1")
    monkeypatch.setenv("TPQ_TRACE_RING", "2097152")
    monkeypatch.setenv("TPQ_TRACE_SPANS", "256")
    monkeypatch.setenv("TPQ_TRACE_SLOW_Q", "0.99")
    monkeypatch.setenv("TPQ_METRICS_DUMP", "/tmp/m.json:2")
    rec5 = ledger.make_record(_record(c=_cfg()), ts=125.0)
    assert rec5["env"]["TPQ_TRACE_TAIL"] == "1"
    assert rec5["env"]["TPQ_TRACE_RING"] == "2097152"
    assert rec5["env"]["TPQ_TRACE_SPANS"] == "256"
    assert rec5["env"]["TPQ_TRACE_SLOW_Q"] == "0.99"
    assert rec5["env"]["TPQ_METRICS_DUMP"] == "/tmp/m.json:2"
    # the fleet-spool knobs ride too (ISSUE 20): a spool-armed run pays the
    # snapshot cadence, and the stream-yield flag changes the scheduler —
    # different experiments
    monkeypatch.setenv("TPQ_OBS_SPOOL", "/tmp/spool")
    monkeypatch.setenv("TPQ_OBS_SPOOL_S", "0.5")
    monkeypatch.setenv("TPQ_OBS_SPOOL_KEEP", "3")
    monkeypatch.setenv("TPQ_OBS_STALE_S", "5")
    monkeypatch.setenv("TPQ_SERVE_STREAM_YIELD", "0")
    rec6 = ledger.make_record(_record(c=_cfg()), ts=125.5)
    assert rec6["env"]["TPQ_OBS_SPOOL"] == "/tmp/spool"
    assert rec6["env"]["TPQ_OBS_SPOOL_S"] == "0.5"
    assert rec6["env"]["TPQ_OBS_SPOOL_KEEP"] == "3"
    assert rec6["env"]["TPQ_OBS_STALE_S"] == "5"
    assert rec6["env"]["TPQ_SERVE_STREAM_YIELD"] == "0"
    assert "python" in rec["env"]
    # inside this repo the short revision resolves
    rev = rec["git_rev"]
    assert rev is None or (isinstance(rev, str) and len(rev) == 12)
    assert rec["configs"]["c"]["rows"] == 1000  # the bench tree rides along


def test_load_side_forms(tmp_path):
    art = tmp_path / "run.json"
    art.write_text(json.dumps(_record(c=_cfg())))
    assert ledger.load_side(str(art))["metric"] == "m"
    lpath = str(tmp_path / "ledger.jsonl")
    for v in (1.0, 2.0, 3.0):
        ledger.append(lpath, {"metric": "m", "value": v, "configs": {}})
    assert ledger.load_side(lpath)["value"] == 3.0          # last by default
    assert ledger.load_side(lpath + "#0")["value"] == 1.0   # absolute
    assert ledger.load_side(lpath + "#-2")["value"] == 2.0  # from the end
    with pytest.raises(ValueError, match="no record #7"):
        ledger.load_side(lpath + "#7")
    empty = tmp_path / "empty.jsonl"
    empty.write_text("")
    with pytest.raises(ValueError, match="empty ledger"):
        ledger.load_side(str(empty))
    notdict = tmp_path / "list.json"
    notdict.write_text("[1, 2]")
    with pytest.raises(ValueError, match="not a run record"):
        ledger.load_side(str(notdict))


def test_rel_noise_small_n_behavior():
    assert ledger.rel_noise([]) == 0.0
    assert ledger.rel_noise([1.0]) == 0.0  # no information
    # n in {2,3}: half-range over median (MAD under-reads at tiny n)
    assert ledger.rel_noise([1.0, 1.2]) == pytest.approx(0.1 / 1.1)
    # n >= 4: normal-consistent relative MAD, robust to one eaten rep
    tight = ledger.rel_noise([1.0, 1.01, 0.99, 1.0, 1.02, 5.0])
    assert tight < 0.05  # the 5.0 outlier does not blow up the band


# ---------------------------------------------------------------------------
# diff: noise bounds, attribution, incomparability
# ---------------------------------------------------------------------------

def test_diff_within_noise_not_flagged():
    """A rerun that moved 5% on metrics whose reps carry ~1% noise stays
    under the 10% human floor: within_noise, nothing flagged."""
    a = _record(c=_cfg(device=1.00e7, host=1.00e6))
    b = _record(c=_cfg(device=1.05e7, host=0.96e6))
    d = ledger.diff(a, b)
    assert d["compared"] >= 2
    assert d["regressions"] == [] and d["improvements"] == []
    assert all(e["verdict"] == "within_noise" for e in d["metrics"].values())


def test_diff_flags_injected_2x_regression_with_attribution():
    """The acceptance scenario: a synthetic 2x device slowdown whose
    registry shows the decompress lane growing 2.1x must be flagged
    outside the noise bounds AND attributed to that stage."""
    a = _record(c=_cfg(device=1e7, stages=_stages(
        io_s=0.2, dec=1.0, stage=0.5, fin=0.1)))
    b = _record(c=_cfg(device=5e6, stages=_stages(
        io_s=0.2, dec=2.1, stage=0.5, fin=0.1)))
    d = ledger.diff(a, b)
    flagged = [e for e in d["regressions"]
               if e["metric"] == "device_rows_per_sec"]
    assert len(flagged) == 1
    e = flagged[0]
    assert e["ratio"] == pytest.approx(0.5)
    assert e["noise_bound"] < 0.5  # the band did not swallow a 2x move
    att = e["attribution"]
    assert att["stage"] == "decompress"
    assert att["ratio"] == pytest.approx(2.1)
    assert att["moved_seconds"] == pytest.approx(1.1)
    # the improvement direction never lands in regressions
    up = _record(c=_cfg(device=2e7))
    d2 = ledger.diff(a, up)
    assert any(e["metric"] == "device_rows_per_sec"
               for e in d2["improvements"])
    assert not d2["regressions"]


def test_diff_noisy_reps_widen_the_band():
    """The same -33% move: flagged on tight reps, absorbed when the reps
    themselves scatter 20% — the band comes from the records' variance."""
    a_tight = _record(c=_cfg(device=1.0e7))
    b_tight = _record(c=_cfg(device=0.67e7))
    assert ledger.diff(a_tight, b_tight)["regressions"]
    noisy = [[0.080, 0.120, 0.095, 0.140, 0.070]]
    a_noisy = _record(c=_cfg(device=1.0e7, device_reps=noisy))
    b_noisy = _record(c=_cfg(device=0.67e7, device_reps=noisy))
    d = ledger.diff(a_noisy, b_noisy)
    assert not [e for e in d["regressions"]
                if e["metric"] == "device_rows_per_sec"]


def test_diff_rows_mismatch_incomparable():
    """A smoke run against a full-scale baseline is a different experiment
    — 'incomparable', never a fake 100x regression."""
    a = _record(c=_cfg(rows=5_000_000))
    b = _record(c=_cfg(device=1e5, rows=20_000))
    d = ledger.diff(a, b)
    assert d["compared"] == 0 and not d["regressions"]
    assert d["incomparable"][0]["config"] == "c"
    assert "5000000" in d["incomparable"][0]["reason"]


def test_diff_link_bytes_ratio_lower_is_better():
    a = _record(c=_cfg(link_bytes_ratio=1.0))
    down = _record(c=_cfg(link_bytes_ratio=0.7))
    up = _record(c=_cfg(link_bytes_ratio=1.5))
    assert any(e["metric"] == "link_bytes_ratio"
               for e in ledger.diff(a, down)["improvements"])
    assert any(e["metric"] == "link_bytes_ratio"
               for e in ledger.diff(a, up)["regressions"])


def test_check_gate_floor_wider_than_diff():
    """-20% beyond tight noise: the 10% human diff flags it, the 30% CI
    gate (2x-class regressions, not drift) does not."""
    a = _record(c=_cfg(device=1.0e7))
    b = _record(c=_cfg(device=0.8e7))
    assert ledger.diff(a, b)["regressions"]
    assert ledger.check(a, b) == []
    big = _record(c=_cfg(device=0.4e7))
    assert ledger.check(a, big)


def test_format_diff_and_history_render():
    a = _record(c=_cfg(device=1e7, stages=_stages(dec=1.0)))
    b = _record(c=_cfg(device=5e6, stages=_stages(dec=2.1)))
    text = ledger.format_diff(ledger.diff(a, b), "A", "B")
    assert "REGRESSION" in text and "c.device_rows_per_sec" in text
    assert "decompress stage moved 2.10x" in text
    clean = ledger.format_diff(ledger.diff(a, a), "A", "A")
    assert "within noise" in clean
    recs = [ledger.make_record({"metric": "m", "value": 1e7,
                                "unit": "rows/s", "vs_baseline": 2.0,
                                "configs": {}}, ts=100.0)]
    hist = ledger.format_history(recs, "ledger.jsonl")
    assert "#0" in hist and "m=10,000,000 rows/s" in hist


# ---------------------------------------------------------------------------
# doctor: the four verdicts + recalibration band (golden CLI output)
# ---------------------------------------------------------------------------

_VERDICT_TREES = {
    "link-bound": _stages(io_s=0.5, dec=0.5, stage=5.0, disp=0.2),
    "host-decompress-bound": _stages(io_s=2.0, dec=3.0, stage=1.0, disp=0.2),
    "stall-bound": _stages(io_s=0.5, dec=0.5, stage=1.0, stall=6.0),
    "device-resolve-bound": _stages(io_s=0.5, dec=0.5, stage=1.0, disp=2.0,
                                    fin=2.5),
}


@pytest.mark.parametrize("verdict", sorted(_VERDICT_TREES))
def test_doctor_four_verdicts_golden_output(verdict, tmp_path):
    tree = {"obs_version": 1, "pipeline": _VERDICT_TREES[verdict]}
    rep = doctor_registry(tree)
    assert rep["verdict"] == verdict
    assert rep["verdict"] == DOCTOR_VERDICTS[rep["dominant_lane"]]
    total = sum(rep["lanes"].values())
    assert rep["dominant_share"] == pytest.approx(
        rep["lanes"][rep["dominant_lane"]] / total, abs=1e-4)
    # golden CLI rendering on the canned registry
    from tpu_parquet.cli import pq_tool

    p = str(tmp_path / "reg.json")
    with open(p, "w") as f:
        json.dump(tree, f)
    out = io.StringIO()
    args = pq_tool.build_parser().parse_args(["doctor", p])
    assert args.func(args, out=out) == 0
    text = out.getvalue()
    assert (f"verdict: {verdict} ({100 * rep['dominant_share']:.0f}% of "
            f"lane seconds)") in text
    # lanes print sorted by seconds, dominant first
    lanes_line = next(l for l in text.splitlines() if l.startswith("lanes:"))
    assert lanes_line.split()[1].startswith(rep["dominant_lane"] + "=")


def test_doctor_host_seconds_fallback():
    """A prefetch=0 run that never routed through the chunk pool has no
    io/decompress seconds — the reader's host_seconds is the host lane."""
    tree = {"obs_version": 1, "pipeline": _stages(stage=0.5),
            "reader": {"host_seconds": 4.0}}
    rep = doctor_registry(tree)
    assert rep["verdict"] == "host-decompress-bound"
    assert rep["lanes"]["host_decompress"] == pytest.approx(4.0)


def test_doctor_empty_and_malformed():
    assert doctor_registry({}) is None
    assert doctor_registry({"pipeline": _stages()}) is None  # all-zero lanes
    assert doctor_registry(None) is None
    assert doctor_registry({"pipeline": "nope"}) is None


def _feedback_tree(predicted, measured, link_bps, stages=None):
    routes = {"plain": {"streams": 1, "shipped_bytes": 1 << 20,
                        "predicted_seconds": predicted,
                        "measured_seconds": measured,
                        "error_ratio": (round(measured / predicted, 3)
                                        if measured and predicted else None)}}
    return {
        "obs_version": 1,
        "pipeline": stages or _stages(io_s=0.5, dec=0.5, stage=2.0),
        "reader": {"planner_link_mbps": 350.0,
                   "ship_feedback": {"link_bytes_per_sec": link_bps,
                                     "routes": routes}},
    }


def test_doctor_recalibration_band():
    # model 2x optimistic (outside the band): prints the measured rate as
    # the TPQ_LINK_MBPS to re-run with — the 1B re-measure procedure
    rep = doctor_registry(_feedback_tree(1.0, 2.0, 2.0e8))
    assert rep["route_model"]["error_ratio"] == pytest.approx(2.0)
    assert rep["recalibrate_link_mbps"] == pytest.approx(200.0)
    # within DOCTOR_ERROR_BAND: re-banking changes nothing worth chasing
    rep = doctor_registry(_feedback_tree(1.0, 1.1, 2.0e8))
    assert "recalibrate_link_mbps" not in rep
    # unmeasured routes (null): explicitly no ratio, no recalibration guess
    rep = doctor_registry(_feedback_tree(1.0, None, 0.0))
    assert rep["route_model"]["error_ratio"] is None
    assert "recalibrate_link_mbps" not in rep


def test_doctor_cli_on_bench_artifact_and_errors(tmp_path):
    from tpu_parquet.cli import pq_tool

    art = tmp_path / "bench.json"
    art.write_text(json.dumps(_record(
        c=_cfg(stages=_stages(io_s=1.0, dec=2.0, stage=0.5)))))
    out = io.StringIO()
    args = pq_tool.build_parser().parse_args(["doctor", str(art)])
    assert args.func(args, out=out) == 0
    assert "host-decompress-bound" in out.getvalue()
    # a registry-less artifact diagnoses instead of tracebacking
    bare = tmp_path / "bare.json"
    bare.write_text(json.dumps({"configs": {"c": {"rows": 1}}}))
    out = io.StringIO()
    args = pq_tool.build_parser().parse_args(["doctor", str(bare)])
    assert args.func(args, out=out) == 1
    assert "no config embeds" in out.getvalue()
    assert pq_tool.main(["doctor", str(tmp_path / "missing.json")]) == 1


def test_pq_tool_bench_diff_and_history_cli(tmp_path):
    from tpu_parquet.cli import pq_tool

    lpath = str(tmp_path / "ledger.jsonl")
    ledger.append(lpath, ledger.make_record(
        _record(c=_cfg(device=1e7)), ts=100.0))
    ledger.append(lpath, ledger.make_record(
        _record(c=_cfg(device=5e6, stages=_stages(dec=2.0))), ts=200.0))
    out = io.StringIO()
    args = pq_tool.build_parser().parse_args(
        ["bench", "diff", lpath + "#0", lpath + "#-1"])
    assert args.func(args, out=out) == 1  # regression -> nonzero
    assert "REGRESSION" in out.getvalue()
    out = io.StringIO()
    args = pq_tool.build_parser().parse_args(
        ["bench", "diff", lpath + "#0", lpath + "#0"])
    assert args.func(args, out=out) == 0
    assert "within noise" in out.getvalue()
    out = io.StringIO()
    args = pq_tool.build_parser().parse_args(["bench", "history", lpath])
    assert args.func(args, out=out) == 0
    text = out.getvalue()
    assert "2 runs" in text and "#0" in text and "#1" in text


# ---------------------------------------------------------------------------
# bench gate plumbing (in-process: deterministic exit codes)
# ---------------------------------------------------------------------------

def test_bench_gate_exit_codes(tmp_path, monkeypatch, capsys):
    sys.path.insert(0, REPO_ROOT)
    import bench

    base = tmp_path / "base.json"
    base.write_text(json.dumps(_record(
        c=_cfg(device=1e7, stages=_stages(dec=1.0)))))
    art = str(tmp_path / "art.json")

    # within the gate floor: rc 0, the check summary rides the record
    rec = _record(c=_cfg(device=0.95e7, stages=_stages(dec=1.05)))
    args = bench.parse_args(["--check-against", str(base), "--no-ledger"])
    assert bench._ledger_and_check(rec, args, art) == 0
    assert rec["check"]["regressions"] == [] and rec["check"]["compared"] > 0
    assert "ledger" not in rec  # --no-ledger

    # a 2x-class regression: rc 2, attributed
    rec = _record(c=_cfg(device=0.4e7, stages=_stages(dec=2.4)))
    assert bench._ledger_and_check(rec, args, art) == 2
    assert rec["check"]["regressions"][0]["attribution"]["stage"] == (
        "decompress")

    # an unloadable baseline fails CLOSED (a typo'd path silently passing
    # CI is the worst failure mode a gate can have)
    rec = _record(c=_cfg())
    args = bench.parse_args(
        ["--check-against", str(tmp_path / "nope.json"), "--no-ledger"])
    assert bench._ledger_and_check(rec, args, art) == 2
    assert rec["check"]["error"]

    # a loadable but WRONG-SHAPE baseline (zero comparable metrics) fails
    # just as loudly as a typo'd path — a gate that compared nothing
    # checked nothing
    empty_base = tmp_path / "wrong.json"
    empty_base.write_text(json.dumps(_record(other=_cfg(rows=999))))
    rec = _record(c=_cfg())
    args = bench.parse_args(["--check-against", str(empty_base),
                             "--no-ledger"])
    assert bench._ledger_and_check(rec, args, art) == 2
    assert rec["check"]["error"] == "no comparable metrics"
    # and the compact line distinguishes it from a baseline that never
    # loaded (different triage: config/rows mismatch vs typo'd path)
    monkeypatch.setenv("BENCH_JSON", str(tmp_path / "b.json"))
    bench.emit_results(dict(rec))
    out = capsys.readouterr().out.strip().splitlines()[-1]
    assert json.loads(out)["check"] == "incomparable_baseline"

    # a malformed BENCH_CHECK_FLOOR falls back instead of crashing before
    # the compact line is emitted (the r04/r05 parsed:null failure class)
    monkeypatch.setenv("BENCH_CHECK_FLOOR", "30%")
    rec = _record(c=_cfg(device=0.95e7))
    args = bench.parse_args(["--check-against", str(base), "--no-ledger"])
    assert bench._ledger_and_check(rec, args, art) == 0
    assert rec["check"]["floor"] == ledger.DEFAULT_CHECK_FLOOR
    monkeypatch.delenv("BENCH_CHECK_FLOOR")

    # the automatic ledger append (TPQ_LEDGER override)
    lpath = str(tmp_path / "runs" / "ledger.jsonl")
    monkeypatch.setenv("TPQ_LEDGER", lpath)
    rec = _record(c=_cfg())
    args = bench.parse_args([])
    assert bench._ledger_and_check(rec, args, art) == 0
    assert rec["ledger"] == {"path": lpath, "seq": 0}
    assert ledger.read(lpath)[0]["ledger_version"] == ledger.LEDGER_VERSION


def test_bench_gate_never_self_compares(tmp_path, monkeypatch):
    """`--check-against ledger.jsonl` with the ledger append active must
    gate against the PREVIOUS recorded run, not the record this run just
    appended — a self-comparison is ratio 1.0 on every metric, i.e. a gate
    that can never fail."""
    sys.path.insert(0, REPO_ROOT)
    import bench

    lpath = str(tmp_path / "ledger.jsonl")
    monkeypatch.setenv("TPQ_LEDGER", lpath)
    # run 0: the fast prior run
    ledger.append(lpath, ledger.make_record(_record(c=_cfg(device=1e7))))
    # run 1: 2x slower, checking against the ledger (its LAST record)
    rec = _record(c=_cfg(device=0.4e7))
    args = bench.parse_args(["--check-against", lpath])
    rc = bench._ledger_and_check(rec, args, str(tmp_path / "art.json"))
    assert rc == 2, "gate compared the run against itself"
    assert rec["check"]["regressions"]
    # and the regressed run was NOT recorded: appending it would make it
    # the very baseline the next run is gated against (see ratchet test)
    assert "ledger" not in rec
    assert len(ledger.read(lpath)) == 1


def test_bench_gate_failed_run_never_becomes_baseline(tmp_path, monkeypatch):
    """The no-ratchet contract: with the ledger itself as the baseline, a
    regression must keep failing run after run — if the red run were
    appended, the NEXT run would compare against it, match within noise,
    and the 2x loss would pass CI forever after one red build."""
    sys.path.insert(0, REPO_ROOT)
    import bench

    lpath = str(tmp_path / "ledger.jsonl")
    monkeypatch.setenv("TPQ_LEDGER", lpath)
    ledger.append(lpath, ledger.make_record(_record(c=_cfg(device=1e7))))
    args = bench.parse_args(["--check-against", lpath])
    art = str(tmp_path / "art.json")

    # the regression fails the gate on EVERY run, not just the first
    for _ in range(2):
        rec = _record(c=_cfg(device=0.4e7, stages=_stages(dec=2.4)))
        assert bench._ledger_and_check(rec, args, art) == 2
    assert len(ledger.read(lpath)) == 1  # only the good run is recorded

    # a recovered run passes against the original baseline and records
    rec = _record(c=_cfg(device=0.98e7))
    assert bench._ledger_and_check(rec, args, art) == 0
    assert rec["ledger"]["seq"] == 1
    assert len(ledger.read(lpath)) == 2


# ---------------------------------------------------------------------------
# end-to-end smoke gate (the CI/tooling satellite)
# ---------------------------------------------------------------------------

def test_bench_smoke_check_against_end_to_end(tmp_path):
    """`bench.py --smoke --check-against BASELINE.json` end to end in one
    subprocess: tiny config, artifact + ledger written, gate exits 0
    against a comparable slower baseline (improvements never fail), the
    compact stdout line stays <2000 chars with the new ledger/check
    fields, and `pq_tool doctor` on the traced run names the bottleneck
    lane consistent with the embedded registry (the acceptance criterion).
    """
    # a comparable baseline (same config, same rows) that this machine is
    # guaranteed to beat: the gate path runs deterministically to exit 0
    baseline = _record(c=None)
    baseline["metric"] = "plain_int64_decode_rows_per_sec_device"
    baseline["configs"] = {"plain_int64": {
        "rows": 20_000, "device_rows_per_sec": 1.0, "host_rows_per_sec": 1.0,
        "host_reps_s": [1.0, 1.0], "device_windows_s": [[1.0, 1.0]],
    }}
    bpath = tmp_path / "BASELINE.json"
    bpath.write_text(json.dumps(baseline))
    env = dict(os.environ,
               JAX_PLATFORMS="cpu",
               BENCH_SCALE="0.002",  # pin rows=20000 to match the baseline
               BENCH_JSON=str(tmp_path / "run.json"),
               TPQ_LEDGER=str(tmp_path / "ledger.jsonl"),
               TPQ_TRACE=str(tmp_path / "trace"))
    r = subprocess.run(
        [sys.executable, BENCH, "--smoke", "--check-against", str(bpath)],
        capture_output=True, text=True, cwd=str(tmp_path), env=env,
        timeout=280)
    assert r.returncode == 0, (r.stdout[-1500:], r.stderr[-1500:])
    last = r.stdout.strip().splitlines()[-1]
    assert len(last) < 2000  # the driver's tail window, with the new fields
    parsed = json.loads(last)
    assert parsed["check"].startswith("ok")
    assert parsed["ledger"].endswith("#0")
    recs = ledger.read(str(tmp_path / "ledger.jsonl"))
    assert len(recs) == 1
    assert recs[0]["ledger_version"] == ledger.LEDGER_VERSION
    assert recs[0]["env"].get("BENCH_SCALE") == "0.002"
    assert recs[0]["configs"]["plain_int64"]["rows"] == 20_000
    # the artifact carries the full check entry (improvements included)
    art = json.loads((tmp_path / "run.json").read_text())
    assert art["check"]["regressions"] == []
    assert art["check"]["compared"] > 0
    # doctor on the traced smoke run: dominant lane matches the registry
    tdoc = json.loads((tmp_path / "trace.plain_int64.json").read_text())
    tree = tdoc["otherData"]["registry"]
    rep = doctor_registry(tree)
    assert rep is not None
    pipe = tree["pipeline"]

    def g(k):
        v = pipe.get(k)
        return float(v) if isinstance(v, (int, float)) else 0.0

    host = (g("io_seconds") + g("decompress_seconds")
            + g("recompress_seconds")) or float(
        (tree.get("reader") or {}).get("host_seconds") or 0.0)
    dev = tree.get("device") or {}
    dev_resolve = sum(float(c.get("device_seconds") or 0.0)
                      for c in (dev.get("routes") or {}).values())
    lanes = {"link": g("stage_seconds"), "host_decompress": host,
             "device_resolve": dev_resolve or (g("dispatch_seconds")
                                               + g("finalize_seconds")),
             "h2d": float((dev.get("h2d") or {}).get("device_seconds")
                          or 0.0),
             "stall": g("stall_seconds")}
    assert rep["dominant_lane"] == max(lanes, key=lambda k: (lanes[k], k))
    assert rep["dominant_share"] == pytest.approx(
        lanes[rep["dominant_lane"]] / sum(lanes.values()), rel=0.10)
    from tpu_parquet.cli import pq_tool

    out = io.StringIO()
    args = pq_tool.build_parser().parse_args(
        ["doctor", str(tmp_path / "trace.plain_int64.json")])
    assert args.func(args, out=out) == 0
    assert f"verdict: {rep['verdict']}" in out.getvalue()
