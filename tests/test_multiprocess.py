"""Real multi-process seam for the work-list sharding (SURVEY.md §5.8).

Everything else in tests/ exercises the sharded decode on a single-process
virtual mesh; this file spawns TWO OS processes joined through
``jax.distributed.initialize`` (4 virtual CPU devices each → one 8-device
global mesh) and drives ``process_local_column`` end-to-end on a real file:
each process decodes only ITS row span, the runtime assembles the global
row-sharded array, and a replicated-out jit checksum must equal the
single-process decode of the same column.  This is the actual cross-process
contract (`make_array_from_process_local_data`, global avals, collective
assembly) that a single-process mesh cannot fake.
"""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

_WORKER = r"""
import os, sys
sys.path.insert(0, os.environ["TPQ_REPO_ROOT"])
import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(
    coordinator_address=sys.argv[1],
    num_processes=2,
    process_id=int(sys.argv[2]),
)
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_parquet.parallel import process_local_column, shard_row_ranges
from tpu_parquet.reader import FileReader

path = sys.argv[3]
assert jax.process_count() == 2, jax.process_count()
assert len(jax.devices()) == 8, len(jax.devices())  # 4 local x 2 processes

mesh = Mesh(np.array(jax.devices()).reshape(8), ("data",))
with FileReader(path) as r:
    arr, total = process_local_column(r, "v", mesh)
    # every process recomputes the identical plan from the footer alone
    spans = shard_row_ranges(total, 2)
    lo, hi = spans[jax.process_index()]

# replicated-out checksum over the GLOBAL array: runs as one pjit across
# both processes, so it exercises the collective assembly for real
@jax.jit
def checks(x):
    n = x.shape[0]
    w = jnp.arange(n, dtype=jnp.int64) % 97
    return jnp.sum(x * w), jnp.sum(x), jnp.max(x)

from tpu_parquet.jax_kernels import enable_x64

with enable_x64():
    got = [int(v) for v in jax.device_get(checks(arr))]

# single-process oracle: host decode of the whole column (+ zero padding to
# the uniform span size, matching process_local_column's tail padding)
with FileReader(path) as r:
    host = np.concatenate(
        [np.asarray(rg["v"].values) for rg in r.iter_row_groups()])
per = spans[0][1] - spans[0][0]
full = np.zeros(per * 2, dtype=np.int64)
full[: len(host)] = host
w = np.arange(len(full), dtype=np.int64) % 97
want = [int((full * w).sum()), int(full.sum()), int(full.max())]
assert got == want, (got, want)

# the process-local shards hold exactly this process's span
local_rows = np.concatenate(
    [np.asarray(s.data).reshape(-1) for s in arr.addressable_shards])
want_local = full[jax.process_index() * per : (jax.process_index() + 1) * per]
assert np.array_equal(np.sort(local_rows), np.sort(want_local))
print(f"proc {jax.process_index()} OK", flush=True)
"""


_LOADER_WORKER = r"""
import os, sys
sys.path.insert(0, os.environ["TPQ_REPO_ROOT"])
import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(
    coordinator_address=sys.argv[1],
    num_processes=2,
    process_id=int(sys.argv[2]),
)
import numpy as np

from tpu_parquet.data import DataLoader
from tpu_parquet.parallel import process_shard

path = sys.argv[3]
shard = process_shard()
assert shard[1] == 2, shard

def fresh(prefetch):
    return DataLoader(path, 512, columns=["v"], shuffle=True, seed=21,
                      shard=shard, shuffle_window=2048, prefetch=prefetch)

# the resume contract, across a REAL process boundary: iterate, save the
# blob, hand it to a brand-new loader (different prefetch), and the
# continuation must be bit-identical to the uninterrupted epoch
want = list(iter(fresh(prefetch=2)))
l = fresh(prefetch=0)
it = iter(l)
first = [next(it) for _ in range(3)]
it.close()
blob = l.state_blob()
rest = list(iter(fresh(prefetch=4).restore(blob)))
got = first + rest
assert len(got) == len(want), (len(got), len(want))
for g, w in zip(got, want):
    assert np.array_equal(g["v"], w["v"]) and np.array_equal(
        g["mask"], w["mask"])

mine = np.concatenate([b["v"][b["mask"]] for b in got])
print(f"proc {shard[0]} LOADER rows={len(mine)} sum={int(mine.sum())}",
      flush=True)
"""


@pytest.mark.skipif(os.environ.get("TPQ_SKIP_MULTIPROC") == "1",
                    reason="multi-process seam disabled by env")
def test_two_process_loader_resume(tmp_path):
    """DataLoader sharding + mid-epoch resume across two OS processes joined
    by jax.distributed: each process derives its shard from
    ``parallel.process_shard()``, resumes from a state blob bit-identically,
    and the parent checks the two shards partition the dataset exactly."""
    from tpu_parquet.format import FieldRepetitionType as FRT, Type
    from tpu_parquet.schema.core import build_schema, data_column
    from tpu_parquet.writer import FileWriter

    p = str(tmp_path / "mp_loader.parquet")
    n = 50_000
    rng = np.random.default_rng(9)
    vals = rng.integers(0, 1 << 40, n)
    schema = build_schema([data_column("v", Type.INT64, FRT.REQUIRED)])
    splits = [0, 9000, 17000, 23000, 31000, 38000, 44000, n]
    with FileWriter(p, schema, codec=1) as w:
        for lo, hi in zip(splits, splits[1:]):
            w.write_columns({"v": vals[lo:hi]})
            w.flush_row_group()  # several uneven units: both shards get work

    outs = _run_pair(tmp_path, _LOADER_WORKER, p)
    rows = sums = 0
    for i, out in enumerate(outs):
        assert f"proc {i} LOADER" in out, out[-4000:]
        tail = out[out.index(f"proc {i} LOADER"):].split()
        rows += int(tail[3].split("=")[1])
        sums += int(tail[4].split("=")[1])
    assert rows == n
    assert sums == int(vals.sum())


def _run_pair(tmp_path, worker_src, path):
    """Spawn two coordinated worker processes; returns their outputs."""
    with socket.socket() as s:
        s.bind(("localhost", 0))
        coord = f"localhost:{s.getsockname()[1]}"
    script = str(tmp_path / "worker.py")
    with open(script, "w") as f:
        f.write(worker_src)
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env["JAX_PLATFORMS"] = "cpu"
    env["TPQ_REPO_ROOT"] = os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))
    env.pop("JAX_NUM_PROCESSES", None)
    procs = [
        subprocess.Popen(
            [sys.executable, script, coord, str(i), path],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True,
        )
        for i in range(2)
    ]
    outs = []
    for pr in procs:
        try:
            out, _ = pr.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
    for i, (pr, out) in enumerate(zip(procs, outs)):
        if (pr.returncode != 0
                and "Multiprocess computations aren't implemented" in out):
            # XLA's CPU backend has no cross-process collectives: the
            # distributed runtime initializes and the per-process decode
            # runs, but the replicated-out pjit cannot execute.  An
            # explicit skip (round-7 hygiene) keeps the seam visible as an
            # environment gap instead of a standing red test; real TPU/GPU
            # CI runs the assertion for real.
            pytest.skip("CPU backend lacks multiprocess collectives "
                        "(XLA: \"Multiprocess computations aren't "
                        "implemented on the CPU backend\")")
        assert pr.returncode == 0, f"proc {i} failed:\n{out[-4000:]}"
    return outs


@pytest.mark.skipif(os.environ.get("TPQ_SKIP_MULTIPROC") == "1",
                    reason="multi-process seam disabled by env")
def test_two_process_global_column(tmp_path):
    from tpu_parquet.format import FieldRepetitionType as FRT, Type
    from tpu_parquet.schema.core import build_schema, data_column
    from tpu_parquet.writer import FileWriter

    p = str(tmp_path / "mp.parquet")
    n = 200_000
    rng = np.random.default_rng(5)
    vals = rng.integers(0, 1 << 40, n)
    schema = build_schema([data_column("v", Type.INT64, FRT.REQUIRED)])
    with FileWriter(p, schema, codec=1, row_group_size=1 << 19) as w:
        w.write_columns({"v": vals})

    outs = _run_pair(tmp_path, _WORKER, p)
    for i, out in enumerate(outs):
        assert f"proc {i} OK" in out, out[-4000:]
