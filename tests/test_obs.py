"""Unified trace/metrics layer tests (ISSUE 4).

Covers the obs.py tentpole end to end: tracer span/instant/counter recording
and Chrome trace-event export (field + nesting validation, the format
Perfetto loads), log-bucketed latency histograms (quantiles, thread/process
merge), the versioned StatsRegistry tree (golden keys — bench parsers and
the driver key on them), the PipelineStats unknown-stage guard, the
disabled-tracer overhead guard, and the full wiring: FileReader /
DeviceFileReader / DataLoader ``trace=`` runs whose artifacts ``pq_tool
trace`` summarizes with overlap efficiency matching ``pipeline_stats()``
within 5%.
"""

import io
import itertools
import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from tpu_parquet.obs import (
    OBS_VERSION, LatencyHistogram, Sampler, StatsRegistry, Tracer,
    current_tracer, doctor_registry, resolve_sample_ms, resolve_tracer,
    trace_summary,
)
from tpu_parquet.pipeline import STAGES, PipelineStats

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------

def _write_ints(path, rows=200_000, groups=4, seed=0):
    from tpu_parquet.format import FieldRepetitionType as FRT, Type
    from tpu_parquet.schema.core import build_schema, data_column
    from tpu_parquet.writer import FileWriter

    rng = np.random.default_rng(seed)
    schema = build_schema([
        data_column("v", Type.INT64, FRT.REQUIRED),
        data_column("w", Type.INT32, FRT.REQUIRED),
    ])
    per = rows // groups
    with FileWriter(path, schema, row_group_size=1) as w:
        for _ in range(groups):
            w.write_columns({
                "v": rng.integers(0, 1 << 40, per),
                "w": rng.integers(0, 1000, per).astype(np.int32),
            })
            w.flush_row_group()
    return path


def _assert_event_fields(events):
    """The acceptance criterion's format validation: every event carries
    pid/tid/ts/ph (X spans additionally dur), all ints, json-serializable."""
    assert events, "no events recorded"
    json.dumps(events)  # round-trippable
    for ev in events:
        assert isinstance(ev.get("ph"), str)
        assert isinstance(ev.get("pid"), int)
        assert isinstance(ev.get("tid"), int)
        if ev["ph"] == "M":
            continue
        assert isinstance(ev.get("ts"), int), ev
        if ev["ph"] == "X":
            assert isinstance(ev.get("dur"), int) and ev["dur"] >= 0, ev


def _assert_nesting(events):
    """Monotonically consistent nesting: on one thread any two spans are
    disjoint or contained (2 µs tolerance for the int-microsecond floor)."""
    by_tid = {}
    for ev in events:
        if ev.get("ph") == "X":
            by_tid.setdefault((ev["pid"], ev["tid"]), []).append(
                (ev["ts"], ev["ts"] + ev["dur"]))
    for spans in by_tid.values():
        for (a0, a1), (b0, b1) in itertools.combinations(spans, 2):
            disjoint = a1 <= b0 + 2 or b1 <= a0 + 2
            a_in_b = b0 <= a0 + 2 and a1 <= b1 + 2
            b_in_a = a0 <= b0 + 2 and b1 <= a1 + 2
            assert disjoint or a_in_b or b_in_a, ((a0, a1), (b0, b1))


# ---------------------------------------------------------------------------
# tracer
# ---------------------------------------------------------------------------

def test_disabled_tracer_is_noop():
    tr = Tracer(enabled=False, ring=None)  # ring=None: no flight tee either
    s1 = tr.span("a", x=1)
    s2 = tr.span("b")
    assert s1 is s2  # the shared no-op singleton: zero allocation per span
    with s1:
        pass
    tr.instant("i", y=2)
    tr.counter("c", v=3)
    tr.complete("x", 0.0, 1.0)
    assert tr.events() == []
    # the DEFAULT disabled tracer still tees into the flight recorder (the
    # always-on black box) without recording any trace events
    tr2 = Tracer(enabled=False)
    assert tr2.active and not tr2.enabled
    tr2.complete("x", 0.0, 1.0)
    assert tr2.events() == []


def test_span_nesting_and_export_fields():
    tr = Tracer()
    with tr.span("outer", rg=0):
        with tr.span("inner"):
            time.sleep(0.002)
        with tr.span("inner"):
            pass
    tr.instant("mark", k="v")
    tr.counter("gauge", rows=7)
    events = tr.events()
    _assert_event_fields(events)
    _assert_nesting(events)
    xs = [e for e in events if e["ph"] == "X"]
    assert [e["name"] for e in xs] == ["inner", "inner", "outer"]
    outer = xs[-1]
    assert outer["args"] == {"rg": 0}
    # children are contained in the parent
    for child in xs[:2]:
        assert outer["ts"] <= child["ts"]
        assert child["ts"] + child["dur"] <= outer["ts"] + outer["dur"]
    doc = tr.export()
    assert set(doc) == {"traceEvents", "displayTimeUnit", "otherData"}
    assert doc["otherData"]["obs_version"] == OBS_VERSION
    # thread metadata names the recording thread
    meta = [e for e in events if e["ph"] == "M"]
    assert meta and meta[0]["name"] == "thread_name"


def test_tracer_write_and_registry_embed(tmp_path):
    tr = Tracer(path=str(tmp_path / "t.json"))
    with tr.span("io"):
        pass
    reg = StatsRegistry()
    reg.histogram("x").record(0.001)
    out = tr.write(registry=reg)
    doc = json.loads((tmp_path / "t.json").read_text())
    assert out == str(tmp_path / "t.json")
    assert doc["otherData"]["registry"]["obs_version"] == OBS_VERSION
    assert doc["otherData"]["registry"]["histograms"]["x"]["count"] == 1


def test_current_tracer_env(tmp_path, monkeypatch):
    monkeypatch.delenv("TPQ_TRACE", raising=False)
    assert not current_tracer().enabled
    p = str(tmp_path / "env.json")
    monkeypatch.setenv("TPQ_TRACE", p)
    tr = current_tracer()
    assert tr.enabled and tr.path == p
    assert current_tracer() is tr  # stable while the env is stable
    monkeypatch.delenv("TPQ_TRACE", raising=False)
    assert not current_tracer().enabled


def test_resolve_tracer_forms(tmp_path):
    tr, owned = resolve_tracer(str(tmp_path / "a.json"))
    assert owned and tr.enabled
    tr2, owned2 = resolve_tracer(tr)
    assert tr2 is tr and not owned2


# ---------------------------------------------------------------------------
# histograms
# ---------------------------------------------------------------------------

def test_histogram_quantiles_log_buckets():
    h = LatencyHistogram()
    for _ in range(90):
        h.record(1e-3)
    for _ in range(10):
        h.record(1e-1)
    assert h.count == 100
    # log2 buckets: <2x relative error around the true value
    assert 0.5e-3 <= h.quantile(0.5) <= 2e-3
    assert 0.05 <= h.quantile(0.95) <= 0.2
    assert h.max_seconds == pytest.approx(0.1)
    assert h.quantile(0.5) <= h.quantile(0.95)


def test_histogram_merge_and_dict_roundtrip():
    a, b = LatencyHistogram(), LatencyHistogram()
    for _ in range(10):
        a.record(1e-4)
        b.record(1e-2)
    b.merge_from(a)
    assert b.count == 20
    c = LatencyHistogram.from_dict(b.as_dict())
    assert c.count == 20 and c.as_dict() == b.as_dict()
    c.merge_dict(b.as_dict())
    assert c.count == 40
    assert c.sum_seconds == pytest.approx(2 * b.sum_seconds)


def test_histogram_zero_and_empty():
    h = LatencyHistogram()
    assert h.quantile(0.5) == 0.0
    h.record(0.0)
    assert h.count == 1 and h.quantile(0.5) == 0.0
    assert h.as_dict()["buckets"] == {"0": 1}


# ---------------------------------------------------------------------------
# PipelineStats: stage guard + histograms (satellite)
# ---------------------------------------------------------------------------

def test_pipeline_add_unknown_stage_raises():
    ps = PipelineStats()
    with pytest.raises(ValueError) as e:
        ps.add("upload", 0.1)
    msg = str(e.value)
    assert "upload" in msg
    for s in STAGES:  # the error NAMES the valid stages
        assert s in msg
    # every documented stage still accumulates
    for s in STAGES:
        ps.add(s, 0.001)
        assert ps.stage_seconds(s) == pytest.approx(0.001)


def test_pipeline_timed_unknown_stage_raises():
    ps = PipelineStats()
    with pytest.raises(ValueError):
        with ps.timed("warp"):
            pass


def test_pipeline_stage_histograms_and_merge():
    a, b = PipelineStats(), PipelineStats()
    for _ in range(5):
        a.add("io", 0.001)
        b.add("io", 0.01)
    b.merge_from(a)
    d = b.as_dict()
    hist = d["stage_histograms"]
    assert list(hist) == ["io"]  # silent stages carry no histogram
    assert hist["io"]["count"] == 10
    assert d["io_seconds"] == pytest.approx(0.055)


# ---------------------------------------------------------------------------
# registry (golden keys: the schema-stability satellite)
# ---------------------------------------------------------------------------

def _full_registry():
    from tpu_parquet.alloc import AllocTracker
    from tpu_parquet.data.loader import LoaderStats
    from tpu_parquet.device_reader import ReaderStats

    reg = StatsRegistry()
    ps = PipelineStats(prefetch=2, budget_bytes=1 << 20)
    ps.add("io", 0.01)
    ps.add("stage", 0.02)
    ps.count_chunk()
    ps.touch_wall()
    rs = ReaderStats()
    rs.count_route("plain", 100, 100, 0.001)
    rs.count_route("recompress", 200, 120, 0.002)
    rs.staged_bytes = 220
    ls = LoaderStats(PipelineStats())
    ls.batches = 3
    al = AllocTracker(1 << 20)
    al.register(4096)
    al.release(4096)
    reg.add_pipeline(ps)
    reg.add_reader(rs)
    reg.add_loader(ls)
    reg.note_alloc_peak(al)
    return reg


def test_registry_tree_golden_keys():
    tree = _full_registry().as_dict()
    assert set(tree) == {"obs_version", "pipeline", "reader", "loader",
                         "io", "data_errors", "device", "serve", "cache",
                         "write", "alloc", "histograms"}
    assert tree["io"] is None  # no IO-backend stats were folded in
    assert tree["data_errors"] is None  # no quarantine engine folded in
    assert tree["device"] is None  # no device timing was folded in
    assert tree["serve"] is None  # no scan service folded in
    assert tree["cache"] is None  # no result cache folded in
    assert tree["write"] is None  # no writer stats folded in
    assert tree["obs_version"] == OBS_VERSION
    assert tree["alloc"] == {"peak_bytes": 4096, "device_peak_bytes": 0}
    assert set(tree["histograms"]) == {"stage.io", "stage.stage"}
    fb = tree["reader"]["ship_feedback"]
    assert set(fb) == {"link_bytes_per_sec", "routes"}
    assert set(fb["routes"]) == {"plain", "recompress"}
    r = fb["routes"]["recompress"]
    assert {"streams", "shipped_bytes", "predicted_seconds",
            "device_unfused_predicted_seconds",
            "measured_seconds", "error_ratio",
            "device_predicted_seconds", "device_measured_seconds",
            "device_error_ratio"} == set(r)
    # measured = shipped / (staged/stage_seconds); stage=0.02s over 220 bytes
    assert r["measured_seconds"] == pytest.approx(120 / (220 / 0.02), rel=1e-3)
    json.dumps(tree)  # artifact-ready


def test_registry_merge_from_and_dict():
    a, b = _full_registry(), _full_registry()
    one = a.as_dict()
    a.merge_from(b)
    t = a.as_dict()
    assert t["pipeline"]["chunks"] == 2
    assert t["reader"]["ship_routes"]["plain"]["streams"] == 2
    assert t["loader"]["batches"] == 6
    assert t["histograms"]["stage.io"]["count"] == 2
    # config and ratio keys must NOT sum across merged sources: prefetch /
    # budget compose by max, and derived rates are recomputed from the
    # merged flows (merging two identical registries leaves them unchanged)
    assert t["pipeline"]["prefetch"] == one["pipeline"]["prefetch"]
    assert t["pipeline"]["budget_bytes"] == one["pipeline"]["budget_bytes"]
    for sect in ("pipeline", "reader", "loader"):
        for k in ("overlap_efficiency", "rows_per_sec", "bytes_per_sec",
                  "pages_per_chunk", "batches_per_sec"):
            if k in (one[sect] or {}):
                assert t[sect][k] == one[sect][k], (sect, k)
    # serialized (cross-process) merge stacks on top
    a.merge_dict(b.as_dict())
    assert a.as_dict()["pipeline"]["chunks"] == 3
    with pytest.raises(ValueError):
        a.merge_dict({"obs_version": 99})


def test_registry_cache_section_golden_keys_and_merge():
    """The result-cache `cache` section (ISSUE 14): per-tier golden keys,
    and the merge contract — flows add, the byte/entry gauges max (two
    snapshots of one shared cache must not sum its footprint)."""
    from tpu_parquet.serve import ResultCache

    rc = ResultCache(max_bytes=1 << 20, hbm_bytes=1 << 20,
                     chunks_enabled=True)
    fk = ("file", "/x", 10, 1)
    rc.put(ResultCache.chunk_key(fk, 0, "a", ("host", "v1")), b"v", 8,
           "host")
    rc.get(ResultCache.chunk_key(fk, 0, "a", ("host", "v1")))
    rc.get(ResultCache.chunk_key(fk, 1, "a", ("host", "v1")))  # miss
    reg = StatsRegistry()
    reg.add_cache(rc.counters())
    tree = reg.as_dict()
    c = tree["cache"]
    assert set(c) == {"single_flight_waits", "host", "device"}
    for tier in ("host", "device"):
        assert set(c[tier]) == {
            "hits", "misses", "evictions", "invalidations", "rejected",
            "held_bytes", "capacity_bytes", "entries", "evict_files",
            "budget_knob"}
    assert c["host"]["budget_knob"] == "TPQ_RESULT_CACHE_MB"
    assert c["device"]["budget_knob"] == "TPQ_RESULT_CACHE_HBM_MB"
    assert c["host"]["hits"] == 1 and c["host"]["misses"] == 1
    assert c["host"]["held_bytes"] == 8 and c["host"]["entries"] == 1
    json.dumps(tree)
    # merge: flows add, gauges max — twice the same tree doubles hits but
    # never the held bytes/capacity/entry gauges
    other = StatsRegistry()
    other.merge_dict(tree)
    other.merge_dict(tree)
    t2 = other.as_dict()["cache"]
    assert t2["host"]["hits"] == 2 and t2["host"]["misses"] == 2
    assert t2["host"]["held_bytes"] == c["host"]["held_bytes"]
    assert t2["host"]["capacity_bytes"] == c["host"]["capacity_bytes"]
    assert t2["host"]["entries"] == c["host"]["entries"]
    assert t2["host"]["evict_files"] == {}


def test_registry_merge_recomputes_derived_ratios():
    """bench_device merges one registry per FILE of a config: the composed
    tree's ratios must come from the merged flows, not a sum of per-file
    ratios (4 files at overlap 1.5 is still overlap 1.5, not 6.0)."""
    from tpu_parquet.device_reader import ReaderStats

    def one_file():
        reg = StatsRegistry()
        rs = ReaderStats()
        rs.rows = 1000
        rs.compressed_bytes = 8000
        rs.pages = 6
        rs.chunks = 2
        rs.wall_seconds = 2.0
        reg.add_reader(rs)
        ps = PipelineStats()
        ps.add("io", 1.0)
        ps.add("stage", 0.5)
        ps.wall_seconds = 1.0
        reg.add_pipeline(ps)
        return reg

    merged = one_file()
    for _ in range(3):
        merged.merge_from(one_file())
    t = merged.as_dict()
    assert t["pipeline"]["wall_seconds"] == pytest.approx(4.0)
    assert t["pipeline"]["overlap_efficiency"] == pytest.approx(1.5)
    assert t["reader"]["rows_per_sec"] == pytest.approx(4000 / 8.0)
    assert t["reader"]["bytes_per_sec"] == pytest.approx(32000 / 8.0)
    assert t["reader"]["pages_per_chunk"] == pytest.approx(3.0)


def test_alloc_peak_tracked_without_budget():
    """The default max_memory=0 configuration must still report the alloc
    high-water mark — that's the configuration the registry observes most."""
    from tpu_parquet.alloc import AllocTracker

    al = AllocTracker(0)
    al.register(1000)
    al.register(2000)
    al.release(2000)
    al.register(500)
    assert al.peak == 3000
    reg = StatsRegistry()
    reg.note_alloc_peak(al)
    assert reg.as_dict()["alloc"]["peak_bytes"] == 3000


def test_trace_summary_sums_walls_across_pipelines():
    """One trace often carries several PipelineStats (one per file of a
    scan): the overlap denominator is the SUM of each pipeline's own max
    wall, not the max across all of them."""
    tr = Tracer()
    for wall in (1.0, 3.0):
        ps = PipelineStats(tracer=tr)
        ps.add("io", wall / 2)
        ps._t0 = time.perf_counter() - wall  # synthetic elapsed wall
        ps.touch_wall()
        # cumulative counters from one stats object: only its max counts
        ps.touch_wall()
    s = trace_summary(tr.export())
    assert s["wall_seconds"] == pytest.approx(4.0, rel=0.05)


def test_pipeline_as_dict_golden_keys():
    d = PipelineStats().as_dict()
    assert set(d) == {
        "prefetch", "budget_bytes", "chunks", "row_groups",
        "io_seconds", "decompress_seconds", "recompress_seconds",
        "stage_seconds", "dispatch_seconds", "finalize_seconds",
        "busy_seconds", "wall_seconds", "stall_seconds",
        "peak_in_flight_bytes", "overlap_efficiency", "stage_histograms",
    }


def test_reader_stats_as_dict_golden_keys():
    from tpu_parquet.device_reader import ReaderStats

    rs = ReaderStats()
    rs.count_route("plain", 10, 10, 0.5, 0.25)
    d = rs.as_dict()
    assert set(d) == {
        "row_groups", "chunks", "pages", "pages_device_expanded",
        "pages_pruned", "rows", "compressed_bytes", "staged_bytes",
        "link_bytes_logical", "link_bytes_shipped", "ship_routes",
        "planner_link_mbps", "host_seconds", "stage_seconds",
        "dispatch_seconds",
        "wall_seconds", "rows_per_sec", "bytes_per_sec", "pages_per_chunk",
        "fused_fallbacks",
    }
    assert set(d["ship_routes"]["plain"]) == {
        "streams", "logical", "shipped", "predicted_s",
        "predicted_device_s", "predicted_unfused_device_s"}
    assert d["ship_routes"]["plain"]["predicted_s"] == 0.5
    assert d["ship_routes"]["plain"]["predicted_device_s"] == 0.25


def test_loader_stats_as_dict_golden_keys():
    from tpu_parquet.data.loader import LoaderStats

    d = LoaderStats(PipelineStats()).as_dict()
    assert set(d) == {
        "batches", "rows", "epochs_completed", "padded_batches",
        "wall_seconds", "decode_wait_seconds", "window_peak_rows",
        "data_errors", "units_skipped", "rows_skipped",
        "rows_per_sec", "batches_per_sec", "pipeline",
    }


# ---------------------------------------------------------------------------
# concurrency (satellite): >= 8 threads, then a 2-OS-process round trip
# ---------------------------------------------------------------------------

def test_tracer_histogram_hammer_8_threads():
    tr = Tracer()
    hist = LatencyHistogram()
    ps = PipelineStats(tracer=tr)
    N, T = 200, 8
    barrier = threading.Barrier(T)

    def worker(k):
        barrier.wait()
        for i in range(N):
            with tr.span("work", thread=k):
                hist.record(1e-6 * (i + 1))
            tr.instant("tick")
            ps.add("decompress", 1e-6)

    threads = [threading.Thread(target=worker, args=(k,)) for k in range(T)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    events = tr.events()
    xs = [e for e in events if e["ph"] == "X"]
    instants = [e for e in events if e["ph"] == "i"]
    metas = [e for e in events if e["ph"] == "M"]
    assert len(xs) == T * N          # no lost span
    assert len(instants) == T * N    # no lost instant
    assert len(metas) == T           # one thread_name per worker
    assert hist.count == T * N       # no lost histogram update
    assert ps.stage_seconds("decompress") == pytest.approx(T * N * 1e-6)
    assert ps.as_dict()["stage_histograms"]["decompress"]["count"] == T * N
    _assert_event_fields(events)
    s = trace_summary(tr.export())
    assert s["stages"]["work"]["count"] == T * N


_CHILD = r"""
import json, sys
sys.path.insert(0, sys.argv[1])
from tpu_parquet.obs import LatencyHistogram, StatsRegistry, Tracer
from tpu_parquet.pipeline import PipelineStats

tr = Tracer()
ps = PipelineStats(tracer=tr)
h = LatencyHistogram()
for i in range(500):
    with ps.timed("io"):
        pass
    h.record(2e-6)
reg = StatsRegistry()
reg.add_pipeline(ps)
print(json.dumps({
    "hist": h.as_dict(),
    "events": tr.events(),
    "registry": reg.as_dict(),
}))
"""


def test_two_process_merge_roundtrip(tmp_path):
    """The loader-resume-shaped 2-OS-process seam: each child records 500
    spans + histogram samples, the parent merges both children through the
    serialized forms — no lost updates, and the merged trace exports a
    document trace_summary still parses."""
    outs = []
    for _ in range(2):
        res = subprocess.run(
            [sys.executable, "-c", _CHILD, REPO_ROOT],
            capture_output=True, text=True, timeout=120,
        )
        assert res.returncode == 0, res.stderr
        outs.append(json.loads(res.stdout))
    hist = LatencyHistogram()
    reg = StatsRegistry()
    tr = Tracer()
    for o in outs:
        hist.merge_dict(o["hist"])
        reg.merge_dict(o["registry"])
        tr.merge_events(o["events"])
    assert hist.count == 1000
    assert hist.sum_seconds == pytest.approx(1000 * 2e-6, rel=1e-6)
    tree = reg.as_dict()
    assert tree["pipeline"]["chunks"] == 0
    assert tree["histograms"]["stage.io"]["count"] == 1000
    events = tr.events()
    assert len([e for e in events if e["ph"] == "X"]) == 1000
    assert len({e["pid"] for e in events}) == 2  # two process tracks
    s = trace_summary(tr.export(registry=reg))
    assert s["stages"]["io"]["count"] == 1000
    _assert_event_fields(events)


# ---------------------------------------------------------------------------
# overhead guard (satellite, tier-1): disabled spans are no-ops
# ---------------------------------------------------------------------------

def test_disabled_tracer_overhead_under_3_percent():
    """The hot decode loop keeps its trace calls unconditionally; the
    disabled-tracer path (spans compiled to no-ops, instants one ``if``)
    must cost <3% against the identical loop with those calls absent.  Both
    sides keep the pre-obs ``PipelineStats.timed`` counters — the "build
    with obs calls absent" is the pre-obs build, which already paid them.
    Interleaved min-of-reps: the minimum is the contention-free cost on a
    noisy VM."""
    import gc

    # the span/ctx allocations trigger gc passes that scan whatever object
    # graphs NEIGHBORING tests left alive — an environment artifact, not
    # tracer cost; a microbenchmark pins the collector like it pins the CPU
    gc.collect()
    gc.disable()
    # ring=None: this guards the PURE no-op path (spans compiled away);
    # the always-on ring tee has its own <3% guard in tests/test_autopsy.py
    tr = Tracer(enabled=False, ring=None)
    ps = PipelineStats(tracer=tr)
    rng = np.random.default_rng(0)
    data = rng.integers(0, 1 << 40, 300_000)

    def work():
        return np.sort(data).sum()

    def once(with_obs):
        t0 = time.perf_counter()
        if with_obs:
            with tr.span("chunk", rg=0):
                with ps.timed("decompress"):
                    work()
            tr.instant("ship", route="plain")
        else:
            with ps.timed("decompress"):
                work()
        return time.perf_counter() - t0

    try:
        for _ in range(3):  # warm caches / allocator
            once(True), once(False)
        base, obs = [], []
        for _ in range(80):
            obs.append(once(True))
            base.append(once(False))
    finally:
        gc.enable()
    assert tr.events() == []  # truly disabled
    # Estimator: median of PAIRED adjacent differences over the interleaved
    # iterations.  Suite-level contention (another test's leftover threads,
    # a periodic scavenger) inflates both halves of an adjacent pair about
    # equally, so the difference cancels the common-mode noise that made
    # min-of-aggregates (and even min-of-iterations) flaky in-suite; the
    # median then discards the pairs a context switch split.
    diffs = sorted(o - b for o, b in zip(obs, base))
    med_diff = diffs[len(diffs) // 2]
    med_base = sorted(base)[len(base) // 2]
    overhead = med_diff / med_base
    assert overhead < 0.03, f"disabled-tracer overhead {overhead:.2%}"
    # absolute backstop, independent of the work's size: a disabled span
    # plus instant costs well under 10 µs even on a loaded VM
    n = 10_000
    t0 = time.perf_counter()
    for _ in range(n):
        with tr.span("chunk"):
            pass
        tr.instant("ship")
    per_call = (time.perf_counter() - t0) / n
    assert per_call < 10e-6, f"null span+instant {per_call * 1e6:.2f} us"


# ---------------------------------------------------------------------------
# end-to-end wiring: readers, loader, pq_tool trace
# ---------------------------------------------------------------------------

def test_filereader_trace_end_to_end(tmp_path):
    """FileReader(prefetch=4, trace=path): the close() artifact is a valid
    trace-event document whose pq_tool-computed overlap efficiency matches
    pipeline_stats() within 5% (the acceptance tolerance)."""
    path = _write_ints(str(tmp_path / "f.parquet"))
    tp = str(tmp_path / "trace.json")
    from tpu_parquet.reader import FileReader

    with FileReader(path, prefetch=4, trace=tp) as r:
        r.read_all()
        pd = r.pipeline_stats().as_dict()
    doc = json.loads(open(tp).read())
    events = doc["traceEvents"]
    _assert_event_fields(events)
    _assert_nesting(events)
    names = {e["name"] for e in events if e["ph"] == "X"}
    assert {"io", "decompress"} <= names
    s = trace_summary(doc)
    assert s["busy_seconds"] == pytest.approx(pd["busy_seconds"], rel=0.02)
    assert s["overlap_efficiency"] == pytest.approx(
        pd["overlap_efficiency"], rel=0.05)
    # the registry rides the same artifact
    reg = doc["otherData"]["registry"]
    assert reg["obs_version"] == OBS_VERSION
    assert reg["pipeline"]["chunks"] == pd["chunks"]


def test_device_reader_trace_ship_feedback(tmp_path):
    """DeviceFileReader(trace=path): stage/dispatch/finalize spans per row
    group plus one `ship` instant per stream carrying the route and the
    planner's predicted seconds — the pq_tool route table reports
    predicted-vs-measured lane seconds from the artifact alone."""
    path = _write_ints(str(tmp_path / "d.parquet"))
    tp = str(tmp_path / "trace.json")
    from tpu_parquet.device_reader import DeviceFileReader

    with DeviceFileReader(path, trace=tp) as r:
        for _ in r.iter_row_groups():
            pass
        st = r.stats().as_dict()
        tree = r.obs_registry().as_dict()
    doc = json.loads(open(tp).read())
    names = {e["name"] for e in doc["traceEvents"] if e["ph"] == "X"}
    assert {"prepare", "stage", "dispatch", "finalize"} <= names
    ships = [e for e in doc["traceEvents"]
             if e["ph"] == "i" and e["name"] == "ship"]
    assert len(ships) == sum(c["streams"]
                             for c in st["ship_routes"].values())
    for ev in ships:
        assert {"route", "column", "logical", "shipped",
                "predicted_s"} <= set(ev["args"])
    s = trace_summary(doc)
    assert set(s["routes"]) == set(st["ship_routes"])
    for route, rr in s["routes"].items():
        assert rr["shipped_bytes"] == st["ship_routes"][route]["shipped"]
        assert rr["measured_seconds"] > 0  # the stage spans carried bytes
    # registry-side feedback agrees with the trace-side aggregation
    fb = tree["reader"]["ship_feedback"]["routes"]
    for route, rr in s["routes"].items():
        assert fb[route]["predicted_seconds"] == pytest.approx(
            rr["predicted_seconds"], abs=2e-5)


def test_loader_trace_spans(tmp_path):
    path = _write_ints(str(tmp_path / "l.parquet"), rows=40_000, groups=4)
    tp = str(tmp_path / "trace.json")
    from tpu_parquet.data import DataLoader

    loader = DataLoader(path, 4096, shuffle=True, seed=3, prefetch=2,
                        shuffle_window=8192, trace=tp)
    n = sum(1 for _ in loader)
    assert n == loader.num_batches
    tr = loader._tracer
    events = tr.events()
    names = {e["name"] for e in events if e["ph"] == "X"}
    assert {"batch", "decode_wait"} <= names
    counters = [e for e in events
                if e["ph"] == "C" and e["name"] == "shuffle_window_rows"]
    assert counters and all(e["args"]["rows"] > 0 for e in counters)
    batches = [e for e in events if e["ph"] == "X" and e["name"] == "batch"]
    assert len(batches) == n
    assert sum(e["args"]["rows"] for e in batches) == loader.num_rows
    tree = loader.obs_registry().as_dict()
    assert tree["loader"]["batches"] == n
    assert tree["pipeline"]["chunks"] > 0  # decode pipeline composed in
    # iteration end IS the loader's close: the artifact (with the registry
    # embedded) must exist without waiting for interpreter exit
    doc = json.loads(open(tp).read())
    assert doc["traceEvents"]
    assert doc["otherData"]["registry"]["loader"]["batches"] == n


def test_tpq_trace_env_activates_readers(tmp_path, monkeypatch):
    """TPQ_TRACE alone (no kwargs) routes every reader's spans to the
    process tracer — the bench/driver activation path."""
    path = _write_ints(str(tmp_path / "e.parquet"), rows=20_000, groups=2)
    p = str(tmp_path / "env_trace.json")
    monkeypatch.setenv("TPQ_TRACE", p)
    from tpu_parquet.reader import FileReader

    tr = current_tracer()
    before = len(tr.events())
    with FileReader(path, prefetch=2) as r:
        r.read_all()
    events = tr.events()[before:]
    assert {e["name"] for e in events if e["ph"] == "X"} >= {"io",
                                                             "decompress"}
    tr.write()
    assert json.loads(open(p).read())["traceEvents"]


def test_pq_tool_trace_cli(tmp_path):
    """`pq_tool trace` renders the per-stage table, overlap, stall and
    route lines from the artifact alone."""
    path = _write_ints(str(tmp_path / "c.parquet"))
    tp = str(tmp_path / "trace.json")
    from tpu_parquet.cli import pq_tool
    from tpu_parquet.device_reader import DeviceFileReader

    with DeviceFileReader(path, prefetch=2, trace=tp) as r:
        for _ in r.iter_row_groups():
            pass
        pd = r.pipeline_stats().as_dict()
    out = io.StringIO()
    args = pq_tool.build_parser().parse_args(["trace", tp])
    assert args.func(args, out=out) == 0
    text = out.getvalue()
    assert "overlap efficiency:" in text
    assert "stall:" in text
    assert "p50_ms" in text and "p95_ms" in text
    assert "ship routes" in text and "predicted_s" in text
    assert "embedded registry: obs_version=1" in text
    # the printed overlap matches pipeline_stats() within the 5% acceptance
    line = next(l for l in text.splitlines()
                if l.startswith("overlap efficiency:"))
    got = float(line.rsplit("= ", 1)[1])
    assert got == pytest.approx(pd["overlap_efficiency"], rel=0.05)


def test_pq_tool_trace_malformed(tmp_path):
    from tpu_parquet.cli import pq_tool

    bad = tmp_path / "bad.json"
    bad.write_text("{not json")
    assert pq_tool.main(["trace", str(bad)]) == 1
    notrace = tmp_path / "no.json"
    notrace.write_text('{"foo": 1}')
    assert pq_tool.main(["trace", str(notrace)]) == 1


# ---------------------------------------------------------------------------
# counter sampler (ISSUE 5 tentpole piece 1)
# ---------------------------------------------------------------------------

def test_sampler_inert_when_disabled():
    """Callers wire the sampler unconditionally: a disabled tracer or a 0
    interval must mean NO thread, ever — start/stop are free no-ops."""
    for sampler in (Sampler(Tracer(enabled=False), 5.0),
                    Sampler(Tracer(), 0.0),
                    Sampler(None, 5.0)):
        assert not sampler.enabled
        sampler.add_source("x", lambda: {"v": 1})
        assert sampler.start() is sampler
        assert sampler._thread is None
        sampler.stop()
        sampler.stop()  # idempotent


def test_resolve_sample_ms_forms(monkeypatch):
    monkeypatch.delenv("TPQ_SAMPLE_MS", raising=False)
    assert resolve_sample_ms() == 0.0
    assert resolve_sample_ms(7) == 7.0
    assert resolve_sample_ms(-3) == 0.0       # clamped, not negative-interval
    assert resolve_sample_ms("bogus") == 0.0  # unparseable kwarg disables
    monkeypatch.setenv("TPQ_SAMPLE_MS", "12.5")
    assert resolve_sample_ms() == 12.5
    assert resolve_sample_ms(5) == 5.0        # kwarg wins over the env
    monkeypatch.setenv("TPQ_SAMPLE_MS", "junk")
    assert resolve_sample_ms() == 0.0


def test_sampler_ticks_counters_and_joins():
    """Counter tracks appear per tick, non-numeric values are filtered, the
    final stop() sample lands the end state, and the thread is joined —
    the thread-leak guard the satellite names."""
    tr = Tracer()
    calls = {"n": 0}

    def src():
        calls["n"] += 1
        return {"count": calls["n"], "label": "str", "flag": True}

    s = Sampler(tr, 2.0, name="tpq-test-sampler")
    s.add_source("prog", src)
    with s:
        assert s.enabled and s._thread is not None
        time.sleep(0.05)
    assert s._thread is None  # joined, not abandoned
    assert all(t.name != "tpq-test-sampler" for t in threading.enumerate())
    events = [e for e in tr.events() if e["ph"] == "C" and e["name"] == "prog"]
    assert len(events) >= 2  # several ticks plus the final stop sample
    for e in events:
        assert set(e["args"]) == {"count"}  # str/bool filtered out
    counts = [e["args"]["count"] for e in events]
    assert counts == sorted(counts)
    assert counts[-1] == calls["n"]  # the last sample IS the end state
    _assert_event_fields(tr.events())
    # restartable after stop (a second epoch reuses the same sampler)
    with s:
        time.sleep(0.006)
    assert s._thread is None


def test_sampler_source_exception_dropped():
    """A raising source is dropped for the tick, never takes the run (or
    the other sources) down."""
    tr = Tracer()
    s = Sampler(tr, 1.0)
    s.add_source("bad", lambda: 1 // 0)
    s.add_source("good", lambda: {"v": 1})
    with s:
        time.sleep(0.02)
    assert s.dropped >= 1
    names = {e["name"] for e in tr.events() if e["ph"] == "C"}
    assert names == {"good"}


def test_sampler_overhead_under_2_percent():
    """The satellite's guard: sampling at the 5 ms cadence consumes <2% of
    a core — per-tick cost over realistic sources (pipeline lanes, reader
    progress, alloc watermarks) bounded against the interval, plus a no-
    spin check (the tick count tracks the cadence, not the CPU).

    Deliberately NOT a wall-clock A/B: on a 2-core cgroup-throttled CI box
    a NO-OP thread waking every 5 ms already costs ~15% in scheduler
    context switches — identical with or without the sampler's code, so an
    A/B would guard the box, not the sampler.  What the sampler itself
    does per tick is what this bounds."""
    from tpu_parquet.alloc import AllocTracker
    from tpu_parquet.device_reader import ReaderStats

    tr = Tracer()
    ps = PipelineStats()
    for stage in STAGES:
        ps.add(stage, 0.01)
    rs = ReaderStats()
    rs.count_route("plain", 1 << 20, 1 << 20, 0.001)
    al = AllocTracker(1 << 20)
    al.register(4096)
    s = Sampler(tr, 5.0, name="tpq-overhead-sampler")
    s.add_source("pipeline_lanes", ps.sample)
    s.add_source("reader_progress",
                 lambda: {"rows": rs.rows, "chunks": rs.chunks,
                          "staged_bytes": rs.staged_bytes})
    s.add_source("alloc_bytes",
                 lambda: dict(zip(("in_use", "peak"), al.snapshot())))
    for _ in range(50):  # warm
        s.sample_once()
    n = 2000
    t0 = time.perf_counter()
    for _ in range(n):
        s.sample_once()
    per_tick = (time.perf_counter() - t0) / n
    budget = 0.02 * (5.0 / 1e3)  # 2% of the 5 ms cadence
    assert per_tick < budget, (
        f"sample tick {per_tick * 1e6:.1f} us > 2% of the 5 ms cadence")
    # no-spin: the thread ticks at the cadence (each tick waits the full
    # interval), so a 60 ms window at 5 ms holds ~12 ticks, never hundreds
    s2 = Sampler(tr, 5.0).add_source("lanes", ps.sample)
    with s2:
        time.sleep(0.06)
    assert 2 <= s2.ticks <= 40, f"sampler spinning: {s2.ticks} ticks in 60ms"


def test_device_reader_sampler_tracks(tmp_path):
    """DeviceFileReader(sample_ms=): throughput/lane/watermark counter
    tracks ride the trace artifact; close() joins the thread (no leak) and
    the final sample carries the end-state totals."""
    path = _write_ints(str(tmp_path / "s.parquet"), rows=100_000, groups=4)
    tp = str(tmp_path / "trace.json")
    from tpu_parquet.device_reader import DeviceFileReader

    with DeviceFileReader(path, prefetch=2, trace=tp, sample_ms=2) as r:
        for _ in r.iter_row_groups():
            pass
        rows = r.stats().rows
    assert all(not t.name.startswith("tpq-sampler")
               for t in threading.enumerate())
    doc = json.loads(open(tp).read())
    tracks = {}
    for e in doc["traceEvents"]:
        if e["ph"] == "C":
            tracks.setdefault(e["name"], []).append(e["args"])
    assert {"reader_progress", "pipeline_lanes", "alloc_bytes"} <= set(tracks)
    # stop() before the artifact write: the curve's last point is the end
    assert tracks["reader_progress"][-1]["rows"] == rows
    lanes = tracks["pipeline_lanes"][-1]
    assert {"io", "decompress", "stage", "stall", "queue_depth"} <= set(lanes)
    assert lanes["queue_depth"] == 0  # drained at end
    # the source must follow the LIVE PipelineStats (iter_row_groups
    # replaces it per scan): a constructor-time binding samples flat zeros
    assert lanes["chunks"] > 0, "sampler froze on the pre-scan PipelineStats"
    assert {"in_use", "peak"} <= set(tracks["alloc_bytes"][-1])


def test_scan_files_sampler_per_reader_tracks(tmp_path):
    """Multi-file scans sample onto ONE shared tracer: each reader's
    counter events must carry a distinct Chrome track id (``(pid, name)``
    alone would interleave every reader's curves into one sawtooth), and
    every reader's FINAL queue_depth sample must be 0 — the shared
    prefetch window's ownership moves file to file, and prefetch_map's
    own end-of-run zero only ever reaches the last owner."""
    from tpu_parquet.device_reader import scan_files

    paths = [_write_ints(str(tmp_path / f"f{i}.parquet"),
                         rows=60_000, groups=3) for i in range(2)]
    tp = str(tmp_path / "scan_trace.json")
    for _ in scan_files(paths, prefetch=2, trace=tp, sample_ms=2):
        pass
    assert all(not t.name.startswith("tpq-sampler")
               for t in threading.enumerate())
    doc = json.loads(open(tp).read())
    per_id = {}
    for e in doc["traceEvents"]:
        if e["ph"] == "C" and e["name"] == "pipeline_lanes":
            per_id.setdefault(e.get("id"), []).append(e["args"])
    assert None not in per_id  # every sample names its reader's track
    assert len(per_id) == len(paths)
    for tid, samples in per_id.items():
        # the stop() tick at each reader's close is its curve's last point:
        # a nonzero here is the stale-gauge bug (a phantom backlog frozen
        # on every reader the end-of-run reset never reached)
        assert samples[-1]["queue_depth"] == 0, tid


def test_loader_sampler_tracks(tmp_path):
    path = _write_ints(str(tmp_path / "l.parquet"), rows=40_000, groups=4)
    from tpu_parquet.data import DataLoader

    loader = DataLoader(path, 4096, prefetch=2,
                        trace=str(tmp_path / "t.json"), sample_ms=2)
    n = sum(1 for _ in loader)
    assert all(not t.name.startswith("tpq-sampler")
               for t in threading.enumerate())
    events = loader._tracer.events()
    tracks = {e["name"] for e in events if e["ph"] == "C"}
    assert {"loader_progress", "pipeline_lanes"} <= tracks
    prog = [e["args"] for e in events
            if e["ph"] == "C" and e["name"] == "loader_progress"]
    assert prog[-1]["batches"] == n
    assert prog[-1]["rows"] == loader.num_rows


# ---------------------------------------------------------------------------
# ship_feedback null contract (satellite: zero-measured-spans case)
# ---------------------------------------------------------------------------

def test_ship_feedback_unmeasured_route_is_null():
    """A route chosen by the planner but never timed (forced route with
    tracing off: no staging seconds anywhere) reports measured_seconds /
    error_ratio null — not a divide-by-zero, not a bogus 0.0."""
    from tpu_parquet.device_reader import ReaderStats

    reg = StatsRegistry()
    rs = ReaderStats()
    rs.count_route("plain", 100, 100, 0.001)
    rs.staged_bytes = 100
    reg.add_reader(rs)  # no pipeline => no stage seconds => no link rate
    fb = reg.ship_feedback()
    assert fb["link_bytes_per_sec"] == 0.0
    r = fb["routes"]["plain"]
    assert r["measured_seconds"] is None
    assert r["error_ratio"] is None
    assert r["predicted_seconds"] == 0.001  # the prediction is still real
    json.dumps(fb)  # null survives the artifact round-trip


def test_ship_feedback_tiny_measured_not_rounded_to_zero():
    """A 100-byte stream on a ~1 GB/s link measures ~1e-7s: display
    rounding must not flatten it to 0.0 (the bogus 'infinitely fast' value
    the null contract rules out) — the ratio is computed on raw values."""
    from tpu_parquet.device_reader import ReaderStats

    reg = StatsRegistry()
    rs = ReaderStats()
    rs.count_route("plain", 100, 100, 1e-7)  # one tiny stream of a big run
    rs.staged_bytes = 1 << 30
    reg.add_reader(rs)
    ps = PipelineStats()
    ps.add("stage", (1 << 30) / 1e9)  # link rate ~1e9 B/s
    reg.add_pipeline(ps)
    r = reg.ship_feedback()["routes"]["plain"]
    assert r["measured_seconds"] == pytest.approx(1e-7)
    assert r["measured_seconds"] != 0.0
    assert r["error_ratio"] == pytest.approx(1.0)


def test_trace_summary_routes_unmeasured_null():
    """Same contract on the trace-side aggregation: ship instants with no
    stage spans yield null measured/error, keys present."""
    tr = Tracer()
    tr.instant("ship", route="plain", column="v", logical=100, shipped=100,
               predicted_s=0.002)
    s = trace_summary(tr.export())
    r = s["routes"]["plain"]
    assert r["measured_seconds"] is None
    assert r["error_ratio"] is None
    assert r["predicted_seconds"] == pytest.approx(0.002)


# ---------------------------------------------------------------------------
# pq_tool trace diagnostics (satellite: diagnose, don't traceback)
# ---------------------------------------------------------------------------

def test_pq_tool_trace_zero_spans_diagnosed(tmp_path):
    from tpu_parquet.cli import pq_tool

    p = str(tmp_path / "empty.json")
    Tracer().write(p)  # valid artifact, zero spans
    out = io.StringIO()
    args = pq_tool.build_parser().parse_args(["trace", p])
    assert args.func(args, out=out) == 1
    text = out.getvalue()
    assert "no spans recorded" in text
    assert text.count("\n") == 1  # one-line diagnosis, not a zero table


def test_pq_tool_trace_missing_registry_diagnosed(tmp_path):
    from tpu_parquet.cli import pq_tool

    tr = Tracer()
    with tr.span("io"):
        pass
    p = str(tmp_path / "noreg.json")
    tr.write(p)  # spans, but no embedded registry
    out = io.StringIO()
    args = pq_tool.build_parser().parse_args(["trace", p])
    assert args.func(args, out=out) == 1
    assert "no embedded registry" in out.getvalue()


# ---------------------------------------------------------------------------
# doctor on a real traced run (the acceptance criterion)
# ---------------------------------------------------------------------------

def test_doctor_on_traced_run_matches_registry(tmp_path):
    """`pq_tool doctor` on a traced run names a bottleneck lane consistent
    with the embedded registry's stage seconds: the dominant lane is the
    recomputed max and its share matches within 10%."""
    from tpu_parquet.cli import pq_tool
    from tpu_parquet.device_reader import DeviceFileReader

    path = _write_ints(str(tmp_path / "doc.parquet"))
    tp = str(tmp_path / "trace.json")
    with DeviceFileReader(path, prefetch=2, trace=tp) as r:
        for _ in r.iter_row_groups():
            pass
    tree = json.loads(open(tp).read())["otherData"]["registry"]
    rep = doctor_registry(tree)
    assert rep is not None
    # recompute the lanes independently from the embedded registry (the
    # device lanes come from the measured `device` section when present)
    pipe = tree["pipeline"]
    dev = tree.get("device") or {}

    def g(k):
        return float(pipe.get(k) or 0.0)

    dev_resolve = sum(float(c.get("device_seconds") or 0.0)
                      for c in (dev.get("routes") or {}).values())
    lanes = {
        "link": g("stage_seconds"),
        "host_decompress": (g("io_seconds") + g("decompress_seconds")
                            + g("recompress_seconds")),
        "device_resolve": dev_resolve or (g("dispatch_seconds")
                                          + g("finalize_seconds")),
        "h2d": float((dev.get("h2d") or {}).get("device_seconds") or 0.0),
        "stall": g("stall_seconds"),
    }
    dominant = max(lanes, key=lanes.get)
    assert rep["dominant_lane"] == dominant
    # doctor rounds lane seconds to 6 decimals for the report
    assert rep["lanes"][dominant] == pytest.approx(lanes[dominant], abs=1e-6)
    assert rep["dominant_share"] == pytest.approx(
        lanes[dominant] / sum(lanes.values()), rel=0.10)
    # the CLI renders the same verdict from the artifact alone
    out = io.StringIO()
    args = pq_tool.build_parser().parse_args(["doctor", tp])
    assert args.func(args, out=out) == 0
    assert f"verdict: {rep['verdict']}" in out.getvalue()


# ---------------------------------------------------------------------------
# bench artifact (satellite): compact stdout line stays parseable
# ---------------------------------------------------------------------------

def test_bench_summary_line_under_2000_chars(tmp_path, monkeypatch, capsys):
    """The r04/r05 `parsed: null` bug class: even with the obs registry
    trees (histograms included) in every config, the stdout LAST line must
    stay under the driver's 2000-char tail window and parse as JSON."""
    import bench

    monkeypatch.setenv("BENCH_JSON", str(tmp_path / "b.json"))
    tree = _full_registry().as_dict()
    record = {
        "metric": "lineitem16_decode_rows_per_sec_device",
        "value": 1.0e7, "unit": "rows/s", "vs_baseline": 9.9,
        # the round-10 ledger/check fields ride the compact line as a few
        # chars each, never as their full entries
        "ledger": {"path": "/long/path/to/some/runs/dir/ledger.jsonl",
                   "seq": 12},
        "check": {"baseline": "BENCH_LOCAL_r08.json", "floor": 0.3,
                  "compared": 42, "regressions": [], "improvements": [],
                  "incomparable": []},
        "configs": {
            name: {
                "rows": 5_000_000, "device_rows_per_sec": 1e7,
                "device_vs_host": 9.9, "link_bytes_shipped": 12345,
                "link_bytes_logical": 23456, "link_bytes_ratio": 0.52,
                "obs": tree,
                "device_windows_s": [[0.5] * 8] * 3,
            }
            for name in ("lineitem16", "plain_int64", "delta_ints",
                         "dict_strings", "nested", "loader", "pipeline")
        },
    }
    bench.emit_results(record)
    outline = capsys.readouterr().out.strip().splitlines()[-1]
    assert len(outline) < 2000
    parsed = json.loads(outline)
    assert parsed["metric"] == record["metric"]
    assert parsed["ledger"] == "ledger.jsonl#12"
    assert parsed["check"] == "ok (42 compared)"
    assert "obs" not in json.dumps(parsed)  # trees live only in the artifact
    # the artifact keeps the full trees, histograms included
    art = json.loads((tmp_path / "b.json").read_text())
    assert art["configs"]["lineitem16"]["obs"]["histograms"]
