"""Async fetch engine tests (ISSUE 18): threaded-vs-engine bit-identity
across the fault matrix, the 256-range stall-storm concurrency claim,
close()/cancel hygiene (no leaked threads, every waiter woken), the hedge
race on the async path, per-tenant default deadlines, and the
``io-concurrency-bound`` doctor verdict.

The acceptance contract: the whole fault matrix holds bit-identically on
the engine path at every prefetch depth; in-flight IO is bounded only by
``TPQ_IO_INFLIGHT`` (one loop thread, hundreds of in-flight ranges); and
the engine cleans up after itself — a closed engine leaves no ``tpq-fetch``
thread and an unfinished fetch's future always settles.
"""

import contextlib
import os
import threading
import time

import numpy as np
import pytest

from tpu_parquet.errors import (CancelledError, DeadlineExceededError,
                                RetryExhaustedError, TransientIOError)
from tpu_parquet.iostore import (FaultInjectingStore, FaultSpec,
                                 GenericRangeStore, IOConfig, LocalStore,
                                 RetryBudget, ScanToken)
from tpu_parquet.iostore_async import (FetchEngine, default_engine_if_running,
                                       engine_enabled, engine_for_store,
                                       get_default_engine,
                                       shutdown_default_engine)
from tpu_parquet.reader import FileReader
from tpu_parquet.resilience import CancelToken
from tpu_parquet.writer import FileWriter


def _write_file(path, groups=3, rows=400, seed=0):
    from tpu_parquet.format import (CompressionCodec,
                                    FieldRepetitionType as FRT, Type)
    from tpu_parquet.schema.core import build_schema, data_column

    schema = build_schema([data_column("a", Type.INT64, FRT.REQUIRED),
                           data_column("b", Type.INT64, FRT.REQUIRED)])
    rng = np.random.default_rng(seed)
    with FileWriter(path, schema, codec=CompressionCodec.SNAPPY) as w:
        for _ in range(groups):
            w.write_columns({"a": rng.integers(0, 1 << 30, rows),
                             "b": rng.integers(0, 1 << 30, rows)})
            w.flush_row_group()
    return path


@pytest.fixture(scope="module")
def pq_file(tmp_path_factory):
    path = str(tmp_path_factory.mktemp("fetch_engine") / "faulty.parquet")
    _write_file(path)
    with FileReader(path) as r:
        base = r.read_pylist()
    return path, base


def _cfg(**kw):
    kw.setdefault("retries", 4)
    kw.setdefault("backoff_ms", 1.0)
    return IOConfig(**kw)


def _fault_factory(spec, config=None, stores=None, seed=0):
    def make(f):
        st = FaultInjectingStore(LocalStore(f), spec,
                                 config=config or _cfg(), seed=seed)
        if stores is not None:
            stores.append(st)
        return st

    return make


def _engine_threads():
    return [t.name for t in threading.enumerate()
            if t.name.startswith("tpq-fetch")]


@pytest.fixture(autouse=True)
def _fresh_default_engine():
    # the default engine is process-global and its stats are cumulative;
    # start each test from a dead engine so threaded-mode registries stay
    # engine-free and leak asserts see only threads the test itself made
    shutdown_default_engine()
    yield
    shutdown_default_engine()


# ---------------------------------------------------------------------------
# bit-identity: fault matrix x {threaded, async} x prefetch depth
# ---------------------------------------------------------------------------

RECOVERABLE = {
    "latency_spike": FaultSpec(latency_s=0.005),
    "transient_errors": FaultSpec(fail_first=2),
    "torn_read": FaultSpec(torn_first=1),
    "torn_then_error": FaultSpec(torn_first=1, fail_first=2),
}


@pytest.mark.parametrize("prefetch", [0, 4])
@pytest.mark.parametrize("fault", sorted(RECOVERABLE))
def test_fault_matrix_threaded_vs_async_bit_identical(
        pq_file, fault, prefetch, monkeypatch):
    """The same faulted file decodes to the same rows on both IO paths,
    with the same recovery counters — the engine reimplements the retry
    loop, it does not reinterpret it."""
    path, base = pq_file
    trees = {}
    for mode, env in (("threaded", "0"), ("async", "1")):
        monkeypatch.setenv("TPQ_IO_ASYNC", env)
        stores = []
        with FileReader(path, prefetch=prefetch,
                        store=_fault_factory(RECOVERABLE[fault],
                                             stores=stores)) as r:
            assert r.read_pylist() == base, f"{mode} path diverged"
            trees[mode] = r.obs_registry().as_dict()["io"]
        assert (engine_for_store(stores[0]) is not None) == (mode == "async")
    for mode, d in trees.items():
        assert d["exhausted"] == 0, mode
        if "transient" in fault or "error" in fault:
            assert d["retries"] > 0 and d["transient_errors"] > 0, mode
        if fault.startswith("torn"):
            assert d["short_reads"] > 0, mode
    # the engine path reports itself: with a prefetch window the engine
    # feed carries the ranges and the io section grows an engine subtree
    # with a reconciling ledger (prefetch=0 keeps the serial sync path,
    # and the threaded mode never has one)
    assert "engine" not in trees["threaded"]
    if prefetch > 0:
        eng = trees["async"]["engine"]
        assert eng["submitted"] > 0
        assert eng["completed"] + eng["failed"] == eng["submitted"]
        assert eng["inflight"] == 0


@pytest.mark.parametrize("prefetch", [0, 4])
def test_exhaustion_identical_on_async_path(pq_file, prefetch, monkeypatch):
    """Terminal verdicts match too: same error type, same attempt log
    shape, byte-identical attempt messages either way."""
    path, _base = pq_file

    def run(env):
        monkeypatch.setenv("TPQ_IO_ASYNC", env)
        with pytest.raises(RetryExhaustedError) as ei:
            with FileReader(path, prefetch=prefetch,
                            store=_fault_factory(
                                FaultSpec(fail_first=99),
                                config=_cfg(retries=2))) as r:
                r.read_all()
        return ei.value

    threaded, eng = run("0"), run("1")
    assert len(threaded.attempts) == len(eng.attempts) == 3
    assert ([a["error"] for a in threaded.attempts]
            == [a["error"] for a in eng.attempts])
    assert (threaded.offset, threaded.size) == (eng.offset, eng.size)


def test_kill_switch_and_inflight_zero_disable_routing(monkeypatch):
    monkeypatch.setenv("TPQ_IO_ASYNC", "0")
    assert not engine_enabled()
    monkeypatch.setenv("TPQ_IO_ASYNC", "1")
    assert engine_enabled()
    monkeypatch.setenv("TPQ_IO_INFLIGHT", "0")
    assert not engine_enabled()
    monkeypatch.delenv("TPQ_IO_INFLIGHT")
    # LocalStore keeps its zero-overhead pread path: never routed
    with open(__file__, "rb") as f:
        assert engine_for_store(LocalStore(f)) is None


# ---------------------------------------------------------------------------
# the concurrency claim: hundreds in flight, one thread
# ---------------------------------------------------------------------------

def test_stall_storm_256_ranges_one_thread(tmp_path):
    """256 ranges through a 50ms-latency store complete in ~one latency
    (not 256 x 50ms), with the in-flight peak at the cap and exactly one
    engine thread doing it."""
    blob = np.random.default_rng(7).integers(
        0, 256, 1 << 20, dtype=np.uint8).tobytes()
    path = tmp_path / "blob.bin"
    path.write_bytes(blob)
    with open(path, "rb") as f:
        st = FaultInjectingStore(LocalStore(f), FaultSpec(latency_s=0.05),
                                 config=_cfg())
        eng = FetchEngine(max_inflight=256, name="tpq-fetch-test")
        try:
            ranges = [((i * 3571) % ((1 << 20) - 4096), 4096)
                      for i in range(256)]
            t0 = time.perf_counter()
            futs = [eng.submit(st, o, s) for o, s in ranges]
            got = [bytes(fu.result(timeout=60)) for fu in futs]
            wall = time.perf_counter() - t0
        finally:
            eng.close()
            st.close()
    assert got == [blob[o:o + s] for o, s in ranges]
    # serial would be 12.8s; generous 4s bound still proves overlap
    assert wall < 4.0, f"storm took {wall:.2f}s — ranges did not overlap"
    assert eng.stats.inflight_peak == 256
    assert eng.stats.completed == 256 and eng.stats.failed == 0
    assert not _engine_threads()


def test_inflight_capped_below_submission_depth(tmp_path):
    """A cap of 4 with 32 submissions: the gauge never passes 4, every
    range still completes, queue-wait is accounted."""
    path = tmp_path / "blob.bin"
    path.write_bytes(bytes(range(256)) * 64)
    with open(path, "rb") as f:
        st = FaultInjectingStore(LocalStore(f), FaultSpec(latency_s=0.01),
                                 config=_cfg())
        eng = FetchEngine(max_inflight=4, name="tpq-fetch-test")
        try:
            futs = [eng.submit(st, 64 * i, 64) for i in range(32)]
            for fu in futs:
                fu.result(timeout=60)
        finally:
            eng.close()
            st.close()
    assert eng.stats.inflight_peak <= 4
    assert eng.stats.completed == 32
    assert eng.stats.queue_wait_seconds > 0  # 28 ranges waited for a slot


# ---------------------------------------------------------------------------
# lifecycle hygiene: close() and cancel wake every waiter, leak nothing
# ---------------------------------------------------------------------------

def test_close_settles_inflight_futures_and_leaks_nothing(tmp_path):
    path = tmp_path / "blob.bin"
    path.write_bytes(b"\xAB" * 4096)
    with open(path, "rb") as f:
        st = FaultInjectingStore(LocalStore(f), FaultSpec(latency_s=30.0),
                                 config=_cfg(retries=0))
        eng = FetchEngine(max_inflight=2, name="tpq-fetch-test")
        futs = [eng.submit(st, 0, 64) for _ in range(4)]
        time.sleep(0.05)  # let the first two enter their stall
        t0 = time.perf_counter()
        eng.close(timeout=10)
        assert time.perf_counter() - t0 < 5.0
        for fu in futs:
            with contextlib.suppress(BaseException):
                fu.result(timeout=5)
            assert fu.done(), "close() left a waiter parked forever"
        st.close()
    assert not _engine_threads()
    st_ = eng.stats
    assert st_.completed + st_.failed == st_.submitted
    assert st_.inflight == 0


def test_cancel_wakes_inflight_fetches_promptly(tmp_path):
    """CancelToken.cancel() from another thread lands the typed verdict in
    well under the injected stall — the engine's cancel event interrupts
    the await, it does not wait the fault out."""
    path = tmp_path / "blob.bin"
    path.write_bytes(b"\xCD" * 4096)
    with open(path, "rb") as f:
        st = FaultInjectingStore(LocalStore(f), FaultSpec(latency_s=30.0),
                                 config=_cfg(retries=0))
        tok = CancelToken()
        scan = ScanToken(budget=RetryBudget(0), cancel=tok)
        eng = FetchEngine(max_inflight=8, name="tpq-fetch-test")
        try:
            futs = [eng.submit(st, 0, 64, scan=scan) for _ in range(6)]
            time.sleep(0.05)
            t0 = time.perf_counter()
            tok.cancel()
            for fu in futs:
                with pytest.raises(CancelledError):
                    fu.result(timeout=10)
            assert time.perf_counter() - t0 < 5.0
        finally:
            eng.close()
            st.close()
    assert not _engine_threads()
    assert eng.stats.failed == 6 and eng.stats.inflight == 0


def test_default_engine_replaced_after_shutdown(monkeypatch):
    monkeypatch.setenv("TPQ_IO_ASYNC", "1")
    eng = get_default_engine()
    assert get_default_engine() is eng
    shutdown_default_engine()
    assert default_engine_if_running() is None
    assert not _engine_threads()
    eng2 = get_default_engine()
    assert eng2 is not eng and not eng2.closed
    shutdown_default_engine()


# ---------------------------------------------------------------------------
# hedging on the async path
# ---------------------------------------------------------------------------

def test_hedge_win_preserved_on_async_path():
    """A store whose FIRST attempt per range stalls and whose duplicate
    returns fast: with hedging on, the engine's race wins long before the
    stall resolves, and the hedge counters say so."""
    import asyncio

    calls = {"n": 0}
    lock = threading.Lock()

    class SlowFirst(GenericRangeStore):
        def size(self):
            return 1 << 20

        async def _fetch_once_async(self, offset, size, timeout):
            with lock:
                calls["n"] += 1
                first = calls["n"] == 1
            if first:
                await asyncio.sleep(0.5)
            return b"\x5A" * size

    st = SlowFirst(config=_cfg(retries=0, hedge_ms=20.0, deadline_s=10.0))
    eng = FetchEngine(max_inflight=8, name="tpq-fetch-test")
    try:
        t0 = time.perf_counter()
        buf = eng.submit(st, 0, 512).result(timeout=10)
        wall = time.perf_counter() - t0
    finally:
        eng.close()
    assert bytes(buf) == b"\x5A" * 512
    assert wall < 0.4, f"hedge never raced: {wall:.3f}s"
    d = st.stats.as_dict()
    assert d["hedges_issued"] >= 1 and d["hedges_won"] >= 1
    assert st._hedges_outstanding == 0  # loser reaped
    assert not _engine_threads()


# ---------------------------------------------------------------------------
# per-tenant default deadlines (serve tier)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def serve_file(tmp_path_factory):
    path = str(tmp_path_factory.mktemp("fetch_serve") / "s.parquet")
    _write_file(path, groups=3, rows=300)
    return path


def test_tenant_default_deadline_inherited(serve_file):
    from tpu_parquet.serve import ScanRequest, ScanService

    svc = ScanService(
        concurrency=2, queue_depth=8,
        store=lambda f: FaultInjectingStore(
            LocalStore(f), FaultSpec(latency_s=0.06),
            config=IOConfig(backoff_ms=0)))
    try:
        t = svc.register_tenant("batch", weight=2, deadline_s=0.05)
        assert t.deadline_s == 0.05
        # no explicit deadline: the tenant default binds and expires
        with pytest.raises(DeadlineExceededError):
            svc.scan(ScanRequest(serve_file, tenant="batch"), timeout=30)
        # an explicit request deadline always outranks the default
        out = svc.scan(ScanRequest(serve_file, tenant="batch",
                                   deadline_s=60.0), timeout=60)
        assert len(out[serve_file]["a"].values) == 900
        # and the stats surface shows the configured default
        sv = svc.serve_stats()
        assert sv["tenants"]["batch"]["deadline_s"] == 0.05
        assert "deadline_s" not in sv["tenants"]["default"]
    finally:
        svc.close()


def test_tenant_deadline_from_spec_string(serve_file):
    from tpu_parquet.serve import ScanService
    from tpu_parquet.serve.tenancy import TenantRegistry

    reg = TenantRegistry(max_memory=1 << 20, spec="gold=4:2.5,bronze=1")
    assert reg.get("gold").deadline_s == 2.5
    assert reg.get("gold").weight == 4
    assert reg.get("bronze").deadline_s is None
    with ScanService(concurrency=1, tenants="slo=2:1.5") as svc:
        assert svc.tenants.get("slo").deadline_s == 1.5


# ---------------------------------------------------------------------------
# the io-concurrency-bound doctor verdict
# ---------------------------------------------------------------------------

def _io_tree(*, peak, cap, qw, fs, prefetch=4, io_s=10.0, decomp_s=1.0):
    return {
        "pipeline": {"io_seconds": io_s, "decompress_seconds": decomp_s,
                     "recompress_seconds": 0.0, "stage_seconds": 0.5,
                     "stall_seconds": 0.0, "prefetch": prefetch},
        "reader": {},
        "io": {"engine": {"submitted": 300, "completed": 300, "failed": 0,
                          "inflight": 0, "inflight_peak": peak,
                          "inflight_cap": cap, "queue_wait_seconds": qw,
                          "fetch_seconds": fs}},
    }


def test_doctor_io_concurrency_pinned_at_cap_names_inflight_knob():
    from tpu_parquet.obs import doctor_registry

    rep = doctor_registry(_io_tree(peak=256, cap=256, qw=50.0, fs=12.0))
    ioc = rep["io_concurrency"]
    assert ioc["verdict"] == "io-concurrency-bound"
    assert ioc["knob"] == "TPQ_IO_INFLIGHT"
    assert "TPQ_IO_INFLIGHT" in ioc["advice"]
    assert ioc["inflight_peak"] == 256 and ioc["inflight_cap"] == 256


def test_doctor_io_concurrency_pinned_at_window_names_prefetch():
    from tpu_parquet.obs import doctor_registry

    rep = doctor_registry(_io_tree(peak=5, cap=256, qw=0.0, fs=12.0,
                                   prefetch=4))
    ioc = rep["io_concurrency"]
    assert ioc["knob"] == "prefetch="
    assert "prefetch" in ioc["advice"]


def test_doctor_io_concurrency_stays_quiet_without_evidence():
    from tpu_parquet.obs import doctor_registry

    # decompress dominates: no concurrency story
    rep = doctor_registry(_io_tree(peak=256, cap=256, qw=50.0, fs=12.0,
                                   io_s=1.0, decomp_s=20.0))
    assert "io_concurrency" not in rep
    # slots pinned but fetches were the slow part, not slot queueing
    rep = doctor_registry(_io_tree(peak=256, cap=256, qw=1.0, fs=12.0))
    assert "io_concurrency" not in rep
    # mid-depth peak: neither at the cap nor at the window — ambiguous
    rep = doctor_registry(_io_tree(peak=64, cap=256, qw=50.0, fs=12.0))
    assert "io_concurrency" not in rep


def test_doctor_io_concurrency_renders(tmp_path):
    import io as _io
    import json

    from tpu_parquet.cli import pq_tool

    rec = {"obs_version": 1, **_io_tree(peak=256, cap=256, qw=50.0, fs=12.0)}
    path = str(tmp_path / "run.json")
    with open(path, "w") as f:
        json.dump(rec, f)
    buf = _io.StringIO()
    rc = pq_tool.cmd_doctor(
        type("A", (), {"file": path, "config": None})(), out=buf)
    assert rc == 0
    out = buf.getvalue()
    assert "io-concurrency-bound" in out
    assert "raise TPQ_IO_INFLIGHT" in out


# ---------------------------------------------------------------------------
# engine observability rides the reader's registry
# ---------------------------------------------------------------------------

def test_engine_section_in_reader_registry(pq_file, monkeypatch):
    path, base = pq_file
    monkeypatch.setenv("TPQ_IO_ASYNC", "1")
    with FileReader(path, prefetch=4,
                    store=_fault_factory(FaultSpec(latency_s=0.001))) as r:
        assert r.read_pylist() == base
        tree = r.obs_registry().as_dict()
    eng = tree["io"]["engine"]
    assert eng["submitted"] > 0 and eng["inflight_cap"] >= 1
    assert "io.queue_wait" in tree["histograms"]
