"""Thrift compact-protocol engine tests.

Round-trips of our own serializer plus cross-validation against pyarrow-produced
footers (pyarrow's C++ writer uses the canonical Apache thrift compact protocol, so
successfully parsing its output validates our wire format end to end — the same role
parquet-mr plays in the reference's compatibility/ harness, SURVEY.md §4.6).
"""

import io

import pytest

from tpu_parquet import format as fmt
from tpu_parquet.footer import ParquetError, read_file_metadata, serialize_footer
from tpu_parquet.thrift import (
    CompactReader,
    CompactWriter,
    ThriftError,
    ThriftStruct,
    deserialize,
    serialize,
)


class Inner(ThriftStruct):
    FIELDS = {1: ("x", "i32"), 2: ("tag", "string")}


class Outer(ThriftStruct):
    FIELDS = {
        1: ("flag", "bool"),
        2: ("n8", "i8"),
        3: ("n16", "i16"),
        4: ("n32", "i32"),
        5: ("n64", "i64"),
        6: ("d", "double"),
        7: ("blob", "binary"),
        8: ("name", "string"),
        9: ("items", ("list", Inner)),
        10: ("nums", ("list", "i64")),
        100: ("far_field", "i32"),  # forces long-form (non-delta) field id
    }


def test_roundtrip_all_types():
    obj = Outer(
        flag=True,
        n8=-5,
        n16=-12345,
        n32=-(2**31) + 1,
        n64=-(2**63) + 1,
        d=3.14159,
        blob=b"\x00\xff\x01",
        name="héllo",
        items=[Inner(x=1, tag="a"), Inner(x=-2, tag="b")],
        nums=list(range(-50, 50)),
        far_field=42,
    )
    buf = serialize(obj)
    back = deserialize(Outer, buf)
    assert back == obj


def test_roundtrip_none_fields_skipped():
    obj = Outer(flag=False, n32=7)
    back = deserialize(Outer, serialize(obj))
    assert back.flag is False
    assert back.n32 == 7
    assert back.n64 is None
    assert back.items is None


def test_unknown_fields_are_skipped():
    # Serialize the full struct but parse with a reduced schema.
    class Reduced(ThriftStruct):
        FIELDS = {4: ("n32", "i32")}

    obj = Outer(
        flag=True, n32=99, d=1.5, blob=b"xyz",
        items=[Inner(x=3, tag="z")], nums=[1, 2, 3],
    )
    back = deserialize(Reduced, serialize(obj))
    assert back.n32 == 99


def test_long_list():
    obj = Outer(nums=list(range(1000)))
    assert deserialize(Outer, serialize(obj)).nums == list(range(1000))


def test_empty_list_and_large_binary():
    obj = Outer(nums=[], blob=b"a" * 100_000)
    back = deserialize(Outer, serialize(obj))
    assert back.nums == []
    assert back.blob == b"a" * 100_000


def test_zigzag_edge_values():
    for v in (0, -1, 1, 2**31 - 1, -(2**31)):
        assert deserialize(Outer, serialize(Outer(n32=v))).n32 == v
    for v in (0, -1, 2**63 - 1, -(2**63)):
        assert deserialize(Outer, serialize(Outer(n64=v))).n64 == v


def test_truncated_input_raises():
    buf = serialize(Outer(nums=list(range(100)), name="abc"))
    for cut in (1, len(buf) // 2, len(buf) - 1):
        with pytest.raises(ThriftError):
            deserialize(Outer, buf[:cut])


def test_garbage_input_raises_not_crashes():
    # Regression posture mirroring the reference's checked-in thrift fuzz crashers
    # (fuzz_test.go:12-28): adversarial bytes must raise ThriftError, never hang/OOM.
    bombs = [
        b"\x19\x19\x19\x19\x19",       # nested list bomb pattern
        b"\x0c" * 40,                  # deep struct nesting
        b"\x08\xff\xff\xff\xff\x0f",   # huge binary length
        b"\x09\xff\xff\xff\xff\xff\x0f",  # huge list
    ]
    for b in bombs:
        with pytest.raises(ThriftError):
            deserialize(Outer, b)


def test_varint_too_long():
    r = CompactReader(b"\xff" * 11)
    with pytest.raises(ThriftError):
        r.read_varint()


def test_varint_over_64_bits_rejected():
    # 10-byte varint encoding a 70-bit value must be rejected, not decoded.
    r = CompactReader(b"\xff" * 9 + b"\x7f")
    with pytest.raises(ThriftError):
        r.read_varint()
    # but a maximal legitimate 64-bit value decodes fine
    r = CompactReader(b"\xff" * 9 + b"\x01")
    assert r.read_varint() == 2**64 - 1


def test_bool_list_roundtrip_and_skip():
    # bool list elements are one byte each on the wire (ColumnIndex.null_pages shape)
    class B(ThriftStruct):
        FIELDS = {1: ("flags", ("list", "bool")), 2: ("after", "i32")}

    obj = B(flags=[True, False, True, False], after=7)
    buf = serialize(obj)
    back = deserialize(B, buf)
    assert back.flags == [True, False, True, False]
    assert back.after == 7

    # skipping an unknown bool-list field must consume exactly its bytes
    class OnlyAfter(ThriftStruct):
        FIELDS = {2: ("after", "i32")}

    assert deserialize(OnlyAfter, buf).after == 7


def test_double_little_endian():
    # The reference's vendored Go thrift writes doubles little-endian
    # (compact_protocol.go WriteDouble); verify byte-level compat.
    w = CompactWriter()
    w.write_double(1.0)
    assert bytes(w.out) == b"\x00\x00\x00\x00\x00\x00\xf0\x3f"


# ---------------------------------------------------------------------------
# Cross-validation against pyarrow (canonical C++ implementation)
# ---------------------------------------------------------------------------

pa = pytest.importorskip("pyarrow")
import pyarrow.parquet as pq  # noqa: E402


def _arrow_file(tmp_path, table, **kw):
    p = tmp_path / "t.parquet"
    pq.write_table(table, p, **kw)
    return p


def test_read_pyarrow_footer_flat(tmp_path):
    table = pa.table(
        {
            "a": pa.array([1, 2, 3], pa.int64()),
            "b": pa.array([1.5, 2.5, None], pa.float64()),
            "s": pa.array(["x", "y", "z"], pa.string()),
        }
    )
    p = _arrow_file(tmp_path, table)
    meta = read_file_metadata(p)
    assert meta.num_rows == 3
    assert len(meta.row_groups) == 1
    names = [e.name for e in meta.schema]
    assert names[0] in ("schema", "root") or meta.schema[0].num_children == 3
    assert {"a", "b", "s"} <= set(names)
    cols = meta.row_groups[0].columns
    assert len(cols) == 3
    assert cols[0].meta_data.num_values == 3
    assert fmt.Type(cols[0].meta_data.type) == fmt.Type.INT64


def test_read_pyarrow_footer_nested_and_logical(tmp_path):
    table = pa.table(
        {
            "lst": pa.array([[1, 2], None, [3]], pa.list_(pa.int32())),
            "mp": pa.array(
                [{"k": 1.0}, None, {"a": 2.0, "b": 3.0}],
                pa.map_(pa.string(), pa.float64()),
            ),
            "ts": pa.array([1, 2, 3], pa.timestamp("ms")),
        }
    )
    p = _arrow_file(tmp_path, table)
    meta = read_file_metadata(p)
    assert meta.num_rows == 3
    by_name = {e.name: e for e in meta.schema}
    assert "lst" in by_name
    lst = by_name["lst"]
    assert lst.logicalType is not None and lst.logicalType.which() == "LIST"
    ts = by_name["ts"]
    assert ts.logicalType.which() == "TIMESTAMP"
    assert ts.logicalType.TIMESTAMP.unit.MILLIS is not None


def test_footer_roundtrip_reserialize(tmp_path):
    """Parse a pyarrow footer, re-serialize with our writer, re-parse: equal."""
    table = pa.table({"a": [1, 2, 3], "s": ["p", "q", None]})
    p = _arrow_file(tmp_path, table)
    meta = read_file_metadata(p)
    blob = serialize_footer(meta)
    meta2 = read_file_metadata(
        io.BytesIO(b"PAR1" + blob), validate_head_magic=True
    )
    assert meta2 == meta


def test_bad_magic_raises(tmp_path):
    p = tmp_path / "bad.parquet"
    p.write_bytes(b"NOPE" + b"\x00" * 100 + b"NOPE")
    with pytest.raises(ParquetError):
        read_file_metadata(p)


def test_truncated_file_raises(tmp_path):
    p = tmp_path / "small.parquet"
    p.write_bytes(b"PAR1")
    with pytest.raises(ParquetError):
        read_file_metadata(p)


def test_bad_footer_length_raises():
    import struct as s

    blob = b"PAR1" + b"\x00" * 10 + s.pack("<I", 9999) + b"PAR1"
    with pytest.raises(ParquetError):
        read_file_metadata(blob)


def test_multi_rowgroup_footer(tmp_path):
    table = pa.table({"a": list(range(1000))})
    p = _arrow_file(tmp_path, table, row_group_size=100)
    meta = read_file_metadata(p)
    assert len(meta.row_groups) == 10
    assert sum(rg.num_rows for rg in meta.row_groups) == 1000
