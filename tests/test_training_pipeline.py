"""End-to-end TPU input pipeline: parquet file → device batches → sharded
jitted train step.

The product story in one test: a pyarrow-written file is decoded by
DeviceFileReader (with predicate pushdown), iter_batches yields fixed-shape
device arrays, each batch is laid out over an 8-device mesh with a
NamedSharding, and a jitted SGD step (whose gradients reduce over the mesh
via XLA-inserted collectives) consumes them — one compile for the whole run.
Runs on the virtual CPU mesh (conftest); the same program compiles for a TPU
pod slice unchanged.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from tpu_parquet.device_reader import DeviceFileReader
from tpu_parquet.parallel import make_mesh
from tpu_parquet.predicate import col


@pytest.fixture(scope="module")
def dataset(tmp_path_factory):
    import pyarrow as pa
    import pyarrow.parquet as pq

    rng = np.random.default_rng(0)
    n = 40_000
    x = rng.normal(size=(n, 8)).astype(np.float32)
    w_true = np.arange(1, 9, dtype=np.float32)
    y = x @ w_true + 0.01 * rng.normal(size=n).astype(np.float32)
    split = rng.integers(0, 10, n)  # column used for pushdown
    p = tmp_path_factory.mktemp("pipe") / "train.parquet"
    cols = {f"f{j}": x[:, j] for j in range(8)}
    cols["label"] = y.astype(np.float32)
    cols["fold"] = split.astype(np.int32)
    pq.write_table(pa.table(cols), p, row_group_size=5000,
                   use_dictionary=False, compression="snappy")
    return p, w_true


def test_train_step_over_mesh(dataset):
    path, w_true = dataset
    mesh = make_mesh()  # 1-D data mesh over the 8 virtual devices
    batch_sharding = NamedSharding(mesh, P("data"))
    repl = NamedSharding(mesh, P())

    feat_names = [f"f{j}" for j in range(8)]

    @jax.jit
    def train_step(w, feats, label):
        def loss(w):
            pred = feats @ w
            return jnp.mean((pred - label) ** 2)

        g = jax.grad(loss)(w)
        return w - 0.1 * g

    w = jax.device_put(jnp.zeros(8, dtype=jnp.float32), repl)
    n_batches = 0
    compiled_shapes = set()
    for _epoch in range(4):
        with DeviceFileReader(path) as r:
            for batch in r.iter_batches(4096):
                feats = jnp.stack([batch[k] for k in feat_names], axis=1)
                feats = jax.device_put(feats, batch_sharding)
                label = jax.device_put(batch["label"], batch_sharding)
                w = train_step(w, feats, label)
                compiled_shapes.add((feats.shape, label.shape))
                n_batches += 1
    w = np.asarray(w)
    assert n_batches == 4 * (40_000 // 4096)
    assert len(compiled_shapes) == 1  # fixed shapes: one executable
    # converged toward the generating weights
    assert np.allclose(w, w_true, atol=0.1), w


def test_pipeline_with_pushdown(dataset):
    path, _ = dataset
    pred = col("fold") < 3  # conservative: keeps groups that may match
    with DeviceFileReader(path, row_filter=pred) as r:
        total = sum(
            int(cols["label"].num_values) for cols in r.iter_row_groups()
        )
        # fold is uniform 0..9 per group, so stats ranges span everything
        # and nothing can be pruned — the pipeline still runs end to end
        assert total == r._host.num_selected_rows
    # a selective predicate on a clustered column does prune
    with DeviceFileReader(path, row_filter=col("label") > 1e9) as r:
        assert sum(1 for _ in r.iter_row_groups()) == 0
