"""CLI tool tests (cmd/parquet-tool + cmd/csv2parquet parity).

Driven through subprocess (the real CLI surface) for the happy paths and through
main(argv) for the matrix.
"""

import io
import json
import pathlib
import subprocess
import sys

REPO_ROOT = str(pathlib.Path(__file__).resolve().parent.parent)

import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from tpu_parquet.cli import csv2parquet, pq_tool
from tpu_parquet.cli.pq_tool import parse_human_size


@pytest.fixture()
def sample(tmp_path):
    p = tmp_path / "s.parquet"
    pq.write_table(
        pa.table({
            "id": pa.array(range(100), pa.int64()),
            "name": pa.array([f"n{i}" for i in range(100)]),
            "lst": pa.array([[i, i + 1] for i in range(100)], pa.list_(pa.int64())),
        }),
        p, row_group_size=40,
    )
    return p


def run_tool(args):
    out = io.StringIO()
    parsed = pq_tool.build_parser().parse_args(args)
    rc = parsed.func(parsed, out=out)
    return rc, out.getvalue()


def test_rowcount(sample):
    rc, out = run_tool(["rowcount", str(sample)])
    assert rc == 0 and out.strip() == "100"


def test_cat_and_head(sample):
    rc, out = run_tool(["head", "-n", "3", str(sample)])
    assert rc == 0
    lines = [json.loads(l) for l in out.splitlines()]
    assert lines[0] == {"id": 0, "name": "n0", "lst": [0, 1]}
    assert len(lines) == 3
    rc, out = run_tool(["cat", str(sample)])
    assert len(out.splitlines()) == 100


def test_meta(sample):
    rc, out = run_tool(["meta", str(sample)])
    assert rc == 0
    assert "rows: 100" in out
    assert "row groups: 3" in out
    assert "R=1 D=3" in out  # lst.list.element levels
    assert "codec=" in out


def test_schema(sample):
    rc, out = run_tool(["schema", str(sample)])
    assert rc == 0
    assert out.startswith("message")
    assert "optional int64 id" in out  # pyarrow writes columns optional
    # output must be parseable by our own DSL
    from tpu_parquet.schema.dsl import parse_schema_definition

    assert parse_schema_definition(out).num_columns == 3


def test_split(sample, tmp_path):
    pattern = str(tmp_path / "part_{}.parquet")
    rc, out = run_tool(
        ["split", "--size", "2KiB", "--output-pattern", pattern, str(sample)]
    )
    assert rc == 0
    parts = sorted(tmp_path.glob("part_*.parquet"))
    assert len(parts) >= 2
    total = 0
    for part in parts:
        t = pq.read_table(part)
        total += t.num_rows
    assert total == 100


def test_parse_human_size():
    assert parse_human_size("4096") == 4096
    assert parse_human_size("100MB") == 100_000_000
    assert parse_human_size("1GiB") == 1 << 30
    assert parse_human_size("1.5KiB") == 1536
    with pytest.raises(ValueError):
        parse_human_size("ten bytes")


def test_cli_subprocess(sample):
    r = subprocess.run(
        [sys.executable, "-m", "tpu_parquet.cli.pq_tool", "rowcount", str(sample)],
        capture_output=True, text=True, cwd=REPO_ROOT,
    )
    assert r.returncode == 0 and r.stdout.strip() == "100"
    r = subprocess.run(
        [sys.executable, "-m", "tpu_parquet.cli.pq_tool", "meta", "/nonexistent"],
        capture_output=True, text=True, cwd=REPO_ROOT,
    )
    assert r.returncode == 1
    assert "pq-tool:" in r.stderr


# ---------------------------------------------------------------------------
# csv2parquet
# ---------------------------------------------------------------------------

def test_csv2parquet_basic(tmp_path):
    csv_path = tmp_path / "in.csv"
    csv_path.write_text(
        "id,name,price,ok,when\n"
        "1,apple,1.5,true,2024-01-01T10:00:00Z\n"
        "2,banana,0.75,false,2024-06-15T20:30:00Z\n"
    )
    out_path = tmp_path / "out.parquet"
    n = csv2parquet.convert(
        str(csv_path), str(out_path),
        csv2parquet.parse_type_hints("id=int64,price=double,ok=boolean,when=timestamp"),
    )
    assert n == 2
    t = pq.read_table(out_path)
    assert t.column("id").to_pylist() == [1, 2]
    assert t.column("name").to_pylist() == ["apple", "banana"]
    assert t.column("ok").to_pylist() == [True, False]
    assert t.column("price").to_pylist() == [1.5, 0.75]


def test_csv2parquet_optional_nulls(tmp_path):
    csv_path = tmp_path / "in.csv"
    csv_path.write_text("a,b\n1,\n,x\n")
    out_path = tmp_path / "out.parquet"
    csv2parquet.convert(
        str(csv_path), str(out_path),
        csv2parquet.parse_type_hints("a=int64"), wrap="optional",
    )
    t = pq.read_table(out_path)
    assert t.column("a").to_pylist() == [1, None]
    assert t.column("b").to_pylist() == [None, "x"]


def test_csv2parquet_errors(tmp_path):
    with pytest.raises(ValueError, match="invalid type hint"):
        csv2parquet.parse_type_hints("justaname")
    with pytest.raises(ValueError, match="unknown type"):
        csv2parquet.parse_type_hints("a=quux")
    csv_path = tmp_path / "bad.csv"
    csv_path.write_text("a,b\n1\n")
    with pytest.raises(ValueError, match="line 2"):
        csv2parquet.convert(str(csv_path), str(tmp_path / "o.parquet"), {})
    csv_path2 = tmp_path / "bad2.csv"
    csv_path2.write_text("a\nnot_an_int\n")
    with pytest.raises(ValueError, match="column 'a'"):
        csv2parquet.convert(
            str(csv_path2), str(tmp_path / "o2.parquet"),
            {"a": "int64"},
        )


def test_csv2parquet_hint_for_unknown_column(tmp_path):
    csv_path = tmp_path / "in.csv"
    csv_path.write_text("a\n1\n")
    with pytest.raises(ValueError, match="unknown column"):
        csv2parquet.convert(
            str(csv_path), str(tmp_path / "o.parquet"), {"zzz": "int64"}
        )


def test_csv2parquet_cli(tmp_path):
    csv_path = tmp_path / "in.csv"
    csv_path.write_text("x,y\n1,hello\n2,world\n")
    out_path = tmp_path / "out.parquet"
    r = subprocess.run(
        [sys.executable, "-m", "tpu_parquet.cli.csv2parquet",
         "-i", str(csv_path), "-o", str(out_path), "--type-hints", "x=int32"],
        capture_output=True, text=True, cwd=REPO_ROOT,
    )
    assert r.returncode == 0, r.stderr
    assert "wrote 2 rows" in r.stdout
    assert pq.read_table(out_path).num_rows == 2
