"""Env-gated apache/parquet-testing corpus runner.

The reference's ground truth is the parquet-testing sample-file corpus, gated
on the files being present (/root/reference/parquet_test.go:12-15 skips each
file with os.Open + SkipNow when absent).  This image has no network, so the
same gating applies here: point ``PARQUET_TESTING_ROOT`` at a checkout of
https://github.com/apache/parquet-testing and every readable ``data/*.parquet``
file is decoded by this library and value-compared against pyarrow row for
row.  Offline the whole module skips cleanly — the loader existing (and
running in any corpus-equipped CI) is the point.

Outside the sealed image, ``TPQ_CORPUS_DIR`` names a fetch-once cache
directory: a ``parquet-testing`` checkout found under it (cloned once by
whatever bootstrap the host allows, e.g.
``git clone https://github.com/apache/parquet-testing
$TPQ_CORPUS_DIR/parquet-testing``) is picked up automatically, so the
conformance runners execute without per-run env plumbing.
``PARQUET_TESTING_ROOT`` still wins when both are set (explicit beats
cache).

Unlike the reference's fixed 20-file list, the runner globs the corpus so new
upstream sample files are picked up automatically.  Files exercising features
out of scope are skipped explicitly with the feature named:

- encrypted files (``*.parquet.encrypted``, AES footers): encryption metadata
  parses (format/__init__.py structs) but decryption is unsupported, same as
  the reference (parquet.go has no decryptor).
- codecs outside {UNCOMPRESSED, SNAPPY, GZIP, ZSTD} (LZ4/BROTLI/LZO): the
  registry raises a codec error; register_codec() is the documented hook.
- pyarrow-unreadable files (malformed/*, corrupt samples): no oracle values.
"""

import glob
import os

import pytest

pa = pytest.importorskip("pyarrow")
pq = pytest.importorskip("pyarrow.parquet")

from tpu_parquet.errors import ParquetError
from tpu_parquet.reader import FileReader

from test_conformance import norm, roundtrip_rows

def _resolve_root():
    """The parquet-testing checkout: explicit PARQUET_TESTING_ROOT first,
    else a ``parquet-testing`` directory under the TPQ_CORPUS_DIR
    fetch-once cache (ROADMAP open item 4 — the corpora can now run
    anywhere the cache exists, not only where the env var is plumbed)."""
    root = os.environ.get("PARQUET_TESTING_ROOT")
    if root and os.path.isdir(os.path.join(root, "data")):
        return root
    cache = os.environ.get("TPQ_CORPUS_DIR")
    if cache:
        cand = os.path.join(cache, "parquet-testing")
        if os.path.isdir(os.path.join(cand, "data")):
            return cand
    return None


ROOT = _resolve_root()

pytestmark = pytest.mark.skipif(
    ROOT is None,
    reason="no apache/parquet-testing checkout (set PARQUET_TESTING_ROOT, "
           "or TPQ_CORPUS_DIR with a parquet-testing clone inside)",
)

# substrings of codec/feature error messages that mark a file as exercising
# an out-of-scope feature rather than a reader bug.  Deliberately narrow:
# only codecs outside the supported set and encryption qualify — an error
# mentioning a *supported* codec (e.g. a snappy corruption) must FAIL.
_UNSUPPORTED_MARKERS = ("lz4", "brotli", "lzo", "encrypt")


def _corpus_files():
    if not ROOT:
        return []
    return sorted(glob.glob(os.path.join(ROOT, "data", "*.parquet")))


@pytest.mark.parametrize("path", _corpus_files(),
                         ids=lambda p: os.path.basename(p))
def test_corpus_file_matches_pyarrow(path):
    try:
        expected = pq.read_table(path).to_pylist()
    except Exception as e:  # noqa: BLE001 — no oracle, nothing to compare
        pytest.skip(f"pyarrow cannot read {os.path.basename(path)}: {e!r}")
    try:
        got = roundtrip_rows(path)
    except ParquetError as e:
        if any(m.lower() in str(e).lower() for m in _UNSUPPORTED_MARKERS):
            pytest.skip(f"out-of-scope feature: {e}")
        raise
    assert len(got) == len(expected), (len(got), len(expected))
    for i, (g, e) in enumerate(zip(got, expected)):
        assert norm(g) == norm(e), f"row {i}: {g!r} != {e!r}"


@pytest.mark.parametrize("path", _corpus_files(),
                         ids=lambda p: os.path.basename(p))
def test_corpus_file_metadata_parses(path):
    """Footer + schema parse must never crash on any corpus file (even ones
    whose data pages use out-of-scope codecs)."""
    try:
        with FileReader(path) as r:
            assert r.metadata.num_rows is not None
            assert r.schema.root is not None
    except ParquetError as e:
        if any(m.lower() in str(e).lower() for m in _UNSUPPORTED_MARKERS):
            pytest.skip(f"out-of-scope feature: {e}")
        raise
