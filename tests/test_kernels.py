"""Unit tests for encoding kernels: bitpack, RLE hybrid, delta, plain, byte arrays.

Mirrors the reference's primitive-level round-trip strategy (SURVEY.md §4.1:
bitpacking32_test.go exhaustive width loops, hybrid_test.go, deltabp_test.go,
types_test.go) with exhaustive widths and adversarial inputs.
"""

import numpy as np
import pytest

from tpu_parquet.column import ByteArrayData
from tpu_parquet.format import Type
from tpu_parquet.kernels import bitpack, bytearray as ba_codec, delta, plain, rle


# ---------------------------------------------------------------------------
# bitpack
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("width", list(range(0, 65)))
def test_bitpack_roundtrip_exhaustive_widths(width):
    rng = np.random.default_rng(width)
    n = 64
    if width == 0:
        vals = np.zeros(n, dtype=np.uint64)
    elif width == 64:
        vals = rng.integers(0, 2**63, n, dtype=np.uint64) * 2 + rng.integers(0, 2, n, dtype=np.uint64)
    else:
        vals = rng.integers(0, 2**width, n, dtype=np.uint64)
    packed = bitpack.pack(vals, width)
    assert len(packed) == (n * width + 7) // 8
    out = bitpack.unpack(packed, width, n)
    np.testing.assert_array_equal(out.astype(np.uint64), vals)


def test_bitpack_known_vector():
    # 3-bit values 0..7 packed LSB-first: the parquet spec's worked example.
    vals = np.arange(8, dtype=np.uint64)
    packed = bitpack.pack(vals, 3)
    assert packed == bytes([0b10001000, 0b11000110, 0b11111010])
    np.testing.assert_array_equal(bitpack.unpack(packed, 3, 8), vals)


def test_bitpack_underflow_raises():
    with pytest.raises(ValueError):
        bitpack.unpack(b"\x01", 8, 9)


# ---------------------------------------------------------------------------
# RLE hybrid
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("width", [1, 2, 3, 5, 8, 12, 16, 20, 32])
@pytest.mark.parametrize("use_rle", [True, False])
def test_hybrid_roundtrip(width, use_rle):
    rng = np.random.default_rng(width)
    hi = min(2**width, 2**31)
    cases = [
        rng.integers(0, hi, 1000),
        np.zeros(777, dtype=np.int64),
        np.full(100, hi - 1, dtype=np.int64),
        np.repeat(rng.integers(0, hi, 20), rng.integers(1, 50, 20)),
        rng.integers(0, hi, 1),
        rng.integers(0, hi, 8),
        rng.integers(0, hi, 9),
    ]
    for vals in cases:
        buf = rle.encode(vals.astype(np.uint64), width, use_rle_runs=use_rle)
        out = rle.decode(buf, width, len(vals))
        np.testing.assert_array_equal(out.astype(np.int64), vals)


def test_hybrid_rle_runs_smaller_for_constant_data():
    vals = np.zeros(10000, dtype=np.uint64)
    with_rle = rle.encode(vals, 1, use_rle_runs=True)
    without = rle.encode(vals, 1, use_rle_runs=False)
    assert len(with_rle) < 10
    assert len(without) > 1000


def test_hybrid_mixed_runs_alignment():
    # short noise + long constant run + short noise: exercises the borrow logic
    rng = np.random.default_rng(0)
    vals = np.concatenate([
        rng.integers(0, 4, 5),
        np.full(1000, 3),
        rng.integers(0, 4, 3),
        np.full(64, 1),
        rng.integers(0, 4, 11),
    ]).astype(np.uint64)
    for width in (2, 3, 7):
        out = rle.decode(rle.encode(vals, width), width, len(vals))
        np.testing.assert_array_equal(out.astype(np.uint64), vals)


def test_hybrid_width_zero():
    buf = rle.encode(np.zeros(50, dtype=np.uint64), 0)
    out = rle.decode(buf, 0, 50)
    np.testing.assert_array_equal(out, np.zeros(50))


def test_hybrid_bomb_run_header_clamped():
    # one tiny input claiming 2^50 RLE repeats must not allocate 2^50 values
    bomb = bytearray()
    v = (1 << 50) << 1
    while v >= 0x80:
        bomb.append((v & 0x7F) | 0x80)
        v >>= 7
    bomb.append(v)
    bomb.append(7)  # the repeated value (width 3 -> 1 byte)
    out = rle.decode(bytes(bomb), 3, 100)
    np.testing.assert_array_equal(out, np.full(100, 7))


def test_gzip_bomb_declared_size_enforced():
    import zlib as _z

    from tpu_parquet.compress import CompressionError, compress_block, decompress_block
    from tpu_parquet.format import CompressionCodec

    bomb_plain = b"\x00" * 50_000_000
    comp = compress_block(bomb_plain, CompressionCodec.GZIP)
    # declares 10 bytes but inflates to 50MB: must raise without materializing
    with pytest.raises(CompressionError):
        decompress_block(comp, CompressionCodec.GZIP, 10)


def test_hybrid_truncated_raises():
    buf = rle.encode(np.arange(100, dtype=np.uint64), 7)
    with pytest.raises(rle.RLEError):
        rle.decode(buf[: len(buf) // 2], 7, 100)
    with pytest.raises(rle.RLEError):
        rle.decode(b"", 7, 1)


def test_hybrid_prefixed():
    vals = np.arange(64, dtype=np.uint64) % 8
    buf = rle.encode_prefixed(vals, 3)
    out, consumed = rle.decode_prefixed(buf, 3, 64)
    assert consumed == len(buf)
    np.testing.assert_array_equal(out.astype(np.uint64), vals)
    with pytest.raises(rle.RLEError):
        rle.decode_prefixed(b"\x01\x00", 3, 64)


def test_hybrid_decoder_reads_rle_runs_from_other_writers():
    # Hand-built stream: RLE run of 13 sevens (width 3), then bitpacked group 0..7
    buf = bytes([13 << 1, 7]) + bytes([(1 << 1) | 1]) + bitpack.pack(
        np.arange(8, dtype=np.uint64), 3
    )
    out = rle.decode(buf, 3, 21)
    np.testing.assert_array_equal(
        out, np.concatenate([np.full(13, 7), np.arange(8)])
    )


# ---------------------------------------------------------------------------
# DELTA_BINARY_PACKED
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("bits", [32, 64])
def test_delta_roundtrip(bits):
    rng = np.random.default_rng(bits)
    lo, hi = (-(2**31), 2**31 - 1) if bits == 32 else (-(2**62), 2**62)
    dtype = np.int32 if bits == 32 else np.int64
    cases = [
        np.arange(1000, dtype=dtype),
        np.arange(1000, 0, -1, dtype=dtype),
        rng.integers(lo, hi, 10_000).astype(dtype),
        np.zeros(1, dtype=dtype),
        np.array([lo, hi, lo, hi, 0], dtype=dtype),  # min-delta overflow edges
        np.array([], dtype=dtype),
        rng.integers(-5, 5, 129).astype(dtype),      # partial final block
        rng.integers(lo, hi, 127).astype(dtype),     # partial final miniblock
        np.full(500, 42, dtype=dtype),
    ]
    for vals in cases:
        buf = delta.encode(vals, bits=bits)
        out, consumed = delta.decode(buf, bits=bits)
        assert consumed == len(buf)
        np.testing.assert_array_equal(out[: len(vals)], vals)


def test_delta_wrapping_min_delta():
    # int64 extremes: delta arithmetic must wrap like the reference's Go int64
    vals = np.array([0, 2**62, -(2**62), 2**62], dtype=np.int64)
    out, _ = delta.decode(delta.encode(vals, bits=64), bits=64)
    np.testing.assert_array_equal(out[:4], vals)


def test_delta_malformed_raises():
    good = delta.encode(np.arange(100, dtype=np.int64))
    for cut in (0, 1, 3, len(good) // 2):
        with pytest.raises(delta.DeltaError):
            delta.decode(good[:cut])
    # invalid block geometry
    with pytest.raises(delta.DeltaError):
        delta.decode(b"\x05\x04\x0a\x00")  # block_size=5 not multiple of 128


# ---------------------------------------------------------------------------
# PLAIN codecs
# ---------------------------------------------------------------------------

def test_plain_fixed_types_roundtrip():
    rng = np.random.default_rng(7)
    cases = [
        (Type.INT32, rng.integers(-(2**31), 2**31, 500).astype(np.int32)),
        (Type.INT64, rng.integers(-(2**63), 2**63 - 1, 500).astype(np.int64)),
        (Type.FLOAT, rng.normal(size=500).astype(np.float32)),
        (Type.DOUBLE, rng.normal(size=500).astype(np.float64)),
    ]
    for ptype, vals in cases:
        buf = plain.encode(vals, ptype)
        out = plain.decode(buf, ptype, len(vals))
        np.testing.assert_array_equal(out, vals)


def test_plain_nan_preserved():
    vals = np.array([np.nan, 1.0, -np.inf, np.inf], dtype=np.float64)
    out = plain.decode(plain.encode(vals, Type.DOUBLE), Type.DOUBLE, 4)
    np.testing.assert_array_equal(np.isnan(out), np.isnan(vals))
    assert out[2] == -np.inf


def test_plain_boolean_roundtrip():
    rng = np.random.default_rng(3)
    for n in (1, 7, 8, 9, 1000):
        vals = rng.integers(0, 2, n).astype(bool)
        out = plain.decode(plain.encode(vals, Type.BOOLEAN), Type.BOOLEAN, n)
        np.testing.assert_array_equal(out, vals)


def test_plain_int96_roundtrip():
    rng = np.random.default_rng(4)
    vals = rng.integers(0, 2**32, (20, 3)).astype("<u4")
    out = plain.decode(plain.encode(vals, Type.INT96), Type.INT96, 20)
    np.testing.assert_array_equal(out, vals)


def test_plain_byte_array_roundtrip():
    items = [b"", b"a", b"hello world", b"\x00" * 100, "héllo".encode()]
    ba = ByteArrayData.from_list(items)
    buf = plain.encode(ba, Type.BYTE_ARRAY)
    out = plain.decode(buf, Type.BYTE_ARRAY, len(items))
    assert out.to_list() == items


def test_plain_byte_array_malformed():
    with pytest.raises(plain.PlainError):
        plain.decode_byte_array(b"\xff\xff\xff\xff", 1)  # huge length
    with pytest.raises(plain.PlainError):
        plain.decode_byte_array(b"\x02\x00\x00\x00a", 1)  # truncated payload
    with pytest.raises(plain.PlainError):
        plain.decode_byte_array(b"", 1)


def test_plain_fixed_len_byte_array():
    items = [b"abcd", b"wxyz", b"1234"]
    ba = ByteArrayData.from_list(items)
    buf = plain.encode(ba, Type.FIXED_LEN_BYTE_ARRAY, type_length=4)
    assert buf == b"abcdwxyz1234"
    out = plain.decode(buf, Type.FIXED_LEN_BYTE_ARRAY, 3, type_length=4)
    assert out.to_list() == items
    with pytest.raises(plain.PlainError):
        plain.encode(ByteArrayData.from_list([b"abc"]), Type.FIXED_LEN_BYTE_ARRAY, 4)


def test_plain_truncated_raises():
    with pytest.raises(plain.PlainError):
        plain.decode(b"\x01\x02", Type.INT64, 1)


# ---------------------------------------------------------------------------
# Delta byte-array codecs
# ---------------------------------------------------------------------------

def test_delta_length_byte_array_roundtrip():
    items = [b"alpha", b"", b"beta", b"gamma" * 50, b"d"]
    ba = ByteArrayData.from_list(items)
    out = ba_codec.decode_delta_length(ba_codec.encode_delta_length(ba), len(items))
    assert out.to_list() == items


def test_delta_byte_array_roundtrip():
    items = [b"apple", b"applesauce", b"applet", b"banana", b"band", b"", b"c"]
    ba = ByteArrayData.from_list(items)
    buf = ba_codec.encode_delta(ba)
    out = ba_codec.decode_delta(buf, len(items))
    assert out.to_list() == items
    # sorted-ish data should beat plain length-delta thanks to prefix sharing
    sorted_items = [f"user_{i:08d}".encode() for i in range(1000)]
    ba2 = ByteArrayData.from_list(sorted_items)
    assert len(ba_codec.encode_delta(ba2)) < len(ba_codec.encode_delta_length(ba2))
    out2 = ba_codec.decode_delta(ba_codec.encode_delta(ba2), 1000)
    assert out2.to_list() == sorted_items


def test_delta_byte_array_malformed():
    items = [b"aa", b"ab"]
    buf = ba_codec.encode_delta(ByteArrayData.from_list(items))
    with pytest.raises((ba_codec.ByteArrayError, delta.DeltaError)):
        ba_codec.decode_delta(buf[: len(buf) - 2], 2)


def test_byte_array_take():
    ba = ByteArrayData.from_list([b"zero", b"one", b"two", b""])
    out = ba.take(np.array([3, 1, 1, 0]))
    assert out.to_list() == [b"", b"one", b"one", b"zero"]
