"""Corruption-containment test family (ISSUE 8).

The contract under test: a data fault (corrupt page, bad CRC, truncated
chunk) is (1) DETECTED by the default-on integrity tier, (2) NAMED — file,
column, row group, page ordinal, byte offset ride the exception and the
quarantine record, (3) CONTAINED under the error policy — skipped units
with exact accounting, bounded by the error budget, (4) DETERMINISTIC —
surviving rows are bit-identical to the clean read of the unaffected
units at every prefetch depth, and a mid-epoch loader checkpoint taken
after a skip resumes bit-identically.
"""

import json
import os

import numpy as np
import pytest

from tpu_parquet.errors import DataIntegrityError, ParquetError
from tpu_parquet.quarantine import (
    ErrorBudget, Quarantine, QuarantineLog, annotate_data_error,
    corrupt_bytes, error_context, resolve_policy, resolve_validate,
    summarize_quarantine_log,
)


# ---------------------------------------------------------------------------
# fixtures: a small CRC'd multi-group file (+ a corrupted copy helper)
# ---------------------------------------------------------------------------

N_GROUPS = 5
ROWS_PER_GROUP = 400


def _write_file(path, codec=None, write_crc=True, groups=N_GROUPS,
                rows=ROWS_PER_GROUP, seed=0):
    from tpu_parquet.format import (
        CompressionCodec, FieldRepetitionType as FRT, Type,
    )
    from tpu_parquet.schema.core import build_schema, data_column
    from tpu_parquet.writer import FileWriter

    codec = CompressionCodec.SNAPPY if codec is None else codec
    rng = np.random.default_rng(seed)
    schema = build_schema([
        data_column("a", Type.INT64, FRT.REQUIRED),
        data_column("b", Type.INT32, FRT.REQUIRED),
    ])
    with FileWriter(str(path), schema, codec=codec, write_crc=write_crc,
                    use_dictionary=False) as w:
        for _ in range(groups):
            w.write_columns({
                "a": rng.integers(0, 1 << 50, rows),
                "b": rng.integers(0, 1 << 20, rows).astype(np.int32),
            })
            w.flush_row_group()
    return str(path)


@pytest.fixture(scope="module")
def clean_file(tmp_path_factory):
    d = tmp_path_factory.mktemp("quarantine")
    path = _write_file(d / "clean.parquet")
    from tpu_parquet.reader import FileReader

    with FileReader(path) as r:
        groups = [{k: np.asarray(v.values)
                   for k, v in r.read_row_group(i).items()}
                  for i in range(r.num_row_groups)]
    return path, groups


def _corrupted_copy(src, tmp_path, row_groups=(2,), mode="bitflip"):
    import shutil

    from tpu_parquet.writer import corrupt_page

    dst = str(tmp_path / "corrupt.parquet")
    shutil.copyfile(src, dst)
    for gi in row_groups:
        corrupt_page(dst, row_group=gi, column=0, page=0, mode=mode,
                     seed=gi)
    return dst


# ---------------------------------------------------------------------------
# policy / validate / budget resolution
# ---------------------------------------------------------------------------

def test_resolve_policy_kwarg_and_env(monkeypatch):
    assert resolve_policy(None) == "raise"
    assert resolve_policy("skip_unit") == "skip_unit"
    with pytest.raises(ValueError):
        resolve_policy("skip_units")  # kwarg typos are strict
    monkeypatch.setenv("TPQ_ON_DATA_ERROR", "skip_file")
    assert resolve_policy(None) == "skip_file"
    monkeypatch.setenv("TPQ_ON_DATA_ERROR", "bogus")
    assert resolve_policy(None) == "raise"  # env typos degrade


def test_resolve_validate(monkeypatch):
    assert resolve_validate(None) is True  # the round-13 default: crc
    assert resolve_validate(False) is False
    assert resolve_validate(True) is True
    assert resolve_validate("off") is False
    assert resolve_validate("crc") is True
    with pytest.raises(ValueError):
        resolve_validate("maybe")
    monkeypatch.setenv("TPQ_VALIDATE", "off")
    assert resolve_validate(None) is False
    monkeypatch.setenv("TPQ_VALIDATE", "nonsense")
    assert resolve_validate(None) is True  # env typos degrade to default


def test_error_budget_env(monkeypatch):
    b = ErrorBudget.from_env()
    assert b.max_errors == 64 and b.max_fraction == 0.5
    monkeypatch.setenv("TPQ_DATA_ERROR_BUDGET", "10")
    assert ErrorBudget.from_env().max_errors == 10
    monkeypatch.setenv("TPQ_DATA_ERROR_BUDGET", "10,0.25")
    b = ErrorBudget.from_env()
    assert b.max_errors == 10 and b.max_fraction == 0.25
    assert b.allowed(100) == 10
    assert b.allowed(8) == 2
    assert b.allowed(None) == 10
    monkeypatch.setenv("TPQ_DATA_ERROR_BUDGET", "garbage")
    assert ErrorBudget.from_env().max_errors == 64


# ---------------------------------------------------------------------------
# annotation + corruption primitives
# ---------------------------------------------------------------------------

def test_annotate_nests_once_inner_wins():
    e = ParquetError("page CRC mismatch: header 0x1, data 0x2")
    annotate_data_error(e, page=3, offset=100)
    annotate_data_error(e, file="f.parquet", column="a", page=999)
    msg = str(e)
    assert msg.count("[") == 1  # ONE suffix, not one per annotation
    assert "page=3" in msg and "page=999" not in msg  # inner wins
    assert "file=f.parquet" in msg and "column=a" in msg
    assert e.data_context["offset"] == 100


def test_error_context_passthrough():
    with pytest.raises(ParquetError) as ei:
        with error_context(file="x", row_group=1):
            raise ParquetError("boom")
    assert ei.value.data_context == {"file": "x", "row_group": 1}
    # non-ParquetError passes through untouched
    with pytest.raises(KeyError):
        with error_context(file="x"):
            raise KeyError("y")


def test_corrupt_bytes_deterministic_and_modes():
    data = bytes(range(256)) * 4
    for mode in ("bitflip", "zero", "truncate"):
        a = corrupt_bytes(data, mode, seed=7)
        b = corrupt_bytes(data, mode, seed=7)
        assert a == b and len(a) == len(data)
    assert corrupt_bytes(data, "bitflip", 1) != corrupt_bytes(data, "bitflip", 2)
    assert corrupt_bytes(data, "bitflip", 1) != data  # always changes
    assert corrupt_bytes(b"", "bitflip", 1) == b""
    with pytest.raises(ValueError):
        corrupt_bytes(data, "nuke", 0)


def test_quarantine_budget_exhaustion_carries_records():
    q = Quarantine("skip_unit", budget=ErrorBudget(2, 1.0))
    q.begin_scan(100)
    q.note(ParquetError("one"), file="f", row_group=0)
    q.note(ParquetError("two"), file="f", row_group=1)
    with pytest.raises(DataIntegrityError) as ei:
        q.note(ParquetError("three"), file="f", row_group=2)
    assert len(ei.value.records) == 3
    assert [r["row_group"] for r in ei.value.records] == [0, 1, 2]
    assert "budget exhausted" in str(ei.value)


def test_quarantine_jsonl_log(tmp_path):
    p = str(tmp_path / "quarantine.jsonl")
    q = Quarantine("skip_unit", log=QuarantineLog(p))
    q.begin_scan(10)
    e = annotate_data_error(ParquetError("bad page"), file="f.parquet",
                            column="a", row_group=2, page=1, offset=1234)
    q.note(e)
    with open(p) as f:
        recs = [json.loads(ln) for ln in f if ln.strip()]
    assert recs == [{
        "file": "f.parquet", "column": "a", "row_group": 2, "page": 1,
        "offset": 1234, "error": "ParquetError",
        "message": str(e)[:500],
    }]


# ---------------------------------------------------------------------------
# default-on validation tier
# ---------------------------------------------------------------------------

def test_crc_default_on_catches_silent_flip(tmp_path):
    """UNCOMPRESSED + a payload bitflip: without CRC the decode would
    succeed silently with wrong data — the round-13 default catches it and
    names file/column/row group/page in the message (the _check_crc
    satellite)."""
    from tpu_parquet.format import CompressionCodec
    from tpu_parquet.reader import FileReader
    from tpu_parquet.writer import corrupt_page

    path = _write_file(tmp_path / "plain.parquet",
                       codec=CompressionCodec.UNCOMPRESSED)
    off, _n = corrupt_page(path, row_group=1, column=0, page=0,
                           mode="bitflip", seed=3)
    with pytest.raises(ParquetError) as ei:
        with FileReader(path) as r:
            r.read_all()
    msg = str(ei.value)
    assert "CRC mismatch" in msg
    assert "plain.parquet" in msg and "column=a" in msg
    assert "row_group=1" in msg and "page=0" in msg and "offset=" in msg
    ctx = ei.value.data_context
    assert ctx["row_group"] == 1 and ctx["column"] == "a"
    # validate_crc=False: the flip decodes silently (proving the default
    # actually changed behavior, not just the message)
    with FileReader(path, validate_crc=False) as r:
        out = r.read_all()
    assert len(out["a"].values) == N_GROUPS * ROWS_PER_GROUP


# ---------------------------------------------------------------------------
# the corrupt-unit fault matrix: policy x prefetch, host reader
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("prefetch", [0, 4])
@pytest.mark.parametrize("policy", ["raise", "skip_unit", "skip_file"])
def test_host_reader_fault_matrix(clean_file, tmp_path, policy, prefetch):
    from tpu_parquet.reader import FileReader

    src, clean_groups = clean_file
    path = _corrupted_copy(src, tmp_path, row_groups=(2,))
    if policy == "raise":
        with pytest.raises(ParquetError) as ei:
            with FileReader(path, prefetch=prefetch) as r:
                list(r.iter_row_groups())
        assert "row_group=2" in str(ei.value)
        return
    with FileReader(path, prefetch=prefetch, on_data_error=policy) as r:
        got = list(r.iter_row_groups())
        q = r.quarantine
    expect = ([0, 1, 3, 4] if policy == "skip_unit" else [0, 1])
    assert len(got) == len(expect)
    for out, gi in zip(got, expect):
        # surviving rows bit-identical to the clean read of that unit
        for k, want in clean_groups[gi].items():
            assert np.array_equal(np.asarray(out[k].values), want), (gi, k)
    recs = q.log.snapshot()
    assert len(recs) == 1 and recs[0]["row_group"] == 2
    assert recs[0]["column"] == "a" and recs[0]["error"] == "ParquetError"
    assert q.units_skipped == (1 if policy == "skip_unit" else 3)
    assert q.files_skipped == (0 if policy == "skip_unit" else 1)


def test_host_reader_budget_exhaustion(clean_file, tmp_path):
    from tpu_parquet.reader import FileReader

    src, _ = clean_file
    path = _corrupted_copy(src, tmp_path, row_groups=(1, 3))
    q = Quarantine("skip_unit", budget=ErrorBudget(1, 1.0))
    with pytest.raises(DataIntegrityError) as ei:
        with FileReader(path, prefetch=0, quarantine=q) as r:
            list(r.iter_row_groups())
    assert len(ei.value.records) == 2
    assert [r["row_group"] for r in ei.value.records] == [1, 3]


def test_registry_data_errors_section(clean_file, tmp_path):
    from tpu_parquet.reader import FileReader

    src, _ = clean_file
    path = _corrupted_copy(src, tmp_path, row_groups=(2,))
    with FileReader(path, on_data_error="skip_unit") as r:
        r.read_all()
        tree = r.obs_registry().as_dict()
    de = tree["data_errors"]
    assert de["errors"] == 1 and de["units_skipped"] == 1
    assert de["rows_skipped"] == ROWS_PER_GROUP
    assert de["by_class"] == {"ParquetError": 1}


def test_explicit_read_row_group_always_raises(clean_file, tmp_path):
    """The skip policy belongs to the ITERATION APIs: an explicitly
    requested row group must raise, not silently skip itself."""
    from tpu_parquet.reader import FileReader

    src, _ = clean_file
    path = _corrupted_copy(src, tmp_path, row_groups=(2,))
    for prefetch in (0, 4):
        with FileReader(path, on_data_error="skip_unit",
                        prefetch=prefetch) as r:
            assert len(r.read_row_group(1)["a"].values) == ROWS_PER_GROUP
            with pytest.raises(ParquetError):
                r.read_row_group(2)


# ---------------------------------------------------------------------------
# fault-injecting store corruption modes (no file mutation)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("mode", ["bitflip", "zero", "truncate"])
def test_store_corruption_modes_quarantined(clean_file, mode):
    """FaultInjectingStore payload corruption: the transport sees a clean
    full-length read, the integrity tier catches the damage, the policy
    engine contains it — and unmatched ranges stay bit-identical."""
    from tpu_parquet.iostore import FaultInjectingStore, FaultSpec, IOConfig, LocalStore
    from tpu_parquet.reader import FileReader

    src, clean_groups = clean_file
    # target row group 2's byte span via the footer
    from tpu_parquet.chunk_decode import validate_chunk_meta
    from tpu_parquet.footer import read_file_metadata
    from tpu_parquet.schema.core import Schema

    with open(src, "rb") as f:
        md = read_file_metadata(f)
    schema = Schema.from_file_metadata(md)
    leaves = {l.path: l for l in schema.leaves}
    spans = []
    for rg in md.row_groups:
        lo, hi = 1 << 62, 0
        for cc in rg.columns:
            cmd, off = validate_chunk_meta(
                cc, leaves[tuple(cc.meta_data.path_in_schema)])
            lo, hi = min(lo, off), max(hi, off + cmd.total_compressed_size)
        spans.append((lo, hi))
    lo2, hi2 = spans[2]
    spec = FaultSpec(corrupt=mode, corrupt_seed=5,
                     match=lambda off, size: lo2 <= off < hi2)
    cfg = IOConfig(retries=0, backoff_ms=0, retry_budget=0, coalesce_gap=0)
    for prefetch in (0, 4):
        store = None
        with FileReader(src, prefetch=prefetch, on_data_error="skip_unit",
                        store=lambda f: FaultInjectingStore(
                            LocalStore(f), spec, config=cfg)) as r:
            got = list(r.iter_row_groups())
            q = r.quarantine
        assert len(got) == 4, mode
        for out, gi in zip(got, [0, 1, 3, 4]):
            for k, want in clean_groups[gi].items():
                assert np.array_equal(np.asarray(out[k].values), want)
        assert [rec["row_group"] for rec in q.log.snapshot()] == [2]


# ---------------------------------------------------------------------------
# device reader + scan_files
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("prefetch", [0, 2])
def test_device_reader_skip_unit(clean_file, tmp_path, prefetch):
    from tpu_parquet.device_reader import DeviceFileReader

    src, clean_groups = clean_file
    path = _corrupted_copy(src, tmp_path, row_groups=(1,))
    with DeviceFileReader(path, on_data_error="skip_unit",
                          prefetch=prefetch) as r:
        got = list(r.iter_row_groups())
        q = r.quarantine
    assert len(got) == N_GROUPS - 1
    for out, gi in zip(got, [0, 2, 3, 4]):
        for k, want in clean_groups[gi].items():
            arr = np.asarray(out[k].values)[:out[k].num_leaf_slots]
            assert np.array_equal(arr, want), (gi, k)
    recs = q.log.snapshot()
    assert len(recs) == 1 and recs[0]["row_group"] == 1
    assert q.units_skipped == 1


def test_scan_files_skip_file_and_shared_engine(clean_file, tmp_path):
    """Multi-file scan: one engine spans files; skip_file drops the bad
    file's REMAINING groups and the other file survives bit-identically."""
    from tpu_parquet.device_reader import scan_files

    src, clean_groups = clean_file
    bad = _corrupted_copy(src, tmp_path, row_groups=(1,))
    good = src
    q = Quarantine("skip_file")
    got = list(scan_files([bad, good], with_path=True, quarantine=q))
    by_path = {}
    for pp, out in got:
        by_path.setdefault(pp, []).append(out)
    # bad file: group 0 survived, 1..4 dropped (1 failed, rest collateral)
    assert len(by_path.get(bad, [])) == 1
    assert len(by_path.get(good, [])) == N_GROUPS
    for out, want in zip(by_path[good], clean_groups):
        for k, arr in want.items():
            got_arr = np.asarray(out[k].values)[:out[k].num_leaf_slots]
            assert np.array_equal(got_arr, arr)
    assert len(q.log) == 1 and q.files_skipped == 1
    assert q.units_skipped == N_GROUPS - 1  # 1 failed + 3 collateral + 0


# ---------------------------------------------------------------------------
# DataLoader: the e2e containment proof
# ---------------------------------------------------------------------------

BS = 128


@pytest.fixture(scope="module")
def loader_dataset(tmp_path_factory):
    """4 files x 4 row groups (16 units, ~1% of pages corrupted = 2 of
    ~32 pages across 2 distinct units) + the per-unit clean arrays."""
    d = tmp_path_factory.mktemp("loader_q")
    paths = [
        _write_file(d / f"part{fi}.parquet", groups=4, rows=300, seed=fi)
        for fi in range(4)
    ]
    from tpu_parquet.reader import FileReader

    clean_units = {}
    for fi, p in enumerate(paths):
        with FileReader(p) as r:
            for gi in range(r.num_row_groups):
                clean_units[(fi, gi)] = {
                    k: np.asarray(v.values)
                    for k, v in r.read_row_group(gi).items()}
    return paths, clean_units


def _corrupt_loader_copy(paths, tmp_path, bad=((1, 2), (3, 0))):
    import shutil

    from tpu_parquet.writer import corrupt_page

    out = []
    for fi, p in enumerate(paths):
        dst = str(tmp_path / os.path.basename(p))
        shutil.copyfile(p, dst)
        out.append(dst)
    for fi, gi in bad:
        corrupt_page(out[fi], row_group=gi, column=0, page=0,
                     mode="bitflip", seed=fi * 7 + gi)
    return out


def _loader(paths, **kw):
    from tpu_parquet.data import DataLoader

    kw.setdefault("seed", 11)
    kw.setdefault("shuffle", True)
    kw.setdefault("shuffle_window", 512)
    return DataLoader(paths, BS, **kw)


def test_loader_e2e_containment_proof(loader_dataset, tmp_path):
    """The ISSUE 8 acceptance e2e: a seeded dataset with corrupted pages
    completes a full epoch under skip_unit with (a) exact quarantine
    accounting, (b) clean-unit batches bit-identical to an uncorrupted
    run's corresponding batches, (c) save->restore mid-epoch after a skip
    replaying identically — at prefetch {0, 4}."""
    paths, clean_units = loader_dataset
    bad = ((1, 2), (3, 0))
    dirty = _corrupt_loader_copy(paths, tmp_path, bad=bad)
    bad_rows = sum(len(clean_units[u]["a"]) for u in bad)

    # the reference stream: the CLEAN dataset with the bad units' rows
    # surgically excluded — what a contained run must reproduce exactly.
    # Same file basenames (the digest is path-independent) so the plan and
    # the block permutations match the dirty run's.
    runs = {}
    for prefetch in (0, 4):
        ld = _loader(dirty, prefetch=prefetch, on_data_error="skip_unit")
        batches = list(ld)
        st = ld.stats()
        # (a) exact accounting: both injected corruptions recorded, nothing
        # else; skipped rows match the two units' footers
        recs = ld._quarantine.log.snapshot()
        assert sorted((r["file"], r["row_group"]) for r in recs) == sorted(
            (dirty[fi], gi) for fi, gi in bad)
        assert all(r["error"] == "ParquetError" and r["page"] == 0
                   for r in recs)
        assert st.units_skipped == 2 and st.rows_skipped == bad_rows
        assert st.data_errors == 2
        assert st.rows == 16 * 300 - bad_rows
        runs[prefetch] = batches
    # deterministic across prefetch depths
    assert len(runs[0]) == len(runs[4])
    for a, b in zip(runs[0], runs[4]):
        for k in a:
            assert np.array_equal(np.asarray(a[k]), np.asarray(b[k]))
    # (b) every surviving row is a clean-unit row, bit-identical: the
    # multiset of yielded 'a' values == the clean units' minus the bad ones
    got = np.concatenate([np.asarray(b["a"])[np.asarray(b["mask"])]
                          for b in runs[0]])
    want = np.concatenate([arr["a"] for u, arr in sorted(clean_units.items())
                           if u not in bad])
    assert np.array_equal(np.sort(got), np.sort(want))


@pytest.mark.parametrize("prefetch", [0, 4])
def test_loader_resume_after_skip_bit_identical(loader_dataset, tmp_path,
                                                prefetch):
    paths, _clean = loader_dataset
    dirty = _corrupt_loader_copy(paths, tmp_path)
    ld = _loader(dirty, prefetch=prefetch, on_data_error="skip_unit")
    it = iter(ld)
    pre = [next(it) for _ in range(24)]  # far enough to pass a skip
    state = ld.state_blob()
    skips_at_ckpt = ld.state()["skipped_units"]
    rest = list(it)
    ld2 = _loader(dirty, prefetch=prefetch, on_data_error="skip_unit")
    ld2.restore(state)
    assert sorted(ld2._skipped_units) == skips_at_ckpt
    rest2 = list(ld2)
    assert len(rest) == len(rest2)
    for a, b in zip(rest, rest2):
        for k in a:
            assert np.array_equal(np.asarray(a[k]), np.asarray(b[k])), k
    # the epoch after the resumed one also lines up with the original's
    nxt, nxt2 = list(ld), list(ld2)
    assert len(nxt) == len(nxt2)
    for a, b in zip(nxt, nxt2):
        for k in a:
            assert np.array_equal(np.asarray(a[k]), np.asarray(b[k])), k


def test_loader_skip_file_checkpoint_carries_bad_files(loader_dataset,
                                                       tmp_path):
    """skip_file mid-epoch: the blob carries the bad-file marking, so a
    restored run drops the bad file's LATER units exactly like the
    uninterrupted one."""
    paths, _clean = loader_dataset
    dirty = _corrupt_loader_copy(paths, tmp_path, bad=((1, 2),))
    ld = _loader(dirty, on_data_error="skip_file")
    it = iter(ld)
    pre = []
    # step until the skip happened, then a couple more batches
    while ld.stats().units_skipped == 0:
        pre.append(next(it))
    pre.append(next(it))
    state = ld.state()
    assert state["skipped_files"] == [1]
    rest = list(it)
    ld2 = _loader(dirty, on_data_error="skip_file")
    ld2.restore(ld.state() if False else state)  # dict form round-trip
    assert ld2._bad_files == {1}
    rest2 = list(ld2)
    assert len(rest) == len(rest2)
    for a, b in zip(rest, rest2):
        for k in a:
            assert np.array_equal(np.asarray(a[k]), np.asarray(b[k])), k


def test_loader_skip_file_wholly_corrupt_costs_one_record(loader_dataset,
                                                          tmp_path):
    """skip_file over a file whose EVERY unit is corrupt: one record (the
    first failure), the rest are collateral skips — no budget charge, so
    even a tiny budget survives (review finding: later failing units of an
    already-bad file must not re-note)."""
    paths, _clean = loader_dataset
    dirty = _corrupt_loader_copy(paths, tmp_path,
                                 bad=tuple((1, g) for g in range(4)))
    q = Quarantine("skip_file", budget=ErrorBudget(1, 1.0))
    ld = _loader(dirty, on_data_error=None, quarantine=q)
    list(ld)
    assert len(q.log) == 1
    assert ld.stats().units_skipped == 4
    assert ld.stats().rows == 12 * 300


def test_loader_contains_corruption_surfacing_as_typeerror(loader_dataset,
                                                           tmp_path,
                                                           monkeypatch):
    """A corruption the CRC tier cannot see can surface as the null-free
    contract TypeError in _decode_unit — it must be contained, not kill
    the epoch (review finding: the seam caught only ParquetError)."""
    from tpu_parquet import reader as reader_mod

    paths, _clean = loader_dataset
    real = reader_mod.FileReader.read_row_group
    state = {"fired": False}

    def fake(self, index, prefetch=None):
        if not state["fired"]:
            state["fired"] = True  # the first-decoded unit "has nulls"
            raise TypeError(
                "DataLoader needs null-free columns; 'a' has 3 nulls")
        return real(self, index, prefetch=prefetch)

    monkeypatch.setattr(reader_mod.FileReader, "read_row_group", fake)
    ld = _loader(paths, on_data_error="skip_unit")
    list(ld)
    assert ld.stats().units_skipped == 1
    recs = ld._quarantine.log.snapshot()
    assert len(recs) == 1 and recs[0]["error"] == "TypeError"


def test_loader_budget_exhaustion_aborts(loader_dataset, tmp_path):
    paths, _clean = loader_dataset
    dirty = _corrupt_loader_copy(paths, tmp_path)  # 2 corrupt units
    q = Quarantine("skip_unit", budget=ErrorBudget(1, 1.0))
    ld = _loader(dirty, on_data_error=None, quarantine=q)
    with pytest.raises(DataIntegrityError) as ei:
        list(ld)
    assert len(ei.value.records) == 2


def test_loader_raise_policy_unchanged(loader_dataset, tmp_path):
    paths, _clean = loader_dataset
    dirty = _corrupt_loader_copy(paths, tmp_path)
    with pytest.raises(ParquetError):
        list(_loader(dirty))


def test_checkpoint_skip_fields_validation(loader_dataset, tmp_path):
    """Tampered skip fields refuse loudly (CheckpointError), and
    pre-round-13 blobs (no skip fields) still restore."""
    from tpu_parquet.data.checkpoint import pack_state, unpack_state
    from tpu_parquet.errors import CheckpointError

    paths, _clean = loader_dataset
    ld = _loader(paths, on_data_error="skip_unit")
    st = ld.state()
    # pre-round-13 blob shape: no skip fields at all
    legacy = {k: v for k, v in st.items()
              if k not in ("skipped_units", "skipped_rows", "skipped_files")}
    ld2 = _loader(paths, on_data_error="skip_unit")
    ld2.restore(pack_state(legacy))
    assert ld2._skipped_units == set()
    for tamper in (
        {"skipped_units": [3, 1]},                  # unsorted
        {"skipped_units": [1, 1]},                  # duplicate
        {"skipped_units": [99999]},                 # out of range
        {"skipped_units": ["1"]},                   # wrong type
        {"skipped_units": [1], "skipped_rows": 7},  # row-sum mismatch
        {"skipped_rows": -1},
        {"skipped_files": [2, 0]},                  # unsorted
        {"skipped_files": [99]},                    # no such file
    ):
        bad = dict(st)
        bad.update(tamper)
        with pytest.raises(CheckpointError):
            _loader(paths).restore(bad)
    # a cursor at shard_rows - skipped_rows (epoch tail after a skip) packs
    u0 = int(ld._my_units[0])
    rows0 = int(ld._unit_rows_all[u0])
    tail = dict(st)
    tail.update(skipped_units=[u0], skipped_rows=rows0,
                rows_taken=st["shard_rows"] - rows0)
    unpack_state(pack_state(tail))


# ---------------------------------------------------------------------------
# kwarg propagation: validate_crc / on_data_error reach every decode seam
# ---------------------------------------------------------------------------

def _host_read(path, **kw):
    from tpu_parquet.reader import FileReader

    with FileReader(path, **kw) as r:
        groups = list(r.iter_row_groups())
        return sum(len(g["a"].values) for g in groups), r.quarantine


def _host_read_prefetch(path, **kw):
    return _host_read(path, prefetch=4, **kw)


def _device_read(path, **kw):
    from tpu_parquet.device_reader import DeviceFileReader

    with DeviceFileReader(path, **kw) as r:
        groups = list(r.iter_row_groups())
        return sum(g["a"].num_leaf_slots for g in groups), r.quarantine


def _device_read_prefetch(path, **kw):
    return _device_read(path, prefetch=2, **kw)


def _scan(path, **kw):
    from tpu_parquet.device_reader import scan_files

    q = Quarantine(kw.pop("on_data_error", None))
    groups = list(scan_files([path], quarantine=q, **kw))
    return sum(g["a"].num_leaf_slots for g in groups), q


def _loader_read(path, **kw):
    from tpu_parquet.data import DataLoader

    ld = DataLoader(path, 64, shuffle=False, **kw)
    list(ld)
    return ld.stats().rows, ld._quarantine


@pytest.mark.parametrize("api", [
    _host_read, _host_read_prefetch, _device_read, _device_read_prefetch,
    _scan, _loader_read,
], ids=["host", "host_prefetch", "device", "device_prefetch", "scan",
        "loader"])
def test_kwarg_propagation_table(tmp_path, api):
    """Table-driven: every public decode surface (1) validates CRCs by
    default, (2) decodes the corruption silently with validate_crc=False
    (UNCOMPRESSED flips are undetectable without the checksum), and
    (3) honors on_data_error=skip_unit end to end."""
    from tpu_parquet.format import CompressionCodec
    from tpu_parquet.writer import corrupt_page

    path = _write_file(tmp_path / "plain.parquet",
                       codec=CompressionCodec.UNCOMPRESSED, groups=3,
                       rows=200)
    corrupt_page(path, row_group=1, column=0, page=0, mode="bitflip",
                 seed=1)
    with pytest.raises(ParquetError):
        api(path)
    rows, _q = api(path, validate_crc=False)
    assert rows == 600  # silent: only the CRC tier could have caught it
    rows, q = api(path, on_data_error="skip_unit")
    assert rows == 400
    assert [r["row_group"] for r in q.log.snapshot()] == [1]


# ---------------------------------------------------------------------------
# observability: flight dump + autopsy verdict + pq_tool quarantine
# ---------------------------------------------------------------------------

def test_autopsy_data_corruption_verdict(clean_file, tmp_path):
    """A dump taken after quarantined failures autopsies to the
    data-corruption verdict naming the first bad (file, column, page).
    (Engines register as WEAK flight sources, so other live engines from
    this test session may contribute counts — the named first-bad record
    is asserted structurally, not by exact identity.)"""
    import io

    from tpu_parquet.cli import pq_tool
    from tpu_parquet.obs import autopsy_dump, flight_recorder
    from tpu_parquet.reader import FileReader

    src, _ = clean_file
    path = _corrupted_copy(src, tmp_path, row_groups=(2,))
    with FileReader(path, on_data_error="skip_unit") as r:
        r.read_all()
        doc = flight_recorder().snapshot(reason="test")
    rep = autopsy_dump(doc)
    assert rep["verdict"] == "data-corruption"
    assert rep["data_errors"]["errors"] >= 1
    first = rep["data_errors"]["first"]
    assert first and first["column"] in ("a", "b")
    assert "row_group" in first and first["error"] == "ParquetError"
    assert "first bad" in rep["probable_cause"]
    # the CLI prints the data line + verdict
    dump_path = str(tmp_path / "dump.json")
    with open(dump_path, "w") as f:
        json.dump(doc, f, default=repr)
    out = io.StringIO()
    rc = pq_tool.cmd_autopsy(type("A", (), {"file": dump_path})(), out=out)
    assert rc == 0
    text = out.getvalue()
    assert "verdict: data-corruption" in text
    assert "quarantined error(s)" in text


def test_pq_tool_quarantine_summary(tmp_path):
    import io

    from tpu_parquet.cli import pq_tool

    p = str(tmp_path / "q.jsonl")
    log = QuarantineLog(p)
    q = Quarantine("skip_unit", log=log)
    q.begin_scan(10)
    for gi, col in ((1, "a"), (1, "b"), (4, "a")):
        e = annotate_data_error(ParquetError(f"bad {gi}.{col}"),
                                file=f"part{gi % 2}.parquet", column=col,
                                row_group=gi, page=0, offset=10)
        q.note(e)
    out = io.StringIO()
    rc = pq_tool.cmd_quarantine(type("A", (), {"file": p})(), out=out)
    assert rc == 0
    text = out.getvalue()
    assert "3 record(s) across 2 file(s)" in text
    assert "first bad: file 'part1.parquet' column 'a' row_group 1" in text
    assert "by column" in text and "by error" in text
    # summarize_quarantine_log shape
    rep = summarize_quarantine_log(log.snapshot())
    assert rep["records"] == 3 and rep["by_class"] == {"ParquetError": 3}
    # unreadable path: exit 1
    out = io.StringIO()
    assert pq_tool.cmd_quarantine(
        type("A", (), {"file": str(tmp_path / "nope.jsonl")})(),
        out=out) == 1


def test_quarantine_flight_instant(clean_file, tmp_path):
    """Each contained failure emits a `quarantine` instant into the
    always-on ring (the black-box trail a post-mortem replays)."""
    from tpu_parquet.obs import flight_recorder
    from tpu_parquet.reader import FileReader

    src, _ = clean_file
    path = _corrupted_copy(src, tmp_path, row_groups=(2,))
    with FileReader(path, on_data_error="skip_unit") as r:
        r.read_all()
        doc = flight_recorder().snapshot(reason="test")
    events = [ev for t in doc["threads"].values()
              for ev in t["events"] if ev["name"] == "quarantine"]
    assert events, "no quarantine instant in the ring"
    assert any(ev.get("args", {}).get("row_group") == 2
               and ev.get("args", {}).get("column") == "a"
               for ev in events)


# ---------------------------------------------------------------------------
# writer helper
# ---------------------------------------------------------------------------

def test_corrupt_page_targets_named_page(tmp_path):
    from tpu_parquet.reader import FileReader
    from tpu_parquet.writer import corrupt_page

    path = _write_file(tmp_path / "t.parquet", groups=3, rows=100)
    off, n = corrupt_page(path, row_group=2, column="b", page=0,
                          mode="zero", seed=4)
    assert n > 0
    with FileReader(path, on_data_error="skip_unit") as r:
        r.read_all()
        recs = r.quarantine.log.snapshot()
    assert len(recs) == 1
    assert recs[0]["row_group"] == 2 and recs[0]["column"] == "b"
    with pytest.raises(KeyError):
        corrupt_page(path, column="nope")
