"""Streaming scan sessions (ISSUE 17): fixed-shape batches over the serve
tier, resumable cursors, and the zero-IO warm path.

The contracts under test, in rough order of importance:

- a streamed scan's concatenated (mask-filtered) batches are BIT-IDENTICAL
  to the one-shot response, at prefetch {0, 4}, host and device (device
  streams project fixed-width columns; object-dtype columns refuse typed);
- a cursor saved mid-stream resumes into a NEW session whose remaining
  batches match the uninterrupted reference exactly — the TPQL checkpoint
  discipline (data/checkpoint.py) applied to the serve tier;
- hostile cursor blobs (truncated, bad magic, off-rail positions, lying
  fingerprints) are refused with CheckpointError, never adopted;
- a warm stream (result cache holds every chunk) performs ZERO store reads
  and ZERO file opens — structural counters, not timings;
- close()/cancel()/deadline reach a CONSUMER BLOCKED IN next() as a typed
  terminal error, and the service leaks no tpq-serve threads.
"""

import os
import sys
import threading
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from test_serve import _write_file  # noqa: E402

from tpu_parquet.column import ByteArrayData  # noqa: E402
from tpu_parquet.errors import (CancelledError, CheckpointError,  # noqa: E402
                                DeadlineExceededError, ParquetError)
from tpu_parquet.iostore import LocalStore  # noqa: E402
from tpu_parquet.serve import (ScanRequest, ScanService,  # noqa: E402
                               StreamingScan, check_cursor_compatible,
                               pack_cursor, request_digest, unpack_cursor)


@pytest.fixture(scope="module")
def files(tmp_path_factory):
    d = tmp_path_factory.mktemp("stream")
    return [_write_file(str(d / f"f{i}.parquet"), seed=10 + i, groups=3,
                        rows=400) for i in range(2)]


def _oneshot_columns(svc, paths, columns=None):
    """Per-column concatenation of the one-shot responses, in path order —
    the reference a streamed session must reproduce byte for byte."""
    out = {}
    res = svc.scan(ScanRequest(paths, columns=columns), timeout=60)
    for p in paths:
        for name, cd in res[p].items():
            parts = cd if isinstance(cd, list) else [cd]
            for part in parts:
                vals = part.values
                if isinstance(vals, ByteArrayData):
                    out.setdefault(name, []).extend(vals.to_list())
                else:
                    out.setdefault(name, []).extend(np.asarray(vals))
    return {n: np.asarray(v, dtype=object if isinstance(v[0], bytes)
                          else None) for n, v in out.items()}


def _drain(session):
    """Mask-filtered per-column concatenation of a stream's batches."""
    cols = {}
    n_batches = 0
    for batch in session:
        mask = np.asarray(batch["mask"])
        for name, arr in batch.items():
            if name == "mask":
                continue
            cols.setdefault(name, []).append(np.asarray(arr)[mask])
        n_batches += 1
    return {n: np.concatenate(v) for n, v in cols.items()}, n_batches


# ---------------------------------------------------------------------------
# bit-identity: streamed == one-shot, host and device
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("prefetch", [0, 4])
def test_stream_matches_oneshot_host(files, prefetch):
    with ScanService(concurrency=2) as svc:
        want = _oneshot_columns(svc, files)
        session = svc.scan(ScanRequest(files, stream=True, batch_rows=128,
                                       prefetch=prefetch), timeout=60)
        assert isinstance(session, StreamingScan)
        got, n_batches = _drain(session)
        # every batch is exactly batch_rows wide; only the mask ragged-edges
        assert n_batches == 20  # ceil(1200/128) per file, 2 files
        for name in ("a", "s"):
            assert np.array_equal(got[name], want[name]), name
        assert session.rows_emitted == 2400


@pytest.mark.parametrize("prefetch", [0, 4])
def test_stream_matches_oneshot_device(files, prefetch):
    # device streams ship each batch through jnp.asarray: object-dtype
    # (BYTE_ARRAY) columns cannot ride, so the projection is fixed-width
    with ScanService(concurrency=2) as svc:
        want = _oneshot_columns(svc, files, columns=["a"])
        session = svc.scan(ScanRequest(files, columns=["a"], stream=True,
                                       batch_rows=256, device=True,
                                       prefetch=prefetch), timeout=60)
        cols = {}
        for batch in session:
            arr = np.asarray(batch["a"])  # device -> host for comparison
            assert arr.shape == (256,)  # fixed-shape: no recompiles downstream
            assert type(batch["a"]).__name__ != "ndarray"  # actually shipped
            cols.setdefault("a", []).append(arr[np.asarray(batch["mask"])])
        got = np.concatenate(cols["a"])
        assert got.dtype == np.int64  # x64 shipping: no silent downcast
        assert np.array_equal(got, want["a"])


def test_device_stream_refuses_object_columns(files):
    with ScanService(concurrency=1) as svc:
        session = svc.scan(ScanRequest([files[0]], stream=True,
                                       batch_rows=100, device=True),
                           timeout=60)
        with pytest.raises(ParquetError, match="device-shippable"):
            for _ in session:
                pass


# ---------------------------------------------------------------------------
# cursor: save mid-stream, resume, identical suffix; hostile blobs refused
# ---------------------------------------------------------------------------

def test_cursor_resume_bit_identical(files):
    with ScanService(concurrency=2) as svc:
        ref = svc.scan(ScanRequest(files, stream=True, batch_rows=128),
                       timeout=60)
        ref_batches = [{n: np.asarray(v) for n, v in b.items()} for b in ref]
        s1 = svc.scan(ScanRequest(files, stream=True, batch_rows=128),
                      timeout=60)
        taken = [next(s1) for _ in range(5)]
        blob = s1.cursor()
        s1.close()
        assert isinstance(blob, bytes) and blob[:4] == b"TPQS"
        s2 = svc.scan(ScanRequest(files, stream=True, batch_rows=128,
                                  cursor=blob), timeout=60)
        rest = list(s2)
        assert len(taken) + len(rest) == len(ref_batches)
        for got, want in zip(taken + rest, ref_batches):
            for name in want:
                assert np.array_equal(np.asarray(got[name]), want[name]), name
        # a terminal session's cursor is adoptable and yields nothing more
        done = svc.scan(ScanRequest(files, stream=True, batch_rows=128,
                                    cursor=s2.cursor()), timeout=60)
        assert list(done) == []


def test_cursor_rejects_hostile_blobs(files):
    with ScanService(concurrency=1) as svc:
        s = svc.scan(ScanRequest(files, stream=True, batch_rows=128),
                     timeout=60)
        next(s)
        blob = s.cursor()
        s.close()
        state = unpack_cursor(blob)
        # structural refusals: truncation, magic, version, off-rail position
        for bad in (blob[:10], b"NOPE" + blob[4:],
                    blob[:4] + (99).to_bytes(2, "big") + blob[6:]):
            with pytest.raises(CheckpointError):
                unpack_cursor(bad)
        lying = dict(state, rows_done=state["rows_done"] + 7)  # off-boundary
        with pytest.raises(CheckpointError):
            pack_cursor(lying)
        # fingerprint refusals, end to end through submit(): a different
        # batch geometry and a different request shape both refuse typed
        with pytest.raises(CheckpointError, match="batch_rows"):
            svc.scan(ScanRequest(files, stream=True, batch_rows=64,
                                 cursor=blob), timeout=60)
        with pytest.raises(CheckpointError, match="request_digest"):
            svc.scan(ScanRequest(files, columns=["a"], stream=True,
                                 batch_rows=128, cursor=blob), timeout=60)
        # the digest pins projection/filter/paths; same-shape re-submit passes
        check_cursor_compatible(state, {
            "batch_rows": 128, "device": False, "n_paths": len(files),
            "request_digest": request_digest(
                ScanRequest(files, stream=True, batch_rows=128))})


# ---------------------------------------------------------------------------
# warm path: a fully-cached stream is zero store IO, structurally
# ---------------------------------------------------------------------------

def test_warm_stream_zero_store_reads(files):
    opens, reads = [], []

    def factory(path):
        store = LocalStore(path)
        opens.append(path)
        orig = store.read_range

        def counting_read(offset, size, **kw):
            reads.append((offset, size))
            return orig(offset, size, **kw)

        store.read_range = counting_read
        return store

    with ScanService(concurrency=1, store=factory,
                     result_cache_mb=64) as svc:
        cold, _ = _drain(svc.scan(ScanRequest([files[0]], stream=True,
                                              batch_rows=100), timeout=60))
        assert opens and reads  # the cold pass did real IO
        o0, r0 = len(opens), len(reads)
        warm_session = svc.scan(ScanRequest([files[0]], stream=True,
                                            batch_rows=100), timeout=60)
        warm, n_batches = _drain(warm_session)
        # structural zero: no new opens, no new ranges — every batch came
        # out of the decoded-result cache
        assert len(opens) == o0 and len(reads) == r0
        assert warm_session.warm_batches == n_batches
        assert warm_session.cold_groups == 0
        for name in cold:
            assert np.array_equal(warm[name], cold[name]), name


# ---------------------------------------------------------------------------
# lifecycle: close/cancel/deadline reach a blocked consumer, typed
# ---------------------------------------------------------------------------

def test_close_drains_blocked_consumer(files):
    before = {t.name for t in threading.enumerate()
              if t.name.startswith("tpq-serve")}
    svc = ScanService(concurrency=1)
    session = svc.scan(ScanRequest(files, stream=True, batch_rows=128),
                       timeout=60)
    got, errs = [], []

    def consume():
        try:
            for batch in session:
                got.append(batch)
                time.sleep(0.2)  # slower than the producer: buffer fills
        except CancelledError as e:
            errs.append(e)

    t = threading.Thread(target=consume)
    t.start()
    time.sleep(0.3)
    svc.close()  # must unblock the consumer with a terminal verdict
    t.join(timeout=10)
    assert not t.is_alive()
    assert errs, "blocked next() never saw the close"
    assert "closed" in str(errs[0])
    time.sleep(0.05)
    after = {t.name for t in threading.enumerate()
             if t.name.startswith("tpq-serve")}
    assert after <= before  # no leaked workers, sessions included


def test_session_cancel_and_deadline(files):
    with ScanService(concurrency=1) as svc:
        s = svc.scan(ScanRequest(files, stream=True, batch_rows=128),
                     timeout=60)
        next(s)
        s.cancel()
        with pytest.raises(CancelledError):
            for _ in s:
                pass
        # stats: the cancelled session is not a silent success.  The
        # consumer sees the terminal verdict BEFORE the worker books the
        # failure, so give the accounting a beat to reconcile.
        deadline = time.time() + 5
        while time.time() < deadline:
            st = svc.serve_stats()
            if st["submitted"] == st["completed"] + st["failed"]:
                break
            time.sleep(0.01)
        assert st["submitted"] == st["completed"] + st["failed"]
    with ScanService(concurrency=1) as svc:
        # an expired deadline may fire at submit pickup (before the
        # session is even handed back) or mid-iteration — typed either way
        with pytest.raises(DeadlineExceededError):
            s = svc.scan(ScanRequest(files, stream=True, batch_rows=64,
                                     deadline_s=0.001), timeout=60)
            while True:
                next(s)


def test_stream_registry_accounting(files):
    with ScanService(concurrency=1) as svc:
        session = svc.scan(ScanRequest([files[0]], stream=True,
                                       batch_rows=200), timeout=60)
        n = len(list(session))
        sv = svc.obs_registry().as_dict()["serve"]
    assert sv["stream_sessions"] == 1
    assert sv["stream_batches"] == n == 6
    assert sv["submitted"] == sv["completed"] == 1
