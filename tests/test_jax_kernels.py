"""Device (JAX) kernel tests: differential against the host NumPy kernels.

The host kernels are the correctness reference (themselves validated against
pyarrow and golden vectors); every device kernel must produce bit-identical
results.  Runs on the virtual 8-device CPU mesh from conftest.py — the same XLA
programs compile for TPU unchanged.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from tpu_parquet import jax_decode as jd
from tpu_parquet import jax_kernels as K
from tpu_parquet.column import ByteArrayData
from tpu_parquet.kernels import bitpack, delta, rle


RNG = np.random.default_rng(42)


# ---------------------------------------------------------------------------
# extract_bits / unpack_bits
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("width", [1, 2, 3, 5, 7, 8, 12, 16, 20, 25, 31, 32])
def test_unpack_bits_matches_host_u32(width):
    count = 1000
    vals = RNG.integers(0, 1 << width, size=count, dtype=np.uint64)
    packed = bitpack.pack(vals, width)
    host = bitpack.unpack(packed, width, count)
    dev = K.unpack_bits(jd.pad_buffer(packed), width, count)
    np.testing.assert_array_equal(np.asarray(dev), host)


@pytest.mark.parametrize("width", [33, 40, 47, 57, 58, 63, 64])
def test_unpack_bits_matches_host_u64(width):
    count = 257
    vals = RNG.integers(0, 1 << min(width, 63), size=count, dtype=np.uint64)
    if width == 64:
        vals[0] = 0xFFFFFFFFFFFFFFFF  # force a full-width value
    packed = bitpack.pack(vals, width)
    host = bitpack.unpack(packed, width, count)
    dev = K.unpack_bits(jd.pad_buffer(packed), width, count)
    np.testing.assert_array_equal(np.asarray(dev), host)


def test_unpack_bits_width0():
    out = K.unpack_bits(jd.pad_buffer(b""), 0, 17)
    np.testing.assert_array_equal(np.asarray(out), np.zeros(17, dtype=np.uint32))


def test_extract_bits_dynamic_widths():
    # per-value widths: value i stored with width w[i] back to back
    widths = np.array([3, 7, 1, 15, 9, 22, 4, 30], dtype=np.int64)
    vals = [int(RNG.integers(0, 1 << w)) for w in widths]
    bitstream = "".join(
        format(v, f"0{w}b")[::-1] for v, w in zip(vals, widths)
    )
    nbytes = (len(bitstream) + 7) // 8
    bitstream = bitstream.ljust(nbytes * 8, "0")
    data = bytes(
        int(bitstream[i * 8 : (i + 1) * 8][::-1], 2) for i in range(nbytes)
    )
    pos = np.concatenate([[0], np.cumsum(widths)[:-1]])
    out = K.extract_bits(
        jd.pad_buffer(data),
        jnp.asarray(pos),
        jnp.asarray(widths, dtype=jnp.int32),
        int(widths.max()),
    )
    np.testing.assert_array_equal(np.asarray(out), np.array(vals, dtype=np.uint32))


# ---------------------------------------------------------------------------
# RLE hybrid
# ---------------------------------------------------------------------------

def _hybrid_roundtrip(values, width):
    encoded = rle.encode(np.asarray(values, dtype=np.uint64), width)
    host = rle.decode(encoded, width, len(values))
    meta = jd.parse_hybrid_meta(encoded, width, len(values))
    dev = jd.decode_hybrid_device(jd.pad_buffer(encoded), meta, width)
    np.testing.assert_array_equal(np.asarray(dev), host)
    np.testing.assert_array_equal(host, np.asarray(values, dtype=host.dtype))


@pytest.mark.parametrize("width", [1, 2, 3, 8, 12, 20, 32])
def test_hybrid_random(width):
    vals = RNG.integers(0, 1 << min(width, 32), size=3000, dtype=np.uint64)
    _hybrid_roundtrip(vals, width)


def test_hybrid_rle_heavy():
    # long constant stretches → encoder emits true RLE runs
    vals = np.concatenate([
        np.full(500, 3), np.full(1000, 1), RNG.integers(0, 8, 77), np.full(2000, 7),
    ]).astype(np.uint64)
    _hybrid_roundtrip(vals, 3)


def test_hybrid_bitpacked_only():
    vals = RNG.integers(0, 4, size=64, dtype=np.uint64)
    enc = rle.encode(vals, 2, use_rle_runs=False)  # reference-style BP-only
    meta = jd.parse_hybrid_meta(enc, 2, 64)
    dev = jd.decode_hybrid_device(jd.pad_buffer(enc), meta, 2)
    np.testing.assert_array_equal(np.asarray(dev), vals.astype(np.uint32))


def test_hybrid_mixed_runs_partial_tail():
    # trailing bit-packed group padding must be trimmed by count
    vals = np.concatenate([np.full(100, 5), RNG.integers(0, 8, 13)]).astype(np.uint64)
    _hybrid_roundtrip(vals, 3)


# ---------------------------------------------------------------------------
# DELTA_BINARY_PACKED
# ---------------------------------------------------------------------------

def _delta_differential(vals, bits):
    enc = delta.encode(np.asarray(vals), bits=bits)
    host, _ = delta.decode(enc, bits=bits)
    meta = jd.parse_delta_meta(enc, bits)
    dev = jd.decode_delta_device(jd.pad_buffer(enc), meta, bits)
    np.testing.assert_array_equal(np.asarray(dev)[: len(vals)], host)


@pytest.mark.parametrize("bits", [32, 64])
def test_delta_random(bits):
    dt = np.int32 if bits == 32 else np.int64
    vals = RNG.integers(-(1 << 20), 1 << 20, size=5000).astype(dt)
    _delta_differential(vals, bits)


@pytest.mark.parametrize("bits", [32, 64])
def test_delta_monotonic(bits):
    dt = np.int32 if bits == 32 else np.int64
    vals = np.cumsum(RNG.integers(0, 100, size=1000)).astype(dt)
    _delta_differential(vals, bits)


def test_delta_extremes_int64():
    vals = np.array(
        [0, (1 << 63) - 1, -(1 << 63), 17, -17, (1 << 62), -(1 << 62)],
        dtype=np.int64,
    )
    _delta_differential(vals, 64)


def test_delta_extremes_int32():
    vals = np.array([0, (1 << 31) - 1, -(1 << 31), 3, -3], dtype=np.int32)
    _delta_differential(vals, 32)


def test_delta_single_and_empty():
    _delta_differential(np.array([42], dtype=np.int64), 64)
    enc = delta.encode(np.zeros(0, dtype=np.int64), bits=64)
    meta = jd.parse_delta_meta(enc, 64)
    assert meta.count == 0


def test_delta_partial_last_block():
    # 130 values: one full 128-block + partial second block
    vals = np.arange(130, dtype=np.int64) * 7 - 300
    _delta_differential(vals, 64)


# ---------------------------------------------------------------------------
# gathers
# ---------------------------------------------------------------------------

def test_dict_gather_int():
    dictionary = RNG.integers(-(1 << 40), 1 << 40, size=100)
    idx = RNG.integers(0, 100, size=1000)
    # pass the host int64 array straight through: the kernel's scoped_x64
    # wrapper converts it on device without truncation (a pre-converted
    # jnp.asarray outside the scope would clamp to int32 under default x32)
    out = K.dict_gather(dictionary, jnp.asarray(idx, dtype=jnp.uint32))
    np.testing.assert_array_equal(np.asarray(out), dictionary[idx])


@pytest.mark.parametrize("dtype", ["float32", "float64", "int32", "int64"])
def test_dict_gather_bytes(dtype):
    dictionary = RNG.standard_normal(100).astype(dtype) if dtype.startswith("f") \
        else RNG.integers(-(1 << 30), 1 << 30, size=100).astype(dtype)
    dictionary[0] = np.array(-0.0 if dtype.startswith("f") else 0, dtype=dtype)
    idx = RNG.integers(0, 100, size=1000)
    rows = dictionary.view(np.uint8).reshape(100, dictionary.dtype.itemsize)
    out = K.dict_gather_bytes(
        jnp.asarray(rows), jnp.asarray(idx, dtype=jnp.uint32), dtype
    )
    got = _from_device(out, dtype, len(idx))
    # bit-exact: compare raw bytes, not float values
    np.testing.assert_array_equal(
        got.view(np.uint8), dictionary[idx].view(np.uint8)
    )


def test_dict_gather_bytes_int96():
    dictionary = RNG.integers(0, 1 << 32, size=(50, 3), dtype=np.uint32)
    idx = RNG.integers(0, 50, size=300)
    rows = dictionary.view(np.uint8).reshape(50, 12)
    out = K.dict_gather_bytes(
        jnp.asarray(rows), jnp.asarray(idx, dtype=jnp.uint32), "uint32"
    )
    np.testing.assert_array_equal(np.asarray(out), dictionary[idx])


def test_ragged_take_matches_host():
    items = [f"str-{i % 37}".encode() * (i % 5) for i in range(50)]
    bad = ByteArrayData.from_list(items)
    idx = RNG.integers(0, 50, size=200)
    host = bad.take(idx)
    out_heap = int((bad.offsets[idx + 1] - bad.offsets[idx]).sum())
    off, heap = K.ragged_take(
        jnp.asarray(bad.offsets), jnp.asarray(bad.heap),
        jnp.asarray(idx), out_heap,
    )
    np.testing.assert_array_equal(np.asarray(off), host.offsets)
    np.testing.assert_array_equal(np.asarray(heap)[:out_heap], host.heap)


# ---------------------------------------------------------------------------
# level reconstruction
# ---------------------------------------------------------------------------

def test_scatter_defined():
    validity = np.array([1, 0, 1, 1, 0, 0, 1], dtype=bool)
    values = np.array([10, 20, 30, 40], dtype=np.int64)
    out = K.scatter_defined(jnp.asarray(values), jnp.asarray(validity), -1)
    np.testing.assert_array_equal(
        np.asarray(out), np.array([10, -1, 20, 30, -1, -1, 40])
    )


def test_row_starts():
    rep = np.array([0, 1, 1, 0, 0, 1, 0], dtype=np.int32)
    starts, row_idx = K.row_starts_from_rep(jnp.asarray(rep))
    np.testing.assert_array_equal(
        np.asarray(starts), np.array([1, 0, 0, 1, 1, 0, 1], dtype=bool)
    )
    np.testing.assert_array_equal(np.asarray(row_idx), np.array([0, 0, 0, 1, 2, 2, 3]))


# ---------------------------------------------------------------------------
# PLAIN / BYTE_STREAM_SPLIT
# ---------------------------------------------------------------------------

def _from_device(out, dtype, n):
    """f64 device representation is uint32[n,2] word pairs; view back."""
    arr = np.asarray(out)
    if dtype == "float64":
        return np.ascontiguousarray(arr).view("<f8").reshape(n)
    return arr


@pytest.mark.parametrize("dtype", ["int32", "int64", "float32", "float64"])
def test_plain_decode_fixed(dtype):
    vals = RNG.standard_normal(500).astype(dtype) if dtype.startswith("f") \
        else RNG.integers(-(1 << 30), 1 << 30, size=500).astype(dtype)
    out = K.plain_decode_fixed(jd.pad_buffer(vals.tobytes()), dtype, 500)
    np.testing.assert_array_equal(_from_device(out, dtype, 500), vals)


@pytest.mark.parametrize("dtype", ["float32", "float64"])
def test_byte_stream_split(dtype):
    vals = RNG.standard_normal(300).astype(dtype)
    w = vals.dtype.itemsize
    interleaved = vals.view(np.uint8).reshape(300, w).T.copy().tobytes()
    out = K.byte_stream_split_decode(jd.pad_buffer(interleaved), dtype, 300)
    np.testing.assert_array_equal(_from_device(out, dtype, 300), vals)


# ---------------------------------------------------------------------------
# scoped x64: the library must never flip the caller's global setting
# ---------------------------------------------------------------------------

def test_scoped_x64_leaves_global_setting_alone():
    """Device decode works without jax_enable_x64, and never turns it on.

    VERDICT round 1, weak #6: an import-time global x64 flip makes the library
    hostile as a training-pipeline dependency.  Every public entry point now
    scopes x64 to the call (jax_kernels.scoped_x64); a co-resident program's
    default x32 semantics must survive a full 64-bit decode.
    """
    import jax

    assert not jax.config.jax_enable_x64, "test harness should run under x32"
    dictionary = RNG.integers(-(1 << 40), 1 << 40, size=16)
    idx = RNG.integers(0, 16, size=64)
    out = K.dict_gather(dictionary, jnp.asarray(idx, dtype=jnp.uint32))
    assert out.dtype == jnp.int64
    np.testing.assert_array_equal(np.asarray(out), dictionary[idx])
    # the global flag is still off, and new arrays still get x32 semantics
    assert not jax.config.jax_enable_x64
    assert jnp.asarray(np.int64(1)).dtype == jnp.int32


# ---------------------------------------------------------------------------
# native meta-parser hostile-input regressions (meta_parse.cpp)
# ---------------------------------------------------------------------------

def _varint(v):
    out = b""
    while True:
        b = v & 0x7F
        v >>= 7
        out += bytes([b | (0x80 if v else 0)])
        if not v:
            return out


def test_delta_meta_huge_block_size_rejected():
    """block_size=2^63 once segfaulted the C walk via i64 truncation; both
    walks must reject it as a DeltaError (decompression-bomb guard)."""
    evil = (_varint(1 << 63) + _varint(1) + _varint(100) + _varint(0)
            + _varint(0) + bytes(16))
    for fn in (lambda b: jd._native_delta_meta(b, 0),
               lambda b: jd._parse_delta_meta_py(b, 64, 0)):
        with pytest.raises(jd.DeltaError):
            fn(evil)


def test_hybrid_meta_width0_huge_groups_parity():
    """width-0 bit-packed run with groups=2^61: (i64)(groups*8) once
    truncated to 0 and stalled the C walk where Python accepted the run."""
    evil = _varint((1 << 61 << 1) | 1)
    a = jd._native_hybrid_meta(evil, len(evil), 0, 0, 5, False)
    b = jd._parse_hybrid_meta_py(evil, 0, 5, 0, len(evil))
    if a is None:
        pytest.skip("native library unavailable")
    assert a.n_runs == b.n_runs and a.consumed == b.consumed
    np.testing.assert_array_equal(a.run_ends, b.run_ends)
