"""Fused decode megakernel tests (ISSUE 13 / ROADMAP direction 2).

The contract under test: the fused routes (`fused_plain`,
`fused_narrow_snappy`) decode BIT-IDENTICALLY to the host reader across
prefetch={0,4} and validate_crc on/off — the megakernels only fuse device
passes, they never own different semantics — and degrade to their unfused
twins (with a counter, never a crash) wherever they cannot claim a stream.
On CPU the whole fused graph runs through the Pallas interpreter
(TPQ_FUSE=1), so tier-1 proves the exact graph a TPU compiles.  The
registry ``device`` section's ``device_passes`` counter is the structural
proof of fusion: one pass per dispatch on fused routes, >=3 on the staged
chains.
"""

import json
import os

import numpy as np
import pytest

from tpu_parquet import native
from tpu_parquet.column import ColumnData
from tpu_parquet.device_reader import DeviceFileReader
from tpu_parquet.format import CompressionCodec, FieldRepetitionType as FRT, Type
from tpu_parquet.reader import FileReader
from tpu_parquet.schema.core import build_schema, data_column
from tpu_parquet.ship import (
    FUSED_ROUTES, ROUTE_FUSED_NARROW_SNAPPY, ROUTE_FUSED_PLAIN,
    ROUTE_NARROW_SNAPPY, ROUTE_PLAIN, ROUTES, UNFUSED_OF, ChunkFacts,
    ShipPlanner, fused_eligible, parse_route,
)
from tpu_parquet.writer import FileWriter, corrupt_page

# group size chosen so the narrow transcode clears the planner's
# MIN_COMPRESS_BYTES gate (narrowed k=2 bytes/value * 40k values = 80 KiB)
# — the fused_narrow_snappy row must be PRICED, not just forceable
N = 80_000
ROWS_PER_GROUP = 40_000


def _columns():
    rng = np.random.default_rng(23)
    return {
        # date-like with run structure: narrow k=2 output is low-entropy
        # AND snappy's matches reference nearby literals (shallow copy
        # chains) — the fused narrow+snappy kernel's home turf
        "dates": np.repeat(19_000 + rng.integers(0, 1200, N // 50),
                           50).astype(np.int64),
        # full 63-bit range: every shrink route declines; the fused PLAIN
        # kernel's lane (the plain_int64 debt)
        "wide": rng.integers(-(1 << 62), 1 << 62, N),
        # 32-bit lanes through both kernels
        "cnt": rng.integers(0, 50_000, N).astype(np.int32),
        "rate": rng.uniform(0, 1, N).astype(np.float32),
        "dbl": np.repeat(rng.uniform(0.0, 1.0, N // 100), 100),
    }


def _schema():
    return build_schema([
        data_column("dates", Type.INT64, FRT.REQUIRED),
        data_column("wide", Type.INT64, FRT.REQUIRED),
        data_column("cnt", Type.INT32, FRT.REQUIRED),
        data_column("rate", Type.FLOAT, FRT.REQUIRED),
        data_column("dbl", Type.DOUBLE, FRT.REQUIRED),
    ])


@pytest.fixture(scope="module")
def fused_file(tmp_path_factory):
    root = tmp_path_factory.mktemp("fused")
    cols = _columns()
    p = str(root / "fused.parquet")
    with FileWriter(p, _schema(), codec=CompressionCodec.SNAPPY,
                    write_crc=True, use_dictionary=False) as w:
        for lo in range(0, N, ROWS_PER_GROUP):
            w.write_columns({k: v[lo:lo + ROWS_PER_GROUP]
                             for k, v in cols.items()})
            w.flush_row_group()
    return p, cols


def _host_groups(path, **kw):
    out = []
    with FileReader(path, **kw) as r:
        for rg in r.iter_row_groups():
            out.append({k: np.asarray(v.values) for k, v in rg.items()})
    return out


def _assert_device_matches(path, host, prefetch=0, **kw):
    with DeviceFileReader(path, prefetch=prefetch, **kw) as r:
        n = 0
        for i, rg in enumerate(r.iter_row_groups()):
            for k, col in rg.items():
                g, w = np.asarray(col.to_host()), host[i][k]
                assert g.dtype == w.dtype, (k, g.dtype, w.dtype)
                assert np.array_equal(g.view(np.uint8).reshape(-1),
                                      w.view(np.uint8).reshape(-1)), k
            n += 1
        assert n == len(host)
        return r


# ---------------------------------------------------------------------------
# bit-identity matrix: fused route x prefetch x validate_crc
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("crc", [None, False])
@pytest.mark.parametrize("prefetch", [0, 4])
@pytest.mark.parametrize("route", list(FUSED_ROUTES))
def test_fused_route_bit_identical(fused_file, route, prefetch, crc,
                                   monkeypatch):
    path, _ = fused_file
    monkeypatch.setenv("TPQ_FUSE", "1")
    monkeypatch.setenv("TPQ_FORCE_ROUTE", route)
    host = _host_groups(path)
    r = _assert_device_matches(path, host, prefetch=prefetch,
                               validate_crc=crc)
    st = r.stats().as_dict()
    # the forced fused route actually RAN where it could (dates always
    # qualifies for both kernels on this file)
    assert st["ship_routes"].get(route, {}).get("streams", 0) >= 1, \
        st["ship_routes"]


def test_planned_fused_bit_identical(fused_file, monkeypatch):
    """TPQ_FUSE=1 with no force: the PLANNER picks fused rows where they
    rank (the plain tie goes to fused_plain) and the scan stays
    bit-identical."""
    path, _ = fused_file
    monkeypatch.delenv("TPQ_FORCE_ROUTE", raising=False)
    monkeypatch.setenv("TPQ_FUSE", "1")
    host = _host_groups(path)
    r = _assert_device_matches(path, host)
    routes = set(r.stats().as_dict()["ship_routes"])
    assert routes & set(FUSED_ROUTES), routes


def test_fuse_off_never_routes_fused(fused_file, monkeypatch):
    path, _ = fused_file
    monkeypatch.delenv("TPQ_FORCE_ROUTE", raising=False)
    monkeypatch.setenv("TPQ_FUSE", "0")
    host = _host_groups(path)
    r = _assert_device_matches(path, host)
    assert not set(r.stats().as_dict()["ship_routes"]) & set(FUSED_ROUTES)


# ---------------------------------------------------------------------------
# quarantine containment through a fused kernel's unit
# ---------------------------------------------------------------------------

def test_fused_corrupt_page_containment(fused_file, tmp_path, monkeypatch):
    """A corrupt page on a chunk HEADED FOR a fused kernel: skip_unit
    accounting is exact and every surviving row group stays bit-identical
    — corruption containment (PR 8) is policy-layer, and fusion must not
    re-open it."""
    import shutil

    src, _ = fused_file
    path = str(tmp_path / "corrupt.parquet")
    shutil.copyfile(src, path)
    # column 0 is `dates` — the stream both fused kernels claim
    corrupt_page(path, row_group=1, column=0, page=0, mode="bitflip",
                 seed=3)
    monkeypatch.setenv("TPQ_FUSE", "1")
    host = _host_groups(src)
    for route in FUSED_ROUTES:
        monkeypatch.setenv("TPQ_FORCE_ROUTE", route)
        with DeviceFileReader(path, on_data_error="skip_unit") as r:
            got = list(r.iter_row_groups())
            q = r.quarantine
            assert q.units_skipped == 1
            recs = q.log.snapshot()
            assert len(recs) == 1 and recs[0]["row_group"] == 1
        assert len(got) == 1  # group 1 quarantined, group 0 survives
        for k, col in got[0].items():
            g, w = np.asarray(col.to_host()), host[0][k]
            assert np.array_equal(g.view(np.uint8).reshape(-1),
                                  w.view(np.uint8).reshape(-1)), (route, k)


# ---------------------------------------------------------------------------
# planner: fused rows, tie preference, eligibility
# ---------------------------------------------------------------------------

def test_planner_offers_fused_rows():
    p = ShipPlanner(link_mbps=350.0, force=None, fuse=True)
    f = ChunkFacts(logical=8 << 20, width=8, narrow_k=3,
                   narrow_possible=True, flat=True)
    order, costs = p.plan(f)
    assert ROUTE_FUSED_PLAIN in costs
    assert ROUTE_FUSED_NARROW_SNAPPY in costs
    # no inter-stage HBM term: the fused device lane is the single pass,
    # strictly below the unfused composite
    dev = p.device_costs(f, routes=costs)
    assert dev[ROUTE_FUSED_NARROW_SNAPPY] < dev[ROUTE_NARROW_SNAPPY]
    # the spill-inclusive unfused prediction (fusion-win's bar) exceeds
    # the fused model for both rows
    unf = p.unfused_device_costs(f, routes=costs)
    for fr in FUSED_ROUTES:
        assert unf[fr] > dev[fr]
    # equal-cost tie goes to fused: plain and fused_plain share host/link
    # terms on a link-bound stream
    if costs[ROUTE_FUSED_PLAIN] == costs[ROUTE_PLAIN]:
        assert order.index(ROUTE_FUSED_PLAIN) < order.index(ROUTE_PLAIN)


def test_planner_fuse_off_and_ineligible():
    off = ShipPlanner(fuse=False)
    f = ChunkFacts(logical=8 << 20, width=8, flat=True)
    assert not set(off.costs(f)) & set(FUSED_ROUTES)
    on = ShipPlanner(fuse=True)
    # not flat (level lanes) / width 0: no fused rows even with fuse on
    assert not set(on.costs(ChunkFacts(logical=8 << 20, width=8,
                                       flat=False))) & set(FUSED_ROUTES)
    assert not set(on.costs(ChunkFacts(logical=8 << 20,
                                       width=0))) & set(FUSED_ROUTES)
    assert fused_eligible(ChunkFacts(logical=1 << 20, width=8)) == \
        FUSED_ROUTES
    assert fused_eligible(ChunkFacts(logical=0, width=8)) == ()


def test_route_registry_is_single_table():
    """Satellite: one route-name registry.  The fused names are in ROUTES
    (so TPQ_FORCE_ROUTE and the ScanPlan route memo accept them), every
    fused name maps to its twin, and parse_route is the one env-validation
    entry point (degrades, never raises)."""
    from tpu_parquet.scanplan import ScanPlan

    for fr in FUSED_ROUTES:
        assert fr in ROUTES
        assert UNFUSED_OF[fr] in ROUTES
    assert parse_route("fused_plain") == ROUTE_FUSED_PLAIN
    assert parse_route(" fused_narrow_snappy ") == ROUTE_FUSED_NARROW_SNAPPY
    assert parse_route("warp-speed") is None
    assert parse_route("") is None
    # the plan IR memoizes fused routes like any other (replay hint)
    plan = ScanPlan(row_groups=[])
    plan.note_route(0, "a", ROUTE_FUSED_NARROW_SNAPPY, "fused")
    assert plan.route_hint(0, "a") == ROUTE_FUSED_NARROW_SNAPPY


def test_forced_fused_on_ineligible_degrades(tmp_path, monkeypatch):
    """Forced fused on a nullable column (level lanes) degrades to the
    unfused route with a COUNTER, not a crash — and stays correct."""
    schema = build_schema([data_column("v", Type.INT64, FRT.OPTIONAL)])
    rng = np.random.default_rng(5)
    defs = (rng.uniform(size=4000) < 0.9).astype(np.int32)
    vals = rng.integers(0, 1 << 40, int(defs.sum()))
    p = str(tmp_path / "opt.parquet")
    with FileWriter(p, schema, codec=CompressionCodec.SNAPPY,
                    use_dictionary=False) as w:
        w.write_columns({"v": ColumnData(values=vals, def_levels=defs,
                                         max_def=1, max_rep=0)})
    monkeypatch.setenv("TPQ_FUSE", "1")
    host = _host_groups(p)
    for route in FUSED_ROUTES:
        monkeypatch.setenv("TPQ_FORCE_ROUTE", route)
        with DeviceFileReader(p) as r:
            for i, rg in enumerate(r.iter_row_groups()):
                got = np.asarray(rg["v"].to_host())
                assert np.array_equal(got, host[i]["v"])
            st = r.stats().as_dict()
        assert st["fused_fallbacks"] >= 1
        assert not set(st["ship_routes"]) & set(FUSED_ROUTES)


# ---------------------------------------------------------------------------
# structural proof: one device pass per fused dispatch, >=3 on the chains
# ---------------------------------------------------------------------------

def _device_routes(path, route, monkeypatch):
    monkeypatch.setenv("TPQ_FORCE_ROUTE", route)
    with DeviceFileReader(path) as r:
        for _ in r.iter_row_groups():
            pass
        return (r.obs_registry().as_dict().get("device") or {}) \
            .get("routes") or {}


@pytest.mark.parametrize("fused_route", list(FUSED_ROUTES))
def test_fused_one_pass_per_dispatch(fused_file, fused_route, monkeypatch):
    """The acceptance bar: fused routes show exactly ONE device pass per
    (row group, column) dispatch in the registry; the unfused twin's chain
    shows >=3 per dispatch on the same file."""
    path, _ = fused_file
    monkeypatch.setenv("TPQ_FUSE", "1")
    dev = _device_routes(path, fused_route, monkeypatch)
    c = dev.get(fused_route)
    assert c is not None and c["dispatches"] >= 1, dev
    assert c["device_passes"] == c["dispatches"], c
    un = _device_routes(path, UNFUSED_OF[fused_route], monkeypatch)
    uc = un.get(UNFUSED_OF[fused_route])
    assert uc is not None and uc["dispatches"] >= 1, un
    assert uc["device_passes"] >= 3 * uc["dispatches"], uc


# ---------------------------------------------------------------------------
# satellites: cached availability, ledger fingerprint, doctor fusion-win
# ---------------------------------------------------------------------------

def test_pallas_available_probed_once(monkeypatch):
    from tpu_parquet import pallas_kernels as pk

    calls = {"n": 0}
    real = pk.jax.devices

    def counting(*a, **k):
        calls["n"] += 1
        return real(*a, **k)

    monkeypatch.setattr(pk.jax, "devices", counting)
    pk._reset_available_cache()
    try:
        first = pk.pallas_available()
        for _ in range(10):
            assert pk.pallas_available() == first
        assert calls["n"] == 1  # one probe, cached thereafter
        assert pk.pallas_mode() in ("compiled", "interpret")
    finally:
        pk._reset_available_cache()


def test_ledger_fingerprint_records_pallas_mode(monkeypatch):
    from tpu_parquet.ledger import env_fingerprint
    from tpu_parquet.pallas_kernels import pallas_mode

    monkeypatch.setenv("TPQ_FUSE", "1")
    fp = env_fingerprint()
    assert fp["TPQ_FUSE"] == "1"
    assert fp["pallas_mode"] == pallas_mode()  # interpret on CPU CI


def test_doctor_fusion_win(tmp_path):
    import argparse
    import io

    from tpu_parquet.cli.pq_tool import cmd_doctor
    from tpu_parquet.obs import OBS_VERSION, doctor_registry

    tree = {
        "obs_version": OBS_VERSION,
        "pipeline": {"stage_seconds": 0.1},
        "reader": {
            "host_seconds": 0.05, "staged_bytes": 1 << 20,
            "ship_routes": {
                "fused_narrow_snappy": {
                    "streams": 4, "logical": 4 << 20, "shipped": 1 << 20,
                    "predicted_s": 0.01, "predicted_device_s": 0.002,
                    "predicted_unfused_device_s": 0.02,
                },
            },
        },
        "device": {
            "dispatches": 4, "device_seconds": 0.005,
            "routes": {"fused_narrow_snappy": {
                "dispatches": 4, "device_seconds": 0.005,
                "bytes_in": 4 << 20, "bytes_staged": 1 << 20,
                "device_passes": 4}},
            "kernels": {"fused": {"dispatches": 4,
                                  "device_seconds": 0.005}},
            "h2d": {"transfers": 1, "device_seconds": 0.001,
                    "bytes": 1 << 20},
        },
    }
    rep = doctor_registry(tree)
    fw = rep.get("fusion_win")
    assert fw is not None
    assert fw["route"] == "fused_narrow_snappy"
    assert fw["speedup"] == pytest.approx(0.02 / 0.005, rel=1e-3)
    # a slower-than-predicted fused lane reports NO win
    worse = json.loads(json.dumps(tree))
    worse["device"]["routes"]["fused_narrow_snappy"]["device_seconds"] = 0.5
    assert doctor_registry(worse).get("fusion_win") is None
    # the CLI renders it
    p = tmp_path / "reg.json"
    p.write_text(json.dumps(tree))
    buf = io.StringIO()
    assert cmd_doctor(argparse.Namespace(file=str(p), config=None),
                      out=buf) == 0
    out = buf.getvalue()
    assert "fusion-win" in out and "fused_narrow_snappy" in out


def test_fused_routes_ride_ship_feedback(fused_file, monkeypatch):
    """The obs spine treats fused routes uniformly: ship_feedback carries
    the fused route with its unfused device prediction, and the device
    section names the `fused` kernel family."""
    path, _ = fused_file
    monkeypatch.setenv("TPQ_FUSE", "1")
    monkeypatch.setenv("TPQ_FORCE_ROUTE", ROUTE_FUSED_NARROW_SNAPPY)
    with DeviceFileReader(path) as r:
        for _ in r.iter_row_groups():
            pass
        tree = r.obs_registry().as_dict()
    fb = tree["reader"]["ship_feedback"]["routes"]
    rec = fb.get(ROUTE_FUSED_NARROW_SNAPPY)
    assert rec is not None
    assert rec["device_unfused_predicted_seconds"] is not None
    assert rec["device_unfused_predicted_seconds"] > 0
    assert "fused" in (tree["device"] or {}).get("kernels", {})
    json.dumps(tree)  # artifact-ready
