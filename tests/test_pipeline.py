"""Overlapped chunk pipeline (tpu_parquet/pipeline.py + prefetch= readers).

ISSUE 1 coverage: bit-identical output across prefetch={0,1,4} with and
without CRC validation, a mid-file corrupt page raising cleanly without
deadlocking or leaking pool threads, and the max_memory budget bounding
in-flight bytes (backpressure, not OOM) — plus unit tests of prefetch_map
ordering/cleanup and InFlightBudget semantics.
"""

import threading
import time

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from tpu_parquet.alloc import InFlightBudget
from tpu_parquet.column import ByteArrayData
from tpu_parquet.footer import ParquetError
from tpu_parquet.pipeline import PipelineStats, prefetch_map
from tpu_parquet.reader import FileReader


def _leaked_pool_threads():
    return [t for t in threading.enumerate()
            if t.is_alive() and t.name.startswith("tpq-prefetch")]


def _make_file(path, rows=40_000, row_group_size=5_000, compression="snappy"):
    rng = np.random.default_rng(11)
    vals = [None if rng.random() < 0.2 else int(v)
            for v in rng.integers(0, 1 << 40, rows)]
    strs = [None if rng.random() < 0.2 else f"name_{i % 257:04d}"
            for i in range(rows)]
    table = pa.table({
        "v": pa.array(vals, pa.int64()),
        "d": pa.array(rng.uniform(0, 1e6, rows), pa.float64()),
        "s": pa.array(strs, pa.string()),
        "k": pa.array(rng.integers(0, 50, rows), pa.int32()),
    })
    pq.write_table(table, path, compression=compression,
                   row_group_size=row_group_size)
    return path


@pytest.fixture(scope="module")
def pfile(tmp_path_factory):
    return str(_make_file(tmp_path_factory.mktemp("pipe") / "p.parquet"))


def _assert_same_columns(a, b):
    assert set(a) == set(b)
    for name in a:
        ca, cb = a[name], b[name]
        assert ca.num_leaf_slots == cb.num_leaf_slots, name
        assert ca.max_def == cb.max_def and ca.max_rep == cb.max_rep, name
        for attr in ("def_levels", "rep_levels"):
            xa, xb = getattr(ca, attr), getattr(cb, attr)
            assert (xa is None) == (xb is None), name
            if xa is not None:
                np.testing.assert_array_equal(xa, xb)
        if isinstance(ca.values, ByteArrayData):
            np.testing.assert_array_equal(ca.values.offsets, cb.values.offsets)
            np.testing.assert_array_equal(ca.values.heap, cb.values.heap)
        else:
            np.testing.assert_array_equal(ca.values, cb.values)


# ---------------------------------------------------------------------------
# correctness: bit-identical across prefetch depths
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("validate_crc", [False, True])
def test_bit_identical_across_prefetch(pfile, validate_crc):
    outs = {}
    for k in (0, 1, 4):
        with FileReader(pfile, validate_crc=validate_crc, prefetch=k) as r:
            groups = list(r.iter_row_groups())
            outs[k] = r.read_all()
            stats = r.pipeline_stats()
        assert len(groups) == 8  # 40k rows / 5k per group
        if k:
            assert stats.chunks == 8 * 4
            assert stats.row_groups == 8
            assert stats.stage_seconds("decompress") > 0
    _assert_same_columns(outs[0], outs[1])
    _assert_same_columns(outs[0], outs[4])
    assert not _leaked_pool_threads()


def test_bit_identical_bytes_source(pfile):
    """The locked seek+read SharedReader path (no usable fd)."""
    with open(pfile, "rb") as f:
        raw = f.read()
    seq = FileReader(raw).read_all()
    pipe = FileReader(raw, prefetch=4).read_all()
    _assert_same_columns(seq, pipe)


def test_read_row_group_and_projection_parity(pfile):
    with FileReader(pfile, columns=["v", "s"]) as r0, \
            FileReader(pfile, columns=["v", "s"], prefetch=3) as r4:
        for i in (0, 3, 7):
            _assert_same_columns(r0.read_row_group(i), r4.read_row_group(i))
        # per-call override: pipelined reader forced sequential and back
        _assert_same_columns(r4.read_row_group(1, prefetch=0),
                             r0.read_row_group(1, prefetch=4))


def test_gzip_codec_parity(tmp_path):
    p = str(_make_file(tmp_path / "g.parquet", rows=8_000,
                       row_group_size=2_000, compression="gzip"))
    _assert_same_columns(FileReader(p).read_all(),
                         FileReader(p, prefetch=4).read_all())


# ---------------------------------------------------------------------------
# corruption: ordered raise, no deadlock, no leaked threads
# ---------------------------------------------------------------------------

def _corrupt_mid_file(pfile, tmp_path):
    with FileReader(pfile) as r:
        md = r.metadata.row_groups[4].columns[0].meta_data
        off = md.data_page_offset
        if (md.dictionary_page_offset is not None
                and md.dictionary_page_offset >= 0):
            off = min(off, md.dictionary_page_offset)
    with open(pfile, "rb") as f:
        raw = bytearray(f.read())
    raw[off:off + 64] = b"\xff" * 64
    bad = tmp_path / "corrupt.parquet"
    bad.write_bytes(bytes(raw))
    return str(bad)


@pytest.mark.parametrize("validate_crc", [False, True])
def test_corrupt_mid_file_page_raises_cleanly(pfile, tmp_path, validate_crc):
    bad = _corrupt_mid_file(pfile, tmp_path)
    t0 = time.perf_counter()
    with FileReader(bad, validate_crc=validate_crc, prefetch=4) as r:
        good = 0
        with pytest.raises(ParquetError):
            for _ in r.iter_row_groups():
                good += 1
        # groups before the corrupt one decoded fine and in order
        assert good == 4
    assert time.perf_counter() - t0 < 60  # no deadlock
    assert not _leaked_pool_threads()


def test_early_abandon_shuts_pool_down(pfile):
    with FileReader(pfile, prefetch=4) as r:
        it = r.iter_row_groups()
        next(it)
        it.close()  # consumer walks away mid-pipeline
    assert not _leaked_pool_threads()


# ---------------------------------------------------------------------------
# memory budget: bounded in-flight bytes, backpressure instead of raise
# ---------------------------------------------------------------------------

def test_max_memory_bounds_in_flight_bytes(pfile):
    with FileReader(pfile) as r:
        costs = []
        for rg in r.metadata.row_groups:
            for cc in rg.columns:
                md = cc.meta_data
                comp = md.total_compressed_size
                costs.append(comp + max(md.total_uncompressed_size or 0, comp))
        baseline = r.read_all()
    budget = 2 * max(costs) + 1024  # room for ~2 chunks, far below the file
    assert budget < sum(costs)
    with FileReader(pfile, max_memory=budget, prefetch=4) as r:
        out = r.read_all()
        stats = r.pipeline_stats()
    _assert_same_columns(baseline, out)
    assert 0 < stats.peak_in_flight_bytes <= budget
    assert stats.as_dict()["budget_bytes"] == budget


# ---------------------------------------------------------------------------
# prefetch_map / InFlightBudget units
# ---------------------------------------------------------------------------

def test_prefetch_map_orders_results():
    def work(i):
        time.sleep(0.02 if i % 3 == 0 else 0.001)  # scramble completion order
        return i * i

    assert list(prefetch_map(range(20), work, 4)) == [i * i for i in range(20)]
    assert not _leaked_pool_threads()


def test_prefetch_map_error_position_and_cleanup():
    seen = []

    def work(i):
        if i == 5:
            raise ValueError("boom")
        seen.append(i)
        return i

    out = []
    with pytest.raises(ValueError, match="boom"):
        for v in prefetch_map(range(10), work, 3):
            out.append(v)
    assert out == [0, 1, 2, 3, 4]  # everything before the failing item
    assert not _leaked_pool_threads()


def test_prefetch_map_consumer_break_cleans_up():
    def work(i):
        time.sleep(0.005)
        return i

    for v in prefetch_map(range(100), work, 4):
        if v == 3:
            break
    assert not _leaked_pool_threads()


def test_prefetch_map_budget_backpressure():
    budget = InFlightBudget(100)
    stats = PipelineStats(prefetch=2, budget_bytes=100)
    in_flight = []
    lock = threading.Lock()
    peak = [0]

    def work(i):
        with lock:
            in_flight.append(i)
            peak[0] = max(peak[0], len(in_flight))
        time.sleep(0.005)
        with lock:
            in_flight.remove(i)
        return i

    out = list(prefetch_map(range(12), work, 4, budget=budget,
                            cost=lambda i: 40, stats=stats))
    assert out == list(range(12))
    assert budget.held == 0
    assert budget.peak <= 100  # never more than 2 x 40 in flight
    assert peak[0] <= 2


def test_in_flight_budget_oversize_admitted_alone():
    b = InFlightBudget(100)
    b.acquire(1000)  # capped at the budget, admitted with nothing in flight
    assert b.held == 100
    assert not b.try_acquire(1)  # nothing else fits alongside
    b.release(1000)
    assert b.held == 0
    assert b.try_acquire(60) and not b.try_acquire(60)
    b.release(60)


def test_in_flight_budget_disabled():
    b = InFlightBudget(0)
    b.acquire(1 << 40)
    assert b.try_acquire(1 << 40)
    b.release(1 << 40)
    assert b.held == 0 and b.peak == 0


# ---------------------------------------------------------------------------
# device reader + scan_files prefetch parity
# ---------------------------------------------------------------------------

def _host_view(col):
    if hasattr(col, "to_host") and callable(getattr(col, "to_host")):
        try:
            col = col.to_host()
        except Exception:  # plain DeviceColumnData has no to_host
            pass
    if isinstance(col, ByteArrayData):
        return np.asarray(col.offsets), np.asarray(col.heap)
    if isinstance(col, np.ndarray):
        return (col,)
    if getattr(col, "values", None) is not None:
        v = np.asarray(col.values)
        n = getattr(col, "n_values", None)
        return (v[:n] if n is not None else v,)
    return np.asarray(col.offsets), np.asarray(col.heap)


def test_device_reader_prefetch_parity(pfile):
    from tpu_parquet.device_reader import DeviceFileReader

    def read(k):
        with DeviceFileReader(pfile, prefetch=k) as r:
            groups = [{n: _host_view(c) for n, c in cols.items()}
                      for cols in r.iter_row_groups()]
            stats = r.pipeline_stats()
        return groups, stats

    g0, _ = read(0)
    g4, s4 = read(4)
    assert len(g0) == len(g4) == 8
    for a, b in zip(g0, g4):
        assert set(a) == set(b)
        for name in a:
            for xa, xb in zip(a[name], b[name]):
                np.testing.assert_array_equal(xa, xb)
    assert s4.chunks == 8 * 4
    assert s4.stage_seconds("decompress") > 0
    assert s4.stage_seconds("dispatch") > 0
    assert not _leaked_pool_threads()


def test_scan_files_prefetch_parity(pfile):
    from tpu_parquet.device_reader import scan_files

    def read(k):
        return [{n: _host_view(c) for n, c in cols.items()}
                for cols in scan_files([pfile, pfile], prefetch=k)]

    g0 = read(0)
    g4 = read(4)
    assert len(g0) == len(g4) == 16
    for a, b in zip(g0, g4):
        for name in a:
            for xa, xb in zip(a[name], b[name]):
                np.testing.assert_array_equal(xa, xb)
    assert not _leaked_pool_threads()


def test_device_prefetch_with_row_filter(tmp_path):
    """The pruning planner runs inside the chunk feed (thread-safe header
    walks through the pread view); yielded groups/pages must match the
    sequential filtered scan exactly."""
    from tpu_parquet.device_reader import DeviceFileReader
    from tpu_parquet.predicate import col

    rng = np.random.default_rng(5)
    n = 20_000
    table = pa.table({
        "k": pa.array(np.sort(rng.integers(0, 1000, n)), pa.int64()),
        "x": pa.array(rng.uniform(0, 1, n), pa.float64()),
    })
    p = str(tmp_path / "f.parquet")
    pq.write_table(table, p, compression="snappy", row_group_size=2_500)
    pred = col("k") < 200

    def read(k):
        with DeviceFileReader(p, row_filter=pred, prefetch=k) as r:
            groups = [{nm: _host_view(c) for nm, c in cols.items()}
                      for cols in r.iter_row_groups()]
            pruned = r.stats().pages_pruned
        return groups, pruned

    g0, pruned0 = read(0)
    g4, pruned4 = read(4)
    assert len(g0) == len(g4) and len(g0) > 0
    assert pruned0 == pruned4
    for a, b in zip(g0, g4):
        for name in a:
            for xa, xb in zip(a[name], b[name]):
                np.testing.assert_array_equal(xa, xb)
    assert not _leaked_pool_threads()


def test_shard_scan_row_groups_pipelined(pfile):
    from tpu_parquet.parallel import shard_scan_row_groups

    with FileReader(pfile) as r:
        seq = {i: out for i, out in shard_scan_row_groups(r, 0, 2)}
        seq.update({i: out for i, out in shard_scan_row_groups(r, 1, 2)})
    with FileReader(pfile) as r:
        pipe = {}
        for s in (0, 1):
            for i, out in shard_scan_row_groups(r, s, 2, prefetch=3):
                pipe[i] = out
    assert set(seq) == set(pipe) == set(range(8))
    for i in seq:
        _assert_same_columns(seq[i], pipe[i])
