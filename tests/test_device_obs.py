"""Device-lane observability tests (ISSUE 9).

Covers the tentpole end to end: the completion-side per-route device timing
lane (``TPQ_DEVICE_TIMING``: DeviceStats golden keys, registry ``device``
section merge paths incl. a 2-OS-process round trip, the <3% disabled-path
overhead guard, the stage/dispatch split replacing the double-counted
``device_seconds`` scalar), HBM residency accounting on ``AllocTracker``
(sampler track + flight dump watermark), the planner's device-lane feedback
(``ship.device_costs`` / ``recalibrate_device_mbps``, ``ship_feedback``
device lane null contract, doctor's ``h2d-bound`` sibling and dominant
route/kernel naming), graceful degradation on artifacts predating the
``device`` section, the CPU-only/no-backend drop path, and the bounded
``TPQ_XPROF`` capture window.
"""

import io
import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from tpu_parquet.obs import (
    OBS_VERSION, StatsRegistry, Tracer, doctor_registry, trace_summary,
)
from tpu_parquet.pipeline import PipelineStats

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _write_ints(path, rows=120_000, groups=3, seed=0):
    from tpu_parquet.format import FieldRepetitionType as FRT, Type
    from tpu_parquet.schema.core import build_schema, data_column
    from tpu_parquet.writer import FileWriter

    rng = np.random.default_rng(seed)
    schema = build_schema([
        data_column("v", Type.INT64, FRT.REQUIRED),
        data_column("w", Type.INT32, FRT.REQUIRED),
    ])
    per = rows // groups
    with FileWriter(path, schema, row_group_size=1) as w:
        for _ in range(groups):
            w.write_columns({
                "v": rng.integers(0, 1 << 40, per),
                "w": rng.integers(0, 1000, per).astype(np.int32),
            })
            w.flush_row_group()
    return path


# ---------------------------------------------------------------------------
# DeviceStats + registry `device` section (golden keys, merge paths)
# ---------------------------------------------------------------------------

def _device_stats():
    from tpu_parquet.device_reader import DeviceStats

    ds = DeviceStats()
    ds.note_dispatch("plain", "plain", 0.01, bytes_in=1000, bytes_staged=1000)
    ds.note_dispatch("device_snappy", "snappy_resolve", 0.03,
                     bytes_in=4000, bytes_staged=1500)
    ds.note_h2d(0.005, 2500)
    return ds


def test_device_stats_as_dict_golden_keys():
    d = _device_stats().as_dict()
    assert set(d) == {"dispatches", "device_seconds", "routes", "kernels",
                      "h2d"}
    assert d["dispatches"] == 2
    assert d["device_seconds"] == pytest.approx(0.04)
    assert set(d["routes"]) == {"plain", "device_snappy"}
    for r in d["routes"].values():
        assert set(r) == {"dispatches", "device_seconds", "bytes_in",
                          "bytes_staged", "device_passes"}
    assert set(d["kernels"]) == {"plain", "snappy_resolve"}
    for k in d["kernels"].values():
        assert set(k) == {"dispatches", "device_seconds"}
    assert set(d["h2d"]) == {"transfers", "device_seconds", "bytes"}
    assert d["h2d"]["bytes"] == 2500
    json.dumps(d)  # artifact-ready


def test_registry_device_section_merge_from_and_dict():
    """The device section composes like io/data_errors: flows add across
    add_device / merge_from / merge_dict (the 2-process seam)."""
    a = StatsRegistry()
    a.add_device(_device_stats())
    b = StatsRegistry()
    b.add_device(_device_stats())
    a.merge_from(b)
    t = a.as_dict()["device"]
    assert t["dispatches"] == 4
    assert t["routes"]["plain"]["dispatches"] == 2
    assert t["routes"]["device_snappy"]["bytes_in"] == 8000
    assert t["kernels"]["snappy_resolve"]["device_seconds"] == (
        pytest.approx(0.06))
    assert t["h2d"]["transfers"] == 2
    # serialized (cross-process) merge stacks on top
    a.merge_dict(b.as_dict())
    assert a.as_dict()["device"]["dispatches"] == 6


_CHILD = r"""
import json, sys
sys.path.insert(0, sys.argv[1])
from tpu_parquet.device_reader import DeviceStats
from tpu_parquet.obs import StatsRegistry

ds = DeviceStats()
for i in range(100):
    ds.note_dispatch("narrow", "narrow", 1e-4, bytes_in=10, bytes_staged=5)
ds.note_h2d(1e-3, 64)
reg = StatsRegistry()
reg.add_device(ds)
print(json.dumps(reg.as_dict()))
"""


def test_two_process_device_merge_roundtrip():
    outs = []
    for _ in range(2):
        res = subprocess.run(
            [sys.executable, "-c", _CHILD, REPO_ROOT],
            capture_output=True, text=True, timeout=120,
        )
        assert res.returncode == 0, res.stderr
        outs.append(json.loads(res.stdout))
    reg = StatsRegistry()
    for o in outs:
        reg.merge_dict(o)
    t = reg.as_dict()["device"]
    assert t["routes"]["narrow"]["dispatches"] == 200
    assert t["routes"]["narrow"]["device_seconds"] == pytest.approx(
        200 * 1e-4, rel=1e-6)
    assert t["kernels"]["narrow"]["dispatches"] == 200
    assert t["h2d"] == {"transfers": 2,
                        "device_seconds": pytest.approx(2e-3),
                        "bytes": 128}


# ---------------------------------------------------------------------------
# planner device lane (ship.device_costs / recalibrate_device_mbps)
# ---------------------------------------------------------------------------

def test_ship_planner_device_costs_keys_and_values():
    from tpu_parquet.ship import ChunkFacts, ROUTE_PLAIN, ShipPlanner

    p = ShipPlanner(link_mbps=350.0, device_mbps=3000.0)
    f = ChunkFacts(logical=8 << 20, width=8, narrow_k=2,
                   narrow_possible=True, native=True)
    costs = p.costs(f)
    dev = p.device_costs(f)
    assert set(dev) == set(costs)  # same feasibility, per-route
    assert dev[ROUTE_PLAIN] == 0.0  # reshape+bitcast: no device compute
    # the compressed routes charge the resolve per OUTPUT byte
    assert dev["recompress"] == pytest.approx((8 << 20) / 3000e6)
    # narrow widens to L; narrow_snappy ALSO resolves the narrowed stream
    # first — strictly more device work, and the same term costs() uses
    assert dev["narrow"] == pytest.approx((8 << 20) / 3000e6)
    assert dev["narrow_snappy"] == pytest.approx((10 << 20) / 3000e6)
    assert dev["narrow_snappy"] > dev["narrow"]


def test_ship_planner_device_mbps_env(monkeypatch):
    from tpu_parquet.ship import ChunkFacts, ShipPlanner, default_planner

    monkeypatch.setenv("TPQ_DEVICE_MBPS", "1500")
    p = ShipPlanner()
    assert p.device_mbps == 1500.0
    # default_planner rebuilds when the env knob changes
    assert default_planner().device_mbps == 1500.0
    monkeypatch.setenv("TPQ_DEVICE_MBPS", "3000")
    assert default_planner().device_mbps == 3000.0
    f = ChunkFacts(logical=1 << 20, width=0, comp_bytes=1 << 19, native=True)
    halved = ShipPlanner(device_mbps=1500.0).device_costs(f)
    full = ShipPlanner(device_mbps=3000.0).device_costs(f)
    assert halved["device_snappy"] == pytest.approx(
        2 * full["device_snappy"])


def test_recalibrate_device_mbps():
    from tpu_parquet.ship import recalibrate_device_mbps

    assert recalibrate_device_mbps(0.0) is None
    assert recalibrate_device_mbps(None) is None
    assert recalibrate_device_mbps(-5.0) is None
    assert recalibrate_device_mbps(2.5e9) == pytest.approx(2500.0)
    assert recalibrate_device_mbps(10.0) == 1.0  # floored at the clamp


# ---------------------------------------------------------------------------
# ship_feedback device lane (null contract) + doctor verdicts
# ---------------------------------------------------------------------------

def test_ship_feedback_device_lane_null_until_measured():
    from tpu_parquet.device_reader import ReaderStats

    reg = StatsRegistry()
    rs = ReaderStats()
    rs.count_route("plain", 100, 100, 0.001, 0.0005)
    rs.staged_bytes = 100
    reg.add_reader(rs)
    r = reg.ship_feedback()["routes"]["plain"]
    # timing lane never ran: predicted real, measured explicitly null
    assert r["device_predicted_seconds"] == pytest.approx(0.0005)
    assert r["device_measured_seconds"] is None
    assert r["device_error_ratio"] is None
    json.dumps(r)
    # the device section arrives (a later merge): the lane fills in
    reg.add_device({"routes": {"plain": {"dispatches": 1,
                                         "device_seconds": 0.001,
                                         "bytes_in": 100,
                                         "bytes_staged": 100}}})
    r = reg.ship_feedback()["routes"]["plain"]
    assert r["device_measured_seconds"] == pytest.approx(0.001)
    assert r["device_error_ratio"] == pytest.approx(2.0)


def _device_tree(routes, h2d_s=0.0, pipeline=None, reader=None):
    dev = {
        "dispatches": sum(c["dispatches"] for c in routes.values()),
        "device_seconds": sum(c["device_seconds"] for c in routes.values()),
        "routes": routes,
        "kernels": {"snappy_resolve": {
            "dispatches": 1,
            "device_seconds": max((c["device_seconds"]
                                   for c in routes.values()), default=0.0),
        }},
        "h2d": {"transfers": 1, "device_seconds": h2d_s, "bytes": 1 << 20},
    }
    return {
        "obs_version": OBS_VERSION,
        "pipeline": pipeline or {"io_seconds": 0.2, "decompress_seconds": 0.2,
                                 "stage_seconds": 0.3},
        "reader": reader or {},
        "device": dev,
    }


def test_doctor_h2d_bound_verdict():
    tree = _device_tree(
        {"plain": {"dispatches": 2, "device_seconds": 0.5,
                   "bytes_in": 1000, "bytes_staged": 1000}},
        h2d_s=5.0)
    rep = doctor_registry(tree)
    assert rep["verdict"] == "h2d-bound"
    assert rep["dominant_lane"] == "h2d"
    assert rep["lanes"]["h2d"] == pytest.approx(5.0)


def test_doctor_names_dominant_device_route_and_recalibrates():
    routes = {
        "device_snappy": {"dispatches": 3, "device_seconds": 4.0,
                          "bytes_in": 4 << 20, "bytes_staged": 1 << 20},
        "plain": {"dispatches": 1, "device_seconds": 0.5,
                  "bytes_in": 1 << 20, "bytes_staged": 1 << 20},
    }
    reader = {"ship_routes": {
        "device_snappy": {"streams": 3, "logical": 4 << 20,
                          "shipped": 1 << 20, "predicted_s": 0.01,
                          "predicted_device_s": 1.0},
    }}
    rep = doctor_registry(_device_tree(routes, reader=reader))
    assert rep["verdict"] == "device-resolve-bound"
    dv = rep["device"]
    assert dv["dominant_route"] == "device_snappy"
    assert dv["dominant_kernel"] == "snappy_resolve"
    assert dv["measured_seconds"] == pytest.approx(4.0)
    assert dv["error_ratio"] == pytest.approx(4.0)  # 4x slower than modeled
    # 4x outside the band: the DOMINANT route's measured resolve rate is
    # the re-run knob ((4<<20) bytes_in / 4.0s ≈ 1.05 MB/s, one decimal) —
    # never a blend that lets plain's near-zero-compute bytes dilute it
    assert rep["recalibrate_device_mbps"] == pytest.approx(1.0)
    assert rep["device"]["measured_device_mbps"] == pytest.approx(1.0)
    # inside the band: no recalibration worth chasing
    reader["ship_routes"]["device_snappy"]["predicted_device_s"] = 4.0
    rep = doctor_registry(_device_tree(routes, reader=reader))
    assert "recalibrate_device_mbps" not in rep
    assert rep["device"]["error_ratio"] == pytest.approx(1.0)


# ---------------------------------------------------------------------------
# graceful degradation on artifacts predating the `device` section
# (table-driven: doctor / trace / ledger.diff over an old banked record)
# ---------------------------------------------------------------------------

_OLD_PIPE = {"io_seconds": 1.0, "decompress_seconds": 2.0,
             "stage_seconds": 0.5, "dispatch_seconds": 0.2,
             "finalize_seconds": 0.1, "stall_seconds": 0.0}


def _old_cfg(dev_rps=1e7):
    """A config shaped like a pre-device-section banked record (the
    BENCH_LOCAL_r08-era schema: obs tree without `device`, ship_routes
    without predicted_device_s)."""
    return {
        "rows": 1000, "device_rows_per_sec": dev_rps,
        "device_windows_s": [[0.1, 0.1]],
        "obs": {
            "obs_version": OBS_VERSION,
            "pipeline": dict(_OLD_PIPE),
            "reader": {"host_seconds": 1.0, "ship_routes": {
                "plain": {"streams": 1, "logical": 10, "shipped": 10,
                          "predicted_s": 0.001}}},
            "alloc": {"peak_bytes": 100},
        },
    }


@pytest.mark.parametrize("surface", ["doctor", "doctor_cli", "trace",
                                     "ledger_diff"])
def test_old_records_degrade_gracefully(surface, tmp_path):
    """Artifacts and ledger records predating the device registry section
    print n/a (or simply omit device rows) — never a KeyError."""
    if surface == "doctor":
        rep = doctor_registry(_old_cfg()["obs"])
        assert rep is not None
        assert "device" not in rep  # nothing fabricated
        assert rep["lanes"]["h2d"] == 0.0  # present, zero — never dominant
        assert rep["lanes"]["device_resolve"] == pytest.approx(0.3)
    elif surface == "doctor_cli":
        from tpu_parquet.cli import pq_tool

        p = str(tmp_path / "old_reg.json")
        with open(p, "w") as f:
            json.dump(_old_cfg()["obs"], f)
        out = io.StringIO()
        args = pq_tool.build_parser().parse_args(["doctor", p])
        assert args.func(args, out=out) == 0
        assert "device: n/a" in out.getvalue()
    elif surface == "trace":
        # a ship instant without predicted_device_s (old trace artifact)
        tr = Tracer()
        tr.instant("ship", route="plain", column="v", logical=10, shipped=10,
                   predicted_s=0.001)
        r = trace_summary(tr.export())["routes"]["plain"]
        assert r["device_predicted_seconds"] == 0.0
        assert r["device_measured_seconds"] is None
        assert r["device_error_ratio"] is None
    else:
        from tpu_parquet import ledger

        old = {"configs": {"c": _old_cfg(1e7)}}
        new = {"configs": {"c": _old_cfg(1e6)}}  # 10x regression
        d = ledger.diff(old, new)
        assert d["regressions"], "regression must still be flagged"
        # attribution over old records: no device pseudo-stages, no raise
        att = d["regressions"][0].get("attribution")
        assert att is None or not att["stage"].startswith("device:")


def test_ledger_attributes_device_route_growth():
    from tpu_parquet import ledger

    a = _old_cfg()
    b = _old_cfg()
    a["obs"]["device"] = {"routes": {"device_snappy": {
        "dispatches": 1, "device_seconds": 0.1, "bytes_in": 1,
        "bytes_staged": 1}}}
    b["obs"]["device"] = {"routes": {"device_snappy": {
        "dispatches": 1, "device_seconds": 5.0, "bytes_in": 1,
        "bytes_staged": 1}}}
    att = ledger.attribute_stages(a, b)
    assert att["stage"] == "device:device_snappy"
    assert att["moved_seconds"] == pytest.approx(4.9)


# ---------------------------------------------------------------------------
# stage/dispatch split (the device_seconds double-count fix)
# ---------------------------------------------------------------------------

def test_serial_run_lane_sum_close_to_wall(tmp_path):
    """On a truly serial run (read_row_group: prepare, stage, and dispatch
    all inline on one thread — iter_row_groups always overlaps staging
    one group deep) host + stage + dispatch lane seconds must sum to ≈
    the reader wall — the property the old shared `device_seconds` scalar
    (worker AND dispatcher adding concurrent intervals) could violate
    from both sides."""
    from tpu_parquet.device_reader import DeviceFileReader

    path = _write_ints(str(tmp_path / "serial.parquet"))
    with DeviceFileReader(path) as r:
        for i in range(r.num_row_groups):
            r.read_row_group(i, finalize=False)
        r.finalize()
        st = r.stats()
        wall = st.wall_seconds
        lanes = st.host_seconds + st.stage_seconds + st.dispatch_seconds
    assert st.stage_seconds > 0.0
    assert st.dispatch_seconds > 0.0
    # disjoint sub-intervals of one thread's wall can never exceed it
    # (+5% timer slack), and the decode work dominates the iteration
    # overhead on a 120k-row file
    assert lanes <= wall * 1.05, (lanes, wall)
    assert lanes >= wall * 0.5, (lanes, wall)


def test_pipelined_run_keeps_lanes_distinct(tmp_path):
    """prefetch>0: the staging worker adds ONLY to stage_seconds, the
    dispatcher ONLY to dispatch_seconds (both nonzero, no shared scalar)."""
    from tpu_parquet.device_reader import DeviceFileReader

    path = _write_ints(str(tmp_path / "piped.parquet"))
    with DeviceFileReader(path, prefetch=2) as r:
        for _ in r.iter_row_groups():
            pass
        d = r.stats().as_dict()
    assert d["stage_seconds"] > 0.0
    assert d["dispatch_seconds"] > 0.0
    assert "device_seconds" not in d  # the double-counted scalar is gone


# ---------------------------------------------------------------------------
# the timing lane end to end (device section, trace table, doctor verdict)
# ---------------------------------------------------------------------------

def test_device_section_end_to_end_with_doctor(tmp_path):
    """Acceptance criterion: on a traced run the registry carries a device
    section whose routes mirror the ship routes, ship_feedback returns a
    populated device lane per route, `pq_tool trace` prints device lanes in
    the p50/p95 table, and `pq_tool doctor` names the dominant device route
    with measured seconds and an error ratio."""
    from tpu_parquet.cli import pq_tool
    from tpu_parquet.device_reader import DeviceFileReader

    path = _write_ints(str(tmp_path / "e2e.parquet"))
    tp = str(tmp_path / "trace.json")
    with DeviceFileReader(path, prefetch=2, trace=tp) as r:
        for _ in r.iter_row_groups():
            pass
        tree = r.obs_registry().as_dict()
        st = r.stats().as_dict()
    dev = tree["device"]
    assert dev is not None and dev["dispatches"] > 0
    # every timed route is a route the planner actually chose — plus the
    # default "plain" attribution for columns with no value-stream ship
    # record (dict-index/levels-only plans)
    assert set(dev["routes"]) <= set(st["ship_routes"]) | {"plain", "h2d"}
    assert dev["h2d"]["transfers"] > 0
    assert dev["h2d"]["bytes"] > 0
    for c in dev["routes"].values():
        assert c["device_seconds"] > 0.0
    assert dev["kernels"], "kernel-family attribution missing"
    # ship_feedback: populated device lane per route (the timed ones)
    fb = tree["reader"]["ship_feedback"]["routes"]
    timed = [r for r in fb.values()
             if r["device_measured_seconds"] is not None]
    assert timed, "no route carries a measured device lane"
    # the trace artifact carries device.<route> spans -> p50/p95 table rows
    out = io.StringIO()
    args = pq_tool.build_parser().parse_args(["trace", tp])
    assert args.func(args, out=out) == 0
    text = out.getvalue()
    assert "device." in text
    # doctor names the dominant device route with its error ratio
    out = io.StringIO()
    args = pq_tool.build_parser().parse_args(["doctor", tp])
    assert args.func(args, out=out) == 0
    text = out.getvalue()
    rep = doctor_registry(tree)
    assert f"device: dominant route {rep['device']['dominant_route']!r}" \
        in text
    assert rep["device"]["measured_seconds"] > 0.0


def test_timing_lane_env_off(tmp_path, monkeypatch):
    """TPQ_DEVICE_TIMING=0: no device section, no timer thread, reads
    unchanged."""
    from tpu_parquet.device_reader import DeviceFileReader

    monkeypatch.setenv("TPQ_DEVICE_TIMING", "0")
    path = _write_ints(str(tmp_path / "off.parquet"), rows=30_000, groups=1)
    with DeviceFileReader(path) as r:
        rows = 0
        for cols in r.iter_row_groups():
            rows += cols["v"].num_values
        tree = r.obs_registry().as_dict()
    assert rows == 30_000
    assert tree["device"] is None
    assert not [t for t in threading.enumerate()
                if t.name.startswith("tpq-devtimer")]


def test_timing_lane_drops_without_backend(tmp_path, monkeypatch, caplog):
    """CPU-only/no-backend satellite: when no jax device is available the
    timing lane (and its sampler track) drop with ONE warning and the read
    stays green."""
    import logging

    import tpu_parquet.device_reader as dr
    from tpu_parquet import obs

    def _no_backend(*a, **k):
        raise RuntimeError("no backend")

    monkeypatch.setattr(dr.jax, "devices", _no_backend)
    obs._env_warned.discard(("TPQ_DEVICE_TIMING", "<no jax device>"))
    with caplog.at_level(logging.WARNING, logger="tpu_parquet.obs"):
        assert dr._device_timing_enabled() is False
        assert dr._device_timing_enabled() is False  # warned ONCE
    warns = [rec for rec in caplog.records
             if "TPQ_DEVICE_TIMING" in rec.getMessage()]
    assert len(warns) == 1
    # restore jax.devices (this CPU test still needs the backend to decode)
    # and drop just the probe: the reader must construct, skip the lane,
    # and read green
    monkeypatch.undo()
    monkeypatch.setattr(dr, "_device_timing_enabled", lambda: False)
    path = _write_ints(str(tmp_path / "nodev.parquet"), rows=30_000,
                       groups=1)
    with dr.DeviceFileReader(path) as r:
        assert r._device_timer.enabled is False
        for _ in r.iter_row_groups():
            pass
        assert r.obs_registry().as_dict()["device"] is None


def test_timer_thread_joins_on_close(tmp_path):
    from tpu_parquet.device_reader import DeviceFileReader

    path = _write_ints(str(tmp_path / "join.parquet"), rows=30_000, groups=1)
    with DeviceFileReader(path) as r:
        for _ in r.iter_row_groups():
            pass
        assert r._device_stats.progress()["dispatches"] >= 0
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        if not [t for t in threading.enumerate()
                if t.name.startswith("tpq-devtimer")]:
            break
        time.sleep(0.01)
    assert not [t for t in threading.enumerate()
                if t.name.startswith("tpq-devtimer")]
    # a submit after close is dropped, never respawns the thread
    r._device_timer.submit("dispatch", "plain", "plain", None, 0.0)
    assert r._device_timer._thread is None


# ---------------------------------------------------------------------------
# HBM residency accounting (AllocTracker device ledger)
# ---------------------------------------------------------------------------

def test_alloc_device_ledger_watermark():
    from tpu_parquet.alloc import AllocTracker, tracker_snapshots

    al = AllocTracker(0)
    al.register_device(1000)
    al.register_device(2000)
    assert al.device_snapshot() == (3000, 3000)
    al.release_device(2000)
    al.register_device(500)
    assert al.device_snapshot() == (1500, 3000)
    # the host ledger's per-row-group reset never touches HBM residency
    al.reset()
    assert al.device_snapshot() == (1500, 3000)
    snaps = [s for s in tracker_snapshots() if s.get("device_peak") == 3000]
    assert snaps and snaps[0]["device_in_use"] == 1500
    # the registry picks the watermark up
    reg = StatsRegistry()
    reg.note_alloc_peak(al)
    assert reg.as_dict()["alloc"]["device_peak_bytes"] == 3000


def test_device_residency_in_sampler_tracks_and_flight_dump(tmp_path):
    """The device_bytes watermark rides the reader's alloc sampler track
    and the flight dump's tracker section (acceptance criterion)."""
    from tpu_parquet.device_reader import DeviceFileReader
    from tpu_parquet.obs import FlightRecorder

    path = _write_ints(str(tmp_path / "resid.parquet"))
    tp = str(tmp_path / "trace.json")
    rec = FlightRecorder(capacity=64)
    with DeviceFileReader(path, trace=tp, sample_ms=5) as r:
        peak_seen = 0
        for _ in r.iter_row_groups():
            in_use, peak = r.alloc.device_snapshot()
            peak_seen = max(peak_seen, in_use)
            doc = rec.snapshot()
        st = r.stats()
        assert peak_seen > 0  # staged buffers were resident mid-scan
        # finalize (iter end) released them
        assert r.alloc.device_snapshot()[0] == 0
        assert r.alloc.device_snapshot()[1] >= peak_seen
        trackers = [t for t in doc["trackers"] if t.get("device_peak")]
        assert trackers, "flight dump carries no device watermark"
    doc = json.loads(open(tp).read())
    alloc_tracks = [e for e in doc["traceEvents"]
                    if e.get("ph") == "C" and e.get("name") == "alloc_bytes"]
    assert alloc_tracks
    assert any("device_peak" in (e.get("args") or {}) for e in alloc_tracks)
    # the device timing track rode the same sampler
    dev_tracks = [e for e in doc["traceEvents"]
                  if e.get("ph") == "C" and e.get("name") == "device"]
    assert dev_tracks
    assert st.staged_bytes > 0


# ---------------------------------------------------------------------------
# overhead guard: the disabled timing lane costs <3% (tier-1)
# ---------------------------------------------------------------------------

def test_disabled_device_timing_overhead_under_3_percent():
    """The tier-1 guard pattern (paired adjacent differences, median): the
    hot loop calling a DISABLED _DeviceTimer.submit per iteration vs the
    identical loop without it must differ by <3%."""
    import gc

    from tpu_parquet.device_reader import DeviceStats, _DeviceTimer

    gc.collect()
    gc.disable()
    timer = _DeviceTimer(DeviceStats(), tracer=None, enabled=False)
    rng = np.random.default_rng(0)
    data = rng.integers(0, 1 << 40, 300_000)

    def work():
        return np.sort(data).sum()

    def once(with_timer):
        t0 = time.perf_counter()
        if with_timer:
            work()
            timer.submit("dispatch", "plain", "plain", None,
                         t0, bytes_in=0, bytes_staged=0)
        else:
            work()
        return time.perf_counter() - t0

    try:
        for _ in range(3):
            once(True), once(False)
        base, obs = [], []
        for _ in range(80):
            obs.append(once(True))
            base.append(once(False))
    finally:
        gc.enable()
    diffs = sorted(o - b for o, b in zip(obs, base))
    med_diff = diffs[len(diffs) // 2]
    med_base = sorted(base)[len(base) // 2]
    overhead = med_diff / med_base
    assert overhead < 0.03, f"disabled device-timing overhead {overhead:.2%}"
    assert timer._thread is None  # disabled lane never starts a thread


def test_worker_serializes_overlapping_intervals():
    """Per-entry intervals anchor at max(own dispatch, previous
    completion): three entries dispatched at the same instant must
    partition the elapsed device lane (~1x), never sum to ~3x it."""
    from tpu_parquet.device_reader import DeviceStats, _DeviceTimer

    stats = DeviceStats()
    timer = _DeviceTimer(stats, tracer=None, enabled=True)
    t0 = time.perf_counter() - 0.5  # all three "dispatched" 0.5s ago
    for route in ("plain", "narrow", "plain"):
        timer.submit("dispatch", route, "plain", None, t0, bytes_in=1)
    timer.drain(timeout=5.0)
    timer.stop()
    total = stats.as_dict()["device_seconds"]
    assert 0.4 < total < 0.7, total  # ~0.5s once, not ~1.5s


def test_fused_path_times_one_entry_per_call(tmp_path):
    """TPQ_FUSE_RG=1 runs ONE executable per row group: the timing lane
    must bank one entry per fused call (per-plan submissions sharing the
    fused t0 would each count the whole wall, ~N_plans x overcount), and a
    mid-session obs_registry() read must drain the completion queue first
    (never observe 1 of a group's dispatches because the worker is still
    blocking on the rest)."""
    code = r"""
import sys
sys.path.insert(0, sys.argv[1])
import numpy as np
from tpu_parquet.format import FieldRepetitionType as FRT, Type
from tpu_parquet.schema.core import build_schema, data_column
from tpu_parquet.writer import FileWriter
from tpu_parquet.device_reader import DeviceFileReader

rng = np.random.default_rng(0)
schema = build_schema([data_column("v", Type.INT64, FRT.REQUIRED),
                       data_column("w", Type.INT32, FRT.REQUIRED)])
path = sys.argv[2]
with FileWriter(path, schema, row_group_size=1) as w:
    for _ in range(3):
        w.write_columns({"v": rng.integers(0, 1 << 40, 20_000),
                         "w": rng.integers(0, 1000, 20_000)
                              .astype(np.int32)})
        w.flush_row_group()
with DeviceFileReader(path) as r:
    for _ in r.iter_row_groups():
        pass
    dev = r.obs_registry().as_dict()["device"]
assert dev["dispatches"] == 3, dev   # one per fused call, drained
assert dev["h2d"]["transfers"] == 3, dev
print("ok")
"""
    env = dict(os.environ, JAX_PLATFORMS="cpu", TPQ_FUSE_RG="1")
    res = subprocess.run(
        [sys.executable, "-c", code, REPO_ROOT,
         str(tmp_path / "fuse.parquet")],
        capture_output=True, text=True, timeout=240, env=env)
    assert res.returncode == 0, (res.stdout[-800:], res.stderr[-800:])
    assert res.stdout.strip().endswith("ok")


def test_residency_pending_vs_outstanding(tmp_path):
    """finalize releases only DISPATCHED groups' bytes: a staged-but-not-
    dispatched buffer (the pipelined stage-ahead) stays on the ledger."""
    from tpu_parquet.device_reader import DeviceFileReader

    path = _write_ints(str(tmp_path / "pend.parquet"), rows=30_000, groups=1)
    with DeviceFileReader(path) as r:
        prepared = r._prepare_row_group(0)
        import time as _t

        t0 = _t.perf_counter()
        buf = prepared[2].stage()
        r._note_staged(prepared[2], buf, t0)
        staged = prepared[2].total
        assert r.alloc.device_snapshot()[0] == staged
        # finalize BEFORE dispatch: the pending buffer must survive
        r.finalize()
        assert r.alloc.device_snapshot()[0] == staged
        r._dispatch_row_group(prepared, buf)
        r.finalize()
        assert r.alloc.device_snapshot()[0] == 0
    # close() is the deferred-scan backstop for still-pending bytes
    assert r.alloc.device_snapshot()[0] == 0


# ---------------------------------------------------------------------------
# TPQ_XPROF bounded window
# ---------------------------------------------------------------------------

def test_xprof_window_captures_once(tmp_path, monkeypatch):
    import tpu_parquet.device_reader as dr

    xdir = str(tmp_path / "xprof")
    monkeypatch.setenv("TPQ_XPROF", xdir)
    monkeypatch.setattr(dr, "_XPROF_DONE", False)
    path = _write_ints(str(tmp_path / "xp.parquet"), rows=30_000, groups=2)
    with dr.DeviceFileReader(path) as r:
        for _ in r.iter_row_groups():
            pass
    assert not dr._XPROF_ACTIVE  # window closed with the scan
    files = [os.path.join(root, f)
             for root, _, fs in os.walk(xdir) for f in fs]
    assert files, "no xprof artifact written"
    # one capture per process: a second scan must not re-open the window
    with dr.DeviceFileReader(path) as r:
        for _ in r.iter_row_groups():
            pass
    assert not dr._XPROF_ACTIVE


def test_xprof_window_covers_scan_files(tmp_path, monkeypatch):
    """scan_files drives _scan_pipeline directly (never iter_row_groups),
    so it must own its own capture window — the multi-file runs the
    feature targets."""
    import tpu_parquet.device_reader as dr

    xdir = str(tmp_path / "xprof_scan")
    monkeypatch.setenv("TPQ_XPROF", xdir)
    monkeypatch.setattr(dr, "_XPROF_DONE", False)
    paths = [_write_ints(str(tmp_path / f"s{i}.parquet"), rows=20_000,
                         groups=1, seed=i) for i in range(2)]
    n = sum(1 for _ in dr.scan_files(paths))
    assert n == 2
    assert not dr._XPROF_ACTIVE
    files = [f for _, _, fs in os.walk(xdir) for f in fs]
    assert files, "scan_files wrote no xprof artifact"
