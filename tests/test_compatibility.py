"""Cross-implementation compatibility harness tests.

In-process version of compatibility/run_tests.bash (the reference's
compatibility/run_tests.bash:14-19 matrix): write the shared sample dataset
with every {codec} x {page version} cell, read it back with our reader AND
with pyarrow, and deep-compare against the source rows.  The parquet-mr leg
runs when PARQUET_TOOLS_JAR + java are available (same env-gating style as
the reference's external corpora, parquet_test.go:12-15).
"""

import json
import os
import shutil
import subprocess
import sys

import pytest

COMPAT = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                      "compatibility")
sys.path.insert(0, COMPAT)

import importlib.util as _ilu  # noqa: E402

# load the harness's build module by path: the bare name `build` would
# collide with PyPA's installed `build` package in sys.modules
_spec = _ilu.spec_from_file_location(
    "tpq_compat_build", os.path.join(COMPAT, "build.py"))
_build = _ilu.module_from_spec(_spec)
_spec.loader.exec_module(_build)
CODECS = _build.CODECS

from data_model import (  # noqa: E402
    SCHEMA_TEXT, from_parquet_row, generate, to_parquet_row,
)

from tpu_parquet.reader import FileReader  # noqa: E402
from tpu_parquet.schema.dsl import parse_schema_definition  # noqa: E402
from tpu_parquet.writer import FileWriter  # noqa: E402


@pytest.fixture(scope="module")
def rows():
    return generate(120, seed=11)


def _write(path, rows, codec, version):
    schema = parse_schema_definition(SCHEMA_TEXT)
    with FileWriter(path, schema, codec=CODECS[codec],
                    data_page_version=version) as w:
        for row in rows:
            w.write_row(to_parquet_row(row))


@pytest.mark.parametrize("codec", sorted(CODECS))
@pytest.mark.parametrize("version", [1, 2])
def test_matrix_cell_roundtrip_and_pyarrow(tmp_path, rows, codec, version):
    import pyarrow.parquet as pq

    from conftest import require_codec

    require_codec(CODECS[codec])

    p = tmp_path / f"out-{codec}-v{version}.parquet"
    _write(p, rows, codec, version)

    with FileReader(p) as r:
        got = [from_parquet_row(row) for row in r.iter_rows()]
    assert got == rows

    # foreign read: pyarrow sees the same values
    t = pq.read_table(p)
    assert t.num_rows == len(rows)
    pl = t.to_pylist()
    for g, w in zip(pl, rows):
        assert g["id"] == w["id"]
        assert g["index"] == w["index"]
        assert list(g.get("tags") or []) == w["tags"]
        assert [dict(f) for f in (g.get("friends") or [])] == w["friends"]
        assert g["latitude"] == pytest.approx(w["latitude"])


@pytest.mark.skipif(
    not (os.environ.get("PARQUET_TOOLS_JAR") and shutil.which("java")),
    reason="PARQUET_TOOLS_JAR / java not available",
)
@pytest.mark.parametrize("codec", ["none", "gzip", "snappy"])
def test_parquet_mr_reads_our_files(tmp_path, rows, codec):
    p = tmp_path / f"mr-{codec}.parquet"
    _write(p, rows, codec, 1)
    out = subprocess.run(
        ["java", "-jar", os.environ["PARQUET_TOOLS_JAR"], "cat", "-j", str(p)],
        capture_output=True, text=True, check=True,
    ).stdout
    got = [json.loads(l) for l in out.splitlines() if l.strip()]
    assert len(got) == len(rows)
    for g, w in zip(got, rows):
        assert g["id"] == w["id"] and g["index"] == w["index"]
