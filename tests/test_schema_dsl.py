"""Schema DSL parser/printer/validator + autoschema tests.

Mirrors the reference's parquetschema test strategy: grammar coverage, round-trip
printing, validation rules, crash-regression inputs (schema_test.go:162,241), and
end-to-end use with the writer.
"""

import dataclasses
import datetime
import uuid
from typing import Dict, List, Optional

import pytest

from tpu_parquet.format import ConvertedType, FieldRepetitionType as FRT, Type
from tpu_parquet.schema.autoschema import AutoSchemaError, schema_from_type
from tpu_parquet.schema.dsl import (
    SchemaParseError,
    parse_schema_definition,
    schema_to_string,
)
from tpu_parquet.schema.validate import SchemaValidationError, validate, validate_strict


def test_parse_simple():
    s = parse_schema_definition("message foo { required int64 bar; }")
    assert s.root.name == "foo"
    assert len(s.leaves) == 1
    assert s.leaves[0].name == "bar"
    assert s.leaves[0].physical_type == Type.INT64
    assert s.leaves[0].repetition == FRT.REQUIRED


def test_parse_all_types_and_annotations():
    text = """message msg {
  required int64 id = 7;
  optional binary name (STRING);
  optional binary blob;
  required boolean flag;
  optional float f32;
  required double f64;
  optional int96 legacy_ts;
  required fixed_len_byte_array(16) uid (UUID);
  optional int32 day (DATE);
  optional int64 ts (TIMESTAMP(MILLIS,true));
  optional int64 t (TIME(NANOS,false));
  optional int32 small (INT(8,true));
  optional int32 price (DECIMAL(9,2));
  optional binary doc (JSON);
  optional int32 old_time (TIME_MILLIS);
}"""
    s = parse_schema_definition(text)
    by = {l.name: l for l in s.leaves}
    assert by["id"].element.field_id == 7
    assert by["name"].logical_type.which() == "STRING"
    assert by["name"].converted_type == ConvertedType.UTF8
    assert by["uid"].type_length == 16
    assert by["uid"].logical_type.which() == "UUID"
    ts = by["ts"].logical_type.TIMESTAMP
    assert ts.isAdjustedToUTC is True and ts.unit.MILLIS is not None
    assert by["ts"].converted_type == ConvertedType.TIMESTAMP_MILLIS
    t = by["t"].logical_type.TIME
    assert t.isAdjustedToUTC is False and t.unit.NANOS is not None
    i = by["small"].logical_type.INTEGER
    assert i.bitWidth == 8 and i.isSigned is True
    assert by["small"].converted_type == ConvertedType.INT_8
    d = by["price"].logical_type.DECIMAL
    assert (d.precision, d.scale) == (9, 2)
    assert by["price"].element.precision == 9
    assert by["old_time"].converted_type == ConvertedType.TIME_MILLIS


def test_parse_nested_groups():
    text = """message m {
  optional group lst (LIST) {
    repeated group list {
      optional binary element (STRING);
    }
  }
  optional group mp (MAP) {
    repeated group key_value {
      required binary key (STRING);
      optional int64 value;
    }
  }
  required group plain {
    required int32 x;
    repeated int64 ys;
  }
}"""
    s = parse_schema_definition(text)
    assert s.num_columns == 5
    lst = s.node_by_path(("lst",))
    assert lst.converted_type == ConvertedType.LIST
    el = s.leaf_by_path(("lst", "list", "element"))
    assert el.max_rep == 1 and el.max_def == 3
    validate(s)
    validate_strict(s)


def test_roundtrip_print_parse():
    text = """message m {
  required int64 id;
  optional binary name (STRING);
  required fixed_len_byte_array(12) iv (INTERVAL);
  optional group tags (LIST) {
    repeated group list {
      optional int64 element (INT(64,false));
    }
  }
  optional int64 ts (TIMESTAMP(NANOS,true));
  optional int32 dec (DECIMAL(5,2));
}"""
    s1 = parse_schema_definition(text)
    printed = schema_to_string(s1)
    s2 = parse_schema_definition(printed)
    assert schema_to_string(s2) == printed
    assert [l.path for l in s1.leaves] == [l.path for l in s2.leaves]
    for l1, l2 in zip(s1.leaves, s2.leaves):
        assert l1.element == l2.element


def test_parse_errors():
    bad = [
        "",
        "msg foo {}",
        "message foo {",
        "message foo { required int64 }",
        "message foo { int64 bar; }",
        "message foo { required unknown bar; }",
        "message foo { required int64 bar }",
        "message foo { required int64 bar; } trailing",
        "message foo { required group g { } }",
        "message foo { required fixed_len_byte_array(0) x; }",
        "message foo { required fixed_len_byte_array(abc) x; }",
        "message foo { optional int64 t (TIMESTAMP(WEEKS,true)); }",
        "message foo { optional int32 i (INT(9,true)); }",
        "message foo { optional int64 x (NOT_A_THING); }",
        "message foo { required int64 bar = x; }",
    ]
    for text in bad:
        with pytest.raises(SchemaParseError):
            parse_schema_definition(text)


def test_validation_rules():
    good = parse_schema_definition(
        "message m { optional binary s (STRING); }"
    )
    validate(good)

    cases = [
        # STRING on non-binary
        "message m { optional int64 s (STRING); }",
        # DATE on non-int32
        "message m { optional int64 d (DATE); }",
        # UUID wrong length
        "message m { optional fixed_len_byte_array(8) u (UUID); }",
        # INTERVAL wrong length
        "message m { optional fixed_len_byte_array(16) u (INTERVAL); }",
        # DECIMAL precision too big for int32
        "message m { optional int32 d (DECIMAL(10,2)); }",
        # DECIMAL scale > precision
        "message m { optional int64 d (DECIMAL(5,6)); }",
        # TIME_MILLIS on int64
        "message m { optional int64 t (TIME_MILLIS); }",
        # INT(64) on int32
        "message m { optional int32 i (INT(64,true)); }",
        # LIST with two children
        """message m { optional group l (LIST) {
             repeated group list { optional int64 element; }
             required int64 extra;
           } }""",
        # MAP with optional key
        """message m { optional group mp (MAP) {
             repeated group key_value {
               optional binary key (STRING);
               optional int64 value;
             } } }""",
    ]
    for text in cases:
        with pytest.raises(SchemaValidationError):
            validate(parse_schema_definition(text))


def test_strict_vs_lenient_athena_bag():
    # Athena-style: bag/array_element names are fine lenient, rejected strict
    text = """message m { optional group l (LIST) {
        repeated group bag { optional int64 array_element; } } }"""
    s = parse_schema_definition(text)
    validate(s)
    with pytest.raises(SchemaValidationError):
        validate_strict(s)


def test_crash_regression_inputs():
    # fuzz-derived crashers (schema_test.go posture): must raise, never hang
    crashers = [
        "message { required int64 x; }" * 100,
        "message m {" + "{" * 200,
        "message m { required group g (LIST) { " * 50,
        "message m { required int64 \x00; }",
        "message " + "a" * 10000 + " { required int64 x; }",
    ]
    for text in crashers:
        try:
            parse_schema_definition(text)
        except SchemaParseError:
            pass


# ---------------------------------------------------------------------------
# autoschema
# ---------------------------------------------------------------------------

def test_autoschema_dataclass():
    @dataclasses.dataclass
    class Person:
        name: str
        age: int
        height: Optional[float]
        tags: List[str]
        attrs: Dict[str, int]
        uid: uuid.UUID
        born: datetime.datetime
        day: datetime.date

    s = schema_from_type(Person)
    text = schema_to_string(s)
    assert "required binary name (STRING)" in text
    assert "required int64 age (INT(64,true))" in text
    assert "optional double height" in text
    assert "tags (LIST)" in text
    assert "attrs (MAP)" in text
    assert "fixed_len_byte_array(16) uid (UUID)" in text
    assert "born (TIMESTAMP(NANOS,true))" in text
    assert "day (DATE)" in text
    validate(s)
    # round-trip through the DSL
    assert schema_to_string(parse_schema_definition(text)) == text


def test_autoschema_nested_dataclass():
    @dataclasses.dataclass
    class Inner:
        x: int
        y: Optional[str]

    @dataclasses.dataclass
    class Outer:
        inner: Optional[Inner]
        items: List[Inner]

    s = schema_from_type(Outer)
    assert s.leaf_by_path(("inner", "x")) is not None
    assert s.leaf_by_path(("items", "list", "element", "y")) is not None
    validate(s)


def test_autoschema_field_rename():
    @dataclasses.dataclass
    class Row:
        MyField: int = dataclasses.field(
            default=0, metadata={"parquet": "my_field"}
        )

    s = schema_from_type(Row)
    assert s.leaves[0].name == "my_field"


def test_autoschema_unsupported():
    class Weird:
        x: complex

    with pytest.raises(AutoSchemaError):
        schema_from_type(Weird)


def test_autoschema_write_read(tmp_path):
    from tpu_parquet.logical import unwrap_row
    from tpu_parquet.reader import FileReader
    from tpu_parquet.writer import FileWriter

    @dataclasses.dataclass
    class Event:
        id: int
        name: str
        score: Optional[float]
        tags: List[str]

    s = schema_from_type(Event, root_name="event")
    p = tmp_path / "auto.parquet"
    rows = [
        {"id": 1, "name": "a", "score": 0.5, "tags": ["x"]},
        {"id": 2, "name": "b", "score": None, "tags": []},
    ]
    with FileWriter(p, s) as w:
        w.write_rows(rows)
    with FileReader(p) as r:
        got = [unwrap_row(r.schema, row) for row in r]
    assert got == rows


def test_parse_reference_sample_schemas():
    """The reference ships 7 sample .schema files; ours must parse them all."""
    import pathlib

    d = pathlib.Path("/root/reference/parquetschema/schema-files")
    if not d.exists():
        pytest.skip("reference schema files unavailable")
    count = 0
    for f in sorted(d.glob("*.schema")):
        s = parse_schema_definition(f.read_text())
        assert s.num_columns >= 1
        # and round-trip through our printer
        s2 = parse_schema_definition(schema_to_string(s))
        assert [l.path for l in s.leaves] == [l.path for l in s2.leaves]
        count += 1
    assert count >= 7


# ---------------------------------------------------------------------------
# File fixtures: the reference ships 7 sample .schema files
# (parquetschema/schema-files/test{1..7}.schema, loaded by
# parquetschema/schema_parser_test.go TestParseSchemaFiles); the same grammar
# corners live in tests/schema-files/ here -- field-id suffixes, MAP with
# MAP_KEY_VALUE, LIST-of-LIST nesting, bare MAP key_value, TIMESTAMP(NANOS),
# DATE, UUID over fixed_len_byte_array(16).
# ---------------------------------------------------------------------------

import glob as _glob
import os as _os

_SCHEMA_DIR = _os.path.join(_os.path.dirname(__file__), "schema-files")


@pytest.mark.parametrize(
    "path", sorted(_glob.glob(_os.path.join(_SCHEMA_DIR, "*.schema"))),
    ids=lambda p: _os.path.basename(p),
)
def test_schema_file_fixture_roundtrip(path):
    """Each fixture parses, prints, and re-parses to the same tree."""
    text = open(path).read()
    schema = parse_schema_definition(text)
    printed = schema_to_string(schema)
    again = parse_schema_definition(printed)
    assert schema_to_string(again) == printed
    # strict validation accepts every fixture (they are all spec-legal)
    validate_strict(schema)


def test_schema_file_fixtures_present():
    assert len(_glob.glob(_os.path.join(_SCHEMA_DIR, "*.schema"))) == 7
