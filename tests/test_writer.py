"""FileWriter tests: interop (pyarrow must read our files), self round-trips,
dictionary decision semantics, page/rowgroup geometry, CRC, stats.

This is the §4.6-equivalent cross-implementation harness: every file we write is
re-read by pyarrow (canonical C++ reader) and compared object-for-object, the same
exact-equality bar the reference's compatibility/ Docker matrix enforces.
"""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from tpu_parquet.column import ByteArrayData, ColumnData
from tpu_parquet.format import (
    CompressionCodec,
    ConvertedType,
    Encoding,
    FieldRepetitionType as FRT,
    IntType,
    LogicalType,
    StringType,
    Type,
)
from tpu_parquet.logical import unwrap_row
from tpu_parquet.reader import FileReader
from tpu_parquet.schema.core import (
    ColumnParameters,
    build_schema,
    data_column,
    group_column,
    list_column,
    map_column,
)
from tpu_parquet.writer import FileWriter


def string_col(name, repetition=FRT.OPTIONAL):
    return data_column(
        name, Type.BYTE_ARRAY, repetition,
        ColumnParameters(
            logical_type=LogicalType(STRING=StringType()),
            converted_type=ConvertedType.UTF8,
        ),
    )


def flat_schema():
    return build_schema([
        data_column("id", Type.INT64, FRT.REQUIRED),
        data_column("score", Type.DOUBLE, FRT.OPTIONAL),
        string_col("name"),
        data_column("active", Type.BOOLEAN, FRT.REQUIRED),
    ])


def sample_rows(n=1000):
    rows = []
    for i in range(n):
        rows.append({
            "id": i,
            "score": None if i % 7 == 0 else i * 0.5,
            "name": None if i % 11 == 0 else f"name_{i % 100}",
            "active": i % 2 == 0,
        })
    return rows


@pytest.mark.parametrize("codec", [
    CompressionCodec.UNCOMPRESSED, CompressionCodec.SNAPPY,
    CompressionCodec.GZIP, CompressionCodec.ZSTD,
])
@pytest.mark.parametrize("version", [1, 2])
def test_pyarrow_reads_our_files_matrix(tmp_path, codec, version):
    from conftest import require_codec

    require_codec(codec)
    p = tmp_path / "out.parquet"
    rows = sample_rows(2000)
    with FileWriter(p, flat_schema(), codec=codec, data_page_version=version) as w:
        w.write_rows(rows)
    table = pq.read_table(p)
    assert table.num_rows == 2000
    got = table.to_pylist()
    for g, e in zip(got, rows):
        assert g == e


@pytest.mark.parametrize("version", [1, 2])
def test_self_roundtrip(tmp_path, version):
    p = tmp_path / "rt.parquet"
    rows = sample_rows(500)
    with FileWriter(p, flat_schema(), data_page_version=version, write_crc=True) as w:
        w.write_rows(rows)
    with FileReader(p, validate_crc=True) as r:
        got = [unwrap_row(r.schema, row) for row in r]
    assert got == rows


def test_columnar_write_path(tmp_path):
    p = tmp_path / "col.parquet"
    schema = build_schema([
        data_column("a", Type.INT64, FRT.REQUIRED),
        data_column("b", Type.DOUBLE, FRT.REQUIRED),
        string_col("s", FRT.REQUIRED),
    ])
    a = np.arange(10_000, dtype=np.int64)
    b = np.linspace(0, 1, 10_000)
    s = ByteArrayData.from_list([f"v{i % 50}".encode() for i in range(10_000)])
    with FileWriter(p, schema) as w:
        w.write_columns({"a": a, "b": b, "s": s})
    t = pq.read_table(p)
    np.testing.assert_array_equal(t.column("a").to_numpy(), a)
    np.testing.assert_allclose(t.column("b").to_numpy(), b)
    assert t.column("s").to_pylist()[:3] == ["v0", "v1", "v2"]


def test_columnar_write_with_nulls(tmp_path):
    p = tmp_path / "nul.parquet"
    schema = build_schema([data_column("v", Type.INT64, FRT.OPTIONAL)])
    leaf = schema.leaves[0]
    defs = np.array([1, 0, 1, 1, 0], dtype=np.int32)
    cd = ColumnData(
        values=np.array([10, 20, 30], dtype=np.int64),
        def_levels=defs, max_def=1, max_rep=0,
    )
    with FileWriter(p, schema) as w:
        w.write_columns({"v": cd})
    assert pq.read_table(p).column("v").to_pylist() == [10, None, 20, 30, None]


def test_nested_list_write(tmp_path):
    p = tmp_path / "lst.parquet"
    schema = build_schema([
        data_column("id", Type.INT64, FRT.REQUIRED),
        list_column("tags", string_col("element", FRT.OPTIONAL)),
    ])
    rows = [
        {"id": 1, "tags": ["a", "b"]},
        {"id": 2, "tags": None},
        {"id": 3, "tags": []},
        {"id": 4, "tags": ["c", None, "d"]},
    ]
    with FileWriter(p, schema) as w:
        w.write_rows(rows)
    got = pq.read_table(p).to_pylist()
    assert got == rows


def test_nested_map_write(tmp_path):
    p = tmp_path / "map.parquet"
    schema = build_schema([
        map_column(
            "m",
            string_col("key", FRT.REQUIRED),
            data_column("value", Type.INT64, FRT.OPTIONAL),
        ),
    ])
    rows = [{"m": {"a": 1, "b": 2}}, {"m": None}, {"m": {}}, {"m": {"c": None}}]
    with FileWriter(p, schema) as w:
        w.write_rows(rows)
    got = pq.read_table(p).to_pylist()
    assert got[0]["m"] == [("a", 1), ("b", 2)]
    assert got[1]["m"] is None
    assert got[2]["m"] == []
    assert got[3]["m"] == [("c", None)]


def test_deep_nested_struct_write(tmp_path):
    p = tmp_path / "deep.parquet"
    schema = build_schema([
        group_column("outer", [
            data_column("x", Type.INT32, FRT.REQUIRED),
            group_column("inner", [
                string_col("s"),
                data_column("ys", Type.INT64, FRT.REPEATED),
            ], FRT.OPTIONAL),
        ], FRT.OPTIONAL),
    ])
    rows = [
        {"outer": {"x": 1, "inner": {"s": "hi", "ys": [1, 2]}}},
        {"outer": {"x": 2, "inner": None}},
        {"outer": None},
        {"outer": {"x": 3, "inner": {"s": None, "ys": []}}},
    ]
    with FileWriter(p, schema) as w:
        w.write_rows(rows)
    # self-read (pyarrow renders bare repeated differently)
    with FileReader(p) as r:
        got = [unwrap_row(r.schema, row) for row in r]
    assert got == rows
    # and pyarrow can still open + count it
    assert pq.read_table(p).num_rows == 4


def test_dictionary_decision_and_fallback(tmp_path):
    # few distinct -> dictionary page present; many -> no dict page
    p1 = tmp_path / "dict.parquet"
    schema = build_schema([string_col("s", FRT.REQUIRED)])
    with FileWriter(p1, schema) as w:
        w.write_rows([{"s": f"v{i % 10}"} for i in range(10_000)])
    with FileReader(p1) as r:
        md = r.metadata.row_groups[0].columns[0].meta_data
        assert md.dictionary_page_offset is not None
        assert int(Encoding.RLE_DICTIONARY) in md.encodings
    assert pq.read_table(p1).column("s").to_pylist()[:2] == ["v0", "v1"]

    p2 = tmp_path / "nodict.parquet"
    with FileWriter(p2, schema) as w:
        w.write_rows([{"s": f"unique_{i}"} for i in range(40_000)])
    with FileReader(p2) as r:
        md = r.metadata.row_groups[0].columns[0].meta_data
        assert md.dictionary_page_offset is None
        assert int(Encoding.RLE_DICTIONARY) not in md.encodings
    assert pq.read_table(p2).num_rows == 40_000


def test_explicit_encodings(tmp_path):
    schema = build_schema([
        data_column("d32", Type.INT32, FRT.REQUIRED),
        data_column("d64", Type.INT64, FRT.REQUIRED),
        string_col("dba", FRT.REQUIRED),
        data_column("bss", Type.DOUBLE, FRT.REQUIRED),
    ])
    p = tmp_path / "enc.parquet"
    rows = [
        {"d32": i, "d64": i * 1000, "dba": f"key_{i:05d}", "bss": i * 0.25}
        for i in range(5000)
    ]
    with FileWriter(
        p, schema, use_dictionary=False,
        column_encodings={
            "d32": Encoding.DELTA_BINARY_PACKED,
            "d64": Encoding.DELTA_BINARY_PACKED,
            "dba": Encoding.DELTA_BYTE_ARRAY,
            "bss": Encoding.BYTE_STREAM_SPLIT,
        },
    ) as w:
        w.write_rows(rows)
    assert pq.read_table(p).to_pylist() == rows


def test_multiple_row_groups_and_pages(tmp_path):
    p = tmp_path / "multi.parquet"
    schema = build_schema([data_column("v", Type.INT64, FRT.REQUIRED)])
    with FileWriter(p, schema, page_size=4096) as w:
        for batch in range(5):
            w.write_columns({"v": np.arange(batch * 10_000, (batch + 1) * 10_000)})
            w.flush_row_group()
    with FileReader(p) as r:
        assert r.num_row_groups == 5
        assert r.num_rows == 50_000
    t = pq.read_table(p)
    np.testing.assert_array_equal(t.column("v").to_numpy(), np.arange(50_000))


def test_auto_rowgroup_flush(tmp_path):
    p = tmp_path / "auto.parquet"
    schema = build_schema([data_column("v", Type.INT64, FRT.REQUIRED)])
    with FileWriter(p, schema, row_group_size=64 * 1024) as w:
        for i in range(50_000):
            w.write_row({"v": i})
    with FileReader(p) as r:
        assert r.num_row_groups > 1
        assert r.num_rows == 50_000


def test_statistics_written(tmp_path):
    p = tmp_path / "stats.parquet"
    schema = build_schema([
        data_column("v", Type.INT64, FRT.OPTIONAL),
        string_col("s", FRT.REQUIRED),
    ])
    rows = [{"v": None if i % 5 == 0 else i, "s": f"x{i:03d}"} for i in range(100)]
    with FileWriter(p, schema) as w:
        w.write_rows(rows)
    meta = pq.read_metadata(p)
    st = meta.row_group(0).column(0).statistics
    assert st.min == 1 and st.max == 99
    assert st.null_count == 20
    st2 = meta.row_group(0).column(1).statistics
    assert st2.min == "x000" and st2.max == "x099"


def test_kv_metadata_and_created_by(tmp_path):
    p = tmp_path / "kv.parquet"
    schema = build_schema([data_column("v", Type.INT64, FRT.REQUIRED)])
    with FileWriter(p, schema, kv_metadata={"who": "tpu", "why": "test"}) as w:
        w.write_row({"v": 1})
    meta = pq.read_metadata(p)
    kv = meta.metadata
    assert kv[b"who"] == b"tpu"
    with FileReader(p) as r:
        assert "tpu-parquet" in r.created_by
        assert r.key_value_metadata()["why"] == "test"


def test_int96_and_fixed_roundtrip(tmp_path):
    p = tmp_path / "i96.parquet"
    schema = build_schema([
        data_column("t", Type.INT96, FRT.REQUIRED),
        data_column("u", Type.FIXED_LEN_BYTE_ARRAY, FRT.REQUIRED,
                    ColumnParameters(type_length=4)),
    ])
    rows = [{"t": bytes(range(i, i + 12)), "u": bytes([i] * 4)} for i in range(20)]
    with FileWriter(p, schema, use_dictionary=False) as w:
        w.write_rows(rows)
    with FileReader(p) as r:
        got = list(r)
    assert got[3]["u"] == bytes([3] * 4)
    assert pq.read_table(p).num_rows == 20


def test_required_missing_raises(tmp_path):
    from tpu_parquet.shred import ShredError

    p = tmp_path / "req.parquet"
    schema = build_schema([data_column("v", Type.INT64, FRT.REQUIRED)])
    with FileWriter(p, schema) as w:
        with pytest.raises(ShredError, match="required"):
            w.write_row({})
        with pytest.raises(ShredError, match="expected int"):
            w.write_row({"v": "nope"})


def test_write_after_close_raises(tmp_path):
    from tpu_parquet.footer import ParquetError

    p = tmp_path / "closed.parquet"
    schema = build_schema([data_column("v", Type.INT64, FRT.REQUIRED)])
    w = FileWriter(p, schema)
    w.write_row({"v": 1})
    w.close()
    with pytest.raises(ParquetError):
        w.write_row({"v": 2})
    w.close()  # idempotent


def test_empty_file(tmp_path):
    p = tmp_path / "empty.parquet"
    schema = build_schema([data_column("v", Type.INT64, FRT.REQUIRED)])
    with FileWriter(p, schema) as w:
        pass
    with FileReader(p) as r:
        assert r.num_rows == 0
    assert pq.read_table(p).num_rows == 0


def test_nan_handling(tmp_path):
    # reference has dedicated NaN tests (readwrite_test.go:1354-1433)
    p = tmp_path / "nan.parquet"
    schema = build_schema([data_column("f", Type.DOUBLE, FRT.REQUIRED)])
    vals = [1.0, float("nan"), float("-inf"), 2.0]
    with FileWriter(p, schema, use_dictionary=False) as w:
        w.write_rows([{"f": v} for v in vals])
    got = pq.read_table(p).column("f").to_pylist()
    assert got[0] == 1.0 and np.isnan(got[1]) and got[2] == float("-inf")
    # stats must ignore NaN
    st = pq.read_metadata(p).row_group(0).column(0).statistics
    assert st.min == -np.inf and st.max == 2.0


def test_writer_output_header_field_sweep(tmp_path):
    """Self-validation beyond what pyarrow tolerates: walk EVERY page header
    of our writer's output and assert the format invariants a stricter
    reader (parquet-mr) would reject on — sizes, value accounting, stats
    bound ordering, dictionary placement."""
    import struct

    from tpu_parquet.chunk_decode import validate_chunk_meta, walk_pages
    from tpu_parquet.format import PageType

    p = str(tmp_path / "sweep.parquet")
    rows = sample_rows(20_000)
    with FileWriter(p, flat_schema(), codec=CompressionCodec.SNAPPY,
                    row_group_size=1 << 16, write_crc=True) as w:
        for row in rows:
            w.write_row(row)
    with FileReader(p) as r:
        leaves = {tuple(l.path): l for l in r.schema.leaves}
        for rg in r.metadata.row_groups:
            for chunk in rg.columns:
                md, offset = validate_chunk_meta(
                    chunk, leaves[tuple(chunk.meta_data.path_in_schema)])
                r._f.seek(offset)
                buf = r._f.read(md.total_compressed_size)
                total = 0
                first = True
                for ps in walk_pages(buf, md.num_values):
                    h = ps.header
                    assert h.compressed_page_size >= 0
                    assert h.uncompressed_page_size >= 0
                    assert h.crc is not None  # write_crc=True: every page
                    if h.type == PageType.DICTIONARY_PAGE:
                        assert first, "dictionary page must be first"
                        assert h.dictionary_page_header.num_values >= 0
                    elif h.type == PageType.DATA_PAGE:
                        dh = h.data_page_header
                        total += dh.num_values
                        st = dh.statistics
                        if st is not None and st.min_value is not None:
                            assert st.min_value <= st.max_value or (
                                # numeric stats compare by decoded value
                                len(st.min_value) in (4, 8))
                            if len(st.min_value) == 8:
                                lo = struct.unpack("<q", st.min_value)[0]
                                hi = struct.unpack("<q", st.max_value)[0]
                                assert lo <= hi
                    first = False
                assert total == md.num_values, "page value accounting"
