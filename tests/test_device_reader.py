"""Batched DeviceFileReader vs host FileReader: bit-for-bit differential.

Same oracle strategy as test_jax_decode.py, but through the fused per-chunk
path (one staged buffer + one dispatch per chunk, deferred checks).
"""

import io

import numpy as np
import pytest

from tpu_parquet.column import ByteArrayData
from tpu_parquet.device_reader import DeviceDictColumn, DeviceFileReader
from tpu_parquet.format import (
    CompressionCodec,
    ConvertedType,
    Encoding,
    FieldRepetitionType as FRT,
    LogicalType,
    StringType,
    Type,
)
from tpu_parquet.reader import FileReader
from tpu_parquet.schema.core import (
    ColumnParameters,
    build_schema,
    data_column,
    list_column,
)
from tpu_parquet.writer import FileWriter

RNG = np.random.default_rng(23)


def _string_col(name, repetition=FRT.OPTIONAL):
    return data_column(
        name, Type.BYTE_ARRAY, repetition,
        ColumnParameters(
            logical_type=LogicalType(STRING=StringType()),
            converted_type=ConvertedType.UTF8,
        ),
    )


def _compare_file(buf_bytes):
    host = FileReader(io.BytesIO(buf_bytes))
    dev = DeviceFileReader(io.BytesIO(buf_bytes))
    for i in range(host.num_row_groups):
        h_cols = host.read_row_group(i)
        d_cols = dev.read_row_group(i)
        assert set(h_cols) == set(d_cols)
        for name, h in h_cols.items():
            d = d_cols[name]
            got = d.to_host()
            if isinstance(h.values, ByteArrayData):
                assert isinstance(got, ByteArrayData), name
                np.testing.assert_array_equal(
                    got.offsets, h.values.offsets, err_msg=name
                )
                np.testing.assert_array_equal(got.heap, h.values.heap, err_msg=name)
            else:
                gv = got
                if h.values.dtype == np.bool_:
                    gv = gv.astype(np.bool_)
                if h.values.dtype.kind == "f":
                    np.testing.assert_array_equal(
                        np.ascontiguousarray(gv).view(np.uint8),
                        np.ascontiguousarray(h.values).view(np.uint8),
                        err_msg=name,
                    )
                else:
                    np.testing.assert_array_equal(gv, h.values, err_msg=name)
            d_def, d_rep = d.levels_to_host()
            for lvl, dl in (("def_levels", d_def), ("rep_levels", d_rep)):
                hl = getattr(h, lvl)
                assert (hl is None) == (dl is None), (name, lvl)
                if hl is not None:
                    np.testing.assert_array_equal(dl, hl, err_msg=name)
    host.close()
    dev.close()


def _write(schema, rows, **kw):
    buf = io.BytesIO()
    with FileWriter(buf, schema, **kw) as w:
        w.write_rows(rows)
    return buf.getvalue()


def _mixed_schema():
    return build_schema([
        data_column("id", Type.INT64, FRT.REQUIRED),
        data_column("x", Type.INT32, FRT.OPTIONAL),
        data_column("score", Type.DOUBLE, FRT.OPTIONAL),
        data_column("ratio", Type.FLOAT, FRT.REQUIRED),
        data_column("active", Type.BOOLEAN, FRT.REQUIRED),
        _string_col("name"),
    ])


def _mixed_rows(n):
    return [
        {
            "id": i * 3 - 1000,
            "x": None if i % 7 == 0 else i % 1000,
            "score": None if i % 11 == 0 else RNG.standard_normal(),
            "ratio": float(i % 13) * 0.5,
            "active": i % 2 == 0,
            "name": f"name-{i % 300}".encode(),
        }
        for i in range(n)
    ]


@pytest.mark.parametrize("codec", [
    CompressionCodec.UNCOMPRESSED, CompressionCodec.SNAPPY,
    CompressionCodec.ZSTD,
])
def test_batched_reader_codecs(codec):
    from conftest import require_codec

    require_codec(codec)
    _compare_file(_write(_mixed_schema(), _mixed_rows(2000), codec=codec))


@pytest.mark.parametrize("version", [1, 2])
def test_batched_reader_page_versions(version):
    _compare_file(
        _write(_mixed_schema(), _mixed_rows(2000), data_page_version=version)
    )


def test_batched_reader_multi_page_multi_rowgroup():
    # small pages + small row groups: concat + global run tables + multi-RG
    _compare_file(_write(
        _mixed_schema(), _mixed_rows(5000),
        page_size=2048, row_group_size=64 << 10,
    ))


def test_batched_reader_delta():
    schema = build_schema([
        data_column("i32", Type.INT32, FRT.REQUIRED),
        data_column("i64", Type.INT64, FRT.REQUIRED),
    ])
    rows = [
        {"i32": int(a), "i64": int(b)}
        for a, b in zip(
            RNG.integers(-(1 << 30), 1 << 30, 5000),
            RNG.integers(-(1 << 62), 1 << 62, 5000),
        )
    ]
    _compare_file(_write(
        schema, rows, use_dictionary=False, page_size=4096,
        column_encodings={"i32": Encoding.DELTA_BINARY_PACKED,
                          "i64": Encoding.DELTA_BINARY_PACKED},
    ))


def test_batched_reader_plain_no_dict():
    schema = build_schema([
        data_column("a", Type.INT64, FRT.REQUIRED),
        data_column("b", Type.DOUBLE, FRT.REQUIRED),
        data_column("c", Type.BOOLEAN, FRT.REQUIRED),
    ])
    rows = [
        {"a": i, "b": RNG.standard_normal(), "c": i % 3 == 0}
        for i in range(4000)
    ]
    _compare_file(_write(schema, rows, use_dictionary=False, page_size=4096))


def test_batched_reader_nested():
    schema = build_schema([
        list_column("tags", data_column("element", Type.INT64, FRT.OPTIONAL)),
        _string_col("label"),
    ])
    rows = []
    for i in range(2000):
        tags = (
            None if i % 13 == 0 else []
            if i % 7 == 0 else [int(j) if j % 3 else None for j in range(i % 6)]
        )
        rows.append({
            "tags": tags,
            "label": None if i % 5 == 0 else f"L{i % 40}".encode(),
        })
    _compare_file(_write(schema, rows, page_size=2048))


def test_dict_column_stays_encoded():
    """Fixed-width dict columns come back as DeviceDictColumn; materialize
    gathers on device and matches."""
    schema = build_schema([data_column("v", Type.INT64, FRT.REQUIRED)])
    rows = [{"v": int(v)} for v in RNG.integers(0, 50, 3000)]
    data = _write(schema, rows)
    dev = DeviceFileReader(io.BytesIO(data))
    col = dev.read_row_group(0)["v"]
    assert isinstance(col, DeviceDictColumn)
    mat = col.materialize()
    host = FileReader(io.BytesIO(data)).read_row_group(0)["v"]
    np.testing.assert_array_equal(mat.to_host(), host.values)
    np.testing.assert_array_equal(col.to_host(), host.values)


def test_batched_reader_column_projection():
    data = _write(_mixed_schema(), _mixed_rows(1000))
    dev = DeviceFileReader(io.BytesIO(data), columns=["id", "name"])
    cols = dev.read_row_group(0)
    assert set(cols) == {"id", "name"}


def test_batched_reader_corrupt_dict_index_host_check():
    """Out-of-range dictionary indices are rejected at decode time.

    With the native header walk, the stream max is computed on host during
    parse (meta_parse.cpp want_max) and the error raises eagerly — the decode
    path needs zero device→host syncs.
    """
    from tpu_parquet.footer import ParquetError
    from tests.test_jax_decode import _craft_dict_chunk
    from tpu_parquet.device_reader import decode_chunk_batched

    schema = build_schema([data_column("v", Type.INT64, FRT.REQUIRED)])
    leaf = schema.leaves[0]
    buf, codec = _craft_dict_chunk([1, 9, 2], np.arange(4))
    deferred = []
    with pytest.raises(ParquetError, match="out of range"):
        decode_chunk_batched(buf, codec, 3, leaf, deferred)
        # pure-Python walk defers to device: drain the check like finalize()
        for mx, dict_len, path in deferred:
            if int(np.asarray(mx)) >= dict_len:
                raise ParquetError(
                    f"dictionary index {int(np.asarray(mx))} out of range "
                    f"({dict_len}) in column {path}"
                )


def test_batched_reader_corrupt_dict_index_deferred_fallback(monkeypatch):
    """Without the native library, the deferred finalize() check still catches
    corrupt indices (the no-toolchain fallback path)."""
    from tpu_parquet.footer import ParquetError
    from tpu_parquet import native
    from tests.test_jax_decode import _craft_dict_chunk
    from tpu_parquet.device_reader import decode_chunk_batched

    monkeypatch.setattr(native, "hybrid_meta", lambda *a, **k: None)
    schema = build_schema([data_column("v", Type.INT64, FRT.REQUIRED)])
    leaf = schema.leaves[0]
    buf, codec = _craft_dict_chunk([1, 9, 2], np.arange(4))
    deferred = []
    col = decode_chunk_batched(buf, codec, 3, leaf, deferred)
    assert deferred, "deferred check must be recorded"
    mx, dict_len, path = deferred[0]
    assert int(np.asarray(mx)) == 9 and dict_len == 4


def test_reader_stats(tmp_path):
    """Observability counters (SURVEY.md §5.5): rows, pages/chunk, staged
    bytes, throughput — populated after a full read."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    p = tmp_path / "s.parquet"
    pq.write_table(
        pa.table({"a": np.arange(20000, dtype=np.int64),
                  "b": np.arange(20000, dtype=np.int64) * 2}),
        p, compression="snappy", row_group_size=6000, use_dictionary=False,
    )
    with DeviceFileReader(p) as r:
        for cols in r.iter_row_groups():
            pass
        st = r.stats()
    assert st.row_groups == 4
    assert st.chunks == 8
    assert st.rows == 20000
    assert st.pages >= st.chunks
    assert st.compressed_bytes > 0
    assert st.staged_bytes >= 2 * 2 * 20000
    from tpu_parquet import native

    if native.available():
        # both int64 columns narrow-transcoded to 2 bytes/value (16-bit
        # span), NOT full 8-byte width; without the native library the
        # transcode bails and full-width staging is correct
        assert st.staged_bytes < 2 * 8 * 20000
    assert st.wall_seconds > 0 and st.rows_per_sec > 0
    assert st.pages_per_chunk >= 1.0
    d = st.as_dict()
    assert d["rows"] == 20000 and d["bytes_per_sec"] > 0


def test_profiler_trace_hook(tmp_path):
    """profile_dir= wraps the decode in a JAX profiler trace (SURVEY §5.1)."""
    import os

    import pyarrow as pa
    import pyarrow.parquet as pq

    p = tmp_path / "t.parquet"
    pq.write_table(pa.table({"a": np.arange(1000, dtype=np.int64)}), p,
                   use_dictionary=False)
    trace_dir = str(tmp_path / "trace")
    with DeviceFileReader(p, profile_dir=trace_dir) as r:
        for cols in r.iter_row_groups():
            pass
    found = []
    for root, _, files in os.walk(trace_dir):
        found.extend(files)
    assert found, "profiler trace produced no files"


def test_device_reader_memory_budget(tmp_path):
    """HBM staging budget (SURVEY §5.3): a tight max_memory raises instead of
    staging an oversized row group; a generous one reads fine."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    from tpu_parquet.alloc import MemoryBudgetExceeded

    p = tmp_path / "b.parquet"
    pq.write_table(pa.table({"a": np.arange(200_000, dtype=np.int64)}), p,
                   use_dictionary=False, compression="snappy")
    with DeviceFileReader(p, max_memory=64 << 20) as r:
        assert sum(1 for _ in r.iter_row_groups()) == 1
    with DeviceFileReader(p, max_memory=100_000) as r:
        with pytest.raises(MemoryBudgetExceeded):
            for _ in r.iter_row_groups():
                pass


def test_iter_batches(tmp_path):
    """Fixed-shape device batches across row-group boundaries."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    n = 10_000
    a = np.arange(n, dtype=np.int64) * 3
    b = np.arange(n, dtype=np.float64) / 7
    p = tmp_path / "b.parquet"
    pq.write_table(pa.table({"a": a, "b": b}), p, row_group_size=2307,
                   use_dictionary=False)
    got_a, got_b = [], []
    with DeviceFileReader(p) as r:
        for batch in r.iter_batches(999):
            assert batch["a"].shape == (999,)
            assert batch["b"].shape == (999, 2) or batch["b"].shape == (999,)
            got_a.append(np.asarray(batch["a"]))
            hb = batch["b"]
            arr = np.asarray(hb)
            if arr.ndim == 2:  # f64 device representation: uint32 word pairs
                arr = np.ascontiguousarray(arr).view("<f8").reshape(-1)
            got_b.append(arr)
    full = n - n % 999  # drop_remainder semantics
    np.testing.assert_array_equal(np.concatenate(got_a), a[:full])
    np.testing.assert_array_equal(np.concatenate(got_b), b[:full])


def test_iter_batches_dict_column_materializes(tmp_path):
    import pyarrow as pa
    import pyarrow.parquet as pq

    vals = np.arange(5000, dtype=np.int64) % 17
    p = tmp_path / "d.parquet"
    pq.write_table(pa.table({"v": vals}), p)  # dictionary-encoded by default
    out = []
    with DeviceFileReader(p) as r:
        for batch in r.iter_batches(512):
            out.append(np.asarray(batch["v"]))
    np.testing.assert_array_equal(np.concatenate(out), vals[: 5000 - 5000 % 512])


def test_iter_batches_rejects_ragged(tmp_path):
    import pyarrow as pa
    import pyarrow.parquet as pq

    p = tmp_path / "s.parquet"
    pq.write_table(pa.table({"s": [f"x{i%1000}" for i in range(3000)]}), p,
                   use_dictionary=False)
    with DeviceFileReader(p) as r:
        with pytest.raises(TypeError, match="ragged"):
            next(iter(r.iter_batches(100)))


def test_mixed_dict_plain_chunk(tmp_path):
    """Dictionary-overflow chunks (dict-encoded page prefix with GROWING index
    widths, then PLAIN suffix) decode on the fused device path bit-for-bit."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    n = 400_000
    vals = np.arange(n, dtype=np.int64) * 7 - 3
    dbl = (np.arange(n) * 0.25) - 100.0
    p = tmp_path / "mix.parquet"
    # tiny dictionary page size limit forces overflow to PLAIN mid-chunk
    pq.write_table(pa.table({"v": vals, "d": dbl}), p,
                   compression="snappy", dictionary_pagesize_limit=64 << 10,
                   row_group_size=n)
    from tpu_parquet.chunk_decode import walk_pages
    from tpu_parquet.format import Encoding, PageType

    # confirm the fixture really is mixed (else the test silently weakens)
    with FileReader(p) as hr:
        md = hr.metadata.row_groups[0].columns[0].meta_data
        data = open(p, "rb").read()
        start = (md.dictionary_page_offset
                 if md.dictionary_page_offset is not None
                 else md.data_page_offset)
        encs = set()
        for ps in walk_pages(data[start : start + md.total_compressed_size],
                             md.num_values):
            if ps.header.type != PageType.DICTIONARY_PAGE:
                dh = ps.header.data_page_header or ps.header.data_page_header_v2
                encs.add(Encoding(dh.encoding))
        assert Encoding.PLAIN in encs and (
            Encoding.RLE_DICTIONARY in encs or Encoding.PLAIN_DICTIONARY in encs
        ), encs
        h = hr.read_row_group(0)
    with DeviceFileReader(p) as dr:
        d = dr.read_row_group(0)
    np.testing.assert_array_equal(np.asarray(d["v"].to_host()), h["v"].values)
    np.testing.assert_array_equal(
        np.asarray(d["d"].to_host()).view(np.uint8),
        np.ascontiguousarray(h["d"].values).view(np.uint8),
    )


def test_growing_dict_width_fused(tmp_path):
    """pyarrow writes multi-page dict chunks whose index bit width GROWS as
    the dictionary fills; the fused per-run-width expansion must decode them
    without falling back to the page-at-a-time path (the config-5 hot spot).
    """
    import pyarrow as pa
    import pyarrow.parquet as pq

    rng = np.random.default_rng(3)
    n = 60_000
    vals = rng.integers(0, 40_000, n)  # dict grows page to page
    p = tmp_path / "grow.parquet"
    pq.write_table(
        pa.table({"v": vals.astype(np.int64),
                  "d": rng.uniform(0, 1, n)}),
        p, compression="snappy", data_page_size=16 << 10,
        row_group_size=1 << 20,
    )
    # confirm the file really has multi-width dict chunks (else the test
    # silently stops covering the vw path)
    import tpu_parquet.device_reader as drmod

    calls = []
    orig = drmod._ChunkAssembler._finish_host

    def spy(self, common):
        calls.append(tuple(self.leaf.path))
        return orig(self, common)

    drmod._ChunkAssembler._finish_host = spy
    try:
        with DeviceFileReader(p) as r:
            got = r.read_row_group(0)
    finally:
        drmod._ChunkAssembler._finish_host = orig
    assert not calls, f"fell back to page-at-a-time host path for {calls}"
    with FileReader(p) as hr:
        h = hr.read_row_group(0)
    np.testing.assert_array_equal(got["v"].to_host(), h["v"].values)
    np.testing.assert_array_equal(
        np.ascontiguousarray(got["d"].to_host()).view(np.uint8),
        np.ascontiguousarray(h["d"].values).view(np.uint8),
    )


def test_flba_and_int96_fused(tmp_path):
    """FLBA (UUID-like) and INT96 PLAIN chunks take the fused rows path,
    not the per-page host fallback."""
    import datetime

    import pyarrow as pa
    import pyarrow.parquet as pq

    rng = np.random.default_rng(8)
    n = 20_000
    uuids = rng.integers(0, 256, (n, 16), dtype=np.uint8)
    ts = [datetime.datetime(2001, 1, 1) + datetime.timedelta(seconds=int(s))
          for s in rng.integers(0, 10**8, n)]
    p = tmp_path / "f.parquet"
    pq.write_table(
        pa.table({
            "u": pa.array([v.tobytes() for v in uuids],
                          type=pa.binary(16)),
            "t": pa.array(ts, type=pa.timestamp("ns")),
        }),
        p, use_dictionary=False, compression="snappy",
        use_deprecated_int96_timestamps=True, data_page_size=32 << 10,
    )
    import tpu_parquet.device_reader as drmod

    calls = []
    orig = drmod._ChunkAssembler._finish_host

    def spy(self, common):
        calls.append(tuple(self.leaf.path))
        return orig(self, common)

    drmod._ChunkAssembler._finish_host = spy
    try:
        with DeviceFileReader(p) as dr:
            d = dr.read_row_group(0)
    finally:
        drmod._ChunkAssembler._finish_host = orig
    assert not calls, f"fell back to page-at-a-time host path for {calls}"
    with FileReader(p) as hr:
        h = hr.read_row_group(0)
    gu = d["u"].to_host()
    np.testing.assert_array_equal(gu.offsets, h["u"].values.offsets)
    np.testing.assert_array_equal(gu.heap, h["u"].values.heap)
    np.testing.assert_array_equal(d["t"].to_host(), h["t"].values)


def test_rle_dict_index_out_of_range_rejected_when_width_covered(tmp_path):
    """RLE run values are raw unmasked bytes, so a dictionary whose length
    covers the full bit-width range (dict_len >= 2^width) does NOT make every
    encodable index valid: an RLE value byte patched out of range must be
    rejected by the host AND the batched device reader alike (the covered
    fast path may skip only the bit-packed O(values) scan)."""
    import jax
    import pytest

    from tpu_parquet.chunk_decode import validate_chunk_meta, walk_pages
    from tpu_parquet.column import ByteArrayData, ColumnData
    from tpu_parquet.errors import ParquetError
    from tpu_parquet.format import (
        CompressionCodec, FieldRepetitionType as FRT, PageType, Type,
    )
    from tpu_parquet.jax_decode import parse_data_page
    from tpu_parquet.reader import FileReader
    from tpu_parquet.schema.core import build_schema, data_column
    from tpu_parquet.writer import FileWriter

    path = str(tmp_path / "oob.parquet")
    schema = build_schema([data_column("s", Type.BYTE_ARRAY, FRT.REQUIRED)])
    # 2-entry dictionary (width=1, covered), long repeated tail -> RLE run
    vals = [b"aa"] * 4 + [b"bb"] * 200
    heap = np.frombuffer(b"".join(vals), np.uint8).copy()
    offs = np.cumsum([0] + [len(v) for v in vals]).astype(np.int64)
    with FileWriter(path, schema, codec=CompressionCodec.UNCOMPRESSED,
                    use_dictionary=True) as w:
        w.write_columns({"s": ColumnData(values=ByteArrayData(
            offsets=offs, heap=heap))})

    # locate the index stream's final RLE run value byte and patch it OOB
    with FileReader(path) as r:
        leaf = next(iter(r.schema.selected_leaves()))
        chunk = r.metadata.row_groups[0].columns[0]
        md, off = validate_chunk_meta(chunk, leaf)
        r._f.seek(off)
        buf = r._f.read(md.total_compressed_size)
        patched = None
        for ps in walk_pages(buf, md.num_values):
            if ps.header.type != PageType.DATA_PAGE:
                continue
            p = parse_data_page(ps, buf, md.codec, leaf)
            stream_file_pos = off + ps.payload_start + p.value_pos
            assert buf[ps.payload_start + p.value_pos] == 1  # width byte
            patched = stream_file_pos + len(buf) - ps.payload_start \
                - p.value_pos - 1  # last byte of the page = RLE value byte
        assert patched is not None
    data = bytearray(open(path, "rb").read())
    assert data[patched] in (0, 1)
    data[patched] = 3  # out of range for dict_len == 2
    open(path, "wb").write(bytes(data))

    with pytest.raises(ParquetError):
        with FileReader(path) as r:
            for _ in r.iter_row_groups():
                pass
    from tpu_parquet.device_reader import DeviceFileReader

    with pytest.raises(ParquetError):
        with DeviceFileReader(path) as r:
            for _ in r.iter_row_groups():
                pass
            r.finalize()


def test_plain_byte_array_device_compaction_matches_host(tmp_path):
    """PLAIN (non-dictionary) BYTE_ARRAY: the device-side lengths->offsets->
    heap compaction (_plain_bytes_pages_jit) must reproduce the host decode
    exactly across multi-page chunks, empty strings, nulls, and multiple row
    groups."""
    from tpu_parquet.column import ByteArrayData, ColumnData
    from tpu_parquet.device_reader import DeviceFileReader
    from tpu_parquet.format import CompressionCodec, FieldRepetitionType as FRT, Type
    from tpu_parquet.reader import FileReader
    from tpu_parquet.schema.core import build_schema, data_column
    from tpu_parquet.writer import FileWriter

    rng = np.random.default_rng(11)
    path = str(tmp_path / "plain_bytes.parquet")
    schema = build_schema([
        data_column("s", Type.BYTE_ARRAY, FRT.REQUIRED),
        data_column("t", Type.BYTE_ARRAY, FRT.OPTIONAL),
    ])
    n = 30_000
    lens = rng.integers(0, 30, n)  # includes empty strings
    heap = rng.integers(65, 91, int(lens.sum()), dtype=np.uint8)
    offs = np.zeros(n + 1, np.int64)
    np.cumsum(lens, out=offs[1:])
    mask = rng.random(n) < 0.25  # nulls for t
    lens_t = lens[~mask]
    offs_t = np.zeros(len(lens_t) + 1, np.int64)
    np.cumsum(lens_t, out=offs_t[1:])
    heap_t = rng.integers(97, 123, int(lens_t.sum()), dtype=np.uint8)
    with FileWriter(path, schema, codec=CompressionCodec.SNAPPY,
                    use_dictionary=False, page_size=16 << 10,
                    row_group_size=200 << 10) as w:
        w.write_columns({
            "s": ColumnData(values=ByteArrayData(offsets=offs, heap=heap)),
            "t": ColumnData(values=ByteArrayData(offsets=offs_t, heap=heap_t),
                            def_levels=(~mask).astype(np.uint32), max_def=1),
        })

    host = {}
    with FileReader(path) as r:
        for rg in r.iter_row_groups():
            for k, v in rg.items():
                host.setdefault(k, []).append(v)
    dev = {}
    with DeviceFileReader(path) as r:
        for rg in r.iter_row_groups():
            for k, v in rg.items():
                dev.setdefault(k, []).append(v)
    assert set(host) == set(dev)
    for k in host:
        assert len(host[k]) == len(dev[k])
        for h, d in zip(host[k], dev[k]):
            dh = d.to_host()
            np.testing.assert_array_equal(h.values.offsets, dh.offsets)
            np.testing.assert_array_equal(h.values.heap, dh.heap)
            dd, _ = d.levels_to_host()
            if h.def_levels is not None:
                np.testing.assert_array_equal(h.def_levels, dd)


def test_scan_files_multi_file_pipeline(tmp_path):
    """scan_files yields every file's row groups in order, equal to per-file
    reads, closes readers, and still raises deferred errors per file."""
    from tpu_parquet.column import ColumnData
    from tpu_parquet.device_reader import DeviceFileReader, scan_files
    from tpu_parquet.format import CompressionCodec, FieldRepetitionType as FRT, Type
    from tpu_parquet.schema.core import build_schema, data_column
    from tpu_parquet.writer import FileWriter

    rng = np.random.default_rng(5)
    schema = build_schema([data_column("v", Type.INT64, FRT.REQUIRED)])
    paths, expect = [], []
    for f in range(3):
        p = str(tmp_path / f"part{f}.parquet")
        vals = rng.integers(-100, 100, 5000 + f * 111)
        with FileWriter(p, schema, codec=CompressionCodec.SNAPPY,
                        row_group_size=16 << 10) as w:
            w.write_columns({"v": ColumnData(values=vals)})
        paths.append(p)
        expect.append(vals)

    got = {p: [] for p in paths}
    for p, cols in scan_files(paths, with_path=True):
        got[p].append(np.asarray(cols["v"].to_host()))
    for p, vals in zip(paths, expect):
        np.testing.assert_array_equal(np.concatenate(got[p]), vals)

    # parity with per-file iteration (row group boundaries included)
    for p in paths:
        per_file = []
        with DeviceFileReader(p) as r:
            for cols in r.iter_row_groups():
                per_file.append(np.asarray(cols["v"].to_host()))
        assert len(per_file) == len(got[p])
        for a, b in zip(per_file, got[p]):
            np.testing.assert_array_equal(a, b)


def test_scan_files_closes_readers_at_boundary_and_on_error(
    tmp_path, monkeypatch
):
    """A finished file's reader closes as soon as its last group is yielded
    (descriptors stay bounded over many shards), and an error mid-scan still
    closes every opened reader."""
    from tpu_parquet.device_reader import DeviceFileReader, scan_files
    from tpu_parquet.errors import ParquetError

    good = str(tmp_path / "good.parquet")
    good2 = str(tmp_path / "good2.parquet")
    bad = str(tmp_path / "bad.parquet")
    _write_oob_dict_file(good, patch=False)
    _write_oob_dict_file(good2, patch=False)
    _write_oob_dict_file(bad, patch=True)

    created = []
    orig = DeviceFileReader.__init__

    def spy(self, *a, **k):
        orig(self, *a, **k)
        created.append(self)

    monkeypatch.setattr(DeviceFileReader, "__init__", spy)

    # boundary closing: by the time file 2's group arrives, file 1 is closed
    seen = []
    for p, cols in scan_files([good, good2], with_path=True):
        seen.append(p)
        if p == good2:
            assert created[0]._host._f.closed
    assert seen == [good, good2]
    assert all(r._host._f.closed for r in created)

    # error propagation: the bad file's out-of-range dictionary index raises
    # (eagerly, during its prepare — pipeline depth means the preceding
    # yield is preempted), and the finally closes every reader
    created.clear()
    with pytest.raises(ParquetError):
        for cols in scan_files([good, bad]):
            pass
    assert len(created) == 2
    assert all(r._host._f.closed for r in created)


def _write_oob_dict_file(path, patch: bool):
    """A 2-entry-dictionary file; with ``patch`` its RLE index run value is
    rewritten out of range (the deferred/covered-width check must reject)."""
    from tpu_parquet.chunk_decode import validate_chunk_meta, walk_pages
    from tpu_parquet.column import ColumnData
    from tpu_parquet.format import PageType
    from tpu_parquet.jax_decode import parse_data_page

    schema = build_schema([data_column("s", Type.BYTE_ARRAY, FRT.REQUIRED)])
    vals = [b"aa"] * 4 + [b"bb"] * 200
    heap = np.frombuffer(b"".join(vals), np.uint8).copy()
    offs = np.cumsum([0] + [len(v) for v in vals]).astype(np.int64)
    with FileWriter(path, schema, codec=CompressionCodec.UNCOMPRESSED,
                    use_dictionary=True) as w:
        w.write_columns({"s": ColumnData(values=ByteArrayData(
            offsets=offs, heap=heap))})
    if not patch:
        return
    with FileReader(path) as r:
        leaf = next(iter(r.schema.selected_leaves()))
        chunk = r.metadata.row_groups[0].columns[0]
        md, off = validate_chunk_meta(chunk, leaf)
        r._f.seek(off)
        buf = r._f.read(md.total_compressed_size)
        patched = None
        for ps in walk_pages(buf, md.num_values):
            if ps.header.type != PageType.DATA_PAGE:
                continue
            parse_data_page(ps, buf, md.codec, leaf)
            patched = off + len(buf) - 1  # last byte = RLE run value byte
        assert patched is not None
    data = bytearray(open(path, "rb").read())
    assert data[patched] in (0, 1)
    data[patched] = 3
    open(path, "wb").write(bytes(data))


def test_narrow_int_transcode_exact(tmp_path):
    """PLAIN INT columns whose span fits < width bytes ship truncated
    (device_reader._plan_narrow_ints) and must reconstruct bit-exactly —
    including negative minima, constant columns, multi-page chunks, and the
    full-range case that must BYPASS the transcode."""
    import tpu_parquet.device_reader as DR

    rng = np.random.default_rng(11)
    cases = {
        "k1": rng.integers(-100, 100, 30000),
        "k3": rng.integers(1, 200_000, 30000),
        "k5_neg": -(1 << 33) + rng.integers(0, 1 << 34, 30000),
        "k8_full": rng.integers(-(1 << 62), 1 << 62, 30000),
        "const": np.full(30000, -42, dtype=np.int64),
        "i32_k2": rng.integers(0, 1000, 30000).astype(np.int32),
        "i32_full": rng.integers(-(1 << 31), (1 << 31) - 1, 30000).astype(np.int32),
    }
    hits = {}
    orig = DR._ChunkAssembler._plan_narrow_ints

    def spy(self, common, stager, name, **kw):
        r = orig(self, common, stager, name, **kw)
        hits[".".join(self.leaf.path)] = r is not None
        return r

    DR._ChunkAssembler._plan_narrow_ints = spy
    try:
        cols = [
            data_column(n, Type.INT32 if v.dtype == np.int32 else Type.INT64,
                        FRT.REQUIRED)
            for n, v in cases.items()
        ]
        path = str(tmp_path / "narrow.parquet")
        with FileWriter(path, build_schema(cols), use_dictionary=False,
                        codec=CompressionCodec.SNAPPY) as w:
            for lo in range(0, 30000, 10000):  # several pages per chunk
                w.write_columns({n: v[lo:lo + 10000] for n, v in cases.items()})
        with DeviceFileReader(path) as r:
            for rg in r.iter_row_groups():
                for n, v in cases.items():
                    got = rg[n].to_host()
                    assert got.dtype == v.dtype, n
                    assert np.array_equal(got, v), n
    finally:
        DR._ChunkAssembler._plan_narrow_ints = orig
    from tpu_parquet import native

    if native.available():
        # wide-span columns (k8_full, i32_full) never reach the narrow
        # planner (stats hint rules them out of the preference list); the
        # mid-width spans rank narrow ahead of shipping the compressed
        # stream and must transcode.  k1/const are the ship planner's
        # judgment call: their snappy payloads are so small (1 significant
        # byte / constant) that keeping them compressed can beat even the
        # 1-byte transcode, so the planner may route them either way —
        # but whenever the narrow planner IS consulted it must succeed.
        assert "k8_full" not in hits and "i32_full" not in hits
        assert all(hits.values()), hits
        assert {"k3", "k5_neg", "i32_k2"} <= {k for k, v in hits.items()
                                              if v}, hits


def test_device_snappy_expansion_exact(tmp_path):
    """Fixed-width PLAIN SNAPPY chunks ship COMPRESSED and expand on device
    (_plan_device_snappy / _snappy_plain_staged_jit).  Values must match the
    host decode bit for bit — including copy-heavy (RLE-style) streams that
    exercise the pointer-doubling resolver, doubles (word-pair form), and v2
    pages whose levels live outside the compressed region."""
    import pyarrow as pa
    import pyarrow.parquet as pq
    import tpu_parquet.device_reader as DR

    rng = np.random.default_rng(5)
    n = 60000
    wide = rng.integers(-(1 << 62), 1 << 62, n)
    rep = np.repeat(rng.integers(0, 40, n // 200), 200) * (1 << 40)  # copies
    dbl = rng.uniform(900.0, 105000.0, n)
    opt = wide.astype("float64")
    opt_mask = rng.random(n) < 0.25
    p = str(tmp_path / "ds.parquet")
    # v2 pages: levels live outside the compressed region, so even the
    # OPTIONAL column is device-snappy eligible.  NOTE pyarrow stores
    # incompressible v2 pages with is_compressed=False — only `rep`
    # (copy-heavy) actually arrives compressed; the others still exercise
    # the route-selection logic and correctness.
    pq.write_table(
        pa.table({
            "wide": wide, "rep": rep, "dbl": dbl,
            "opt": pa.array(np.where(opt_mask, np.nan, opt),
                            mask=opt_mask),
        }),
        p, compression="snappy", use_dictionary=False,
        data_page_version="2.0", row_group_size=20000,
    )
    used = []
    orig = DR._ChunkAssembler._plan_device_snappy

    def spy(self, common, stager, name):
        r = orig(self, common, stager, name)
        used.append((".".join(self.leaf.path), r is not None))
        return r

    DR._ChunkAssembler._plan_device_snappy = spy
    try:
        host = {}
        with FileReader(p) as r:
            for rg in r.iter_row_groups():
                for k, v in rg.items():
                    host.setdefault(k, []).append(v)
        with DeviceFileReader(p) as r:
            for i, rg in enumerate(r.iter_row_groups()):
                for k, col in rg.items():
                    hv = host[k][i].values
                    got = col.to_host()
                    assert np.array_equal(
                        np.asarray(got).view(np.uint8).reshape(-1),
                        np.asarray(hv).view(np.uint8).reshape(-1),
                    ), k
    finally:
        DR._ChunkAssembler._plan_device_snappy = orig
    from tpu_parquet import native

    if native.available():
        # the copy-heavy column is the one pyarrow actually compressed; it
        # must have taken the device expansion path in every row group
        assert [k for k, ok in used if ok].count("rep") == 3


def test_device_snappy_kill_switch(tmp_path, monkeypatch):
    """TPQ_DEVICE_SNAPPY=0 must force the host-decompress path with
    identical results (the A/B the bench and debugging rely on)."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    rng = np.random.default_rng(6)
    vals = rng.integers(-(1 << 62), 1 << 62, 30000)
    p = str(tmp_path / "ks.parquet")
    pq.write_table(pa.table({"v": vals}), p, compression="snappy",
                   use_dictionary=False)
    monkeypatch.setenv("TPQ_DEVICE_SNAPPY", "0")
    with DeviceFileReader(p) as r:
        (rg,) = list(r.iter_row_groups())
        assert np.array_equal(rg["v"].to_host(), vals)


def test_device_snappy_deep_copy_chain(tmp_path, monkeypatch):
    """A constant DOUBLE column produces an RLE-style snappy stream whose
    copy chain is thousands of ops deep — the pointer-doubling resolver
    must converge within its static iteration bound and stay bit-exact.
    (Floats never take the narrow-int transcode, so this routes through
    _plan_device_snappy by construction.)"""
    import tpu_parquet.device_reader as DR

    monkeypatch.delenv("TPQ_DEVICE_SNAPPY", raising=False)
    n = 300000
    vals = np.full(n, 1.2345678e5)  # constant: maximal back-reference chains
    schema = build_schema([data_column("d", Type.DOUBLE, FRT.REQUIRED)])
    p = str(tmp_path / "deep.parquet")
    with FileWriter(p, schema, use_dictionary=False,
                    codec=CompressionCodec.SNAPPY, page_size=1 << 20) as w:
        w.write_columns({"d": vals})
    used = []
    orig = DR._ChunkAssembler._plan_device_snappy

    def spy(self, common, stager, name):
        r = orig(self, common, stager, name)
        used.append(r is not None)
        return r

    monkeypatch.setattr(DR._ChunkAssembler, "_plan_device_snappy", spy)
    with DeviceFileReader(p) as r:
        out = np.concatenate(
            [np.asarray(rg["d"].to_host()) for rg in r.iter_row_groups()]
        )
        st = r.stats()
    assert np.array_equal(out.view(np.uint8), vals.view(np.uint8))
    from tpu_parquet import native

    if native.available():
        assert all(used) and used, used
        assert st.pages_device_expanded > 0


def test_snappy_plan_four_byte_offset_copy():
    """Hand-crafted stream with a kind-3 (4-byte little-endian offset) copy
    — a tag our own compressor never emits — must plan identically to the
    native decompressor's output (the device resolver consumes exactly this
    plan; the host-resolver differential pins its semantics)."""
    from tpu_parquet import native

    if not native.available():
        pytest.skip("native library unavailable")
    # uncompressed: 70000 literal bytes then 100 bytes copied from offset 65540
    lit = bytes(range(256)) * 274  # 70144 bytes
    lit = lit[:70000]
    out_len = 70100
    stream = bytearray()
    # uvarint length header
    v = out_len
    while v >= 0x80:
        stream.append((v & 0x7F) | 0x80)
        v >>= 7
    stream.append(v)
    # literal (len-1 >= 60 -> 62<<2 with 3 extra length bytes)
    ln = len(lit) - 1
    stream.append(62 << 2)
    stream += bytes([ln & 0xFF, (ln >> 8) & 0xFF, (ln >> 16) & 0xFF])
    stream += lit
    # kind-3 copy: len 100 (split: 64 + 36), offset 65540 (> 2^16)
    for clen in (64, 36):
        stream.append(((clen - 1) << 2) | 3)
        off = 65540
        stream += bytes([off & 0xFF, (off >> 8) & 0xFF,
                         (off >> 16) & 0xFF, (off >> 24) & 0xFF])
    data = bytes(stream)
    want = native.snappy_decompress(data, out_len)
    r = native.snappy_plan(data, out_len)
    assert not isinstance(r, int) and r is not None
    dst_end, op_src, is_lit, depth = r
    # execute the plan on host (mirror of the device resolver's semantics)
    out = np.zeros(out_len, np.uint8)
    comp = np.frombuffer(data, np.uint8)
    start = 0
    for e, s, lt in zip(dst_end, op_src, is_lit):
        if lt:
            out[start:e] = comp[s : s + (e - start)]
        else:
            for i in range(e - start):
                out[start + i] = out[start - s + (i % s)]
        start = e
    assert bytes(out) == bytes(want)
    assert depth >= 1


def test_fused_row_group_mode_matches_default():
    """TPQ_FUSE_RG=1 (the opt-in whole-row-group fused jit) must decode
    byte-identically to the default per-plan dispatch — the opt-in path
    shares the _Plan contract and would otherwise rot untested."""
    import tpu_parquet.device_reader as dr

    path = _write(_mixed_schema(), _mixed_rows(3000),
                  page_size=4096, row_group_size=128 << 10)
    old = dr._FUSE_RG
    dr._FUSE_RG = True
    try:
        _compare_file(path)
    finally:
        dr._FUSE_RG = old
