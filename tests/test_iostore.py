"""Fault-tolerant IO backend tests (ISSUE 7): the fault matrix, the
coalescing planner, the degradation ladder, truncation surfacing, and the
TPQ_* env-parsing hardening.

The acceptance contract: every injected transient fault recovers to
bit-identical output; exhausted retries raise RetryExhaustedError with an
attempt log; an injected stall fires the watchdog and ``pq_tool autopsy``
classifies the dump as network-stall naming the offending range.
"""

import io
import json
import logging
import os
import threading

import numpy as np
import pytest

from tpu_parquet.errors import (HangError, ParquetError, RetryExhaustedError,
                                TransientIOError)
from tpu_parquet.iostore import (CoalescedFetcher, FaultInjectingStore,
                                 FaultSpec, GenericRangeStore, IOConfig,
                                 LocalStore, plan_coalesced, require_full,
                                 resolve_store)
from tpu_parquet.reader import FileReader
from tpu_parquet.writer import FileWriter


def _write_file(path, groups=3, rows=400, seed=0):
    from tpu_parquet.format import (CompressionCodec,
                                    FieldRepetitionType as FRT, Type)
    from tpu_parquet.schema.core import build_schema, data_column

    schema = build_schema([data_column("a", Type.INT64, FRT.REQUIRED),
                           data_column("b", Type.INT64, FRT.REQUIRED)])
    rng = np.random.default_rng(seed)
    with FileWriter(path, schema, codec=CompressionCodec.SNAPPY) as w:
        for _ in range(groups):
            w.write_columns({"a": rng.integers(0, 1 << 30, rows),
                             "b": rng.integers(0, 1 << 30, rows)})
            w.flush_row_group()
    return path


@pytest.fixture(scope="module")
def pq_file(tmp_path_factory):
    path = str(tmp_path_factory.mktemp("iostore") / "faulty.parquet")
    _write_file(path)
    with FileReader(path) as r:
        base = r.read_pylist()
    return path, base


def _cfg(**kw):
    kw.setdefault("retries", 4)
    kw.setdefault("backoff_ms", 1.0)
    return IOConfig(**kw)


def _fault_factory(spec, config=None, stores=None, seed=0):
    def make(f):
        st = FaultInjectingStore(LocalStore(f), spec,
                                 config=config or _cfg(), seed=seed)
        if stores is not None:
            stores.append(st)
        return st

    return make


def _obs_threads():
    return [t.name for t in threading.enumerate()
            if t.name.startswith(("tpq-sampler", "tpq-watchdog"))]


# ---------------------------------------------------------------------------
# the fault matrix: recoverable faults are invisible in the output
# ---------------------------------------------------------------------------

RECOVERABLE = {
    "latency_spike": FaultSpec(latency_s=0.005),
    "transient_errors": FaultSpec(fail_first=2),
    "torn_read": FaultSpec(torn_first=1),
    "torn_then_error": FaultSpec(torn_first=1, fail_first=2),
}


@pytest.mark.parametrize("prefetch", [0, 4])
@pytest.mark.parametrize("fault", sorted(RECOVERABLE))
def test_fault_matrix_recovers_bit_identical(pq_file, fault, prefetch):
    path, base = pq_file
    stores = []
    with FileReader(path, prefetch=prefetch,
                    store=_fault_factory(RECOVERABLE[fault],
                                         stores=stores)) as r:
        assert r.read_pylist() == base
        tree = r.obs_registry().as_dict()
    d = tree["io"]
    assert d["exhausted"] == 0
    if "transient" in fault or "error" in fault:
        assert d["retries"] > 0 and d["transient_errors"] > 0
    if fault.startswith("torn"):
        assert d["short_reads"] > 0


@pytest.mark.parametrize("prefetch", [0, 4])
def test_retries_exhausted_raises_with_attempt_log(pq_file, prefetch):
    path, _base = pq_file
    with pytest.raises(RetryExhaustedError) as ei:
        with FileReader(path, prefetch=prefetch,
                        store=_fault_factory(
                            FaultSpec(fail_first=99),
                            config=_cfg(retries=2))) as r:
            r.read_all()
    e = ei.value
    assert len(e.attempts) == 3  # first try + 2 retries
    assert e.offset is not None and e.size
    assert all("injected transient" in a["error"] for a in e.attempts)


def test_per_scan_retry_budget_exhausts(pq_file):
    path, _base = pq_file
    # every chunk fails twice; a 1-retry scan budget dies long before the
    # per-request retry limit would
    with pytest.raises(RetryExhaustedError, match="retry budget"):
        with FileReader(path, prefetch=0,
                        store=_fault_factory(
                            FaultSpec(fail_first=2),
                            config=_cfg(retries=4, retry_budget=1))) as r:
            r.read_all()


def test_retry_budget_resets_per_scan(pq_file):
    path, base = pq_file
    stores = []
    # 6 chunk reads x 1 transient each = 6 retries per scan: a 8-retry
    # budget survives any single scan but would die on the second scan if
    # the budget leaked across begin_scan()
    fac = _fault_factory(FaultSpec(fail_first=1),
                         config=_cfg(retries=2, retry_budget=8),
                         stores=stores)
    with FileReader(path, prefetch=4, store=fac) as r:
        assert r.read_pylist() == base
        stores[0].spec = FaultSpec(fail_first=2)  # fresh faults, scan 2
        stores[0]._attempts.clear()
        assert r.read_pylist() == base


def test_deadline_bounds_a_slow_store(pq_file):
    path, _base = pq_file
    with pytest.raises(RetryExhaustedError):
        with FileReader(path, prefetch=0,
                        store=_fault_factory(
                            FaultSpec(latency_s=0.2),
                            config=_cfg(retries=3,
                                        deadline_s=0.05))) as r:
            r.read_all()


def test_deadline_env_knob(pq_file, monkeypatch):
    monkeypatch.setenv("TPQ_IO_DEADLINE_S", "0.04")
    path, _base = pq_file
    stores = []
    with pytest.raises(RetryExhaustedError):
        with FileReader(path, prefetch=0,
                        store=_fault_factory(FaultSpec(latency_s=0.2),
                                             config=IOConfig.from_env(),
                                             stores=stores)) as r:
            r.read_all()
    assert stores[0].stats.deadline_hits > 0


# ---------------------------------------------------------------------------
# stall -> watchdog -> HangError -> autopsy network-stall naming the range
# ---------------------------------------------------------------------------

def test_stall_fires_watchdog_and_autopsy_names_range(tmp_path, monkeypatch):
    from tpu_parquet.device_reader import DeviceFileReader
    from tpu_parquet.obs import autopsy_dump

    monkeypatch.setenv("TPQ_FLIGHT", str(tmp_path / "stall_dump.json"))
    path = _write_file(str(tmp_path / "stall.parquet"))
    stores = []
    dr = DeviceFileReader(
        path, prefetch=2, max_memory=1 << 20, hang_s=0.3,
        store=_fault_factory(FaultSpec(stall_first=1, stall_s=60.0),
                             config=_cfg(retries=0), stores=stores))
    try:
        with pytest.raises(HangError) as ei:
            for _ in dr.iter_row_groups():
                pass
    finally:
        for s in stores:
            s.release()
        dr.close()
    assert not _obs_threads()
    e = ei.value
    assert e.dump_path and os.path.exists(e.dump_path)
    with open(e.dump_path) as f:
        rep = autopsy_dump(json.load(f))
    assert rep["verdict"] == "network-stall"
    assert rep["io"] is not None
    assert rep["io"]["size"] > 0 and rep["io"]["age_s"] > 0
    assert str(rep["io"]["offset"]) in rep["probable_cause"]


def test_stall_sequential_path_also_raises_hang(tmp_path, monkeypatch):
    """prefetch=0: the CONSUMER thread itself is pinned inside the stalled
    fetch — the watchdog's store abort must wake it there too."""
    from tpu_parquet.device_reader import DeviceFileReader

    monkeypatch.setenv("TPQ_FLIGHT", str(tmp_path / "stall0_dump.json"))
    path = _write_file(str(tmp_path / "stall0.parquet"))
    stores = []
    dr = DeviceFileReader(
        path, prefetch=0, hang_s=0.3,
        store=_fault_factory(FaultSpec(stall_first=1, stall_s=60.0),
                             config=_cfg(retries=0), stores=stores))
    try:
        with pytest.raises(HangError):
            for _ in dr.iter_row_groups():
                pass
    finally:
        for s in stores:
            s.release()
        dr.close()
    assert not _obs_threads()


def test_scan_files_through_fault_store(pq_file, tmp_path):
    from tpu_parquet.device_reader import scan_files

    path, base = pq_file
    path2 = _write_file(str(tmp_path / "second.parquet"), seed=7)
    rows = {"a": [], "b": []}
    for cols in scan_files([path, path2], prefetch=2,
                           store=_fault_factory(FaultSpec(fail_first=1))):
        for k, v in cols.items():
            rows[k].extend(np.asarray(v.to_host()).tolist())
    with FileReader(path2) as r:
        base2 = r.read_pylist()
    assert rows["a"] == base["a"] + base2["a"]
    assert not _obs_threads()


# ---------------------------------------------------------------------------
# coalescing: planner + ladder
# ---------------------------------------------------------------------------

def test_plan_coalesced_merges_within_gap():
    plan = plan_coalesced([(0, 100), (110, 50), (1000, 20)], gap=16)
    assert [(g.offset, g.size) for g in plan] == [(0, 160), (1000, 20)]
    assert plan[0].members == {(0, 100): 1, (110, 50): 1}


def test_plan_coalesced_respects_cap_and_determinism():
    ranges = [(i * 120, 100) for i in range(8)]
    plan = plan_coalesced(ranges, gap=64, max_span=300)
    assert all(g.size <= 300 for g in plan)
    again = plan_coalesced(list(reversed(ranges)), gap=64, max_span=300)
    assert [g.key() for g in plan] == [g.key() for g in again]
    # full coverage, no member lost to the splits
    members = [m for g in plan for m in g.members]
    assert sorted(members) == sorted(ranges)


def test_coalesced_reads_used_on_fault_store(pq_file):
    path, base = pq_file
    stores = []
    with FileReader(path, prefetch=4,
                    store=_fault_factory(FaultSpec(), stores=stores)) as r:
        assert r.read_pylist() == base
    d = stores[0].stats.as_dict()
    assert d["coalesced_spans"] > 0
    # fewer store round trips than chunks: that is the point
    assert d["reads"] <= d["coalesced_spans"] + 1


def test_coalesced_failure_degrades_to_single_ranges(pq_file):
    path, base = pq_file
    stores = []

    def only_big(offset, size):
        return size > 6000  # spans only: members stay healthy

    with FileReader(path, prefetch=4,
                    store=_fault_factory(
                        FaultSpec(fail_first=99, match=only_big),
                        config=_cfg(retries=1), stores=stores)) as r:
        assert r.read_pylist() == base  # ladder: span fails, singles serve
    d = stores[0].stats.as_dict()
    assert d["coalesce_fallbacks"] > 0
    assert stores[0].coalesce_disabled  # 2+ span failures: stop trying


def test_lying_span_size_degrades_not_corrupts():
    data = bytes(range(256)) * 8

    class Lying(GenericRangeStore):
        def size(self):
            return len(data)

        def _fetch_once(self, offset, size, timeout):
            buf = data[offset: offset + size]
            return buf[:-5] if size > 120 else buf

    st = Lying(config=_cfg(retries=1, coalesce_gap=64))
    fetcher = CoalescedFetcher(st, [(0, 100), (100, 100)])
    assert fetcher.groups == 1
    assert fetcher.read(0, 100) == data[:100]
    assert fetcher.read(100, 100) == data[100:200]
    assert st.stats.coalesce_fallbacks == 1


def test_eof_padded_full_length_lie_is_rejected():
    """A store that pads its EOF reads to full length fabricates bytes —
    read_range must reject the provably-past-EOF response, so the ladder
    serves the members from honest single reads (fuzz finding)."""
    data = bytes(range(200)) * 2  # 400-byte object

    class Padding(GenericRangeStore):
        def size(self):
            return len(data)

        def _fetch_once(self, offset, size, timeout):
            buf = data[offset: offset + size]
            if len(buf) < size and size > 120:
                return buf + b"\x00" * (size - len(buf))  # padded EOF span
            return buf

    st = Padding(config=_cfg(retries=1, coalesce_gap=64))
    # two members whose coalesced span ends 50 bytes past EOF
    fetcher = CoalescedFetcher(st, [(250, 100), (350, 100)])
    assert fetcher.read(250, 100) == data[250:350]
    assert fetcher.read(350, 100) == data[350:]  # honest short EOF read
    assert st.stats.coalesce_fallbacks == 1
    # a direct full-length-past-EOF response exhausts as a lie, never serves
    with pytest.raises(RetryExhaustedError, match="past EOF"):
        st.read_range(300, 150)


def test_local_store_never_coalesces(pq_file):
    path, base = pq_file
    with FileReader(path, prefetch=4) as r:
        assert r.read_pylist() == base
        assert r._store.stats is None
        assert not r._store.prefers_coalescing
        assert r.obs_registry().as_dict()["io"] is None


# ---------------------------------------------------------------------------
# truncation: a short file is named as such (satellite 1)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("prefetch", [0, 4])
def test_truncated_file_names_offset_got_want(pq_file, prefetch):
    path, _base = pq_file
    with open(path, "rb") as f:
        whole = f.read()
    from tpu_parquet.footer import read_file_metadata

    md = read_file_metadata(io.BytesIO(whole))
    with pytest.raises(ParquetError, match=r"truncated file.*wanted \d+ "
                       r"bytes at offset \d+, got \d+"):
        with FileReader(io.BytesIO(whole[:200]), metadata=md,
                        prefetch=prefetch) as r:
            r.read_all()


def test_truncated_sequential_read_chunk_names_offset(pq_file):
    """The prefetch=0 read_row_group path (chunk_decode.read_chunk) names
    the truncation too — not just the pipeline's decode_item."""
    from tpu_parquet.footer import read_file_metadata

    path, _base = pq_file
    with open(path, "rb") as f:
        whole = f.read()
    md = read_file_metadata(io.BytesIO(whole))
    with pytest.raises(ParquetError, match="truncated file reading column"):
        with FileReader(io.BytesIO(whole[:150]), metadata=md) as r:
            r.read_row_group(0)


def test_require_full_passthrough():
    assert require_full(b"abcd", 0, 4) == b"abcd"
    with pytest.raises(ParquetError, match="column x.y"):
        require_full(b"ab", 10, 4, context="column x.y")


# ---------------------------------------------------------------------------
# env hardening: malformed numeric knobs degrade with one warning
# ---------------------------------------------------------------------------

NUMERIC_KNOBS = [
    # (env name, resolver, expected default)
    ("TPQ_SAMPLE_MS",
     lambda: __import__("tpu_parquet.obs", fromlist=["resolve_sample_ms"])
     .resolve_sample_ms(), 0.0),
    ("TPQ_HANG_S",
     lambda: __import__("tpu_parquet.obs", fromlist=["resolve_hang_s"])
     .resolve_hang_s(), 0.0),
    ("TPQ_RING_EVENTS",
     lambda: __import__("tpu_parquet.obs", fromlist=["FlightRecorder"])
     .FlightRecorder().capacity, 256),
    ("TPQ_LINK_MBPS",
     lambda: __import__("tpu_parquet.ship", fromlist=["ShipPlanner"])
     .ShipPlanner().link_mbps, 350.0),
    ("TPQ_IO_DEADLINE_S", lambda: IOConfig.from_env().deadline_s, 0.0),
    ("TPQ_IO_RETRIES", lambda: IOConfig.from_env().retries, 4),
    ("TPQ_IO_BACKOFF_MS", lambda: IOConfig.from_env().backoff_ms, 25.0),
    ("TPQ_IO_RETRY_BUDGET", lambda: IOConfig.from_env().retry_budget, 64),
    ("TPQ_IO_COALESCE_GAP", lambda: IOConfig.from_env().coalesce_gap,
     1 << 16),
]


@pytest.mark.parametrize("name,resolve,default",
                         NUMERIC_KNOBS, ids=[k[0] for k in NUMERIC_KNOBS])
def test_malformed_env_degrades_with_warning(name, resolve, default,
                                             monkeypatch, caplog):
    bad = f"abc-{name}"  # unique per knob: the once-per-value warning fires
    monkeypatch.setenv(name, bad)
    with caplog.at_level(logging.WARNING, logger="tpu_parquet.obs"):
        assert resolve() == default  # degraded, not raised
    assert any(bad in rec.message for rec in caplog.records)


@pytest.mark.parametrize("name,resolve,default",
                         NUMERIC_KNOBS, ids=[k[0] for k in NUMERIC_KNOBS])
def test_valid_env_still_parses(name, resolve, default, monkeypatch):
    monkeypatch.setenv(name, "7")
    v = resolve()
    assert v == pytest.approx(7)


def test_negative_numeric_env_clamps(monkeypatch):
    monkeypatch.setenv("TPQ_IO_RETRIES", "-3")
    assert IOConfig.from_env().retries == 0


# ---------------------------------------------------------------------------
# store plumbing details
# ---------------------------------------------------------------------------

def test_local_store_bytesio_and_size():
    st = LocalStore(io.BytesIO(b"0123456789"))
    assert not st.parallel  # no usable fd: the locked seek+read path
    assert st.size() == 10
    assert st.read_range(2, 4) == b"2345"
    assert st.read_range(8, 10) == b"89"  # short at EOF, no raise


def test_resolve_store_forms(pq_file):
    path, _base = pq_file
    f = open(path, "rb")
    try:
        assert isinstance(resolve_store(f, None), LocalStore)
        st = FaultInjectingStore(LocalStore(f))
        assert resolve_store(f, st) is st
        assert isinstance(resolve_store(f, lambda g: LocalStore(g)),
                          LocalStore)
        with pytest.raises(TypeError):
            resolve_store(f, lambda g: object())
        with pytest.raises(TypeError):
            resolve_store(f, 42)
    finally:
        f.close()


def test_torn_reread_verification_mismatch_costs_a_retry():
    """A full re-read that DISAGREES with the torn attempt's prefix is
    rejected as a transient fault (data instability) and retried; a
    subsequent consistent read is accepted — CRC at the decode layer stays
    the terminal integrity check."""
    flips = {"n": 0}

    class Unstable(GenericRangeStore):
        def size(self):
            return 1 << 20

        def _fetch_once(self, offset, size, timeout):
            flips["n"] += 1
            if flips["n"] == 1:
                return b"\xAA" * (size // 2)  # torn
            return (b"\xBB" if flips["n"] == 2 else b"\xAA") * size

    st = Unstable(config=_cfg(retries=5))
    out = st.read_range(0, 100)
    # attempt 1 torn, attempt 2 full-but-mismatched (rejected), attempt 3
    # matches the torn prefix and is accepted
    assert flips["n"] == 3
    assert out == b"\xAA" * 100
    assert st.stats.short_reads == 1
    assert st.stats.transient_errors == 2


def test_abort_poisons_inflight_and_future_reads():
    boom = HangError("wedged", dump_path="/tmp/x.json")

    class Slow(GenericRangeStore):
        def size(self):
            return 1 << 20

        def _fetch_once(self, offset, size, timeout):
            raise TransientIOError("flaky")

    st = Slow(config=_cfg(retries=50, backoff_ms=5))
    done = {}

    def reader():
        try:
            st.read_range(0, 64)
        except BaseException as e:  # noqa: BLE001
            done["exc"] = e

    t = threading.Thread(target=reader)
    t.start()
    st.abort(boom)
    t.join(timeout=10)
    assert not t.is_alive()
    assert done["exc"] is boom
    with pytest.raises(HangError):
        st.read_range(64, 64)
    st.begin_scan()  # a new scan clears the poison
    with pytest.raises(RetryExhaustedError):
        st.read_range(64, 64)
