"""Training-input loader test family (ISSUE 2).

The loader's whole contract is determinism: the shuffled order is a pure
function of (seed, epoch, cursor) — so prefetch depth must not change it,
shards must partition it, and save→restore must re-enter it bit-identically
at any batch boundary.  Every test here asserts one face of that contract on
a small multi-file, multi-row-group, ragged-tailed dataset.
"""

import numpy as np
import pytest

from tpu_parquet.data import DataLoader, pack_state, unpack_state
from tpu_parquet.data.checkpoint import MAGIC, STATE_VERSION
from tpu_parquet.errors import CheckpointError, ParquetError

BS = 256


@pytest.fixture(scope="module")
def dataset(tmp_path_factory):
    """Two files, ten row groups of uneven sizes, three dtypes, a ragged
    epoch tail (total % BS != 0), plus a string column to project out."""
    from tpu_parquet.column import ByteArrayData, ColumnData
    from tpu_parquet.format import (
        CompressionCodec, FieldRepetitionType as FRT, Type,
    )
    from tpu_parquet.schema.core import build_schema, data_column
    from tpu_parquet.writer import FileWriter

    d = tmp_path_factory.mktemp("loader")
    rng = np.random.default_rng(0)
    schema = build_schema([
        data_column("a", Type.INT64, FRT.REQUIRED),
        data_column("b", Type.DOUBLE, FRT.REQUIRED),
        data_column("c", Type.INT32, FRT.REQUIRED),
        data_column("s", Type.BYTE_ARRAY, FRT.REQUIRED),
    ])
    paths, sizes = [], []
    for fi, groups in enumerate([(900, 1100, 500, 1000, 700),
                                 (1000, 300, 1300, 800, 411)]):
        p = str(d / f"part{fi}.parquet")
        with FileWriter(p, schema, codec=CompressionCodec.SNAPPY) as w:
            for n in groups:
                strs = [b"s%d" % i for i in range(n)]
                w.write_columns({
                    "a": rng.integers(0, 1 << 50, n),
                    "b": rng.uniform(-1, 1, n),
                    "c": rng.integers(0, 1 << 20, n).astype(np.int32),
                    "s": ColumnData(values=ByteArrayData.from_list(strs)),
                })
                w.flush_row_group()
            sizes.extend(groups)
        paths.append(p)
    return paths, sum(sizes)


COLS = ["a", "b", "c"]


def _loader(paths, **kw):
    kw.setdefault("columns", COLS)
    kw.setdefault("seed", 3)
    kw.setdefault("shuffle", True)
    kw.setdefault("shuffle_window", 1000)
    return DataLoader(paths, BS, **kw)


def _assert_batches_equal(got, want):
    assert len(got) == len(want)
    for g, w in zip(got, want):
        assert set(g) == set(w)
        for c in g:
            assert np.array_equal(np.asarray(g[c]), np.asarray(w[c])), c


def _take(loader, n):
    """First n batches of the current epoch, closing the iterator cleanly."""
    it = iter(loader)
    out = []
    for batch in it:
        out.append(batch)
        if len(out) == n:
            it.close()
            break
    return out


# ---------------------------------------------------------------------------
# determinism
# ---------------------------------------------------------------------------

def test_prefetch_depth_never_changes_the_stream(dataset):
    paths, _total = dataset
    runs = {k: list(iter(_loader(paths, prefetch=k))) for k in (0, 1, 4)}
    _assert_batches_equal(runs[1], runs[0])
    _assert_batches_equal(runs[4], runs[0])


def test_same_seed_same_order_fresh_process_objects(dataset):
    paths, _ = dataset
    _assert_batches_equal(list(iter(_loader(paths))),
                          list(iter(_loader(paths))))


def test_seed_and_epoch_reshuffle(dataset):
    paths, total = dataset
    base = np.concatenate([b["a"][b["mask"]] for b in iter(_loader(paths))])
    other_seed = np.concatenate(
        [b["a"][b["mask"]] for b in iter(_loader(paths, seed=4))])
    l2 = _loader(paths)
    list(iter(l2))  # epoch 0
    epoch1 = np.concatenate([b["a"][b["mask"]] for b in iter(l2)])
    assert not np.array_equal(base, other_seed)
    assert not np.array_equal(base, epoch1)
    # same multiset every time: a shuffle, never a resample
    assert np.array_equal(np.sort(base), np.sort(other_seed))
    assert np.array_equal(np.sort(base), np.sort(epoch1))
    assert len(base) == total


def test_unshuffled_order_is_file_order(dataset):
    from tpu_parquet.reader import FileReader

    paths, total = dataset
    got = np.concatenate([
        b["a"][b["mask"]]
        for b in iter(_loader(paths, shuffle=False))
    ])
    want = np.concatenate([
        np.asarray(rg["a"].values)
        for p in paths
        for rg in FileReader(p, columns=["a"]).iter_row_groups()
    ])
    assert np.array_equal(got, want) and len(got) == total


# ---------------------------------------------------------------------------
# sharding
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("n_shards", [1, 2, 8])
def test_shard_union_equals_whole(dataset, n_shards):
    paths, total = dataset
    whole = np.sort(np.concatenate(
        [b["a"][b["mask"]] for b in iter(_loader(paths))]))
    parts = [
        np.concatenate([b["a"][b["mask"]] for b in
                        iter(_loader(paths, shard=(i, n_shards)))]
                       or [np.zeros(0, dtype=np.int64)])
        for i in range(n_shards)
    ]
    got = np.sort(np.concatenate(parts))
    assert len(got) == total == len(whole)
    assert np.array_equal(got, whole)


def test_empty_shard_is_a_clean_noop(dataset):
    paths, _ = dataset
    l = _loader(paths, shard=(15, 16))  # 10 units, 16 shards: someone's empty
    if l.num_rows == 0:
        assert list(iter(l)) == []
        assert l.epoch == 1  # the epoch still advances
    else:  # LPT filled every shard: still a valid partition member
        assert sum(b["mask"].sum() for b in iter(l)) == l.num_rows


# ---------------------------------------------------------------------------
# checkpoint / resume
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("shard", [(0, 1), (1, 2)])
@pytest.mark.parametrize("prefetch", [0, 1, 4])
@pytest.mark.parametrize("cut", [1, 7, 13])
def test_save_restore_bit_identical(dataset, prefetch, cut, shard):
    paths, _ = dataset
    want = list(iter(_loader(paths, prefetch=prefetch, shard=shard)))
    l = _loader(paths, prefetch=0, shard=shard)
    first = _take(l, cut)
    assert len(first) == cut, "fixture too small for this cut point"
    blob = l.state_blob()
    resumed = _loader(paths, prefetch=prefetch, shard=shard).restore(blob)
    rest = list(iter(resumed))
    _assert_batches_equal(first + rest, want)
    assert resumed.epoch == 1


def test_restore_across_epoch_boundary(dataset):
    paths, _ = dataset
    ref = _loader(paths, prefetch=2)
    want = list(ref.epochs(3))
    l = _loader(paths, prefetch=0)
    first = list(iter(l)) + _take(l, 5)  # 1 full epoch + 5 batches of epoch 1
    resumed = _loader(paths, prefetch=4).restore(l.state())
    rest = list(resumed.epochs(2))  # the remainder of epoch 1 + epoch 2
    _assert_batches_equal(first + rest, want)


def test_state_blob_roundtrip(dataset):
    paths, _ = dataset
    l = _loader(paths)
    _take(l, 3)
    st = l.state()
    assert unpack_state(pack_state(st)) == st
    assert st["rows_taken"] == 3 * BS and st["version"] == STATE_VERSION


def test_checkpoint_rejects_garbage(dataset):
    paths, _ = dataset
    l = _loader(paths)
    blob = l.state_blob()
    for bad in (
        b"",                                   # empty
        b"NOPE" + blob[4:],                    # bad magic
        blob[:-10],                            # truncated payload
        MAGIC + (99).to_bytes(2, "big") + blob[6:],  # version bump
        MAGIC + blob[4:6] + b"{not json",      # corrupt payload
    ):
        with pytest.raises(CheckpointError):
            l.restore(bad)
    # structurally valid but wrong pipeline: every fingerprint field refuses
    for key, val in (("batch_size", BS + 1), ("shuffle", False),
                     ("shuffle_window", 999), ("shard", [1, 2]),
                     ("n_units", 11), ("total_rows", 1),
                     ("drop_remainder", True)):
        st = dict(l.state())
        st[key] = val
        if key in ("total_rows",):  # keep shard_rows <= total_rows valid
            st["shard_rows"] = 0
            st["rows_taken"] = 0
        with pytest.raises(CheckpointError):
            l.restore(st)
    # cursor past the shard's rows
    st = dict(l.state())
    st["rows_taken"] = st["shard_rows"] + 1
    with pytest.raises(CheckpointError):
        l.restore(st)
    # cursor off the batch grid: no state() call can produce it, so adopting
    # it would shift every later batch by a fraction of a batch
    st = dict(l.state())
    st["rows_taken"] = BS + 1
    with pytest.raises(CheckpointError):
        l.restore(st)
    # floats where ints belong (json round-trips them as floats)
    st = dict(l.state())
    st["epoch"] = 1.0
    with pytest.raises(CheckpointError):
        l.restore(st)


def test_checkpoint_rejects_reordered_dataset(dataset):
    paths, _ = dataset
    blob = _loader(paths).state_blob()
    # same files, same counts — different order: the dataset digest refuses
    with pytest.raises(CheckpointError, match="dataset_digest"):
        _loader(list(reversed(paths))).restore(blob)


# ---------------------------------------------------------------------------
# batch geometry
# ---------------------------------------------------------------------------

def test_ragged_tail_pads_and_masks(dataset):
    paths, total = dataset
    batches = list(iter(_loader(paths)))
    assert total % BS != 0, "fixture must leave a ragged tail"
    assert len(batches) == -(-total // BS)
    for b in batches[:-1]:
        assert b["mask"].all() and len(b["a"]) == BS
    tail = batches[-1]
    assert tail["mask"].sum() == total % BS
    assert not tail["mask"][total % BS:].any()
    for c in COLS:
        assert len(tail[c]) == BS
        assert (np.asarray(tail[c])[~tail["mask"]] == 0).all()


def test_drop_remainder(dataset):
    paths, total = dataset
    batches = list(iter(_loader(paths, drop_remainder=True)))
    assert len(batches) == total // BS
    assert all("mask" not in b for b in batches)
    assert all(len(b["a"]) == BS for b in batches)


def test_mask_key_collision_and_rename(dataset):
    paths, _ = dataset
    with pytest.raises(ValueError):
        DataLoader(paths, BS, columns=COLS, mask_key="a")
    l = _loader(paths, mask_key="valid")
    b = next(iter(l))
    assert "valid" in b and "mask" not in b


def test_to_device_batches(dataset):
    import jax

    paths, _ = dataset
    host = next(iter(_loader(paths)))
    dev = next(iter(_loader(paths, to_device=True)))
    for c in host:
        assert isinstance(dev[c], jax.Array)
        assert np.array_equal(np.asarray(dev[c]), np.asarray(host[c])), c


# ---------------------------------------------------------------------------
# validation + observability
# ---------------------------------------------------------------------------

def test_column_validation(dataset):
    paths, _ = dataset
    with pytest.raises(TypeError):  # byte-array column has no static shape
        DataLoader(paths, BS, columns=["a", "s"])
    with pytest.raises(TypeError):  # default selection includes "s"
        DataLoader(paths, BS)
    with pytest.raises(ParquetError):
        DataLoader(paths, BS, columns=["nope"])
    with pytest.raises(ValueError):
        DataLoader(paths, 0, columns=COLS)
    with pytest.raises(ValueError):
        DataLoader(paths, BS, columns=COLS, shard=(2, 2))


def test_loader_stats(dataset):
    paths, total = dataset
    l = _loader(paths, prefetch=2)
    list(iter(l))
    st = l.stats()
    assert st.rows == total and st.batches == -(-total // BS)
    assert st.epochs_completed == 1 and st.padded_batches == 1
    d = st.as_dict()
    assert d["rows_per_sec"] > 0 and d["window_peak_rows"] >= 1000
    assert d["pipeline"]["row_groups"] == 10  # one per decoded unit
    assert d["pipeline"]["chunks"] == 30  # 3 selected columns per unit
