"""ISSUE 14: the tiered decoded-result cache — hot scans skip decode.

The contracts under test, in rough order of importance:

- STRUCTURAL hit-path proof: a repeated identical scan with the cache warm
  performs ZERO ``ByteStore.read_range`` calls and ZERO device decode
  dispatches (the registry ``io``/``device`` sections are unchanged
  between hit N and hit N+1), and returns bit-identical arrays vs a cold
  scan — at prefetch {0, 4} x CRC {on, off}, host and device shapes;
- the ScanService hit path serves straight from the cache (no reader, no
  store even constructed) and charges the ACTUAL cached decoded size
  against the admission budget, not the plan's full-decode estimate;
- the HBM tier registers residency on the cache's AllocTracker device
  ledger, is visible in flight-dump tracker snapshots, and evicts under
  device-memory pressure so ``device_peak`` stays bounded;
- a mutated file invalidates with EXACT accounting — never stale bytes;
- builds are single-flight: N concurrent first-touches decode once;
- the PR 10 dict seam is folded in: dictionaries live in the SAME LRU
  under the same byte budget, and PlanCache's dict counters still work.
"""

import io
import json
import os
import threading

import numpy as np
import pytest

from tpu_parquet.column import ByteArrayData, ColumnData
from tpu_parquet.device_reader import DeviceFileReader, scan_files
from tpu_parquet.format import CompressionCodec, FieldRepetitionType as FRT, Type
from tpu_parquet.iostore import FaultInjectingStore, LocalStore
from tpu_parquet.reader import FileReader
from tpu_parquet.schema.core import build_schema, data_column
from tpu_parquet.serve import (PlanCache, ResultCache, ScanRequest,
                               ScanService)
from tpu_parquet.serve.result_cache import column_nbytes
from tpu_parquet.writer import FileWriter


def _strings(vals):
    return ColumnData(values=ByteArrayData(
        offsets=np.cumsum([0] + [len(v) for v in vals]),
        heap=np.frombuffer(b"".join(vals), np.uint8).copy(),
    ))


def _write_file(path, seed=0, groups=2, rows=400):
    rng = np.random.default_rng(seed)
    schema = build_schema([
        data_column("a", Type.INT64, FRT.REQUIRED),
        data_column("s", Type.BYTE_ARRAY, FRT.REQUIRED),
    ])
    pool = [b"alpha", b"beta", b"gamma", b"delta", b""]
    with open(path, "wb") as fh:
        with FileWriter(fh, schema, codec=CompressionCodec.SNAPPY) as w:
            for _g in range(groups):
                svals = [pool[i] for i in rng.integers(0, len(pool), rows)]
                w.write_columns({
                    "a": rng.integers(-(1 << 40), 1 << 40, rows),
                    "s": _strings(svals),
                })
                w.flush_row_group()
    return path


@pytest.fixture(scope="module")
def afile(tmp_path_factory):
    d = tmp_path_factory.mktemp("result_cache")
    return _write_file(str(d / "f.parquet"))


def _warm_cache():
    return PlanCache(result_cache_mb=64, result_cache_hbm_mb=64)


def _counting_factory(stores):
    def factory(f):
        st = FaultInjectingStore(LocalStore(f))
        stores.append(st)
        return st
    return factory


def _assert_cols_equal(got, want):
    assert set(got) == set(want)
    for name in want:
        g, w = got[name], want[name]
        if isinstance(w.values, ByteArrayData):
            np.testing.assert_array_equal(g.values.offsets, w.values.offsets)
            np.testing.assert_array_equal(g.values.heap, w.values.heap)
        else:
            np.testing.assert_array_equal(g.values, w.values)


# ---------------------------------------------------------------------------
# the structural acceptance: warm scan = zero reads, zero dispatches,
# bit-identical, at prefetch {0,4} x CRC {on,off}
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("prefetch", [0, 4])
@pytest.mark.parametrize("crc", [True, False])
def test_warm_host_scan_zero_reads_bit_identical(afile, prefetch, crc):
    cache = _warm_cache()
    stores = []
    factory = _counting_factory(stores)

    def scan():
        kw = cache.reader_kwargs(afile, device=False, validate_crc=crc)
        assert "result_cache" in kw
        with FileReader(afile, prefetch=prefetch, validate_crc=crc,
                        store=factory, **kw) as r:
            out = r.read_all()
            reg = r.obs_registry().as_dict()
        return out, reg, stores[-1].stats.reads

    cold, _reg0, cold_reads = scan()
    assert cold_reads > 0  # the cold scan actually read bytes
    warm1, reg1, reads1 = scan()
    warm2, reg2, reads2 = scan()
    # ZERO store reads on the warm path, both times
    assert reads1 == 0 and reads2 == 0
    # the registry io section is unchanged between hit N and hit N+1
    assert reg1["io"] == reg2["io"]
    assert reg1["io"]["reads"] == 0
    _assert_cols_equal(warm1, cold)
    _assert_cols_equal(warm2, cold)


def _dispatches(reg):
    dev = reg.get("device") or {}
    return sum(int(c.get("dispatches", 0))
               for c in (dev.get("routes") or {}).values())


@pytest.mark.parametrize("prefetch", [0, 4])
@pytest.mark.parametrize("crc", [True, False])
def test_warm_device_scan_zero_reads_zero_dispatches(afile, prefetch, crc):
    cache = _warm_cache()
    stores = []
    factory = _counting_factory(stores)

    def scan():
        kw = cache.reader_kwargs(afile, device=True, validate_crc=crc)
        assert "result_cache" in kw
        with DeviceFileReader(afile, prefetch=prefetch, validate_crc=crc,
                              store=factory, **kw) as r:
            out = [{k: np.asarray(v.to_host()) for k, v in g.items()}
                   for g in r.iter_row_groups()]
            reg = r.obs_registry().as_dict()
        return out, reg, stores[-1].stats.reads

    cold, reg0, cold_reads = scan()
    assert cold_reads > 0
    assert _dispatches(reg0) > 0  # the cold scan dispatched device work
    warm1, reg1, reads1 = scan()
    warm2, reg2, reads2 = scan()
    # ZERO reads and ZERO new device decode dispatches on the warm path
    assert reads1 == 0 and reads2 == 0
    assert _dispatches(reg1) == 0 and _dispatches(reg2) == 0
    # io and device registry sections unchanged between hit N and hit N+1
    assert reg1["io"] == reg2["io"]
    assert reg1["device"] == reg2["device"]
    assert len(warm1) == len(cold) == 2
    for g1, g2, g3 in zip(cold, warm1, warm2):
        for k in g1:
            np.testing.assert_array_equal(g1[k], g2[k])
            np.testing.assert_array_equal(g1[k], g3[k])


def test_scan_files_plan_cache_second_sweep_reads_nothing(tmp_path):
    files = [_write_file(str(tmp_path / f"z{i}.parquet"), seed=i)
             for i in range(3)]
    cache = _warm_cache()
    stores = []
    factory = _counting_factory(stores)

    def sweep():
        stores.clear()
        out = []
        for cols in scan_files(files, columns=["a"], plan_cache=cache,
                               store=factory):
            out.append(np.asarray(cols["a"].to_host()))
        return np.concatenate(out), sum(st.stats.reads for st in stores)

    first, reads1 = sweep()
    second, reads2 = sweep()
    assert reads1 > 0 and reads2 == 0  # the whole second sweep read NOTHING
    np.testing.assert_array_equal(first, second)
    c = cache.results.counters()["device"]
    assert c["hits"] >= 6  # 3 files x 2 row groups x 1 column


# ---------------------------------------------------------------------------
# ScanService: the hit path and the admission-charge satellite
# ---------------------------------------------------------------------------

def test_service_hit_path_constructs_no_store(afile):
    stores = []
    factory = _counting_factory(stores)
    with ScanService(concurrency=2, store=factory,
                     result_cache_mb=64) as svc:
        cold = svc.scan(ScanRequest(afile))[afile]
        n_after_cold = len(stores)
        warm = svc.scan(ScanRequest(afile))[afile]
        # the hit path never opened a reader — so no store was constructed
        assert len(stores) == n_after_cold
        _assert_cols_equal(warm, cold)
        c = svc.cache.results.counters()["host"]
        assert c["hits"] >= 4  # 2 row groups x 2 columns served from cache


def test_service_hit_path_charges_actual_cached_bytes(afile):
    cache = _warm_cache()
    with ScanService(concurrency=1, cache=cache) as svc:
        svc.scan(ScanRequest(afile))  # populate
    key = cache.file_key(afile)
    plan = cache.plan(key, None, None)
    units = [ResultCache.chunk_key(key, rg, c, ("host", "v1"))
             for rg in plan.selected_ordinals() for c in plan.columns]
    got = cache.results.lookup_units(units)
    assert got is not None
    actual = sum(n for _v, n in got)
    estimate = plan.estimated_bytes()
    assert actual != estimate  # the two charges are distinguishable here
    with ScanService(concurrency=1, cache=cache,
                     max_memory=1 << 30) as svc2:
        out = svc2.scan(ScanRequest(afile))[afile]
        assert out["a"].num_leaf_slots > 0
        # the satellite fix: the hit path charged the ACTUAL cached size,
        # not plan.estimated_bytes() — hot traffic never queues behind a
        # phantom full-decode charge
        assert svc2._budget.peak == actual


def test_service_without_result_cache_unchanged(afile):
    # TPQ_RESULT_CACHE_MB unset: the tier is off, requests run readers
    with ScanService(concurrency=1) as svc:
        assert not svc.cache.results.chunks_enabled
        a = svc.scan(ScanRequest(afile))[afile]
        b = svc.scan(ScanRequest(afile))[afile]
        _assert_cols_equal(a, b)
        c = svc.cache.results.counters()["host"]
        assert c["entries"] >= 0  # dictionaries may live there; chunks not
        assert all(k[0] != "chunk" for k in svc.cache.results._entries)


# ---------------------------------------------------------------------------
# HBM tier: residency ledger + eviction under device pressure
# ---------------------------------------------------------------------------

def test_hbm_tier_residency_and_eviction_bound(tmp_path):
    path = _write_file(str(tmp_path / "big.parquet"), seed=3, groups=6,
                       rows=600)
    # an HBM budget that fits any single column but far below the file's
    # decoded size: the device tier must evict under pressure (columns
    # larger than the whole cap would be REJECTED instead — a different
    # code path) and its peak must stay bounded
    cap = 24 << 10
    cache = PlanCache(results=ResultCache(max_bytes=1 << 20, hbm_bytes=cap,
                                          chunks_enabled=True))
    kw = cache.reader_kwargs(path, device=True)
    with DeviceFileReader(path, **kw) as r:
        for _ in r.iter_row_groups():
            pass
    rc = cache.results
    c = rc.counters()["device"]
    in_use, peak = rc.tracker.device_snapshot()
    assert c["evictions"] > 0  # pressure actually evicted
    assert in_use == c["held_bytes"] <= cap
    assert peak <= cap  # the bound held at EVERY instant, not just now
    # residency is visible to flight dumps via the live tracker registry
    from tpu_parquet.alloc import tracker_snapshots

    assert any(t["device_in_use"] == in_use and t["device_peak"] == peak
               for t in tracker_snapshots())


def test_warm_response_column_order_matches_cold(tmp_path):
    """Cache temperature must never transpose a response's column order:
    the warm assembly follows the footer chunk order the readers fill in,
    not plan.columns' sorted order."""
    path = str(tmp_path / "order.parquet")
    schema = build_schema([
        data_column("zz", Type.INT64, FRT.REQUIRED),
        data_column("aa", Type.INT64, FRT.REQUIRED),
    ])
    rng = np.random.default_rng(2)
    with open(path, "wb") as fh:
        with FileWriter(fh, schema, codec=CompressionCodec.SNAPPY) as w:
            w.write_columns({"zz": rng.integers(0, 9, 100),
                             "aa": rng.integers(0, 9, 100)})
    with ScanService(concurrency=1, result_cache_mb=64) as svc:
        cold = svc.scan(ScanRequest(path))[path]
        warm = svc.scan(ScanRequest(path))[path]
    assert list(cold) == ["zz", "aa"]  # footer order, not sorted
    assert list(warm) == list(cold)
    _assert_cols_equal(warm, cold)


def test_device_pending_publish_bounded_by_tier_capacity(tmp_path):
    """The publish-at-finalize ledger must not pin every decoded group
    until the end of the scan: pending residency stays within the device
    tier's capacity (oldest pending groups are dropped unpublished)."""
    path = _write_file(str(tmp_path / "big2.parquet"), seed=8, groups=6,
                       rows=600)
    cap = 8 << 10
    cache = PlanCache(results=ResultCache(max_bytes=1 << 20, hbm_bytes=cap,
                                          chunks_enabled=True))
    kw = cache.reader_kwargs(path, device=True)
    with DeviceFileReader(path, **kw) as r:
        peak_pending = 0
        for _ in r.iter_row_groups():
            peak_pending = max(peak_pending, r._rc_pending_bytes)
        # within 2x the tier cap (the documented pinning bound), modulo
        # the newest group (never dropped)
        assert peak_pending <= 2 * cap + (8 << 10)
        assert len(r._rc_pending) < 6  # old groups were dropped, not kept


def test_oversized_entry_rejected_not_admitted():
    rc = ResultCache(max_bytes=64, hbm_bytes=0, chunks_enabled=True)
    full = ResultCache.chunk_key(("file", "/x", 1, 1), 0, "a", ("host", "v0"))
    assert not rc.put(full, b"x" * 100, 100, "host")
    c = rc.counters()["host"]
    assert c["rejected"] == 1 and c["entries"] == 0 and c["held_bytes"] == 0


# ---------------------------------------------------------------------------
# invalidation: a mutated file can never serve stale decoded bytes
# ---------------------------------------------------------------------------

def test_mutation_invalidates_exactly_never_stale(tmp_path):
    path = _write_file(str(tmp_path / "mut.parquet"), seed=5)
    cache = _warm_cache()
    with ScanService(concurrency=1, cache=cache) as svc:
        first = svc.scan(ScanRequest(path))[path]
        svc.scan(ScanRequest(path))  # provably warm
        entries_before = cache.results.counters()["host"]["entries"]
        inv0 = cache.results.counters()["host"]["invalidations"]
        assert entries_before > 0
        _write_file(path, seed=6)  # new bytes, same shape
        st = os.stat(path)
        os.utime(path, ns=(st.st_atime_ns, st.st_mtime_ns + 1_000_000))
        after = svc.scan(ScanRequest(path))[path]
        inv1 = cache.results.counters()["host"]["invalidations"]
    # exact accounting: EVERY entry of the old generation was invalidated
    assert inv1 - inv0 == entries_before
    # and the served bytes are the new file's, never stale
    assert not np.array_equal(first["a"].values, after["a"].values)
    with FileReader(path) as r:
        fresh = r.read_all()
    _assert_cols_equal(after, fresh)


# ---------------------------------------------------------------------------
# single-flight: one decode populates all concurrent waiters
# ---------------------------------------------------------------------------

def test_single_flight_builds_once_for_concurrent_waiters():
    rc = ResultCache(max_bytes=1 << 20, hbm_bytes=0, chunks_enabled=True)
    full = ResultCache.chunk_key(("file", "/x", 1, 1), 0, "a", ("host", "v0"))
    builds = []
    gate = threading.Event()
    started = threading.Event()

    def build():
        builds.append(threading.get_ident())
        started.set()
        gate.wait(5)
        return b"value", 5

    results, errors = [], []

    def worker():
        try:
            results.append(rc.get_or_build(full, build, "host"))
        except BaseException as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=worker) for _ in range(6)]
    threads[0].start()
    started.wait(5)  # the first builder is inside build()
    for t in threads[1:]:
        t.start()
    import time

    time.sleep(0.1)  # let the waiters queue up on the build lock
    gate.set()
    for t in threads:
        t.join(10)
    assert not errors, errors
    assert len(builds) == 1  # ONE decode populated all six callers
    assert all(v == b"value" for v in results)
    assert rc.single_flight_waits >= 1
    c = rc.counters()["host"]
    assert c["misses"] == 1 and c["hits"] == 5


def test_single_flight_failed_build_not_published():
    rc = ResultCache(max_bytes=1 << 20, hbm_bytes=0, chunks_enabled=True)
    full = ResultCache.chunk_key(("file", "/x", 1, 1), 0, "a", ("host", "v0"))

    def bad():
        raise ValueError("decode failed")

    with pytest.raises(ValueError):
        rc.get_or_build(full, bad, "host")
    assert rc.get(full) is None  # a failed decode is never servable
    assert rc.get_or_build(full, lambda: (b"ok", 2), "host") == b"ok"


# ---------------------------------------------------------------------------
# the dict-cache fold: one LRU, one byte budget
# ---------------------------------------------------------------------------

def test_dictionaries_fold_into_result_cache_lru(afile):
    cache = PlanCache()  # result tier off: the dict store still works
    kw = cache.reader_kwargs(afile, device=False)
    with FileReader(afile, **kw) as r:
        r.read_all()
    with FileReader(afile, **cache.reader_kwargs(afile, device=False)) as r:
        r.read_all()
    c = cache.counters()
    assert c["dict_hits"] > 0  # the PR 10 seam still serves
    # the decoded dictionaries live in the RESULT cache's LRU (one LRU,
    # one byte budget) — not in the plan cache's entry map
    rcounters = cache.results.counters()["host"]
    assert rcounters["entries"] > 0 and rcounters["held_bytes"] > 0
    assert all(k[0] in ("footer", "plan") for k in cache._entries)
    assert all(k[0] == "dict" for k in cache.results._entries)
    # ...and the dict store is bounded by the plan cache's budget when the
    # result tier is unsized
    assert (cache.results.tier_capacity("host") == cache.max_bytes)


def test_dict_fallback_shares_plan_budget():
    """With the result tier unsized, dictionary bytes ride the plan
    cache's ONE budget: the same footer load that fits an empty cache
    evicts once dictionaries hold most of it."""
    k1, k2 = ("file", "/x", 1, 1), ("file", "/y", 1, 1)
    lean = PlanCache(max_bytes=1000)
    lean._put("footer", (k1,), "f1", 300)
    lean._put("footer", (k2,), "f2", 300)
    assert lean.counters()["evictions"] == 0  # 600B fits the 1000B budget
    full = PlanCache(max_bytes=1000)
    assert full.results.dict_fallback_active
    full.dict_put(k1, 0, "a", "host:v0", b"d", 900)
    full._put("footer", (k1,), "f1", 300)
    full._put("footer", (k2,), "f2", 300)
    assert full.counters()["evictions"] >= 1  # displaced by dict bytes
    # a sized result tier detaches the dictionary store from this budget
    assert not _warm_cache().results.dict_fallback_active


# ---------------------------------------------------------------------------
# obs: doctor verdict + serve-stats CLI
# ---------------------------------------------------------------------------

def _thrash_tree():
    return {
        "obs_version": 1,
        "pipeline": {"stage_seconds": 0.2, "io_seconds": 0.1},
        "reader": {},
        "cache": {
            "single_flight_waits": 0,
            "host": {"hits": 3, "misses": 17, "evictions": 40,
                     "invalidations": 0, "rejected": 0,
                     "held_bytes": 900, "capacity_bytes": 1024,
                     "entries": 4, "budget_knob": "TPQ_PLAN_CACHE_MB",
                     "evict_files": {"/data/hot.parquet": 25,
                                     "/data/cold.parquet": 15}},
            "device": {"hits": 0, "misses": 0, "evictions": 0,
                       "invalidations": 0, "rejected": 0, "held_bytes": 0,
                       "capacity_bytes": 0, "entries": 0,
                       "evict_files": {}},
        },
    }


def test_doctor_cache_thrash_verdict(tmp_path):
    from tpu_parquet.obs import doctor_registry

    rep = doctor_registry(_thrash_tree())
    ct = rep["cache"]
    assert ct["verdict"] == "cache-thrash"
    assert ct["tier"] == "host"
    assert ct["top_evict_file"] == "/data/hot.parquet"
    assert ct["top_evict_count"] == 25
    assert ct["evictions"] == 40
    # merged registries ADD the per-file eviction counts, so the ranking
    # stays truthful across snapshots (a scalar top-file pair could not)
    from tpu_parquet.obs import StatsRegistry

    merged = StatsRegistry()
    merged.merge_dict(_thrash_tree())
    merged.merge_dict(_thrash_tree())
    mt = merged.as_dict()["cache"]["host"]["evict_files"]
    assert mt == {"/data/hot.parquet": 50, "/data/cold.parquet": 30}
    # a healthy cache (high hit rate) never trips the verdict
    healthy = _thrash_tree()
    healthy["cache"]["host"].update(hits=90, misses=10)
    assert "cache" not in doctor_registry(healthy)
    # CLI renders the verdict and names the knob
    path = str(tmp_path / "reg.json")
    with open(path, "w") as f:
        json.dump(_thrash_tree(), f)
    from tpu_parquet.cli import pq_tool

    buf = io.StringIO()
    rc = pq_tool.cmd_doctor(
        type("A", (), {"file": path, "config": None})(), out=buf)
    out = buf.getvalue()
    assert rc == 0
    assert "cache-thrash" in out and "hot.parquet" in out
    # the advisory names the knob that GOVERNS the tier (the fixture is a
    # dict-fallback host tier riding the plan cache's budget)
    assert "TPQ_PLAN_CACHE_MB" in out


def test_serve_stats_cli_result_cache_lines(afile, tmp_path):
    with ScanService(concurrency=2, result_cache_mb=64) as svc:
        for _ in range(3):
            svc.scan(ScanRequest(afile))
        tree = svc.obs_registry().as_dict()
    path = str(tmp_path / "reg.json")
    with open(path, "w") as f:
        json.dump(tree, f)
    from tpu_parquet.cli import pq_tool

    buf = io.StringIO()
    rc = pq_tool.cmd_serve_stats(
        type("A", (), {"file": path, "config": None})(), out=buf)
    out = buf.getvalue()
    assert rc == 0
    assert "result cache [host]" in out
    assert "cache hits" in out  # the plan-cache line survives unchanged


# ---------------------------------------------------------------------------
# decode-signature discipline
# ---------------------------------------------------------------------------

def test_crc_tiers_never_share_entries(afile):
    cache = _warm_cache()
    kw = cache.reader_kwargs(afile, device=False, validate_crc=True)
    with FileReader(afile, validate_crc=True, **kw) as r:
        r.read_all()
    hits_before = cache.results.counters()["host"]["hits"]
    # a validate_crc=False scan has a different signature: it must MISS
    kw2 = cache.reader_kwargs(afile, device=False, validate_crc=False)
    with FileReader(afile, validate_crc=False, **kw2) as r:
        r.read_all()
    c = cache.results.counters()["host"]
    assert c["hits"] == hits_before  # no cross-tier adoption
    assert kw["result_cache"].sig != kw2["result_cache"].sig


def test_host_and_device_shapes_never_share_entries(afile):
    cache = _warm_cache()
    with FileReader(afile, **cache.reader_kwargs(afile, device=False)) as r:
        host = r.read_all()
    kw = cache.reader_kwargs(afile, device=True)
    with DeviceFileReader(afile, **kw) as r:
        groups = list(r.iter_row_groups())
    # both shapes decoded fresh (host hits 0 crossover), both correct
    got = np.concatenate([np.asarray(g["a"].to_host()) for g in groups])
    np.testing.assert_array_equal(got, host["a"].values)
    sigs = {k[4][0] for k in cache.results._entries if k[0] == "chunk"}
    assert sigs == {"host", "dev"}


def test_mismatched_adapter_tier_dropped_not_adopted(afile):
    """A device-signed adapter handed to a host FileReader (or vice
    versa) is DROPPED, never adopted: publishing host ColumnData under a
    device signature would serve host arrays to a later device reader."""
    cache = _warm_cache()
    dev_kw = cache.reader_kwargs(afile, device=True)
    host_kw = dict(dev_kw)  # the wrong-shape hand-off
    with FileReader(afile, **host_kw) as r:
        assert r._result_cache is None  # dropped at the door
        host = r.read_all()
    # nothing was published under the device signature by the host read
    assert all(k[4][0] != "dev" for k in cache.results._entries
               if k[0] == "chunk")
    # and the device reader now decodes fresh, correct device arrays
    with DeviceFileReader(afile, **cache.reader_kwargs(afile,
                                                       device=True)) as r:
        groups = list(r.iter_row_groups())
    got = np.concatenate([np.asarray(g["a"].to_host()) for g in groups])
    np.testing.assert_array_equal(got, host["a"].values)
    # symmetric: a host-signed adapter is dropped by the device reader
    with DeviceFileReader(afile, **cache.reader_kwargs(afile,
                                                       device=False)) as r:
        assert r._result_cache is None


def test_crc_or_fingerprint_mismatched_adapter_dropped(afile):
    """Adoption validates the WHOLE signature, not just the tier: a
    v0-signed adapter handed to a validate_crc=True reader (or a
    device adapter signed for a different predicate fingerprint) is
    dropped — never a vector for serving unvalidated or wrongly-pruned
    decodes."""
    cache = _warm_cache()
    kw = cache.reader_kwargs(afile, device=False, validate_crc=False)
    with FileReader(afile, validate_crc=True, **kw) as r:
        assert r._result_cache is None
    kwd = cache.reader_kwargs(afile, device=True, validate_crc=False)
    with DeviceFileReader(afile, validate_crc=True, **kwd) as r:
        assert r._result_cache is None
    # a filter-fingerprint mismatch on the device shape is dropped too
    kwf = cache.reader_kwargs(afile, device=True, row_filter=None)
    kwf.pop("plan")  # the plan is filter-scoped; let the reader rebuild
    from tpu_parquet.predicate import col

    with DeviceFileReader(afile, row_filter=col("a") > 0, **kwf) as r:
        assert r._result_cache is None
    # and the matching hand-off is adopted
    ok = cache.reader_kwargs(afile, device=True, validate_crc=True)
    with DeviceFileReader(afile, validate_crc=True, **ok) as r:
        assert r._result_cache is not None


def test_stale_generation_publisher_rejected():
    """A scan still bound to a pre-mutation generation must not roll the
    generation map back: its put is rejected, the fresh warm set stays
    intact, and its own stale bytes never become servable."""
    rc = ResultCache(max_bytes=1 << 20, hbm_bytes=0, chunks_enabled=True)
    g1 = ("file", "/x", 100, 1000)
    g2 = ("file", "/x", 120, 2000)  # newer mtime: the real current file
    old_key = ResultCache.chunk_key(g1, 0, "a", ("host", "v1"))
    rc.put(old_key, b"old", 3, "host")
    rc.note_generation(g2)  # the footer observed the mutation
    new_key = ResultCache.chunk_key(g2, 0, "a", ("host", "v1"))
    assert rc.put(new_key, b"new", 3, "host")
    # the straggler publishes under g1: rejected, nothing wiped
    assert not rc.put(old_key, b"stale", 5, "host")
    assert rc.get(new_key) == b"new"
    assert rc.get(old_key) is None
    c = rc.counters()["host"]
    assert c["rejected"] >= 1 and c["entries"] == 1
    # a genuinely newer generation still supersedes via put alone
    g3 = ("file", "/x", 130, 3000)
    assert rc.put(ResultCache.chunk_key(g3, 0, "a", ("host", "v1")),
                  b"v3", 2, "host")
    assert rc.get(new_key) is None  # g2 invalidated by g3


def test_straggling_footer_observation_does_not_wipe():
    """A footer build that STARTED before a mutation and completes after
    the new generation is warm (its generation is older by mtime) must
    not roll the generation map back and wipe the fresh working set."""
    rc = ResultCache(max_bytes=1 << 20, hbm_bytes=0, chunks_enabled=True)
    g1 = ("file", "/x", 100, 1000)
    g2 = ("file", "/x", 120, 2000)
    rc.note_generation(g2)
    k2 = ResultCache.chunk_key(g2, 0, "a", ("host", "v1"))
    assert rc.put(k2, b"new", 3, "host")
    rc.note_generation(g1)  # the straggler's observation: adopts nothing
    assert rc.get(k2) == b"new"
    assert rc.counters()["host"]["invalidations"] == 0


def test_device_cold_misses_counted_at_prefetch(afile):
    """The prefetch feed's skip probe is a cold group's ONLY lookup: it
    must count the misses, or a churning device tier reads ~100% hit."""
    cache = _warm_cache()
    kw = cache.reader_kwargs(afile, device=True)
    with DeviceFileReader(afile, prefetch=4, **kw) as r:
        for _ in r.iter_row_groups():
            pass
    c = cache.results.counters()["device"]
    assert c["misses"] >= 4  # 2 row groups x 2 columns, all cold


def test_column_nbytes_accounting(afile):
    with FileReader(afile) as r:
        out = r.read_all()
    n = column_nbytes(out["s"])
    assert n == (out["s"].values.offsets.nbytes
                 + out["s"].values.heap.nbytes)
    assert column_nbytes(out["a"]) == out["a"].values.nbytes
