"""Black-box flight recorder / hang watchdog / autopsy tests (ISSUE 6).

Covers the crash/hang half of the observability layer end to end: the
always-on per-thread event ring (bounds, tee from disabled tracers, <3%
overhead on the tier-1 guard pattern), ``InFlightBudget`` waiter
instrumentation and watchdog abort, the forced-wedge acceptance path
(zero-headroom budget -> watchdog dump within ``hang_s`` -> ``pq_tool
autopsy`` golden budget-wait verdict), the ``TPQ_DUMP_SIGNAL`` subprocess
round-trip, worker-crash ring/dump triggers, the autopsy rule table on
golden dumps, watchdog/sampler shared-cadence hygiene (surviving a tracer
closed underneath them), thread-leak checks on every reader/loader close
path, and the doctor/trace ledger-ref satellites.
"""

import io
import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from tpu_parquet import ledger
from tpu_parquet.alloc import InFlightBudget
from tpu_parquet.errors import HangError
from tpu_parquet.obs import (
    FLIGHT_VERSION, OBS_VERSION, FlightRecorder, Sampler, Tracer, Watchdog,
    autopsy_dump, flight_dump_path, flight_recorder, note_worker_crash,
    resolve_hang_s,
)
from tpu_parquet.pipeline import PipelineStats, prefetch_map

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _obs_threads():
    return [t.name for t in threading.enumerate()
            if t.name.startswith(("tpq-sampler", "tpq-watchdog"))]


def _write_ints(path, rows=6000, groups=3, seed=0):
    from tpu_parquet.format import FieldRepetitionType as FRT, Type
    from tpu_parquet.schema.core import build_schema, data_column
    from tpu_parquet.writer import FileWriter

    rng = np.random.default_rng(seed)
    schema = build_schema([data_column("v", Type.INT64, FRT.REQUIRED)])
    per = rows // groups
    with FileWriter(path, schema, row_group_size=1) as w:
        for _ in range(groups):
            w.write_columns({"v": rng.integers(0, 1 << 40, per)})
            w.flush_row_group()
    return path


# ---------------------------------------------------------------------------
# flight recorder: ring semantics + snapshot schema
# ---------------------------------------------------------------------------

def test_ring_bounds_per_thread_and_snapshot_keys():
    rec = FlightRecorder(capacity=4)
    for i in range(20):
        rec.record("X", f"ev{i}", float(i), 0.001, {"n": i})
    snap = rec.snapshot(reason="explicit")
    # versioned document with the golden top-level keys (the autopsy and
    # the driver key on them)
    assert snap["flight_version"] == FLIGHT_VERSION
    assert snap["obs_version"] == OBS_VERSION
    for key in ("reason", "ts", "pid", "ring_capacity", "threads",
                "budgets", "trackers", "samples", "registry", "watchdog",
                "error"):
        assert key in snap, key
    me = snap["threads"][str(threading.get_ident())]
    # bounded: only the LAST capacity events survive, newest last
    assert [e["name"] for e in me["events"]] == ["ev16", "ev17", "ev18",
                                                 "ev19"]
    assert me["last_event"]["name"] == "ev19"
    assert me["alive"] and me["stack"]  # this thread's stack is captured
    json.dumps(snap)  # dump-ready

    # a second thread gets its OWN ring: a chatty main thread can never
    # evict the stalled worker's history
    def worker():
        rec.record("X", "worker_ev", 1.0, 0.0, None)

    t = threading.Thread(target=worker, name="ring-worker")
    t.start()
    t.join()
    for _ in range(50):
        rec.record("i", "chatty", 2.0)
    snap = rec.snapshot()
    names = {v["name"]: v for v in snap["threads"].values()}
    assert [e["name"] for e in names["ring-worker"]["events"]] == [
        "worker_ev"]
    assert not names["ring-worker"]["alive"]


def test_ring_capacity_env_and_disabled(monkeypatch):
    monkeypatch.setenv("TPQ_RING_EVENTS", "7")
    assert FlightRecorder().capacity == 7
    rec = FlightRecorder(capacity=0)
    assert not rec.enabled
    rec.record("X", "x", 0.0)
    assert rec.snapshot()["ring_capacity"] == 0
    monkeypatch.setenv("TPQ_RING_EVENTS", "junk")
    assert FlightRecorder().capacity == 256  # invalid env -> default
    monkeypatch.delenv("TPQ_FLIGHT", raising=False)
    assert flight_dump_path() == f"tpq_flight.{os.getpid()}.json"
    monkeypatch.setenv("TPQ_FLIGHT", "/tmp/custom.json")
    assert flight_dump_path() == "/tmp/custom.json"


def test_disabled_tracer_tees_spans_into_ring():
    """The always-on contract: with no TPQ_TRACE, the disabled tracer's
    complete/instant calls still land in the flight ring — the last N
    events per thread survive in memory for a post-mortem."""
    rec = FlightRecorder(capacity=16)
    tr = Tracer(enabled=False, ring=rec)
    assert tr.active and not tr.enabled
    ps = PipelineStats(tracer=tr)
    with ps.timed("io", rg=3):
        pass
    with tr.span("chunk"):
        pass
    tr.instant("ship", route="plain")
    assert tr.events() == []  # no trace events: the ring is the only record
    snap = rec.snapshot()
    evs = [e for t in snap["threads"].values() for e in t["events"]]
    by_name = {e["name"]: e for e in evs}
    assert {"io", "chunk", "ship"} <= set(by_name)
    assert by_name["io"]["args"] == {"rg": 3}
    assert by_name["io"]["ph"] == "X" and by_name["ship"]["ph"] == "i"


def test_always_on_recorder_overhead_under_3_percent():
    """The acceptance criterion's overhead guard, on the existing tier-1
    pattern (paired adjacent differences over interleaved reps): the hot
    loop with a ring-teeing DISABLED tracer vs the identical loop with no
    obs calls must differ by <3%."""
    import gc

    gc.collect()
    gc.disable()
    rec = FlightRecorder(capacity=256)
    tr = Tracer(enabled=False, ring=rec)
    ps_obs = PipelineStats(tracer=tr)
    ps_base = PipelineStats(tracer=Tracer(enabled=False, ring=None))
    rng = np.random.default_rng(0)
    data = rng.integers(0, 1 << 40, 300_000)

    def work():
        return np.sort(data).sum()

    def once(with_ring):
        t0 = time.perf_counter()
        if with_ring:
            with tr.span("chunk", rg=0):
                with ps_obs.timed("decompress"):
                    work()
            tr.instant("ship", route="plain")
        else:
            with ps_base.timed("decompress"):
                work()
        return time.perf_counter() - t0

    try:
        for _ in range(3):
            once(True), once(False)
        base, obs = [], []
        for _ in range(80):
            obs.append(once(True))
            base.append(once(False))
    finally:
        gc.enable()
    diffs = sorted(o - b for o, b in zip(obs, base))
    med_diff = diffs[len(diffs) // 2]
    med_base = sorted(base)[len(base) // 2]
    overhead = med_diff / med_base
    assert overhead < 0.03, f"always-on recorder overhead {overhead:.2%}"
    # absolute backstop: one ring-teed span + instant well under 10 us
    n = 10_000
    t0 = time.perf_counter()
    for _ in range(n):
        with tr.span("chunk"):
            pass
        tr.instant("ship")
    per_call = (time.perf_counter() - t0) / n
    assert per_call < 10e-6, f"ring span+instant {per_call * 1e6:.2f} us"


# ---------------------------------------------------------------------------
# budget waiter instrumentation + abort (satellite)
# ---------------------------------------------------------------------------

def test_budget_snapshot_waiters_and_longest_wait():
    b = InFlightBudget(10)
    b.acquire(10)
    snap = b.snapshot()
    assert snap == {"held": 10, "peak": 10, "max_bytes": 10, "waiters": 0,
                    "longest_wait_s": 0.0}
    started = threading.Event()
    done = threading.Event()

    def waiter():
        started.set()
        b.acquire(5)  # blocks until the release below
        done.set()

    t = threading.Thread(target=waiter)
    t.start()
    started.wait(5)
    deadline = time.monotonic() + 5
    while b.snapshot()["waiters"] == 0 and time.monotonic() < deadline:
        time.sleep(0.005)
    time.sleep(0.05)
    snap = b.snapshot()
    assert snap["waiters"] == 1
    assert snap["longest_wait_s"] >= 0.04  # the age GROWS while blocked
    b.release(10)
    assert done.wait(5)
    t.join()
    assert b.snapshot()["waiters"] == 0  # the waiter entry is cleaned up


def test_budget_abort_wakes_waiter_with_the_exception():
    b = InFlightBudget(1)
    b.acquire(1)
    caught = {}

    def waiter():
        try:
            b.acquire(1)
        except HangError as e:
            caught["e"] = e

    t = threading.Thread(target=waiter)
    t.start()
    deadline = time.monotonic() + 5
    while b.snapshot()["waiters"] == 0 and time.monotonic() < deadline:
        time.sleep(0.005)
    err = HangError("wedged", dump_path="/tmp/d.json")
    b.abort(err)
    t.join(timeout=5)
    assert not t.is_alive()
    assert caught["e"] is err
    # poisoned for future blocking acquires too (the pipeline is dead)
    with pytest.raises(HangError):
        b.acquire(1)


# ---------------------------------------------------------------------------
# watchdog lifecycle + the forced-wedge acceptance path
# ---------------------------------------------------------------------------

def test_resolve_hang_s_forms(monkeypatch):
    monkeypatch.delenv("TPQ_HANG_S", raising=False)
    assert resolve_hang_s() == 0.0
    assert resolve_hang_s(2.5) == 2.5
    monkeypatch.setenv("TPQ_HANG_S", "7")
    assert resolve_hang_s() == 7.0
    assert resolve_hang_s(0) == 0.0  # explicit kwarg 0 beats the env
    monkeypatch.setenv("TPQ_HANG_S", "junk")
    assert resolve_hang_s() == 0.0


def test_watchdog_inert_disabled_and_leak_free():
    wd = Watchdog(0)
    assert not wd.enabled
    wd.watch("x", lambda: 1)
    wd.start()
    assert wd._thread is None  # inert: no thread at hang_s=0
    wd.stop()
    # enabled but nothing watched: also inert (nothing to judge progress by)
    wd2 = Watchdog(5.0)
    wd2.start()
    assert wd2._thread is None
    # enabled + watched: start/stop joins, restartable, never leaks
    wd3 = Watchdog(5.0, name="tpq-watchdog-leaktest")
    wd3.watch("x", lambda: time.perf_counter())  # always advancing
    with wd3:
        assert wd3._thread is not None
        time.sleep(0.02)
    assert wd3._thread is None
    assert all(t.name != "tpq-watchdog-leaktest"
               for t in threading.enumerate())
    assert not wd3.fired
    with pytest.raises(ValueError, match="policy"):
        Watchdog(1.0, policy="explode")


def test_hang_policy_env_typo_degrades_not_fatal(monkeypatch):
    """A TPQ_HANG_POLICY typo must not crash every reader/loader
    construction (resolve_hang_s treats malformed TPQ_HANG_S the same
    way); an explicit bad kwarg is a code bug and still raises."""
    monkeypatch.setenv("TPQ_HANG_POLICY", "warn")
    assert Watchdog(1.0).policy == "raise"  # env typo: safe default
    assert Watchdog(0).policy == "raise"  # even disabled: no raise
    with pytest.raises(ValueError, match="policy"):
        Watchdog(1.0, policy="warn")  # explicit kwarg stays strict


def test_idle_unscanned_reader_never_fires(tmp_path):
    """A reader built long before its first scan must not read as a hang:
    its counter lanes are frozen at 0, so the init-time consumer gate is
    the only thing keeping the watchdog honest."""
    from tpu_parquet.device_reader import DeviceFileReader

    path = _write_ints(str(tmp_path / "idle.parquet"))
    with DeviceFileReader(path, prefetch=2, max_memory=1 << 24,
                          hang_s=0.2) as r:
        time.sleep(0.9)  # several deadlines with no scan started
        assert not r._watchdog.fired
        # (iterating at a 0.2s deadline would legitimately fire on the
        # first unit of work — JAX compile; the healthy-iteration shape
        # is test_device_reader_hang_s_arms_and_close_joins at hang_s=60)
    assert not _obs_threads()


def test_abort_hooks_do_not_accumulate_across_scans(tmp_path):
    """Each feed's budget.abort hook must deregister on teardown: a
    reader-lifetime watchdog otherwise pins every past scan's budget."""
    from tpu_parquet.device_reader import DeviceFileReader

    path = _write_ints(str(tmp_path / "hooks.parquet"))
    with DeviceFileReader(path, prefetch=2, max_memory=1 << 24,
                          hang_s=60) as r:
        # reader-LIFETIME hooks (the store abort registered at
        # construction) are allowed; per-SCAN budget hooks must not pile up
        baseline = len(r._watchdog._abort_hooks)
        for _ in range(3):
            for _ in r.iter_row_groups():
                pass
        assert len(r._watchdog._abort_hooks) == baseline
    assert not _obs_threads()


def test_forced_wedge_dump_and_golden_autopsy_verdict(tmp_path):
    """THE acceptance criterion: a pipeline starved by a zero-headroom
    InFlightBudget triggers a watchdog dump within hang_s, the submitter
    raises HangError (policy raise), and `pq_tool autopsy` on the dump
    names the stalled lane and classifies the blocked thread as
    budget-wait — asserted as a golden verdict."""
    from tpu_parquet.cli import pq_tool

    dump = str(tmp_path / "wedge.json")
    rec = FlightRecorder(capacity=64)
    tr = Tracer(enabled=False, ring=rec)
    budget = InFlightBudget(1)
    budget.acquire(1)  # pre-starved: nothing will ever release it
    stats = PipelineStats(prefetch=2, budget_bytes=1, tracer=tr)
    wd = Watchdog(0.4, recorder=rec, policy="raise", dump_path=dump,
                  name="tpq-watchdog-wedge")
    wd.watch("pipeline", stats.sample)
    wd.add_abort_hook(budget.abort)
    wd.start()
    result = {}

    def submit():
        try:
            list(prefetch_map([1, 2], lambda x: x, 2, budget=budget,
                              cost=lambda x: 1, stats=stats))
        except BaseException as e:  # noqa: BLE001
            result["error"] = e

    t0 = time.monotonic()
    t = threading.Thread(target=submit, name="wedge-submitter")
    t.start()
    t.join(timeout=10)
    elapsed = time.monotonic() - t0
    wd.stop()
    assert not t.is_alive(), "submitter still wedged after the deadline"
    assert elapsed < 8.0  # fired within hang_s (+ cadence), not at timeout
    err = result["error"]
    assert isinstance(err, HangError)
    assert err.dump_path == dump
    assert wd.fired and wd.error is err
    with pytest.raises(HangError):
        wd.check()

    doc = json.loads(open(dump).read())
    assert doc["flight_version"] == FLIGHT_VERSION
    assert doc["reason"] == "hang"
    assert doc["watchdog"]["hang_s"] == 0.4
    # the dump carries the starved budget's waiter facts
    starved = [b for b in doc["budgets"] if b["waiters"]]
    assert starved and starved[0]["longest_wait_s"] > 0
    # the live pipeline's lane sample rode along (flight source registry)
    assert any(k.startswith("pipeline[") for k in doc["samples"])

    rep = autopsy_dump(doc)
    assert rep["verdict"] == "budget-wait"  # the golden verdict
    assert rep["stalled_first"].startswith("pipeline.")
    by_name = {t["name"]: t for t in rep["threads"].values()}
    assert by_name["wedge-submitter"]["class"] == "budget-wait"
    assert "InFlightBudget" in rep["probable_cause"]

    # the CLI renders it and exits 0
    out = io.StringIO()
    args = pq_tool.build_parser().parse_args(["autopsy", dump])
    assert args.func(args, out=out) == 0
    text = out.getvalue()
    assert "verdict: budget-wait" in text
    assert "wedge-submitter" in text and "probable cause:" in text
    assert not _obs_threads()


def test_watchdog_log_policy_dumps_and_continues(tmp_path):
    """Policy "log": the dump is the artifact, the run continues — and
    after the wedge clears, the re-armed watchdog does not re-fire."""
    dump = str(tmp_path / "logged.json")
    rec = FlightRecorder(capacity=16)
    counter = {"n": 0}
    wd = Watchdog(0.15, recorder=rec, policy="log", dump_path=dump,
                  name="tpq-watchdog-logtest")
    wd.watch("progress", lambda: counter["n"])
    with wd:
        time.sleep(0.6)  # frozen: must fire (and maybe re-fire) without raising
        assert wd.fired and wd.error is None
        assert os.path.exists(dump)
        wd.check()  # no pending error under the log policy
        fired_dumps = wd.last_dump
        for _ in range(8):  # progress resumes: re-armed, stays quiet
            counter["n"] += 1
            time.sleep(0.05)
    assert wd.last_dump == fired_dumps or wd.last_dump == dump
    assert json.loads(open(dump).read())["watchdog"]["policy"] == "log"


def test_watchdog_heartbeat_exception_never_fires_spuriously():
    """A raising heartbeat is dropped (counted), not treated as frozen."""
    wd = Watchdog(0.15, recorder=FlightRecorder(capacity=4), policy="log",
                  name="tpq-watchdog-exctest")
    wd.watch("bad", lambda: 1 // 0)
    wd.watch("good", lambda: time.perf_counter())
    with wd:
        time.sleep(0.4)
    assert wd.dropped >= 1
    assert not wd.fired  # the good lane kept advancing


# ---------------------------------------------------------------------------
# shared-cadence hygiene: tracer closed underneath sampler/watchdog (satellite)
# ---------------------------------------------------------------------------

class _ClosableTracer(Tracer):
    """A tracer whose counter() starts raising once 'closed' — the
    scan_files early-close shape, sharpened to the worst case."""

    def __init__(self):
        super().__init__(ring=None)
        self.closed = False

    def counter(self, name, track_id=None, **values):
        if self.closed:
            raise RuntimeError("tracer closed underneath the sampler")
        super().counter(name, track_id=track_id, **values)


def test_sampler_survives_tracer_closed_mid_run():
    tr = _ClosableTracer()
    s = Sampler(tr, 2.0, name="tpq-sampler-closetest")
    s.add_source("lanes", lambda: {"v": 1})
    with s:
        time.sleep(0.02)
        ticks_before = s.ticks
        tr.closed = True  # scan_files closes/writes the shared tracer
        time.sleep(0.05)
        assert s.ticks > ticks_before  # the daemon thread SURVIVED the close
    assert s._thread is None
    assert s.dropped >= 1  # the post-close ticks were dropped, not fatal
    assert all(t.name != "tpq-sampler-closetest"
               for t in threading.enumerate())


class _BrokenDumpRecorder(FlightRecorder):
    def dump(self, *a, **k):
        raise OSError("disk gone")


def test_watchdog_survives_unwritable_dump():
    """An unwritable dump must not mask the hang: the watchdog still fires,
    still aborts, and the HangError's dump_path is None."""
    budget = InFlightBudget(1)
    budget.acquire(1)
    wd = Watchdog(0.1, recorder=_BrokenDumpRecorder(capacity=4),
                  policy="raise", name="tpq-watchdog-dumpfail")
    wd.watch("x", lambda: 0)
    wd.add_abort_hook(budget.abort)
    with wd:
        time.sleep(0.4)
    assert wd.fired and isinstance(wd.error, HangError)
    assert wd.error.dump_path is None and wd.last_dump is None
    with pytest.raises(HangError):
        budget.acquire(1)


# ---------------------------------------------------------------------------
# worker-crash trigger
# ---------------------------------------------------------------------------

def test_worker_crash_lands_in_ring_and_dumps_under_tpq_flight(
        tmp_path, monkeypatch):
    import tpu_parquet.obs as obs_mod

    dump = str(tmp_path / "crash.json")
    monkeypatch.setenv("TPQ_FLIGHT", dump)
    monkeypatch.setattr(obs_mod, "_crash_dump_done", False)

    def boom(x):
        if x == 2:
            raise ValueError("deliberate worker death")
        return x

    with pytest.raises(ValueError, match="deliberate"):
        list(prefetch_map([1, 2, 3], boom, prefetch=2))
    # the crash is in the process ring regardless of any env
    snap = flight_recorder().snapshot()
    crashes = [e for t in snap["threads"].values() for e in t["events"]
               if e["name"] == "worker_crash"]
    assert crashes and crashes[-1]["args"]["type"] == "ValueError"
    # and TPQ_FLIGHT wrote the once-per-process dump
    doc = json.loads(open(dump).read())
    assert doc["reason"] == "worker-crash"
    assert doc["error"]["type"] == "ValueError"
    assert autopsy_dump(doc)["error"]["type"] == "ValueError"


def test_worker_crash_without_tpq_flight_writes_nothing(
        tmp_path, monkeypatch):
    import tpu_parquet.obs as obs_mod

    monkeypatch.delenv("TPQ_FLIGHT", raising=False)
    monkeypatch.setattr(obs_mod, "_crash_dump_done", False)
    monkeypatch.chdir(tmp_path)

    def die(x):
        raise RuntimeError("worker death without TPQ_FLIGHT")

    with pytest.raises(RuntimeError):
        list(prefetch_map([1], die, prefetch=1))
    assert list(tmp_path.iterdir()) == []  # deliberate raises stay file-less


# ---------------------------------------------------------------------------
# autopsy rule table on golden dumps
# ---------------------------------------------------------------------------

def _golden_dump(threads, budgets=(), watchdog=None, reason="hang"):
    return {
        "flight_version": FLIGHT_VERSION, "obs_version": OBS_VERSION,
        "reason": reason, "ts": 0.0, "pid": 1, "ring_capacity": 64,
        "threads": threads, "budgets": list(budgets), "trackers": [],
        "samples": {}, "registry": None, "watchdog": watchdog,
        "error": None,
    }


def _thread(name, stack, alive=True, last=None):
    return {"name": name, "alive": alive, "events": [],
            "last_event": last, "stack": stack}


_Q_GET = [
    {"file": "/usr/lib/python3.11/threading.py", "func": "wait", "line": 1,
     "code": ""},
    {"file": "/usr/lib/python3.11/queue.py", "func": "get", "line": 1,
     "code": ""},
][::-1]
_DEV_SYNC = [
    {"file": "/site-packages/jax/_src/array.py", "func": "block_until_ready",
     "line": 1, "code": ""},
]
_USER = [{"file": "/app/train.py", "func": "step", "line": 10, "code": ""}]


def test_autopsy_rule_table_queue_get_dead_worker():
    doc = _golden_dump(
        {"1": _thread("MainThread", _Q_GET),
         "2": _thread("tpq-prefetch_0", [], alive=False)},
        watchdog={"hang_s": 1.0, "ages": {"pipeline.io": 3.0},
                  "stalled_first": "pipeline.io", "policy": "log"})
    rep = autopsy_dump(doc)
    assert rep["threads"]["1"]["class"] == "queue-get"
    assert rep["verdict"] == "dead-worker"
    assert "tpq-prefetch_0" in rep["probable_cause"]


def test_autopsy_rule_table_device_sync():
    doc = _golden_dump({"1": _thread("MainThread", _DEV_SYNC)})
    rep = autopsy_dump(doc)
    assert rep["threads"]["1"]["class"] == "device-sync"
    assert rep["verdict"] == "device-sync"


def test_autopsy_rule_table_stalled_lane_and_inconclusive():
    doc = _golden_dump(
        {"1": _thread("MainThread", _USER,
                      last={"name": "batch", "age_s": 9.0})},
        watchdog={"hang_s": 1.0, "ages": {"loader.batches": 9.0},
                  "stalled_first": "loader.batches", "policy": "raise"})
    rep = autopsy_dump(doc)
    assert rep["threads"]["1"]["class"] == "running"
    assert rep["verdict"] == "stalled-loader"
    assert rep["threads"]["1"]["last_event"] == {"name": "batch",
                                                 "age_s": 9.0}
    rep = autopsy_dump(_golden_dump({"1": _thread("MainThread", _USER)}))
    assert rep["verdict"] == "inconclusive"


def test_autopsy_budget_waiters_win_even_without_stacks():
    """The budget snapshot alone is enough for the verdict: a dump taken by
    a signal handler inside the wedged thread shows obs frames on top, but
    the waiter count tells the truth."""
    doc = _golden_dump({"1": _thread("MainThread", [])},
                       budgets=[{"held": 1, "peak": 1, "max_bytes": 1,
                                 "waiters": 2, "longest_wait_s": 12.5}])
    rep = autopsy_dump(doc)
    assert rep["verdict"] == "budget-wait"
    assert rep["budget"] == {"waiters": 2, "longest_wait_s": 12.5}
    assert "12.5s" in rep["probable_cause"]


def test_autopsy_refuses_non_dumps(tmp_path):
    from tpu_parquet.cli import pq_tool

    with pytest.raises(ValueError, match="flight_version"):
        autopsy_dump({"traceEvents": []})
    with pytest.raises(ValueError, match="flight_version"):
        autopsy_dump({"flight_version": 99})
    p = tmp_path / "notadump.json"
    p.write_text(json.dumps({"traceEvents": []}))
    out = io.StringIO()
    args = pq_tool.build_parser().parse_args(["autopsy", str(p)])
    assert args.func(args, out=out) == 1
    assert "flight_version" in out.getvalue()
    assert pq_tool.main(["autopsy", str(tmp_path / "missing.json")]) == 1


# ---------------------------------------------------------------------------
# TPQ_DUMP_SIGNAL end-to-end (subprocess; satellite)
# ---------------------------------------------------------------------------

_WEDGE_CHILD = r"""
import sys, threading
from tpu_parquet.alloc import InFlightBudget  # noqa: F401 (imports obs hooks)
import tpu_parquet.obs  # installs TPQ_DUMP_SIGNAL handler from the env
b = InFlightBudget(1)
b.acquire(1)
print("READY", flush=True)
b.acquire(1)  # wedges forever: the waiter the dump must show
"""


@pytest.mark.skipif(not hasattr(signal, "SIGUSR1"), reason="POSIX signals")
def test_dump_signal_roundtrip_hung_child_to_autopsy(tmp_path):
    """Send TPQ_DUMP_SIGNAL to a hung child; the dump file appears and
    `pq_tool autopsy` exits 0 with a budget-wait verdict."""
    dump = str(tmp_path / "signal.json")
    env = dict(os.environ, TPQ_DUMP_SIGNAL="SIGUSR1", TPQ_FLIGHT=dump,
               JAX_PLATFORMS="cpu")
    child = subprocess.Popen([sys.executable, "-c", _WEDGE_CHILD],
                             stdout=subprocess.PIPE, text=True, env=env,
                             cwd=REPO_ROOT)
    try:
        assert child.stdout.readline().strip() == "READY"
        time.sleep(0.2)  # let the second acquire actually block
        os.kill(child.pid, signal.SIGUSR1)
        deadline = time.monotonic() + 20
        while not os.path.exists(dump) and time.monotonic() < deadline:
            time.sleep(0.05)
        # the write may still be in flight: wait for valid JSON
        doc = None
        while time.monotonic() < deadline:
            try:
                doc = json.loads(open(dump).read())
                break
            except (OSError, json.JSONDecodeError):
                time.sleep(0.05)
        assert doc is not None, "no dump after TPQ_DUMP_SIGNAL"
    finally:
        child.kill()
        child.wait()
    assert doc["reason"] == "signal"
    assert any(b["waiters"] for b in doc["budgets"])
    rep = autopsy_dump(doc)
    assert rep["verdict"] == "budget-wait"
    proc = subprocess.run(
        [sys.executable, "-m", "tpu_parquet.cli.pq_tool", "autopsy", dump],
        capture_output=True, text=True, cwd=REPO_ROOT,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert proc.returncode == 0, proc.stderr
    assert "verdict: budget-wait" in proc.stdout


def test_excepthook_installed_only_with_tpq_flight(monkeypatch):
    import tpu_parquet.obs as obs_mod

    monkeypatch.delenv("TPQ_FLIGHT", raising=False)
    monkeypatch.delenv("TPQ_DUMP_SIGNAL", raising=False)
    assert obs_mod.install_flight_hooks(force=True) == {
        "signal": False, "excepthook": False}
    prev = sys.excepthook
    try:
        monkeypatch.setenv("TPQ_FLIGHT", "/tmp/x.json")
        monkeypatch.setenv("TPQ_DUMP_SIGNAL", "NOSUCHSIG")
        took = obs_mod.install_flight_hooks(force=True)
        assert took == {"signal": False, "excepthook": True}
        assert sys.excepthook is not prev
    finally:
        sys.excepthook = prev


# ---------------------------------------------------------------------------
# wiring: reader / scan / loader arm + stop cleanly (thread-leak acceptance)
# ---------------------------------------------------------------------------

def test_device_reader_hang_s_arms_and_close_joins(tmp_path):
    from tpu_parquet.device_reader import DeviceFileReader

    path = _write_ints(str(tmp_path / "a.parquet"))
    with DeviceFileReader(path, prefetch=2, max_memory=1 << 24,
                          hang_s=60) as r:
        assert r._watchdog.enabled and r._watchdog._thread is not None
        budgets = []
        for _ in r.iter_row_groups():
            budgets.append(r._live_budget)  # the feed late-bound its budget
        assert not r._watchdog.fired
        # bound while the feed is live (the drained tail may already be None)
        assert budgets and budgets[0] is not None
        assert budgets[0].snapshot()["waiters"] == 0
        # the dead feed must un-bind: no stale budget in later flight dumps
        assert r._live_budget is None
    assert not _obs_threads()
    # env-armed form + kwarg-0 override
    os.environ["TPQ_HANG_S"] = "60"
    try:
        with DeviceFileReader(path, hang_s=0) as r:
            assert not r._watchdog.enabled  # explicit 0 beats the env
        with DeviceFileReader(path) as r:
            assert r._watchdog.enabled
    finally:
        del os.environ["TPQ_HANG_S"]
    assert not _obs_threads()


def test_scan_files_one_watchdog_and_early_close_joins(tmp_path):
    from tpu_parquet.device_reader import scan_files

    paths = [_write_ints(str(tmp_path / f"{i}.parquet"), seed=i)
             for i in range(2)]
    # full scan, then an early-abandoned scan: both must leave zero threads
    n = sum(1 for _ in scan_files(paths, prefetch=2, max_memory=1 << 24,
                                  hang_s=60))
    assert n == 6
    gen = scan_files(paths, prefetch=2, max_memory=1 << 24, hang_s=60)
    next(gen)
    gen.close()  # the scan_files early-close path the satellite names
    assert not _obs_threads()


def test_loader_hang_s_arms_per_epoch_and_stops(tmp_path):
    from tpu_parquet.data.loader import DataLoader

    path = _write_ints(str(tmp_path / "l.parquet"))
    dl = DataLoader(path, batch_size=512, prefetch=2, max_memory=1 << 24,
                    hang_s=60, shuffle=True, seed=7)
    it = iter(dl)
    next(it)
    assert dl._watchdog is not None and dl._watchdog._thread is not None
    it.close()  # early abandon: the finally path must join the watchdog
    assert dl._watchdog is None
    assert not _obs_threads()
    # a full epoch also cleans up
    for _ in dl:
        pass
    assert not _obs_threads()


# ---------------------------------------------------------------------------
# doctor/trace ledger refs (satellite)
# ---------------------------------------------------------------------------

def _lane_tree():
    return {"obs_version": OBS_VERSION,
            "pipeline": {"io_seconds": 1.0, "decompress_seconds": 2.0,
                         "recompress_seconds": 0.0, "stage_seconds": 0.5,
                         "dispatch_seconds": 0.1, "finalize_seconds": 0.0,
                         "stall_seconds": 0.0}}


def test_ledger_latest_and_bare_hash_refs(tmp_path, monkeypatch):
    lpath = str(tmp_path / "ledger.jsonl")
    for v in (1.0, 2.0):
        ledger.append(lpath, {"metric": "m", "value": v, "configs": {}})
    monkeypatch.setenv("TPQ_LEDGER", lpath)
    assert ledger.default_path() == lpath
    assert ledger.load_side("latest")["value"] == 2.0
    assert ledger.load_side("latest#0")["value"] == 1.0
    assert ledger.load_side("#-2")["value"] == 1.0
    for spec in ("latest", "latest#0", "#1", "a/ledger.jsonl", "l.jsonl#2"):
        assert ledger.is_ref(spec), spec
    for spec in ("run.json", "trace.lineitem16.json", "dump.json"):
        assert not ledger.is_ref(spec), spec
    monkeypatch.delenv("TPQ_LEDGER", raising=False)
    assert ledger.default_path() == "ledger.jsonl"


def test_pq_tool_doctor_accepts_ledger_refs(tmp_path, monkeypatch):
    from tpu_parquet.cli import pq_tool

    lpath = str(tmp_path / "ledger.jsonl")
    rec = {"metric": "m", "value": 1.0,
           "configs": {"cfg": {"rows": 10, "obs": _lane_tree()}}}
    ledger.append(lpath, rec)
    monkeypatch.setenv("TPQ_LEDGER", lpath)
    for spec in ("latest", "#0", lpath + "#0", lpath):
        out = io.StringIO()
        args = pq_tool.build_parser().parse_args(["doctor", spec])
        assert args.func(args, out=out) == 0, spec
        assert "host-decompress-bound" in out.getvalue(), spec


def test_pq_tool_trace_accepts_ledger_refs(tmp_path, monkeypatch):
    from tpu_parquet.cli import pq_tool
    from tpu_parquet.obs import StatsRegistry

    # the run's trace artifact, where bench would have written it
    base = str(tmp_path / "trace")
    tr = Tracer(path=f"{base}.cfg.json")
    with tr.span("io"):
        time.sleep(0.001)
    reg = StatsRegistry()
    tr.write(registry=reg)
    lpath = str(tmp_path / "ledger.jsonl")
    ledger.append(lpath, {
        "metric": "m", "value": 1.0, "env": {"TPQ_TRACE": base},
        "configs": {"cfg": {"rows": 10}}})
    monkeypatch.setenv("TPQ_LEDGER", lpath)
    out = io.StringIO()
    args = pq_tool.build_parser().parse_args(["trace", "latest"])
    assert args.func(args, out=out) == 0
    text = out.getvalue()
    assert f"{base}.cfg.json" in text and "io" in text
    # a record without TPQ_TRACE diagnoses in one line, exit 1
    ledger.append(lpath, {"metric": "m", "value": 1.0, "env": {},
                          "configs": {"cfg": {"rows": 10}}})
    out = io.StringIO()
    args = pq_tool.build_parser().parse_args(["trace", "latest"])
    assert args.func(args, out=out) == 1
    assert "without TPQ_TRACE" in out.getvalue()
    # --config names a missing artifact explicitly, exit 1
    out = io.StringIO()
    args = pq_tool.build_parser().parse_args(
        ["trace", "latest#0", "--config", "other"])
    assert args.func(args, out=out) == 1
    assert "not found" in out.getvalue()
