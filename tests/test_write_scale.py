"""ISSUE 15: tpu_parquet.write — distributed sharded writer + compaction.

The contracts under test, in rough order of importance:

- BIT-FAITHFULNESS: the N-worker sharded write's merged single file is
  byte-identical to the single-writer file over the same batches, and the
  manifest form reads back identically through FileReader /
  DeviceFileReader / scan_files / DataLoader at prefetch {0, 4} — with
  CRCs present and validated by default (TPQ_WRITE_CRC mirrors
  TPQ_VALIDATE);
- footer-merge validation: truncated/lying/overlapping/mismatched shard
  footers are rejected with typed ParquetError, never silently merged;
- the manifest is a versioned atomic commit point: generation bumps are
  monotonic, malformed documents are typed rejections;
- compaction is crash-safe and cache-coherent: many small files become
  few large ones with CRCs always written, the publish is atomic
  (manifest flips last), a concurrent reader/serve sweep never sees a
  torn or stale dataset, and a writer-driven rewrite bumps the
  PlanCache/ResultCache generation with EXACT invalidation counts;
- writer observability: the registry ``write`` section's golden keys,
  its merge contract, and pq_tool doctor's write-lane attribution.
"""

import io
import json
import os
import threading

import numpy as np
import pytest

from tpu_parquet.column import ByteArrayData, ColumnData
from tpu_parquet.device_reader import scan_files
from tpu_parquet.errors import ParquetError
from tpu_parquet.footer import read_file_metadata
from tpu_parquet.format import (CompressionCodec, FieldRepetitionType as FRT,
                                PageType, Type)
from tpu_parquet.obs import StatsRegistry, doctor_registry
from tpu_parquet.reader import FileReader, _concat_column_data
from tpu_parquet.schema.core import build_schema, data_column
from tpu_parquet.serve import PlanCache, ScanRequest, ScanService
from tpu_parquet.write import (MANIFEST_NAME, WriteStats, compact,
                               CompactionService, expand_dataset,
                               load_manifest, merge_files, merge_footers,
                               write_manifest, write_sharded)
from tpu_parquet.write.sharded import encode_row_group
from tpu_parquet.writer import FileWriter, resolve_write_crc


def _schema():
    return build_schema([
        data_column("a", Type.INT64, FRT.REQUIRED),
        data_column("b", Type.DOUBLE, FRT.REQUIRED),
        data_column("s", Type.BYTE_ARRAY, FRT.REQUIRED),
    ])


def _batches(n_rgs=6, rows=800, seed=0):
    rng = np.random.default_rng(seed)
    pool = [b"alpha", b"beta", b"gamma-gamma", b"", b"delta"]
    out = []
    for _ in range(n_rgs):
        svals = [pool[i] for i in rng.integers(0, len(pool), rows)]
        out.append({
            "a": rng.integers(0, 1 << 40, rows).astype(np.int64),
            "b": rng.random(rows),
            "s": ColumnData(values=ByteArrayData(
                offsets=np.cumsum([0] + [len(v) for v in svals]),
                heap=np.frombuffer(b"".join(svals), np.uint8).copy())),
        })
    return out


def _single_writer_file(path, schema, batches, **kw):
    with FileWriter(path, schema, **kw) as w:
        for b in batches:
            w.write_columns(b)
            w.flush_row_group()
    return path


def _read_all_concat(paths, prefetch=0):
    cols: dict = {}
    for p in paths:
        with FileReader(p, prefetch=prefetch) as r:
            for k, v in r.read_all().items():
                cols.setdefault(k, []).append(v)
    return {k: _concat_column_data(v) for k, v in cols.items()}


def _assert_cols_equal(got, want):
    assert set(got) == set(want)
    for name in want:
        g, w = got[name], want[name]
        if isinstance(w.values, ByteArrayData):
            np.testing.assert_array_equal(g.values.offsets, w.values.offsets)
            np.testing.assert_array_equal(g.values.heap, w.values.heap)
        else:
            np.testing.assert_array_equal(g.values, w.values)


# ---------------------------------------------------------------------------
# bit-faithfulness: merged file == single-writer file, manifest reads equal
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("workers", [1, 3])
def test_sharded_file_bit_identical_to_single_writer(tmp_path, workers):
    schema, batches = _schema(), _batches()
    single = _single_writer_file(str(tmp_path / "single.parquet"),
                                 schema, batches)
    merged = str(tmp_path / "merged.parquet")
    res = write_sharded(merged, schema, batches, workers=workers)
    assert res.layout == "file" and res.files == 1
    assert res.rows == sum(len(b["b"]) for b in batches)
    assert open(single, "rb").read() == open(merged, "rb").read()


@pytest.mark.parametrize("prefetch", [0, 4])
def test_sharded_outputs_read_back_bit_identical(tmp_path, prefetch):
    schema, batches = _schema(), _batches()
    single = _single_writer_file(str(tmp_path / "single.parquet"),
                                 schema, batches)
    merged = str(tmp_path / "merged.parquet")
    write_sharded(merged, schema, batches, workers=2)
    d = tmp_path / "ds"
    d.mkdir()
    res = write_sharded(str(d), schema, batches, workers=2,
                        target_file_bytes=20_000)
    assert res.files > 1, "target_file_bytes must cut several members"
    want = _read_all_concat([single], prefetch=prefetch)
    _assert_cols_equal(_read_all_concat([merged], prefetch=prefetch), want)
    paths, m = expand_dataset(str(d))
    assert m is not None and m.generation == 1
    assert all(os.path.isabs(p) for p in paths)  # resolved member paths
    _assert_cols_equal(_read_all_concat(paths, prefetch=prefetch), want)


@pytest.mark.parametrize("prefetch", [0, 4])
def test_manifest_scans_as_one_device_dataset(tmp_path, prefetch):
    """scan_files accepts the manifest (path OR directory) and yields the
    same groups, in order, as the single-writer file."""
    schema, batches = _schema(), _batches(n_rgs=4)
    single = _single_writer_file(str(tmp_path / "single.parquet"),
                                 schema, batches)
    d = tmp_path / "ds"
    d.mkdir()
    write_sharded(str(d), schema, batches, workers=2,
                  target_file_bytes=20_000)

    def groups(src):
        out = []
        for cols in scan_files(src, prefetch=prefetch):
            out.append({k: np.asarray(v.to_host())
                        if not isinstance(batches[0][k], ColumnData)
                        else v.to_host() for k, v in cols.items()})
        return out

    got = groups(str(d))
    want = groups([single])
    assert len(got) == len(want) == 4
    for g, w in zip(got, want):
        assert set(g) == set(w)
        for k in w:
            if isinstance(w[k], ByteArrayData):
                np.testing.assert_array_equal(
                    np.asarray(g[k].offsets), np.asarray(w[k].offsets))
                np.testing.assert_array_equal(
                    np.asarray(g[k].heap), np.asarray(w[k].heap))
            else:
                np.testing.assert_array_equal(np.asarray(g[k]),
                                              np.asarray(w[k]))


@pytest.mark.parametrize("prefetch", [0, 4])
def test_dataloader_consumes_manifest_as_one_dataset(tmp_path, prefetch):
    from tpu_parquet.data import DataLoader

    schema = build_schema([
        data_column("a", Type.INT64, FRT.REQUIRED),
        data_column("b", Type.DOUBLE, FRT.REQUIRED),
    ])
    batches = [{k: v for k, v in b.items() if k != "s"}
               for b in _batches(n_rgs=4, rows=500)]
    single = _single_writer_file(str(tmp_path / "single.parquet"),
                                 schema, batches)
    d = tmp_path / "ds"
    d.mkdir()
    write_sharded(str(d), schema, batches, workers=2,
                  target_file_bytes=10_000)

    def stream(src):
        dl = DataLoader(src, batch_size=128, shuffle=False,
                        drop_remainder=True, prefetch=prefetch)
        return [{k: np.asarray(v) for k, v in b.items()}
                for b in dl]

    got, want = stream(str(d)), stream(single)
    assert len(got) == len(want) > 0
    for g, w in zip(got, want):
        for k in w:
            np.testing.assert_array_equal(g[k], w[k])


# ---------------------------------------------------------------------------
# TPQ_WRITE_CRC: default-on CRCs, validated by the default reader tier
# ---------------------------------------------------------------------------

def _first_page_has_crc(path) -> bool:
    from tpu_parquet.chunk_decode import validate_chunk_meta, walk_pages
    from tpu_parquet.schema.core import Schema

    with open(path, "rb") as f:
        md = read_file_metadata(f)
        schema = Schema.from_file_metadata(md)
        chunk = md.row_groups[0].columns[0]
        cmd, offset = validate_chunk_meta(chunk, schema.leaves[0])
        f.seek(offset)
        buf = f.read(cmd.total_compressed_size)
    for ps in walk_pages(buf, cmd.num_values):
        return ps.header.crc is not None
    return False


def test_write_crc_defaults_on_and_validates(tmp_path, monkeypatch):
    monkeypatch.delenv("TPQ_WRITE_CRC", raising=False)
    schema, batches = _schema(), _batches(n_rgs=2)
    merged = str(tmp_path / "m.parquet")
    write_sharded(merged, schema, batches, workers=2)
    assert _first_page_has_crc(merged), "default-on CRCs missing"
    # and the default reader tier actually verifies them
    from tpu_parquet.writer import corrupt_page

    corrupt_page(merged, 0, 0, 0, mode="bitflip", seed=3)
    with pytest.raises(ParquetError, match="(?i)crc"):
        with FileReader(merged) as r:
            r.read_all()


def test_write_crc_env_knob_contract(tmp_path, monkeypatch):
    # env off -> no CRCs written
    monkeypatch.setenv("TPQ_WRITE_CRC", "0")
    schema, batches = _schema(), _batches(n_rgs=1)
    off = str(tmp_path / "off.parquet")
    _single_writer_file(off, schema, batches)
    assert not _first_page_has_crc(off)
    # explicit kwarg wins over the env
    on = str(tmp_path / "on.parquet")
    _single_writer_file(on, schema, batches, write_crc=True)
    assert _first_page_has_crc(on)
    # malformed env degrades to default-on with a warning, never a raise
    monkeypatch.setenv("TPQ_WRITE_CRC", "bananas")
    assert resolve_write_crc(None) is True
    # kwarg strings are strict
    with pytest.raises(ValueError):
        resolve_write_crc("bananas")
    assert resolve_write_crc("off") is False and resolve_write_crc("on")


# ---------------------------------------------------------------------------
# footer merge: typed rejections
# ---------------------------------------------------------------------------

def test_merge_files_roundtrip_and_rejections(tmp_path):
    schema, batches = _schema(), _batches(n_rgs=4)
    parts = []
    for i in range(2):
        p = str(tmp_path / f"part{i}.parquet")
        _single_writer_file(p, schema, batches[2 * i: 2 * i + 2])
        parts.append(p)
    single = _single_writer_file(str(tmp_path / "single.parquet"),
                                 schema, batches)
    out = str(tmp_path / "merged.parquet")
    merged_meta = merge_files(out, parts)
    assert merged_meta.num_rows == sum(len(b["b"]) for b in batches)
    assert open(out, "rb").read() == open(single, "rb").read()

    # schema mismatch is a typed rejection
    other_schema = build_schema([data_column("z", Type.INT64, FRT.REQUIRED)])
    alien = str(tmp_path / "alien.parquet")
    with FileWriter(alien, other_schema) as w:
        w.write_columns({"z": np.arange(5, dtype=np.int64)})
    with pytest.raises(ParquetError, match="schema does not match"):
        merge_files(str(tmp_path / "x.parquet"), [parts[0], alien])

    # a lying footer (num_rows disagrees with its groups) is rejected
    meta = read_file_metadata(parts[0])
    meta.num_rows += 1
    with pytest.raises(ParquetError, match="lying shard footer"):
        merge_footers([(meta, os.path.getsize(parts[0]))])

    # a truncated shard (footer spans past the data segment) is rejected
    good = read_file_metadata(parts[0])
    with pytest.raises(ParquetError, match="past the data segment"):
        merge_footers([(good, 128)])

    # failure never publishes: no merged temp left behind
    leftovers = [f for f in os.listdir(tmp_path) if ".tmp-" in f]
    assert not leftovers, leftovers


# ---------------------------------------------------------------------------
# manifest: versioned, atomic, monotonic
# ---------------------------------------------------------------------------

def test_manifest_round_trip_generation_and_rejections(tmp_path):
    schema, batches = _schema(), _batches(n_rgs=2)
    d = tmp_path / "ds"
    d.mkdir()
    write_sharded(str(d), schema, batches, workers=1,
                  target_file_bytes=10_000)
    m = load_manifest(str(d))
    assert m.generation == 1 and m.total_rows == 1600
    # a second publish bumps the generation
    m2 = write_manifest(str(d), m.member_paths())
    assert m2.generation == 2
    # an explicit non-advancing generation is rejected
    with pytest.raises(ParquetError, match="must advance"):
        write_manifest(str(d), m.member_paths(), generation=1)
    # malformed documents are typed rejections
    mp = str(d / MANIFEST_NAME)
    doc = json.load(open(mp))
    for mutate, pat in [
        (lambda x: x.update(magic="NOPE"), "magic"),
        (lambda x: x.update(manifest_version=99), "manifest_version"),
        (lambda x: x.update(generation=0), "generation"),
        (lambda x: x.update(files=[]), "file list"),
        (lambda x: x["files"][0].update(path="/abs/path.parquet"),
         "escapes"),
        (lambda x: x["files"][0].update(rows=-1), "non-negative"),
        (lambda x: x.update(total_rows=7), "member sum"),
    ]:
        bad = json.loads(json.dumps(doc))
        mutate(bad)
        json.dump(bad, open(mp, "w"))
        with pytest.raises(ParquetError, match=pat):
            load_manifest(str(d))
    # and no temp files linger from the atomic publishes
    assert not [f for f in os.listdir(d) if ".tmp-" in f]


# ---------------------------------------------------------------------------
# compaction: many small -> few large, CRCs always, atomic + coherent
# ---------------------------------------------------------------------------

def _fragmented_dataset(tmp_path, n_files=8, rows=300, seed=0):
    schema = _schema()
    d = tmp_path / "frag"
    d.mkdir()
    rng_batches = _batches(n_rgs=n_files, rows=rows, seed=seed)
    paths = []
    for i, b in enumerate(rng_batches):
        p = str(d / f"in-{i:03d}.parquet")
        _single_writer_file(p, schema, [b])
        paths.append(p)
    write_manifest(str(d), paths)
    return d, schema, paths


def test_compaction_end_to_end(tmp_path, monkeypatch):
    monkeypatch.setenv("TPQ_WRITE_CRC", "0")  # compaction must override
    d, schema, paths = _fragmented_dataset(tmp_path)
    want = _read_all_concat(paths)
    rep = compact(str(d), target_file_bytes=1 << 20, workers=2)
    assert rep.files_before == 8 and rep.files_after < 8
    assert rep.rows == 2400
    assert rep.row_groups_after < rep.row_groups_before
    assert rep.generation == 2
    assert 0 < rep.link_bytes_ratio <= 1.1
    m = load_manifest(str(d))
    assert m.generation == 2
    assert [os.path.basename(p) for p in m.member_paths()] == \
        [os.path.basename(p) for p in rep.out_paths]
    # content preserved bit-identically, CRCs written despite the env
    _assert_cols_equal(_read_all_concat(m.member_paths()), want)
    for p in m.member_paths():
        assert _first_page_has_crc(p), "compaction must always write CRCs"
    # inputs kept by default (readers holding generation 1 stay whole)
    assert all(os.path.exists(p) for p in paths)
    # remove_inputs unlinks superseded members after the flip
    rep2 = compact(str(d), target_file_bytes=1 << 20, workers=1,
                   remove_inputs=True)
    assert rep2.generation == 3
    _assert_cols_equal(_read_all_concat(load_manifest(str(d)).member_paths()),
                       want)
    assert all(not os.path.exists(p) for p in rep.out_paths)


def test_rewrite_never_touches_previous_generation_members(tmp_path):
    """Member filenames are generation-unique: re-writing a live manifest
    dataset must never os.replace the previous generation's members
    before the manifest flips — a reader holding the old manifest stays
    whole."""
    schema, batches = _schema(), _batches(n_rgs=3)
    d = tmp_path / "live"
    d.mkdir()
    r1 = write_sharded(str(d), schema, batches, workers=2,
                       target_file_bytes=10_000)
    gen1_bytes = {p: open(p, "rb").read() for p in r1.paths}
    r2 = write_sharded(str(d), schema, _batches(n_rgs=3, seed=9),
                       workers=2, target_file_bytes=10_000)
    assert r1.generation == 1 and r2.generation == 2
    assert not (set(r1.paths) & set(r2.paths)), "member names collided"
    for p, data in gen1_bytes.items():  # old generation untouched on disk
        assert open(p, "rb").read() == data
    assert load_manifest(str(d)).generation == 2


def test_compaction_service_policy(tmp_path):
    d, _schema_, _paths = _fragmented_dataset(tmp_path)
    svc = CompactionService(min_file_bytes=1 << 20, max_small_files=4,
                            target_file_bytes=1 << 20)
    rep = svc.run_once(str(d))
    assert rep is not None and rep.files_after < rep.files_before
    # after compaction the dataset is no longer fragmented: no-op
    assert svc.run_once(str(d)) is None


def test_writer_driven_generation_bump_exact_invalidation(tmp_path):
    """The satellite: a REAL writer rewrite (atomic publish onto a live
    path) bumps the PlanCache/ResultCache generation with exact counts —
    no synthetic mtime games — and zero stale bytes are served."""
    schema, batches = _schema(), _batches(n_rgs=2, seed=1)
    path = str(tmp_path / "live.parquet")
    write_sharded(path, schema, batches, workers=2)
    cache = PlanCache(result_cache_mb=64)
    with ScanService(concurrency=1, cache=cache) as svc:
        first = svc.scan(ScanRequest(path))[path]
        svc.scan(ScanRequest(path))  # provably warm
        plan_entries = cache.counters()["entries"]
        res_entries = cache.results.counters()["host"]["entries"]
        inv0_plan = cache.counters()["invalidations"]
        inv0_res = cache.results.counters()["host"]["invalidations"]
        assert plan_entries > 0 and res_entries > 0
        # the writer-driven mutation: new content, atomic replace, and
        # the publish notifies the cache (no reader ever re-opens first)
        new_batches = _batches(n_rgs=2, seed=2)
        write_sharded(path, schema, new_batches, workers=2,
                      plan_cache=cache)
        # eager + exact: EVERY entry of the old generation dropped NOW
        assert (cache.counters()["invalidations"] - inv0_plan
                == plan_entries)
        assert (cache.results.counters()["host"]["invalidations"]
                - inv0_res == res_entries)
        after = svc.scan(ScanRequest(path))[path]
    # zero stale bytes: the served columns are the NEW file's
    with FileReader(path) as r:
        fresh = r.read_all()
    _assert_cols_equal(after, fresh)
    assert not np.array_equal(np.asarray(first["a"].values),
                              np.asarray(after["a"].values))


def test_compaction_mid_sweep_never_torn_or_stale(tmp_path):
    """A serve sweep running concurrently with compaction: every response
    is bit-identical to the dataset's canonical content — never a torn
    member, never a stale mixture (compaction preserves content, so ANY
    generation must serve the same rows)."""
    d, schema, paths = _fragmented_dataset(tmp_path, n_files=6)
    want = _read_all_concat(paths)
    cache = PlanCache(result_cache_mb=32)
    errors: list = []
    stop = threading.Event()

    def sweep():
        try:
            with ScanService(concurrency=2, cache=cache) as svc:
                while not stop.is_set():
                    members = load_manifest(str(d)).member_paths()
                    got: dict = {}
                    for p in members:
                        for k, v in svc.scan(ScanRequest(p))[p].items():
                            got.setdefault(k, []).append(v)
                    cat = {k: _concat_column_data(v)
                           for k, v in got.items()}
                    for k in want:
                        assert np.array_equal(
                            np.asarray(cat[k].values.heap
                                       if isinstance(cat[k].values,
                                                     ByteArrayData)
                                       else cat[k].values),
                            np.asarray(want[k].values.heap
                                       if isinstance(want[k].values,
                                                     ByteArrayData)
                                       else want[k].values)), k
        except BaseException as e:  # noqa: BLE001
            errors.append(e)

    t = threading.Thread(target=sweep)
    t.start()
    try:
        rep = compact(str(d), target_file_bytes=1 << 20, workers=2,
                      plan_cache=cache)
        assert rep.files_after < rep.files_before
        # one more compaction for extra churn while the sweep runs
        compact(str(d), target_file_bytes=1 << 20, workers=1,
                plan_cache=cache)
    finally:
        stop.set()
        t.join(30)
    assert not errors, errors[0]


# ---------------------------------------------------------------------------
# observability: the write section + doctor attribution
# ---------------------------------------------------------------------------

def test_write_stats_registry_golden_keys_and_merge():
    st = WriteStats(workers=3)
    st.add("encode", 0.2)
    st.add("compress", 0.05)
    st.add("flush", 0.01)
    st.count_row_group(100, chunks=2)
    st.count_file(4096)
    st.touch_wall()
    reg = StatsRegistry()
    reg.add_write(st)
    tree = reg.as_dict()
    w = tree["write"]
    assert set(w) == {
        "workers", "rows", "row_groups", "chunks", "files", "bytes_written",
        "encode_seconds", "compress_seconds", "flush_seconds",
        "merge_seconds", "compact_seconds", "stall_seconds", "wall_seconds",
        "busy_seconds", "rows_per_sec", "bytes_per_sec",
    }
    assert w["workers"] == 3 and w["rows"] == 100 and w["files"] == 1
    assert "write.encode" in tree["histograms"]
    json.dumps(tree)  # artifact-ready
    # merge contract: flows add, workers max, derived rates recomputed
    st2 = WriteStats(workers=2)
    st2.add("encode", 0.1)
    st2.count_row_group(50, chunks=1)
    reg.add_write(st2)
    t2 = reg.as_dict()["write"]
    assert t2["rows"] == 150 and t2["workers"] == 3
    assert t2["encode_seconds"] == pytest.approx(0.3)
    # cross-process dict merge path
    reg2 = StatsRegistry()
    reg2.merge_dict(reg.as_dict())
    assert reg2.as_dict()["write"]["rows"] == 150
    # WriteStats.merge_from composes the same way
    st.merge_from(st2)
    assert st.rows == 150 and st.workers == 3


def test_write_stats_unknown_stage_raises():
    with pytest.raises(ValueError, match="unknown write stage"):
        WriteStats().add("teleport", 1.0)


def test_doctor_attributes_slow_write(tmp_path, capsys):
    schema, batches = _schema(), _batches(n_rgs=3)
    st = WriteStats()
    write_sharded(str(tmp_path / "w.parquet"), schema, batches,
                  workers=2, stats=st)
    reg = StatsRegistry()
    reg.add_write(st)
    rep = doctor_registry(reg.as_dict())
    assert rep is not None and "write" in rep
    assert rep["write"]["verdict"].startswith("write-")
    assert rep["write"]["dominant_lane"] in ("encode", "compress", "flush",
                                             "merge", "compact", "stall")
    # the CLI renders the write verdict line
    from tpu_parquet.cli.pq_tool import cmd_doctor

    p = str(tmp_path / "reg.json")
    json.dump(reg.as_dict(), open(p, "w"))

    class A:
        file = p
        config = None

    out = io.StringIO()
    assert cmd_doctor(A(), out=out) == 0
    text = out.getvalue()
    assert "write verdict: write-" in text and "write:" in text


def test_filewriter_books_write_lanes(tmp_path):
    st = WriteStats()
    schema, batches = _schema(), _batches(n_rgs=1)
    _single_writer_file(str(tmp_path / "x.parquet"), schema, batches,
                        stats=st, codec=CompressionCodec.SNAPPY)
    d = st.as_dict()
    assert d["rows"] == 800 and d["row_groups"] == 1 and d["chunks"] == 3
    assert d["encode_seconds"] > 0
    assert d["compress_seconds"] > 0
    assert d["flush_seconds"] > 0
    # the lanes PARTITION the chunk wall: a single-threaded write's busy
    # seconds can never exceed its open..close wall (booked once, not twice)
    assert d["busy_seconds"] <= d["wall_seconds"] + 0.05


# ---------------------------------------------------------------------------
# the CLI: pq_tool merge / compact
# ---------------------------------------------------------------------------

def test_pq_tool_merge_and_compact(tmp_path):
    from tpu_parquet.cli import pq_tool

    def run_tool(args):
        buf = io.StringIO()
        parsed = pq_tool.build_parser().parse_args(args)
        return parsed.func(parsed, out=buf), buf.getvalue()

    schema, batches = _schema(), _batches(n_rgs=4)
    parts = []
    for i in range(2):
        p = str(tmp_path / f"p{i}.parquet")
        _single_writer_file(p, schema, batches[2 * i: 2 * i + 2])
        parts.append(p)
    out = str(tmp_path / "merged.parquet")
    rc, text = run_tool(["merge", out, *parts])
    assert rc == 0 and "merged 2 file(s)" in text
    with FileReader(out) as r:
        assert r.metadata.num_rows == 3200

    d = tmp_path / "ds"
    d.mkdir()
    for i, p in enumerate(parts):
        os.link(p, str(d / f"m{i}.parquet"))
    rc, text = run_tool(["compact", str(d / "m0.parquet"),
                         str(d / "m1.parquet"),
                         "--out", str(d), "--target-size", "64MB"])
    assert rc == 0
    assert "compacted 2 file(s)" in text and "link bytes" in text
    m = load_manifest(str(d))
    assert m.total_rows == 3200


# ---------------------------------------------------------------------------
# budget/backpressure + worker-encode helpers
# ---------------------------------------------------------------------------

def test_sharded_write_respects_memory_budget(tmp_path):
    schema, batches = _schema(), _batches(n_rgs=6)
    st = WriteStats()
    res = write_sharded(str(tmp_path / "b.parquet"), schema, batches,
                        workers=3, max_memory=1 << 20, stats=st)
    assert res.rows == 4800  # bounded, not broken
    single = _single_writer_file(str(tmp_path / "s.parquet"), schema,
                                 _batches(n_rgs=6))
    assert (open(single, "rb").read()
            == open(str(tmp_path / "b.parquet"), "rb").read())


def test_encode_row_group_blob_is_a_valid_file():
    schema, batches = _schema(), _batches(n_rgs=1)
    blob, meta = encode_row_group(schema, batches[0])
    assert meta.num_rows == 800 and len(meta.row_groups) == 1
    with FileReader(io.BytesIO(blob)) as r:
        got = r.read_all()
    assert len(np.asarray(got["a"].values)) == 800


def test_write_sharded_rejects_empty_and_bad_layout(tmp_path):
    schema = _schema()
    with pytest.raises(ParquetError, match="no row groups"):
        write_sharded(str(tmp_path / "e.parquet"), schema, [])
    with pytest.raises(ValueError, match="layout"):
        write_sharded(str(tmp_path / "e.parquet"), schema, _batches(1),
                      layout="zipfile")
    with pytest.raises(ParquetError, match="directory"):
        write_sharded(str(tmp_path / "nodir"), schema, _batches(1),
                      layout="manifest")


def test_worker_failure_leaves_no_temp_and_joins_pool(tmp_path):
    schema = _schema()
    good = _batches(n_rgs=2)

    def gen():
        yield good[0]
        raise RuntimeError("producer died")

    with pytest.raises(RuntimeError, match="producer died"):
        write_sharded(str(tmp_path / "dead.parquet"), schema, gen(),
                      workers=2)
    assert not [f for f in os.listdir(tmp_path) if ".tmp-" in f]
    leaked = [t.name for t in threading.enumerate()
              if t.name.startswith("tpq-prefetch")]
    assert not leaked, leaked
