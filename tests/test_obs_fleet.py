"""ISSUE 20: fleet observability — spool, aggregate, stitch, diagnose.

The contracts under test, in rough order of importance:

- EXACT RECONCILIATION: the aggregated fleet registry equals the
  per-process registries by construction — counters sum, ``_MERGE_MAXED``
  gauges max, histogram buckets add, exemplars survive without
  duplicating or orphaning trace ids;
- the spool is crash-tolerant plumbing: torn/garbage/version-skewed
  files are counted rejections, stale generations are skipped, a failing
  source is a counted drop — none of it ever raises into the data path;
- the doctor names processes: ``straggler`` carries host:pid + dominant
  lane, ``dead-process`` fires on a stale heartbeat, fleet ``slo-burn``
  says which process retained the exemplar;
- request traces stitch across OS-process seams (``trace_context`` →
  ``TPQ_TRACE_CONTEXT`` → ``adopt_context``), and the CLI renders one
  multi-pid tree from the spool alone;
- the real entry points (ScanService / DataLoader / write_sharded)
  auto-arm a spool member when ``TPQ_OBS_SPOOL`` is set and leak no
  threads after close;
- the whole seam holds across three real OS processes (the e2e at the
  bottom).
"""

import io
import json
import os
import subprocess
import sys
import textwrap
import threading
import time

import pytest

sys.path.insert(0, os.path.dirname(__file__))

from test_serve import _write_file  # noqa: E402

from tpu_parquet.cli import pq_tool  # noqa: E402
from tpu_parquet.obs import (LatencyHistogram, RequestTrace,  # noqa: E402
                             StatsRegistry, current_request_trace,
                             set_request_trace)
from tpu_parquet.obs_fleet import (FleetAggregator, SpoolWriter,  # noqa: E402
                                   ambient_request_trace, doctor_fleet,
                                   process_lanes, render_fleet_openmetrics,
                                   stitch_traces)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_tool(args):
    out = io.StringIO()
    parsed = pq_tool.build_parser().parse_args(args)
    rc = parsed.func(parsed, out=out)
    return rc, out.getvalue()


def _member(spool, host, pid, role="serve", registry=None, **kw):
    """A manually-driven (huge interval) spool member for one fake
    process; publish via ``publish_once``."""
    reg = registry if registry is not None else StatsRegistry()
    w = SpoolWriter(reg, role=role, spool_dir=str(spool), interval_s=999.0,
                    keep=kw.pop("keep", 4), host=host, pid=pid, **kw)
    return reg, w


def _spool_threads():
    return [t.name for t in threading.enumerate()
            if t.name.startswith("tpq-spool")]


# ---------------------------------------------------------------------------
# SpoolWriter
# ---------------------------------------------------------------------------

def test_spool_disabled_without_env(monkeypatch):
    monkeypatch.delenv("TPQ_OBS_SPOOL", raising=False)
    w = SpoolWriter(StatsRegistry(), role="serve")
    assert not w.enabled
    assert w.start() is w and w._thread is None  # start is a no-op
    assert w.publish_once() is None
    w.stop()
    assert w.written == 0 and w.dropped == 0


def test_spool_publish_prune_heartbeat_seq(tmp_path):
    reg, w = _member(tmp_path, "nodeA", 101, keep=2)
    reg.add_write({"rows": 7})
    paths = [w.publish_once() for _ in range(5)]
    assert all(p is not None for p in paths)
    files = sorted(os.listdir(tmp_path))
    # pruned down to keep=2, newest generations survive
    assert files == ["nodeA-101-serve.00000004.json",
                     "nodeA-101-serve.00000005.json"]
    docs = [json.load(open(tmp_path / f)) for f in files]
    assert [d["seq"] for d in docs] == [4, 5]
    assert docs[0]["heartbeat_ts"] <= docs[1]["heartbeat_ts"]  # monotonic
    d = docs[-1]
    assert d["spool_version"] == 1 and d["host"] == "nodeA" \
        and d["pid"] == 101 and d["role"] == "serve" \
        and d["registry"]["write"]["rows"] == 7 and d["traces"] == []
    assert w.written == 5 and w.dropped == 0


def test_spool_failing_source_counts_never_raises(tmp_path):
    def boom():
        raise RuntimeError("registry exploded")

    w = SpoolWriter(boom, role="serve", spool_dir=str(tmp_path),
                    interval_s=999.0)
    assert w.publish_once() is None  # no raise
    assert w.dropped == 1 and w.written == 0


def test_spool_thread_lifecycle_publishes_final_generation(tmp_path):
    reg, _ = _member(tmp_path, "x", 1)
    w = SpoolWriter(reg, role="loader", spool_dir=str(tmp_path),
                    interval_s=60.0, host="x", pid=1)
    w.start()
    assert _spool_threads() == ["tpq-spool-loader"]
    w.stop()  # publishes the final generation on the way out
    assert _spool_threads() == []
    assert any(f.startswith("x-1-loader.") for f in os.listdir(tmp_path))


# ---------------------------------------------------------------------------
# FleetAggregator: exact reconciliation + rejection accounting
# ---------------------------------------------------------------------------

def test_aggregate_reconciles_exactly(tmp_path):
    rows, workers, hist_n = [], [], 0
    for i, (host, pid) in enumerate([("h0", 1), ("h0", 2), ("h1", 3)]):
        reg, w = _member(tmp_path, host, pid, role="writer")
        reg.add_write({"rows": 100 * (i + 1), "workers": i + 1})
        rows.append(100 * (i + 1))
        workers.append(i + 1)
        for j in range(i + 1):
            reg.histogram("serve.request").record(
                1e-3 * (j + 1), exemplar=f"t-{host}-{pid}-{j}")
            hist_n += 1
        assert w.publish_once() is not None
    snap = FleetAggregator(spool_dir=str(tmp_path)).scan()
    assert snap["fleet_version"] == 1
    assert snap["rejected"] == 0 and snap["stale_skipped"] == 0
    assert snap["files_scanned"] == 3
    assert sorted(snap["processes"]) == ["h0:1", "h0:2", "h1:3"]
    assert all(p["role"] == "writer" and not p["stale"]
               for p in snap["processes"].values())
    merged = snap["registry"]
    # counters reconcile EXACTLY: flows sum, gauges max
    assert merged["write"]["rows"] == sum(rows)
    assert merged["write"]["workers"] == max(workers)
    hist = merged["histograms"]["serve.request"]
    assert hist["count"] == hist_n


def test_aggregate_rejects_garbage_and_skips_stale_generations(tmp_path):
    reg, w = _member(tmp_path, "h", 1, keep=4)
    reg.add_write({"rows": 5})
    w.publish_once()
    reg.add_write({"rows": 5})
    w.publish_once()  # gen 2 supersedes gen 1
    (tmp_path / "zz-torn.json").write_bytes(b'{"spool_version": 1, "ho')
    (tmp_path / "zz-list.json").write_text("[1, 2, 3]\n")
    (tmp_path / "zz-skew.json").write_text(json.dumps(
        {"spool_version": 999, "host": "h", "pid": 9, "seq": 1,
         "heartbeat_ts": time.time(), "registry": {}}))
    (tmp_path / "notes.txt").write_text("not a spool file\n")  # ignored
    snap = FleetAggregator(spool_dir=str(tmp_path)).scan()
    assert snap["files_scanned"] == 5  # the .txt never counts
    assert snap["rejected"] == 3
    assert snap["stale_skipped"] == 1
    assert list(snap["processes"]) == ["h:1"]
    # only the NEWEST generation counted — no double-merge
    assert snap["registry"]["write"]["rows"] == 10
    assert snap["processes"]["h:1"]["seq"] == 2


def test_aggregate_missing_spool_is_empty_never_raises(tmp_path):
    snap = FleetAggregator(spool_dir=str(tmp_path / "nope")).scan()
    assert snap["processes"] == {} and snap["files_scanned"] == 0


# ---------------------------------------------------------------------------
# exemplars survive the spool → merge_dict round-trip (satellite 4)
# ---------------------------------------------------------------------------

def test_exemplars_survive_spool_merge_roundtrip(tmp_path):
    want = {}  # trace_id -> raw seconds
    for i, (host, pid) in enumerate([("h0", 1), ("h1", 2)]):
        reg, w = _member(tmp_path, host, pid)
        for j in range(3):
            s = (2.0 ** (8 * i + 2 * j)) / 1e6  # distinct buckets per member
            tid = f"t-{host}-{j}"
            reg.histogram("serve.request").record(s, exemplar=tid)
            want[tid] = s
        w.publish_once()
    snap = FleetAggregator(spool_dir=str(tmp_path)).scan()
    hd = snap["registry"]["histograms"]["serve.request"]
    got = {ex[0]: ex[1] for ex in (hd.get("exemplars") or {}).values()}
    # no duplicated ids (one exemplar per bucket, distinct buckets here),
    # no orphans (every retained id is one we recorded), and each raw
    # value re-derives the bucket it was filed under
    assert set(got) == set(want)
    for idx, ex in hd["exemplars"].items():
        assert LatencyHistogram.bucket_index(float(ex[1])) == int(idx)
        assert abs(got[ex[0]] - want[ex[0]]) < 1e-12
    # a second merge hop (fleet snapshot folded again) keeps them intact
    reg2 = StatsRegistry()
    reg2.merge_dict(snap["registry"])
    hd2 = reg2.as_dict()["histograms"]["serve.request"]
    assert hd2["exemplars"] == hd["exemplars"]
    assert hd2["count"] == hd["count"] == 6


# ---------------------------------------------------------------------------
# doctor: straggler / dead-process / fleet slo-burn
# ---------------------------------------------------------------------------

def _fleet_with_straggler(tmp_path, slow=10.0):
    for i, (host, pid) in enumerate([("h0", 1), ("h0", 2), ("h1", 3)]):
        reg, w = _member(tmp_path, host, pid, role="writer")
        reg.add_write({"rows": 10,
                       "encode_seconds": slow if i == 2 else 1.0})
        w.publish_once()
    return FleetAggregator(spool_dir=str(tmp_path)).scan()


def test_straggler_names_process_and_dominant_lane(tmp_path):
    snap = _fleet_with_straggler(tmp_path)
    rep = doctor_fleet(snap)
    blocks = [b for b in rep["verdicts"] if b["verdict"] == "straggler"]
    assert len(blocks) == 1, rep["verdicts"]
    b = blocks[0]
    assert b["process"] == "h1:3" and b["role"] == "writer"
    assert b["dominant_lane"] == "write_encode"
    assert b["deviation"] > 1.0  # ~10x the fleet median
    assert "h1:3" in b["advice"] or "write_encode" in b["advice"]


def test_no_straggler_below_min_procs_or_band(tmp_path):
    # two members only: below STRAGGLER_MIN_PROCS, never fires
    for i, pid in enumerate([1, 2]):
        reg, w = _member(tmp_path, "h", pid)
        reg.add_write({"encode_seconds": 10.0 if i else 1.0})
        w.publish_once()
    snap = FleetAggregator(spool_dir=str(tmp_path)).scan()
    rep = doctor_fleet(snap)
    verdicts = (rep or {}).get("verdicts") or []
    assert not [b for b in verdicts if b["verdict"] == "straggler"]
    # and a flat fleet (3 equal members) stays quiet too
    for f in os.listdir(tmp_path):
        os.remove(tmp_path / f)
    snap = _fleet_with_straggler(tmp_path, slow=1.0)
    rep = doctor_fleet(snap)
    verdicts = (rep or {}).get("verdicts") or []
    assert not [b for b in verdicts if b["verdict"] == "straggler"]


def test_dead_process_fires_on_stale_heartbeat(tmp_path):
    reg, w = _member(tmp_path, "live", 1)
    reg.add_write({"rows": 1})
    w.publish_once()
    dead = {"spool_version": 1, "host": "gone", "pid": 9, "role": "loader",
            "seq": 3, "heartbeat_ts": time.time() - 3600,
            "registry": StatsRegistry().as_dict(), "traces": []}
    (tmp_path / "gone-9.00000003.json").write_text(json.dumps(dead))
    snap = FleetAggregator(spool_dir=str(tmp_path), stale_s=5.0).scan()
    assert snap["processes"]["gone:9"]["stale"]
    assert not snap["processes"]["live:1"]["stale"]
    rep = doctor_fleet(snap)
    blocks = [b for b in rep["verdicts"] if b["verdict"] == "dead-process"]
    assert len(blocks) == 1
    b = blocks[0]
    assert b["process"] == "gone:9" and b["role"] == "loader"
    assert b["heartbeat_age_s"] > 3000 and b["stale_after_s"] == 5.0


def test_scan_now_override_ages_every_heartbeat(tmp_path):
    reg, w = _member(tmp_path, "h", 1)
    w.publish_once()
    agg = FleetAggregator(spool_dir=str(tmp_path), stale_s=10.0)
    assert not agg.scan()["processes"]["h:1"]["stale"]
    assert agg.scan(now=time.time() + 100)["processes"]["h:1"]["stale"]


def test_fleet_slo_burn_names_exemplar_owner(tmp_path):
    reg, w = _member(tmp_path, "h0", 1)
    reg.add_serve({"tenants": {"gold": {"weight": 2, "slo_p99_ms": 1.0}},
                   "submitted": 5, "done": 5})
    reg.histogram("serve.tenant.gold").record(0.05, exemplar="t-gold-slow")
    w.publish_once()
    reg2, w2 = _member(tmp_path, "h1", 2)  # innocent bystander
    reg2.add_write({"rows": 1})
    w2.publish_once()
    snap = FleetAggregator(spool_dir=str(tmp_path)).scan()
    rep = doctor_fleet(snap)
    blocks = [b for b in rep["verdicts"] if b["verdict"] == "slo-burn"]
    assert len(blocks) == 1, rep["verdicts"]
    b = blocks[0]
    assert b["tenant"] == "gold" and b["exemplar_trace"] == "t-gold-slow"
    # the fleet doctor says WHICH process retained the evidence
    assert b["exemplar_process"] == "h0:1"
    assert "h0:1" in b["advice"]


def test_process_lanes_cover_read_and_write_sides():
    lanes = process_lanes({
        "pipeline": {"stage_seconds": 2.0, "io_seconds": 1.0,
                     "decompress_seconds": 0.5, "stall_seconds": 0.25},
        "write": {"encode_seconds": 3.0, "flush_seconds": 1.5},
        "serve": {"queue_wait_seconds": 0.75},
    })
    assert lanes["link"] == 2.0
    assert lanes["host_decompress"] == 1.5  # io + decompress
    assert lanes["stall"] == 0.25
    assert lanes["write_encode"] == 3.0 and lanes["write_flush"] == 1.5
    assert lanes["admission"] == 0.75


# ---------------------------------------------------------------------------
# OpenMetrics rendering
# ---------------------------------------------------------------------------

def test_render_fleet_openmetrics_labels_and_exemplars(tmp_path):
    reg, w = _member(tmp_path, "nodeA", 101, role="serve")
    reg.add_write({"rows": 9})
    reg.histogram("serve.request").record(0.002, exemplar="t-om-1")
    w.publish_once()
    text = render_fleet_openmetrics(
        FleetAggregator(spool_dir=str(tmp_path)).scan())
    assert text.endswith("# EOF\n")
    labels = 'host="nodeA",pid="101",role="serve"'
    assert f"tpq_write_rows{{{labels}}} 9" in text
    assert f"tpq_fleet_heartbeat_age_seconds{{{labels}}}" in text
    assert 'trace_id="t-om-1"' in text  # exemplar rides the bucket line
    assert f"tpq_serve_request_seconds_count{{{labels}}} 1" in text


# ---------------------------------------------------------------------------
# cross-process trace stitching
# ---------------------------------------------------------------------------

def test_trace_context_roundtrip_and_validation():
    tr = RequestTrace(trace_id="req-parent")
    ctx = tr.trace_context()
    assert ctx["trace_id"] == "req-parent" and ctx["pid"] == os.getpid()
    child = RequestTrace.adopt_context(ctx)
    assert child.trace_id != tr.trace_id  # ids stay process-unique
    assert child.origin["trace_id"] == "req-parent"
    assert child.origin["pid"] == os.getpid()
    with pytest.raises(ValueError):
        RequestTrace.adopt_context("not a dict")
    with pytest.raises(ValueError):
        RequestTrace.adopt_context({"host": "h"})  # no trace_id


def test_stitch_traces_dedups_and_sorts_children():
    root = {"trace_id": "R", "spans": []}
    mk = lambda tid, host, pid: {"trace_id": tid, "host": host, "pid": pid,
                                 "origin": {"trace_id": "R"}, "spans": []}
    docs = [root, mk("c2", "h1", 7), mk("c1", "h0", 3),
            mk("c1", "h0", 3),               # republished generation
            {"trace_id": "other", "origin": {"trace_id": "X"}}]
    st = stitch_traces(docs, "R")
    assert st["root"] is root
    assert [c["trace_id"] for c in st["children"]] == ["c1", "c2"]
    # children with no root still stitch (the parent process may not spool)
    st = stitch_traces(docs[1:], "R")
    assert st["root"] is None and len(st["children"]) == 2
    assert stitch_traces(docs, "nope") is None


def test_ambient_request_trace_adopts_env(monkeypatch):
    set_request_trace(None)
    try:
        monkeypatch.delenv("TPQ_TRACE_CONTEXT", raising=False)
        assert ambient_request_trace() is None
        parent = RequestTrace(trace_id="req-env")
        monkeypatch.setenv("TPQ_TRACE_CONTEXT",
                           json.dumps(parent.trace_context()))
        tr = ambient_request_trace()
        assert tr is not None and tr.origin["trace_id"] == "req-env"
        # installed thread-locally: nested code finds the SAME trace
        assert current_request_trace() is tr
        assert ambient_request_trace() is tr
        # a live thread-local trace beats the env blob
        set_request_trace(None)
        mine = RequestTrace(trace_id="req-mine")
        set_request_trace(mine)
        assert ambient_request_trace() is mine
    finally:
        set_request_trace(None)


def test_ambient_request_trace_malformed_env_degrades(monkeypatch):
    set_request_trace(None)
    try:
        monkeypatch.setenv("TPQ_TRACE_CONTEXT", "{not json")
        assert ambient_request_trace() is None  # warn_env_once, no raise
        monkeypatch.setenv("TPQ_TRACE_CONTEXT", '{"host": "h"}')
        assert ambient_request_trace() is None  # valid JSON, invalid blob
    finally:
        set_request_trace(None)


# ---------------------------------------------------------------------------
# CLI: pq_tool top / trace --request --spool
# ---------------------------------------------------------------------------

def _three_member_spool(tmp_path):
    for i, (pid, role) in enumerate([(101, "serve"), (102, "loader"),
                                     (103, "writer")]):
        reg, w = _member(tmp_path, "nodeA", pid, role=role)
        reg.add_write({"rows": 10 * (i + 1), "encode_seconds": 0.1})
        w.publish_once()


def test_top_once_golden(tmp_path):
    _three_member_spool(tmp_path)
    rc, out = run_tool(["top", str(tmp_path), "--once"])
    assert rc == 0, out
    assert "fleet top" in out and "3 process(es)" in out
    for pid, role in [(101, "serve"), (102, "loader"), (103, "writer")]:
        assert f"nodeA:{pid}" in out and role in out
    assert "verdicts: none" in out


def test_top_once_renders_verdicts(tmp_path):
    _fleet_with_straggler(tmp_path)
    rc, out = run_tool(["top", str(tmp_path), "--once"])
    assert rc == 0
    assert "straggler" in out and "h1:3" in out and "write_encode" in out


def test_top_empty_spool_rc1(tmp_path):
    rc, out = run_tool(["top", str(tmp_path), "--once"])
    assert rc == 1 and "no spool members" in out


def test_metrics_spool_renders_fleet_exposition(tmp_path):
    _three_member_spool(tmp_path)
    rc, out = run_tool(["metrics", "--spool", str(tmp_path)])
    assert rc == 0
    assert 'tpq_write_rows{host="nodeA",pid="101",role="serve"} 10' in out
    assert out.rstrip().endswith("# EOF")
    rc, out = run_tool(["metrics", "--spool", str(tmp_path / "empty")])
    assert rc == 1 and "no spool members" in out
    rc, out = run_tool(["metrics"])
    assert rc == 2 and "FILE is required" in out


def test_trace_without_file_or_spool_errors():
    rc, out = run_tool(["trace"])
    assert rc == 2 and "FILE is required" in out
    rc, out = run_tool(["trace", "--request", "abc"])
    assert rc == 1 and "--spool" in out


def test_trace_request_stitches_from_spool(tmp_path):
    parent = RequestTrace(trace_id="req-stitch01")
    with parent.span("plan"):
        pass
    parent.finish()
    child = RequestTrace.adopt_context(parent.trace_context())
    with child.span("child-decode", unit=3):
        pass
    child.finish()
    cdoc = child.as_dict()
    cdoc["host"], cdoc["pid"] = "workerbox", 4242  # a remote process's doc
    _, w1 = _member(tmp_path, "h0", 1,
                    sampler=lambda: [parent.as_dict()])
    w1.publish_once()
    _, w2 = _member(tmp_path, "workerbox", 4242, role="loader",
                    sampler=lambda: [cdoc])
    w2.publish_once()
    rc, out = run_tool(["trace", "--request", "req-stitch",
                        "--spool", str(tmp_path)])
    assert rc == 0, out
    assert "req-stitch01" in out and "plan" in out
    assert "child [workerbox:4242]" in out and "child-decode" in out


# ---------------------------------------------------------------------------
# tenancy: shared tenants.json (satellite 1)
# ---------------------------------------------------------------------------

def test_tenant_file_spec_and_from_file(tmp_path):
    from tpu_parquet.serve.tenancy import TenantRegistry, tenant_table

    p = tmp_path / "tenants.json"
    p.write_text(json.dumps({
        "gold": {"weight": 3, "deadline_s": 2.5, "slo_p99_ms": 50},
        "bronze": 1,                       # bare-number weight form
        "weird": {"weight": -4},           # floored to 1
        "": {"weight": 9},                 # nameless: dropped
        "bool": True,                      # malformed entry: dropped
    }))
    table = tenant_table(f"@{p}")
    assert table["gold"] == {"weight": 3, "deadline_s": 2.5,
                             "slo_p99_ms": 50.0}
    assert table["bronze"]["weight"] == 1
    assert table["weird"]["weight"] == 1
    assert set(table) == {"gold", "bronze", "weird"}
    regy = TenantRegistry.from_file(str(p))
    t = regy.get("gold")
    assert t is not None and t.weight == 3 and t.slo_p99_ms == 50.0


def test_tenant_file_malformed_degrades(tmp_path):
    from tpu_parquet.serve.tenancy import tenant_table

    p = tmp_path / "tenants.json"
    p.write_text("{broken json")
    assert tenant_table(f"@{p}") == {}          # warn_env_once, no raise
    assert tenant_table(f"@{tmp_path}/missing.json") == {}
    p.write_text("[1, 2]")                       # not an object
    assert tenant_table(f"@{p}") == {}


# ---------------------------------------------------------------------------
# stream-aware fair scheduling (satellite 2)
# ---------------------------------------------------------------------------

def _yield_service(tmp_path, stream_yield):
    from tpu_parquet.serve import ScanService

    svc = ScanService(concurrency=1, queue_depth=64, fair=True,
                      result_cache_mb=0, stream_yield=stream_yield)
    svc.register_tenant("victim", weight=2)
    svc.register_tenant("noisy", weight=1)
    return svc


@pytest.mark.parametrize("stream_yield", [True, False])
def test_stream_yields_slot_between_batches(tmp_path, stream_yield):
    from tpu_parquet.serve import ScanRequest

    path = str(tmp_path / "f.parquet")
    _write_file(path, seed=3, groups=8, rows=800)
    svc = _yield_service(tmp_path, stream_yield)
    try:
        session = svc.scan(ScanRequest(path, columns=["a"], tenant="noisy",
                                       stream=True, batch_rows=100),
                           timeout=60)
        rows = 0
        victims = []
        for i, batch in enumerate(session):
            rows += len(batch["a"])
            # keep another tenant visibly waiting while the stream runs
            if i < 8:
                victims.append(svc.submit(
                    ScanRequest(path, columns=["a"], tenant="victim")))
        assert rows == 8 * 800
        for t in victims:
            got = t.result(60)[path]["a"]
            assert got.num_leaf_slots == 8 * 800
        stats = svc.serve_stats()
        if stream_yield:
            assert stats["stream_yields"] > 0
        else:
            assert stats["stream_yields"] == 0
    finally:
        svc.close()


# ---------------------------------------------------------------------------
# auto-armed spool members at the real entry points
# ---------------------------------------------------------------------------

def _roles_in(spool):
    roles = set()
    for fn in os.listdir(spool):
        if fn.endswith(".json"):
            roles.add(json.load(open(os.path.join(spool, fn)))["role"])
    return roles


def test_scan_service_auto_arms_spool(tmp_path, monkeypatch):
    from tpu_parquet.serve import ScanRequest, ScanService

    spool = tmp_path / "spool"
    monkeypatch.setenv("TPQ_OBS_SPOOL", str(spool))
    monkeypatch.setenv("TPQ_OBS_SPOOL_S", "60")  # stop() publishes anyway
    path = str(tmp_path / "f.parquet")
    _write_file(path, seed=1, groups=2, rows=300)
    svc = ScanService(concurrency=1, result_cache_mb=0)
    try:
        svc.scan(ScanRequest(path, columns=["a"]), timeout=60)
    finally:
        svc.close()
    assert _spool_threads() == []  # no leak after close
    assert _roles_in(spool) == {"serve"}
    snap = FleetAggregator(spool_dir=str(spool)).scan()
    assert snap["registry"]["serve"]["submitted"] >= 1


def test_loader_and_writer_auto_arm_spool(tmp_path, monkeypatch):
    import numpy as np

    from tpu_parquet.column import ByteArrayData, ColumnData
    from tpu_parquet.data import DataLoader
    from tpu_parquet.format import FieldRepetitionType as FRT, Type
    from tpu_parquet.schema.core import build_schema, data_column
    from tpu_parquet.write import write_sharded

    spool = tmp_path / "spool"
    monkeypatch.setenv("TPQ_OBS_SPOOL", str(spool))
    monkeypatch.setenv("TPQ_OBS_SPOOL_S", "60")
    schema = build_schema([data_column("a", Type.INT64, FRT.REQUIRED)])
    rng = np.random.default_rng(0)
    batches = [{"a": rng.integers(0, 1 << 20, 400)} for _ in range(3)]
    out = str(tmp_path / "data.parquet")
    write_sharded(out, schema, batches, workers=2)
    assert "writer" in _roles_in(spool)
    n = 0
    for batch in DataLoader([out], 300, columns=["a"], shuffle=False):
        n += len(batch["a"])
    assert n == 1200
    assert _roles_in(spool) == {"writer", "loader"}
    assert _spool_threads() == []
    snap = FleetAggregator(spool_dir=str(spool)).scan()
    assert snap["rejected"] == 0
    assert snap["registry"]["write"]["rows"] == 1200
    # one OS process armed two entry points: the roles fold into ONE
    # process entry (neither member's generations clobbered the other's)
    assert len(snap["processes"]) == 1
    (proc,) = snap["processes"].values()
    assert proc["role"] == "loader+writer"
    assert proc["registry"]["write"]["rows"] == 1200
    assert proc["registry"]["loader"]["batches"] == 4


# ---------------------------------------------------------------------------
# the 3-OS-process end-to-end
# ---------------------------------------------------------------------------

_WORKER_SRC = textwrap.dedent("""
    import json, os, sys, time

    from tpu_parquet.obs import StatsRegistry
    from tpu_parquet.obs_fleet import SpoolWriter, ambient_request_trace

    spool, idx, mode = sys.argv[1], int(sys.argv[2]), sys.argv[3]
    reg = StatsRegistry()
    reg.add_write({"rows": 100 * (idx + 1), "workers": idx + 1,
                   "encode_seconds": 10.0 if mode == "slow" else 1.0})
    reg.histogram("serve.request").record(1e-3 * (idx + 1),
                                          exemplar="t-w%d" % idx)
    tr = ambient_request_trace()  # adopts TPQ_TRACE_CONTEXT
    if tr is not None:
        with tr.span("child-work", idx=idx):
            pass
        tr.finish()
    w = SpoolWriter(reg, role="loader", spool_dir=spool, interval_s=999.0,
                    sampler=lambda: [tr.as_dict()] if tr else [])
    if mode == "dead":
        w.publish_once()
        print(json.dumps({"pid": os.getpid(), "host": w.host}), flush=True)
        time.sleep(600)  # parent kills us; our heartbeat goes stale
        sys.exit(0)
    print(json.dumps({"pid": os.getpid(), "host": w.host}), flush=True)
    sys.stdin.readline()  # parent's go signal: publish a FRESH heartbeat
    path = w.publish_once()
    assert path is not None
    sys.exit(0)
""")


def test_three_process_fleet_e2e(tmp_path):
    spool = str(tmp_path / "spool")
    script = tmp_path / "worker.py"
    script.write_text(_WORKER_SRC)
    parent = RequestTrace(trace_id="req-e2e-fleet")
    with parent.span("orchestrate"):
        pass
    parent.finish()
    env = dict(os.environ,
               PYTHONPATH=REPO_ROOT,
               TPQ_TRACE_CONTEXT=json.dumps(parent.trace_context()))
    env.pop("TPQ_OBS_SPOOL", None)
    modes = ["live", "live", "slow", "dead"]
    procs, info = [], []
    try:
        for idx, mode in enumerate(modes):
            procs.append(subprocess.Popen(
                [sys.executable, str(script), spool, str(idx), mode],
                env=env, stdin=subprocess.PIPE, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True))
        for p in procs:
            line = p.stdout.readline()
            assert line.strip(), "worker died before publishing"
            info.append(json.loads(line))
        dead_pid, dead_host = info[3]["pid"], info[3]["host"]
        slow_pid, slow_host = info[2]["pid"], info[2]["host"]
        time.sleep(0.9)  # the dead worker's heartbeat ages past stale_s
        procs[3].kill()
        for p in procs[:3]:  # live workers republish fresh heartbeats
            p.stdin.write("go\n")
            p.stdin.flush()
            assert p.wait(timeout=60) == 0, p.stdout.read()
        # the parent process is a fleet member too (role serve)
        preg = StatsRegistry()
        preg.add_serve({"submitted": 1, "done": 1})
        pw = SpoolWriter(preg, role="serve", spool_dir=spool,
                         interval_s=999.0,
                         sampler=lambda: [parent.as_dict()])
        assert pw.publish_once() is not None
    finally:
        for p in procs:
            p.kill()
            if p.stdin:
                p.stdin.close()
            if p.stdout:
                p.stdout.close()
            p.wait(timeout=30)

    snap = FleetAggregator(spool_dir=spool, stale_s=0.5).scan()
    assert snap["rejected"] == 0, snap
    assert len(snap["processes"]) == 5  # 4 workers + the parent

    # exact reconciliation across real OS processes: counters == sum of
    # the per-process registries, gauges == max
    merged = snap["registry"]
    assert merged["write"]["rows"] == 100 + 200 + 300 + 400
    assert merged["write"]["workers"] == 4
    assert merged["histograms"]["serve.request"]["count"] == 4
    assert merged["serve"]["submitted"] == 1

    rep = doctor_fleet(snap)
    verdicts = rep["verdicts"]
    dead = [b for b in verdicts if b["verdict"] == "dead-process"]
    assert [b["process"] for b in dead] == [f"{dead_host}:{dead_pid}"]
    # straggler names the injected-slow process by host:pid + its lane
    strag = [b for b in verdicts if b["verdict"] == "straggler"]
    assert len(strag) == 1, verdicts
    assert strag[0]["process"] == f"{slow_host}:{slow_pid}"
    assert strag[0]["dominant_lane"] == "write_encode"

    # one stitched tree, spans from >= 2 pids, rendered by the CLI
    rc, out = run_tool(["trace", "--request", "req-e2e-fleet",
                        "--spool", spool])
    assert rc == 0, out
    assert "orchestrate" in out and "child-work" in out
    child_pids = {int(ln.split(":")[1].split("]")[0])
                  for ln in out.splitlines() if ln.startswith("  child [")}
    assert len(child_pids) >= 2  # live workers adopted across the seam
    assert os.getpid() not in child_pids

    # and the fleet exposition carries every member's labels
    text = render_fleet_openmetrics(snap)
    assert f'pid="{dead_pid}"' in text and f'pid="{os.getpid()}"' in text
