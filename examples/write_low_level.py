"""Low-level write: schema DSL + row maps → parquet file.

Mirror of the reference's examples/write-low-level/main.go:22-58 — parse a
message schema, write row maps with SNAPPY, close (footer written once).

    python examples/write_low_level.py [output.parquet]
"""

import sys

sys.path.insert(0, __import__("os").path.dirname(__import__("os").path.dirname(__import__("os").path.abspath(__file__))))

from tpu_parquet.format import CompressionCodec
from tpu_parquet.schema.dsl import parse_schema_definition
from tpu_parquet.writer import FileWriter

SCHEMA = parse_schema_definition("""
message test {
    required int64 id;
    required binary city (STRING);
    optional int64 population;
}
""")

CITIES = [
    (1, b"Berlin", 3_520_031),
    (2, b"Hamburg", 1_787_408),
    (3, b"Munich", 1_450_381),
    (4, b"Cologne", 1_060_582),
    (5, b"Frankfurt", 732_688),
]


def main(path: str = "output.parquet") -> None:
    with FileWriter(
        path, SCHEMA, codec=CompressionCodec.SNAPPY, created_by="write-lowlevel"
    ) as w:
        for id_, city, pop in CITIES:
            w.write_row({"id": id_, "city": city, "population": pop})
    print(f"wrote {len(CITIES)} rows to {path}")


if __name__ == "__main__":
    main(sys.argv[1] if len(sys.argv) > 1 else "output.parquet")
