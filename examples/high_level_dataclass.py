"""High-level object API: dataclasses in, dataclasses out.

Mirror of the reference's examples/high-level-reflection/main.go — the floor
layer marshals typed records (reflection there, dataclass fields here) and
scans them back.

    python examples/high_level_dataclass.py [output.parquet]
"""

import sys

sys.path.insert(0, __import__("os").path.dirname(__import__("os").path.dirname(__import__("os").path.abspath(__file__))))
from dataclasses import dataclass

from tpu_parquet import floor
from tpu_parquet.schema.dsl import parse_schema_definition

SCHEMA = parse_schema_definition("""
message record {
    required binary name (STRING);
    optional binary data;
    required double score;
}
""")


@dataclass
class Record:
    name: str
    data: bytes
    score: float


def main(path: str = "output.parquet") -> None:
    rows = [
        Record(name="Test", data=bytes([0xFF, 0x0A, 0x8E, 0x00, 0x12]), score=23.5),
        Record(name="Second", data=b"", score=-1.5),
    ]
    with floor.Writer(path, SCHEMA) as w:
        w.write_many(rows)
    with floor.Reader(path, Record) as r:
        for rec in r.scan_all(Record):
            print(rec)


if __name__ == "__main__":
    main(sys.argv[1] if len(sys.argv) > 1 else "output.parquet")
