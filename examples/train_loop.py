"""Training loop over the checkpointable input pipeline.

The product story of tpu_parquet.data in one file: a parquet dataset becomes
shuffled, sharded, resumable device batches feeding a jitted SGD step — fixed
shapes, one compile — and the input position checkpoints alongside the model
(save mid-epoch, restore, and the remaining batches are bit-identical to the
uninterrupted run).

    python examples/train_loop.py [file.parquet]
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import jax
import jax.numpy as jnp

from tpu_parquet.data import DataLoader

BATCH = 1024
FEATURES = [f"f{j}" for j in range(8)]


def write_demo(path: str) -> None:
    """A linear-regression dataset: 8 float features, 1 label, 6 row groups."""
    from tpu_parquet.format import FieldRepetitionType as FRT, Type
    from tpu_parquet.schema.core import build_schema, data_column
    from tpu_parquet.writer import FileWriter

    rng = np.random.default_rng(0)
    schema = build_schema(
        [data_column(f, Type.FLOAT, FRT.REQUIRED) for f in FEATURES]
        + [data_column("label", Type.FLOAT, FRT.REQUIRED)]
    )
    w_true = np.arange(1, 9, dtype=np.float32)
    with FileWriter(path, schema) as w:
        for _ in range(6):
            n = int(rng.integers(4_000, 7_000))
            x = rng.normal(size=(n, 8)).astype(np.float32)
            y = x @ w_true + 0.01 * rng.normal(size=n).astype(np.float32)
            w.write_columns({**{f: x[:, j] for j, f in enumerate(FEATURES)},
                             "label": y})
            w.flush_row_group()


@jax.jit
def train_step(w, feats, label, mask):
    """One masked SGD step: the pad rows of the epoch's ragged tail carry
    mask=False and contribute zero gradient."""

    def loss(w):
        err = (feats @ w - label) * mask
        return jnp.sum(err * err) / jnp.maximum(jnp.sum(mask), 1.0)

    return w - 0.1 * jax.grad(loss)(w)


def run_epoch(w, loader):
    for batch in loader:  # device-resident, fixed shapes: one executable
        feats = jnp.stack([batch[f] for f in FEATURES], axis=1)
        w = train_step(w, feats, batch["label"],
                       batch["mask"].astype(jnp.float32))
    return w


def main(path: str) -> None:
    loader = DataLoader(
        path, BATCH,
        columns=FEATURES + ["label"],
        shuffle=True, seed=42,
        prefetch=2,          # decode overlaps the train step's host time
        to_device=True,      # batches land as jax arrays
        # on a multi-host job: shard=tpu_parquet.parallel.process_shard()
    )
    w = jnp.zeros(8, dtype=jnp.float32)
    w = run_epoch(w, loader)  # epoch 0

    # mid-epoch checkpoint: save the input position with the model, restore
    # into a FRESH loader, and training continues exactly where it left off
    it = iter(loader)
    for _ in range(loader.num_batches // 2):
        batch = next(it)
        feats = jnp.stack([batch[f] for f in FEATURES], axis=1)
        w = train_step(w, feats, batch["label"],
                       batch["mask"].astype(jnp.float32))
    it.close()
    blob = loader.state_blob()  # ~300 bytes, versioned, validated on load
    print(f"checkpointed at epoch {loader.epoch}, "
          f"{loader.state()['rows_taken']} rows in ({len(blob)} B blob)")

    resumed = DataLoader(path, BATCH, columns=FEATURES + ["label"],
                         shuffle=True, prefetch=2, to_device=True,
                         ).restore(blob)
    w = run_epoch(w, resumed)  # the rest of epoch 1

    print(f"learned weights: {np.round(np.asarray(w), 2)}")
    print(f"loader stats: {resumed.stats().as_dict()}")


if __name__ == "__main__":
    if len(sys.argv) == 2:
        main(sys.argv[1])
    else:
        demo = "/tmp/train_demo.parquet"
        write_demo(demo)
        main(demo)
