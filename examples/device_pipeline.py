"""TPU input pipeline: decode a file straight into device-resident columns.

The framework's reason to exist (no reference counterpart — this replaces
the row-by-row scan with columns living in HBM): open → per row group,
host decompress/parse overlapped with one staged transfer → XLA kernels →
jax Arrays, ready to feed a jitted training step without further copies.

    python examples/device_pipeline.py [file.parquet]
"""

import sys

sys.path.insert(0, __import__("os").path.dirname(__import__("os").path.dirname(__import__("os").path.abspath(__file__))))

import jax

from tpu_parquet.device_reader import DeviceFileReader


def main(path: str) -> None:
    with DeviceFileReader(path) as r:
        for i, cols in enumerate(r.iter_row_groups()):
            arrs = {
                name: next(
                    a for a in (c.values, getattr(c, "indices", None),
                                c.offsets, c.def_levels)
                    if a is not None
                )
                for name, c in cols.items()
            }
            jax.block_until_ready(jax.tree.leaves(arrs))
            print(f"row group {i}: " + ", ".join(
                f"{k}={getattr(v, 'shape', type(v).__name__)}"
                for k, v in arrs.items()))
        st = r.stats()
        print(f"decoded {st.rows} rows at {st.rows_per_sec/1e6:.1f} M rows/s "
              f"({st.bytes_per_sec/1e6:.0f} MB/s compressed, "
              f"{st.staged_bytes/1e6:.0f} MB staged to HBM)")


if __name__ == "__main__":
    if len(sys.argv) != 2:
        # self-demo: write a small file first
        import examples.write_low_level as wl

        wl.main("/tmp/example.parquet")
        main("/tmp/example.parquet")
    else:
        main(sys.argv[1])
