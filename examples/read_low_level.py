"""Low-level read: open a file, print its schema and every row.

Mirror of the reference's examples/read-low-level/main.go:27-63 — iterate
``FileReader.iter_rows()`` (NextRow parity) and print each record's fields.

    python examples/read_low_level.py file1.parquet [file2.parquet ...]
"""

import sys

sys.path.insert(0, __import__("os").path.dirname(__import__("os").path.dirname(__import__("os").path.abspath(__file__))))

from tpu_parquet.reader import FileReader
from tpu_parquet.schema.dsl import schema_to_string


def print_file(path: str) -> None:
    with FileReader(path) as r:
        print(f"Printing file {path}")
        print(f"Schema: {schema_to_string(r.schema)}")
        count = 0
        for count, row in enumerate(r.iter_rows(), start=1):
            print(f"Record {count - 1}:")
            for k, v in row.items():
                if isinstance(v, bytes):
                    v = v.decode("utf-8", errors="replace")
                print(f"\t{k} = {v}")
        print(f"End of file {path} ({count} records)")


if __name__ == "__main__":
    if len(sys.argv) < 2:
        sys.exit(f"usage: {sys.argv[0]} file.parquet [...]")
    for f in sys.argv[1:]:
        print_file(f)
