"""Builder-style rows + predicate-pushdown scan (no dataclass needed).

Two API surfaces with no direct reference example but full reference
parity: the floor builder (floor/interfaces/marshaller.go MarshalObject
shapes — schema-guided nested row construction without defining a class)
and statistics-based pushdown (`row_filter=` prunes row groups from chunk
stats and whole-page runs from page stats before anything decompresses).

    python examples/builder_and_filter.py [dir]
"""

import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tpu_parquet.floor.builder import RowBuilder, RowView
from tpu_parquet.predicate import col
from tpu_parquet.reader import FileReader
from tpu_parquet.schema.dsl import parse_schema_definition
from tpu_parquet.writer import FileWriter

SCHEMA = """message order {
  required int64 order_id;
  required group customer {
    required binary name (STRING);
  }
  optional group items (LIST) {
    repeated group list {
      required binary element (STRING);
    }
  }
}"""


def main(outdir: str) -> None:
    schema = parse_schema_definition(SCHEMA)
    path = os.path.join(outdir, "orders.parquet")

    # -- build rows programmatically, guided by the schema ------------------
    with FileWriter(path, schema, codec=1, row_group_size=1 << 14) as w:
        for i in range(10_000):
            b = RowBuilder(schema.root)
            b.field("order_id").set(i)
            b.field("customer").group().field("name").set(f"cust-{i % 97}".encode())
            items = b.field("items").list()
            for j in range(i % 3):
                items.add().set(f"sku-{j}".encode())
            w.write_row(b.data)

    # -- filtered scan: row groups AND whole pages the predicate provably
    #    cannot match are skipped before decompression ----------------------
    pred = (col("order_id") >= 9_000) & (col("order_id") < 9_010)
    hits = []
    with FileReader(path, row_filter=pred) as r:
        for row in r.iter_rows():
            v = RowView(row, schema.root)
            if 9_000 <= v.field("order_id").int64() < 9_010:  # exact re-filter
                hits.append((
                    v.field("order_id").int64(),
                    v.field("customer").group().field("name").bytes(),
                    [e.bytes() for e in v.field("items").list()],
                ))
    print(f"matched {len(hits)} rows; first: {hits[0]}")
    assert [h[0] for h in hits] == list(range(9_000, 9_010))


if __name__ == "__main__":
    with tempfile.TemporaryDirectory() as d:
        main(sys.argv[1] if len(sys.argv) > 1 else d)
