"""Shared data model for the cross-implementation compatibility harness.

Mirrors the role of the reference's compatibility/data_model.go: one schema +
one JSON-serializable sample dataset that ``build.py`` writes to parquet and
``compare.py`` (plus the parquet-mr / pyarrow cross-readers) verify byte-for-
byte at the value level.  The shapes deliberately cover the surface the
reference's harness exercises (compatibility/data_model.go:13-42): flat
strings/ints/bool/doubles, a nested group, LIST of strings, LIST of int32,
and a repeated group of structs — the sample data itself is generated here
(deterministic seed), not copied from anywhere.
"""

from __future__ import annotations

import json
import random
import string

SCHEMA_TEXT = """message sample {
  required binary id (STRING);
  required int64 index;
  required binary guid (STRING);
  required boolean is_active;
  required binary balance (STRING);
  required int32 age;
  required binary eye_color (STRING);
  required group name {
    required binary first (STRING);
    required binary last (STRING);
  }
  required binary company (STRING);
  required binary email (STRING);
  required double latitude;
  required double longitude;
  repeated binary tags (STRING);
  repeated int32 range;
  repeated group friends {
    required int32 id;
    required binary name (STRING);
  }
  required binary greeting (STRING);
  required binary favorite_fruit (STRING);
}"""

_FRUIT = ["apple", "banana", "strawberry"]
_COLORS = ["blue", "brown", "green"]


def _word(rng: random.Random, n: int) -> str:
    return "".join(rng.choice(string.ascii_lowercase) for _ in range(n))


def generate(n: int = 500, seed: int = 7) -> list[dict]:
    """Deterministic sample rows, JSON-representable (strings, not bytes)."""
    rng = random.Random(seed)
    rows = []
    for i in range(n):
        rows.append({
            "id": "".join(rng.choice("0123456789abcdef") for _ in range(24)),
            "index": i,
            "guid": "-".join(
                _word(rng, k) for k in (8, 4, 4, 4, 12)
            ),
            "is_active": rng.random() < 0.5,
            "balance": f"${rng.uniform(1000, 4000):,.2f}",
            "age": rng.randint(20, 40),
            "eye_color": rng.choice(_COLORS),
            "name": {"first": _word(rng, 6).title(),
                     "last": _word(rng, 8).title()},
            "company": _word(rng, 9).upper(),
            "email": f"{_word(rng, 6)}@{_word(rng, 8)}.com",
            "latitude": round(rng.uniform(-90, 90), 6),
            "longitude": round(rng.uniform(-180, 180), 6),
            "tags": [_word(rng, rng.randint(3, 10))
                     for _ in range(rng.randint(0, 7))],
            "range": list(range(rng.randint(0, 10))),
            "friends": [
                {"id": j, "name": f"{_word(rng, 5).title()} "
                                  f"{_word(rng, 7).title()}"}
                for j in range(rng.randint(0, 3))
            ],
            "greeting": f"Hello, {_word(rng, 6)}! You have "
                        f"{rng.randint(1, 20)} unread messages.",
            "favorite_fruit": rng.choice(_FRUIT),
        })
    return rows


def load_json(path: str) -> list[dict]:
    with open(path, "r", encoding="utf-8") as f:
        return json.load(f)


def save_json(rows: list[dict], path: str) -> None:
    with open(path, "w", encoding="utf-8") as f:
        json.dump(rows, f, indent=1)


def to_parquet_row(row: dict) -> dict:
    """JSON row → writer row map (strings become bytes, like toMap())."""
    return {
        "id": row["id"].encode(),
        "index": row["index"],
        "guid": row["guid"].encode(),
        "is_active": row["is_active"],
        "balance": row["balance"].encode(),
        "age": row["age"],
        "eye_color": row["eye_color"].encode(),
        "name": {"first": row["name"]["first"].encode(),
                 "last": row["name"]["last"].encode()},
        "company": row["company"].encode(),
        "email": row["email"].encode(),
        "latitude": row["latitude"],
        "longitude": row["longitude"],
        "tags": [t.encode() for t in row["tags"]],
        "range": list(row["range"]),
        "friends": [{"id": f["id"], "name": f["name"].encode()}
                    for f in row["friends"]],
        "greeting": row["greeting"].encode(),
        "favorite_fruit": row["favorite_fruit"].encode(),
    }


def from_parquet_row(row: dict) -> dict:
    """Reader row map → JSON-comparable row (bytes back to str).

    Repeated fields read back as lists (possibly absent when empty — the
    format cannot distinguish empty repeated from missing); normalize to [].
    """
    def s(v):
        return v.decode() if isinstance(v, (bytes, bytearray)) else v

    out = {
        "id": s(row["id"]),
        "index": int(row["index"]),
        "guid": s(row["guid"]),
        "is_active": bool(row["is_active"]),
        "balance": s(row["balance"]),
        "age": int(row["age"]),
        "eye_color": s(row["eye_color"]),
        "name": {"first": s(row["name"]["first"]),
                 "last": s(row["name"]["last"])},
        "company": s(row["company"]),
        "email": s(row["email"]),
        "latitude": float(row["latitude"]),
        "longitude": float(row["longitude"]),
        "tags": [s(t) for t in (row.get("tags") or [])],
        "range": [int(v) for v in (row.get("range") or [])],
        "friends": [{"id": int(f["id"]), "name": s(f["name"])}
                    for f in (row.get("friends") or [])],
        "greeting": s(row["greeting"]),
        "favorite_fruit": s(row["favorite_fruit"]),
    }
    return out
