#!/usr/bin/env bash
# Cross-implementation interop matrix, mirroring the reference's
# compatibility/run_tests.bash:3-20 ({codec} x {page version}) and extending
# it with zstd and a pyarrow foreign-read leg that needs no Java.
#
# When $PARQUET_TOOLS_JAR points at a parquet-mr parquet-tools jar (and java
# is on PATH) every cell is additionally read back by parquet-mr via
# `java -jar $PARQUET_TOOLS_JAR cat -j`, the same jar the reference's Docker
# image builds.
set -euo pipefail
cd "$(dirname "$0")"

PY=${PYTHON:-python}
WORK=${WORK_DIR:-$(mktemp -d)}

$PY - <<EOF
from data_model import generate, save_json
save_json(generate(500), "$WORK/data.json")
EOF

rebuild_and_compare() {
  comp=$1
  version=$2
  out="$WORK/out-${comp}-${version}.parquet"
  $PY build.py --json "$WORK/data.json" --pq "$out" --compression "$comp" --version "$version"
  $PY compare.py --json "$WORK/data.json" --pq "$out"
  $PY compare.py --json "$WORK/data.json" --pq "$out" --reader pyarrow
  if [[ -n "${PARQUET_TOOLS_JAR:-}" ]] && command -v java >/dev/null; then
    java -jar "$PARQUET_TOOLS_JAR" cat -j "$out" > "$out.mr.jsonl"
    $PY - "$WORK/data.json" "$out.mr.jsonl" <<'EOF'
import json, sys
want = json.load(open(sys.argv[1]))
got = [json.loads(l) for l in open(sys.argv[2]) if l.strip()]
assert len(got) == len(want), (len(got), len(want))
for g, w in zip(got, want):
    assert g["id"] == w["id"] and g["index"] == w["index"], (g, w)
    assert g.get("tags", []) == w["tags"], (g, w)
print(f"OK: parquet-mr read {len(got)} rows")
EOF
  fi
}

for comp in none gzip snappy zstd; do
  for version in v1 v2; do
    rebuild_and_compare "$comp" "$version"
  done
done

echo "compatibility matrix PASSED (workdir $WORK)"
