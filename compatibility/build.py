"""Write the sample dataset to parquet — one cell of the interop matrix.

Python twin of the reference's compatibility/build.go:17-78: load JSON rows,
write them with the chosen codec and page version, so foreign readers
(parquet-mr's parquet-tools, pyarrow) can verify the output.

    python build.py --json data.json --pq out.parquet \
        --compression snappy --version v1
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

from tpu_parquet.format import CompressionCodec
from tpu_parquet.schema.dsl import parse_schema_definition
from tpu_parquet.writer import FileWriter

from data_model import SCHEMA_TEXT, load_json, to_parquet_row

CODECS = {
    "none": CompressionCodec.UNCOMPRESSED,
    "gzip": CompressionCodec.GZIP,
    "snappy": CompressionCodec.SNAPPY,
    "zstd": CompressionCodec.ZSTD,
}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--json", default="data.json")
    ap.add_argument("--pq", default="out.parquet")
    ap.add_argument("--compression", default="snappy", choices=sorted(CODECS))
    ap.add_argument("--version", default="v1", choices=["v1", "v2"])
    args = ap.parse_args(argv)

    rows = load_json(args.json)
    schema = parse_schema_definition(SCHEMA_TEXT)
    with FileWriter(
        args.pq, schema,
        codec=CODECS[args.compression],
        data_page_version=2 if args.version == "v2" else 1,
        created_by="tpu-parquet compatibility harness",
    ) as w:
        for row in rows:
            w.write_row(to_parquet_row(row))
    print(f"wrote {len(rows)} rows to {args.pq} "
          f"({args.compression}, pages {args.version})")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
