"""Read a matrix cell back and deep-compare against the source JSON.

Python twin of the reference's compatibility/compare.go:10-39.  With
``--reader pyarrow`` the file is read by pyarrow instead of our own reader —
a true cross-implementation check that runs without Java.

    python compare.py --json data.json --pq out.parquet [--reader pyarrow]
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

from data_model import from_parquet_row, load_json


def read_ours(path: str) -> list[dict]:
    from tpu_parquet.reader import FileReader

    with FileReader(path) as r:
        return [from_parquet_row(row) for row in r.iter_rows()]


def read_pyarrow(path: str) -> list[dict]:
    import pyarrow.parquet as pq

    rows = pq.read_table(path).to_pylist()
    # pyarrow reads `repeated` fields (no LIST annotation) as lists already,
    # and binary(STRING) as str; normalize through the same shape
    out = []
    for row in rows:
        out.append({
            **{k: row[k] for k in (
                "id", "index", "guid", "is_active", "balance", "age",
                "eye_color", "company", "email", "latitude", "longitude",
                "greeting", "favorite_fruit",
            )},
            "name": dict(row["name"]),
            "tags": list(row.get("tags") or []),
            "range": list(row.get("range") or []),
            "friends": [dict(f) for f in (row.get("friends") or [])],
        })
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--json", default="data.json")
    ap.add_argument("--pq", default="out.parquet")
    ap.add_argument("--reader", default="ours", choices=["ours", "pyarrow"])
    args = ap.parse_args(argv)

    want = load_json(args.json)
    got = read_ours(args.pq) if args.reader == "ours" else read_pyarrow(args.pq)
    if len(got) != len(want):
        print(f"FAIL: row count {len(got)} != {len(want)}", file=sys.stderr)
        return 1
    for i, (g, w) in enumerate(zip(got, want)):
        if g != w:
            for k in w:
                if g.get(k) != w[k]:
                    print(f"FAIL row {i} field {k!r}: {g.get(k)!r} != {w[k]!r}",
                          file=sys.stderr)
            return 1
    print(f"OK: {len(got)} rows equal ({args.reader} reader)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
