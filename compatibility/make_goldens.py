"""Generate the checked-in interop golden files (tests/golden/).

The reference's ground-truth interop leg is parquet-mr via Docker
(compatibility/run_tests.bash in the Go repo) — unrunnable in this image (no
Java, no network).  The substitute, executed in CI on every run
(tests/test_golden.py):

  one golden file per {codec} x {data page v1, v2} x {CRC off, on} cell,
  byte-written by THIS repo's writer from deterministic data, checked into
  the tree.  The test asserts
    (a) regenerating the cell reproduces the checked-in bytes EXACTLY for
        the fully-in-repo codecs (UNCOMPRESSED, SNAPPY) — an encoding-level
        assertion that catches any unintended format drift, and
    (b) pyarrow (Apache Arrow C++, the independent implementation) reads
        every golden value-exact, and
    (c) this repo re-reads pyarrow's REWRITE of the same table value-exact
        (both the host and the device reader).

Run this script only to regenerate the goldens after a DELIBERATE format
change, then commit the diff: `python compatibility/make_goldens.py`.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

import numpy as np

from tpu_parquet.column import ByteArrayData, ColumnData
from tpu_parquet.compress import CompressionError
from tpu_parquet.format import (
    CompressionCodec, ConvertedType, FieldRepetitionType as FRT, LogicalType,
    StringType, Type,
)
from tpu_parquet.schema.core import (
    ColumnParameters, build_schema, data_column, list_column,
)
from tpu_parquet.writer import FileWriter

GOLDEN_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "..", "tests", "golden")
CODECS = {
    "uncompressed": CompressionCodec.UNCOMPRESSED,
    "snappy": CompressionCodec.SNAPPY,
    "gzip": CompressionCodec.GZIP,
    "zstd": CompressionCodec.ZSTD,
}
ROWS = 500


def golden_schema():
    S = ColumnParameters(logical_type=LogicalType(STRING=StringType()),
                         converted_type=ConvertedType.UTF8)
    return build_schema([
        data_column("id", Type.INT64, FRT.REQUIRED),
        data_column("x", Type.INT32, FRT.OPTIONAL),
        data_column("score", Type.DOUBLE, FRT.OPTIONAL),
        data_column("flag", Type.BOOLEAN, FRT.REQUIRED),
        data_column("name", Type.BYTE_ARRAY, FRT.OPTIONAL, S),
        list_column("tags", data_column("element", Type.INT64, FRT.OPTIONAL)),
    ])


def golden_rows():
    """Deterministic mixed rows: nulls, empty lists, null elements, dict-able
    strings, negative ints — every shape the readers must round-trip."""
    rng = np.random.default_rng(20260730)
    rows = []
    for i in range(ROWS):
        rows.append({
            "id": int(i * 3 - 500),
            "x": None if i % 7 == 0 else int(i % 97),
            "score": None if i % 11 == 0 else float(rng.standard_normal()),
            "flag": i % 2 == 0,
            "name": None if i % 5 == 0 else f"name-{i % 37}".encode(),
            "tags": (None if i % 13 == 0 else []
                     if i % 6 == 0 else
                     [int(j) if j % 3 else None for j in range(i % 5)]),
        })
    return rows


def cell_name(codec: str, version: int, crc: bool) -> str:
    return f"golden_{codec}_v{version}{'_crc' if crc else ''}.parquet"


def write_cell(path, codec_name, version, crc):
    with FileWriter(
        path, golden_schema(), codec=CODECS[codec_name],
        data_page_version=version, write_crc=crc, page_size=4096,
        row_group_size=8 << 10, created_by="tpu_parquet-golden",
    ) as w:
        w.write_rows(golden_rows())


def main():
    os.makedirs(GOLDEN_DIR, exist_ok=True)
    for codec in CODECS:
        for version in (1, 2):
            for crc in (False, True):
                name = cell_name(codec, version, crc)
                path = os.path.join(GOLDEN_DIR, name)
                # write-to-temp + rename: a codec unavailable in THIS
                # environment (zstd without the zstandard module) must
                # skip its cells, never truncate the checked-in bytes the
                # writer already opened
                tmp = path + ".tmp"
                try:
                    write_cell(tmp, codec, version, crc)
                except (CompressionError, ImportError) as e:
                    # codec unavailable in THIS environment (zstd without
                    # the zstandard module): keep the checked-in bytes.  Any
                    # other failure is a real writer regression and must
                    # abort the regeneration loudly.
                    if os.path.exists(tmp):
                        os.unlink(tmp)
                    print(f"{name}: SKIPPED ({e}) — checked-in bytes kept")
                    continue
                os.replace(tmp, path)
                print(f"{name}: {os.path.getsize(path)} bytes")


if __name__ == "__main__":
    main()
