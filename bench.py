"""Benchmark: device (TPU) columnar decode vs host (NumPy) columnar decode.

Output contract (round-6 artifact plumbing — the r04/r05 one-line JSON
overflowed the driver's 2000-char tail window, leaving the binding record
unparseable):

- FULL results are written as indented multi-line JSON to the artifact file
  (``BENCH_JSON`` env, default ``BENCH_LOCAL_latest.json`` next to this
  script);
- stdout's LAST line is ONE compact JSON summary, guaranteed < 2000 chars:
    {"metric": ..., "value": N, "unit": "rows/s", "vs_baseline": N,
     "artifact": ..., "configs": {<scalar highlights only>}}
Everything else goes to stderr.

Configs mirror BASELINE.md (sizes scaled to keep a driver run in minutes;
scale with BENCH_SCALE):

  1 plain_int64    single INT64 PLAIN column, SNAPPY
  2 delta_ints     INT32 + INT64 DELTA_BINARY_PACKED
  3 dict_strings   BYTE_ARRAY STRING dictionary, RLE_DICTIONARY indices
  4 lineitem16     TPC-H lineitem, all 16 columns, mixed encodings  [headline]
  5 nested         LIST + MAP logical types (pyarrow-written, NYC-taxi-like)

Per config: device rows/s + decoded MB/s, host rows/s, device/host ratio.
The headline "value"/"vs_baseline" is config 4 — the full-width mixed schema.

"value" is end-to-end device-path decode throughput: file open → footer → per
chunk IO → host decompress + native structure parse → XLA kernels → device
arrays, blocked until ready (columns stay on device; that is the product).
"vs_baseline" divides by the host NumPy columnar decoder on the same file — a
*stricter* denominator than the pure-Go reference (value-at-a-time,
interface-dispatched, one boxed value per datum; SURVEY.md §3.1 hot loops),
which cannot run here (no Go toolchain in the image).  pyarrow (Arrow C++) is
additionally timed on the identical files as an independent cross-check
denominator.  Since round 3, PLAIN BYTE_ARRAY value streams also decode on
device (host walks only the length prefixes — device_reader.py), so no
config carries a host-bound value-decode share anymore.

Sampling protocol (disclosed here and in README) — SYMMETRIC since round 5:
- the within-sample estimator is the MEDIAN on BOTH sides of every ratio:
  a device window's median of reps vs the baselines' median of
  BENCH_BASELINE_REPS reps — no side gets min-of-n noise rejection the
  other lacks (the round-1..4 asymmetry).
- across WINDOWS the device estimate is the best window median.  Windows
  exist because the tunneled link suffers exogenous multi-minute
  congestion that does not touch the CPU-bound baselines; selecting the
  cleanest window selects measurement CONDITIONS, not lucky reps — the
  within-window median still rejects per-rep noise.  Every window's full
  rep list and its link probe ship in the JSON (device_windows_s,
  host_reps_s, pyarrow_reps_s, link_mb_per_sec_*), so any other estimator
  can be recomputed from the artifact.
- EVERY config's device reps are sampled in up to 1 + BENCH_RESAMPLE
  time-separated windows (default 3 total) — because the tunneled TPU link
  shows transient multi-minute congestion (own probes have recorded
  93 MB/s and 1.5 GB/s within one run); a single burst of back-to-back
  reps samples only one weather window.  The best-window selection
  above spans them.
  Resample windows stop early at 60% of the time budget so the baselines
  (phase B) always fit.
- link bandwidth is probed (one 64 MB transfer) before and after phase A and
  recorded in the JSON, so a depressed headline is attributable from the
  artifact itself.

A ``pipeline`` section (BENCH_PIPELINE=0 to skip) benches the overlapped
chunk pipeline at host decode prefetch={0,4} — on the headline file AND on
plain_int64 (the round-4 ≥0.9×-host target, re-measured against the overlap
path) — with the per-stage counters (overlap efficiency = busy/wall) from
``FileReader.pipeline_stats()``.  A ``loader`` section (BENCH_LOADER=0 to
skip) measures one shuffled ``data.DataLoader`` epoch over the headline
file's fixed-width columns at prefetch={0,4} vs a raw ``scan_files`` pass.

Env knobs: BENCH_SCALE (default 1.0), BENCH_DEVICE_REPS (default 4),
BENCH_BASELINE_REPS (default: one below device reps, capped at 3),
BENCH_CONFIGS (comma list, default "4,2,3,1,5" — headline banked first),
BENCH_RESAMPLE (default 2 — extra sampling windows over all configs),
BENCH_JSON (artifact path).

Run ledger + regression gate (round-10 — tpu_parquet/ledger.py): every run
appends its full record (config, git rev, env fingerprint, registry trees,
per-rep timings) to an append-only ``ledger.jsonl`` next to the artifact
(``TPQ_LEDGER`` overrides; ``--no-ledger`` skips).  ``--check-against
BASELINE`` (a bench artifact, a ledger, or ``ledger.jsonl#N``) gates the
run: per-metric deltas with noise bounds from rep variance
(BENCH_CHECK_FLOOR, default 0.30), exit 2 on a regression beyond noise —
the compact stdout line is ALWAYS emitted first, so the driver still gets
its record.  A run that FAILS the gate is not recorded to the ledger
(its numbers still land in the artifact + compact line): with the ledger
as the baseline, recording the red run would make it the next run's
baseline and ratchet the regression in after a single red build.  ``--smoke`` shrinks to one tiny config with every optional
section off: the end-to-end plumbing exercise CI runs in seconds.
"""

import json
import os
import sys
import time


def log(*a):
    print(*a, file=sys.stderr, flush=True)


SCALE = float(os.environ.get("BENCH_SCALE", "1.0"))
# device reps are cheap (~0.1-1s each warm); best-of-4 rides out the
# tunnel-weather windows that can depress a single rep 2-4x
REPS = int(os.environ.get("BENCH_DEVICE_REPS", "4"))
# baselines are the slow half of the budget: one rep fewer than the device
# (the asymmetry is disclosed in the module docstring and the output JSON)
BASELINE_REPS = int(os.environ.get("BENCH_BASELINE_REPS",
                                   str(max(min(REPS - 1, 3), 1))))
# two extra windows by default: BENCH_r04 logs show the link swinging
# 136->1500 MB/s across minutes; the window loop is budget-guarded, so a
# slow run simply takes fewer windows
RESAMPLE = int(os.environ.get("BENCH_RESAMPLE", "2"))
WHICH = os.environ.get("BENCH_CONFIGS", "4,2,3,1,5").split(",")
# soft wall-clock budget: finish the current config, then emit JSON with
# whatever was measured (the driver must ALWAYS get its one line)
TIME_BUDGET = float(os.environ.get("BENCH_TIME_BUDGET", "600"))
_T_START = time.perf_counter()

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# generators (cached in /tmp, one-time)
# ---------------------------------------------------------------------------

def _writer(path, schema, **kw):
    from tpu_parquet.format import CompressionCodec
    from tpu_parquet.writer import FileWriter

    kw.setdefault("codec", CompressionCodec.SNAPPY)
    kw.setdefault("row_group_size", 128 << 20)
    # CRC every page: the round-13 default-on validation tier
    # (validate="crc") must actually exercise on every bench read, and the
    # data_faults section needs checksummed pages to corrupt
    kw.setdefault("write_crc", True)
    return FileWriter(path, schema, **kw)


def _pool_col(idx, pool):
    """ColumnData of pool[idx] (shared by the read generators + write bench)."""
    import numpy as np
    from tpu_parquet.column import ByteArrayData, ColumnData

    lens = np.array([len(pool[i]) for i in range(len(pool))])[idx]
    offs = np.zeros(len(idx) + 1, dtype=np.int64)
    np.cumsum(lens, out=offs[1:])
    heap = np.frombuffer(b"".join(pool[i] for i in idx), dtype=np.uint8).copy()
    return ColumnData(values=ByteArrayData(offsets=offs, heap=heap))


def _strings_col(rng, n, pool):
    return _pool_col(rng.integers(0, len(pool), n), pool)


def gen_plain_int64(path, rows):
    import numpy as np
    from tpu_parquet.format import FieldRepetitionType as FRT, Type
    from tpu_parquet.schema.core import build_schema, data_column

    rng = np.random.default_rng(1)
    schema = build_schema([data_column("v", Type.INT64, FRT.REQUIRED)])
    with _writer(path, schema, use_dictionary=False) as w:
        for lo in range(0, rows, 2_000_000):
            n = min(2_000_000, rows - lo)
            w.write_columns({"v": rng.integers(-(1 << 62), 1 << 62, n)})


def gen_delta_ints(path, rows):
    import numpy as np
    from tpu_parquet.format import Encoding, FieldRepetitionType as FRT, Type
    from tpu_parquet.schema.core import build_schema, data_column

    rng = np.random.default_rng(2)
    schema = build_schema([
        data_column("k64", Type.INT64, FRT.REQUIRED),
        data_column("d32", Type.INT32, FRT.REQUIRED),
    ])
    with _writer(
        path, schema, use_dictionary=False,
        column_encodings={"k64": Encoding.DELTA_BINARY_PACKED,
                          "d32": Encoding.DELTA_BINARY_PACKED},
    ) as w:
        key = 0
        for lo in range(0, rows, 2_000_000):
            n = min(2_000_000, rows - lo)
            keys = key + np.cumsum(rng.integers(1, 9, n))
            key = int(keys[-1])
            w.write_columns({
                "k64": keys.astype(np.int64),
                "d32": (10000 + rng.integers(0, 5000, n)).astype(np.int32),
            })


def gen_dict_strings(path, rows):
    import numpy as np
    from tpu_parquet.format import (
        ConvertedType, FieldRepetitionType as FRT, LogicalType, StringType, Type,
    )
    from tpu_parquet.schema.core import ColumnParameters, build_schema, data_column

    rng = np.random.default_rng(3)
    pool = [f"supplier_name_{i:04d}".encode() for i in range(1000)]
    schema = build_schema([
        data_column("s", Type.BYTE_ARRAY, FRT.REQUIRED, ColumnParameters(
            logical_type=LogicalType(STRING=StringType()),
            converted_type=ConvertedType.UTF8)),
    ])
    with _writer(path, schema, use_dictionary=True) as w:
        for lo in range(0, rows, 2_000_000):
            n = min(2_000_000, rows - lo)
            w.write_columns({"s": _strings_col(rng, n, pool)})


def gen_lineitem16(path, rows, rows_per_group=1_000_000):
    import numpy as np
    from tpu_parquet.format import (
        ConvertedType, Encoding, FieldRepetitionType as FRT, LogicalType,
        StringType, Type,
    )
    from tpu_parquet.schema.core import ColumnParameters, build_schema, data_column

    rng = np.random.default_rng(4)
    S = lambda: ColumnParameters(logical_type=LogicalType(STRING=StringType()),
                                 converted_type=ConvertedType.UTF8)
    schema = build_schema([
        data_column("l_orderkey", Type.INT64, FRT.REQUIRED),
        data_column("l_partkey", Type.INT64, FRT.REQUIRED),
        data_column("l_suppkey", Type.INT64, FRT.REQUIRED),
        data_column("l_linenumber", Type.INT32, FRT.REQUIRED),
        data_column("l_quantity", Type.INT64, FRT.REQUIRED),
        data_column("l_extendedprice", Type.DOUBLE, FRT.REQUIRED),
        data_column("l_discount", Type.DOUBLE, FRT.REQUIRED),
        data_column("l_tax", Type.DOUBLE, FRT.REQUIRED),
        data_column("l_returnflag", Type.BYTE_ARRAY, FRT.REQUIRED, S()),
        data_column("l_linestatus", Type.BYTE_ARRAY, FRT.REQUIRED, S()),
        data_column("l_shipdate", Type.INT32, FRT.REQUIRED),
        data_column("l_commitdate", Type.INT32, FRT.REQUIRED),
        data_column("l_receiptdate", Type.INT32, FRT.REQUIRED),
        data_column("l_shipinstruct", Type.BYTE_ARRAY, FRT.REQUIRED, S()),
        data_column("l_shipmode", Type.BYTE_ARRAY, FRT.REQUIRED, S()),
        data_column("l_comment", Type.BYTE_ARRAY, FRT.REQUIRED, S()),
    ])
    flags = [b"A", b"N", b"R"]
    status = [b"F", b"O"]
    instr = [b"DELIVER IN PERSON", b"COLLECT COD", b"NONE", b"TAKE BACK RETURN"]
    modes = [b"AIR", b"FOB", b"MAIL", b"RAIL", b"REG AIR", b"SHIP", b"TRUCK"]
    words = [f"word{i}".encode() for i in range(64)]
    with _writer(
        path, schema, use_dictionary=True,
        column_encodings={"l_orderkey": Encoding.DELTA_BINARY_PACKED,
                          "l_shipdate": Encoding.DELTA_BINARY_PACKED,
                          "l_commitdate": Encoding.DELTA_BINARY_PACKED,
                          "l_receiptdate": Encoding.DELTA_BINARY_PACKED},
    ) as w:
        key = 0
        for lo in range(0, rows, rows_per_group):
            n = min(rows_per_group, rows - lo)
            keys = key + np.cumsum(rng.integers(1, 5, n))
            key = int(keys[-1])
            # l_comment: free-text-ish plain strings (the host-bound column)
            comment_pool = [b" ".join(
                words[j % 64] for j in range(i, i + 5)) for i in range(256)]
            w.write_columns({
                "l_orderkey": keys.astype(np.int64),
                "l_partkey": rng.integers(1, 200_000, n),
                "l_suppkey": rng.integers(1, 10_000, n),
                "l_linenumber": rng.integers(1, 8, n).astype(np.int32),
                "l_quantity": rng.integers(1, 51, n),
                "l_extendedprice": rng.uniform(900, 105_000, n),
                "l_discount": rng.uniform(0, 0.1, n).round(2),
                "l_tax": rng.uniform(0, 0.08, n).round(2),
                "l_returnflag": _strings_col(rng, n, flags),
                "l_linestatus": _strings_col(rng, n, status),
                "l_shipdate": (8035 + rng.integers(0, 2526, n)).astype(np.int32),
                "l_commitdate": (8035 + rng.integers(0, 2526, n)).astype(np.int32),
                "l_receiptdate": (8035 + rng.integers(0, 2526, n)).astype(np.int32),
                "l_shipinstruct": _strings_col(rng, n, instr),
                "l_shipmode": _strings_col(rng, n, modes),
                "l_comment": _strings_col(rng, n, comment_pool),
            })
            # one group per chunk.  At the default 1M-row chunking this is
            # byte-identical to the old size-trigger behavior (each chunk is
            # ~130MB >= the 128MB threshold, and only the final chunk can be
            # smaller — close() flushed it alone either way), so cached
            # /tmp files from earlier rounds stay comparable; the explicit
            # flush exists for the loader bench's smaller rows_per_group.
            w.flush_row_group()


def gen_nested(path, rows):
    """NYC-taxi-like nested shapes, written by pyarrow (foreign writer).

    TWO files (BASELINE config 5 is a multi-file row-group scan); the bench
    paths discover the `.part2` sibling and scan both."""
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    # the .part2 sibling is written FIRST: the main file is the generation
    # cache key, so its existence must imply the sibling exists too
    for part, (seed, out) in enumerate([(6, path + ".part2"), (5, path)]):
        rng = np.random.default_rng(seed)
        n = rows // 2 if part == 0 else rows - rows // 2
        lens = rng.integers(0, 5, n)
        flat = rng.integers(0, 300, int(lens.sum()))
        offs = np.zeros(n + 1, dtype=np.int32)
        np.cumsum(lens, out=offs[1:])
        zones = pa.ListArray.from_arrays(pa.array(offs), pa.array(flat))
        keys = ["fare", "tip", "tolls"]
        mk = [{k: float(rng.uniform(1, 60)) for k in keys[: rng.integers(1, 4)]}
              for _ in range(256)]
        t = pa.table({
            "trip_id": np.arange(n, dtype=np.int64),
            "zones": zones,
            "charges": pa.array([mk[i % 256] for i in range(n)],
                                type=pa.map_(pa.string(), pa.float64())),
            "distance": rng.uniform(0.3, 40.0, n),
        })
        pq.write_table(t, out, compression="snappy", row_group_size=1 << 20)


def _bench_paths(path):
    """The config's file set: the main file plus the multi-file siblings."""
    sib = path + ".part2"
    return [path, sib] if os.path.exists(sib) else [path]


# ---------------------------------------------------------------------------
# measurement
# ---------------------------------------------------------------------------

def _uncompressed_mb(path):
    from tpu_parquet.reader import FileReader

    total = 0
    for p in _bench_paths(path):
        with FileReader(p) as r:
            total += sum(
                cc.meta_data.total_uncompressed_size or 0
                for rg in r.metadata.row_groups for cc in rg.columns
            )
    return total / 1e6


def _device_run(path):
    import jax
    from tpu_parquet.device_reader import scan_files

    outs = []
    # one continuous pipeline across the config's whole file set (the
    # multi-file dataset scan of BASELINE config 5)
    for cols in scan_files(_bench_paths(path)):
        outs.extend(cols.values())
    arrs = [a for o in outs
            for a in (o.values, o.offsets, o.heap,
                      getattr(o, "indices", None))
            if a is not None]
    jax.block_until_ready(arrs)


def device_reps(path, rows, reps, tag=""):
    """Timed device reps (caller ensures executables are warm); returns the
    list of rep times (the caller pools samples across windows and takes the
    MEDIAN — see the sampling-protocol docstring)."""
    out = []
    for i in range(reps):
        t0 = time.perf_counter()
        _device_run(path)
        dt = time.perf_counter() - t0
        log(f"  device rep{tag} {i}: {dt:.3f}s ({rows/dt/1e6:.2f} M rows/s)")
        out.append(dt)
    return out


def _median(xs):
    xs = sorted(xs)
    m = len(xs) // 2
    return xs[m] if len(xs) % 2 else 0.5 * (xs[m - 1] + xs[m])


def _best_window(windows):
    """THE device estimator (see the sampling-protocol docstring): median
    within each window, cleanest window across.  Single definition so the
    resample loop and every phase-B ratio can never diverge."""
    return min(_median(w) for w in windows)


def probe_link(mb=64):
    """One host→device transfer of ``mb`` MB, recorded in the output JSON so a
    congested-tunnel run is attributable from the artifact itself.  Doubles as
    the transfer warm-up (the link ramps up over the first transfers)."""
    import jax
    import numpy as np

    a = np.zeros(mb << 20, dtype=np.uint8)
    t0 = time.perf_counter()
    jax.block_until_ready(jax.device_put(a))
    rate = mb / (time.perf_counter() - t0)
    log(f"link probe: {rate:.0f} MB/s ({mb} MB)")
    return round(rate, 1)


def bench_device(path, rows, name=""):
    from tpu_parquet.device_reader import DeviceFileReader
    from tpu_parquet.obs import StatsRegistry, Tracer

    _device_run(path)  # warm: XLA executables cached after this
    samples = device_reps(path, rows, REPS)
    # observability from one instrumented pass (SURVEY.md §5.5), accumulated
    # over every file of the config (multi-file nested scan) into ONE
    # obs.StatsRegistry tree (histograms + ship feedback included — the
    # artifact carries the planner's predicted-vs-measured lane seconds).
    # The ship-planner counters (per-route link bytes — ship.py) prove the
    # link-byte cut from the artifact alone: `link_bytes_shipped` vs
    # `link_bytes_logical` is the transfer the planner removed.
    # With TPQ_TRACE=<base> set, the instrumented pass additionally writes a
    # Perfetto-loadable trace artifact per config at <base>.<config>.json.
    ship = {"link_bytes_shipped": 0, "link_bytes_logical": 0,
            "ship_routes": {}}
    reg = StatsRegistry()
    trace_base = (_TRACE_BASE if _TRACE_BASE is not None
                  else os.environ.get("TPQ_TRACE", ""))
    tracer = Tracer(path=f"{trace_base}.{name}.json") if trace_base else None
    for p in _bench_paths(path):
        with DeviceFileReader(p, trace=tracer) as r:
            for cols in r.iter_row_groups():
                pass
            d = r.stats().as_dict()
            log(f"  reader stats[{os.path.basename(p)}]: {d}")
            reg.merge_from(r.obs_registry())
            ship["link_bytes_shipped"] += d["link_bytes_shipped"]
            ship["link_bytes_logical"] += d["link_bytes_logical"]
            for route, c in d["ship_routes"].items():
                agg = ship["ship_routes"].setdefault(
                    route, {"streams": 0, "logical": 0, "shipped": 0})
                for k in agg:
                    agg[k] += c[k]
    if ship["link_bytes_logical"]:
        ship["link_bytes_ratio"] = round(
            ship["link_bytes_shipped"] / ship["link_bytes_logical"], 4)
    if tracer is not None:
        log(f"  trace artifact: {tracer.write(registry=reg)}")
    ship["obs"] = reg.as_dict()
    # the per-route device completion lane (TPQ_DEVICE_TIMING, default on):
    # smoke exercises this section end to end, and the ledger diff
    # attributes device regressions to a specific route from it
    dev = ship["obs"].get("device")
    if dev:
        log(f"  device lanes: dispatches={dev.get('dispatches')} "
            f"device_seconds={dev.get('device_seconds')} "
            f"routes={sorted((dev.get('routes') or {}))} "
            f"h2d_s={(dev.get('h2d') or {}).get('device_seconds')}")
    else:
        log("  device lanes: n/a (timing lane disabled)")
    return samples, ship


def bench_pyarrow(path, rows):
    """Independent cross-check denominator: pyarrow.parquet.read_table on the
    identical files (Apache Arrow C++, multi-threaded).  The self-measured
    NumPy host decoder stays the primary vs_baseline denominator (it mirrors
    the reference's single-threaded decode loop); this number anchors it
    against code this repo didn't write."""
    import pyarrow.parquet as pq

    def run():
        for p in _bench_paths(path):
            pq.read_table(p)

    run()
    samples = []
    for i in range(BASELINE_REPS):
        t0 = time.perf_counter()
        run()
        dt = time.perf_counter() - t0
        log(f"  pyarrow rep {i}: {dt:.3f}s ({rows/dt/1e6:.2f} M rows/s)")
        samples.append(dt)
    return samples


def bench_host(path, rows, upload=False):
    """Host NumPy decode; with ``upload``, decoded arrays are also staged to
    the device — the apples-to-apples pipeline baseline, since the device
    path's output is already HBM-resident."""
    import jax
    import numpy as np
    from tpu_parquet.column import ByteArrayData
    from tpu_parquet.reader import FileReader

    def run():
        staged = []
        for p in _bench_paths(path):
            with FileReader(p) as r:
                for rg in r.iter_row_groups():
                    if upload:
                        for cd in rg.values():
                            v = cd.values
                            if isinstance(v, ByteArrayData):
                                staged.append(jax.device_put(v.offsets))
                                staged.append(jax.device_put(v.heap))
                            else:
                                staged.append(
                                    jax.device_put(np.ascontiguousarray(v)))
        if staged:
            jax.block_until_ready(staged)

    run()
    samples = []
    for i in range(BASELINE_REPS):
        t0 = time.perf_counter()
        run()
        dt = time.perf_counter() - t0
        tag = "host+upload" if upload else "host"
        log(f"  {tag} rep {i}: {dt:.3f}s ({rows/dt/1e6:.2f} M rows/s)")
        samples.append(dt)
    return samples


CONFIGS = {
    "1": ("plain_int64", gen_plain_int64, 10_000_000),
    "2": ("delta_ints", gen_delta_ints, 10_000_000),
    "3": ("dict_strings", gen_dict_strings, 10_000_000),
    "4": ("lineitem16", gen_lineitem16, 5_000_000),
    "5": ("nested", gen_nested, 2_000_000),
}


def bench_writes(rows=2_000_000, reps=2):
    """Writer throughput (host encode; the reference ships write benchmarks,
    floor/writer_test.go:606-647, but records no numbers).  Data is built
    in memory first so the timing covers ONLY the write; pyarrow writes the
    identical data as the independent denominator."""
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq
    from tpu_parquet.format import (
        ConvertedType, FieldRepetitionType as FRT, LogicalType, StringType,
        Type,
    )
    from tpu_parquet.schema.core import (
        ColumnParameters, build_schema, data_column,
    )

    rng = np.random.default_rng(7)
    S = lambda: ColumnParameters(
        logical_type=LogicalType(STRING=StringType()),
        converted_type=ConvertedType.UTF8)

    def strings(pool):
        idx = rng.integers(0, len(pool), rows)
        return _pool_col(idx, pool), pa.array([pool[i].decode() for i in idx])

    pool = [f"supplier_name_{i:04d}".encode() for i in range(1000)]
    scol, sarr = strings(pool)
    ints = rng.integers(-(1 << 62), 1 << 62, rows)
    li_np = {
        "l_orderkey": np.cumsum(rng.integers(1, 5, rows)).astype(np.int64),
        "l_partkey": rng.integers(1, 200_000, rows),
        "l_quantity": rng.integers(1, 51, rows),
        "l_extendedprice": rng.uniform(900, 105_000, rows),
    }
    mcol, marr = strings([b"AIR", b"FOB", b"MAIL", b"RAIL", b"SHIP"])
    cases = {
        "write_plain_int64": (
            build_schema([data_column("v", Type.INT64, FRT.REQUIRED)]),
            {"v": ints}, dict(use_dictionary=False),
            pa.table({"v": ints}), dict(use_dictionary=False),
        ),
        "write_dict_strings": (
            build_schema([data_column("s", Type.BYTE_ARRAY, FRT.REQUIRED,
                                      S())]),
            {"s": scol}, dict(use_dictionary=True),
            pa.table({"s": sarr}), {},
        ),
        "write_lineitem5": (
            build_schema(
                [data_column(k, Type.DOUBLE if v.dtype == np.float64
                             else Type.INT64, FRT.REQUIRED)
                 for k, v in li_np.items()]
                + [data_column("l_shipmode", Type.BYTE_ARRAY, FRT.REQUIRED,
                               S())]),
            {**li_np, "l_shipmode": mcol}, dict(use_dictionary=True),
            pa.table({**li_np, "l_shipmode": marr}), {},
        ),
    }
    out = {}
    import io as _io

    for name, (schema, data, kw, patab, pakw) in cases.items():
        best = pa_best = float("inf")
        for _ in range(reps):
            # memory sinks on BOTH sides: the doc contract is "timing covers
            # ONLY the write", and this VM's disk writeback (85-156 ms per
            # 16 MB, with truncate-flush stalls on rewrite) was the
            # dominant, weather-like term for whichever writer ran second
            t0 = time.perf_counter()
            with _writer(_io.BytesIO(), schema, **kw) as w:
                w.write_columns(data)
            best = min(best, time.perf_counter() - t0)
            t0 = time.perf_counter()
            pq.write_table(patab, pa.BufferOutputStream(),
                           compression="snappy", **pakw)
            pa_best = min(pa_best, time.perf_counter() - t0)
        out[name] = {
            "rows": rows,
            "write_rows_per_sec": round(rows / best, 1),
            "pyarrow_write_rows_per_sec": round(rows / pa_best, 1),
            "write_vs_pyarrow": round(pa_best / best, 3),
        }
        log(f"{name}: {rows / best / 1e6:.1f} M rows/s "
            f"({pa_best / best:.2f}x pyarrow write)")
    return out


def bench_write_scale(smoke=False):
    """ISSUE 15 acceptance: the write side of scale.

    Two phases, banked to the ledger like every section:

    - ``encode``: N-worker sharded encode (write.write_sharded, the merged
      single-file layout — bit-identity with the single writer is the
      tier-1 test's job, the bench banks throughput) vs the single-writer
      baseline over the SAME batches; ``encode_speedup`` is the headline.
    - ``compaction``: a fragmented many-small-files dataset compacted to
      few large through the ship planner's codec replanning; banks
      before/after file counts and the planner-modeled link-byte ratio.

    Skip with BENCH_WRITE=0; ``--smoke`` runs it tiny.  The exit-3
    thread-leak gate is unchanged: the encode pool joins inside
    write_sharded, nothing daemonized outlives the section.
    """
    import shutil
    import tempfile

    import numpy as np
    from tpu_parquet.format import FieldRepetitionType as FRT, Type
    from tpu_parquet.schema.core import build_schema, data_column
    from tpu_parquet.write import WriteStats, compact, write_sharded
    from tpu_parquet.writer import FileWriter

    rng = np.random.default_rng(11)
    rows_per_rg = 20_000 if smoke else 500_000
    n_rgs = 4 if smoke else 12
    workers = int(os.environ.get("BENCH_WRITE_WORKERS",
                                 str(min(os.cpu_count() or 1, 8))))
    schema = build_schema([
        data_column("k", Type.INT64, FRT.REQUIRED),
        data_column("v", Type.DOUBLE, FRT.REQUIRED),
    ])
    batches = [{"k": rng.integers(0, 1 << 40, rows_per_rg).astype(np.int64),
                "v": rng.random(rows_per_rg)} for _ in range(n_rgs)]
    total_rows = rows_per_rg * n_rgs
    tmp = tempfile.mkdtemp(prefix="tpq-bench-write-")
    out = {}
    try:
        # single-writer baseline (same batches, same row-group cuts)
        single = os.path.join(tmp, "single.parquet")
        t0 = time.perf_counter()
        with FileWriter(single, schema) as w:
            for b in batches:
                w.write_columns(b)
                w.flush_row_group()
        single_s = time.perf_counter() - t0

        st = WriteStats()
        merged = os.path.join(tmp, "merged.parquet")
        t0 = time.perf_counter()
        res = write_sharded(merged, schema, batches, workers=workers,
                            stats=st)
        sharded_s = time.perf_counter() - t0
        same = (os.path.getsize(single) == os.path.getsize(merged))
        out["encode"] = {
            "rows": total_rows,
            "row_groups": n_rgs,
            "workers": st.workers,
            "single_writer_s": round(single_s, 4),
            "sharded_s": round(sharded_s, 4),
            "encode_speedup": round(single_s / sharded_s, 3),
            "sharded_rows_per_sec": round(total_rows / sharded_s, 1),
            "bytes_written": res.bytes_written,
            "size_matches_single": bool(same),
            "stall_seconds": round(st.stall_seconds, 4),
        }
        log(f"write_scale encode: {workers} workers "
            f"{total_rows / sharded_s / 1e6:.2f} M rows/s "
            f"({single_s / sharded_s:.2f}x single writer)")

        # compaction: fragment the same data into many small files first
        frag = os.path.join(tmp, "frag")
        os.makedirs(frag)
        small = []
        for i, b in enumerate(batches):
            for j, lo in enumerate(range(0, rows_per_rg,
                                         max(rows_per_rg // 4, 1))):
                hi = min(lo + max(rows_per_rg // 4, 1), rows_per_rg)
                p = os.path.join(frag, f"in-{i:03d}-{j}.parquet")
                with FileWriter(p, schema) as w:
                    w.write_columns({k: v[lo:hi] for k, v in b.items()})
                small.append(p)
        t0 = time.perf_counter()
        rep = compact(small, out=frag, workers=workers)
        compact_s = time.perf_counter() - t0
        d = rep.as_dict()
        d["compact_s"] = round(compact_s, 4)
        out["compaction"] = d
        log(f"write_scale compaction: {d['files_before']} -> "
            f"{d['files_after']} files, link ratio "
            f"{d['link_bytes_ratio']:.3f} in {compact_s:.2f}s")
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    return out


def bench_pipeline(path, rows, reps=3):
    """Overlapped-chunk-pipeline bench (ISSUE 1 acceptance gate): host
    decode of the lineitem16 file at prefetch={0,4} — same file, same
    decoder, only the pipeline depth differs — plus the per-stage counters
    that make the speedup attributable (overlap efficiency = sum of stage
    seconds / wall seconds; 1.0 is perfectly serial)."""
    from tpu_parquet.reader import FileReader

    out = {"rows": rows}
    for k in (0, 4):
        best = float("inf")
        best_stats = None
        for i in range(reps):
            t0 = time.perf_counter()
            with FileReader(path, prefetch=k) as r:
                r.read_all()
                st = r.pipeline_stats()
            dt = time.perf_counter() - t0
            log(f"  pipeline prefetch={k} rep {i}: {dt:.3f}s "
                f"({rows/dt/1e6:.2f} M rows/s)")
            if dt < best:
                best, best_stats = dt, st.as_dict()
        out[f"prefetch{k}_s"] = round(best, 3)
        out[f"prefetch{k}_rows_per_sec"] = round(rows / best, 1)
        if k:
            for key in ("io_seconds", "decompress_seconds", "stall_seconds",
                        "busy_seconds", "overlap_efficiency",
                        "peak_in_flight_bytes"):
                out[key] = best_stats[key]
    out["pipeline_speedup"] = round(out["prefetch0_s"] / out["prefetch4_s"], 3)
    log(f"pipeline: {out['pipeline_speedup']:.2f}x at prefetch=4 "
        f"(overlap efficiency {out['overlap_efficiency']:.2f})")
    return out


def bench_loader(path, rows, reps=None):
    """Training-input loader bench (ISSUE 2 acceptance gate): one shuffled
    epoch of ``data.DataLoader`` over the lineitem16 fixed-width columns at
    prefetch={0,4} — same files, same shuffle seed, only the overlap depth
    differs — plus a raw ``scan_files`` pass over the same columns as the
    no-shuffle/no-batch reference.  Reps INTERLEAVE the two depths (this
    VM's weather — page-cache drops, CPU steal — lasts seconds to minutes,
    so alternating reps exposes both sides to the same conditions; own
    back-to-back trials have recorded the same config at 3.0s and 6.8s)."""
    import jax
    from tpu_parquet.data import DataLoader
    from tpu_parquet.device_reader import scan_files

    if reps is None:
        reps = int(os.environ.get("BENCH_LOADER_REPS", "4"))
    reps = max(reps, 1)  # 0 reps would leave the medians/stats unpopulated
    # (skip the section with BENCH_LOADER=0 instead)
    cols = ["l_orderkey", "l_partkey", "l_suppkey", "l_linenumber",
            "l_quantity", "l_extendedprice", "l_discount", "l_tax",
            "l_shipdate", "l_commitdate", "l_receiptdate"]
    # dedicated file with TRAINING-shaped row groups (~250k rows each, vs the
    # decode bench's single-transfer-optimized 1M-row groups): the loader
    # pipelines at unit granularity, and a 5-unit file spends 15% of its
    # wall on the first unit's cold decode that lookahead can never hide
    # layout-stamped name: a cached file from a build with different group
    # sizing can never be silently reused
    lpath = f"{path}.loader_rg250k"
    if not os.path.exists(lpath):
        t0 = time.perf_counter()
        gen_lineitem16(lpath, rows, rows_per_group=250_000)
        log(f"generated {lpath} in {time.perf_counter()-t0:.1f}s")
    path = lpath
    out = {"rows": rows, "batch_size": 8192, "columns": len(cols),
           "rows_per_group": 250_000}
    for p in _bench_paths(path):  # warm the page cache off the timed path
        with open(p, "rb", buffering=0) as f:
            while f.read(32 << 20):
                pass
    warm = DataLoader(_bench_paths(path), 8192, columns=cols, shuffle=True,
                      seed=11, prefetch=2, shuffle_window=1 << 16,
                      drop_remainder=True)
    for _ in warm:  # one untimed epoch: allocator/thread warmup off both sides
        pass
    times = {0: [], 4: []}
    last_stats = None
    emitted = 0
    for i in range(reps):
        for k in (0, 4):
            loader = DataLoader(_bench_paths(path), 8192, columns=cols,
                                shuffle=True, seed=11, prefetch=k,
                                shuffle_window=1 << 16, drop_remainder=True)
            t0 = time.perf_counter()
            emitted = 0
            for batch in loader:
                emitted += len(batch["l_orderkey"])
            dt = time.perf_counter() - t0
            log(f"  loader prefetch={k} rep {i}: {dt:.3f}s "
                f"({emitted/dt/1e6:.2f} M rows/s)")
            times[k].append(dt)
            if k:
                last_stats = loader.stats().as_dict()
                last_obs = loader.obs_registry().as_dict()
    # MEDIAN of the interleaved reps on BOTH sides (the repo's symmetric-
    # estimator rule): best-of would hand the ratio to whichever depth got
    # the one quiet window on this weather-prone VM
    for k in (0, 4):
        out[f"prefetch{k}_s"] = round(_median(times[k]), 3)
        out[f"prefetch{k}_reps_s"] = [round(t, 3) for t in times[k]]
        out[f"prefetch{k}_rows_per_sec"] = round(emitted / _median(times[k]), 1)
    out["decode_wait_seconds"] = last_stats["decode_wait_seconds"]
    out["window_peak_rows"] = last_stats["window_peak_rows"]
    out["obs"] = last_obs  # registry tree (histograms incl.) for the artifact
    out["rows_emitted"] = emitted
    out["loader_speedup"] = round(out["prefetch0_s"] / out["prefetch4_s"], 3)
    # raw device scan of the identical columns: what the loader's shuffle +
    # batch assembly + host residency cost against the bare multi-file scan.
    # MEDIAN of reps, like the loader sides above — the symmetric-estimator
    # rule applies to this ratio too.
    try:
        scans = []
        for _ in range(reps):
            t0 = time.perf_counter()
            arrs = []
            for colsd in scan_files(_bench_paths(path), columns=cols):
                arrs.extend(v.values for v in colsd.values()
                            if v.values is not None)
            jax.block_until_ready(arrs)
            scans.append(time.perf_counter() - t0)
        out["scan_files_reps_s"] = [round(t, 3) for t in scans]
        out["scan_files_rows_per_sec"] = round(rows / _median(scans), 1)
        out["loader_vs_scan"] = round(
            (emitted / _median(times[4]))
            / (rows / _median(scans)), 3)
    except Exception as e:  # noqa: BLE001 — reference only
        log(f"loader scan reference FAILED: {e!r}")
    log(f"loader: {out['loader_speedup']:.2f}x at prefetch=4 "
        f"({out['prefetch4_rows_per_sec']/1e6:.2f} M rows/s shuffled)")
    return out


def bench_io_faults(path, rows, reps=3):
    """Fault-tolerant IO backend bench (ISSUE 7 acceptance gate): the
    lineitem16 host decode through three store configurations —

    - ``local``: the default ``LocalStore`` path.  Banked to the ledger so
      ``--check-against`` guards the zero-fault overhead of the store
      indirection (the pre-PR pipeline numbers are the same file/decoder).
    - ``generic``: a zero-fault ``FaultInjectingStore`` (the
      GenericRangeStore machinery + range coalescing, nothing injected) —
      the pure cost of the retry/coalescing bookkeeping.
    - ``faults``: fixed injected latency per store round trip plus one
      transient error on ~1/8 of ranges — overlap efficiency shows how
      much of the injected latency the prefetch pool hides, and the retry
      counters prove the faults actually fired.
    """
    from tpu_parquet.iostore import (FaultInjectingStore, FaultSpec,
                                     IOConfig, LocalStore)
    from tpu_parquet.reader import FileReader

    inject_s = 2e-4
    cfg = IOConfig(retries=4, backoff_ms=1.0, retry_budget=0)
    flaky = FaultSpec(latency_s=inject_s, fail_first=1,
                      match=lambda off, size: (off >> 12) % 8 == 0)
    stores = {
        "local": None,
        "generic": lambda f: FaultInjectingStore(
            LocalStore(f), FaultSpec(), config=cfg, seed=0),
        "faults": lambda f: FaultInjectingStore(
            LocalStore(f), flaky, config=cfg, seed=0),
    }
    out = {"rows": rows, "injected_latency_s": inject_s}
    for tag, factory in stores.items():
        best, best_tree = float("inf"), None
        for i in range(reps):
            t0 = time.perf_counter()
            with FileReader(path, prefetch=4, store=factory) as r:
                r.read_all()
                tree = r.obs_registry().as_dict()
            dt = time.perf_counter() - t0
            log(f"  io_faults {tag} rep {i}: {dt:.3f}s "
                f"({rows/dt/1e6:.2f} M rows/s)")
            if dt < best:
                best, best_tree = dt, tree
        out[f"{tag}_s"] = round(best, 3)
        out[f"{tag}_rows_per_sec"] = round(rows / best, 1)
        out[f"{tag}_overlap_efficiency"] = (
            best_tree["pipeline"]["overlap_efficiency"])
        if best_tree["io"] is not None:
            io_tree = best_tree["io"]
            out[f"{tag}_retries"] = io_tree["retries"]
            out[f"{tag}_coalesced_spans"] = io_tree["coalesced_spans"]
            out[f"{tag}_store_reads"] = io_tree["reads"]
    # the two ratios the section exists for: indirection cost on the local
    # path (gate target <= 1.02x) and the injected-fault recovery cost
    out["store_overhead_ratio"] = round(out["generic_s"] / out["local_s"], 3)
    out["fault_overhead_ratio"] = round(out["faults_s"] / out["local_s"], 3)
    log(f"io_faults: store overhead {out['store_overhead_ratio']:.3f}x, "
        f"with faults {out['fault_overhead_ratio']:.3f}x "
        f"({out.get('faults_retries', 0)} retries recovered)")
    return out


def bench_data_faults(path, rows, reps=3):
    """Corruption-containment bench (ISSUE 8 acceptance gate), two halves:

    - the clean path: the lineitem16 host decode with validation OFF vs the
      round-13 default (``validate="crc"``; bench files carry CRCs) —
      ``validate_overhead_ratio`` is the <1.03x guard the default-on tier
      must hold;
    - the dirty path: a copy of the file with ~1 corrupt page per 100 is
      read under ``skip_unit`` — ``quarantined`` proves the faults fired
      and were contained, ``faulty_s`` what a degraded scan costs.
    """
    import shutil

    from tpu_parquet.reader import FileReader
    from tpu_parquet.writer import corrupt_page

    out = {"rows": rows}
    for tag, validate in (("novalidate", False), ("validate", "crc")):
        best = float("inf")
        for i in range(reps):
            t0 = time.perf_counter()
            with FileReader(path, prefetch=4, validate_crc=validate) as r:
                r.read_all()
            dt = time.perf_counter() - t0
            log(f"  data_faults {tag} rep {i}: {dt:.3f}s "
                f"({rows/dt/1e6:.2f} M rows/s)")
            best = min(best, dt)
        out[f"{tag}_s"] = round(best, 3)
        out[f"{tag}_rows_per_sec"] = round(rows / best, 1)
    out["validate_overhead_ratio"] = round(
        out["validate_s"] / out["novalidate_s"], 3)

    dirty = path + ".corrupt"
    shutil.copyfile(path, dirty)
    try:
        from tpu_parquet.footer import read_file_metadata

        with open(dirty, "rb") as f:
            md = read_file_metadata(f)
        n_cols = len(md.row_groups[0].columns or [])
        corrupted = 0
        for gi in range(len(md.row_groups)):
            # ~1 corrupt page per 100 columns-chunks, deterministic spread
            for ci in range(n_cols):
                if (gi * n_cols + ci) % 100 == 0:
                    corrupt_page(dirty, row_group=gi, column=ci,
                                 mode="bitflip", seed=gi * 131 + ci)
                    corrupted += 1
        best, q = float("inf"), None
        for i in range(reps):
            t0 = time.perf_counter()
            with FileReader(dirty, prefetch=4,
                            on_data_error="skip_unit") as r:
                r.read_all()
                q = r.quarantine
            dt = time.perf_counter() - t0
            log(f"  data_faults skip_unit rep {i}: {dt:.3f}s "
                f"({q.units_skipped} unit(s) skipped)")
            best = min(best, dt)
        out["faulty_s"] = round(best, 3)
        out["pages_corrupted"] = corrupted
        out["quarantined"] = len(q.log)
        out["units_skipped"] = q.units_skipped
    finally:
        os.unlink(dirty)
    log(f"data_faults: validate overhead "
        f"{out['validate_overhead_ratio']:.3f}x (gate <= 1.03), "
        f"{out['quarantined']}/{out['pages_corrupted']} corruptions "
        f"quarantined under skip_unit")
    return out


def bench_serve(path, rows, clients_sweep=(1, 4, 16)):
    """High-QPS scan service bench (ISSUE 10): a concurrency sweep over ONE
    shared ScanService vs the same queries run sequentially one-shot.

    Each of N client threads runs Q queries (rotating column projections,
    host decode) through a shared service whose PlanCache holds footers,
    ScanPlan IR, and decoded dictionaries; the one-shot baseline opens a
    fresh FileReader per query — paying the footer parse, the plan build,
    and the dictionary decode every time.  Reports per-clients wall +
    p50/p95 request latency + cache hit rate, and ``plan_cache_speedup``:
    one-shot per-query wall / served-at-1-client per-query wall (same
    concurrency, so the delta IS the shared-state win).  Skip with
    BENCH_SERVE=0; ``--smoke`` exercises it end to end.
    """
    import threading

    from tpu_parquet.reader import FileReader
    from tpu_parquet.serve import ScanRequest, ScanService

    q_per_client = int(os.environ.get("BENCH_SERVE_QUERIES", "6"))
    with FileReader(path) as r0:
        cols = [".".join(l.path) for l in r0.schema.selected_leaves()]
    projections = [None, cols[: max(len(cols) // 2, 1)], cols[:1]]
    out = {"rows": rows, "queries_per_client": q_per_client}

    # one-shot baseline: fresh reader per query, nothing shared
    t0 = time.perf_counter()
    for i in range(q_per_client):
        with FileReader(path, columns=projections[i % len(projections)]) as r:
            r.read_all()
    oneshot_s = time.perf_counter() - t0
    out["oneshot_wall_s"] = round(oneshot_s, 4)
    out["oneshot_per_query_s"] = round(oneshot_s / q_per_client, 5)
    log(f"  serve one-shot: {q_per_client} queries in {oneshot_s:.3f}s")

    for clients in clients_sweep:
        svc = ScanService(concurrency=min(clients, 8),
                          queue_depth=max(2 * clients, 4))
        errors = []

        def run_client(ci):
            try:
                for i in range(q_per_client):
                    svc.scan(ScanRequest(
                        path, columns=projections[(ci + i)
                                                  % len(projections)]))
            except Exception as e:  # noqa: BLE001 — reported, not fatal
                errors.append(repr(e))

        t0 = time.perf_counter()
        threads = [threading.Thread(target=run_client, args=(ci,))
                   for ci in range(clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        tree = svc.obs_registry().as_dict()
        svc.close()
        sv = tree["serve"]
        cache = sv["cache"]
        hits = sum(cache[f"{k}_hits"] for k in ("footer", "plan", "dict"))
        total = hits + sum(cache[f"{k}_misses"]
                           for k in ("footer", "plan", "dict"))
        hist = (tree.get("histograms") or {}).get("serve.request") or {}
        nq = clients * q_per_client
        from tpu_parquet.obs import LatencyHistogram as _LH
        p99_s = _LH.from_dict(hist).quantile(0.99) if hist else 0.0
        entry = {
            "wall_s": round(wall, 4),
            "per_query_s": round(wall / nq, 5),
            "queries": nq,
            "p50_ms": round(float(hist.get("p50_seconds", 0.0)) * 1e3, 3),
            "p95_ms": round(float(hist.get("p95_seconds", 0.0)) * 1e3, 3),
            "p99_ms": round(p99_s * 1e3, 3),
            "cache_hit_rate": round(hits / total, 4) if total else 0.0,
            "queue_wait_s": sv["queue_wait_seconds"],
        }
        if errors:
            entry["errors"] = errors[:3]
        out[f"clients{clients}"] = entry
        log(f"  serve {clients} client(s): {nq} queries in {wall:.3f}s "
            f"(p95 {entry['p95_ms']:.1f}ms, p99 {entry['p99_ms']:.1f}ms, "
            f"hit rate {entry['cache_hit_rate']:.0%})")
    c1 = out.get("clients1")
    if c1 and c1["per_query_s"]:
        out["plan_cache_speedup"] = round(
            out["oneshot_per_query_s"] / c1["per_query_s"], 3)
        log(f"serve: plan_cache_speedup "
            f"{out['plan_cache_speedup']:.2f}x (shared plan/footer/dict "
            f"cache vs one-shot opens)")
    return out


def bench_serve_cache(path, rows, smoke=False):
    """Tiered result-cache A/B over the serve tier (ISSUE 14).

    Three phases, all against real ``ScanService`` instances:

    1. **hot/cold A/B** — the same repeated scan of the bench file with the
       result tier OFF (``result_cache_mb=0`` — the PR 10 plan/footer/dict
       cache baseline) vs ON; banks per-phase p50 and
       ``warm_speedup_p50`` (cold p50 / warm p50 — the decode work a hot
       request no longer does);
    2. **zipfian mix** — a hot-set + long-tail access pattern over K small
       generated files with the cache sized to hold roughly the hot set:
       banks p50/p95/p99 and per-tier hit rates (the realistic "millions
       of users re-scan hot files" shape);
    3. **mutation mid-sweep** — a warmed file is rewritten in place
       (generation moves): banks the exact ``invalidations`` delta and
       proves the served bytes are the NEW file's, never stale.

    Skip with BENCH_SERVE_CACHE=0; ``--smoke`` runs every phase tiny.
    """
    import shutil
    import tempfile

    import numpy as np

    from tpu_parquet.obs import LatencyHistogram as _LH
    from tpu_parquet.serve import ScanRequest, ScanService

    reps = 6 if smoke else int(os.environ.get("BENCH_SERVE_CACHE_QUERIES",
                                              "16"))
    out = {"rows": rows, "queries": reps}

    def latencies(svc, reqs):
        lat = []
        for rq in reqs:
            t0 = time.perf_counter()
            svc.scan(rq)
            lat.append(time.perf_counter() - t0)
        lat.sort()
        return lat

    def q(lat, f):
        return lat[min(int(f * len(lat)), len(lat) - 1)] if lat else 0.0

    # -- phase 1: hot/cold A/B on the bench file ---------------------------
    with ScanService(concurrency=2, result_cache_mb=0) as svc:
        svc.scan(ScanRequest(path))  # warm the plan/footer/dict cache
        cold = latencies(svc, [ScanRequest(path) for _ in range(reps)])
    with ScanService(concurrency=2, result_cache_mb=1024) as svc:
        svc.scan(ScanRequest(path))  # one populating scan
        warm = latencies(svc, [ScanRequest(path) for _ in range(reps)])
        ch = svc.cache.results.counters()["host"]
    out["cold_p50_ms"] = round(q(cold, 0.5) * 1e3, 3)
    out["warm_p50_ms"] = round(q(warm, 0.5) * 1e3, 3)
    out["warm_speedup_p50"] = round(
        q(cold, 0.5) / q(warm, 0.5), 2) if q(warm, 0.5) else 0.0
    out["warm_hit_rate"] = round(
        ch["hits"] / (ch["hits"] + ch["misses"]), 4) \
        if ch["hits"] + ch["misses"] else 0.0
    log(f"  serve_cache A/B: cold p50 {out['cold_p50_ms']:.2f}ms, warm p50 "
        f"{out['warm_p50_ms']:.2f}ms ({out['warm_speedup_p50']:.1f}x, "
        f"hit rate {out['warm_hit_rate']:.0%})")

    # -- small generated files for the zipf + mutation phases --------------
    def write_small(p, seed, n):
        from tpu_parquet.format import (CompressionCodec,
                                        FieldRepetitionType as FRT, Type)
        from tpu_parquet.schema.core import build_schema, data_column
        from tpu_parquet.writer import FileWriter

        rng = np.random.default_rng(seed)
        schema = build_schema([data_column("a", Type.INT64, FRT.REQUIRED),
                               data_column("b", Type.INT64, FRT.REQUIRED)])
        with open(p, "wb") as fh:
            with FileWriter(fh, schema,
                            codec=CompressionCodec.SNAPPY) as w:
                for _g in range(2):
                    w.write_columns({
                        "a": rng.integers(-(1 << 40), 1 << 40, n // 2),
                        "b": rng.integers(0, 1 << 20, n // 2)})
                    w.flush_row_group()
        return p

    tmp = tempfile.mkdtemp(prefix="tpq_serve_cache_")
    try:
        n_files = 5 if smoke else 8
        n_rows = 2_000 if smoke else 50_000
        zq = 40 if smoke else 200
        files = [write_small(os.path.join(tmp, f"z{i}.parquet"), i, n_rows)
                 for i in range(n_files)]
        # size the cache to ~2.5 files' decoded bytes (rounded UP to the
        # MB knob granularity): the hot set fits, the long tail churns —
        # the shape the tier exists for
        per_file = max(n_rows * 16, 1)
        cache_mb = max(-(-int(2.5 * per_file) // (1 << 20)), 1)
        rng = np.random.default_rng(7)
        ranks = np.minimum(rng.zipf(1.3, zq) - 1, n_files - 1)
        with ScanService(concurrency=2, result_cache_mb=cache_mb) as svc:
            lat = latencies(svc, [ScanRequest(files[r]) for r in ranks])
            tree = svc.obs_registry().as_dict()
        ct = tree["cache"]["host"]
        hist = (tree.get("histograms") or {}).get("serve.request") or {}
        zipf = {
            "files": n_files, "queries": zq, "cache_mb": cache_mb,
            "p50_ms": round(q(lat, 0.5) * 1e3, 3),
            "p95_ms": round(q(lat, 0.95) * 1e3, 3),
            "p99_ms": round(
                _LH.from_dict(hist).quantile(0.99) * 1e3
                if hist else q(lat, 0.99) * 1e3, 3),
            "host_hit_rate": round(
                ct["hits"] / (ct["hits"] + ct["misses"]), 4)
            if ct["hits"] + ct["misses"] else 0.0,
            "evictions": ct["evictions"],
        }
        out["zipf"] = zipf
        log(f"  serve_cache zipf: {zq} queries over {n_files} files, p50 "
            f"{zipf['p50_ms']:.2f}ms p99 {zipf['p99_ms']:.2f}ms, host hit "
            f"rate {zipf['host_hit_rate']:.0%}, "
            f"{zipf['evictions']} evictions")

        # -- phase 3: mutation mid-sweep ----------------------------------
        mut = os.path.join(tmp, "mut.parquet")
        write_small(mut, 100, n_rows)
        with ScanService(concurrency=2, result_cache_mb=cache_mb) as svc:
            first = svc.scan(ScanRequest(mut))[mut]
            svc.scan(ScanRequest(mut))  # provably warm
            inv0 = svc.cache.results.counters()["host"]["invalidations"]
            write_small(mut, 101, n_rows)  # new generation, new bytes
            st = os.stat(mut)
            os.utime(mut, ns=(st.st_atime_ns, st.st_mtime_ns + 1_000_000))
            after = svc.scan(ScanRequest(mut))[mut]
            inv1 = svc.cache.results.counters()["host"]["invalidations"]
        stale = bool(np.array_equal(first["a"].values, after["a"].values))
        out["mutation"] = {"invalidations": inv1 - inv0,
                           "stale_served": stale}
        log(f"  serve_cache mutation: {inv1 - inv0} invalidations, "
            f"stale_served={stale}")
        if stale or inv1 - inv0 <= 0:
            raise RuntimeError(
                f"result-cache mutation phase failed: stale={stale}, "
                f"invalidations={inv1 - inv0}")
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    return out


def bench_fused(files, smoke=False):
    """Fused-vs-unfused decode A/B per dominant kernel family (ISSUE 13).

    For each family the PR 9 registry names as dominant on the bench
    configs — ``plain`` (plain_int64's fixed-width lane) and
    ``narrow_snappy`` (lineitem16's narrow lane) — one forced-route scan
    per side (``TPQ_FORCE_ROUTE`` accepts the fused names exactly for
    this A/B), banking the registry ``device`` section's per-route
    ``device_seconds`` / ``dispatches`` / ``device_passes`` plus the
    degrade counter.  The structural bar holds in ANY mode: fused routes
    must show device_passes == dispatches (one pass per (row group,
    column)) where the unfused twin shows >= 3 per dispatch.  The TIMING
    bar (fused device_seconds <= unfused) only binds on compiled (Mosaic)
    runs — ``pallas_mode`` rides the record so the ledger knows which
    kind this was; interpret-mode seconds are not kernel measurements.
    Skip with BENCH_FUSED=0; --smoke runs it tiny.
    """
    from tpu_parquet.device_reader import DeviceFileReader
    from tpu_parquet.pallas_kernels import pallas_mode

    def one(path, route):
        # save/restore, not pop: an operator-forced route must survive this
        # section for the later ones and the ledger env fingerprint
        prev = os.environ.get("TPQ_FORCE_ROUTE")
        os.environ["TPQ_FORCE_ROUTE"] = route
        try:
            t0 = time.perf_counter()
            with DeviceFileReader(path) as r:
                for _ in r.iter_row_groups():
                    pass
                wall = time.perf_counter() - t0
                st = r.stats().as_dict()
                dev = (r.obs_registry().as_dict().get("device")
                       or {}).get("routes") or {}
        finally:
            if prev is None:
                os.environ.pop("TPQ_FORCE_ROUTE", None)
            else:
                os.environ["TPQ_FORCE_ROUTE"] = prev
        c = dev.get(route) or {}
        return {
            "route": route,
            "wall_seconds": round(wall, 4),
            "device_seconds": c.get("device_seconds", 0.0),
            "dispatches": c.get("dispatches", 0),
            "device_passes": c.get("device_passes", 0),
            "streams": (st["ship_routes"].get(route) or {}).get("streams", 0),
            "fused_fallbacks": st.get("fused_fallbacks", 0),
        }

    prev_fuse = os.environ.get("TPQ_FUSE")
    os.environ["TPQ_FUSE"] = "1"
    out = {"pallas_mode": pallas_mode(), "families": {}}
    try:
        for family, fused_route, path in (
                ("plain", "fused_plain", files.get("plain_int64")),
                ("narrow_snappy", "fused_narrow_snappy",
                 files.get("lineitem16"))):
            if path is None:
                continue
            fused = one(path, fused_route)
            unfused = one(path, family)
            fam = {"fused": fused, "unfused": unfused}
            if fused["dispatches"]:
                fam["fused_passes_per_dispatch"] = round(
                    fused["device_passes"] / fused["dispatches"], 3)
            if unfused["dispatches"]:
                fam["unfused_passes_per_dispatch"] = round(
                    unfused["device_passes"] / unfused["dispatches"], 3)
            if fused["device_seconds"] and unfused["device_seconds"]:
                fam["device_seconds_ratio"] = round(
                    fused["device_seconds"] / unfused["device_seconds"], 4)
            out["families"][family] = fam
            log(f"  fused[{family}]: fused {fused['dispatches']} disp/"
                f"{fused['device_passes']} passes "
                f"{fused['device_seconds']:.6f}s (fallbacks "
                f"{fused['fused_fallbacks']}) vs unfused "
                f"{unfused['dispatches']} disp/{unfused['device_passes']} "
                f"passes {unfused['device_seconds']:.6f}s")
    finally:
        if prev_fuse is None:
            os.environ.pop("TPQ_FUSE", None)
        else:
            os.environ["TPQ_FUSE"] = prev_fuse
    return out


def bench_serve_faults(path, rows, smoke=False):
    """Fault-injected serve sweep (ISSUE 11): the same shared ScanService
    under a seeded stall storm, hedging OFF vs ON.

    Every 4th KiB-aligned range's FIRST attempt stalls (the
    FaultInjectingStore ``stall_first`` shape — retries recover, so
    results stay bit-identical); without hedging each stalled range costs
    ~stall_s of tail, with hedging the duplicate fetch (attempt 2 at the
    same offset: clean) wins the race after ``hedge_ms``.  Banks p50/p95/
    p99 per mode, the hedge win-rate + wasted bytes that justify it, a
    brownout micro-phase's shed counts, and the leaked-thread count (the
    hedge duplicate path rides the exit-3 gate).  Skip with
    BENCH_SERVE_FAULTS=0; ``--smoke`` runs a tiny phase.
    """
    import threading

    from tpu_parquet.errors import OverloadError
    from tpu_parquet.iostore import (FaultInjectingStore, FaultSpec,
                                     IOConfig, LocalStore)
    from tpu_parquet.obs import LatencyHistogram
    from tpu_parquet.reader import FileReader
    from tpu_parquet.serve import (PRIORITY_HIGH, PRIORITY_LOW, ScanRequest,
                                   ScanService)

    clients = 2 if smoke else 4
    q_per_client = 2 if smoke else int(
        os.environ.get("BENCH_SERVE_FAULT_QUERIES", "6"))
    stall_s = 0.08 if smoke else 0.3
    hedge_ms = 10.0
    spec = FaultSpec(stall_first=1, stall_s=stall_s,
                     match=lambda o, s: (o >> 10) % 4 == 0)
    with FileReader(path) as r0:
        cols = [".".join(l.path) for l in r0.schema.selected_leaves()]
        expect = r0.read_all()
    out = {"rows": rows, "stall_s": stall_s, "hedge_ms": hedge_ms,
           "queries": clients * q_per_client}

    for mode, h_ms in (("hedge_off", 0.0), ("hedge_on", hedge_ms)):
        cfg = IOConfig(retries=4, backoff_ms=1.0, hedge_ms=h_ms,
                       hedge_max=8)
        svc = ScanService(
            concurrency=min(clients, 4), queue_depth=max(4 * clients, 8),
            store=lambda f: FaultInjectingStore(LocalStore(f), spec,
                                                config=cfg))
        errors = []

        def run_client(ci):
            try:
                for i in range(q_per_client):
                    svc.scan(ScanRequest(
                        path, columns=[cols[(ci + i) % len(cols)]]),
                        timeout=600)
            except Exception as e:  # noqa: BLE001 — reported, not fatal
                errors.append(repr(e))

        t0 = time.perf_counter()
        threads = [threading.Thread(target=run_client, args=(ci,))
                   for ci in range(clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        # bit-identity proof: a full-response scan through the faulted
        # (and possibly hedged) path must match the clean one-shot read
        # byte for byte — exactly the lie hedge_mismatches exists to catch
        import numpy as _np

        res = svc.scan(ScanRequest(path), timeout=600)[path]
        for name, want in expect.items():
            got = res[name]
            parts = got if isinstance(got, list) else [got]
            got_rows = sum(p.num_leaf_slots for p in parts)
            assert got_rows == want.num_leaf_slots, \
                f"{mode}: {name} rows {got_rows} != {want.num_leaf_slots}"
            wv = want.values
            if hasattr(wv, "heap"):
                got_heap = _np.concatenate(
                    [_np.asarray(p.values.heap) for p in parts])
                assert _np.array_equal(got_heap, _np.asarray(wv.heap)), \
                    f"{mode}: {name} heap bytes diverged"
            else:
                got_vals = _np.concatenate(
                    [_np.asarray(p.values) for p in parts])
                assert (got_vals.view(_np.uint8).tobytes()
                        == _np.asarray(wv).view(_np.uint8).tobytes()), \
                    f"{mode}: {name} value bytes diverged"
        tree = svc.obs_registry().as_dict()
        svc.close()
        hist = (tree.get("histograms") or {}).get("serve.request") or {}
        h = LatencyHistogram.from_dict(hist) if hist else LatencyHistogram()
        io = tree.get("io") or {}
        issued = int(io.get("hedges_issued", 0))
        entry = {
            "wall_s": round(wall, 4),
            "p50_ms": round(h.quantile(0.5) * 1e3, 3),
            "p95_ms": round(h.quantile(0.95) * 1e3, 3),
            "p99_ms": round(h.quantile(0.99) * 1e3, 3),
            "hedges_issued": issued,
            "hedges_won": int(io.get("hedges_won", 0)),
            "hedge_win_rate": (round(io.get("hedges_won", 0) / issued, 3)
                               if issued else 0.0),
            "hedges_wasted_bytes": int(io.get("hedges_wasted_bytes", 0)),
            "retries": int(io.get("retries", 0)),
        }
        if errors:
            entry["errors"] = errors[:3]
        out[mode] = entry
        log(f"  serve_faults {mode}: p99 {entry['p99_ms']:.1f}ms "
            f"(p50 {entry['p50_ms']:.1f}ms), {issued} hedges, "
            f"win rate {entry['hedge_win_rate']:.0%}")
    if out["hedge_off"]["p99_ms"]:
        out["p99_cut_ratio"] = round(
            out["hedge_on"]["p99_ms"] / out["hedge_off"]["p99_ms"], 3)
        log(f"serve_faults: hedged p99 is "
            f"{out['p99_cut_ratio']:.2f}x of unhedged under the stall "
            f"storm (lower is better)")

    # brownout micro-phase: a burst past capacity sheds LOW with a
    # retry_after_s hint while HIGH still admits
    svc = ScanService(concurrency=1, queue_depth=4, brownout=0.25,
                      store=lambda f: FaultInjectingStore(
                          LocalStore(f),
                          FaultSpec(latency_s=0.03),
                          config=IOConfig(backoff_ms=1.0)))
    tickets, shed_hint = [], None
    for i in range(12):
        try:
            tickets.append(svc.submit(ScanRequest(
                path, columns=[cols[0]], priority=PRIORITY_LOW)))
        except OverloadError as e:
            shed_hint = e.retry_after_s
    high_ok = True
    try:
        tickets.append(svc.submit(ScanRequest(
            path, columns=[cols[0]], priority=PRIORITY_HIGH)))
    except OverloadError:
        high_ok = False
    for t in tickets:
        try:
            t.result(600)
        except Exception:  # noqa: BLE001 — shed accounting is the product
            pass
    sheds = svc.serve_stats()["sheds"]
    svc.close()
    out["brownout"] = {"sheds": sheds, "high_admitted": high_ok,
                      "retry_after_s": shed_hint}
    log(f"  serve_faults brownout: shed {sheds} "
        f"(high admitted: {high_ok}, retry_after {shed_hint})")
    leaked = [t.name for t in threading.enumerate()
              if t.name.startswith("tpq-hedge")]
    out["leaked_hedge_threads"] = len(leaked)
    assert not leaked, f"hedge racers leaked: {leaked}"
    return out


def bench_serve_tenants(path, rows, smoke=False):
    """Noisy-neighbor QoS A/B (ISSUE 17): a victim tenant's request
    latency isolated, then under a noisy tenant's flood with the global
    FIFO queue, then under weighted deficit-round-robin fair-share.

    One worker (concurrency=1) + a fixed per-range injected latency +
    result cache OFF make each request's cost deterministic, so the
    queueing discipline is the ONLY variable: under FIFO the victim's
    burst waits behind the whole flood; under fair-share (victim weight 3
    vs noisy 1) it overtakes after at most a quantum.  Banks victim
    p50/p95/p99 per phase and the fifo/fair degradation ratios, plus the
    per-tenant serve accounting that proves both tenants ran.  Streaming
    sessions ride the same tpq-serve workers, so this phase's clean-close
    assertion (and the exit-3 gate's ``tpq-serve`` prefix) covers them.
    Skip with BENCH_SERVE_TENANTS=0; ``--smoke`` runs a tiny phase.
    """
    import threading

    from tpu_parquet.iostore import (FaultInjectingStore, FaultSpec,
                                     IOConfig, LocalStore)
    from tpu_parquet.reader import FileReader
    from tpu_parquet.serve import ScanRequest, ScanService

    lat = 0.004 if smoke else 0.02
    noisy_n = 6 if smoke else 20
    victim_n = 3 if smoke else 6
    rounds = 1 if smoke else 2
    with FileReader(path) as r0:
        col = ".".join(r0.schema.selected_leaves()[0].path)

    def mk_svc(fair):
        svc = ScanService(
            concurrency=1, queue_depth=4 * (noisy_n + victim_n),
            fair=fair, result_cache_mb=0,
            store=lambda f: FaultInjectingStore(
                LocalStore(f), FaultSpec(latency_s=lat),
                config=IOConfig(backoff_ms=1.0)))
        svc.register_tenant("victim", weight=3)
        svc.register_tenant("noisy", weight=1)
        return svc

    def quantile(walls, q):
        s = sorted(walls)
        return s[min(int(q * len(s)), len(s) - 1)]

    def victim_burst(svc):
        walls = []
        for _ in range(victim_n):
            t0 = time.perf_counter()
            svc.scan(ScanRequest(path, columns=[col], tenant="victim"),
                     timeout=600)
            walls.append(time.perf_counter() - t0)
        return walls

    out = {"rows": rows, "latency_s": lat, "noisy_requests": noisy_n,
           "victim_requests": victim_n * rounds, "victim_weight": 3}
    for phase, fair in (("isolated", True), ("fifo", False), ("fair", True)):
        svc = mk_svc(fair)
        walls, noisy_tickets = [], []
        for _ in range(rounds):
            if phase != "isolated":
                noisy_tickets += [
                    svc.submit(ScanRequest(path, columns=[col],
                                           tenant="noisy"))
                    for _ in range(noisy_n)]
            walls += victim_burst(svc)
        for t in noisy_tickets:
            t.result(600)
        stats = svc.serve_stats()
        svc.close()
        out[phase] = {
            "p50_ms": round(quantile(walls, 0.5) * 1e3, 3),
            "p95_ms": round(quantile(walls, 0.95) * 1e3, 3),
            "p99_ms": round(quantile(walls, 0.99) * 1e3, 3),
            "victim_submitted": stats["tenants"]["victim"]["submitted"],
            "noisy_submitted": stats["tenants"].get(
                "noisy", {}).get("submitted", 0),
        }
        log(f"  serve_tenants {phase}: victim p99 "
            f"{out[phase]['p99_ms']:.1f}ms (p50 {out[phase]['p50_ms']:.1f}"
            f"ms)")
    base = out["isolated"]["p99_ms"] or 1e-9
    out["fifo_ratio"] = round(out["fifo"]["p99_ms"] / base, 3)
    out["fair_ratio"] = round(out["fair"]["p99_ms"] / base, 3)
    log(f"serve_tenants: victim p99 degradation under flood — FIFO "
        f"{out['fifo_ratio']:.1f}x vs fair-share {out['fair_ratio']:.1f}x "
        f"of isolated (lower is better)")
    # structural bar: with one worker and a deterministic per-request
    # cost, fair-share MUST beat FIFO for the victim — equality means the
    # scheduler isn't actually discriminating by tenant
    assert out["fair"]["p99_ms"] < out["fifo"]["p99_ms"], out

    # streaming slot-yield A/B (ISSUE 20): the same single worker, but the
    # noisy tenant holds a LONG streaming session instead of a flood.
    # Slot-pinned (stream_yield=False), the session owns the only worker
    # until the whole file has streamed and every victim one-shot queues
    # behind it; with batch-granular yielding the session re-queues itself
    # whenever another tenant is waiting (DRR at batch granularity), so
    # the victim overtakes after at most one batch.
    batch_rows = max(rows // 32, 1)
    for phase, yield_on in (("stream_pinned", False), ("stream_yield", True)):
        svc = ScanService(
            concurrency=1, queue_depth=4 * (noisy_n + victim_n),
            fair=True, result_cache_mb=0, stream_yield=yield_on,
            store=lambda f: FaultInjectingStore(
                LocalStore(f), FaultSpec(latency_s=lat),
                config=IOConfig(backoff_ms=1.0)))
        svc.register_tenant("victim", weight=3)
        svc.register_tenant("noisy", weight=1)
        session = svc.submit(ScanRequest(
            path, columns=[col], tenant="noisy", stream=True,
            batch_rows=batch_rows)).result(600)
        batches = []
        consumer = threading.Thread(
            target=lambda: batches.extend(1 for _ in session),
            name="bench-stream-drain")
        consumer.start()
        walls = victim_burst(svc)
        consumer.join(600)
        stats = svc.serve_stats()
        svc.close()
        out[phase] = {
            "p50_ms": round(quantile(walls, 0.5) * 1e3, 3),
            "p99_ms": round(quantile(walls, 0.99) * 1e3, 3),
            "stream_batches": len(batches),
            "slot_yields": stats.get("stream_yields", 0),
        }
        log(f"  serve_tenants {phase}: victim p99 "
            f"{out[phase]['p99_ms']:.1f}ms over {len(batches)} streamed "
            f"batch(es), {out[phase]['slot_yields']} slot yield(s)")
    out["stream_yield_ratio"] = round(
        out["stream_yield"]["p99_ms"]
        / (out["stream_pinned"]["p99_ms"] or 1e-9), 3)
    # structural bar: yielding MUST improve the victim's p99 against the
    # slot-pinned stream, and the yield counter must prove the mechanism
    # actually fired (not a lucky scheduling accident)
    assert out["stream_yield"]["p99_ms"] < out["stream_pinned"]["p99_ms"], out
    assert out["stream_yield"]["slot_yields"] > 0, out["stream_yield"]
    leaked = [t.name for t in threading.enumerate()
              if t.name.startswith("tpq-serve")]
    out["leaked_serve_threads"] = len(leaked)
    assert not leaked, f"serve workers leaked: {leaked}"
    return out


def bench_io_scale(path, rows, smoke=False):
    """IO-concurrency scaling A/B (ISSUE 18): the async fetch engine vs a
    blocking-read thread pool, sweeping the in-flight target under a fixed
    per-range injected latency.

    Each leg fetches k ranges through a 50ms-latency store.  The threaded
    leg uses a pool capped at 32 workers — the realistic decode-worker
    ceiling the old path had (in the pipeline, ``prefetch=`` bounds it);
    the engine leg multiplexes all k as futures on ONE loop thread with
    ``max_inflight=k``.  At k=8 the legs tie; by k=256 the pool is queue-
    bound at its thread cap while the engine overlaps everything — the
    banked ratio is the headline.  Results must be byte-identical between
    legs and no engine/pool thread may survive the phase.  Skip with
    BENCH_IOSCALE=0; ``--smoke`` runs a tiny sweep.
    """
    import threading
    from concurrent.futures import ThreadPoolExecutor

    from tpu_parquet.iostore import (FaultInjectingStore, FaultSpec,
                                     IOConfig, LocalStore)
    from tpu_parquet.iostore_async import FetchEngine

    lat = 0.01 if smoke else 0.05
    sweep = (4, 16) if smoke else (8, 64, 256)
    pool_cap = 32
    rsize = 4096
    fsize = os.path.getsize(path)

    def ranges_for(k):
        step = max((fsize - rsize) // max(k, 1), 1)
        return [((i * step) % max(fsize - rsize, 1), rsize)
                for i in range(k)]

    def mk_store(f):
        return FaultInjectingStore(
            LocalStore(f), FaultSpec(latency_s=lat),
            config=IOConfig(backoff_ms=1.0))

    def quantile(walls, q):
        s = sorted(walls)
        return s[min(int(q * len(s)), len(s) - 1)]

    out = {"rows": rows, "latency_s": lat, "pool_threads": pool_cap,
           "range_bytes": rsize}
    for k in sweep:
        want = ranges_for(k)
        fobj = open(path, "rb")
        st_t = mk_store(fobj)
        walls_t = []

        def read_one(r, _st=st_t, _w=walls_t):
            t0 = time.perf_counter()
            buf = _st.read_range(*r)
            _w.append(time.perf_counter() - t0)
            return bytes(buf)

        t0 = time.perf_counter()
        with ThreadPoolExecutor(max_workers=min(k, pool_cap)) as ex:
            got_t = list(ex.map(read_one, want))
        wall_t = time.perf_counter() - t0

        st_e = mk_store(fobj)
        eng = FetchEngine(max_inflight=k)
        walls_e, done_at = [], {}
        try:
            t0 = time.perf_counter()
            futs = [eng.submit(st_e, o, s) for o, s in want]
            for f in futs:
                f.add_done_callback(
                    lambda _f, _t0=t0: done_at.setdefault(
                        id(_f), time.perf_counter() - _t0))
            got_e = [bytes(f.result(timeout=600)) for f in futs]
            wall_e = time.perf_counter() - t0
            walls_e = [done_at[id(f)] for f in futs]
            peak = eng.stats.inflight_peak
        finally:
            eng.close()
            fobj.close()
        assert got_t == got_e, \
            f"engine leg diverged from threaded leg at k={k}"
        ratio = wall_t / wall_e if wall_e else 0.0
        out[f"k{k}"] = {
            "threaded_s": round(wall_t, 4), "engine_s": round(wall_e, 4),
            "ratio": round(ratio, 3),
            "threaded_p99_ms": round(quantile(walls_t, 0.99) * 1e3, 2),
            "engine_p99_ms": round(quantile(walls_e, 0.99) * 1e3, 2),
            "engine_inflight_peak": peak,
        }
        log(f"  io_scale k={k}: threaded {wall_t:.3f}s vs engine "
            f"{wall_e:.3f}s ({ratio:.1f}x), engine peak {peak} in flight")
        if not smoke and k > pool_cap:
            # structural bar: past the pool's thread cap the engine MUST
            # win — parity there means it isn't actually multiplexing
            assert ratio >= (4.0 if k >= 8 * pool_cap else 1.2), out
    leaked = [t.name for t in threading.enumerate()
              if t.name.startswith("tpq-fetch")]
    out["leaked_engine_threads"] = len(leaked)
    assert not leaked, f"fetch-engine threads leaked: {leaked}"
    return out


def bench_obs_overhead(path, rows, smoke=False):
    """Tracing-cost A/B (ISSUE 19): the serve workload with request
    tracing disabled (``TPQ_TRACE_TAIL=0``), tail-sampled at the default
    rate, and retain-all (``TPQ_TRACE_TAIL=1``).

    Each leg runs the same warmed multi-client query mix through a fresh
    ``ScanService`` and banks p50/p99 request latency from the service's
    own histogram; the headline is ``tail_p50_overhead`` (tail-sampled
    p50 / tracing-off p50 — the cost every production request pays).  The
    acceptance figure is <=1.03; the asserted bar is looser because
    sub-millisecond p50s are scheduler-noise-dominated at bench scale.
    The retain-all leg additionally proves the export ring honours its
    byte bound and that the off leg creates no traces at all.  The
    ``fleet`` leg (ISSUE 20) re-runs the tail-sampled mix with the
    cross-process spool armed (``TPQ_OBS_SPOOL``, fast cadence) — its
    headline ``fleet_p50_overhead`` is the snapshot publisher's cost on
    top of tail sampling (acceptance figure <=1.03), and the leg proves
    the published generations aggregate cleanly.  Skip with BENCH_OBS=0;
    ``--smoke`` runs a tiny mix.
    """
    import shutil
    import tempfile
    import threading

    from tpu_parquet.reader import FileReader
    from tpu_parquet.serve import ScanRequest, ScanService

    q_per_client = (4 if smoke
                    else int(os.environ.get("BENCH_OBS_QUERIES", "24")))
    clients = 2 if smoke else 4
    with FileReader(path) as r0:
        cols = [".".join(l.path) for l in r0.schema.selected_leaves()]
    projections = [None, cols[: max(len(cols) // 2, 1)], cols[:1]]
    out = {"rows": rows, "queries": clients * q_per_client}
    saved = os.environ.get("TPQ_TRACE_TAIL")
    saved_spool = {k: os.environ.get(k)
                   for k in ("TPQ_OBS_SPOOL", "TPQ_OBS_SPOOL_S")}
    spool_dir = tempfile.mkdtemp(prefix="tpq-bench-spool-")
    try:
        for leg, val in (("off", "0"), ("tail", None), ("retain_all", "1"),
                         ("fleet", None)):
            if val is None:
                os.environ.pop("TPQ_TRACE_TAIL", None)
            else:
                os.environ["TPQ_TRACE_TAIL"] = val
            if leg == "fleet":
                os.environ["TPQ_OBS_SPOOL"] = spool_dir
                os.environ["TPQ_OBS_SPOOL_S"] = "0.2"
            else:
                os.environ.pop("TPQ_OBS_SPOOL", None)
                os.environ.pop("TPQ_OBS_SPOOL_S", None)
            svc = ScanService(concurrency=min(clients, 8),
                              queue_depth=max(2 * clients, 4))
            errors = []

            def run_client(ci, _svc=svc, _errs=errors):
                try:
                    for i in range(q_per_client):
                        _svc.scan(ScanRequest(
                            path,
                            columns=projections[(ci + i)
                                                % len(projections)]))
                except Exception as e:  # noqa: BLE001 — reported
                    _errs.append(repr(e))

            # warm the plan/footer/dict cache first so every leg measures
            # the same steady state — the first-open footer parse would
            # swamp a percent-level tracing delta
            svc.scan(ScanRequest(path))
            t0 = time.perf_counter()
            threads = [threading.Thread(target=run_client, args=(ci,))
                       for ci in range(clients)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            wall = time.perf_counter() - t0
            tree = svc.obs_registry().as_dict()
            trace = svc.serve_stats()["trace"]
            svc.close()
            hist = (tree.get("histograms") or {}).get("serve.request") or {}
            from tpu_parquet.obs import LatencyHistogram as _LH
            p99_s = _LH.from_dict(hist).quantile(0.99) if hist else 0.0
            entry = {
                "wall_s": round(wall, 4),
                "p50_ms": round(
                    float(hist.get("p50_seconds", 0.0)) * 1e3, 3),
                "p99_ms": round(p99_s * 1e3, 3),
                "traces_offered": trace["offered"],
                "traces_retained": trace["retained"],
                "ring_bytes": trace["retained_bytes"],
            }
            if errors:
                entry["errors"] = errors[:3]
            assert trace["retained_bytes"] <= trace["ring_capacity_bytes"], \
                f"export ring over its byte bound in {leg} leg: {trace}"
            if leg == "fleet":
                # the spool must have published generations that aggregate
                # cleanly — otherwise the leg measured an inert spool
                from tpu_parquet.obs_fleet import FleetAggregator
                snap = FleetAggregator(spool_dir=spool_dir).scan()
                entry["spool_files"] = snap["files_scanned"]
                entry["spool_rejected"] = snap["rejected"]
                entry["spool_processes"] = len(snap["processes"])
                assert snap["files_scanned"] > 0 and snap["rejected"] == 0 \
                    and any(p.get("role") == "serve"
                            for p in snap["processes"].values()), snap
            out[leg] = entry
            log(f"  obs_overhead {leg}: {wall:.3f}s wall, "
                f"p50 {entry['p50_ms']:.3f}ms p99 {entry['p99_ms']:.3f}ms, "
                f"{trace['retained']}/{trace['offered']} traces retained")
    finally:
        if saved is None:
            os.environ.pop("TPQ_TRACE_TAIL", None)
        else:
            os.environ["TPQ_TRACE_TAIL"] = saved
        for k, v in saved_spool.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        shutil.rmtree(spool_dir, ignore_errors=True)
    off = out["off"]
    if off["p50_ms"]:
        for leg in ("tail", "retain_all", "fleet"):
            out[f"{leg}_p50_overhead"] = round(
                out[leg]["p50_ms"] / off["p50_ms"], 4)
            out[f"{leg}_p99_overhead"] = (round(
                out[leg]["p99_ms"] / off["p99_ms"], 4)
                if off["p99_ms"] else 0.0)
        log(f"obs_overhead: tail-sampled p50 "
            f"{out['tail_p50_overhead']:.3f}x of tracing-off (acceptance "
            f"figure <=1.03), retain-all "
            f"{out['retain_all_p50_overhead']:.3f}x, spool-armed "
            f"{out['fleet_p50_overhead']:.3f}x (acceptance <=1.03)")
        if not smoke:
            # generous structural bar — percent-level deltas drown in
            # scheduler noise here; the banked ratio is the honest figure,
            # this only catches a gross regression
            assert out["tail_p50_overhead"] <= 1.5, out
            assert out["fleet_p50_overhead"] <= 1.5, out
    # off must be genuinely off (zero traces created), retain-all must
    # actually retain — otherwise the A/B measured nothing
    assert off["traces_offered"] == 0, off
    assert out["retain_all"]["traces_retained"] > 0, out["retain_all"]
    leaked = [t.name for t in threading.enumerate()
              if t.name.startswith(("tpq-serve", "tpq-metricsdump",
                                    "tpq-spool"))]
    assert not leaked, f"serve/dumper/spool threads leaked: {leaked}"
    return out


def _enable_compile_cache():
    """Persistent XLA compilation cache (one implementation: the library's —
    device_reader._enable_compile_cache defers to an app-configured dir /
    JAX_COMPILATION_CACHE_DIR and defaults to a per-user path)."""
    import jax
    from tpu_parquet.device_reader import _enable_compile_cache as lib_enable

    lib_enable()
    log(f"compilation cache: {jax.config.jax_compilation_cache_dir}")


def _pallas_microbench(width=13, n=8_000_000):
    """Best-of-5 fixed-width unpack: Mosaic plane kernel vs XLA gather path."""
    import jax
    import numpy as np

    from tpu_parquet import jax_kernels as K
    from tpu_parquet.jax_decode import pad_buffer
    from tpu_parquet.kernels import bitpack
    from tpu_parquet.pallas_kernels import (
        _unpack_pallas_jit, build_planes, pallas_available,
    )

    rng = np.random.default_rng(1)
    vals = rng.integers(0, 1 << width, n, dtype=np.uint64)
    packed = np.frombuffer(bitpack.pack(vals, width), np.uint8)
    planes = build_planes(packed, width, n)
    buf_dev = pad_buffer(packed)
    interp = not pallas_available()
    with jax.enable_x64():
        jax.block_until_ready(K.unpack_bits(buf_dev, width, n))
    jax.block_until_ready(
        _unpack_pallas_jit(planes, width=width, count=n, interpret=interp))
    t_xla = t_pl = float("inf")
    for _ in range(5):
        t0 = time.perf_counter()
        with jax.enable_x64():
            jax.block_until_ready(K.unpack_bits(buf_dev, width, n))
        t_xla = min(t_xla, time.perf_counter() - t0)
        t0 = time.perf_counter()
        jax.block_until_ready(
            _unpack_pallas_jit(planes, width=width, count=n, interpret=interp))
        t_pl = min(t_pl, time.perf_counter() - t0)
    return {
        "width": width,
        "xla_mvals_per_sec": round(n / t_xla / 1e6, 1),
        "pallas_mvals_per_sec": round(n / t_pl / 1e6, 1),
        "pallas_speedup": round(t_xla / t_pl, 2),
    }


# per-config scalar keys worth repeating on the compact stdout line; rep
# lists, window arrays, and sampling metadata live only in the artifact file
_SUMMARY_KEYS = (
    "rows", "device_rows_per_sec", "device_mb_per_sec", "device_vs_host",
    "device_vs_pyarrow", "device_vs_host_pipeline", "host_rows_per_sec",
    "pyarrow_rows_per_sec", "pipeline_speedup", "prefetch0_rows_per_sec",
    "prefetch4_rows_per_sec", "overlap_efficiency", "loader_speedup",
    "loader_vs_scan", "scan_files_rows_per_sec", "device_vs_host_prefetch4",
    "pallas_speedup", "link_bytes_shipped", "link_bytes_logical",
    "link_bytes_ratio",
)
_SUMMARY_LIMIT = 1990  # < the driver's 2000-char tail window, with margin


def parse_args(argv=None):
    import argparse

    p = argparse.ArgumentParser(
        description="tpu-parquet benchmark (see the module docstring for "
                    "the env knobs)")
    p.add_argument("--smoke", action="store_true",
                   help="tiny single-config run (plain_int64, ~20k rows, "
                        "optional sections off) exercising the full "
                        "artifact/ledger/gate plumbing end to end")
    p.add_argument("--check-against", metavar="BASELINE", default=None,
                   help="regression gate: compare this run against a prior "
                        "bench artifact / ledger / ledger.jsonl#N; exit 2 "
                        "when a metric regresses beyond its noise bound")
    p.add_argument("--no-ledger", action="store_true",
                   help="skip the automatic ledger.jsonl append")
    return p.parse_args(argv)


def _ledger_and_check(record, args, artifact_path):
    """Gate the run against a baseline, then append it to the ledger.

    Mutates ``record`` (adds ``ledger``/``check`` keys, surfaced on the
    compact line by emit_results); returns the exit code the caller should
    use AFTER emitting — the driver's JSON line always comes first.

    The gate runs BEFORE the append, and a failed gate (regression,
    unloadable baseline, nothing comparable) skips the append entirely:
    with ``--check-against ledger.jsonl`` the baseline is the previous
    recorded run, so recording a regressed run would make it the very
    baseline the NEXT run is compared against — one red build and the
    regression is ratcheted in as the new normal.  (This ordering also
    keeps a self-comparison impossible: the record this run would write
    can never be its own ratio-1.0 baseline.)  The run's numbers are
    still banked in the BENCH artifact and the compact line.
    """
    rc = _check_gate(record, args)
    if not args.no_ledger:
        from tpu_parquet import ledger as _ledger

        if rc == 0:
            # smoke runs default to their OWN ledger file: a tiny-config
            # record appended to the full-run ledger.jsonl would become the
            # last record — i.e. the `--check-against ledger.jsonl` baseline
            # — and every full run after it would gate rows-incomparable
            # (exit 2, never recorded), wedging CI until someone hand-edits
            # the ledger.  An explicit TPQ_LEDGER still wins.
            default_name = ("ledger.smoke.jsonl" if args.smoke
                            else "ledger.jsonl")
            lpath = os.environ.get("TPQ_LEDGER") or os.path.join(
                os.path.dirname(os.path.abspath(artifact_path)),
                default_name)
            try:
                seq = _ledger.append(lpath, _ledger.make_record(record))
                record["ledger"] = {"path": lpath, "seq": seq}
                log(f"ledger: appended run #{seq} to {lpath}")
            except OSError as e:
                log(f"ledger append FAILED ({lpath}): {e!r}")
        else:
            log("ledger: gate failed — run NOT recorded (a regressed run "
                "must never become the next run's baseline)")
    return rc


def _check_gate(record, args) -> int:
    """The ``--check-against`` evaluation alone: sets ``record['check']``,
    returns the gate exit code (0 pass, 2 fail)."""
    from tpu_parquet import ledger as _ledger

    if not args.check_against:
        return 0
    baseline = baseline_error = None
    try:
        baseline = _ledger.load_side(args.check_against)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        baseline_error = e
    if baseline_error is not None:
        # an unloadable baseline must FAIL the gate: a typo'd path silently
        # passing CI is the worst failure mode a gate can have
        log(f"check-against: cannot load baseline "
            f"{args.check_against}: {baseline_error!r}")
        record["check"] = {"baseline": args.check_against,
                           "error": str(baseline_error), "regressions": []}
        return 2
    floor_env = os.environ.get("BENCH_CHECK_FLOOR", "")
    try:
        floor = float(floor_env) if floor_env else _ledger.DEFAULT_CHECK_FLOOR
    except ValueError:
        # a malformed knob must not take down the emit contract (the driver
        # line always comes first) — fall back and say so
        log(f"check-against: unparseable BENCH_CHECK_FLOOR={floor_env!r}, "
            f"using default {_ledger.DEFAULT_CHECK_FLOOR}")
        floor = _ledger.DEFAULT_CHECK_FLOOR
    d = _ledger.diff(baseline, record, floor=floor)
    record["check"] = {
        "baseline": args.check_against,
        "floor": floor,
        "compared": d["compared"],
        "regressions": d["regressions"],
        "improvements": d["improvements"],
        "incomparable": d["incomparable"],
    }
    log(_ledger.format_diff(d, args.check_against, "this run").rstrip())
    if d["compared"] == 0:
        # a gate that compared nothing checked nothing: a loadable but
        # wrong-shape baseline (a trace artifact, a full-scale record vs a
        # smoke run) must fail just as loudly as a typo'd path
        log("check-against: 0 comparable metrics — the baseline does not "
            "cover this run's configs/rows; failing the gate")
        record["check"]["error"] = "no comparable metrics"
        return 2
    if d["regressions"]:
        log(f"check-against: {len(d['regressions'])} regression(s) beyond "
            f"noise bounds — exiting nonzero")
        return 2
    return 0


def _artifact_path():
    """ONE resolution of the artifact location — emit_results writes it and
    the ledger lands next to it, so the two must never diverge."""
    return os.environ.get("BENCH_JSON") or os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "BENCH_LOCAL_latest.json")


def emit_results(record, out_path=None):
    """VERDICT r5 blocker fix: the full results go to a BENCH artifact file
    as INDENTED multi-line JSON, and stdout's LAST line is a compact
    single-line summary guaranteed under the driver's 2000-char tail window
    (the r04/r05 one-line JSON overflowed it: ``parsed: null`` two rounds
    running).  ``BENCH_JSON`` overrides the artifact path."""
    out_path = out_path or _artifact_path()
    artifact_name = os.path.basename(out_path)
    try:
        with open(out_path, "w") as f:
            json.dump(record, f, indent=2, sort_keys=True)
            f.write("\n")
        log(f"full results: {out_path}")
    except OSError as e:
        log(f"artifact write FAILED ({out_path}): {e!r}")
        # never point the summary at a stale file from an earlier round
        artifact_name = None
    compact = {k: record[k] for k in ("metric", "value", "unit",
                                      "vs_baseline")}
    compact["artifact"] = artifact_name
    # ledger/check summaries stay a few chars each on the compact line;
    # the full entries (attributions included) live in the artifact
    led = record.get("ledger")
    if led:
        compact["ledger"] = f"{os.path.basename(led['path'])}#{led['seq']}"
    chk = record.get("check")
    if chk is not None:
        if chk.get("error"):
            # distinguish the two gate-failure shapes for whoever triages
            # from the compact line alone: a baseline that never loaded vs
            # one that loaded but covered none of this run's configs/rows
            # (only the latter carries the diff's "compared" count)
            compact["check"] = ("incomparable_baseline" if "compared" in chk
                                else "baseline_unloadable")
        elif chk.get("regressions"):
            compact["check"] = f"{len(chk['regressions'])} regressions"
        else:
            compact["check"] = f"ok ({chk.get('compared', 0)} compared)"
    cfgs = {}
    for name, r in record.get("configs", {}).items():
        if not isinstance(r, dict):
            continue
        c = {k: r[k] for k in _SUMMARY_KEYS
             if isinstance(r.get(k), (int, float))}
        if c:
            cfgs[name] = c
    compact["configs"] = cfgs
    line = json.dumps(compact, separators=(",", ":"))
    while len(line) > _SUMMARY_LIMIT and cfgs:
        # shed the bulkiest config until the line fits; the artifact file
        # keeps everything
        bulkiest = max(cfgs, key=lambda n: len(json.dumps(cfgs[n])))
        del cfgs[bulkiest]
        line = json.dumps(compact, separators=(",", ":"))
    if len(line) > _SUMMARY_LIMIT:
        compact.pop("configs", None)
        line = json.dumps(compact, separators=(",", ":"))
    print(line)


_TRACE_BASE: "str | None" = None  # main() moves TPQ_TRACE here (see below)


def main(argv=None):
    global _TRACE_BASE, SCALE, REPS, BASELINE_REPS, RESAMPLE, WHICH
    import jax

    args = parse_args(argv)
    if args.smoke:
        # one tiny config, optional sections off, unless the env explicitly
        # says otherwise — the end-to-end plumbing run, not a measurement
        SCALE = float(os.environ.get("BENCH_SCALE", "0.002"))
        REPS = int(os.environ.get("BENCH_DEVICE_REPS", "2"))
        BASELINE_REPS = int(os.environ.get("BENCH_BASELINE_REPS", "1"))
        RESAMPLE = int(os.environ.get("BENCH_RESAMPLE", "0"))
        WHICH = os.environ.get("BENCH_CONFIGS", "1").split(",")
        for knob in ("BENCH_PIPELINE", "BENCH_LOADER", "BENCH_WRITES",
                     "BENCH_PALLAS", "BENCH_IOFAULTS", "BENCH_DATAFAULTS"):
            os.environ.setdefault(knob, "0")
        # the smoke/tier-1 gate path runs with the hang watchdog ARMED (a
        # generous deadline: it must never fire on a slow box, only on a
        # true wedge) so recorder+watchdog wiring is exercised on every
        # gate run; the zero-daemon-thread assert at the end of main()
        # proves every reader stopped it
        os.environ.setdefault("TPQ_HANG_S", "300")

    # Claim TPQ_TRACE for the per-config artifacts and UNSET it: left in the
    # env it would enable the process-global tracer inside every TIMED rep —
    # live span recording perturbing the samples the benchmark reports, and
    # every rep's events buffering until exit.  Only bench_device's
    # instrumented pass (its own per-config Tracer) records.
    _TRACE_BASE = os.environ.pop("TPQ_TRACE", "")

    _enable_compile_cache()
    log(f"jax devices: {jax.devices()}")
    results = {}
    headline = None
    dev_times = {}   # name -> (dev_t, path, rows, key)
    meta = {"device_reps": REPS, "baseline_reps": BASELINE_REPS}
    try:
        meta["link_mb_per_sec_start"] = probe_link()
        # feed the MEASURED link speed to the ship planner (ship.py reads
        # TPQ_LINK_MBPS) so route choices below reflect this run's weather,
        # not the default planning point; an explicit env wins
        if "TPQ_LINK_MBPS" not in os.environ:
            os.environ["TPQ_LINK_MBPS"] = str(meta["link_mb_per_sec_start"])
            meta["planner_link_mbps"] = meta["link_mb_per_sec_start"]
    except Exception as e:  # noqa: BLE001 — diagnostics only
        log(f"link probe FAILED: {e!r}")

    def over_budget():
        # never trips before the first result exists: the driver must always
        # get at least one measured config in its JSON line
        return bool(results) and time.perf_counter() - _T_START > TIME_BUDGET

    # ------------------------------------------------------------------
    # Phase A: every config's DEVICE measurement, banked first in clean
    # air.  Device scans barely affect each other, but a config's baseline
    # phases (especially the host+upload burst: hundreds of MB of
    # device_put) depress subsequent transfer throughput for tens of
    # seconds on the tunneled backend — measured 4x on config 2 when the
    # phases were interleaved.  Baselines therefore run in phase B, after
    # every device number is already recorded.
    # ------------------------------------------------------------------
    for key in WHICH:
        key = key.strip()
        if key not in CONFIGS:
            continue
        if over_budget():
            log(f"time budget {TIME_BUDGET}s reached; skipping config {key}")
            continue
        name, gen, base_rows = CONFIGS[key]
        rows = int(base_rows * SCALE)
        path = f"/tmp/tpq_bench_{name}_{rows}.parquet"
        # the nested config is multi-file: ALL parts must exist or the scan
        # quietly under-reads while `rows` stays the full denominator
        required = [path] + ([path + ".part2"] if name == "nested" else [])
        if not all(os.path.exists(p) for p in required):
            t0 = time.perf_counter()
            try:
                gen(path, rows)
            except Exception as e:  # noqa: BLE001
                log(f"config {key} {name} generation FAILED: {e!r}; skipping")
                if os.path.exists(path):
                    os.unlink(path)
                continue
            gen_mb = sum(os.path.getsize(p) for p in required) / 1e6
            log(f"generated {path} ({len(required)} file(s)): {gen_mb:.1f} MB "
                f"in {time.perf_counter()-t0:.1f}s")
        mb = _uncompressed_mb(path)
        log(f"config {key} {name}: {rows} rows, {mb:.0f} MB uncompressed")
        try:
            samples, ship = bench_device(path, rows, name=name)
        except Exception as e:  # noqa: BLE001 — one bad config (or a tunnel
            # hiccup mid-compile) must not cost the driver its JSON line
            log(f"config {key} {name} FAILED: {e!r}; continuing")
            continue
        dev_t = _median(samples)
        results[name] = {
            "rows": rows,
            "device_rows_per_sec": round(rows / dev_t, 1),
            "device_mb_per_sec": round(mb / dev_t, 1),
            "device_windows_s": [[round(t, 3) for t in samples]],
            **ship,
        }
        dev_times[name] = ([samples], path, rows, key, mb)
        log(f"config {key} {name}: device "
            f"{results[name]['device_rows_per_sec']/1e6:.1f} M rows/s "
            f"({results[name]['device_mb_per_sec']:.0f} MB/s)")
        if name == "lineitem16":
            headline = results[name]

    # ------------------------------------------------------------------
    # Phase A': extra sampling windows over every config.  Transient
    # congestion on the tunneled link lasts minutes (own probes have
    # recorded 93 MB/s and 1.5 GB/s within one run); re-sampling each
    # config's device reps later in the run gives the best-window-median estimator more
    # weather windows.  Same metric, same estimator — sampled at several
    # points in time.  Windows stop at 60% of the budget: the phase-B
    # baselines (the vs_baseline denominator the driver records) must
    # always fit.
    # ------------------------------------------------------------------
    resample_reps = max(REPS - 2, 2)
    meta["resample_windows"] = 0
    meta["resample_reps"] = resample_reps

    def windows_over_budget():
        return (bool(results)
                and time.perf_counter() - _T_START > 0.6 * TIME_BUDGET)

    for rs in range(RESAMPLE):
        if not dev_times or windows_over_budget():
            break
        try:  # probe failure must not forfeit the sampling window itself
            meta[f"link_mb_per_sec_w{rs + 1}"] = probe_link()
        except Exception as e:  # noqa: BLE001 — diagnostics only
            log(f"window link probe FAILED: {e!r}")
        # headline first (banked before the budget can run out), then the
        # rest — BENCH_r04 weather log shows the link swinging 150→1500 MB/s
        # within one run, so every config deserves a second window
        order = sorted(dev_times, key=lambda n: n != "lineitem16")
        window_complete = True
        for name in order:
            if windows_over_budget():
                window_complete = False
                break
            windows, path, rows, key, mb = dev_times[name]
            try:
                extra = device_reps(path, rows, resample_reps,
                                    tag=f".{name}.w{rs + 1}")
            except Exception as e:  # noqa: BLE001
                log(f"{name} resample FAILED: {e!r}")
                continue
            meta[f"w{rs + 1}_sampled"] = meta.get(f"w{rs + 1}_sampled", 0) + 1
            windows.append(extra)
            # best WINDOW median (see the sampling-protocol docstring):
            # median within a window, cleanest weather window across
            t = _best_window(windows)
            r = results[name]
            r["device_rows_per_sec"] = round(rows / t, 1)
            r["device_mb_per_sec"] = round(mb / t, 1)
            r["device_windows_s"] = [[round(x, 3) for x in w]
                                     for w in windows]
            log(f"{name} best window median after window {rs + 1}: "
                f"{r['device_rows_per_sec'] / 1e6:.1f} M rows/s")
        if window_complete:
            meta["resample_windows"] = rs + 1

    # ------------------------------------------------------------------
    # Phase B: baselines (host decode, pyarrow, host decode + upload).
    # host/pyarrow are CPU-bound and indifferent to tunnel state; the
    # upload baselines run last so their transfer bursts cannot poison any
    # measurement that matters.
    # ------------------------------------------------------------------
    for name, (windows, path, rows, key, mb) in dev_times.items():
        r = results[name]
        dev_t = _best_window(windows)
        if over_budget():
            log(f"time budget reached; skipping baselines for {name}")
            continue
        try:
            hs = bench_host(path, rows)
            host_t = _median(hs)
            r["host_rows_per_sec"] = round(rows / host_t, 1)
            r["host_reps_s"] = [round(x, 3) for x in hs]
            r["device_vs_host"] = round(host_t / dev_t, 3)
        except Exception as e:  # noqa: BLE001 — keep the paid-for device
            # numbers even when the host baseline dies
            log(f"config {key} host baseline FAILED: {e!r}")
        try:
            ps_ = bench_pyarrow(path, rows)
            pa_t = _median(ps_)
            r["pyarrow_rows_per_sec"] = round(rows / pa_t, 1)
            r["pyarrow_reps_s"] = [round(x, 3) for x in ps_]
            r["device_vs_pyarrow"] = round(pa_t / dev_t, 3)
        except Exception as e:  # noqa: BLE001 — independent denominator only
            log(f"config {key} pyarrow baseline FAILED: {e!r}")
    for name, (windows, path, rows, key, mb) in dev_times.items():
        r = results[name]
        dev_t = _best_window(windows)
        if over_budget():
            log(f"time budget reached; skipping upload baseline for {name}")
            continue
        # both paths ending device-resident (the training-pipeline view);
        # skippable under time pressure — the primary metrics above are
        # never discarded once measured
        try:
            pipe_t = _median(bench_host(path, rows, upload=True))
            r["device_vs_host_pipeline"] = round(pipe_t / dev_t, 3)
        except Exception as e:  # noqa: BLE001
            log(f"config {key} upload baseline FAILED: {e!r}")
        vs = r.get("device_vs_host")
        pipe = r.get("device_vs_host_pipeline")
        log(f"config {key} {name}: device {r['device_rows_per_sec']/1e6:.1f} M rows/s "
            f"({r['device_mb_per_sec']:.0f} MB/s)"
            + (f", {vs:.1f}x host" if vs is not None else "")
            + (f", {pipe:.1f}x host+upload pipeline" if pipe is not None else ""))

    def _config_file(cfg_key):
        """The config's bench file (reusing the measured path, else
        generating); returns (path, rows)."""
        name, gen, base_rows = CONFIGS[cfg_key]
        entry = dev_times.get(name)
        if entry is not None:
            _w, ppath, prows, _k, _mb = entry
            return ppath, prows
        prows = int(base_rows * SCALE)
        ppath = f"/tmp/tpq_bench_{name}_{prows}.parquet"
        if not os.path.exists(ppath):
            gen(ppath, prows)
        return ppath, prows

    # Overlapped chunk pipeline: host decode prefetch={0,4} on the headline
    # file (ISSUE 1 acceptance: >= 1.3x sequential) AND on plain_int64 (the
    # round-4 ≥0.9x-host target, re-measured against the overlap path —
    # ISSUE 2 satellite).  Skip: BENCH_PIPELINE=0.
    if os.environ.get("BENCH_PIPELINE", "1") != "0" and not over_budget():
        for cfg_key, out_name in (("4", "pipeline"),
                                  ("1", "pipeline_plain_int64")):
            try:
                ppath, prows = _config_file(cfg_key)
                results[out_name] = bench_pipeline(ppath, prows)
                if cfg_key == "1":
                    dev = results.get("plain_int64", {}).get(
                        "device_rows_per_sec")
                    if dev:
                        # the round-4 target ratio, with the overlapped host
                        # decode as the denominator
                        results[out_name]["device_vs_host_prefetch4"] = round(
                            dev / results[out_name]["prefetch4_rows_per_sec"],
                            3)
            except Exception as e:  # noqa: BLE001
                log(f"pipeline bench ({out_name}) FAILED: {e!r}")
            if over_budget():
                break

    # Training-input loader: shuffled-epoch throughput at prefetch={0,4} on
    # the headline file's fixed-width columns.  Skip: BENCH_LOADER=0.
    if os.environ.get("BENCH_LOADER", "1") != "0" and not over_budget():
        try:
            ppath, prows = _config_file("4")
            results["loader"] = bench_loader(ppath, prows)
        except Exception as e:  # noqa: BLE001
            log(f"loader bench FAILED: {e!r}")

    # Fault-tolerant IO backend: store indirection overhead + injected-
    # fault recovery on the headline file.  Skip with BENCH_IOFAULTS=0.
    if os.environ.get("BENCH_IOFAULTS", "1") != "0" and not over_budget():
        try:
            ppath, prows = _config_file("4")
            results["io_faults"] = bench_io_faults(ppath, prows)
        except Exception as e:  # noqa: BLE001
            log(f"io_faults bench FAILED: {e!r}")

    # Corruption containment: default-on validation overhead (<1.03x gate)
    # + seeded-corruption skip_unit accounting.  Skip with BENCH_DATAFAULTS=0.
    if os.environ.get("BENCH_DATAFAULTS", "1") != "0" and not over_budget():
        try:
            ppath, prows = _config_file("4")
            results["data_faults"] = bench_data_faults(ppath, prows)
        except Exception as e:  # noqa: BLE001
            log(f"data_faults bench FAILED: {e!r}")

    # High-QPS scan service: concurrency sweep over a shared ScanService
    # vs sequential one-shot opens (plan/footer/dict cache win + p50/p95
    # SLOs).  Skip with BENCH_SERVE=0; smoke DOES run it (cheap, and the
    # service's thread lifecycle rides the leak gate below).
    if os.environ.get("BENCH_SERVE", "1") != "0" and not over_budget():
        try:
            ppath, prows = _config_file("4")
            results["serve"] = bench_serve(ppath, prows)
        except Exception as e:  # noqa: BLE001
            log(f"serve bench FAILED: {e!r}")

    # Tiered result cache (ISSUE 14): hot/cold A/B (warm-vs-cold speedup),
    # zipfian hot-set + long-tail mix, and mutation-mid-sweep invalidation
    # accounting.  Skip with BENCH_SERVE_CACHE=0; smoke runs it tiny.
    if (os.environ.get("BENCH_SERVE_CACHE", "1") != "0"
            and not over_budget()):
        try:
            ppath, prows = _config_file("4")
            entry = bench_serve_cache(ppath, prows, smoke=args.smoke)
            if isinstance(results.get("serve"), dict):
                results["serve"]["result_cache"] = entry
            else:
                results["serve"] = {"result_cache": entry}
        except Exception as e:  # noqa: BLE001
            log(f"serve_cache bench FAILED: {e!r}")

    # Request-lifecycle resilience: the serve sweep under a seeded stall
    # storm, hedging off vs on (p99 cut + win rate), a brownout shed
    # phase, and the hedge thread-leak assertion.  Skip with
    # BENCH_SERVE_FAULTS=0; smoke runs a tiny phase.
    if os.environ.get("BENCH_SERVE_FAULTS", "1") != "0" and not over_budget():
        try:
            ppath, prows = _config_file("4")
            results["serve_faults"] = bench_serve_faults(
                ppath, prows, smoke=args.smoke)
        except Exception as e:  # noqa: BLE001
            log(f"serve_faults bench FAILED: {e!r}")

    # Multi-tenant fair-share QoS (ISSUE 17): victim-tenant p99 isolated
    # vs under a noisy flood, FIFO vs weighted DRR — the fairness win in
    # one ratio.  Streaming sessions ride tpq-serve workers, so the
    # exit-3 leak gate below covers them via the existing prefix.  Skip
    # with BENCH_SERVE_TENANTS=0; smoke runs a tiny phase.
    if (os.environ.get("BENCH_SERVE_TENANTS", "1") != "0"
            and not over_budget()):
        try:
            ppath, prows = _config_file("4")
            results["serve_tenants"] = bench_serve_tenants(
                ppath, prows, smoke=args.smoke)
        except Exception as e:  # noqa: BLE001
            log(f"serve_tenants bench FAILED: {e!r}")

    # IO-concurrency scaling (ISSUE 18): async fetch engine vs blocking-
    # read thread pool under 50ms injected latency, sweeping in-flight
    # {8, 64, 256} — byte-identity and the no-leaked-threads bar are
    # asserted inside.  Skip with BENCH_IOSCALE=0; smoke runs a tiny sweep.
    if os.environ.get("BENCH_IOSCALE", "1") != "0" and not over_budget():
        try:
            ppath, prows = _config_file("4")
            results["io_scale"] = bench_io_scale(
                ppath, prows, smoke=args.smoke)
        except Exception as e:  # noqa: BLE001
            log(f"io_scale bench FAILED: {e!r}")

    # Tracing-cost A/B (ISSUE 19): the serve workload with request tracing
    # off / tail-sampled / retain-all — banks p50/p99 overhead ratios and
    # asserts the export-ring byte bound + the zero-traces-when-off bar.
    # Skip with BENCH_OBS=0; smoke runs a tiny mix.
    if os.environ.get("BENCH_OBS", "1") != "0" and not over_budget():
        try:
            ppath, prows = _config_file("4")
            results["obs_overhead"] = bench_obs_overhead(
                ppath, prows, smoke=args.smoke)
        except Exception as e:  # noqa: BLE001
            log(f"obs_overhead bench FAILED: {e!r}")

    # Fused-vs-unfused device decode A/B on the dominant kernel families
    # (ISSUE 13): forced-route scans banking device_seconds + dispatch/
    # pass counts per side.  Skip with BENCH_FUSED=0; smoke runs it tiny
    # (the structural pass-count bar holds even in interpret mode).
    if os.environ.get("BENCH_FUSED", "1") != "0" and not over_budget():
        try:
            fused_files = {}
            for cfg_key, cname in (("1", "plain_int64"), ("4", "lineitem16")):
                try:
                    fused_files[cname] = _config_file(cfg_key)[0]
                except Exception as e:  # noqa: BLE001
                    log(f"fused bench: no {cname} file: {e!r}")
            results["fused"] = bench_fused(fused_files, smoke=args.smoke)
        except Exception as e:  # noqa: BLE001
            log(f"fused bench FAILED: {e!r}")

    # Writer throughput (host encode; ~10s).  Skip with BENCH_WRITES=0.
    if os.environ.get("BENCH_WRITES", "1") != "0" and not over_budget():
        try:
            results["writes"] = bench_writes()
        except Exception as e:  # noqa: BLE001
            log(f"write bench FAILED: {e!r}")

    # Write-at-scale: N-worker sharded encode vs single writer + the
    # compaction pass's file-count and planner link-byte ratio (ISSUE 15).
    # Skip with BENCH_WRITE=0; --smoke runs it tiny.
    if os.environ.get("BENCH_WRITE", "1") != "0" and not over_budget():
        try:
            results["write_scale"] = bench_write_scale(smoke=args.smoke)
        except Exception as e:  # noqa: BLE001
            log(f"write_scale bench FAILED: {e!r}")

    # Pallas vs XLA bit-unpack microbench (the L1 primitive).
    # Cheap (~5s); skip with BENCH_PALLAS=0.
    if os.environ.get("BENCH_PALLAS", "1") != "0" and not over_budget():
        try:
            results["pallas_unpack"] = _pallas_microbench()
            log(f"pallas unpack microbench: {results['pallas_unpack']}")
        except Exception as e:  # noqa: BLE001
            log(f"pallas microbench FAILED: {e!r}")

    try:
        meta["link_mb_per_sec_end"] = probe_link()
    except Exception as e:  # noqa: BLE001
        log(f"end link probe FAILED: {e!r}")
    if args.smoke:
        meta["smoke"] = True
    results["sampling"] = meta

    headline_name = "lineitem16"
    if headline is None:  # config 4 not run: fall back to the first DECODE
        # result (the pallas microbench entry has no rows/s and must never
        # become the headline)
        decode_results = {k: v for k, v in results.items()
                          if "device_rows_per_sec" in v}
        if not decode_results:
            emit_results({"metric": "no_valid_configs", "value": 0.0,
                          "unit": "rows/s", "vs_baseline": 0.0,
                          "configs": results})
            sys.exit(1)
        headline_name, headline = next(iter(decode_results.items()))
    record = {
        "metric": f"{headline_name}_decode_rows_per_sec_device",
        "value": headline["device_rows_per_sec"],
        "unit": "rows/s",
        "vs_baseline": headline.get("device_vs_host", 0.0),
        "configs": results,
    }
    artifact_path = _artifact_path()
    # ledger + gate run BEFORE emit (their summaries ride the compact line)
    # but the exit happens AFTER: the driver always gets its JSON line
    rc = _ledger_and_check(record, args, artifact_path)
    emit_results(record, artifact_path)
    # obs daemon hygiene: every sampler/watchdog any reader started must be
    # stopped by now (readers close in their benches) — a leak here is a
    # thread-lifecycle regression the smoke gate must catch.  The
    # tpq-serve prefix also covers streaming scan sessions: they execute
    # ON the service's worker threads, so a session close() leaving its
    # producer wedged shows up here as a leaked worker.  After emit: the
    # driver always gets its JSON line first.
    import threading

    # the shared fetch engine is process-lived by design (scans reuse its
    # loop thread); benches are done with it here, so shut it down and hold
    # it to the same zero-leak bar as every other daemon
    from tpu_parquet.iostore_async import shutdown_default_engine

    shutdown_default_engine()
    leaked = [t.name for t in threading.enumerate()
              if t.name.startswith(("tpq-sampler", "tpq-watchdog",
                                    "tpq-devtimer", "tpq-hedge",
                                    "tpq-serve", "tpq-fetch",
                                    "tpq-metricsdump", "tpq-spool"))]
    if leaked:
        log(f"FAIL: obs daemon threads leaked after completion: {leaked}")
        sys.exit(3)
    if rc:
        sys.exit(rc)


if __name__ == "__main__":
    main()
