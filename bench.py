"""Benchmark: device (TPU) columnar decode vs host (NumPy) columnar decode.

Prints exactly ONE JSON line on stdout:
    {"metric": ..., "value": N, "unit": "rows/s", "vs_baseline": N}
Everything else goes to stderr.

Workload (BASELINE.md configs 1-3 folded into one lineitem-like file):
    l_orderkey   INT64  DELTA_BINARY_PACKED   (sorted keys: small deltas)
    l_quantity   INT64  PLAIN
    l_shipdate   INT32  DELTA_BINARY_PACKED
    l_returnflag BYTE_ARRAY dictionary (3 distinct, RLE_DICTIONARY)
compressed with SNAPPY (native C++ codec in tree).

"value" is end-to-end device-path decode throughput: file open → footer → per
chunk IO → host decompress + structure parse → XLA kernels → device arrays,
blocked until ready (columns stay on device; that is the product).
"vs_baseline" divides by the host NumPy columnar decoder measured on the same
file — a *stricter* denominator than the pure-Go reference (value-at-a-time,
interface-dispatched, one boxed value per datum; see SURVEY.md §3.1 hot loops),
which cannot run here (no Go toolchain in the image).

Env knobs: BENCH_ROWS (default 10_000_000), BENCH_DEVICE_REPS (default 3).
"""

import json
import os
import sys
import time


def log(*a):
    print(*a, file=sys.stderr, flush=True)


ROWS = int(os.environ.get("BENCH_ROWS", 10_000_000))
REPS = int(os.environ.get("BENCH_DEVICE_REPS", 3))
CACHE = f"/tmp/tpq_bench_lineitem_{ROWS}.parquet"


def generate(path):
    import numpy as np

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from tpu_parquet.format import (
        CompressionCodec, ConvertedType, Encoding,
        FieldRepetitionType as FRT, LogicalType, StringType, Type,
    )
    from tpu_parquet.schema.core import (
        ColumnParameters, build_schema, data_column,
    )
    from tpu_parquet.writer import FileWriter

    rng = np.random.default_rng(42)
    schema = build_schema([
        data_column("l_orderkey", Type.INT64, FRT.REQUIRED),
        data_column("l_quantity", Type.INT64, FRT.REQUIRED),
        data_column("l_shipdate", Type.INT32, FRT.REQUIRED),
        data_column(
            "l_returnflag", Type.BYTE_ARRAY, FRT.REQUIRED,
            ColumnParameters(
                logical_type=LogicalType(STRING=StringType()),
                converted_type=ConvertedType.UTF8,
            ),
        ),
    ])
    t0 = time.perf_counter()
    with FileWriter(
        path, schema,
        codec=CompressionCodec.SNAPPY,
        column_encodings={
            "l_orderkey": Encoding.DELTA_BINARY_PACKED,
            "l_shipdate": Encoding.DELTA_BINARY_PACKED,
        },
        use_dictionary=True,
        row_group_size=128 << 20,
    ) as w:
        step = 2_000_000
        key = 0
        flags = np.array([b"A", b"N", b"R"], dtype=object)
        for lo in range(0, ROWS, step):
            n = min(step, ROWS - lo)
            keys = key + np.cumsum(rng.integers(1, 5, n))
            key = int(keys[-1])
            from tpu_parquet.column import ByteArrayData, ColumnData

            flag_idx = rng.integers(0, 3, n)
            flag_col = ByteArrayData(
                offsets=np.arange(n + 1, dtype=np.int64),
                heap=np.frombuffer(
                    b"".join(flags[flag_idx]), dtype=np.uint8
                ).copy(),
            )
            w.write_columns({
                "l_orderkey": keys.astype(np.int64),
                "l_quantity": rng.integers(1, 51, n).astype(np.int64),
                "l_shipdate": (8035 + rng.integers(0, 2526, n)).astype(np.int32),
                "l_returnflag": ColumnData(values=flag_col),
            })
    log(f"generated {path}: {os.path.getsize(path)/1e6:.1f} MB "
        f"in {time.perf_counter()-t0:.1f}s")


def bench_device(path):
    import jax
    from tpu_parquet.device_reader import DeviceFileReader

    def run():
        r = DeviceFileReader(path)
        outs = []
        for cols in r.iter_row_groups():
            outs.extend(cols.values())
        arrs = []
        for o in outs:
            arrs.extend(
                a for a in (o.values, o.offsets, o.heap,
                            getattr(o, "indices", None))
                if a is not None
            )
        jax.block_until_ready(arrs)
        r.close()

    run()  # warm: XLA compiles cached after this
    best = float("inf")
    for i in range(REPS):
        t0 = time.perf_counter()
        run()
        dt = time.perf_counter() - t0
        log(f"device rep {i}: {dt:.3f}s ({ROWS/dt/1e6:.2f} M rows/s)")
        best = min(best, dt)
    return ROWS / best


def bench_host(path):
    from tpu_parquet.reader import FileReader

    def run():
        r = FileReader(path)
        for rg in r.iter_row_groups():
            pass
        r.close()

    run()
    best = float("inf")
    for i in range(max(REPS - 1, 1)):
        t0 = time.perf_counter()
        run()
        dt = time.perf_counter() - t0
        log(f"host rep {i}: {dt:.3f}s ({ROWS/dt/1e6:.2f} M rows/s)")
        best = min(best, dt)
    return ROWS / best


def main():
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    if not os.path.exists(CACHE):
        generate(CACHE)
    import jax

    log(f"jax devices: {jax.devices()}")
    dev = bench_device(CACHE)
    host = bench_host(CACHE)
    print(json.dumps({
        "metric": "lineitem4_decode_rows_per_sec_device",
        "value": round(dev, 1),
        "unit": "rows/s",
        "vs_baseline": round(dev / host, 3),
    }))


if __name__ == "__main__":
    main()
