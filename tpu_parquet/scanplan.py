"""ScanPlan IR: the reusable plan a scan executes, carved out of the readers.

Until this module every query path re-derived the same facts from scratch on
every open: the footer walk that turns row-group metadata into per-chunk byte
ranges, the statistics-based row-group pruning verdict, the page-level
predicate-pushdown plan (header walks + skip sets), and the ship planner's
route ranking (including its *failed* host probes — a narrow transcode that
didn't fit is re-attempted every scan).  This module centralizes plan
construction as an explicit, serializable IR:

    ScanPlan = file identity + projection + filter fingerprint
             + per-row-group chunk byte ranges (the footer slice)
             + row-group keep verdicts (group pruning)
             + memoized page-pruning skip sets
             + memoized ship-route choices + kernel families

Three consumers share it (no duplicated planning logic):

- the one-shot readers (``FileReader`` / ``DeviceFileReader``) build one per
  open — or accept a prebuilt one via ``plan=`` and *replay* it: group
  pruning is not recomputed, page-pruning header walks are skipped, and the
  ship planner starts from the memoized route instead of re-probing;
- :func:`~tpu_parquet.device_reader.scan_files` threads one plan per file
  through the same kwarg;
- ``tpu_parquet.serve.ScanService`` caches ScanPlans in its
  :class:`~tpu_parquet.serve.PlanCache` keyed by ``(file identity,
  projection, filter)`` and replays them across requests — and uses
  :meth:`ScanPlan.estimated_bytes` as the admission-control cost of a
  request before any byte is read.

The IR is deliberately *metadata-level*: byte ranges and route choices, not
traced executables — it is the unit a mesh scheduler can later shard across
hosts (ROADMAP direction 1), and it serializes (:meth:`ScanPlan.serialize`)
with the same versioned/validated discipline as the loader checkpoint blob
(fuzz target ``scan_plan`` holds the line).
"""

from __future__ import annotations

import json
import threading
from dataclasses import dataclass, field
from typing import Optional

from .chunk_decode import validate_chunk_meta, walk_pages
from .errors import ParquetError
from .format import PageType
from .ship import ROUTES

__all__ = [
    "SCANPLAN_VERSION", "ChunkPlan", "RowGroupPlan", "ScanPlan",
    "build_scan_plan", "row_group_chunks", "row_group_byte_span",
    "walk_header_pages", "plan_page_pruning", "predicate_fingerprint",
]

SCANPLAN_VERSION = 1
_MAGIC = b"TPQP"

# kernel-family names a deserialized plan may carry (device_reader's
# _KERNEL_FAMILIES values plus the host-only marker); anything else in a
# blob is a lie the deserializer rejects
_FAMILIES = frozenset((
    "snappy_resolve", "narrow", "levels", "gather", "unpack", "plain",
    "host",
))


# ---------------------------------------------------------------------------
# the shared footer walk (single source of truth for chunk byte ranges)
# ---------------------------------------------------------------------------

def row_group_chunks(rg, leaves):
    """Walk one row group's SELECTED column chunks in file order.

    Yields ``(path, leaf, chunk, md, offset)`` per selected leaf —
    ``md``/``offset`` already through :func:`validate_chunk_meta` (the
    dictionary-page-offset min, the type check, the external-file
    rejection).  This is the one chunk walk every consumer shares: the
    sequential reader, the prefetch feeds, and :func:`build_scan_plan`.
    """
    for chunk in rg.columns or []:
        md = chunk.meta_data
        if md is None or md.path_in_schema is None:
            raise ParquetError("column chunk missing metadata/path")
        path = tuple(md.path_in_schema)
        leaf = leaves.get(path)
        if leaf is None:
            continue  # unselected: never read its bytes
        md, offset = validate_chunk_meta(chunk, leaf)
        yield path, leaf, chunk, md, offset


def row_group_byte_span(rg, leaves) -> "tuple[int, int]":
    """One row group's contiguous data byte span ``(start, end)`` over ALL
    its chunks — the relocation unit of the write-side footer merge
    (:mod:`tpu_parquet.write.merge`).  Rides the same
    :func:`validate_chunk_meta` walk as every read path, so a lying shard
    footer is rejected with the same typed errors a reader would raise."""
    start = end = None
    for _path, _leaf, _chunk, md, offset in row_group_chunks(rg, leaves):
        lo = int(offset)
        hi = lo + int(md.total_compressed_size or 0)
        start = lo if start is None else min(start, lo)
        end = hi if end is None else max(end, hi)
    if start is None:
        raise ParquetError("row group has no selected column chunks")
    return start, end


# ---------------------------------------------------------------------------
# IR nodes
# ---------------------------------------------------------------------------

@dataclass
class ChunkPlan:
    """One column chunk's slice of the footer: where its bytes live and what
    the admission/cost models need to know without reading them."""

    column: str          # dotted path
    offset: int          # first byte (dictionary page included)
    size: int            # total_compressed_size
    usize: int           # total_uncompressed_size (0 when absent)
    codec: int
    num_values: int

    def as_dict(self) -> dict:
        return {"column": self.column, "offset": self.offset,
                "size": self.size, "usize": self.usize,
                "codec": self.codec, "num_values": self.num_values}


@dataclass
class RowGroupPlan:
    ordinal: int
    num_rows: int
    chunks: list = field(default_factory=list)  # [ChunkPlan], file order

    def as_dict(self) -> dict:
        return {"ordinal": self.ordinal, "num_rows": self.num_rows,
                "chunks": [c.as_dict() for c in self.chunks]}


class ScanPlan:
    """The plan IR: footer slice + pruning verdicts + route memo.

    Thread-safe: the route/pruning memos are written by reader consumer
    threads and read by prefetch-pool workers (the service shares one plan
    across many concurrent requests).
    """

    __slots__ = ("version", "file_key", "columns", "filter_fp", "rg_keep",
                 "row_groups", "_routes", "_pruning", "_lock", "_nbytes")

    def __init__(self, file_key=None, columns=None, filter_fp=None,
                 rg_keep=None, row_groups=None):
        self.version = SCANPLAN_VERSION
        self.file_key = tuple(file_key) if file_key is not None else None
        self.columns = tuple(columns) if columns is not None else None
        self.filter_fp = filter_fp
        self.rg_keep = list(rg_keep) if rg_keep is not None else None
        self.row_groups: list[RowGroupPlan] = list(row_groups or [])
        self._routes: dict = {}   # (rg, column) -> (route, family|None)
        self._pruning: dict = {}  # rg -> (skip {path_tuple: set} | None, rows_dropped)
        self._lock = threading.Lock()
        self._nbytes: Optional[int] = None

    # -- identity ------------------------------------------------------------

    def cache_key(self) -> tuple:
        """What makes two plans interchangeable: the file generation, the
        projection, and the filter.  The route/pruning memos are NOT part of
        the key — they are replayable accelerations of the same plan."""
        return (self.file_key, self.columns, self.filter_fp)

    def nbytes(self) -> int:
        """Approximate in-memory footprint (cache accounting)."""
        if self._nbytes is None:
            self._nbytes = len(self.serialize())
        return self._nbytes

    # -- admission cost -------------------------------------------------------

    def estimated_bytes(self) -> int:
        """Worst-case bytes a scan of this plan holds in flight: compressed
        + decompressed per selected chunk of every surviving row group —
        the admission-control charge ``serve.ScanService`` acquires before
        a request touches a byte."""
        total = 0
        for rgp in self.row_groups:
            if self.rg_keep is not None and not (
                    0 <= rgp.ordinal < len(self.rg_keep)
                    and self.rg_keep[rgp.ordinal]):
                continue
            for c in rgp.chunks:
                total += c.size + max(c.usize, c.size)
        return total

    def selected_ordinals(self) -> list:
        """Row-group ordinals the group-pruning verdict keeps."""
        return [rgp.ordinal for rgp in self.row_groups
                if self.rg_keep is None
                or (0 <= rgp.ordinal < len(self.rg_keep)
                    and self.rg_keep[rgp.ordinal])]

    # -- route memo (the ship planner's replayable decisions) -----------------

    def note_route(self, rg: int, column: str, route: str,
                   family: "str | None" = None) -> None:
        if route not in ROUTES:
            return
        with self._lock:
            self._routes[(int(rg), column)] = (route, family)
            self._nbytes = None

    def route_hint(self, rg: int, column: str) -> "str | None":
        with self._lock:
            rec = self._routes.get((int(rg), column))
        return rec[0] if rec is not None else None

    def routes_table(self) -> dict:
        """``{(rg, column): (route, family)}`` snapshot (stats surface)."""
        with self._lock:
            return dict(self._routes)

    # -- page-pruning memo ----------------------------------------------------

    def note_pruning(self, rg: int, skip, rows_dropped: int) -> None:
        """Record a page-pruning outcome: ``skip`` is the reader-shaped
        ``{path_tuple: set(ordinals)}`` (or None — planned, nothing to
        prune / ineligible)."""
        with self._lock:
            self._pruning[int(rg)] = (
                None if skip is None
                else {tuple(p): set(s) for p, s in skip.items()},
                int(rows_dropped))
            self._nbytes = None

    def pruning_hint(self, rg: int):
        """``(skip, rows_dropped)`` when this row group's pruning was
        already planned under this plan's filter; None = never planned."""
        with self._lock:
            rec = self._pruning.get(int(rg))
            if rec is None:
                return None
            skip, dropped = rec
            return (None if skip is None
                    else {p: set(s) for p, s in skip.items()}), dropped

    # -- serialization --------------------------------------------------------

    def serialize(self) -> bytes:
        with self._lock:
            routes = {f"{rg}\x00{col}": [route, family]
                      for (rg, col), (route, family)
                      in sorted(self._routes.items())}
            pruning = {str(rg): [
                (None if skip is None
                 else {".".join(p): sorted(int(x) for x in s)
                       for p, s in sorted(skip.items())}),
                dropped,
            ] for rg, (skip, dropped) in sorted(self._pruning.items())}
        doc = {
            "file_key": list(self.file_key) if self.file_key else None,
            "columns": list(self.columns) if self.columns is not None else None,
            "filter_fp": self.filter_fp,
            "rg_keep": ([bool(x) for x in self.rg_keep]
                        if self.rg_keep is not None else None),
            "row_groups": [rgp.as_dict() for rgp in self.row_groups],
            "routes": routes,
            "pruning": pruning,
        }
        body = json.dumps(doc, separators=(",", ":"), sort_keys=True)
        return _MAGIC + bytes([SCANPLAN_VERSION]) + body.encode()

    @classmethod
    def deserialize(cls, data: bytes) -> "ScanPlan":
        """Strictly-validated inverse of :meth:`serialize`: any structural
        lie (bad magic/version, wrong types, negative byte ranges, unknown
        routes) raises :class:`ParquetError` — a cached or shipped plan
        must never be adopted on faith."""
        if not isinstance(data, (bytes, bytearray, memoryview)):
            raise ParquetError("scan plan: not bytes")
        data = bytes(data)
        if len(data) < len(_MAGIC) + 1 or data[:len(_MAGIC)] != _MAGIC:
            raise ParquetError("scan plan: bad magic")
        if data[len(_MAGIC)] != SCANPLAN_VERSION:
            raise ParquetError(
                f"scan plan: unknown version {data[len(_MAGIC)]}")
        try:
            doc = json.loads(data[len(_MAGIC) + 1:].decode())
        except (ValueError, UnicodeDecodeError) as e:
            raise ParquetError(f"scan plan: corrupt body: {e}") from e
        if not isinstance(doc, dict):
            raise ParquetError("scan plan: body is not an object")

        def _nn_int(v, what):
            if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                raise ParquetError(f"scan plan: invalid {what}: {v!r}")
            return v

        fk = doc.get("file_key")
        if fk is not None:
            if not isinstance(fk, list) or not all(
                    isinstance(x, (str, int, float)) or x is None
                    for x in fk):
                raise ParquetError("scan plan: invalid file_key")
            fk = tuple(fk)
        cols = doc.get("columns")
        if cols is not None and (not isinstance(cols, list) or not all(
                isinstance(c, str) for c in cols)):
            raise ParquetError("scan plan: invalid columns")
        fp = doc.get("filter_fp")
        if fp is not None and not isinstance(fp, str):
            raise ParquetError("scan plan: invalid filter_fp")
        keep = doc.get("rg_keep")
        if keep is not None and (not isinstance(keep, list) or not all(
                isinstance(x, bool) for x in keep)):
            raise ParquetError("scan plan: invalid rg_keep")
        rgs_doc = doc.get("row_groups")
        if not isinstance(rgs_doc, list):
            raise ParquetError("scan plan: invalid row_groups")
        row_groups = []
        seen_ord = set()
        for rd in rgs_doc:
            if not isinstance(rd, dict):
                raise ParquetError("scan plan: row group is not an object")
            o = _nn_int(rd.get("ordinal"), "row group ordinal")
            if o in seen_ord:
                raise ParquetError(f"scan plan: duplicate row group {o}")
            seen_ord.add(o)
            nr = _nn_int(rd.get("num_rows"), "num_rows")
            chunks_doc = rd.get("chunks")
            if not isinstance(chunks_doc, list):
                raise ParquetError("scan plan: invalid chunks")
            chunks = []
            for cd in chunks_doc:
                if not isinstance(cd, dict) or not isinstance(
                        cd.get("column"), str):
                    raise ParquetError("scan plan: invalid chunk entry")
                chunks.append(ChunkPlan(
                    column=cd["column"],
                    offset=_nn_int(cd.get("offset"), "chunk offset"),
                    size=_nn_int(cd.get("size"), "chunk size"),
                    usize=_nn_int(cd.get("usize"), "chunk usize"),
                    codec=_nn_int(cd.get("codec"), "chunk codec"),
                    num_values=_nn_int(cd.get("num_values"), "num_values"),
                ))
            row_groups.append(RowGroupPlan(ordinal=o, num_rows=nr,
                                           chunks=chunks))
        plan = cls(file_key=fk, columns=cols, filter_fp=fp, rg_keep=keep,
                   row_groups=row_groups)
        routes = doc.get("routes") or {}
        if not isinstance(routes, dict):
            raise ParquetError("scan plan: invalid routes")
        for key, rec in routes.items():
            if (not isinstance(key, str) or "\x00" not in key
                    or not isinstance(rec, list) or len(rec) != 2):
                raise ParquetError("scan plan: invalid route entry")
            rg_s, col = key.split("\x00", 1)
            try:
                rg = int(rg_s)
            except ValueError:
                raise ParquetError(
                    f"scan plan: invalid route row group {rg_s!r}") from None
            route, family = rec
            if rg < 0 or not isinstance(route, str) or route not in ROUTES:
                raise ParquetError(f"scan plan: unknown route {route!r}")
            if family is not None and (not isinstance(family, str)
                                       or family not in _FAMILIES):
                raise ParquetError(
                    f"scan plan: unknown kernel family {family!r}")
            plan._routes[(rg, col)] = (route, family)
        pruning = doc.get("pruning") or {}
        if not isinstance(pruning, dict):
            raise ParquetError("scan plan: invalid pruning")
        for rg_s, rec in pruning.items():
            try:
                rg = int(rg_s)
            except ValueError:
                raise ParquetError(
                    f"scan plan: invalid pruning row group {rg_s!r}") from None
            if rg < 0 or not isinstance(rec, list) or len(rec) != 2:
                raise ParquetError("scan plan: invalid pruning entry")
            skip_doc, dropped = rec
            dropped = _nn_int(dropped, "rows_dropped")
            if skip_doc is None:
                plan._pruning[rg] = (None, dropped)
                continue
            if not isinstance(skip_doc, dict):
                raise ParquetError("scan plan: invalid pruning skip set")
            skip = {}
            for col, ordinals in skip_doc.items():
                if (not isinstance(col, str) or not isinstance(ordinals, list)
                        or not all(isinstance(x, int)
                                   and not isinstance(x, bool) and x >= 0
                                   for x in ordinals)):
                    raise ParquetError("scan plan: invalid pruning ordinals")
                skip[tuple(col.split("."))] = set(ordinals)
            plan._pruning[rg] = (skip, dropped)
        return plan


# ---------------------------------------------------------------------------
# construction
# ---------------------------------------------------------------------------

def predicate_fingerprint(pred) -> "str | None":
    """A stable content fingerprint for a row filter, or None when the
    predicate cannot be fingerprinted (an exotic subclass whose repr leaks
    object identity) — an un-fingerprintable filter simply never matches a
    cached plan, it is never wrongly matched."""
    if pred is None:
        return None
    r = repr(pred)
    if " object at 0x" in r:
        return None
    return r


def build_scan_plan(metadata, schema, *, file_key=None, row_filter=None,
                    filter_fp=None, rg_keep=None) -> ScanPlan:
    """Build the ScanPlan for ``metadata`` under ``schema``'s CURRENT column
    selection: chunk byte ranges via the shared footer walk, group-pruning
    verdicts from ``row_filter`` (or adopt a precomputed ``rg_keep`` so a
    reader that already pruned never pays twice)."""
    leaves = {l.path: l for l in schema.selected_leaves()}
    columns = tuple(sorted(".".join(p) for p in leaves))
    if row_filter is not None:
        if rg_keep is None:
            from .predicate import prune_row_groups

            rg_keep = prune_row_groups(metadata, schema, row_filter)
        if filter_fp is None:
            filter_fp = predicate_fingerprint(row_filter)
    row_groups = []
    for i, rg in enumerate(metadata.row_groups):
        chunks = [
            ChunkPlan(
                column=".".join(path), offset=int(offset),
                size=int(md.total_compressed_size or 0),
                usize=int(md.total_uncompressed_size or 0),
                codec=int(md.codec or 0),
                num_values=int(md.num_values or 0),
            )
            for path, _leaf, _chunk, md, offset in row_group_chunks(rg, leaves)
        ]
        row_groups.append(RowGroupPlan(ordinal=i,
                                       num_rows=int(rg.num_rows or 0),
                                       chunks=chunks))
    return ScanPlan(file_key=file_key, columns=columns, filter_fp=filter_fp,
                    rg_keep=rg_keep, row_groups=row_groups)


def apply_selection(schema, columns) -> None:
    """Validate-then-apply a column projection on a Schema (shared by
    ``FileReader.set_selected_columns`` and the serve cache's plan builds).
    Validates BEFORE applying — a failed call leaves the selection as it
    was — and raises the one canonical no-such-columns ParquetError."""
    if columns is None:
        schema.set_selected(None)
        return
    paths = [tuple(c.split(".")) if isinstance(c, str) else tuple(c)
             for c in columns]
    if not schema.selection_matches(paths):
        known = [".".join(l.path) for l in schema.leaves]
        raise ParquetError(
            f"selected columns {['.'.join(p) for p in paths]} "
            f"match no schema columns; available: {known}"
        )
    schema.set_selected(paths)


def int_stats_span(statistics, leaf) -> "tuple[int, int] | None":
    """Decode chunk Statistics min/max into an int span hint, if plausible.

    Returns (min, max) for INT32/INT64 leaves whose stats carry well-formed
    PLAIN-encoded bounds, else None.  A planning INPUT (it routes the
    narrow-transcode vs device-snappy choice), never trusted for
    correctness — malformed or lying stats are simply ignored.
    """
    import numpy as np

    from .format import Type

    if (statistics is None
            or leaf.physical_type not in (Type.INT32, Type.INT64)):
        return None
    width = 8 if leaf.physical_type == Type.INT64 else 4
    dt = "<i8" if width == 8 else "<i4"
    lo = (statistics.min_value if statistics.min_value is not None
          else statistics.min)
    hi = (statistics.max_value if statistics.max_value is not None
          else statistics.max)
    if (not isinstance(lo, (bytes, bytearray)) or len(lo) != width
            or not isinstance(hi, (bytes, bytearray)) or len(hi) != width):
        return None
    lo_v = int(np.frombuffer(lo, dt)[0])
    hi_v = int(np.frombuffer(hi, dt)[0])
    if lo_v > hi_v:
        return None
    return lo_v, hi_v


# ---------------------------------------------------------------------------
# page-level predicate pushdown planning (moved from device_reader)
# ---------------------------------------------------------------------------

def walk_header_pages(f, offset: int, size: int, num_values: int):
    """Page headers of a chunk read via seeks — header bytes only, never
    the payloads (the pruning planner needs page BOUNDARIES of every
    selected column; loading whole chunks for that doubled peak host
    memory under row_filter).  Returns the data-page headers in order."""
    from .chunk_decode import _read_page_header
    from .thrift import ThriftError

    headers = []
    pos = 0
    seen = 0
    seen_dict = False
    while seen < num_values:
        if pos >= size:
            raise ParquetError(
                f"chunk exhausted at {seen}/{num_values} values")
        win = 1024
        while True:
            f.seek(offset + pos)
            head = f.read(min(win, size - pos))
            try:
                header, hlen = _read_page_header(head, 0)
                break
            except ThriftError as e:
                # could be a truncated window, not corruption: widen
                # until the whole remaining chunk has been tried
                if win >= size - pos:
                    raise ParquetError(
                        f"corrupt page header: {e}") from e
                win *= 8
        csize = header.compressed_page_size
        if csize is None or csize < 0:
            raise ParquetError(f"invalid compressed page size {csize}")
        usize = header.uncompressed_page_size
        if usize is None or usize < 0:
            raise ParquetError(f"invalid uncompressed page size {usize}")
        if hlen + csize > size - pos:
            raise ParquetError("page payload extends past chunk end")
        # CONTRACT: the data-page ordinals this walk yields must match
        # walk_pages' exactly — skip_pages indices computed here are
        # applied against walk_pages' sequence in _collect_chunk, so
        # the reject set below mirrors walk_pages (missing per-type
        # headers raise; anything else would silently shift ordinals
        # and prune the wrong pages)
        if header.type == PageType.DATA_PAGE:
            if header.data_page_header is None:
                raise ParquetError("data page v1 missing its header")
            seen += header.data_page_header.num_values or 0
            headers.append(header)
        elif header.type == PageType.DATA_PAGE_V2:
            if header.data_page_header_v2 is None:
                raise ParquetError("data page v2 missing its header")
            seen += header.data_page_header_v2.num_values or 0
            headers.append(header)
        elif header.type == PageType.DICTIONARY_PAGE:
            if seen_dict or headers:
                raise ParquetError("unexpected extra dictionary page")
            if header.dictionary_page_header is None:
                raise ParquetError("dictionary page missing its header")
            seen_dict = True
        pos += hlen + csize
    return headers


def plan_page_pruning(rg, leaves, schema, pred, f):
    """Page-level predicate pushdown planning (beyond the reference, which
    writes page Statistics but never reads them): within a surviving row
    group, maximal row runs the predicate provably cannot match — aligned
    to whole-page boundaries of EVERY selected column — are dropped by
    skipping those pages outright (no decompression, no staging, no
    decode).  Returns ``({column_path: set(data-page ordinals to skip)},
    rows_dropped, filter_chunk_bufs)``, or ``(None, 0, bufs)`` when
    ineligible (no filter, repeated columns, a filter column
    absent/repeated).

    Output contract (same lattice as group pruning): yielded rows are a
    SUPERSET of matching rows — callers re-filter exactly; whole-page
    alignment keeps every column's yielded rows identical.
    """
    if pred is None:
        return None, 0, {}
    from .predicate import prune_pages

    all_leaves = {".".join(l.path): l for l in schema.leaves}
    if any(l.max_rep > 0 for l in leaves.values()):
        return None, 0, {}
    fcols = set(pred.columns())
    for name in fcols:
        leaf = all_leaves.get(name)
        if leaf is None or leaf.max_rep > 0:
            return None, 0, {}
    by_path = {}
    for chunk in rg.columns or []:
        md = chunk.meta_data
        if md is not None and md.path_in_schema:
            by_path[".".join(md.path_in_schema)] = chunk
    if not fcols <= set(by_path):
        return None, 0, {}
    filter_pages = {}
    boundaries = {}
    # FILTER chunks' bytes, handed to the decode loop when also selected
    # — the planner already paid their IO.  Non-filter selected columns
    # are walked header-only via seeks (loading their whole chunks here
    # roughly doubled peak host memory under row_filter); the decode
    # loop reads them exactly once, as without a filter.
    bufs: dict = {}
    walk = set(fcols) | {".".join(p) for p in leaves}
    for name in walk:
        chunk = by_path.get(name)
        if chunk is None:
            return None, 0, bufs  # selected column missing: decode raises
        leaf = all_leaves[name]
        md, offset = validate_chunk_meta(chunk, leaf)
        if name in fcols:
            f.seek(offset)
            buf = f.read(md.total_compressed_size)
            if tuple(name.split(".")) in leaves:
                bufs[tuple(name.split("."))] = buf
            hdrs = [ps.header for ps in walk_pages(buf, md.num_values)]
        else:
            hdrs = walk_header_pages(
                f, offset, md.total_compressed_size, md.num_values)
        ends, stats = [], []
        total = 0
        for h in hdrs:
            if h.type == PageType.DATA_PAGE and h.data_page_header:
                total += h.data_page_header.num_values or 0
                st = h.data_page_header.statistics
            elif (h.type == PageType.DATA_PAGE_V2
                  and h.data_page_header_v2):
                total += h.data_page_header_v2.num_values or 0
                st = h.data_page_header_v2.statistics
            else:
                continue
            ends.append(total)
            stats.append(st)
        boundaries[name] = ends
        if name in fcols:
            filter_pages[name] = (ends, stats, md.type)
    num_rows = rg.num_rows or 0
    sel_bounds = {n: boundaries[n]
                  for n in {".".join(p) for p in leaves}}
    runs = prune_pages(filter_pages, sel_bounds, num_rows, pred,
                       all_leaves)
    if not runs:
        return None, 0, bufs
    skip = {}
    for path in leaves:
        name = ".".join(path)
        ends = boundaries[name]
        drop = set()
        start = 0
        for i, e in enumerate(ends):
            if any(a <= start and e <= b for a, b in runs):
                drop.add(i)
            start = e
        if drop:
            skip[path] = drop
    rows_dropped = sum(b - a for a, b in runs)
    return skip, rows_dropped, bufs
