"""Fault-tolerant range-read IO backend: the byte layer under SharedReader.

Production traffic reads Parquet from object stores, not local disk
(ROADMAP direction 4), but until this module every byte entered through an
infallible-``os.pread`` assumption: one transient stall or short read and
the pipeline either wedged (diagnosed, since PR 6, by the watchdog) or died
with an opaque downstream decode error.  The reference design's strict
layer separation (PAPER.md §1: raw bytes below L0, everything above
untouched) means the fix slots BENEATH the existing reader/pipeline stack —
no decode layer changes.  Three stores:

- :class:`LocalStore` — the existing ``os.pread`` path (locked seek+read
  for fd-less sources), the zero-overhead default.  No retries, no
  deadlines, no coalescing: a local fd does not fail transiently, and the
  lineitem16 pipeline bench guards the indirection at ≤2%.
- :class:`GenericRangeStore` — the robustness core any real GCS/S3 adapter
  inherits: per-request deadlines (``TPQ_IO_DEADLINE_S``), bounded retries
  with exponential backoff + decorrelated jitter (``TPQ_IO_RETRIES``,
  ``TPQ_IO_BACKOFF_MS``) under a per-scan retry budget
  (``TPQ_IO_RETRY_BUDGET``), short/torn-read detection with verified
  re-reads, and graceful degradation from coalesced to single-range
  fetches when a coalesced read repeatedly fails.  Subclasses implement
  one method: :meth:`GenericRangeStore._fetch_once`.
- :class:`FaultInjectingStore` — deterministic seeded injection of latency,
  transient errors, torn/short reads, and stalls over any inner store, so
  tier-1 exercises every failure path without a network.

On top, :func:`plan_coalesced` merges adjacent column-chunk ranges (gap
threshold ``TPQ_IO_COALESCE_GAP``) and :class:`CoalescedFetcher` fans the
merged spans out on the existing prefetch pool — the io lane issues fewer,
larger, individually-retryable requests.  The degradation ladder on
failure: coalesced span → per-member single ranges → error
(:class:`~tpu_parquet.errors.RetryExhaustedError` carrying the attempt
log).  Observability rides the PR 4-6 machinery: per-store
:class:`IOStats` fold into ``obs.StatsRegistry`` (the ``io`` section), the
``progress()`` counters feed an ``io_retries`` sampler track and a
watchdog heartbeat lane, and every store registers as a flight source so a
stalled fetch's dump names the in-flight range (``pq_tool autopsy``
verdict ``network-stall``).
"""

from __future__ import annotations

import asyncio
import os
import random
import threading
import time
from dataclasses import dataclass
from typing import Callable, Optional

from .errors import ParquetError, RetryExhaustedError, TransientIOError
from .obs import (LatencyHistogram, env_float, env_int,
                  register_flight_source)

__all__ = [
    "ByteStore", "CoalescedFetcher", "FaultInjectingStore", "FaultSpec",
    "GenericRangeStore", "IOConfig", "IOStats", "LocalStore", "RetryBudget",
    "ScanToken", "plan_coalesced", "require_full", "resolve_store",
]

# ceiling on one coalesced span: bounds the extra bytes a merged fetch can
# hold beyond its members (column chunks are ~1 MB; a 16-column row group
# merges to tens of MB, well under this)
MAX_COALESCED_SPAN = 64 << 20
# a coalesced span failing this many times in one scan disables coalescing
# for the REST of the scan (ladder step: the store is evidently unhappy
# with large reads; stop paying a failed big fetch per row group)
COALESCE_DISABLE_AFTER = 2
# minimum successful fetches before the learned (auto) hedge delay trusts
# the latency histogram's p90 — hedging on a cold histogram would duplicate
# everything or nothing
HEDGE_MIN_SAMPLES = 16


def require_full(buf: bytes, offset: int, size: int,
                 context: str = "") -> bytes:
    """Raise a clear ``ParquetError`` when a range read came back short.

    The page-read callsites use this instead of letting a silently-short
    buffer reach the decoder (where it dies as a confusing CRC/structure
    error): a truncated file is named as such, with offset/got/want.
    """
    if len(buf) != size:
        where = f" reading {context}" if context else ""
        raise ParquetError(
            f"truncated file{where}: wanted {size} bytes at offset "
            f"{offset}, got {len(buf)} — the file is shorter than its "
            f"metadata claims")
    return buf


# ---------------------------------------------------------------------------
# config + stats + retry budget
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class IOConfig:
    """Robustness knobs for :class:`GenericRangeStore` (env-resolved once
    per store at construction, so tests can flip the env per store).

    - ``deadline_s``     per-request wall ceiling across all of a read's
      attempts (0 = none): a fetch that cannot finish by then raises
      ``RetryExhaustedError`` instead of pinning a worker forever.
    - ``retries``        max re-attempts per range after the first try.
    - ``backoff_ms``     base backoff; actual sleeps use decorrelated
      jitter (``sleep = uniform(base, prev * 3)``, capped at 64× base) so
      a fleet of readers hitting one throttled store doesn't re-arrive in
      lockstep.
    - ``retry_budget``   per-SCAN cap on total retries (0 = unlimited): a
      store failing everywhere should fail the scan after a bounded amount
      of wheel-spinning, not after retries × chunks sleeps.
    - ``coalesce_gap``   merge adjacent ranges when the hole between them
      is at most this many bytes (0 disables coalescing).
    - ``hedge_ms``       tail-latency hedging: a fetch still in flight
      after this many milliseconds gets a duplicate issued, first success
      wins (``TPQ_IO_HEDGE_MS``).  ``0`` disables (the default — hedging
      duplicates bytes and must be opted into); ``auto`` (any negative
      value) learns the delay from the store's own fetch-latency p90 once
      enough samples exist — "duplicate the slowest decile".
    - ``hedge_max``      cap on concurrently outstanding hedge duplicates
      per store (``TPQ_IO_HEDGE_MAX``): a melting store must not be
      hammered with one duplicate per stuck read.
    """

    deadline_s: float = 0.0
    retries: int = 4
    backoff_ms: float = 25.0
    retry_budget: int = 64
    coalesce_gap: int = 1 << 16
    hedge_ms: float = 0.0
    hedge_max: int = 4

    @classmethod
    def from_env(cls) -> "IOConfig":
        raw_hedge = os.environ.get("TPQ_IO_HEDGE_MS", "")
        hedge_ms = (-1.0 if raw_hedge.strip().lower() == "auto"
                    else env_float("TPQ_IO_HEDGE_MS", 0.0))
        return cls(
            deadline_s=env_float("TPQ_IO_DEADLINE_S", 0.0, lo=0.0),
            retries=env_int("TPQ_IO_RETRIES", 4, lo=0),
            backoff_ms=env_float("TPQ_IO_BACKOFF_MS", 25.0, lo=0.0),
            retry_budget=env_int("TPQ_IO_RETRY_BUDGET", 64, lo=0),
            coalesce_gap=env_int("TPQ_IO_COALESCE_GAP", 1 << 16, lo=0),
            hedge_ms=hedge_ms,
            hedge_max=env_int("TPQ_IO_HEDGE_MAX", 4, lo=1),
        )


class RetryBudget:
    """Per-scan cap on total retries (thread-safe; 0 = unlimited)."""

    def __init__(self, max_retries: int = 0):
        self.max_retries = int(max_retries)
        self.spent = 0
        self._lock = threading.Lock()

    def spend(self) -> bool:
        """Take one retry from the budget; False when it is exhausted."""
        with self._lock:
            if 0 < self.max_retries <= self.spent:
                return False
            self.spent += 1
            return True


class ScanToken:
    """One scan's lifecycle state on a store: its OWN retry budget,
    coalescing-degradation state, request deadline, and cancel token.

    ``begin_scan()`` used to reset store-WIDE state, which was wrong the
    moment two requests shared one store (the serve tier's instance-store
    form): one request's ``begin_scan`` refreshed the budget another was
    mid-way through spending, and one flaky request's retries drained
    everyone's.  Now every scan holds its token and passes it down
    (``read_range(scan=...)``, :class:`CoalescedFetcher`), so budgets and
    degradation ladders are request-scoped; the store keeps a default
    token only for direct single-scan callers.

    ``deadline`` is an absolute ``time.monotonic()`` point the retry loop
    folds into every attempt's timeout; ``cancel`` is the request's
    :class:`~tpu_parquet.resilience.CancelToken`, checked between attempts
    so a cancelled/expired request raises its TYPED verdict instead of
    burning the transport.
    """

    __slots__ = ("budget", "deadline", "cancel", "coalesce_failures",
                 "coalesce_disabled", "_lock")

    def __init__(self, budget: "RetryBudget | None" = None,
                 deadline: "float | None" = None, cancel=None,
                 coalesce_disabled: bool = False):
        self.budget = budget if budget is not None else RetryBudget(0)
        self.deadline = deadline
        self.cancel = cancel
        self.coalesce_failures = 0
        self.coalesce_disabled = coalesce_disabled
        self._lock = threading.Lock()

    def note_coalesce_failure(self) -> bool:
        """Count one failed coalesced span; True when the ladder says this
        scan should stop planning coalesced fetches."""
        with self._lock:
            self.coalesce_failures += 1
            if self.coalesce_failures >= COALESCE_DISABLE_AFTER:
                self.coalesce_disabled = True
            return self.coalesce_disabled


class IOStats:
    """Retry/backoff/coalescing counters for one store (thread-safe).

    ``as_dict()`` is the ``io`` section of ``obs.StatsRegistry`` — all
    flows, so multi-store scans compose by addition.  ``sample()`` adds the
    point-in-time in-flight range for flight dumps (the fact a hang autopsy
    needs: WHICH range was being fetched when everything froze).
    """

    def __init__(self):
        self._lock = threading.Lock()
        self.reads = 0
        self.bytes_read = 0
        self.retries = 0
        self.backoff_seconds = 0.0
        self.transient_errors = 0
        self.short_reads = 0
        self.deadline_hits = 0
        self.exhausted = 0
        self.coalesced_spans = 0
        self.coalesced_bytes = 0
        self.coalesce_fallbacks = 0
        # tail-latency hedging (GenericRangeStore._hedged_fetch): issued
        # duplicates, races the duplicate won, the loser's bytes (paid but
        # unused — the cost side of the p99 cut), and verified-identity
        # violations (both sides returned, bytes differed)
        self.hedges_issued = 0
        self.hedges_won = 0
        self.hedges_wasted_bytes = 0
        self.hedge_mismatches = 0
        # successful-fetch latency (the learned hedge delay's p90 source)
        self.fetch_hist = LatencyHistogram()
        # thread ident -> (offset, size, started) of the fetch in flight
        self._inflight: dict[int, tuple[int, int, float]] = {}

    def add(self, field: str, n=1) -> None:
        with self._lock:
            setattr(self, field, getattr(self, field) + n)

    def enter(self, offset: int, size: int) -> None:
        with self._lock:
            self._inflight[threading.get_ident()] = (
                offset, size, time.monotonic())

    def exit(self) -> None:
        with self._lock:
            self._inflight.pop(threading.get_ident(), None)

    def progress(self) -> dict:
        """Monotonic counters only — the watchdog heartbeat contract: they
        FREEZE while a fetch is stalled (so the dog can fire) and keep
        advancing while the store is merely retrying (a retry loop with
        backoff is working as designed, not a hang — the deadline and the
        retry budget bound it, not the watchdog)."""
        with self._lock:
            return {
                "reads": self.reads,
                "bytes_read": self.bytes_read,
                "retries": self.retries,
                "transient_errors": self.transient_errors,
                "short_reads": self.short_reads,
            }

    def sample(self) -> dict:
        out = self.progress()
        with self._lock:
            if self._inflight:
                now = time.monotonic()
                off, size, t0 = max(self._inflight.values(),
                                    key=lambda v: now - v[2])
                out["inflight_offset"] = off
                out["inflight_size"] = size
                out["inflight_age_s"] = round(now - t0, 3)
        return out

    def as_dict(self) -> dict:
        with self._lock:
            return {
                "reads": self.reads,
                "bytes_read": self.bytes_read,
                "retries": self.retries,
                "backoff_seconds": round(self.backoff_seconds, 6),
                "transient_errors": self.transient_errors,
                "short_reads": self.short_reads,
                "deadline_hits": self.deadline_hits,
                "exhausted": self.exhausted,
                "coalesced_spans": self.coalesced_spans,
                "coalesced_bytes": self.coalesced_bytes,
                "coalesce_fallbacks": self.coalesce_fallbacks,
                "hedges_issued": self.hedges_issued,
                "hedges_won": self.hedges_won,
                "hedges_wasted_bytes": self.hedges_wasted_bytes,
                "hedge_mismatches": self.hedge_mismatches,
            }


# ---------------------------------------------------------------------------
# the store interface + the zero-overhead local default
# ---------------------------------------------------------------------------

class ByteStore:
    """Positioned byte source under :class:`~tpu_parquet.pipeline
    .SharedReader`: ``read_range``/``size`` plus capability flags.

    ``read_range`` returns UP TO ``size`` bytes — short only when the
    underlying object genuinely ends early (callers surface that as a
    truncated-file :func:`require_full` ParquetError).  ``parallel`` says
    concurrent ``read_range`` calls are safe; ``prefers_coalescing`` opts
    the store into the range-merging planner (local fds say no: the page
    cache already does it better).
    """

    parallel = True
    prefers_coalescing = False
    coalesce_gap = 0
    # capability flag for the async fetch engine (iostore_async.FetchEngine):
    # True when the store carries the non-blocking attempt primitive
    # (GenericRangeStore._fetch_once_async), so a scan can put hundreds of
    # ranges in flight on ONE event-loop thread.  LocalStore stays False —
    # its os.pread path is zero-overhead and never routes through the engine.
    supports_async = False
    stats: "IOStats | None" = None
    # object-identity token for read-through caches (serve.PlanCache):
    # a stable name + generation marker for the REMOTE object this store
    # reads (a URL + etag, a blob id + generation).  None = not cacheable
    # across re-opened stores; together with ``size()`` it forms the cache
    # key, so a changed object (new etag or new size) invalidates cleanly.
    identity_token: "str | None" = None

    def read_range(self, offset: int, size: int,
                   deadline: "float | None" = None,
                   scan: "ScanToken | None" = None) -> bytes:
        raise NotImplementedError

    def size(self) -> int:
        """Total object size.  Consulted on the read path (EOF vs torn-read
        classification) — implementations must cache it, not re-stat a
        remote object per read."""
        raise NotImplementedError

    def begin_scan(self, deadline: "float | None" = None,
                   cancel=None) -> "ScanToken | None":
        """Scan boundary hook: mints this scan's :class:`ScanToken` (its
        own retry budget + coalescing state, carrying the request's
        ``deadline``/``cancel``) which the scan passes back on every
        ``read_range(scan=...)``.  Plain stores return None — a local fd
        has no retry state to scope."""
        return None

    def abort(self, exc: BaseException) -> None:
        """Poison the store: in-flight and future reads raise ``exc``.

        The watchdog's raise-policy hook (same contract as
        ``InFlightBudget.abort``): a fetch stalled inside the transport
        would otherwise pin its worker — and the consumer blocked on that
        worker's future — past any deadline the watchdog enforces.  No-op
        for plain local stores (their reads cannot stall).
        """

    def close(self) -> None:
        pass


class LocalStore(ByteStore):
    """The current local path, unchanged in behavior: ``os.pread`` on real
    files (fully parallel, never touches the shared fd position), a lock
    around seek+read for fd-less sources (BytesIO, wrapped streams).  Does
    NOT own the file object."""

    def __init__(self, f):
        self._f = f
        self._lock = threading.Lock()
        self._size: "int | None" = None
        self._fd: Optional[int] = None
        try:
            self._fd = f.fileno()
        except Exception:  # noqa: BLE001 — io.UnsupportedOperation et al.
            self._fd = None
        if self._fd is not None:
            # some file-likes expose a fileno that pread cannot serve (a
            # pipe), and some platforms lack os.pread entirely (Windows);
            # probe once and fall back to the locked path forever
            try:
                os.pread(self._fd, 0, 0)
            except (OSError, AttributeError):
                self._fd = None

    @property
    def parallel(self) -> bool:
        return self._fd is not None

    def read_range(self, offset: int, size: int,
                   deadline: "float | None" = None,
                   scan: "ScanToken | None" = None) -> bytes:
        if self._fd is not None:
            parts = []
            pos = offset
            remaining = size
            while remaining > 0:
                b = os.pread(self._fd, remaining, pos)
                if not b:
                    break
                parts.append(b)
                pos += len(b)
                remaining -= len(b)
            return b"".join(parts) if len(parts) != 1 else parts[0]
        with self._lock:
            self._f.seek(offset)
            return self._f.read(size)

    def size(self) -> int:
        if self._size is None:
            if self._fd is not None:
                self._size = os.fstat(self._fd).st_size
            else:
                with self._lock:
                    pos = self._f.tell()
                    self._f.seek(0, os.SEEK_END)
                    self._size = self._f.tell()
                    self._f.seek(pos)
        return self._size


# ---------------------------------------------------------------------------
# the robustness core
# ---------------------------------------------------------------------------

_store_seq = iter(range(1, 1 << 62))


class _FetchRace:
    """First-success-wins rendezvous between a primary fetch and its hedge.

    ``settle`` is called by each racer exactly once; the first SUCCESS
    claims the win and wakes the waiter immediately — the loser drains in
    the background, its bytes accounted (``hedges_wasted_bytes``) and its
    payload verified against the winner's (a mismatch means the transport
    returned different bytes for the same range: ``hedge_mismatches``,
    the same class of lie the torn-read verifier exists for).  If every
    racer fails, the waiter wakes with the first error.
    """

    __slots__ = ("lock", "event", "launched", "resolved", "winner_role",
                 "winner_buf", "errors")

    def __init__(self):
        self.lock = threading.Lock()
        self.event = threading.Event()
        self.launched = 0
        self.resolved = 0
        self.winner_role: "str | None" = None
        self.winner_buf: "bytes | None" = None
        self.errors: list = []

    def settle(self, role: str, buf: "bytes | None",
               err: "BaseException | None", stats: "IOStats") -> None:
        with self.lock:
            self.resolved += 1
            if err is not None:
                self.errors.append(err)
            elif self.winner_buf is None:
                self.winner_role = role
                self.winner_buf = buf
                self.event.set()
            else:
                # loser success: paid, unused — account and verify
                stats.add("hedges_wasted_bytes", len(buf))
                if buf != self.winner_buf:
                    stats.add("hedge_mismatches")
            if self.resolved >= self.launched and self.winner_buf is None:
                self.event.set()


class GenericRangeStore(ByteStore):
    """Retry/backoff/deadline core for unreliable range-read transports.

    Subclasses implement :meth:`_fetch_once` — one attempt, which may
    return short/torn bytes or raise :class:`~tpu_parquet.errors
    .TransientIOError` (or ``OSError``/``TimeoutError``) for retryable
    faults.  ``read_range`` wraps it with:

    - a per-request deadline (``TPQ_IO_DEADLINE_S`` / the ``deadline``
      argument, an absolute ``time.monotonic()`` point) spanning all
      attempts;
    - bounded retries with exponential backoff + decorrelated jitter,
      spending from the per-scan :class:`RetryBudget`;
    - short/torn-read detection with a VERIFIED re-read: a short buffer not
      at EOF retries, and the re-read's prefix must match what the torn
      attempt returned (a mismatch means the transport is returning
      garbage, which is itself a transient fault);
    - an attempt log carried on the terminal
      :class:`~tpu_parquet.errors.RetryExhaustedError`.

    A genuine EOF (``offset + got >= size()``) returns the short buffer
    as-is — truncation is the CALLER's diagnosis (:func:`require_full`
    names offset/got/want), not a retry loop's.
    """

    prefers_coalescing = True

    def __init__(self, config: "IOConfig | None" = None, seed: int = 0,
                 identity_token: "str | None" = None):
        self.config = config if config is not None else IOConfig.from_env()
        # see ByteStore.identity_token: adapters pass the remote object's
        # stable name + generation (URL + etag) so re-opened stores hit the
        # serve-layer footer/plan caches instead of re-fetching
        self.identity_token = identity_token
        self.coalesce_gap = self.config.coalesce_gap
        self.stats = IOStats()
        self._rng = random.Random(seed)
        self._rng_lock = threading.Lock()
        # the default scan token: direct single-scan callers (footer
        # reads, cache warms) ride it; real scans mint their own via
        # begin_scan() so concurrent requests never share retry budgets
        self._default_scan = ScanToken(
            RetryBudget(self.config.retry_budget),
            coalesce_disabled=self.coalesce_gap <= 0)
        # tail-latency hedging state (read_range -> _hedged_fetch): the
        # semaphore caps outstanding duplicates, the thread set lets
        # close() reap in-flight racers (loser accounted, never leaked)
        self._hedge_sem = threading.BoundedSemaphore(
            max(int(self.config.hedge_max), 1))
        self._hedges_outstanding = 0  # mirror of the semaphore's deficit
        self._hedge_threads: "set[threading.Thread]" = set()
        self._hedge_lock = threading.Lock()
        # watchdog abort plumbing (see ByteStore.abort): checked between
        # attempts, and implementations poll it inside long waits
        self._abort_exc: "BaseException | None" = None
        self._abort_event = threading.Event()
        # flight-source registration (weak): a hang dump must name the
        # range in flight at the moment of the wedge — see obs.autopsy_dump
        register_flight_source(f"iostore[{next(_store_seq)}]", self.stats,
                               "sample")

    # -- the one method subclasses provide ------------------------------------

    def _fetch_once(self, offset: int, size: int,
                    timeout: "float | None") -> bytes:
        """One fetch attempt.  ``timeout`` is the seconds left under the
        request's deadline (None = unbounded); implementations honor it as
        well as their transport allows."""
        raise NotImplementedError

    async def _fetch_once_async(self, offset: int, size: int,
                                timeout: "float | None") -> bytes:
        """The non-blocking twin of :meth:`_fetch_once`: one attempt as a
        coroutine on the fetch engine's event loop — waits (latency,
        stalls, socket reads in a real adapter) must be ``await``\\ ed, not
        slept, so hundreds of attempts overlap on one thread.  A subclass
        providing this flips :attr:`supports_async` and becomes eligible
        for :class:`tpu_parquet.iostore_async.FetchEngine` routing; the
        retry/hedge discipline around it lives engine-side
        (``FetchEngine._read_range_async``) and mirrors :meth:`read_range`
        bit-for-bit."""
        raise NotImplementedError(
            f"{type(self).__name__} has no async fetch primitive")

    @property
    def supports_async(self) -> bool:  # type: ignore[override]
        return (type(self)._fetch_once_async
                is not GenericRangeStore._fetch_once_async)

    # -- scan lifecycle -------------------------------------------------------

    def begin_scan(self, deadline: "float | None" = None,
                   cancel=None) -> ScanToken:
        """Mint a fresh :class:`ScanToken` for one scan.  Concurrent scans
        each hold their own and pass it on every read, so none of them can
        drain or refresh another's budget.  The store's DEFAULT token (the
        one scan-less ``read_range`` callers ride) is refreshed to a
        sibling sharing the new budget but carrying NO deadline/cancel —
        a footer read or cache warm on a shared store must never inherit
        some other request's expiry verdict."""
        if deadline is None and cancel is not None:
            deadline = getattr(cancel, "deadline", None)
        token = ScanToken(RetryBudget(self.config.retry_budget),
                          deadline=deadline, cancel=cancel,
                          coalesce_disabled=self.coalesce_gap <= 0)
        self._default_scan = ScanToken(
            token.budget, coalesce_disabled=self.coalesce_gap <= 0)
        self._abort_exc = None
        self._abort_event.clear()
        return token

    @property
    def coalesce_disabled(self) -> bool:
        """Default-token view of the coalescing ladder (back-compat for
        callers without a token; token holders read their own)."""
        return self._default_scan.coalesce_disabled

    def abort(self, exc: BaseException) -> None:
        self._abort_exc = exc
        self._abort_event.set()

    def note_coalesce_failure(self, scan: "ScanToken | None" = None) -> None:
        """A coalesced span exhausted its retries: after
        ``COALESCE_DISABLE_AFTER`` of these in one scan, stop planning
        coalesced fetches entirely (ladder: coalesced → single-range).
        Scoped to the failing SCAN's token — one request's unhappy store
        no longer degrades its neighbors.  The default token mirrors the
        note so the store-level ``coalesce_disabled`` view (single-scan
        callers, post-mortem inspection) keeps its pre-token semantics;
        the next ``begin_scan`` resets it as it always did."""
        self.stats.add("coalesce_fallbacks")
        if scan is not None and scan is not self._default_scan:
            scan.note_coalesce_failure()
        self._default_scan.note_coalesce_failure()

    def close(self) -> None:
        """Reap in-flight hedge racers: every spawned fetch thread is
        joined (their fetches are bounded by the config deadline/stall
        caps), so a closed store leaves nothing for the bench leak gate
        to find."""
        with self._hedge_lock:
            racers = list(self._hedge_threads)
        for t in racers:
            t.join(timeout=30)

    # -- tail-latency hedging -------------------------------------------------

    def _hedge_delay_s(self) -> "float | None":
        """The delay after which a slow fetch earns a duplicate: None =
        hedging off (the default), a fixed ``hedge_ms`` when configured,
        or the store's own successful-fetch p90 once enough samples exist
        (``hedge_ms`` < 0 = auto) — "duplicate the slowest decile"."""
        ms = self.config.hedge_ms
        if ms == 0:
            return None
        if ms > 0:
            return ms / 1e3
        hist = self.stats.fetch_hist
        if hist.count < HEDGE_MIN_SAMPLES:
            return None
        p90 = hist.quantile(0.9)
        return p90 if p90 > 0 else None

    def _spawn_racer(self, race: "_FetchRace", role: str, offset: int,
                     size: int, timeout: "float | None",
                     release_sem: bool = False) -> None:
        with race.lock:
            race.launched += 1

        def run():
            stats = self.stats
            stats.enter(offset, size)  # flight dumps see the racer's range
            t0 = time.monotonic()
            try:
                try:
                    buf = self._fetch_once(offset, size, timeout)
                    err = None
                except BaseException as e:  # noqa: BLE001 — re-raised by loser/winner logic
                    buf, err = None, e
            finally:
                stats.exit()
            if err is None:
                stats.fetch_hist.record(time.monotonic() - t0)
            race.settle(role, buf, err, stats)
            if release_sem:
                with self._hedge_lock:
                    self._hedges_outstanding -= 1
                self._hedge_sem.release()
            with self._hedge_lock:
                self._hedge_threads.discard(threading.current_thread())

        t = threading.Thread(target=run, name="tpq-hedge", daemon=True)
        with self._hedge_lock:
            self._hedge_threads.add(t)
        t.start()

    def _hedged_fetch(self, offset: int, size: int,
                      timeout: "float | None", delay: float) -> bytes:
        """One hedged attempt: the primary fetch runs on a racer thread;
        if it is still out after ``delay`` (and the hedge cap has room), a
        duplicate is issued — first SUCCESS wins, the loser is drained in
        the background with its bytes accounted (``hedges_wasted_bytes``)
        and its payload verified against the winner's
        (``hedge_mismatches``), never leaked (close() joins racers)."""
        race = _FetchRace()
        self._spawn_racer(race, "primary", offset, size, timeout)
        if not race.event.wait(delay):
            if self._hedge_sem.acquire(blocking=False):
                with self._hedge_lock:
                    self._hedges_outstanding += 1
                self.stats.add("hedges_issued")
                self._spawn_racer(race, "hedge", offset, size, timeout,
                                  release_sem=True)
        race.event.wait()  # first success, or every racer failed
        with race.lock:
            if race.winner_buf is not None:
                if race.winner_role == "hedge":
                    self.stats.add("hedges_won")
                return race.winner_buf
            raise race.errors[0]

    # -- the retry loop -------------------------------------------------------

    def _fetch(self, offset: int, size: int,
               timeout: "float | None") -> bytes:
        """One attempt, hedged when the store has a hedge delay (see
        :meth:`_hedged_fetch`); the plain direct call otherwise.  The
        racer path costs one thread spawn per attempt, so it is skipped
        outright while the hedge cap is saturated — a fetch that could
        not earn a duplicate anyway must not pay the race overhead."""
        delay = self._hedge_delay_s()
        if delay is None or \
                self._hedges_outstanding >= self.config.hedge_max:
            t0 = time.monotonic()
            buf = self._fetch_once(offset, size, timeout)
            self.stats.fetch_hist.record(time.monotonic() - t0)
            return buf
        return self._hedged_fetch(offset, size, timeout, delay)

    def read_range(self, offset: int, size: int,
                   deadline: "float | None" = None,
                   scan: "ScanToken | None" = None) -> bytes:
        if scan is None:
            scan = self._default_scan
        cancel = scan.cancel
        trace = getattr(cancel, "trace", None) if cancel is not None else None
        if trace is None:
            # tracing off (or no request context): the retry loop runs
            # bare — zero added work on the hot path
            return self._read_range_retry(offset, size, deadline, scan)
        attempts: list[dict] = []
        h0, w0 = self.stats.hedges_issued, self.stats.hedges_won
        with trace.span("fetch", offset=offset, size=size):
            try:
                buf = self._read_range_retry(offset, size, deadline, scan,
                                             attempts_out=attempts)
            finally:
                # retry/hedge annotations on the span the `with` just
                # opened (the thread's open-span stack still points at it
                # inside this finally)
                if attempts:
                    trace.annotate(retries=len(attempts),
                                   last_error=attempts[-1]["error"])
                hi = self.stats.hedges_issued - h0
                if hi > 0:
                    trace.annotate(
                        hedged=hi, hedge_won=self.stats.hedges_won > w0)
        return buf

    def _read_range_retry(self, offset: int, size: int,
                          deadline: "float | None" = None,
                          scan: "ScanToken | None" = None,
                          attempts_out: "list | None" = None) -> bytes:
        cfg = self.config
        if scan is None:
            scan = self._default_scan
        # the binding deadline is the TIGHTEST of: the caller's explicit
        # point, the scan token's request deadline, and the store's
        # per-request config ceiling
        if cfg.deadline_s > 0:
            cfg_deadline = time.monotonic() + cfg.deadline_s
            deadline = (cfg_deadline if deadline is None
                        else min(deadline, cfg_deadline))
        if scan.deadline is not None:
            deadline = (scan.deadline if deadline is None
                        else min(deadline, scan.deadline))
        cancel = scan.cancel
        attempts: list[dict] = ([] if attempts_out is None else attempts_out)
        torn_prefix: "bytes | None" = None
        backoff = cfg.backoff_ms / 1e3
        stats = self.stats
        stats.enter(offset, size)
        try:
            for attempt in range(cfg.retries + 1):
                if self._abort_exc is not None:
                    raise self._abort_exc
                if cancel is not None:
                    # typed per-request verdict (DeadlineExceededError /
                    # CancelledError) — an expired or cancelled request
                    # stops issuing transport attempts right here
                    cancel.check()
                t0 = time.monotonic()
                try:
                    timeout = None
                    if deadline is not None:
                        timeout = deadline - t0
                        if timeout <= 0:
                            raise TransientIOError(
                                f"deadline exceeded before attempt "
                                f"{attempt} of range [{offset}, "
                                f"{offset + size})")
                    buf = self._fetch(offset, size, timeout)
                    if len(buf) == size and offset + size > self.size():
                        # a full-length response for a range that provably
                        # extends past EOF is fabricated bytes (a store
                        # padding its EOF reads) — never serve them
                        raise TransientIOError(
                            f"full-length read for range [{offset}, "
                            f"{offset + size}) past EOF at {self.size()}")
                    if len(buf) == size:
                        if torn_prefix is not None and not buf.startswith(
                                torn_prefix):
                            # verified re-read failed: the transport is
                            # returning DIFFERENT bytes for the same range
                            torn_prefix = None
                            raise TransientIOError(
                                f"re-read of range [{offset}, "
                                f"{offset + size}) does not match the torn "
                                f"attempt's prefix")
                        stats.add("reads")
                        stats.add("bytes_read", size)
                        return buf
                    if len(buf) > size:
                        raise TransientIOError(
                            f"overlong read: got {len(buf)} bytes for a "
                            f"{size}-byte range at {offset}")
                    if offset + len(buf) >= self.size():
                        # genuine EOF: return short; the caller names the
                        # truncation (require_full), retrying can't help
                        stats.add("reads")
                        stats.add("bytes_read", len(buf))
                        return buf
                    stats.add("short_reads")
                    if len(buf) > (len(torn_prefix or b"")):
                        torn_prefix = bytes(buf)
                    raise TransientIOError(
                        f"short read: got {len(buf)} of {size} bytes at "
                        f"{offset} (torn read, not EOF)")
                except RetryExhaustedError:
                    raise
                except (TransientIOError, TimeoutError, OSError) as e:
                    if self._abort_exc is not None:
                        # the watchdog fired mid-attempt: its error (with
                        # the dump path) outranks the transport's
                        raise self._abort_exc from e
                    if cancel is not None:
                        # an expired/cancelled request's typed verdict
                        # outranks the transport error its expiry caused
                        cancel.check()
                    stats.add("transient_errors")
                    attempts.append({
                        "attempt": attempt,
                        "error": f"{type(e).__name__}: {e}",
                        "elapsed_ms": round(
                            (time.monotonic() - t0) * 1e3, 3),
                    })
                    # deadline checked BEFORE retry exhaustion so one
                    # expiry counts exactly once, whichever branch noticed
                    # it (the pre-attempt raise lands here too)
                    if deadline is not None and time.monotonic() >= deadline:
                        stats.add("deadline_hits")
                        stats.add("exhausted")
                        raise RetryExhaustedError(
                            f"range [{offset}, {offset + size}) deadline "
                            f"exceeded after {attempt + 1} attempt(s)",
                            attempts=attempts, offset=offset, size=size,
                        ) from e
                    if attempt >= cfg.retries:
                        stats.add("exhausted")
                        raise RetryExhaustedError(
                            f"range [{offset}, {offset + size}) failed "
                            f"after {attempt + 1} attempt(s): {e}",
                            attempts=attempts, offset=offset, size=size,
                        ) from e
                    if not scan.budget.spend():
                        stats.add("exhausted")
                        raise RetryExhaustedError(
                            f"range [{offset}, {offset + size}): per-scan "
                            f"retry budget "
                            f"({scan.budget.max_retries}) exhausted",
                            attempts=attempts, offset=offset, size=size,
                        ) from e
                    # decorrelated jitter: sleep ~U(base, prev*3), capped
                    if backoff > 0:
                        with self._rng_lock:
                            backoff = min(
                                self._rng.uniform(cfg.backoff_ms / 1e3,
                                                  backoff * 3),
                                cfg.backoff_ms / 1e3 * 64)
                        if deadline is not None:
                            backoff = min(
                                backoff,
                                max(deadline - time.monotonic(), 0.0))
                        attempts[-1]["backoff_ms"] = round(backoff * 1e3, 3)
                        stats.add("retries")
                        stats.add("backoff_seconds", backoff)
                        time.sleep(backoff)
                    else:
                        stats.add("retries")
            raise AssertionError("unreachable: the retry loop always "
                                 "returns or raises")  # pragma: no cover
        finally:
            stats.exit()


# ---------------------------------------------------------------------------
# deterministic fault injection
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class FaultSpec:
    """What :class:`FaultInjectingStore` injects, per matched range.

    Attempt-indexed (the i-th attempt at a given offset), so a spec like
    ``fail_first=2`` means "the first two attempts fail, the third
    succeeds" — deterministic under concurrency because the decision keys
    on ``(offset, attempt#)``, never on global call order.

    - ``latency_s``   fixed extra latency per matched fetch;
    - ``fail_first``  first N attempts raise a TransientIOError;
    - ``torn_first``  the N attempts AFTER the failures return a torn
      (half-length) prefix — the injected sequence per range is errors,
      then torn reads, then healthy;
    - ``stall_first`` first N attempts block for ``stall_s`` (or until the
      store's :meth:`FaultInjectingStore.release` — the injected "network
      stall" the watchdog must catch);
    - ``corrupt``     payload corruption mode (``bitflip`` | ``zero`` |
      ``truncate``, see :func:`tpu_parquet.quarantine.corrupt_bytes`):
      matched ranges return length-preserving CORRUPTED bytes — the same
      bytes on every attempt (keyed by ``corrupt_seed ^ offset``, never by
      attempt or call order), because data corruption is a property of the
      stored object, not of the transport, and retries must not "heal" it.
      This is the tier-1 vehicle for the integrity tier + policy engine
      (quarantine.py): the transport sees a clean read, the CRC/decode
      sanity checks catch the damage;
    - ``match``       predicate ``(offset, size) -> bool`` choosing which
      ranges are faulty (None = all).
    """

    latency_s: float = 0.0
    fail_first: int = 0
    torn_first: int = 0
    stall_first: int = 0
    stall_s: float = 30.0
    corrupt: "str | None" = None
    corrupt_seed: int = 0
    match: "Callable[[int, int], bool] | None" = None


class FaultInjectingStore(GenericRangeStore):
    """Seeded, deterministic fault injection over any inner store.

    The tier-1 test vehicle for the whole failure matrix: every injected
    transient fault must recover to bit-identical output; exhausted retries
    must raise ``RetryExhaustedError`` with the attempt log; an injected
    stall must fire the watchdog.  ``release()`` unblocks any in-progress
    stalls (tests call it in teardown so a joined pool never waits the full
    ``stall_s``).
    """

    def __init__(self, inner: ByteStore, spec: "FaultSpec | None" = None,
                 config: "IOConfig | None" = None, seed: int = 0,
                 identity_token: "str | None" = None):
        super().__init__(config=config, seed=seed,
                         identity_token=(identity_token
                                         if identity_token is not None
                                         else inner.identity_token))
        self.inner = inner
        self.spec = spec if spec is not None else FaultSpec()
        self._attempts: dict[int, int] = {}  # offset -> attempts so far
        self._attempts_lock = threading.Lock()
        self._unstall = threading.Event()

    def release(self) -> None:
        """Unblock every current and future injected stall."""
        self._unstall.set()

    def close(self) -> None:
        # stalls die with the store: close() must never leave a racer (or
        # a test teardown) waiting out a full stall_s
        self.release()
        super().close()

    def size(self) -> int:
        return self.inner.size()

    def _spec_for(self, offset: int, size: int, attempt: int) -> FaultSpec:
        """The spec governing one fetch attempt.  The base store's spec is
        static; :class:`~tpu_parquet.resilience.ChaosSchedule` subclasses
        override this to drive PHASES (stall storms, transient bursts,
        per-file blackouts) off a shared read-ordinal clock."""
        return self.spec

    def _fetch_once(self, offset: int, size: int,
                    timeout: "float | None") -> bytes:
        if (self.spec.match is not None
                and not self.spec.match(offset, size)):
            return self.inner.read_range(offset, size)
        with self._attempts_lock:
            n = self._attempts.get(offset, 0)
            self._attempts[offset] = n + 1
        spec = self._spec_for(offset, size, n)
        if spec.latency_s > 0:
            wait = spec.latency_s
            if timeout is not None and wait > timeout:
                time.sleep(max(timeout, 0.0))
                raise TransientIOError(
                    f"injected latency {spec.latency_s:g}s exceeded the "
                    f"deadline for range [{offset}, {offset + size})")
            time.sleep(wait)
        if n < spec.stall_first:
            deadline = time.monotonic() + (spec.stall_s if timeout is None
                                           else min(spec.stall_s, timeout))
            # sliced wait: wakes promptly on release() AND on a watchdog
            # abort (two events can't be waited on together)
            while not self._unstall.is_set() and self._abort_exc is None:
                left = deadline - time.monotonic()
                if left <= 0:
                    break
                self._unstall.wait(min(left, 0.05))
            raise TransientIOError(
                f"injected stall at range [{offset}, {offset + size}) "
                f"(attempt {n})")
        if n < spec.fail_first:
            raise TransientIOError(
                f"injected transient error #{n} at range "
                f"[{offset}, {offset + size})")
        buf = self.inner.read_range(offset, size)
        if n < spec.fail_first + spec.torn_first and len(buf) > 1:
            return buf[: max(len(buf) // 2, 1)]
        if spec.corrupt is not None:
            from .quarantine import corrupt_bytes

            # keyed per RANGE (offset), never per attempt: the same bytes
            # come back on every retry — corruption lives in the object
            buf = corrupt_bytes(bytes(buf), spec.corrupt,
                                spec.corrupt_seed ^ offset)
        return buf

    async def _fetch_once_async(self, offset: int, size: int,
                                timeout: "float | None") -> bytes:
        """The async twin of :meth:`_fetch_once`, decision-for-decision:
        the SAME per-offset attempt counter and the same ``_spec_for``
        hook (so a :class:`~tpu_parquet.resilience.ChaosSchedule` drives
        the async path unchanged), with every injected wait ``await``\\ ed
        instead of slept — an injected 50 ms latency on 256 ranges costs
        ~50 ms wall, not 256 thread-slots.  The inner read itself stays a
        blocking call on the loop (it is a local fd / memory buffer in
        every test topology; a real network adapter awaits its socket)."""
        if (self.spec.match is not None
                and not self.spec.match(offset, size)):
            return self.inner.read_range(offset, size)
        with self._attempts_lock:
            n = self._attempts.get(offset, 0)
            self._attempts[offset] = n + 1
        spec = self._spec_for(offset, size, n)
        if spec.latency_s > 0:
            wait = spec.latency_s
            if timeout is not None and wait > timeout:
                await asyncio.sleep(max(timeout, 0.0))
                raise TransientIOError(
                    f"injected latency {spec.latency_s:g}s exceeded the "
                    f"deadline for range [{offset}, {offset + size})")
            await asyncio.sleep(wait)
        if n < spec.stall_first:
            deadline = time.monotonic() + (spec.stall_s if timeout is None
                                           else min(spec.stall_s, timeout))
            # sliced wait: wakes promptly on release() AND on a watchdog
            # abort; the events are threading primitives set off-loop, so
            # poll them (the engine's cancel race bounds a cancelled scan)
            while not self._unstall.is_set() and self._abort_exc is None:
                left = deadline - time.monotonic()
                if left <= 0:
                    break
                await asyncio.sleep(min(left, 0.05))
            raise TransientIOError(
                f"injected stall at range [{offset}, {offset + size}) "
                f"(attempt {n})")
        if n < spec.fail_first:
            raise TransientIOError(
                f"injected transient error #{n} at range "
                f"[{offset}, {offset + size})")
        buf = self.inner.read_range(offset, size)
        if n < spec.fail_first + spec.torn_first and len(buf) > 1:
            return buf[: max(len(buf) // 2, 1)]
        if spec.corrupt is not None:
            from .quarantine import corrupt_bytes

            buf = corrupt_bytes(bytes(buf), spec.corrupt,
                                spec.corrupt_seed ^ offset)
        return buf


# ---------------------------------------------------------------------------
# range coalescing
# ---------------------------------------------------------------------------

class _Group:
    """One planned coalesced span: ``[offset, offset+size)`` covering
    ``members`` (a multiset of the input ``(offset, size)`` ranges)."""

    __slots__ = ("offset", "size", "members", "buf", "remaining",
                 "degraded", "lock")

    def __init__(self, offset: int, size: int, members: dict):
        self.offset = offset
        self.size = size
        self.members = members          # (offset, size) -> count
        self.remaining = sum(members.values())
        self.buf: "bytes | None" = None
        self.degraded = False
        self.lock = threading.Lock()

    def key(self) -> tuple:
        return (self.offset, self.size, tuple(sorted(self.members.items())))


def plan_coalesced(ranges, gap: int,
                   max_span: int = MAX_COALESCED_SPAN) -> "list[_Group]":
    """Merge ``(offset, size)`` ranges whose holes are at most ``gap``.

    Deterministic (pure function of the multiset of inputs), covering
    (every nonzero input range lands in exactly one group, with
    multiplicity), and bounded: groups are sorted and DISJOINT (a range
    overlapping the current group always joins it — fetching the overlap
    twice in two groups would be the one shape worse than either
    alternative), no group bridges a hole wider than ``gap``, and a group
    merged across holes never exceeds ``max_span`` (a lone range larger
    than that forms its own group — it must be fetched regardless; forced
    overlap-merges may also exceed it).  Zero/negative-size ranges are
    dropped (they read zero bytes regardless).
    """
    items = sorted((int(o), int(s)) for o, s in ranges if int(s) > 0)
    groups: list[_Group] = []
    cur: "dict | None" = None
    cur_off = cur_end = 0
    for off, size in items:
        end = off + size
        if cur is not None and (off < cur_end or (
                off - cur_end <= gap
                and max(end, cur_end) - cur_off <= max_span)):
            cur[(off, size)] = cur.get((off, size), 0) + 1
            cur_end = max(cur_end, end)
            continue
        if cur is not None:
            groups.append(_Group(cur_off, cur_end - cur_off, cur))
        cur = {(off, size): 1}
        cur_off, cur_end = off, end
    if cur is not None:
        groups.append(_Group(cur_off, cur_end - cur_off, cur))
    return groups


class CoalescedFetcher:
    """Serve member ranges of one coalescing plan from merged fetches.

    Built per row group on the consumer thread; the FIRST worker to touch a
    group pays its one big ``read_range`` on its own pool thread (that is
    how coalesced spans fan out on the existing prefetch pool), every other
    member slices the cached buffer.  The buffer drops as soon as its last
    member is consumed.  Failure ladder: a span whose fetch exhausts its
    retries (or comes back the wrong length — a store lying about sizes)
    marks the group degraded, and its members fall back to individual
    single-range reads; repeated span failures disable coalescing for the
    rest of the scan (``GenericRangeStore.note_coalesce_failure``).

    **Engine mode** (``engine=`` a :class:`tpu_parquet.iostore_async
    .FetchEngine`): construction SUBMITS every planned fetch — merged
    spans and lone ranges alike — so a whole row group's IO is in flight
    the moment the pipeline pulls its first item; ``read`` then merely
    awaits the matching future.  ``coalesce=False`` (the ladder said stop
    merging) keeps engine mode but submits single ranges only.  The
    failure ladder is unchanged: a failed span future degrades the group
    to per-member engine fetches.
    """

    def __init__(self, store: ByteStore, ranges,
                 gap: "int | None" = None,
                 max_span: int = MAX_COALESCED_SPAN,
                 scan: "ScanToken | None" = None,
                 engine=None, coalesce: bool = True):
        self.store = store
        self.scan = scan  # the owning scan's token: budget + ladder scope
        self._engine = engine
        g = (store.coalesce_gap if gap is None else gap) if coalesce else 0
        self._by_member: dict[tuple, _Group] = {}
        # engine mode: futures submitted up front — one per merged span
        # (keyed by group identity) and a queue per lone (offset, size)
        # (a deque, because the same range can be requested twice)
        self._span_futs: dict[int, object] = {}
        self._single_futs: dict[tuple, list] = {}
        for grp in plan_coalesced(ranges, g, max_span):
            if len(grp.members) <= 1:
                # lone range: a merged fetch buys nothing — but the engine
                # still wants it in flight NOW, not when decode reaches it
                if engine is not None:
                    for (o, s), cnt in grp.members.items():
                        futs = self._single_futs.setdefault((o, s), [])
                        for _ in range(cnt):
                            futs.append(engine.submit(store, o, s,
                                                      scan=scan))
                continue
            for m in grp.members:
                self._by_member[m] = grp
            if engine is not None:
                self._span_futs[id(grp)] = engine.submit(
                    store, grp.offset, grp.size, scan=scan)
        self.groups = len({id(g) for g in self._by_member.values()})

    def _fetch_single(self, offset: int, size: int) -> bytes:
        """One single-range read on whichever path this fetcher rides:
        a pre-submitted engine future when one is queued for this range,
        a fresh engine submission otherwise, or the plain blocking read."""
        if self._engine is not None:
            futs = self._single_futs.get((offset, size))
            if futs:
                return futs.pop(0).result()
            return self._engine.submit(self.store, offset, size,
                                       scan=self.scan).result()
        return self.store.read_range(offset, size, scan=self.scan)

    def read(self, offset: int, size: int) -> bytes:
        grp = self._by_member.get((offset, size))
        if grp is None:
            return self._fetch_single(offset, size)
        with grp.lock:
            if grp.buf is None and not grp.degraded:
                try:
                    fut = self._span_futs.pop(id(grp), None)
                    if fut is not None:
                        buf = fut.result()
                    elif self._engine is not None:
                        buf = self._engine.submit(
                            self.store, grp.offset, grp.size,
                            scan=self.scan).result()
                    else:
                        buf = self.store.read_range(grp.offset, grp.size,
                                                    scan=self.scan)
                    if len(buf) != grp.size:
                        # short span: EOF mid-group or a lying store —
                        # per-member reads diagnose precisely
                        raise TransientIOError(
                            f"coalesced span [{grp.offset}, "
                            f"{grp.offset + grp.size}) returned "
                            f"{len(buf)} bytes")
                    grp.buf = buf
                    st = self.store.stats
                    if st is not None:
                        st.add("coalesced_spans")
                        st.add("coalesced_bytes", grp.size)
                except (RetryExhaustedError, TransientIOError, OSError):
                    grp.degraded = True
                    note = getattr(self.store, "note_coalesce_failure",
                                   None)
                    if note is not None:
                        note(self.scan)
            if grp.buf is not None:
                lo = offset - grp.offset
                out = grp.buf[lo: lo + size]
                grp.remaining -= 1
                if grp.remaining <= 0:
                    grp.buf = None  # last member consumed: drop the span
                return out
        # degraded: individual single-range fetch (outside the group lock,
        # so members recover in parallel); its own retries still apply, and
        # ITS failure is the ladder's final rung — the error propagates
        return self._fetch_single(offset, size)


# ---------------------------------------------------------------------------
# store selection
# ---------------------------------------------------------------------------

def resolve_store(f, store: "ByteStore | Callable | None") -> ByteStore:
    """Resolve a reader's ``store=`` option against its open file.

    ``None`` → :class:`LocalStore` over ``f`` (the zero-overhead default);
    a :class:`ByteStore` → itself (single-file use; the caller owns it);
    a callable → ``store(f)`` — the factory form multi-file scans need
    (each file gets its own store, e.g.
    ``lambda f: FaultInjectingStore(LocalStore(f), spec)``).
    """
    if store is None:
        return LocalStore(f)
    if isinstance(store, ByteStore):
        return store
    if callable(store):
        st = store(f)
        if not isinstance(st, ByteStore):
            raise TypeError(
                f"store factory returned {type(st).__name__}, "
                f"not a ByteStore")
        return st
    raise TypeError(f"store must be None, a ByteStore, or a factory "
                    f"callable; got {type(store).__name__}")
