"""Statistics-based row-group pruning (predicate pushdown).

The reference writes chunk statistics (stats.go, chunk_writer.go:283-290) but
leaves filtering to the caller; a TPU input pipeline wants the reader to skip
row groups that cannot match *before* paying IO + decode, so this module
evaluates a small predicate algebra against the footer's per-chunk min/max/
null_count — no data pages are read for pruned groups (the skipChunk
discipline, chunk_reader.go:271-297, lifted to whole row groups).

    from tpu_parquet.predicate import col
    pred = (col("l_shipdate") >= 8766) & (col("l_quantity") < 24)
    with FileReader(path, row_filter=pred) as r:      # or DeviceFileReader
        for cols in r.iter_row_groups():              # pruned groups skipped
            ...

Soundness: every node evaluates to a pair of bounds — ``can_match`` (False
only when NO row in the group can satisfy the predicate) and ``always_match``
(True only when EVERY row must).  Missing or unreadable statistics degrade to
(True, False) — never prune on absent evidence.  SQL comparison semantics:
a NULL value satisfies no comparison, so ``~(col > v)`` is NOT ``col <= v``
— negation swaps the two bounds, which stays sound for both.
"""

from __future__ import annotations

import bisect
import struct
from dataclasses import dataclass
from typing import Optional

from .format import ConvertedType, Type
from .errors import ParquetError

__all__ = ["col", "Predicate", "prune_row_groups", "chunk_stats_range",
           "parse_filter"]


_INT_FMT = {Type.INT32: "<i", Type.INT64: "<q"}
_FLT_FMT = {Type.FLOAT: "<f", Type.DOUBLE: "<d"}


def _is_unsigned(elem) -> bool:
    ct = getattr(elem, "converted_type", None)
    if ct in (ConvertedType.UINT_8, ConvertedType.UINT_16,
              ConvertedType.UINT_32, ConvertedType.UINT_64):
        return True
    lt = getattr(elem, "logicalType", None)
    it = getattr(lt, "INTEGER", None) if lt is not None else None
    return it is not None and it.isSigned is False


def _is_decimal(elem) -> bool:
    """DECIMAL stats order by signed numeric value, not by the raw-int or
    lexicographic order this module compares with — and the row APIs yield
    SCALED Decimal values, so even int-backed decimals would compare against
    the wrong magnitude.  Degrade to no-evidence."""
    if getattr(elem, "converted_type", None) == ConvertedType.DECIMAL:
        return True
    lt = getattr(elem, "logicalType", None)
    return lt is not None and getattr(lt, "DECIMAL", None) is not None


def _decode_bound(raw: Optional[bytes], ptype: int, elem,
                  deprecated: bool) -> Optional[object]:
    """Decode one serialized min/max bound to a comparable Python value.

    ``deprecated`` marks the legacy Statistics.min/max fields, whose ordering
    is ambiguous for anything but plain signed numerics (PARQUET-251: old
    writers compared BYTE_ARRAY with *signed* bytes) — they degrade to
    no-evidence except for INT/FLOAT/DOUBLE.
    """
    if raw is None:
        return None
    if _is_decimal(elem):
        return None
    try:
        if ptype in _INT_FMT:
            if len(raw) != struct.calcsize(_INT_FMT[ptype]):
                return None
            # unsigned columns (converted OR logical type) sort differently
            # than the signed decode: degrade to no-evidence
            if _is_unsigned(elem):
                return None
            return struct.unpack(_INT_FMT[ptype], raw)[0]
        if ptype in _FLT_FMT:
            if len(raw) != struct.calcsize(_FLT_FMT[ptype]):
                return None
            return struct.unpack(_FLT_FMT[ptype], raw)[0]
        if ptype == Type.BYTE_ARRAY and not deprecated:
            return bytes(raw)
    except (struct.error, TypeError):
        return None
    return None


def stats_range(st, ptype, elem, num_values):
    """(min, max, null_count, num_values, ptype) from one Statistics object
    (chunk- or page-level); None bounds where absent/undecodable."""
    if st is None:
        return None, None, None, num_values, ptype
    if st.min_value is not None or st.max_value is not None:
        mn_raw, mx_raw, deprecated = st.min_value, st.max_value, False
    else:
        mn_raw, mx_raw, deprecated = st.min, st.max, True
    mn = _decode_bound(mn_raw, ptype, elem, deprecated)
    mx = _decode_bound(mx_raw, ptype, elem, deprecated)
    return mn, mx, st.null_count, num_values, ptype


def chunk_stats_range(md, elem):
    """(min, max, null_count, num_values, ptype) from one chunk's metadata;
    None bounds where statistics are absent/undecodable."""
    return stats_range(md.statistics, md.type, elem, md.num_values)


@dataclass(frozen=True)
class _Bounds:
    can: bool      # upper bound: group MAY contain a matching row
    always: bool   # lower bound: EVERY row in the group matches

    def __invert__(self):
        return _Bounds(can=not self.always, always=not self.can)


_NO_EVIDENCE = _Bounds(True, False)


class Predicate:
    """Base class; combine with ``&``, ``|``, ``~``."""

    def __and__(self, other):
        return _And(self, other)

    def __or__(self, other):
        return _Or(self, other)

    def __invert__(self):
        return _Not(self)

    # -- evaluation ---------------------------------------------------------

    def _bounds(self, stats_of) -> _Bounds:  # pragma: no cover - abstract
        raise NotImplementedError

    def columns(self) -> set:
        raise NotImplementedError


@dataclass(frozen=True)
class _And(Predicate):
    a: Predicate
    b: Predicate

    def _bounds(self, stats_of):
        x, y = self.a._bounds(stats_of), self.b._bounds(stats_of)
        return _Bounds(x.can and y.can, x.always and y.always)

    def columns(self):
        return self.a.columns() | self.b.columns()


@dataclass(frozen=True)
class _Or(Predicate):
    a: Predicate
    b: Predicate

    def _bounds(self, stats_of):
        x, y = self.a._bounds(stats_of), self.b._bounds(stats_of)
        return _Bounds(x.can or y.can, x.always or y.always)

    def columns(self):
        return self.a.columns() | self.b.columns()


@dataclass(frozen=True)
class _Not(Predicate):
    a: Predicate

    def _bounds(self, stats_of):
        return ~self.a._bounds(stats_of)

    def columns(self):
        return self.a.columns()


@dataclass(frozen=True)
class _Cmp(Predicate):
    """column <op> literal.  NULL rows satisfy no comparison."""

    column: str
    op: str  # lt le gt ge eq ne
    value: object

    def columns(self):
        return {self.column}

    def _bounds(self, stats_of):
        got = stats_of(self.column)
        if got is None:
            return _NO_EVIDENCE
        mn, mx, nulls, num, ptype = got
        v = self.value
        if isinstance(v, str):
            v = v.encode()
        all_null = nulls is not None and num is not None and nulls == num
        if all_null:
            return _Bounds(False, False)  # no non-null row to satisfy anything
        no_nulls = nulls == 0
        if mn is None or mx is None:
            return _NO_EVIDENCE
        # FLOAT/DOUBLE stats exclude NaN rows (this repo's stats.py; other
        # writers vary).  A NaN row satisfies NO ordered comparison and EVERY
        # inequality — so for floats the 'always' bound can never be proven
        # from min/max, and 'ne' may always match.
        is_float = ptype in _FLT_FMT
        try:
            if self.op == "lt":
                can, always = mn < v, mx < v
            elif self.op == "le":
                can, always = mn <= v, mx <= v
            elif self.op == "gt":
                can, always = mx > v, mn > v
            elif self.op == "ge":
                can, always = mx >= v, mn >= v
            elif self.op == "eq":
                can, always = mn <= v <= mx, mn == v == mx
            elif self.op == "ne":
                can, always = is_float or not (mn == v == mx), v < mn or v > mx
            else:  # pragma: no cover
                raise ParquetError(f"unknown predicate op {self.op}")
        except TypeError:
            return _NO_EVIDENCE  # incomparable literal: no evidence
        if is_float:
            always = False  # a possible NaN row breaks every 'always' proof
        return _Bounds(can, always and no_nulls)


@dataclass(frozen=True)
class _IsNull(Predicate):
    column: str
    want_null: bool

    def columns(self):
        return {self.column}

    def _bounds(self, stats_of):
        got = stats_of(self.column)
        if got is None:
            return _NO_EVIDENCE
        _, _, nulls, num, _ = got
        if nulls is None or num is None:
            return _NO_EVIDENCE
        has_null = nulls > 0
        all_null = nulls == num
        if self.want_null:
            return _Bounds(has_null, all_null)
        return _Bounds(not all_null, not has_null)


class _Column:
    """Comparison builder: ``col("a") > 3`` etc."""

    def __init__(self, name: str):
        self._name = name

    def __lt__(self, v):
        return _Cmp(self._name, "lt", v)

    def __le__(self, v):
        return _Cmp(self._name, "le", v)

    def __gt__(self, v):
        return _Cmp(self._name, "gt", v)

    def __ge__(self, v):
        return _Cmp(self._name, "ge", v)

    def __eq__(self, v):  # noqa: PLR0124
        return _Cmp(self._name, "eq", v)

    def __ne__(self, v):
        return _Cmp(self._name, "ne", v)

    def __hash__(self):
        return hash(self._name)

    def is_null(self):
        return _IsNull(self._name, True)

    def not_null(self):
        return _IsNull(self._name, False)

    def between(self, lo, hi):
        """lo <= col <= hi (inclusive both ends)."""
        return _Cmp(self._name, "ge", lo) & _Cmp(self._name, "le", hi)


def col(name: str) -> _Column:
    """Start a predicate on a (dotted) column path."""
    return _Column(name)


def parse_filter(text: str) -> Predicate:
    """Parse a textual predicate: ``"a > 5 and (b == 'x' or not c <= 3.5)"``.

    Python expression syntax via the ``ast`` module (no eval): comparisons of
    a column name against an int/float/str/bytes literal, combined with
    ``and``/``or``/``not``; ``col == None`` / ``col != None`` map to
    is_null/not_null.  Dotted column paths are written ``a.b.c``.
    """
    import ast

    try:
        tree = ast.parse(text, mode="eval")
    except SyntaxError as e:
        raise ParquetError(f"invalid filter expression: {e}") from None

    def name_of(node):
        parts = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            raise ParquetError("filter: column must be a (dotted) name")
        parts.append(node.id)
        return ".".join(reversed(parts))

    def literal(node):
        if isinstance(node, ast.Constant) and isinstance(
            node.value, (int, float, str, bytes, type(None))
        ):
            return node.value
        if (isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub)
                and isinstance(node.operand, ast.Constant)
                and isinstance(node.operand.value, (int, float))):
            return -node.operand.value
        raise ParquetError("filter: literal must be int/float/str/None")

    OPS = {ast.Lt: "lt", ast.LtE: "le", ast.Gt: "gt", ast.GtE: "ge",
           ast.Eq: "eq", ast.NotEq: "ne"}
    FLIP = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le",
            "eq": "eq", "ne": "ne"}

    def walk(node) -> Predicate:
        if isinstance(node, ast.BoolOp):
            parts = [walk(v) for v in node.values]
            out = parts[0]
            for nxt in parts[1:]:
                out = (out & nxt) if isinstance(node.op, ast.And) else (out | nxt)
            return out
        if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.Not):
            return ~walk(node.operand)
        if isinstance(node, ast.Compare):
            if len(node.ops) != 1:
                raise ParquetError("filter: chained comparisons unsupported")
            op = OPS.get(type(node.ops[0]))
            if op is None:
                raise ParquetError("filter: unsupported comparison operator")
            lhs, rhs = node.left, node.comparators[0]
            if isinstance(lhs, (ast.Name, ast.Attribute)):
                name, lit = name_of(lhs), literal(rhs)
            elif isinstance(rhs, (ast.Name, ast.Attribute)):
                name, lit, op = name_of(rhs), literal(lhs), FLIP[op]
            else:
                raise ParquetError("filter: one side must be a column name")
            if lit is None:
                if op == "eq":
                    return _IsNull(name, True)
                if op == "ne":
                    return _IsNull(name, False)
                raise ParquetError("filter: None only supports ==/!=")
            return _Cmp(name, op, lit)
        raise ParquetError(
            f"filter: unsupported syntax {ast.dump(node)[:40]}"
        )

    return walk(tree.body)


def prune_pages(filter_pages, all_boundaries, num_rows, predicate,
                leaves) -> "list[tuple[int, int]]":
    """Whole-page-aligned droppable row runs within one (flat) row group.

    ``filter_pages``: {column: (ends, stats_list, ptype)} — per data page of
    each FILTER column, the cumulative row end and the page-header
    Statistics (None where absent).  ``all_boundaries``: {column: ends} for
    EVERY selected column.  Returns maximal row runs [a, b) where the
    predicate provably matches no row, SHRUNK so that a and b are page
    boundaries of every selected column — dropping such a run means every
    column drops only whole pages, so decoded columns stay row-aligned with
    no sub-page surgery (the page analog of prune_row_groups' lattice;
    beyond the reference, which carries page stats but never reads them).

    Soundness mirrors prune_row_groups: absent/undecodable stats are
    no-evidence, repeated columns never arrive here (callers gate on
    max_rep == 0).
    """
    # elementary breakpoints: every filter column's page edges
    bps = {0, num_rows}
    for ends, _, _ in filter_pages.values():
        bps.update(int(e) for e in ends)
    bps = sorted(b for b in bps if 0 <= b <= num_rows)
    dropped = []
    for a, b in zip(bps[:-1], bps[1:]):
        if a >= b:
            continue

        def stats_of(name, _a=a):
            fp = filter_pages.get(name)
            if fp is None:
                return None
            ends, stats_list, ptype = fp
            # the page containing row _a (elementary: one page per column)
            i = bisect.bisect_right(ends, _a)
            if i >= len(stats_list):
                return None
            start = int(ends[i - 1]) if i else 0
            return stats_range(stats_list[i], ptype, leaves[name].element,
                               int(ends[i]) - start)

        if not predicate._bounds(stats_of).can:
            if dropped and dropped[-1][1] == a:
                dropped[-1] = (dropped[-1][0], b)
            else:
                dropped.append((a, b))
    # shrink each run to whole-page edges of EVERY selected column — to a
    # FIXED POINT: rounding to one column's edges can land between another's
    # (lo only rises, hi only falls, so this terminates)
    out = []
    for a, b in dropped:
        lo, hi = a, b
        changed = True
        while changed and lo < hi:
            changed = False
            for ends in all_boundaries.values():
                edges = [0] + [int(e) for e in ends]
                i = bisect.bisect_left(edges, lo)
                lo2 = edges[i] if i < len(edges) else num_rows
                j = bisect.bisect_right(edges, hi) - 1
                hi2 = edges[j] if j >= 0 else 0
                nlo, nhi = max(lo, lo2), min(hi, hi2)
                if (nlo, nhi) != (lo, hi):
                    lo, hi = nlo, nhi
                    changed = True
        if lo < hi:
            out.append((lo, hi))
    return out


def prune_row_groups(metadata, schema, predicate: Predicate) -> list[bool]:
    """Per-row-group keep/skip flags: False means NO row can match.

    Unknown columns raise (a typo would silently disable pruning);
    group/repeated columns and absent stats never cause pruning.
    """
    leaves = {".".join(l.path): l for l in schema.leaves}
    for name in predicate.columns():
        if name not in leaves:
            raise ParquetError(f"row_filter references unknown column {name!r}")
    keep = []
    for rg in metadata.row_groups:
        by_name = {}
        for chunk in rg.columns or []:
            md = chunk.meta_data
            if md is not None and md.path_in_schema:
                by_name[".".join(md.path_in_schema)] = md

        def stats_of(name, _by=by_name):
            md = _by.get(name)
            if md is None:
                return None
            leaf = leaves[name]
            if leaf.max_rep > 0:
                return None  # repeated: row<->value mapping is not 1:1
            return chunk_stats_range(md, leaf.element)

        keep.append(predicate._bounds(stats_of).can)
    return keep
