"""Thrift compact-protocol engine.

A from-scratch, declarative implementation of the Thrift compact protocol — the only
wire format Apache Parquet uses for its metadata (file footer, page headers).  The
reference implementation relies on the full apache/thrift Go runtime plus 12.5k lines
of generated code (/root/reference/parquet/parquet.go); here the ~20 structs Parquet
needs are described by small declarative field specs (see tpu_parquet/format/__init__.py)
and serialized by this generic engine.

Wire-format facts implemented here (verified against the thrift spec and the behaviour
of the reference's vendored Go runtime, e.g. compact_protocol.go: doubles are
little-endian, i16/i32/i64 are zigzag varints, field ids are delta-encoded):

  field header  : one byte ``(delta << 4) | ctype``; delta==0 → explicit zigzag varint id
  bool fields   : value carried in the header ctype (1=true, 2=false)
  list header   : one byte ``(size << 4) | elem_ctype``; size==15 → explicit varint size
  binary/string : varint length + bytes
  struct        : fields then a 0x00 stop byte

Malformed-input hardening mirrors the posture of the reference's fuzz-hardened
helpers.go:103-119 readThrift path: all reads are bounds-checked against the buffer and
raise ``ThriftError`` instead of crashing, and containers are size-sanity-checked.
"""

from __future__ import annotations

from .errors import ParquetError

import struct as _struct
from typing import Any, Callable, Optional

__all__ = [
    "ThriftError",
    "ThriftStruct",
    "read_struct",
    "write_struct",
    "serialize",
    "deserialize",
    "CompactReader",
    "CompactWriter",
]


class ThriftError(ParquetError):
    """Raised on malformed thrift input (truncated, oversized, or type-confused)."""


# Compact-protocol wire type ids.
CT_STOP = 0x00
CT_TRUE = 0x01
CT_FALSE = 0x02
CT_BYTE = 0x03
CT_I16 = 0x04
CT_I32 = 0x05
CT_I64 = 0x06
CT_DOUBLE = 0x07
CT_BINARY = 0x08
CT_LIST = 0x09
CT_SET = 0x0A
CT_MAP = 0x0B
CT_STRUCT = 0x0C

# Declarative field-spec atoms → compact wire type.
_ATOM_CTYPE = {
    "bool": CT_TRUE,  # placeholder; bools are special-cased in field headers
    "i8": CT_BYTE,
    "i16": CT_I16,
    "i32": CT_I32,
    "i64": CT_I64,
    "double": CT_DOUBLE,
    "binary": CT_BINARY,
    "string": CT_BINARY,
}

# Hard cap on any single container/blob parsed from untrusted bytes.  Real parquet
# footers have a few thousand schema elements; 16M entries is far beyond legitimate
# use and cheap insurance against decompression-bomb-style thrift payloads (the
# reference defends the same way via its allocTracker, alloc.go:10-89).
_MAX_CONTAINER = 1 << 24


def _spec_ctype(spec: Any) -> int:
    if isinstance(spec, str):
        return _ATOM_CTYPE[spec]
    if isinstance(spec, tuple):
        if spec[0] == "list":
            return CT_LIST
        if spec[0] == "map":
            return CT_MAP
    if isinstance(spec, type) and issubclass(spec, ThriftStruct):
        return CT_STRUCT
    raise TypeError(f"bad thrift field spec: {spec!r}")


def _zigzag32(n: int) -> int:
    if not -(1 << 31) <= n < (1 << 31):
        raise ThriftError(f"value {n} out of range for 32-bit thrift field")
    return ((n << 1) ^ (n >> 31)) & 0xFFFFFFFF


def _zigzag64(n: int) -> int:
    if not -(1 << 63) <= n < (1 << 63):
        raise ThriftError(f"value {n} out of range for 64-bit thrift field")
    return ((n << 1) ^ (n >> 63)) & 0xFFFFFFFFFFFFFFFF


def _unzigzag(n: int) -> int:
    return (n >> 1) ^ -(n & 1)


class CompactReader:
    """Cursor over a bytes-like object decoding compact-protocol primitives."""

    __slots__ = ("buf", "pos", "end")

    def __init__(self, buf, pos: int = 0, end: Optional[int] = None):
        if isinstance(buf, memoryview):
            buf = bytes(buf)
        self.buf = buf
        self.pos = pos
        self.end = len(buf) if end is None else end

    def _need(self, n: int) -> int:
        p = self.pos
        if p + n > self.end:
            raise ThriftError(
                f"truncated thrift input: need {n} bytes at {p}, have {self.end - p}"
            )
        self.pos = p + n
        return p

    def read_byte(self) -> int:
        p = self._need(1)
        return self.buf[p]

    def read_varint(self) -> int:
        """Unsigned LEB128 varint (unbounded width is rejected past 10 bytes)."""
        result = 0
        shift = 0
        buf = self.buf
        pos = self.pos
        end = self.end
        while True:
            if pos >= end:
                raise ThriftError("truncated varint")
            b = buf[pos]
            pos += 1
            result |= (b & 0x7F) << shift
            if not b & 0x80:
                break
            shift += 7
            if shift > 63:
                raise ThriftError("varint too long")
        if result >> 64:
            raise ThriftError("varint exceeds 64 bits")
        self.pos = pos
        return result

    def read_zigzag(self) -> int:
        return _unzigzag(self.read_varint())

    def read_double(self) -> float:
        p = self._need(8)
        return _struct.unpack_from("<d", self.buf, p)[0]

    def read_binary(self) -> bytes:
        n = self.read_varint()
        if n > _MAX_CONTAINER:
            raise ThriftError(f"thrift binary of {n} bytes exceeds sanity cap")
        p = self._need(n)
        return bytes(self.buf[p : p + n])

    def read_list_header(self) -> tuple[int, int]:
        b = self.read_byte()
        size = (b >> 4) & 0x0F
        etype = b & 0x0F
        if size == 15:
            size = self.read_varint()
        if size > _MAX_CONTAINER:
            raise ThriftError(f"thrift list of {size} elements exceeds sanity cap")
        return size, etype

    def read_field_header(self, last_fid: int) -> tuple[int, int]:
        """Returns (ctype, field_id); ctype==CT_STOP terminates the struct."""
        b = self.read_byte()
        if b == CT_STOP:
            return CT_STOP, 0
        ctype = b & 0x0F
        delta = (b >> 4) & 0x0F
        fid = last_fid + delta if delta else self.read_zigzag()
        return ctype, fid

    # -- skipping unknown fields (forward/backward compat + fuzz robustness) ------

    def skip(self, ctype: int, depth: int = 0) -> None:
        if depth > 32:
            raise ThriftError("thrift nesting too deep")
        if ctype in (CT_TRUE, CT_FALSE):
            return
        if ctype == CT_BYTE:
            self._need(1)
        elif ctype in (CT_I16, CT_I32, CT_I64):
            self.read_varint()
        elif ctype == CT_DOUBLE:
            self._need(8)
        elif ctype == CT_BINARY:
            n = self.read_varint()
            if n > _MAX_CONTAINER:
                raise ThriftError("oversized binary while skipping")
            self._need(n)
        elif ctype in (CT_LIST, CT_SET):
            size, etype = self.read_list_header()
            if etype in (CT_TRUE, CT_FALSE):
                # list elements carry bools as one byte each (unlike field headers)
                self._need(size)
            else:
                for _ in range(size):
                    self.skip(etype, depth + 1)
        elif ctype == CT_MAP:
            size = self.read_varint()
            if size > _MAX_CONTAINER:
                raise ThriftError("oversized map while skipping")
            if size:
                kv = self.read_byte()
                ktype, vtype = (kv >> 4) & 0x0F, kv & 0x0F
                for _ in range(size):
                    self.skip(ktype, depth + 1)
                    self.skip(vtype, depth + 1)
        elif ctype == CT_STRUCT:
            last = 0
            while True:
                ft, fid = self.read_field_header(last)
                if ft == CT_STOP:
                    return
                if ft not in (CT_TRUE, CT_FALSE):
                    self.skip(ft, depth + 1)
                last = fid
        else:
            raise ThriftError(f"cannot skip unknown thrift ctype {ctype}")


class CompactWriter:
    """Append-only compact-protocol emitter into a bytearray."""

    __slots__ = ("out",)

    def __init__(self):
        self.out = bytearray()

    def write_byte(self, b: int) -> None:
        self.out.append(b & 0xFF)

    def write_varint(self, n: int) -> None:
        out = self.out
        while True:
            if n < 0x80:
                out.append(n)
                return
            out.append((n & 0x7F) | 0x80)
            n >>= 7

    def write_zigzag32(self, n: int) -> None:
        self.write_varint(_zigzag32(n))

    def write_zigzag64(self, n: int) -> None:
        self.write_varint(_zigzag64(n))

    def write_double(self, v: float) -> None:
        self.out += _struct.pack("<d", v)

    def write_binary(self, v: bytes) -> None:
        self.write_varint(len(v))
        self.out += v

    def write_list_header(self, size: int, etype: int) -> None:
        if size < 15:
            self.write_byte((size << 4) | etype)
        else:
            self.write_byte(0xF0 | etype)
            self.write_varint(size)

    def write_field_header(self, ctype: int, fid: int, last_fid: int) -> None:
        delta = fid - last_fid
        if 0 < delta <= 15:
            self.write_byte((delta << 4) | ctype)
        else:
            self.write_byte(ctype)
            self.write_zigzag32(fid)


class ThriftStruct:
    """Base for declaratively-specified thrift structs.

    Subclasses set ``FIELDS``: a dict ``{field_id: (attr_name, spec)}`` where spec is
    an atom string ('bool','i8','i16','i32','i64','double','binary','string'), a
    ``('list', spec)`` tuple, or a ThriftStruct subclass.  Unset/None fields are
    omitted on write; unknown fields are skipped on read.
    """

    FIELDS: dict[int, tuple[str, Any]] = {}

    def __init__(self, **kwargs):
        for _, (name, _spec) in self.FIELDS.items():
            setattr(self, name, kwargs.pop(name, None))
        if kwargs:
            raise TypeError(f"{type(self).__name__}: unknown fields {sorted(kwargs)}")

    def __repr__(self):
        parts = []
        for _, (name, _spec) in sorted(self.FIELDS.items()):
            v = getattr(self, name)
            if v is not None:
                parts.append(f"{name}={v!r}")
        return f"{type(self).__name__}({', '.join(parts)})"

    def __eq__(self, other):
        if type(other) is not type(self):
            return NotImplemented
        return all(
            getattr(self, name) == getattr(other, name)
            for _, (name, _spec) in self.FIELDS.items()
        )

    __hash__ = None


def _read_value(
    r: CompactReader, spec: Any, ctype: int, depth: int, from_field: bool = False
) -> Any:
    if depth > 32:
        raise ThriftError("thrift nesting too deep")
    if isinstance(spec, str):
        if spec == "bool":
            if from_field:
                # in field context the value is carried in the header's ctype
                return ctype == CT_TRUE
            # list/set elements carry bools as one byte each (0x01/0x02)
            return r.read_byte() == CT_TRUE
        if spec == "i8":
            v = r.read_byte()
            return v - 256 if v >= 128 else v
        if spec in ("i16", "i32", "i64"):
            return r.read_zigzag()
        if spec == "double":
            return r.read_double()
        if spec == "binary":
            return r.read_binary()
        if spec == "string":
            return r.read_binary().decode("utf-8", errors="replace")
        raise TypeError(f"bad atom spec {spec!r}")
    if isinstance(spec, tuple) and spec[0] == "list":
        size, etype = r.read_list_header()
        elem_spec = spec[1]
        # type-confusion guard: if the wire's element type doesn't match the
        # spec, consume the list per the wire type and treat the field as absent
        if elem_spec == "bool":
            ok = etype in (CT_TRUE, CT_FALSE)
        else:
            ok = etype == _spec_ctype(elem_spec)
        if not ok:
            if etype in (CT_TRUE, CT_FALSE):
                r._need(size)
            else:
                for _ in range(size):
                    r.skip(etype, depth + 1)
            return None
        return [_read_value(r, elem_spec, etype, depth + 1) for _ in range(size)]
    if isinstance(spec, type) and issubclass(spec, ThriftStruct):
        return _read_struct_body(r, spec, depth + 1)
    raise TypeError(f"bad thrift field spec: {spec!r}")


def _read_struct_body(r: CompactReader, cls: type, depth: int = 0):
    if depth > 32:
        raise ThriftError("thrift nesting too deep")
    obj = cls()
    fields = cls.FIELDS
    last = 0
    while True:
        ctype, fid = r.read_field_header(last)
        if ctype == CT_STOP:
            return obj
        ent = fields.get(fid)
        if ent is None:
            r.skip(ctype, depth)
        else:
            name, spec = ent
            # Guard against wire-type/spec confusion on malformed input: a field id
            # we know, carrying a different wire type, is skipped by its wire type.
            if spec == "bool":
                ok = ctype in (CT_TRUE, CT_FALSE)
            else:
                ok = ctype == _spec_ctype(spec) or (
                    ctype == CT_SET and isinstance(spec, tuple) and spec[0] == "list"
                )
            if ok:
                setattr(obj, name, _read_value(r, spec, ctype, depth, from_field=True))
            else:
                r.skip(ctype, depth)
        last = fid


def _write_value(w: CompactWriter, spec: Any, v: Any) -> None:
    if isinstance(spec, str):
        if spec == "bool":
            w.write_byte(CT_TRUE if v else CT_FALSE)
        elif spec == "i8":
            w.write_byte(v & 0xFF)
        elif spec in ("i16", "i32"):
            w.write_zigzag32(int(v))
        elif spec == "i64":
            w.write_zigzag64(int(v))
        elif spec == "double":
            w.write_double(v)
        elif spec == "binary":
            w.write_binary(bytes(v))
        elif spec == "string":
            w.write_binary(v.encode("utf-8") if isinstance(v, str) else bytes(v))
        else:
            raise TypeError(f"bad atom spec {spec!r}")
    elif isinstance(spec, tuple) and spec[0] == "list":
        elem_spec = spec[1]
        w.write_list_header(len(v), _spec_ctype(elem_spec))
        for item in v:
            _write_value(w, elem_spec, item)
    elif isinstance(spec, type) and issubclass(spec, ThriftStruct):
        _write_struct_body(w, v)
    else:
        raise TypeError(f"bad thrift field spec: {spec!r}")


def _write_struct_body(w: CompactWriter, obj: ThriftStruct) -> None:
    last = 0
    for fid in sorted(obj.FIELDS):
        name, spec = obj.FIELDS[fid]
        v = getattr(obj, name)
        if v is None:
            continue
        if spec == "bool":
            w.write_field_header(CT_TRUE if v else CT_FALSE, fid, last)
        else:
            w.write_field_header(_spec_ctype(spec), fid, last)
            _write_value(w, spec, v)
        last = fid
    w.write_byte(CT_STOP)


def read_struct(cls: type, buf, pos: int = 0) -> tuple[Any, int]:
    """Parse one ``cls`` from ``buf[pos:]``; returns (object, end_position)."""
    r = CompactReader(buf, pos)
    obj = _read_struct_body(r, cls)
    return obj, r.pos


def write_struct(obj: ThriftStruct) -> bytes:
    w = CompactWriter()
    _write_struct_body(w, obj)
    return bytes(w.out)


# Friendlier aliases used by higher layers.
serialize = write_struct


def deserialize(cls: type, buf) -> Any:
    obj, _ = read_struct(cls, buf)
    return obj
